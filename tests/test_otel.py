"""Distributed tracing & OTLP export (tier-1, CPU backend).

1. **Trace context**: W3C traceparent mint/parse/format round-trip,
   and a traced scheduler run whose EVERY event carries one trace id.
2. **OTLP golden keys**: otel_schema.json pinned both ways — against
   the ``OTLP_*`` constants AND a document generated from a real run
   (the ``trace_schema.json`` pattern for the export shape).
3. **Span tree**: query → stage → task → kernel spans, deterministic
   ids, parent links all resolving, error status on failed queries.
4. **Cross-process propagation** (acceptance): a worker-subprocess
   segment and a service HTTP submission (``traceparent`` header) both
   share the driver/submitter's trace id; ``merge_event_logs``
   reconciles driver + worker segments into one tree.
5. **Sinks**: file sink per query, HTTP pusher delivers to a live
   collector and shuts down leak-free; disarmed = structural no-op
   (poisoned conversion, like the trace-off gate).
6. **Flame profiles**: collapsed-stack writer format + CLI.
"""

import json
import os
import threading
import urllib.request

import pytest

from blaze_tpu import conf
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime import monitor, otel, trace, trace_report
from blaze_tpu.runtime.scheduler import (
    run_stages, split_stages, worker_task_spec,
)
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def data():
    return generate_all(0.01)


def _scans(data, n_parts=2):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=16384),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


@pytest.fixture
def armed(tmp_path):
    """Tracing + OTLP file sink armed; everything restored after."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path / "ev"))
    trace.reset()
    conf.OTEL_ENABLE.set(True)
    conf.OTEL_DIR.set(str(tmp_path / "otel"))
    otel.reset()
    try:
        yield tmp_path
    finally:
        otel.shutdown_pusher()
        conf.OTEL_ENABLE.set(False)
        conf.OTEL_DIR.set("")
        conf.OTEL_ENDPOINT.set("")
        otel.reset()
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
        assert otel.otel_threads() == []


def _run_q6(data, query_id="otel_q6"):
    with monitor.query_span(query_id, mode="scheduler") as log_path:
        stages, mgr = split_stages(build_query("q6", _scans(data), 2))
        rows = sum(b.num_rows for b in run_stages(stages, mgr))
    assert rows > 0
    return log_path


# ------------------------------------------------- 1. trace context

def test_traceparent_roundtrip():
    tid = trace.new_trace_id()
    sid = trace.span_id_for(tid, "query:q6")
    tp = trace.format_traceparent(tid, sid)
    assert trace.parse_traceparent(tp) == (tid, sid)
    # span ids are deterministic (the cross-process reassembly key)
    assert trace.span_id_for(tid, "query:q6") == sid
    assert trace.span_id_for(tid, "stage:0") != sid


@pytest.mark.parametrize("bad", [
    "", "garbage", "00-short-span-01",
    "00-" + "g" * 32 + "-" + "0" * 16 + "-01",   # non-hex trace id
    "00-" + "0" * 32 + "-" + "0" * 8 + "-01",    # short span id
])
def test_malformed_traceparent_degrades_to_none(bad):
    assert trace.parse_traceparent(bad) is None


def test_every_event_carries_one_trace_id(data, armed):
    log_path = _run_q6(data, "tid_q6")
    events = trace.read_event_log(log_path)
    assert events
    tids = {e.get("trace_id") for e in events}
    assert len(tids) == 1 and None not in tids, (
        f"events without the query's trace id: "
        f"{sorted({e['type'] for e in events if 'trace_id' not in e})}")


def test_explicit_trace_id_and_parent_span_honored(data, armed):
    tid = trace.new_trace_id()
    parent = trace.span_id_for(tid, "caller")
    with monitor.query_span("tid_explicit", mode="scheduler",
                            trace_id=tid, parent_span=parent) as lp:
        stages, mgr = split_stages(build_query("q6", _scans(data), 2))
        assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    events = trace.read_event_log(lp)
    assert {e.get("trace_id") for e in events} == {tid}
    start = next(e for e in events if e["type"] == "query_start")
    assert start["parent_span_id"] == parent
    # the exported root span links under the caller's span
    doc = otel.events_to_otlp(events)
    root = next(s for s in otel.span_index(doc)
                if s["name"] == "query:tid_explicit")
    assert root["traceId"] == tid
    assert root["parentSpanId"] == parent


# ------------------------------------------------- 2. golden OTLP keys

def test_otlp_schema_pins_constants_two_way():
    schema = otel.load_schema()
    pairs = {
        "top_level": otel.OTLP_TOP_KEYS,
        "resource_span": otel.OTLP_RESOURCE_SPAN_KEYS,
        "scope_span": otel.OTLP_SCOPE_SPAN_KEYS,
        "span": otel.OTLP_SPAN_KEYS,
        "status": otel.OTLP_STATUS_KEYS,
        "attribute": otel.OTLP_ATTRIBUTE_KEYS,
    }
    # registry and constants in lockstep, BOTH ways: a key added to one
    # without the other is drift
    for name, const in pairs.items():
        assert list(const) == schema[name], name
    assert set(schema) - {"title"} == set(pairs)


def test_generated_document_matches_golden_keys(data, armed):
    events = trace.read_event_log(_run_q6(data, "golden_q6"))
    doc = otel.events_to_otlp(events)
    assert set(doc) == set(otel.OTLP_TOP_KEYS)
    for rs in doc["resourceSpans"]:
        assert set(otel.OTLP_RESOURCE_SPAN_KEYS) <= set(rs)
        for ss in rs["scopeSpans"]:
            assert set(otel.OTLP_SCOPE_SPAN_KEYS) <= set(ss)
            for s in ss["spans"]:
                # spans carry EXACTLY the golden keys — the export
                # side of the two-way gate
                assert set(s) == set(otel.OTLP_SPAN_KEYS), s["name"]
                assert set(otel.OTLP_STATUS_KEYS) <= set(s["status"])
                for a in s["attributes"]:
                    assert set(a) == set(otel.OTLP_ATTRIBUTE_KEYS)
    json.dumps(doc)  # serializable as-is


# ------------------------------------------------- 3. span tree shape

def test_span_tree_query_stage_task_kernel(data, armed):
    events = trace.read_event_log(_run_q6(data, "tree_q6"))
    spans = otel.span_index(otel.events_to_otlp(events))
    assert len({s["traceId"] for s in spans}) == 1
    kinds = {s["name"].split(":")[0] for s in spans}
    assert {"query", "stage", "task", "kernel"} <= kinds
    by_id = {s["spanId"]: s for s in spans}
    roots = [s for s in spans if not s["parentSpanId"]]
    assert [s["name"] for s in roots] == ["query:tree_q6"]
    for s in spans:
        if s["parentSpanId"]:
            assert s["parentSpanId"] in by_id, (s["name"], "dangling")
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
        assert s["status"]["code"] == otel.STATUS_OK
    # kernel spans hang off stage spans; task spans too
    for s in spans:
        kind = s["name"].split(":")[0]
        if kind in ("kernel", "task"):
            assert by_id[s["parentSpanId"]]["name"].startswith("stage:")


def test_failed_query_exports_error_status(armed):
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.runtime import faults
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("v", DataType.int64())])
    plan = MemoryScanExec(
        [[batch_from_pydict({"v": [1, 2, 3]}, schema)]], schema)
    conf.FAULTS_SPEC.set("task.compute@1")
    conf.TASK_MAX_ATTEMPTS.set(1)  # first failure is terminal
    faults.reset()
    try:
        with pytest.raises(Exception):
            with monitor.query_span("err_q", mode="scheduler") as lp:
                stages, mgr = split_stages(plan)
                list(run_stages(stages, mgr))
    finally:
        conf.FAULTS_SPEC.set("")
        conf.TASK_MAX_ATTEMPTS.set(4)
        faults.reset()
    events = trace.read_event_log(lp)
    root = next(s for s in otel.span_index(otel.events_to_otlp(events))
                if s["name"] == "query:err_q")
    assert root["status"]["code"] == otel.STATUS_ERROR


# -------------------------------------- 4. cross-process propagation

def test_worker_task_spec_carries_ambient_traceparent(data, armed):
    stages, mgr = split_stages(build_query("q6", _scans(data), 2))
    stage = stages[-1]
    # outside a traced span: no traceparent key
    spec = worker_task_spec(stage, mgr, 0)
    assert "traceparent" not in spec
    with trace.query("spec_q") :
        ctx = trace.current_trace_context()
        spec = worker_task_spec(stage, mgr, 0, output="/tmp/out.frames")
    assert trace.parse_traceparent(spec["traceparent"])[0] == ctx[0]
    assert spec["partition"] == 0 and spec["shuffle_root"] == mgr.root


def test_restored_context_attributes_worker_side_events(tmp_path, armed):
    """The worker mechanism, in-process: run_task under a context
    restored from a traceparent (what worker.main does) emits events
    carrying the DRIVER's trace id into this segment's log."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.runtime.scheduler import build_task
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.serde.from_proto import run_task

    schema = Schema([Field("x", DataType.int64())])
    src = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(50))}, schema)]], schema)
    stages, mgr = split_stages(src)
    driver_tid = trace.new_trace_id()
    tp = trace.format_traceparent(
        driver_tid, trace.span_id_for(driver_tid, "query:w"))
    parsed = trace.parse_traceparent(tp)
    tok = trace.set_trace_context(*parsed)
    try:
        _, td = build_task(stages[-1], mgr, 0)
        for _ in run_task(td):
            pass
    finally:
        trace.reset_trace_context(tok)
    # the worker-side events (task_kernels/task_plan in the default
    # log) carry the driver's trace id
    default_log = os.path.join(trace.log_dir(),
                               f"blaze-{os.getpid()}.jsonl")
    events = [e for e in trace.read_event_log(default_log)
              if e.get("trace_id") == driver_tid]
    assert {"task_kernels", "task_plan"} <= {e["type"] for e in events}


@pytest.mark.slow
def test_worker_subprocess_shares_driver_trace_id(tmp_path, armed, data):
    """THE cross-process acceptance: a real worker SUBPROCESS run under
    the driver's traceparent writes its own event-log segment whose
    events carry the driver's trace id; merge_event_logs reconciles
    driver + worker segments, and the OTLP conversion of the merged
    stream stays a single parent-linked trace."""
    import struct

    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.ops import ParquetScanExec, ParquetSinkExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.runtime.worker import run_worker_with_retry
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("x", DataType.int64())])
    src = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(64))}, schema)]], schema)
    pq = str(tmp_path / "in.parquet")
    sink = ParquetSinkExec(src, pq)
    for _ in sink.execute(0, TaskContext(0, 1)):
        pass
    pq = sink.written_files[0] if sink.written_files else pq
    plan = ParquetScanExec([[pq]], schema)

    worker_logs = str(tmp_path / "wlogs")
    with monitor.query_span("xproc_q", mode="scheduler") as driver_log:
        from blaze_tpu.parallel.shuffle import LocalShuffleManager

        stages, mgr = split_stages(
            plan, LocalShuffleManager(str(tmp_path / "sh")))
        driver_tid = trace.current_trace_context()[0]
        spec = worker_task_spec(stages[-1], mgr, 0,
                                output=str(tmp_path / "r.frames"))
        assert trace.parse_traceparent(spec["traceparent"])[0] == driver_tid
        run_worker_with_retry(
            spec, str(tmp_path), "xp0", max_attempts=2,
            env={"PYTHONPATH": REPO,
                 "BLAZE_TRACE_ENABLED": "1",
                 "BLAZE_EVENTLOG_DIR": worker_logs})
    assert os.path.exists(str(tmp_path / "r.frames"))
    wfiles = trace_report.event_log_files(worker_logs)
    assert wfiles, "worker wrote no event-log segment"
    worker_events = trace_report.merge_event_logs(wfiles)
    w_tids = {e.get("trace_id") for e in worker_events}
    assert w_tids == {driver_tid}, w_tids

    merged = trace_report.merge_event_logs(
        [driver_log] + wfiles, trace_id=driver_tid)
    assert {e.get("trace_id") for e in merged} == {driver_tid}
    assert merged == sorted(merged, key=lambda e: e.get("ts", 0.0))
    spans = otel.span_index(otel.events_to_otlp(merged))
    assert {s["traceId"] for s in spans} == {driver_tid}
    # the worker's task span exists and parents under a driver stage
    names = {s["name"] for s in spans}
    assert any(n.startswith("task:") for n in names)
    by_id = {s["spanId"]: s for s in spans}
    for s in spans:
        if s["parentSpanId"]:
            assert s["parentSpanId"] in by_id, (s["name"], "dangling")
    # struct import used: keep the linter honest about the frames file
    raw = open(str(tmp_path / "r.frames"), "rb").read()
    (ln,) = struct.unpack_from("<I", raw, 0)
    assert ln > 0


def test_service_http_submission_shares_submitter_trace(data, armed):
    """THE service acceptance: an HTTP submission with a standard
    ``traceparent`` header yields an execution whose event log, OTLP
    export, and /metrics histogram exemplar all resolve to the
    SUBMITTER's trace id (response echoes it)."""
    from blaze_tpu.runtime import service

    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    monitor.reset()
    svc = None
    try:
        srv = monitor.ensure_server()
        svc = service.QueryService().start()
        scans = _scans(data)
        service.set_http_builders(
            {"q6": lambda: build_query("q6", scans, 2)})
        tid = trace.new_trace_id()
        parent = trace.span_id_for(tid, "submitter")
        req = urllib.request.Request(
            srv.url + "/service/submit",
            data=json.dumps({"query": "q6", "pool": "etl"}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": trace.format_traceparent(tid, parent)})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
            assert r.status == 200
        assert out["rows"] > 0
        assert out["trace_id"] == tid

        # the execution's event log carries the submitter's trace id
        logs = trace_report.event_log_files(str(armed / "ev"))
        events = trace_report.merge_event_logs(logs, trace_id=tid)
        assert events, "no events under the submitter's trace id"
        start = next(e for e in events if e["type"] == "query_start")
        assert start["parent_span_id"] == parent

        # the OTLP export is a single tree under that id
        sink_files = [f for f in os.listdir(str(armed / "otel"))
                      if f.endswith("-spans.json")]
        assert sink_files
        doc = json.load(open(os.path.join(str(armed / "otel"),
                                          sink_files[-1])))
        spans = otel.span_index(doc)
        assert {s["traceId"] for s in spans} == {tid}
        root = next(s for s in spans if s["name"].startswith("query:"))
        assert root["parentSpanId"] == parent

        # /metrics histograms expose an exemplar resolving to the trace
        # (OpenMetrics dialect — exemplar syntax is negotiated)
        mreq = urllib.request.Request(
            srv.url + "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(mreq, timeout=10) as r:
            prom = r.read().decode()
        assert f'trace_id="{tid}"' in prom
    finally:
        if svc is not None:
            svc.shutdown()
        monitor.shutdown_server()
        conf.MONITOR_ENABLE.set(False)
        conf.MONITOR_PORT.set(4048)
        monitor.reset()


# --------------------------------------------------------- 5. sinks

def test_file_sink_written_per_query(data, armed):
    _run_q6(data, "sink_q6")
    files = [f for f in os.listdir(str(armed / "otel"))
             if f.startswith("sink_q6-")]
    assert len(files) == 1
    doc = json.load(open(os.path.join(str(armed / "otel"), files[0])))
    assert otel.span_index(doc)
    assert otel.counters()["exports"] >= 1


def test_pusher_delivers_and_shuts_down_clean(data, tmp_path):
    """A live mini-collector receives the POSTed OTLP document; the
    pusher thread dies with shutdown (the leak gate --chaos also
    runs)."""
    import http.server

    received = []
    done = threading.Event()

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append(json.loads(self.rfile.read(n)))
            done.set()
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), Collector)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path / "ev"))
    trace.reset()
    conf.OTEL_ENABLE.set(True)
    conf.OTEL_DIR.set(str(tmp_path / "otel"))
    conf.OTEL_ENDPOINT.set(
        f"http://127.0.0.1:{httpd.server_address[1]}/v1/traces")
    conf.OTEL_FLUSH_MS.set(25)
    otel.reset()
    try:
        _run_q6(data, "push_q6")
        assert done.wait(10), "collector never received a push"
        spans = otel.span_index(received[0])
        assert any(s["name"] == "query:push_q6" for s in spans)
    finally:
        otel.shutdown_pusher()
        assert otel.otel_threads() == []
        httpd.shutdown()
        httpd.server_close()
        conf.OTEL_ENABLE.set(False)
        conf.OTEL_DIR.set("")
        conf.OTEL_ENDPOINT.set("")
        conf.OTEL_FLUSH_MS.set(1000)
        otel.reset()
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


def test_disarmed_export_is_structural_noop(data, tmp_path, monkeypatch):
    """With spark.blaze.otel.enabled=false (the default) the span-exit
    hook never reaches conversion, sinks, or the pusher — poisoned
    like the trace-off gate."""
    def poisoned(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("otel path reached while disarmed")

    conf.OTEL_ENABLE.set(False)
    otel.reset()
    monkeypatch.setattr(otel, "events_to_otlp", poisoned)
    monkeypatch.setattr(otel, "_ensure_pusher", poisoned)
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        _run_q6(data, "noop_q6")
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    assert otel.counters()["exports"] == 0


# ------------------------------------------------- 6. flame profiles

def test_collapsed_stacks_format_and_writer(data, armed, tmp_path, capsys):
    events = trace.read_event_log(_run_q6(data, "flame_q6"))
    lines = trace_report.collapsed_stacks(events)
    assert lines
    for ln in lines:
        stack, _, val = ln.rpartition(" ")
        assert int(val) >= 1
        assert stack.startswith("flame_q6;")
        assert ";" in stack
    # both families present: kernel splits and the plan-node tree
    assert any(";device" in ln for ln in lines)
    assert any(";plan;" in ln for ln in lines)
    out = str(tmp_path / "flame.txt")
    n = trace_report.write_flame(events, out)
    assert n == len(lines)
    assert open(out).read().splitlines() == lines


def test_cli_report_flame_and_directory_merge(data, armed, capsys):
    import blaze_tpu.__main__ as cli

    log_path = _run_q6(data, "cli_q6")
    ev_dir = os.path.dirname(log_path)
    rc = cli.main(["--report", ev_dir, "--flame", "-"])
    assert rc == 0
    outp = capsys.readouterr().out
    assert "cli_q6;" in outp
    rc = cli.main(["--report", log_path])
    assert rc == 0
    assert "trace " in capsys.readouterr().out  # header shows trace id
