"""Scale-tier TPC-H differentials: SF0.1, 4 partitions, under a capped
memory budget so sort/agg/shuffle SPILL — the overflow/skew/multi-batch
regime the SF0.002 suite cannot reach (≙ the reference's 1 GB CI
dataset, tpcds-reusable.yml).  Every comparison is exact (int128
accumulation makes even the decimal averages digit-exact)."""

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch import oracle as O
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

pytestmark = pytest.mark.slow

SCALE = 0.1
N_PARTS = 4
BUDGET = 2 << 20  # bytes: far below the SF0.1 working set


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], N_PARTS, batch_rows=16384),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _spill_count(plan) -> int:
    total = 0
    seen = set()

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        nonlocal total
        total += node.metrics.get("spill_count")
        for c in node.children:
            walk(c)

    walk(plan)
    return total


def run_capped(plan):
    """Capped budget + the FILE shuffle tier (the in-process exchange
    keeps map output in HBM and never touches the spill machinery)."""
    MemManager.init(BUDGET)
    old = conf.EXCHANGE_IN_PROCESS.get()
    conf.EXCHANGE_IN_PROCESS.set(False)
    try:
        out = {f.name: [] for f in plan.schema.fields}
        for p in range(plan.num_partitions()):
            for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
                d = batch_to_pydict(b)
                for k in out:
                    out[k].extend(d[k])
        return out, _spill_count(plan)
    finally:
        conf.EXCHANGE_IN_PROCESS.set(old)
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))


def test_q1_scale_exact(data, scans):
    # q1's partial agg collapses to 4 groups BELOW the exchange, so no
    # operator ever buffers enough to spill — this case is about exact
    # int128 arithmetic at 600k rows
    plan = build_query("q1", scans, N_PARTS)
    got, _ = run_capped(plan)
    exp = O.oracle_q1(data)
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert keys == sorted(keys)
    assert set(keys) == set(exp)
    for i, k in enumerate(keys):
        e = exp[k]
        for m in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                  "count_order", "avg_qty", "avg_price", "avg_disc"):
            assert got[m][i] == e[m], (k, m)


def test_q3_scale_exact_with_spills(data, scans):
    got, spills = run_capped(build_query("q3", scans, N_PARTS))
    exp = O.oracle_q3(data)
    rows = list(zip(got["l_orderkey"], got["revenue"],
                    got["o_orderdate"], got["o_shippriority"]))
    assert len(rows) == len(exp)
    assert set((r[0], r[1]) for r in rows) == set((r[0], r[1]) for r in exp)
    assert [r[1] for r in rows] == sorted([r[1] for r in rows], reverse=True)
    assert spills > 0, "the shuffled join must spill under the capped budget"


def test_q18_scale_exact_with_spills(data, scans):
    plan = build_query("q18", scans, N_PARTS)
    got, spills = run_capped(plan)
    exp = O.oracle_q18(data)
    rows = list(zip(got["c_name"], got["c_custkey"], got["o_orderkey"],
                    got["o_orderdate"], got["o_totalprice"], got["qsum"]))
    assert len(rows) == len(exp)
    assert set(r[2] for r in rows) == set(e[2] for e in exp)
    assert [r[4] for r in rows] == sorted([r[4] for r in rows], reverse=True)
    by_key = {e[2]: e for e in exp}
    for r in rows:
        e = by_key[r[2]]
        assert (r[1], r[5]) == (e[1], e[5]), r[2]


def test_q21_scale_exact(data, scans):
    got, _ = run_capped(build_query("q21", scans, N_PARTS))
    exp = O.oracle_q21(data)
    assert dict(zip(got["s_name"], got["numwait"])) == exp
