"""Multi-tenant query service (ISSUE 11): admission control,
fair-share scheduling, per-pool isolation, backpressure, supervision.

1. **Soak** (tier-1-sized here, ``slow`` full variant): 3 pools x 4
   sessions driving 21+ queries through the service concurrently —
   weighted fairness pinned by a tolerance band over the DRR gate's
   contended lease shares at the first pool-drain mark, typed
   rejection on oversubmission (never a hang), and zero leaked
   threads / spill files / running registry entries after drain.
2. **Isolation**: one quota-busting query is cancelled with
   ``reason="quota"`` while its neighbors complete byte-identical to
   their serial runs.
3. **Gate units**: DRR share convergence, contended-charge
   accounting, abandoned waiters.
4. **Admission units**: queue_full / queue_timeout / shutdown sheds,
   HTTP submit mapping (200 / 429 / 404).
5. **Backpressure**: the bounded result queue throttles the producer;
   an abandoned consumer cancels instead of wedging it.
6. **Supervision**: deadline + heartbeat-age wedge reaping.
7. **Monitor correctness under concurrency** (the PR 8 style
   deterministic two-thread interleaving, armed lockset + lock-order
   checkers): two simultaneously-running queries never
   cross-attribute rows/heartbeats/counters in /queries or /metrics.
8. **Satellites**: history JSONL + ``/queries?all=1``, statsd lines,
   per-task kernel splits in /queries.
"""

import contextlib
import glob
import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs.ir import Col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.parallel.exchange import NativeShuffleExchangeExec
from blaze_tpu.parallel.shuffle import HashPartitioning
from blaze_tpu.runtime import lockset, memmgr, monitor, service, trace
from blaze_tpu.runtime.context import QueryCancelledError
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.runtime.service import (
    FairShareGate, QueryRejectedError, QueryService,
)
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])


@pytest.fixture(autouse=True, scope="module")
def _armed_checkers():
    """The whole suite runs under the runtime lock-order assertion AND
    the Eraser-style lockset checker: the service's new shared state
    (admission queue, DRR gate, owner tags) is exactly the concurrency
    seam the PR 8 machinery exists to gate.  The error-escape recorder
    and resource ledger (spark.blaze.verify.errors) ride along: a
    FATAL-class error absorbed at an audited handler site or a leaked
    lease/spill/temp fails the module."""
    from blaze_tpu.analysis import locks as lock_verify

    from blaze_tpu.runtime import errors, ledger

    conf.VERIFY_LOCKS.set(True)
    lock_verify.refresh()
    conf.VERIFY_LOCKSET.set(True)
    lockset.refresh()
    conf.VERIFY_ERRORS.set(True)
    errors.refresh()
    ledger.refresh()
    yield
    escaped = errors.escapes()
    leaked = ledger.leaks()
    assert lockset.reported() == [], (
        "lockset violations during the service suite: "
        + "; ".join(lockset.reported()))
    conf.VERIFY_LOCKS.set(False)
    lock_verify.refresh()
    conf.VERIFY_LOCKSET.set(False)
    lockset.refresh()
    conf.VERIFY_ERRORS.set(False)
    errors.refresh()
    ledger.refresh()
    assert escaped == [], (
        "FATAL-class error absorbed at an audited site during the "
        "service suite: " + "; ".join(escaped))
    assert leaked == [], (
        "resource-ledger leaks during the service suite: "
        + "; ".join(leaked))


@pytest.fixture
def armed_monitor():
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    conf.MONITOR_HEARTBEAT_MS.set(5)
    monitor.reset()
    try:
        yield monitor
    finally:
        monitor.shutdown_server()
        conf.MONITOR_ENABLE.set(False)
        conf.MONITOR_PORT.set(4048)
        conf.MONITOR_HEARTBEAT_MS.set(1000)
        monitor.reset()
        assert monitor.monitor_threads() == []


@pytest.fixture
def svc_conf():
    """Service knobs restored after each test (pool weights/quotas are
    plain conf entries: clear the ones tests set)."""
    keys = (conf.SERVICE_MAX_CONCURRENT, conf.SERVICE_MAX_QUEUED,
            conf.SERVICE_QUEUE_TIMEOUT_MS, conf.SERVICE_WEDGE_MS,
            conf.SERVICE_RESULT_QUEUE_DEPTH, conf.QUERY_TIMEOUT_MS)
    prev = [k.get() for k in keys]
    yield conf
    for k, v in zip(keys, prev):
        k.set(v)
    for key in list(conf._values):
        if key.startswith("spark.blaze.service.pool."):
            del conf._values[key]


def _make_plan(seed: int = 0, rows: int = 2500, batches: int = 2,
               parts: int = 2, keys: int = 50):
    """A 2-stage plan (map shuffle + result) over deterministic data."""
    rng = np.random.RandomState(seed)
    part_batches = []
    for _ in range(parts):
        part_batches.append([
            batch_from_pydict(
                {"k": rng.randint(0, keys, rows).tolist(),
                 "v": rng.randint(0, 1000, rows).tolist()}, SCHEMA)
            for _ in range(batches)])
    scan = MemoryScanExec(part_batches, SCHEMA)
    return NativeShuffleExchangeExec(scan, HashPartitioning([Col("k")], 2))


@contextlib.contextmanager
def _uniform_task_cost(sleep_s: float):
    """Patch ``from_proto.run_task`` to prepend a fixed GIL-free sleep
    to every task — uniform 'device work' that survives the
    TaskDefinition serde boundary (a custom ExecNode subclass does
    not: the scheduler reconstructs plans from proto), so the fairness
    soak measures the DRR gate's policy instead of XLA compile noise
    and host-side GIL contention, while every other layer (serde,
    shuffle files, monitor, cancellation) stays fully real."""
    from blaze_tpu.serde import from_proto

    orig = from_proto.run_task

    def slow_run_task(td, *a, **kw):
        time.sleep(sleep_s)
        return orig(td, *a, **kw)

    from_proto.run_task = slow_run_task
    try:
        yield
    finally:
        from_proto.run_task = orig


def _sorted_rows(batches) -> list:
    rows = []
    for b in batches:
        d = batch_to_pydict(b)
        cols = sorted(d)
        rows.extend(zip(*[d[c] for c in cols]))
    return sorted(rows)


def _serial_rows(seed: int, **kw) -> list:
    stages, manager = split_stages(_make_plan(seed, **kw))
    return _sorted_rows(run_stages(stages, manager))


def _assert_no_service_leaks(spills_before):
    assert service.service_threads() == [], "leaked blaze-service threads"
    leaked = set(glob.glob(os.path.join(
        tempfile.gettempdir(), "blaze_spill_*"))) - spills_before
    assert not leaked, f"leaked spill files: {sorted(leaked)[:4]}"


# ----------------------------------------------------------- 1. soak

def _soak(n_per_pool: int, rows: int, task_sleep_s: float):
    weights = {"p3": 3.0, "p2": 2.0, "p1": 1.0}
    for name, w in weights.items():
        conf.set_conf(f"spark.blaze.service.pool.{name}.weight", w)
    # several runnable queries per pool so every pool has lease demand
    # whenever it has credit — fairness is a property of SATURATED
    # pools (an idle pool rightly cedes its share)
    conf.SERVICE_MAX_CONCURRENT.set(9)
    conf.SERVICE_MAX_QUEUED.set(64)
    spills_before = set(glob.glob(os.path.join(
        tempfile.gettempdir(), "blaze_spill_*")))
    svc = QueryService().start()
    try:
        handles = []
        i = 0
        # equal work per pool: every pool stays saturated until the
        # heaviest drains, so the first drain-mark shares are judged
        # while ALL pools contend — the window where DRR shares must
        # match the weights
        with _uniform_task_cost(task_sleep_s):
            for k in range(n_per_pool):
                for pool in weights:
                    h = svc.submit(
                        f"soak_{pool}_{k}", pool=pool, session=f"s{i % 4}",
                        build=lambda i=i: _make_plan(i, rows=rows))
                    handles.append(h)
                    i += 1
            assert len(handles) >= 21
            assert len({h.session for h in handles}) >= 4
            for h in handles:
                got = _sorted_rows(h.result(timeout=300))
                assert h.status == "done"
                assert len(got) > 0
        # ---- fairness: tolerance band at the first pool-drain mark
        marks = svc.drain_marks()
        assert set(marks) == set(weights), "every pool drained"
        first_pool = min(marks, key=lambda p: marks[p]["t"])
        shares = marks[first_pool]["shares"]
        contended = {p: shares[p]["contended_ns"] for p in weights}
        total = sum(contended.values())
        assert total > 0, "the gate never saw contention"
        wsum = sum(weights.values())
        for pool, w in weights.items():
            got = contended[pool] / total
            want = w / wsum
            assert abs(got - want) <= 0.5 * want + 0.05, (
                f"pool {pool}: contended lease share {got:.3f} outside "
                f"the tolerance band of its weight share {want:.3f} "
                f"(all: { {p: round(contended[p] / total, 3) for p in contended} })")
        # heavier pools must not come out BEHIND lighter ones, and
        # with equal work per pool the heaviest backlog must drain no
        # later than the lightest (strict first-place ordering between
        # p3 and p2 is too schedule-sensitive to pin)
        assert contended["p3"] > contended["p1"], (
            "weight-3 pool got less contended lease time than weight-1")
        assert marks["p3"]["t"] <= marks["p1"]["t"], (
            "the weight-3 pool drained its equal backlog AFTER the "
            "weight-1 pool — fair share inverted")
        # ---- counters
        counters = svc.stats()["counters"]
        assert counters["queries_admitted"] == len(handles)
        assert counters.get("queries_queued", 0) > 0, (
            "the soak never exercised the queue")
        # the main soak used a FRESH MemoryScanExec per submission, so
        # every query was a (stored) result-cache miss by construction
        assert counters.get("queries_cache_hits", 0) == 0
        # ---- result cache: resubmitting over the SAME source must be
        # served off-device — cache-hit-rate > 0 and ZERO DRR lease
        # turns on the hit path (runtime/querycache.py)
        rng = np.random.RandomState(99)
        shared = {}
        for pool in weights:
            b = batch_from_pydict(
                {"k": rng.randint(0, 50, 500).tolist(),
                 "v": rng.randint(0, 1000, 500).tolist()}, SCHEMA)
            shared[pool] = MemoryScanExec([[b], [b]], SCHEMA)

        def run_shared(tag, pool):
            h = svc.submit(
                f"cache_{tag}_{pool}", pool=pool,
                build=lambda s=shared[pool]: NativeShuffleExchangeExec(
                    s, HashPartitioning([Col("k")], 2)))
            rows = _sorted_rows(h.result(timeout=120))
            assert h.status == "done"
            return rows

        miss_rows = {p: run_shared("miss", p) for p in weights}
        before = dict(svc.stats()["counters"])
        hit_rows = {p: run_shared("hit", p) for p in weights}
        counters = svc.stats()["counters"]
        hits = (counters.get("queries_cache_hits", 0)
                - before.get("queries_cache_hits", 0))
        assert hits == len(weights), (
            f"expected every repeated submission to hit, got {hits}")
        # a hit never takes a device-lease turn: the per-lease turn
        # counter published at hit time must have summed to zero
        assert counters.get("cache_hit_lease_turns", 0) == 0, counters
        # cached results are served byte-identical to the fresh run
        assert hit_rows == miss_rows
        cache = svc.stats()["cache"]
        assert cache["result"]["entries"] >= len(weights)
        assert cache["counters"]["result_cache_hits"] >= len(weights)
    finally:
        svc.shutdown()
    _assert_no_service_leaks(spills_before)
    snap = monitor.snapshot()
    running = [q for q in snap["queries"] if q["status"] == "running"]
    assert running == [], f"registry entries stuck running: {running}"


def test_soak_fairness_admission_drain(armed_monitor, svc_conf):
    # task sleeps dominate host-side work (small rows), so the lease
    # is the bottleneck and the DRR shares are judgeable — see
    # _uniform_task_cost
    _soak(n_per_pool=7, rows=500, task_sleep_s=0.035)


@pytest.mark.slow
def test_soak_full(armed_monitor, svc_conf):
    _soak(n_per_pool=12, rows=2000, task_sleep_s=0.05)


def test_oversubmission_sheds_typed_never_hangs(armed_monitor, svc_conf):
    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(1)
    svc = QueryService().start()
    try:
        outcomes = []
        for i in range(6):
            try:
                outcomes.append(svc.submit(
                    f"over{i}", build=lambda i=i: _make_plan(i)))
            except QueryRejectedError as e:
                assert e.retryable and e.http_status == 429
                assert e.reason == "queue_full"
                outcomes.append("rejected")
        rejected = sum(1 for o in outcomes if o == "rejected")
        assert rejected >= 1, "oversubmission never shed"
        t0 = time.monotonic()
        for h in outcomes:
            if h == "rejected":
                continue
            h.result(timeout=120)
            assert h.status == "done"
        assert time.monotonic() - t0 < 120
        assert svc.stats()["counters"]["queries_rejected"] == rejected
    finally:
        svc.shutdown()


# ------------------------------------------------------ 2. isolation

def test_quota_breach_cancels_owner_only(armed_monitor, svc_conf):
    """A quota-busting query walks the owner-only spill rung, then is
    cancelled with reason="quota"; neighbors in other pools finish
    byte-identical to their serial runs."""
    conf.SERVICE_MAX_CONCURRENT.set(3)
    conf.set_conf("spark.blaze.service.pool.small.quota", 64)
    serial = {s: _serial_rows(s) for s in (21, 22)}
    spills_before = set(glob.glob(os.path.join(
        tempfile.gettempdir(), "blaze_spill_*")))
    svc = QueryService().start()
    try:
        buster = svc.submit(
            "buster", pool="small",
            build=lambda: _make_plan(7, rows=4000, batches=8))
        neighbors = [svc.submit(f"n{s}", pool="roomy",
                                build=lambda s=s: _make_plan(s))
                     for s in (21, 22)]
        with pytest.raises(QueryCancelledError) as ei:
            buster.result(timeout=120)
        assert ei.value.reason == "quota"
        assert buster.status == "cancelled"
        for h, s in zip(neighbors, (21, 22)):
            assert _sorted_rows(h.result(timeout=120)) == serial[s], (
                f"neighbor {h.query_id} diverged from its serial run")
        assert svc.stats()["counters"]["queries_quota_cancelled"] == 1
    finally:
        svc.shutdown()
    _assert_no_service_leaks(spills_before)


def test_owner_filtered_force_spill_never_touches_neighbors():
    """memmgr rung-1 isolation: force_spill(owner=...) drains only the
    tagged query's consumers."""
    from blaze_tpu.runtime.memmgr import MemConsumer, MemManager

    mgr = MemManager(total=1 << 20)

    class C(MemConsumer):
        def __init__(self, name):
            super().__init__()
            self.name = name
            self.spilled = 0

        def spill(self):
            freed = self._mem_used
            self.spilled += 1
            self.set_mem_used_no_trigger(0)
            return freed

    mine, theirs = C("mine"), C("theirs")
    tok = memmgr.set_owner_tag(("q1", "small"))
    try:
        mgr.register_consumer(mine)
    finally:
        memmgr.reset_owner(tok)
    tok = memmgr.set_owner_tag(("q2", "roomy"))
    try:
        mgr.register_consumer(theirs)
    finally:
        memmgr.reset_owner(tok)
    mine.set_mem_used_no_trigger(1000)
    theirs.set_mem_used_no_trigger(2000)
    assert mgr.used_by_owner(("q1", "small")) == 1000
    assert mgr.used_by_pools() == {"small": 1000, "roomy": 2000}
    freed = mgr.force_spill(owner=("q1", "small"))
    assert freed == 1000
    assert mine.spilled == 1 and theirs.spilled == 0
    assert mgr.used_by_owner(("q2", "roomy")) == 2000


# ------------------------------------------------------ 3. gate units

def test_gate_drr_shares_follow_weights(svc_conf):
    """Synthetic turns, no service: two saturated pools at weights
    3:1 split contended lease time ~3:1."""
    conf.set_conf("spark.blaze.service.pool.heavy.weight", 3.0)
    conf.set_conf("spark.blaze.service.pool.light.weight", 1.0)
    gate = FairShareGate(slots=1, quantum_ns=2_000_000)
    stop = time.monotonic() + 1.2
    errors = []

    def worker(pool):
        try:
            while time.monotonic() < stop:
                with gate.turn(pool):
                    time.sleep(0.004)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(p,), daemon=True)
               for p in ("heavy", "light", "heavy", "light")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    assert not errors
    shares = gate.shares()
    h = shares["heavy"]["contended_ns"]
    l = shares["light"]["contended_ns"]
    assert h > 0 and l > 0
    ratio = h / l
    assert 1.8 <= ratio <= 4.5, (
        f"contended share ratio {ratio:.2f} far from the 3:1 weights")


def test_gate_abandoned_waiter_releases_nothing(svc_conf):
    """A waiter that gives up (query cancel while queued for a turn)
    never consumes a slot; the holder's release still pumps others."""
    from blaze_tpu.runtime.context import CancelScope

    gate = FairShareGate(slots=1)
    first = gate.acquire("a")
    scope = CancelScope("q")
    scope.cancel()
    with pytest.raises(QueryCancelledError):
        gate.acquire("b", scope=scope)
    gate.release(first)
    # the abandoned waiter must not have swallowed the freed slot
    t = gate.acquire("c")
    gate.release(t)


def test_gate_pause_resume_charges_separately(svc_conf):
    gate = FairShareGate(slots=1)
    turn = gate.acquire("p")
    time.sleep(0.02)
    gate.pause(turn)
    charged_mid = gate.shares()["p"]["charged_ns"]
    assert charged_mid > 0
    assert not turn.held
    # while paused the slot is free for someone else
    other = gate.acquire("q")
    gate.release(other)
    gate.resume(turn)
    assert turn.held
    gate.release(turn)
    assert gate.shares()["p"]["charged_ns"] >= charged_mid


# ------------------------------------------- 4. admission + HTTP units

def test_queue_timeout_sheds_typed(armed_monitor, svc_conf):
    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(4)
    conf.SERVICE_QUEUE_TIMEOUT_MS.set(60)
    svc = QueryService().start()
    try:
        slow = svc.submit("slowq",
                          build=lambda: _make_plan(1, rows=6000, batches=6))
        queued = svc.submit("queuedq", build=lambda: _make_plan(2))
        with pytest.raises(QueryRejectedError) as ei:
            queued.result(timeout=60)
        assert ei.value.reason == "queue_timeout"
        assert queued.status == "rejected"
        slow.result(timeout=120)
    finally:
        svc.shutdown()


def test_shutdown_sheds_queue_and_cancels_running(armed_monitor, svc_conf):
    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(4)
    svc = QueryService().start()
    running = svc.submit("runner",
                         build=lambda: _make_plan(1, rows=6000, batches=8))
    queued = svc.submit("parked", build=lambda: _make_plan(2))
    svc.shutdown()
    with pytest.raises(QueryRejectedError) as ei:
        queued.result(timeout=30)
    assert ei.value.reason == "shutdown"
    # the running query was cancelled or finished first — terminal
    # either way, never hung
    try:
        running.result(timeout=30)
        assert running.status == "done"
    except QueryCancelledError:
        assert running.status == "cancelled"
    assert service.service_threads() == []


def test_http_submit_mapping(armed_monitor, svc_conf):
    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(0)
    assert service.http_submit({"query": "x"})[0] == 503
    svc = QueryService().start()
    try:
        service.set_http_builders({"demo": lambda: _make_plan(3)})
        status, doc = service.http_submit({"query": "nope"})
        assert status == 404
        status, doc = service.http_submit(
            {"query": "demo", "pool": "web", "session": "s9"})
        assert status == 200
        assert doc["rows"] == 10000 and doc["pool"] == "web"
        # saturate the one slot, then a second submission is shed 429
        blocker = svc.submit(
            "blocker", build=lambda: _make_plan(1, rows=6000, batches=6))
        status, doc = service.http_submit({"query": "demo"})
        assert status == 429 and doc["retryable"] is True
        blocker.result(timeout=120)
    finally:
        service.set_http_builders({})
        svc.shutdown()


def test_http_submit_over_real_server(armed_monitor, svc_conf):
    """End-to-end over the wire: POST /service/submit returns 200 with
    rows, and a shed submission answers HTTP 429."""
    import urllib.error
    import urllib.request

    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(0)
    srv = monitor.ensure_server()
    assert srv is not None
    svc = QueryService().start()
    try:
        service.set_http_builders({
            "demo": lambda: _make_plan(3),
            "slow": lambda: _make_plan(1, rows=6000, batches=6)})

        def post(doc):
            req = urllib.request.Request(
                srv.url + "/service/submit",
                data=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    return r.status, json.load(r)
            except urllib.error.HTTPError as e:
                return e.code, json.load(e)

        status, doc = post({"query": "demo", "pool": "web"})
        assert status == 200 and doc["rows"] == 10000
        blocker = svc.submit("blocker",
                             build=lambda: _make_plan(1, rows=6000,
                                                      batches=6))
        status, doc = post({"query": "demo"})
        assert status == 429 and doc["retryable"] is True
        blocker.result(timeout=120)
    finally:
        service.set_http_builders({})
        svc.shutdown()


# ------------------------------------------------- 5. backpressure

def test_backpressure_bounds_buffering(armed_monitor, svc_conf):
    """A slow consumer never sees more than resultQueueDepth batches
    buffered: the producer blocks on the bounded queue (holding no
    lease turn) instead of ballooning host memory."""
    conf.SERVICE_RESULT_QUEUE_DEPTH.set(2)
    svc = QueryService().start()
    try:
        h = svc.submit("bp", build=lambda: _make_plan(5, rows=500,
                                                      batches=6, parts=4))
        got = 0
        for b in h.batches(timeout=120):
            assert h._q.qsize() <= 2
            got += b.num_rows
            time.sleep(0.01)  # slow consumer
        assert h.status == "done" and got == h.rows
    finally:
        svc.shutdown()


def test_abandoned_consumer_cancels_producer(armed_monitor, svc_conf):
    conf.SERVICE_RESULT_QUEUE_DEPTH.set(1)
    svc = QueryService().start()
    try:
        h = svc.submit("abandoned",
                       build=lambda: _make_plan(5, rows=4000, batches=6,
                                                parts=4))
        it = h.batches(timeout=60)
        next(it)          # producer is now blocked on the full queue
        h.close()         # consumer walks away
        assert h.wait(30), "producer wedged after its consumer left"
        assert h.status in ("cancelled", "done")
    finally:
        svc.shutdown()
    assert service.service_threads() == []


# ------------------------------------------------- 6. supervision

def test_deadline_enforced_per_submission(armed_monitor, svc_conf):
    svc = QueryService().start()
    try:
        h = svc.submit("deadline",
                       build=lambda: _make_plan(1, rows=8000, batches=8),
                       timeout_ms=1)
        with pytest.raises(QueryCancelledError) as ei:
            h.result(timeout=60)
        assert ei.value.reason == "deadline"
    finally:
        svc.shutdown()


def test_wedge_reap_via_heartbeat_age(armed_monitor, svc_conf):
    """A query that stops beating (its task stalls cooperatively
    before producing any batch) is reaped by the supervisor once its
    registry heartbeat age crosses spark.blaze.service.wedgeMs —
    cancelled with reason="wedged"."""
    from blaze_tpu.serde import from_proto

    conf.SERVICE_WEDGE_MS.set(150)
    orig = from_proto.run_task

    def stalling_run_task(td, *a, cancel_event=None, **kw):
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if cancel_event is not None and cancel_event.is_set():
                break
            time.sleep(0.01)
        return orig(td, *a, cancel_event=cancel_event, **kw)

    from_proto.run_task = stalling_run_task
    svc = QueryService().start()
    t0 = time.monotonic()
    try:
        h = svc.submit("wedged", build=lambda: _make_plan(1))
        with pytest.raises(QueryCancelledError) as ei:
            h.result(timeout=60)
        assert ei.value.reason == "wedged"
        assert time.monotonic() - t0 < 15, "reap took the stall timeout"
    finally:
        from_proto.run_task = orig
        svc.shutdown()


# ------------------------- 7. concurrent monitor correctness (PR 8 style)

def test_concurrent_queries_no_cross_attribution(armed_monitor, svc_conf):
    """Two queries running SIMULTANEOUSLY (barrier-interleaved per
    batch, so both are mid-flight the whole time) land their own rows,
    heartbeats, and counters in /queries and /metrics — no
    cross-attribution — under the armed lockset + lock-order
    checkers."""
    barrier = threading.Barrier(2, timeout=30)

    class GatedScan(MemoryScanExec):
        def execute(self, partition, ctx):
            for b in super().execute(partition, ctx):
                barrier.wait()
                yield b

    def run_one(qid, rows, out):
        batches = [[batch_from_pydict(
            {"k": list(range(rows)), "v": [1] * rows}, SCHEMA)
            for _ in range(3)]]
        plan = GatedScan(batches, SCHEMA)
        try:
            with monitor.query_span(qid, mode="in-process"):
                tally = []
                monitor.drive_result_stage(
                    plan, lambda b: tally.append(b.num_rows))
                out[qid] = sum(tally)
        except BaseException as e:  # noqa: BLE001
            out[qid] = e

    out = {}
    ta = threading.Thread(target=run_one, args=("qa", 300, out),
                          daemon=True)
    tb = threading.Thread(target=run_one, args=("qb", 40, out),
                          daemon=True)
    ta.start()
    tb.start()
    ta.join(60)
    tb.join(60)
    assert out["qa"] == 900 and out["qb"] == 120, f"bad drive: {out}"
    snap = monitor.snapshot()
    by_id = {q["query_id"]: q for q in snap["queries"]}
    assert by_id["qa"]["stages"][0]["rows"] == 900
    assert by_id["qb"]["stages"][0]["rows"] == 120
    assert by_id["qa"]["status"] == "done"
    assert by_id["qb"]["status"] == "done"
    text = monitor.render_prometheus()
    rows_by_query = {}
    for line in text.splitlines():
        if line.startswith("blaze_query_stage_rows{"):
            labels, value = line.rsplit(" ", 1)
            for qid in ("qa", "qb"):
                if f'query="{qid}"' in labels:
                    rows_by_query[qid] = int(float(value))
    assert rows_by_query == {"qa": 900, "qb": 120}
    assert lockset.reported() == []


# --------------------------------------------------- 8. satellites

def test_history_jsonl_and_queries_all(armed_monitor, svc_conf, tmp_path):
    """Finished-query summaries persist to the JSONL history and
    /queries?all=1 serves them after the in-memory ring forgot —
    including across a monitor reset."""
    conf.MONITOR_HISTORY_DIR.set(str(tmp_path))
    monitor.reset()
    with monitor.query_span("remembered", mode="in-process",
                            pool="etl", session="s1"):
        pass
    hist = monitor.read_history()
    assert [h["query_id"] for h in hist] == ["remembered"]
    assert hist[0]["status"] == "done"
    assert hist[0]["pool"] == "etl" and hist[0]["session"] == "s1"
    # live snapshot dedups: the entry is still in the ring
    snap = monitor.snapshot(include_history=True)
    assert [q["query_id"] for q in snap["queries"]] == ["remembered"]
    # after a reset the ring is empty — only ?all=1 still serves it
    monitor.reset()
    conf.MONITOR_HISTORY_DIR.set(str(tmp_path))
    monitor.reset()
    assert monitor.snapshot()["queries"] == []
    snap = monitor.snapshot(include_history=True)
    assert [q["query_id"] for q in snap["queries"]] == ["remembered"]
    conf.MONITOR_HISTORY_DIR.set("")
    monitor.reset()


def test_history_rollover_size_capped(armed_monitor, svc_conf, tmp_path):
    conf.MONITOR_HISTORY_DIR.set(str(tmp_path))
    conf.MONITOR_HISTORY_MAX_BYTES.set(512)
    monitor.reset()
    for i in range(12):
        with monitor.query_span(f"roll{i}", mode="in-process"):
            pass
    segs = glob.glob(str(tmp_path / "history-*.jsonl.seg*"))
    assert segs, "history never rolled over past the size cap"
    got = [h["query_id"] for h in monitor.read_history()]
    assert got == [f"roll{i}" for i in range(12)], (
        "rollover lost or reordered history entries")
    conf.MONITOR_HISTORY_DIR.set("")
    conf.MONITOR_HISTORY_MAX_BYTES.set(4 << 20)
    monitor.reset()


def test_statsd_lines_and_pusher(armed_monitor, svc_conf):
    import socket

    with monitor.query_span("statsq", mode="in-process"):
        pass
    lines = monitor.render_statsd_lines()
    assert any(ln.startswith("blaze_monitor_queries:") and ln.endswith("|g")
               for ln in lines), lines[:5]
    labeled = [ln for ln in lines if ln.startswith("blaze_query_elapsed")]
    assert labeled and ".statsq:" in labeled[0], (
        "label values must flatten into the statsd metric name")
    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(10)
    try:
        pusher = monitor._StatsdPusher(
            f"127.0.0.1:{sink.getsockname()[1]}").start()
        try:
            data, _ = sink.recvfrom(65536)
            assert b"|g" in data
        finally:
            pusher.shutdown()
        assert not pusher._thread.is_alive()
    finally:
        sink.close()


def test_statsd_disarmed_is_structural_noop(armed_monitor, svc_conf):
    assert str(conf.MONITOR_STATSD.get() or "") == ""
    monitor.ensure_server()
    assert monitor._STATSD_PUSHER is None
    assert not [t for t in threading.enumerate()
                if t.name == "blaze-monitor-statsd"]


def test_queries_surface_per_task_kernel_split(armed_monitor, svc_conf,
                                               tmp_path):
    """With tracing armed, /queries carries each task's
    device_ns/dispatch_ns split (from the PR 3 kernel sinks) and the
    --watch table renders the dev/disp column."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with monitor.query_span("ksplit", mode="scheduler"):
            stages, manager = split_stages(_make_plan(3))
            assert sum(b.num_rows for b in run_stages(stages, manager)) > 0
        snap = monitor.snapshot()
        q = next(x for x in snap["queries"] if x["query_id"] == "ksplit")
        map_stage = next(s for s in q["stages"] if s["kind"] == "map")
        assert map_stage["device_ns"] > 0, (
            "traced map tasks must surface their device-time split")
        task = next(iter(map_stage["tasks"].values()))
        assert task["device_ns"] > 0
        assert "dispatch_ns" in task
        watch = monitor.render_watch(snap)
        assert "dev/disp" in watch
        # heartbeat events carry the same split
        events = trace.read_event_log(
            glob.glob(str(tmp_path / "ksplit-*.jsonl"))[0])
        beats = [e for e in events if e["type"] == "task_heartbeat"]
        assert beats and all("device_ns" in e for e in beats)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


def test_untraced_beats_report_zero_split(armed_monitor, svc_conf):
    with monitor.query_span("nosplit", mode="scheduler"):
        stages, manager = split_stages(_make_plan(4))
        assert sum(b.num_rows for b in run_stages(stages, manager)) > 0
    snap = monitor.snapshot()
    q = next(x for x in snap["queries"] if x["query_id"] == "nosplit")
    for st in q["stages"]:
        assert st["device_ns"] == 0 and st["dispatch_ns"] == 0


def test_service_stats_in_queries_and_metrics(armed_monitor, svc_conf):
    svc = QueryService().start()
    try:
        h = svc.submit("statq", pool="etl", session="s2",
                       build=lambda: _make_plan(6))
        h.result(timeout=120)
        snap = monitor.snapshot()
        assert snap["service"]["counters"]["queries_admitted"] == 1
        assert "etl" in snap["service"]["pools"]
        entry = next(q for q in snap["queries"]
                     if q["query_id"] == "statq")
        assert entry["pool"] == "etl" and entry["session"] == "s2"
        text = monitor.render_prometheus()
        assert "blaze_service_queries_admitted 1" in text
        assert 'blaze_service_pool_weight{pool="etl"}' in text
        watch = monitor.render_watch(snap)
        assert "pool etl" in watch and "pool=etl" in watch
    finally:
        svc.shutdown()


def test_broadcast_ids_are_process_unique():
    """Concurrent service queries share the process RESOURCES map:
    broadcast ids minted per split_stages call must never collide."""
    from blaze_tpu.runtime.scheduler import next_broadcast_id

    a = next_broadcast_id()
    b = next_broadcast_id()
    assert a != b
