"""Shuffle: wire-format roundtrips, hash-partition writer/reader
end-to-end, broadcast exchange, ICI all-to-all path.

≙ reference batch/scalar serde roundtrip tests + the shuffle halves of
the TPC-DS differential suite (SURVEY.md §4)."""

import numpy as np
import pytest

import jax

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.io import deserialize_batch, serialize_batch
from blaze_tpu.io.ipc_compression import compress_frame, decompress_frame
from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr, MemoryScanExec
from blaze_tpu.parallel import (
    BroadcastExchangeExec,
    HashPartitioning,
    NativeShuffleExchangeExec,
)
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([
    Field("k", DataType.int64()),
    Field("s", DataType.string(16)),
    Field("d", DataType.decimal(12, 2)),
])


def make_batch(n, seed=0):
    rng = np.random.RandomState(seed)
    return batch_from_pydict(
        {
            "k": [int(v) if v % 7 else None for v in rng.randint(0, 50, n)],
            "s": [f"row{v}" if v % 5 else None for v in rng.randint(0, 99, n)],
            "d": [round(float(v), 2) for v in rng.uniform(-100, 100, n)],
        },
        SCHEMA,
    )


def test_batch_serde_roundtrip():
    b = make_batch(37)
    data = serialize_batch(b)
    b2 = deserialize_batch(data, SCHEMA)
    assert batch_to_pydict(b2) == batch_to_pydict(b)


def test_frame_roundtrip():
    payload = b"hello world" * 1000
    assert decompress_frame(compress_frame(payload)) == payload
    # incompressible stays raw
    raw = bytes(np.random.RandomState(0).bytes(100))
    assert decompress_frame(compress_frame(raw)) == raw


@pytest.mark.parametrize("in_process", [True, False])
def test_shuffle_exchange_end_to_end(in_process):
    """Both exchange data planes: the device-resident in-process fast
    path and the .data/.index file shuffle (the cross-process tier)."""
    from blaze_tpu import conf

    n_parts_in, n_parts_out = 3, 4
    batches = [[make_batch(50, seed=i)] for i in range(n_parts_in)]
    src = MemoryScanExec(batches, SCHEMA)
    old = conf.EXCHANGE_IN_PROCESS.get()
    conf.EXCHANGE_IN_PROCESS.set(in_process)
    try:
        _run_exchange_end_to_end(batches, src, n_parts_out)
    finally:
        conf.EXCHANGE_IN_PROCESS.set(old)


def _run_exchange_end_to_end(batches, src, n_parts_out):
    ex = NativeShuffleExchangeExec(src, HashPartitioning([col("k")], n_parts_out))

    all_rows = []
    seen_keys_per_part = []
    for p in range(n_parts_out):
        ctx = TaskContext(p, n_parts_out)
        keys = set()
        for b in ex.execute(p, ctx):
            d = batch_to_pydict(b)
            keys.update(d["k"])
            all_rows.extend(zip(d["k"], d["s"], d["d"]))
        seen_keys_per_part.append(keys)
    # row multiset preserved
    expected = []
    for part in batches:
        for b in part:
            d = batch_to_pydict(b)
            expected.extend(zip(d["k"], d["s"], d["d"]))
    key_of = lambda r: tuple((v is None, v) for v in r)
    assert sorted(all_rows, key=key_of) == sorted(expected, key=key_of)
    # co-partitioning: each key appears in exactly one output partition
    for i in range(n_parts_out):
        for j in range(i + 1, n_parts_out):
            assert not (seen_keys_per_part[i] & seen_keys_per_part[j])


def test_shuffle_plus_final_agg():
    """partial agg -> hash exchange on group key -> final agg ==
    the canonical two-stage group-by (TPC-H q01 shape)."""
    n_parts = 3
    batches = [[make_batch(80, seed=10 + i)] for i in range(n_parts)]
    src = MemoryScanExec(batches, SCHEMA)
    part = AggExec(
        src, AggMode.PARTIAL,
        [GroupingExpr(col("k"), "k")],
        [AggFunction("sum", col("d"), "sd"), AggFunction("count_star", None, "n")],
    )
    ex = NativeShuffleExchangeExec(part, HashPartitioning([col("k")], 4))
    final = AggExec(
        ex, AggMode.FINAL,
        [GroupingExpr(col("k"), "k")],
        part.aggs,
    )
    got = {}
    for p in range(4):
        for b in final.execute(p, TaskContext(p, 4)):
            d = batch_to_pydict(b)
            for k, sd, n in zip(d["k"], d["sd"], d["n"]):
                assert k not in got, "group split across partitions"
                got[k] = (sd, n)
    # oracle: plain python
    exp = {}
    for part_b in batches:
        for b in part_b:
            d = batch_to_pydict(b)
            for k, dd in zip(d["k"], d["d"]):
                s, c = exp.get(k, (0, 0))
                exp[k] = (s + (dd if dd is not None else 0), c + 1)
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1]
        assert got[k][0] == exp[k][0]


def test_broadcast_exchange_replicates():
    src = MemoryScanExec([[make_batch(10, seed=1)], [make_batch(5, seed=2)]], SCHEMA)
    bx = BroadcastExchangeExec(src)
    rows1 = sum(b.num_rows for b in bx.execute(0, TaskContext(0, 1)))
    rows2 = sum(b.num_rows for b in bx.execute(0, TaskContext(0, 1)))
    assert rows1 == rows2 == 15


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs virtual multi-device mesh")
def test_ici_all_to_all_exchange():
    from blaze_tpu.parallel.ici import ici_shuffle
    from blaze_tpu.parallel.mesh import make_mesh

    n_dev = 4
    mesh = make_mesh(n_dev)
    cap = 64
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    rng = np.random.RandomState(3)
    ks = rng.randint(0, 1000, n_dev * cap)
    per_shard_rows = np.full(n_dev, cap, np.int32)
    # make some rows padding on each shard
    per_shard_rows[1] = 30
    batch = batch_from_pydict(
        {"k": ks.tolist(), "v": list(range(n_dev * cap))}, schema, capacity=n_dev * cap
    )
    out_cols, totals = ici_shuffle(mesh, batch, per_shard_rows, [col("k")])
    totals = np.asarray(totals)
    total_rows = int(totals.sum())
    assert total_rows == cap * (n_dev - 1) + 30
    # verify each received row landed on the right device
    from blaze_tpu.exprs.hash import murmur3_columns, pmod
    from blaze_tpu.batch import Column

    k_all = np.asarray(out_cols[0].data)      # (n_dev * local_out,)
    valid = np.asarray(out_cols[0].validity)
    local_out = k_all.shape[0] // n_dev
    for d in range(n_dev):
        seg = k_all[d * local_out : (d + 1) * local_out]
        vmask = valid[d * local_out : (d + 1) * local_out]
        kept = seg[vmask]
        if kept.size:
            c = Column(DataType.int64(), kept.astype(np.int64), np.ones(kept.size, bool))
            pids = np.asarray(pmod(murmur3_columns([c]), n_dev))
            assert (pids == d).all()


def test_range_partitioned_global_sort():
    """RangePartitioning exchange + per-partition sorts == global sort:
    partitions hold disjoint key ranges in partition order (incl. nulls
    and string keys)."""
    from blaze_tpu.ops import SortExec, SortField
    from blaze_tpu.parallel import RangePartitioning

    n_parts_in, n_out = 3, 4
    batches = [[make_batch(60, seed=20 + i)] for i in range(n_parts_in)]
    src = MemoryScanExec(batches, SCHEMA)
    fields = [SortField(col("k"), ascending=True, nulls_first=True),
              SortField(col("s"), ascending=False, nulls_first=False)]
    ex = NativeShuffleExchangeExec(src, RangePartitioning(fields, n_out))
    # per-partition sort, then concatenate partitions in order
    srt = SortExec(ex, fields)
    rows = []
    for p in range(n_out):
        for b in srt.execute(p, TaskContext(p, n_out)):
            d = batch_to_pydict(b)
            rows.extend(zip(d["k"], d["s"], d["d"]))
    # oracle: global sort of all input rows by the same keys
    allrows = []
    for part in batches:
        for b in part:
            d = batch_to_pydict(b)
            allrows.extend(zip(d["k"], d["s"], d["d"]))

    # compare the primary-key order and the row multiset (secondary
    # tie-break details differ between python and engine comparators)
    ks = [r[0] for r in rows]
    exp_ks = sorted((r[0] for r in allrows), key=lambda v: (v is not None, v))
    assert ks == exp_ks
    key_of = lambda r: tuple((v is None, v) for v in r)
    assert sorted(rows, key=key_of) == sorted(allrows, key=key_of)


def test_range_partitioning_mixed_string_widths():
    """Range keys over string columns whose physical padded widths
    differ per batch (runtime-width strings): word counts are aligned
    per field, so ordering stays correct."""
    from blaze_tpu.batch import Column, RecordBatch
    from blaze_tpu.ops import SortExec, SortField
    from blaze_tpu.parallel import RangePartitioning

    schema = Schema([Field("s", DataType.string(16))])

    def batch_with_width(strings, width):
        n = len(strings)
        data = np.zeros((n, width), np.uint8)
        lengths = np.zeros(n, np.int32)
        for i, t in enumerate(strings):
            b = t.encode()
            data[i, : len(b)] = np.frombuffer(b, np.uint8)
            lengths[i] = len(b)
        col_ = Column(DataType.string(16), data, np.ones(n, bool), lengths)
        return RecordBatch(schema, [col_], n)

    b1 = batch_with_width(["apple", "zebra", "mango"], 8)       # 1 data word
    b2 = batch_with_width(["banana", "cherry", "apricots"], 16)  # 2 data words
    src = MemoryScanExec([[b1], [b2]], schema)
    ex = NativeShuffleExchangeExec(src, RangePartitioning([SortField(col("s"))], 2))
    srt = SortExec(ex, [SortField(col("s"))])
    got = []
    for p in range(2):
        for b in srt.execute(p, TaskContext(p, 2)):
            got.extend(batch_to_pydict(b)["s"])
    assert got == sorted(["apple", "zebra", "mango", "banana", "cherry", "apricots"])

    # DESCENDING with prefix-related keys across widths: inverted
    # padding words (~0) must not disagree with a narrower batch's
    # normalized words (regression: zero-word alignment broke this)
    b1 = batch_with_width(["applepie", "zebra", "aaa"], 8)
    b2 = batch_with_width(["applepieX", "applepie", "mango"], 16)
    src = MemoryScanExec([[b1], [b2]], schema)
    fields_d = [SortField(col("s"), ascending=False)]
    ex = NativeShuffleExchangeExec(src, RangePartitioning(fields_d, 2))
    srt = SortExec(ex, fields_d)
    got = []
    for p in range(2):
        for b in srt.execute(p, TaskContext(p, 2)):
            got.extend(batch_to_pydict(b)["s"])
    assert got == sorted(
        ["applepie", "zebra", "aaa", "applepieX", "applepie", "mango"], reverse=True
    )


def test_inprocess_exchange_hbm_budget_fallback():
    """A stage output beyond the HBM budget falls back to the spillable
    file shuffle instead of accumulating device-resident."""
    from blaze_tpu import conf

    n_parts_in, n_parts_out = 3, 4
    batches = [[make_batch(50, seed=i)] for i in range(n_parts_in)]
    src = MemoryScanExec(batches, SCHEMA)
    old = conf.DEVICE_MEMORY_BUDGET.get()
    conf.DEVICE_MEMORY_BUDGET.set(1024)  # absurdly small
    try:
        ex = NativeShuffleExchangeExec(src, HashPartitioning([col("k")], n_parts_out))
        _run_exchange_end_to_end(batches, src, n_parts_out)
        # the helper builds its own exchange; run this one too to see
        # the fallback flag flip
        rows = 0
        for p in range(n_parts_out):
            for b in ex.execute(p, TaskContext(p, n_parts_out)):
                rows += b.num_rows
        assert ex._hbm_fallback
        assert rows == sum(b.num_rows for part in batches for b in part)
    finally:
        conf.DEVICE_MEMORY_BUDGET.set(old)


def test_range_partitioning_across_serde_file_shuffle():
    """Range-partitioned global sort through the STAGE SCHEDULER: the
    scheduler's driver-side boundary pass fills the partitioning's
    boundary words, every map task crosses the TaskDefinition protobuf
    boundary, and the shuffle rides real .data/.index files — the
    distributed path the in-process exchange cannot cover
    (≙ Spark's RangePartitioner sample job + ShuffleDependency)."""
    from blaze_tpu import conf
    from blaze_tpu.ops import SortExec, SortField
    from blaze_tpu.parallel import RangePartitioning
    from blaze_tpu.runtime.scheduler import run_stages, split_stages

    old = conf.EXCHANGE_IN_PROCESS.get()
    conf.EXCHANGE_IN_PROCESS.set(False)  # force the file-shuffle tier
    try:
        n_parts_in, n_out = 3, 4
        batches = [[make_batch(60, seed=40 + i)] for i in range(n_parts_in)]
        src = MemoryScanExec(batches, SCHEMA)
        fields = [SortField(col("k"), ascending=True, nulls_first=True)]
        ex = NativeShuffleExchangeExec(src, RangePartitioning(fields, n_out))
        plan = SortExec(ex, fields)
        stages, manager = split_stages(plan)
        rows = []
        for b in run_stages(stages, manager):
            d = batch_to_pydict(b)
            rows.extend(zip(d["k"], d["s"], d["d"]))
        allrows = []
        for part in batches:
            for b in part:
                d = batch_to_pydict(b)
                allrows.extend(zip(d["k"], d["s"], d["d"]))
        ks = [r[0] for r in rows]
        exp_ks = sorted((r[0] for r in allrows), key=lambda v: (v is not None, v))
        assert ks == exp_ks, "global order broken across the serde boundary"
        key_of = lambda r: tuple((v is None, v) for v in r)
        assert sorted(rows, key=key_of) == sorted(allrows, key=key_of)
        # the boundary pass must have filled serializable boundaries
        assert ex.partitioning.boundaries is not None
    finally:
        conf.EXCHANGE_IN_PROCESS.set(old)
