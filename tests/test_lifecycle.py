"""Query lifecycle robustness (ISSUE 9): end-to-end cancellation,
deadlines, and graceful degradation under memory pressure.

1. **CancelScope units**: first-cancel-wins, fan-out into attached
   attempt events, deadline expiry raising the typed error with the
   stage/task frontier, registry lookup via ``cancel_query``.
2. **OOM ladder**: the ``@oom`` faults grammar, RESOURCE_EXHAUSTED
   classification, batch splitting, the FusedStageExec rungs
   (downshift -> eager -> DeviceOomError) each byte-identical to the
   undisturbed run, the tier-5 fused-write fallback, and an injected
   mid-query OOM absorbed end-to-end through the scheduler.
3. **Cancellation end-to-end**: an external ``cancel_query`` against a
   live scheduler run returns QueryCancelledError promptly, the
   registry shows the terminal status, the event log pairs
   ``query_cancel_requested`` with ``query_cancelled``, and nothing
   leaks — no attempt thread, no ``.inprogress`` shuffle temp, no
   ``blaze_spill_*`` file (the cancellation resource leak, fixed).
4. **Interleaving** (test_guarded.py style): a query cancel racing the
   winner attempt's shuffle commit — the commit is all-or-nothing,
   never a partial file.
5. **Surfacing**: /queries//metrics/--watch terminal statuses and
   degradation counters, with the finished-query gauge rule intact.
"""

import glob
import json
import os
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.runtime import (dispatch, errors, faults, ledger, monitor,
                               oom, trace)
from blaze_tpu.runtime.context import (
    CancelScope, QueryCancelledError, QueryDeadlineError, cancel_query,
    cancel_scope, current_cancel_scope,
)
from blaze_tpu.runtime.retry import FATAL, RETRY, classify
from blaze_tpu.runtime.scheduler import run_stages, split_stages

import spark_fixtures as F  # noqa: E402
from test_spark_convert import make_session, q6_like_plan  # noqa: E402


# the one leak oracle (runtime/ledger.py) — the hand-rolled sweep this
# suite used to carry moved there (ISSUE 15 consolidation)
_attempt_threads = ledger.attempt_threads


@pytest.fixture(autouse=True)
def _clean_lifecycle():
    """Every scenario starts with no faults, no deadline, the default
    ladder depth, and leaves nothing armed, registered, or running.
    The whole suite runs with the error-escape recorder AND the
    per-query resource ledger armed (spark.blaze.verify.errors): a
    FATAL-class error absorbed at an audited broad-except site, or a
    spill/temp/registration/lease still live at query end, fails the
    test that caused it."""
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    conf.QUERY_TIMEOUT_MS.set(0)
    faults.reset()
    conf.VERIFY_ERRORS.set(True)
    errors.refresh()
    ledger.refresh()
    yield
    escaped = errors.escapes()
    leaked = ledger.leaks()
    conf.VERIFY_ERRORS.set(False)
    errors.refresh()
    ledger.refresh()
    assert escaped == [], (
        "FATAL-class error absorbed at an audited site: "
        + "; ".join(escaped))
    assert leaked == [], "resource-ledger leaks: " + "; ".join(leaked)
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.1)
    conf.QUERY_TIMEOUT_MS.set(0)
    conf.OOM_MAX_DOWNSHIFTS.set(2)
    conf.TRACE_ENABLE.set(False)
    conf.EVENT_LOG_DIR.set("")
    conf.MONITOR_ENABLE.set(False)
    conf.MONITOR_HEARTBEAT_MS.set(1000)
    faults.reset()
    trace.reset()
    monitor.reset()
    deadline = time.monotonic() + 10
    while _attempt_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _attempt_threads() == [], "leaked attempt threads"


def _scheduler_rows(sess, plan_json):
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    out = []
    for b in run_stages(stages, manager):
        out.append(b)
    return out, manager


# ------------------------------------------------- 1. CancelScope units

def test_cancel_scope_first_cancel_wins_and_fans_out():
    scope = CancelScope("q0")
    attached = threading.Event()
    scope.attach(attached)
    assert scope.cancel("cancel") is True
    assert scope.cancel("deadline") is False  # idempotent, reason kept
    assert scope.reason == "cancel" and scope.cancelled
    assert attached.is_set()
    # attaching to an already-cancelled scope fires immediately
    late = threading.Event()
    scope.attach(late)
    assert late.is_set()
    with pytest.raises(QueryCancelledError) as ei:
        scope.check(3, 1)
    assert ei.value.stage_id == 3 and ei.value.task == 1
    assert ei.value.query_id == "q0"


def test_cancel_scope_deadline_raises_typed_with_frontier():
    scope = CancelScope("qd", timeout_ms=1)
    time.sleep(0.01)
    with pytest.raises(QueryDeadlineError) as ei:
        scope.check(2, 0)
    assert ei.value.reason == "deadline"
    assert ei.value.timeout_ms == 1
    assert ei.value.stage_id == 2 and ei.value.task == 0
    # a deadline IS a cancel: one except clause catches both
    assert isinstance(ei.value, QueryCancelledError)


def test_cancel_query_reaches_registered_scope_only():
    assert cancel_query("nope") is False
    with cancel_scope("q_reg", timeout_ms=0) as scope:
        assert current_cancel_scope() is scope
        assert cancel_query("q_reg") is True
        assert scope.cancelled
        assert cancel_query("q_reg") is True  # idempotent
    assert cancel_query("q_reg") is False  # unregistered on exit


def test_classification_cancel_fatal_oom_retryable():
    assert classify(QueryCancelledError("q")) == FATAL
    assert classify(QueryDeadlineError("q", 5)) == FATAL
    assert classify(oom.DeviceOomError("fused_stage")) == RETRY


# ------------------------------------------------ 2. OOM ladder pieces

def test_oom_faults_grammar():
    rules = faults.parse_spec("kernel.dispatch@3@oom,task.compute@1@a0")
    assert rules[0] == ("kernel.dispatch", 3, None, None, True)
    assert rules[1] == ("task.compute", 1, 0, None, False)
    assert faults.format_spec(rules) == \
        "kernel.dispatch@3@oom,task.compute@1@a0"
    with pytest.raises(ValueError):
        faults.parse_spec("task.compute@1@oom@slow100")  # exclusive
    with pytest.raises(ValueError):
        faults.parse_spec("task.compute@1@oom@oom")
    spec = faults.random_spec(11, n_faults=0, n_ooms=2)
    assert spec.count("@oom") == 2 and "kernel.dispatch@" in spec


def test_injected_oom_is_resource_exhausted():
    exc = faults.InjectedOom("kernel.dispatch", 1)
    assert oom.is_resource_exhausted(exc)
    assert oom.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating ..."))
    assert not oom.is_resource_exhausted(RuntimeError("boom"))
    assert not oom.is_resource_exhausted(MemoryError())  # host OOM: FATAL


def test_split_batch_halves_preserve_rows():
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("x", DataType.int64())])
    b = batch_from_pydict({"x": list(range(11))}, schema)
    pieces = oom.split_batch(b)
    assert [p.num_rows for p in pieces] == [5, 6]
    got = [v for p in pieces for v in batch_to_pydict(p)["x"]]
    assert got == list(range(11))
    one = batch_from_pydict({"x": [7]}, schema)
    assert oom.split_batch(one) == [one]


def _fused_chain_plan(n_rows=600, parts=2):
    """scan -> filter -> project collapsed into one FusedStageExec."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.exprs.ir import Alias, BinOp, Lit
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.fusion import FusedStageExec, fuse_traceable_chains
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("x", DataType.int64()),
                     Field("y", DataType.int64())])
    rng = np.random.RandomState(3)
    per = n_rows // parts
    batches = [
        [batch_from_pydict(
            {"x": [int(v) for v in rng.randint(0, 100, per)],
             "y": [int(v) for v in rng.randint(0, 100, per)]}, schema)]
        for _ in range(parts)
    ]
    scan = MemoryScanExec(batches, schema)
    f = FilterExec(scan, BinOp(">", col("x"), Lit(20, DataType.int64())))
    p = ProjectExec(f, [col("x"),
                        Alias(BinOp("+", col("y"), Lit(1, DataType.int64())),
                              "y1")], ["x", "y1"])
    plan = fuse_traceable_chains(p)
    assert isinstance(plan, FusedStageExec)
    return plan


def _drive(plan):
    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.runtime.context import TaskContext

    rows = {"x": [], "y1": []}
    for part in range(plan.num_partitions()):
        for b in plan.execute(part, TaskContext(part, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in rows:
                rows[k].extend(d[k])
    return rows


def _flaky_kernel(plan, fail_calls):
    """Replace the fused program with one that raises
    RESOURCE_EXHAUSTED on the given 1-based call numbers."""
    real = plan._kernel
    calls = {"n": 0}

    def flaky(cols, num_rows):
        calls["n"] += 1
        if calls["n"] in fail_calls:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected test OOM")
        return real(cols, num_rows)

    plan._kernel = flaky
    return calls


def test_fused_stage_downshift_identical():
    baseline = _drive(_fused_chain_plan())
    plan = _fused_chain_plan()
    _flaky_kernel(plan, {1})  # first batch OOMs once -> split in half
    with dispatch.capture() as cap:
        got = _drive(plan)
    assert got == baseline
    assert cap.get("batch_downshifts") == 1
    assert not cap.get("eager_fallbacks")


def test_fused_stage_eager_fallback_identical():
    baseline = _drive(_fused_chain_plan())
    conf.OOM_MAX_DOWNSHIFTS.set(0)  # rung 2 disabled -> straight to eager
    plan = _fused_chain_plan()
    _flaky_kernel(plan, {1})
    with dispatch.capture() as cap:
        got = _drive(plan)
    assert got == baseline
    assert cap.get("eager_fallbacks") == 1
    assert not cap.get("batch_downshifts")


def test_fused_stage_ladder_exhausted_raises_device_oom():
    conf.OOM_MAX_DOWNSHIFTS.set(0)
    plan = _fused_chain_plan()
    _flaky_kernel(plan, set(range(1, 100)))

    def eager_boom(batch):
        raise RuntimeError("RESOURCE_EXHAUSTED: still too big")

    plan._eager_run = eager_boom
    with pytest.raises(oom.DeviceOomError):
        _drive(plan)


def test_fused_stage_non_oom_errors_propagate_unladdered():
    plan = _fused_chain_plan()
    real = plan._kernel
    plan._kernel = lambda cols, n: (_ for _ in ()).throw(
        ValueError("not an OOM"))
    with pytest.raises(ValueError):
        _drive(plan)
    plan._kernel = real


def test_fused_write_oom_falls_back_byte_identical(tmp_path):
    """Tier-5 fused shuffle write: an OOM mid-stream decomposes to the
    per-kernel path (absorbed chain transforms still applied) and the
    committed .data/.index files are byte-identical to the fused
    run's."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops.fusion import optimize_plan
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec
    from blaze_tpu.runtime.context import TaskContext

    def write(tag, sabotage):
        plan = _fused_chain_plan()
        data = str(tmp_path / f"{tag}.data")
        index = str(tmp_path / f"{tag}.index")
        w = optimize_plan(ShuffleWriterExec(
            plan, HashPartitioning([col("x")], 4), data, index))
        assert w._fused_write is not None and w._fused_fns
        if sabotage:
            real = w._fused_write
            state = {"n": 0}

            def flaky(*a):
                state["n"] += 1
                if state["n"] == 1:
                    raise RuntimeError("RESOURCE_EXHAUSTED: injected")
                return real(*a)

            w._fused_write = flaky
        list(w.execute(0, TaskContext(0, 1)))
        return open(data, "rb").read(), open(index, "rb").read()

    clean = write("clean", sabotage=False)
    with dispatch.capture() as cap:
        degraded = write("degraded", sabotage=True)
    assert degraded == clean
    assert cap.get("eager_fallbacks") == 1


def test_injected_oom_absorbed_end_to_end():
    """The acceptance shape: a seeded ``kernel.dispatch@N@oom`` on a
    scheduler run resolves via the ladder with byte-identical results,
    and the event log pairs the ``kind=oom`` fault with its
    ``oom_recovery``."""
    from blaze_tpu.runtime import trace_report

    sess, _ = make_session()
    baseline, _ = _scheduler_rows(sess, F.flatten(q6_like_plan()))
    base_rows = [b.num_rows for b in baseline]

    conf.TRACE_ENABLE.set(True)
    trace.reset()
    conf.FAULTS_SPEC.set("kernel.dispatch@2@oom")
    faults.reset()
    try:
        with dispatch.capture() as cap:
            with monitor.query_span("oom_e2e", mode="scheduler") as log:
                got, _ = _scheduler_rows(sess, F.flatten(q6_like_plan()))
    finally:
        conf.FAULTS_SPEC.set("")
        faults.reset()
        conf.TRACE_ENABLE.set(False)
        trace.reset()
    assert [b.num_rows for b in got] == base_rows
    from blaze_tpu.batch import batch_to_pydict

    assert [batch_to_pydict(b) for b in got] == \
        [batch_to_pydict(b) for b in baseline]
    assert cap.get("oom_recoveries", 0) >= 1
    events = trace.read_event_log(log)
    oom_faults = [e for e in events if e["type"] == "fault_injected"
                  and e.get("kind") == "oom"]
    assert len(oom_faults) == 1
    rec = trace_report.reconcile_faults(events)
    assert rec["reconciled"], rec["unpaired"]
    assert any(e["type"] == "oom_recovery" and e["action"] == "spill"
               for e in events)


# ------------------------------- 3. resource reclamation (the leak fix)

def test_repartitioner_release_reclaims_spill_files(monkeypatch):
    """The cancellation resource leak: a non-committing attempt's spill
    FILES must be reclaimed at rollback, not at process exit."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.parallel import shuffle as shuffle_mod
    from blaze_tpu.runtime.memmgr import FileSpill
    from blaze_tpu.runtime.metrics import MetricsSet
    from blaze_tpu.schema import DataType, Field, Schema

    made = []

    def file_spill(codec=None):
        sp = FileSpill("zlib")
        made.append(sp.path)
        return sp

    monkeypatch.setattr(shuffle_mod, "try_new_spill", file_spill)
    schema = Schema([Field("x", DataType.int64())])
    rep = shuffle_mod.ShuffleRepartitioner(schema, 2, MetricsSet())
    b = batch_from_pydict({"x": list(range(64))}, schema).to_host()
    rep.insert_sorted(b, np.array([32, 32]))
    assert rep.spill() > 0
    assert made and all(os.path.exists(p) for p in made)
    rep.release()
    assert not any(os.path.exists(p) for p in made), "spill files leaked"
    # idempotent — a second release (post-commit path) is a no-op
    rep.release()


def test_writer_releases_spills_on_cancel(monkeypatch, tmp_path):
    """A cancelled map attempt (mid-stream cancel event) exits without
    committing AND without leaking its spill files."""
    from blaze_tpu.exprs import col
    from blaze_tpu.parallel import shuffle as shuffle_mod
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.runtime.memmgr import FileSpill

    made = []

    def file_spill(codec=None):
        sp = FileSpill("zlib")
        made.append(sp.path)
        return sp

    monkeypatch.setattr(shuffle_mod, "try_new_spill", file_spill)
    conf.SHUFFLE_ASYNC_WRITE.set(False)
    try:
        plan = _fused_chain_plan()
        data = str(tmp_path / "c.data")
        w = ShuffleWriterExec(plan, HashPartitioning([col("x")], 4),
                              data, str(tmp_path / "c.index"))
        cancel = threading.Event()
        ctx = TaskContext(0, 1, cancel_event=cancel)
        stream = w.execute(0, ctx)
        # drive the side-effect stream with a spill forced mid-flight,
        # then cancel before the commit
        rep_holder = {}
        real_insert = shuffle_mod._insert_host

        def spilling_insert(rep, schema, item):
            rep_holder["rep"] = rep
            real_insert(rep, schema, item)
            rep.spill()
            cancel.set()

        monkeypatch.setattr(shuffle_mod, "_insert_host", spilling_insert)
        list(stream)
        assert made, "test never spilled"
        assert not any(os.path.exists(p) for p in made), "spill files leaked"
        assert not os.path.exists(data), "cancelled attempt committed"
    finally:
        conf.SHUFFLE_ASYNC_WRITE.set(True)


def test_manager_sweep_inprogress_units(tmp_path):
    from blaze_tpu.parallel.shuffle import LocalShuffleManager

    mgr = LocalShuffleManager(str(tmp_path))
    for fn in ("shuffle_0_1.data.inprogress.a2",
               "shuffle_0_1.index.inprogress.a2",
               "shuffle_0_2.data.inprogress.a0",
               "shuffle_1_0.data.inprogress.a1",
               "shuffle_0_1.data"):
        (tmp_path / fn).write_bytes(b"x")
    # exact (shuffle, map, attempt): only that attempt's temps go
    assert mgr.sweep_inprogress(0, 1, 2) == 2
    assert (tmp_path / "shuffle_0_2.data.inprogress.a0").exists()
    assert (tmp_path / "shuffle_0_1.data").exists()  # committed: kept
    # everything in-progress
    assert mgr.sweep_inprogress() == 2
    assert (tmp_path / "shuffle_0_1.data").exists()


# ------------------------------------ 4. cancellation end-to-end + HTTP

def _slow_spec(ms=250):
    return f"task.compute@1@slow{ms},task.compute@3@slow{ms}"


def test_external_cancel_mid_query_reconciles():
    sess, _ = make_session()
    conf.TRACE_ENABLE.set(True)
    trace.reset()
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_HEARTBEAT_MS.set(50)
    monitor.reset()
    conf.FAULTS_SPEC.set(_slow_spec())
    faults.reset()
    spills_before = set(glob.glob(ledger.spill_glob()))
    state = {}

    def run():
        try:
            with monitor.query_span("cxl_e2e", mode="scheduler") as lp:
                state["log"] = lp
                plan = sess.plan(F.flatten(q6_like_plan()))
                stages, mgr = split_stages(plan)
                state["root"] = mgr.root
                for b in run_stages(stages, mgr):
                    pass
        except BaseException as e:  # noqa: BLE001
            state["exc"] = e

    t = threading.Thread(target=run)
    t.start()
    for _ in range(400):  # wait until the scope is registered
        if cancel_query("cxl_e2e"):
            break
        time.sleep(0.005)
    t0 = time.monotonic()
    t.join(15)
    latency = time.monotonic() - t0
    assert not t.is_alive()
    assert isinstance(state.get("exc"), QueryCancelledError), state.get("exc")
    # prompt: well inside 2x the slow-fault sleep + heartbeat slack
    assert latency < 2.0, latency
    # registry terminal status
    snap = monitor.snapshot()
    q = next(x for x in snap["queries"] if x["query_id"] == "cxl_e2e")
    assert q["status"] == "cancelled"
    # event pairing
    from blaze_tpu.runtime import trace_report

    events = trace.read_event_log(state["log"])
    cxl = trace_report.reconcile_cancellation(events)
    assert cxl["requested"] == 1 and cxl["cancelled"] == 1
    assert cxl["reconciled"]
    end = next(e for e in events if e["type"] == "query_end")
    assert end["status"] == "cancelled"
    # zero leaks: threads, shuffle temps, spill files, ledger — the
    # one oracle (runtime/ledger.py) the chaos arms share
    assert ledger.leak_audit(shuffle_root=state["root"],
                             spills_before=spills_before) == []


def test_query_deadline_end_to_end():
    sess, _ = make_session()
    conf.QUERY_TIMEOUT_MS.set(120)
    conf.FAULTS_SPEC.set(_slow_spec(300))
    faults.reset()
    conf.MONITOR_ENABLE.set(True)
    monitor.reset()
    with pytest.raises(QueryDeadlineError) as ei:
        with monitor.query_span("ddl_e2e", mode="scheduler"):
            rows, _ = _scheduler_rows(sess, F.flatten(q6_like_plan()))
    assert ei.value.reason == "deadline"
    assert ei.value.stage_id is not None  # frontier recorded
    snap = monitor.snapshot()
    q = next(x for x in snap["queries"] if x["query_id"] == "ddl_e2e")
    assert q["status"] == "deadline_exceeded"


def test_http_cancel_endpoint(tmp_path):
    sess, _ = make_session()
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    conf.MONITOR_HEARTBEAT_MS.set(50)
    monitor.reset()
    srv = monitor.ensure_server()
    try:
        # unknown query: 404, cancelled=false
        req = urllib.request.Request(
            srv.url + "/queries/ghost/cancel", method="POST", data=b"")
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        conf.FAULTS_SPEC.set(_slow_spec())
        faults.reset()
        state = {}

        def run():
            try:
                with monitor.query_span("http_cxl", mode="scheduler"):
                    _scheduler_rows(sess, F.flatten(q6_like_plan()))
            except BaseException as e:  # noqa: BLE001
                state["exc"] = e

        t = threading.Thread(target=run)
        t.start()
        code = None
        for _ in range(400):
            req = urllib.request.Request(
                srv.url + "/queries/http_cxl/cancel", method="POST",
                data=b"")
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    code = r.status
                    body = json.loads(r.read())
                    break
            except urllib.error.HTTPError:
                time.sleep(0.005)  # scope not registered yet
        t.join(15)
        assert code == 200 and body == {"query_id": "http_cxl",
                                        "cancelled": True}
        assert isinstance(state.get("exc"), QueryCancelledError)
    finally:
        monitor.shutdown_server()
        conf.MONITOR_PORT.set(4048)
        assert monitor.monitor_threads() == []


# ---------------------- 5. cancel vs winner-commit interleaving (S3)

def _commit_barrier_writer(tmp_path, monkeypatch, tag):
    from blaze_tpu.exprs import col
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec

    plan = _fused_chain_plan()
    data = str(tmp_path / f"{tag}.data")
    index = str(tmp_path / f"{tag}.index")
    w = ShuffleWriterExec(plan, HashPartitioning([col("x")], 4), data, index)
    return w, data, index


def test_cancel_racing_winner_commit_is_all_or_nothing(tmp_path,
                                                       monkeypatch):
    """S3 interleaving: the cancel lands while the winner attempt is
    INSIDE write_output — past its last cooperative check.  The commit
    must complete fully (both files, readable, complete rows); a
    partial shuffle file must never appear.  Armed lock-order +
    lockset checkers stay quiet."""
    from blaze_tpu.analysis import locks as alocks
    from blaze_tpu.parallel.shuffle import ShuffleRepartitioner
    from blaze_tpu.runtime import lockset
    from blaze_tpu.runtime.context import TaskContext

    alocks.arm(True)
    lockset.arm(True)
    try:
        w, data, index = _commit_barrier_writer(tmp_path, monkeypatch, "win")
        in_commit = threading.Barrier(2, timeout=10)
        cancel_landed = threading.Barrier(2, timeout=10)
        cancel = threading.Event()
        real = ShuffleRepartitioner.write_output

        def gated(self, dp, ip):
            in_commit.wait()      # driver: commit has started
            cancel_landed.wait()  # driver has fired the cancel
            return real(self, dp, ip)

        monkeypatch.setattr(ShuffleRepartitioner, "write_output", gated)
        errs = []

        def winner():
            try:
                list(w.execute(0, TaskContext(0, 1, cancel_event=cancel)))
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=winner)
        t.start()
        in_commit.wait()
        cancel.set()              # the query cancel, mid-commit
        cancel_landed.wait()
        t.join(15)
        assert not t.is_alive() and not errs, errs
        # FULL commit: both files present, index consistent, rows whole
        assert os.path.exists(data) and os.path.exists(index)
        assert not any(".inprogress" in f for f in os.listdir(tmp_path))
        import struct

        raw = open(index, "rb").read()
        offsets = struct.unpack(f"<{len(raw) // 8}Q", raw)
        assert offsets[-1] == os.path.getsize(data)
        assert w.partition_lengths is not None
        assert sum(w.partition_lengths) == os.path.getsize(data)
    finally:
        alocks.arm(False)
        lockset.arm(False)


def test_cancel_before_commit_rolls_back_fully(tmp_path, monkeypatch):
    """S3 inverse interleaving: the cancel lands BEFORE the winner's
    commit check — the attempt must publish NOTHING (no data, no
    index, no .inprogress temp)."""
    from blaze_tpu.parallel import shuffle as shuffle_mod
    from blaze_tpu.runtime.context import TaskContext

    w, data, index = _commit_barrier_writer(tmp_path, monkeypatch, "lose")
    cancel = threading.Event()
    real_insert = shuffle_mod._insert_host
    conf.SHUFFLE_ASYNC_WRITE.set(False)
    try:
        def cancelling_insert(rep, schema, item):
            real_insert(rep, schema, item)
            cancel.set()          # lands between batches, pre-commit

        monkeypatch.setattr(shuffle_mod, "_insert_host", cancelling_insert)
        list(w.execute(0, TaskContext(0, 1, cancel_event=cancel)))
    finally:
        conf.SHUFFLE_ASYNC_WRITE.set(True)
    assert not os.path.exists(data) and not os.path.exists(index)
    assert not any(".inprogress" in f for f in os.listdir(tmp_path))
    assert w.partition_lengths is None


def test_cancel_during_result_drain_never_returns_truncated_ok():
    """Regression (review finding): the cooperative operator seams STOP
    yielding on cancel instead of raising, so a cancel landing while
    the final result task drains used to end the stream quietly and
    hand the caller a silently TRUNCATED row set with status ok.  The
    post-loop checkpoint must surface QueryCancelledError instead."""
    sess, _ = make_session(partitions=1)
    plan_json = F.flatten(q6_like_plan())
    # warm every kernel so the map stage is milliseconds
    _scheduler_rows(sess, plan_json)
    # hit 2 = the RESULT task's decode (1 map task + 1 result task):
    # the sleep guarantees the cancel lands before its plan drive,
    # so the cancelled agg yields NOTHING and the loop ends quietly
    conf.FAULTS_SPEC.set("task.compute@2@slow600")
    faults.reset()
    state = {}

    def run():
        try:
            with monitor.query_span("trunc_cxl", mode="scheduler"):
                state["out"] = _scheduler_rows(sess, plan_json)[0]
        except BaseException as e:  # noqa: BLE001
            state["exc"] = e

    t = threading.Thread(target=run)
    t.start()
    for _ in range(400):
        if cancel_query("trunc_cxl"):
            break
        time.sleep(0.005)
    t.join(15)
    assert not t.is_alive()
    # the one unacceptable outcome is a quiet return (truncated "ok")
    assert "out" not in state, "cancelled query returned truncated rows"
    assert isinstance(state.get("exc"), QueryCancelledError), \
        state.get("exc")


def test_cancel_reaches_concurrent_speculative_attempts():
    """A query cancel mid-stage with the concurrent attempt runner live
    (speculation armed) must stop ALL racing attempts: each attempt's
    private cancel event is attached to the scope, the runner's poll
    loop is a checkpoint, and every attempt thread joins — the
    regression for the res_scope/CancelScope shadowing bug where
    concurrent attempts never saw the query cancel."""
    sess, _ = make_session()
    conf.SPECULATION_ENABLE.set(True)
    conf.SPECULATION_WEDGE_MS.set(10_000)  # runner on, wedge quiet
    conf.STAGE_TASK_CONCURRENCY.set(2)
    conf.FAULTS_SPEC.set(_slow_spec(400))
    faults.reset()
    state = {}
    try:
        def run():
            try:
                with monitor.query_span("spec_cxl", mode="scheduler"):
                    _scheduler_rows(sess, F.flatten(q6_like_plan()))
            except BaseException as e:  # noqa: BLE001
                state["exc"] = e

        t = threading.Thread(target=run)
        t.start()
        for _ in range(400):
            if cancel_query("spec_cxl"):
                break
            time.sleep(0.005)
        t.join(15)
        assert not t.is_alive()
        assert isinstance(state.get("exc"), QueryCancelledError), \
            state.get("exc")
    finally:
        conf.SPECULATION_ENABLE.set(False)
        conf.SPECULATION_WEDGE_MS.set(0)
        conf.STAGE_TASK_CONCURRENCY.set(1)
    deadline = time.monotonic() + 10
    while _attempt_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _attempt_threads() == []


# ----------------------------- 6. surfacing: /metrics, --watch, status

def test_prometheus_terminal_and_degradation_rules():
    """Finished queries keep the PR 5 heartbeat-age rule (no
    forever-climbing gauge) and export their frozen degradation
    counters; terminal statuses surface in /queries and --watch."""
    conf.MONITOR_ENABLE.set(True)
    monitor.reset()
    with monitor.query("prom_q", mode="scheduler"):
        monitor.stage_started(0, "map", 2)
        monitor.stage_progress_update(
            0, rows=10, bytes_=100, batches=1, tasks_done=1,
            counters={"xla_dispatches": 4, "oom_recoveries": 2,
                      "batch_downshifts": 1, "eager_fallbacks": 0})
        monitor.stage_finished(0, "ok",
                               counters={"xla_dispatches": 4,
                                         "oom_recoveries": 2,
                                         "batch_downshifts": 1})
    text = monitor.render_prometheus()
    assert ('blaze_query_stage_oom_recoveries'
            '{query="prom_q",stage="0"} 2') in text
    assert ('blaze_query_stage_batch_downshifts'
            '{query="prom_q",stage="0"} 1') in text
    # zero-valued per-stage series are omitted; finished query exports
    # no heartbeat age (the forever-climbing gauge rule)
    assert "blaze_query_stage_eager_fallbacks" not in text
    assert 'blaze_query_heartbeat_age_seconds{query="prom_q"}' not in text
    snap = monitor.snapshot()
    q = next(x for x in snap["queries"] if x["query_id"] == "prom_q")
    assert q["status"] == "done"
    frame = monitor.render_watch(snap)
    assert "DONE" in frame
    assert "oom 2 spill/1 downshift/0 eager" in frame


def test_watch_surfaces_cancelled_status():
    conf.MONITOR_ENABLE.set(True)
    monitor.reset()
    with pytest.raises(QueryCancelledError):
        with monitor.query("watch_cxl"):
            raise QueryCancelledError("watch_cxl")
    frame = monitor.render_watch(monitor.snapshot())
    assert "CANCELL" in frame.upper()
