"""Expression CSE + trace-time short-circuit.

≙ reference CachedExprsEvaluator (common/cached_exprs_evaluator.rs:
48-506): common subexpressions lower once per projection, and literal
and/or operands short-circuit so the dead side is never lowered.
"""

import jax
import numpy as np

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.compile import LOWER_STATS, lower
from blaze_tpu.exprs.ir import ScalarFunc
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([Field("a", DataType.int64()), Field("b", DataType.int64())])


def _count_nodes(fn):
    before = LOWER_STATS["nodes"]
    out = fn()
    return out, LOWER_STATS["nodes"] - before


def _env(b):
    return {f.name: c for f, c in zip(b.schema.fields, b.columns)}


def test_shared_subtree_lowers_once():
    b = batch_from_pydict({"a": [1, 2, 3], "b": [4, 5, 6]}, SCHEMA)
    env = _env(b)
    shared = (col("a") + col("b")) * (col("a") + col("b"))

    # fresh memo per call: within one tree the repeated (a+b) subtree
    # still lowers once
    _, n1 = _count_nodes(lambda: lower(shared, SCHEMA, env, b.capacity))
    # nodes: mul, add, a, b  (the second add is a cache hit)
    assert n1 == 4, n1

    # one memo across sibling expressions
    memo = {}
    _, n2 = _count_nodes(
        lambda: [
            lower(col("a") + col("b"), SCHEMA, env, b.capacity, memo),
            lower((col("a") + col("b")) * lit(2), SCHEMA, env, b.capacity, memo),
        ]
    )
    # add+a+b, then mul+lit only (add is a hit)
    assert n2 == 5, n2


def test_short_circuit_skips_dead_side():
    b = batch_from_pydict({"a": [1, 2, 3], "b": [4, 5, 6]}, SCHEMA)
    env = _env(b)
    # md5 is host-only: lowering it on device RAISES — the dead operand
    # proves the side is truly never lowered
    expensive = ScalarFunc("md5", [col("a").cast(DataType.string(16))])

    out, n = _count_nodes(
        lambda: lower(lit(False) & expensive, SCHEMA, env, b.capacity)
    )
    assert n == 1, n
    assert not bool(np.asarray(out.data)[:3].any())

    out, n = _count_nodes(
        lambda: lower(expensive | lit(True), SCHEMA, env, b.capacity)
    )
    assert n == 1, n
    assert bool(np.asarray(out.data)[:3].all())

    # true AND x == x (x still lowers)
    out, _ = _count_nodes(
        lambda: lower(lit(True) & (col("a") > col("b")), SCHEMA, env, b.capacity)
    )
    assert list(np.asarray(out.data)[:3]) == [False, False, False]


def test_plan_time_fold_covers_host_subtrees():
    """false AND <host-only md5> never reaches host_eval either: the
    fold happens BEFORE split_host_exprs at plan build."""
    from blaze_tpu.exprs.compile import fold_literals
    from blaze_tpu.exprs.ir import BinOp, Lit

    dead = lit(False) & ScalarFunc("md5", [col("a").cast(DataType.string(16))])
    folded = fold_literals(dead)
    assert isinstance(folded, Lit) and folded.value is False
    # end-to-end: a projection with the dead side evaluates without
    # ever running the host function
    b = batch_from_pydict({"a": [1], "b": [2]}, SCHEMA)
    p = ProjectExec(MemoryScanExec([[b]], SCHEMA), [dead.alias("x")])
    assert p._host_parts == []  # md5 was folded away before extraction
    d = batch_to_pydict(list(p.execute(0, TaskContext(0, 1)))[0])
    assert d["x"] == [False]


def test_projection_results_unchanged():
    """q1-shaped projection: disc_price shared by two outputs — results
    identical, and correct."""
    schema = Schema([
        Field("price", DataType.decimal(12, 2)),
        Field("disc", DataType.decimal(12, 2)),
        Field("tax", DataType.decimal(12, 2)),
    ])
    data = {"price": [10.0, 20.0], "disc": [0.1, 0.2], "tax": [0.05, 0.08]}
    b = batch_from_pydict(data, schema)
    disc_price = col("price") * (lit(1, DataType.decimal(12, 2)) - col("disc"))
    p = ProjectExec(
        MemoryScanExec([[b]], schema),
        [
            disc_price.alias("disc_price"),
            (disc_price * (lit(1, DataType.decimal(12, 2)) + col("tax"))).alias("charge"),
        ],
    )
    d = batch_to_pydict(list(p.execute(0, TaskContext(0, 1)))[0])
    assert d["disc_price"] == [90000, 160000]  # decimal(p, 4)-scaled unscaled ints
    assert len(d["charge"]) == 2
