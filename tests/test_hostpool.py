"""Elastic worker-host pool: placement, liveness, lost-worker
recovery, blacklisting, and degradation (runtime/hostpool.py + the
scheduler's ``pool=`` placement seam).

Tier-1 (NOT slow-marked): the pooled workers are tiny ``--serve``
subprocesses over a parquet two-stage hash query, so the suite runs in
seconds.  Covers the ROADMAP item-1 done-evidence — a deterministic
2-process exchange smoke over framed shuffle blocks, byte-identical
with the in-process run — plus the worker-kill recovery contract:
``@kill`` SIGKILLs a pooled worker mid-stage, the dead worker's
committed map outputs partially re-run on survivors
(``map_tasks_rerun`` strictly less than ``n_tasks``), repeat offenders
blacklist, and a fully-collapsed pool degrades to in-process execution
instead of failing the query.
"""

import os

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.ops import MemoryScanExec, ParquetScanExec, ParquetSinkExec
from blaze_tpu.parallel.shuffle import LocalShuffleManager
from blaze_tpu.runtime import dispatch, faults, ledger
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.hostpool import (
    HostPool, WorkerLostError, WorkerTaskError, WorkerTaskFatalError,
)
from blaze_tpu.runtime.metrics import MetricNode
from blaze_tpu.runtime.retry import FATAL, RETRY, classify
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.runtime import worker as worker_mod
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import BlazeSparkSession

import spark_fixtures as F

SCHEMA = Schema([
    Field("l_quantity", DataType.int64()),
    Field("l_extendedprice", DataType.int64()),
    Field("l_discount", DataType.int64()),
])


@pytest.fixture(autouse=True)
def _clean_state():
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    faults.reset()
    yield
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.1)
    conf.HOST_BLACKLIST_MAX_FAILURES.set(2)
    faults.reset()


def _write_parquet_inputs(tmp_path, n_files=3, rows=120):
    rng = np.random.RandomState(7)
    files, data = [], {"l_quantity": [], "l_extendedprice": [],
                       "l_discount": []}
    for i in range(n_files):
        d = {
            "l_quantity": [int(v) for v in rng.randint(1, 50, rows)],
            "l_extendedprice": [int(v) for v in rng.randint(100, 10000, rows)],
            "l_discount": [int(v) for v in rng.randint(0, 10, rows)],
        }
        for k in data:
            data[k].extend(d[k])
        src = MemoryScanExec([[batch_from_pydict(d, SCHEMA)]], SCHEMA)
        path = str(tmp_path / f"lineitem_{i}.parquet")
        sink = ParquetSinkExec(src, path)
        for _ in sink.execute(0, TaskContext(0, 1)):
            pass
        files.append(sink.written_files[0] if sink.written_files else path)
    return files, data


def _two_stage_plan(files):
    """scan -> filter -> project -> partial agg -> exchange -> final
    agg: one map task per parquet file, a real framed-block shuffle in
    the middle — the plan ships to pooled workers (no driver-process
    resources)."""
    scan = ParquetScanExec([[f] for f in files], SCHEMA)
    sess = BlazeSparkSession()
    sess.register_table("lineitem", scan)
    s = F.scan("lineitem", [F.attr("l_quantity", 1),
                            F.attr("l_extendedprice", 2),
                            F.attr("l_discount", 3)])
    f = F.filter_(
        F.binop("And",
                F.binop("LessThan", F.attr("l_quantity", 1), F.lit(24, "long")),
                F.binop("GreaterThanOrEqual", F.attr("l_discount", 3),
                        F.lit(5, "long"))),
        s,
    )
    pr = F.project(
        [F.alias(F.binop("Multiply", F.attr("l_extendedprice", 2),
                         F.attr("l_discount", 3)), "rev", 10)],
        f,
    )
    partial = F.hash_agg([], [F.agg_expr(F.sum_(F.attr("rev", 10)),
                                         "Partial", 20)], pr)
    ex = F.shuffle(F.single_partition(), partial)
    final = F.hash_agg(
        [], [F.agg_expr(F.sum_(F.attr("rev", 10)), "Final", 20)], ex,
        result=[F.alias(F.attr("s", 20), "revenue", 21)],
    )
    return sess, F.flatten(final)


def _run(sess, plan_json, root, pool=None, metrics=None):
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan, LocalShuffleManager(str(root)))
    rows = []
    for b in run_stages(stages, manager, metrics=metrics, pool=pool):
        d = batch_to_pydict(b)
        rows.extend(zip(*[d[k] for k in sorted(d)]))
    return sorted(rows)


# ------------------------------------------------- faults grammar

def test_kill_modifier_parse_format_roundtrip():
    rules = faults.parse_spec("worker.task@3@kill,shuffle.fetch@1@a0@kill")
    assert faults.format_spec(rules) == \
        "worker.task@3@kill,shuffle.fetch@1@a0@kill"


def test_worker_task_site_registered():
    assert "worker.task" in faults.SITES


# ------------------------------------------------- typed errors

def test_hostpool_error_dispositions():
    assert classify(WorkerLostError("w0", "sigkill")) == RETRY
    assert classify(WorkerTaskError("ValueError", "boom")) == RETRY
    assert classify(WorkerTaskFatalError("AssertionError", "bug")) == FATAL


def test_worker_lost_error_carries_sorted_lost_outputs():
    e = WorkerLostError("w1", "exit status 1",
                        lost_outputs={3: [2, 0], 1: []})
    assert e.lost_outputs == {3: [0, 2]}
    assert "w1" in str(e) and "exit status 1" in str(e)


# ------------------------------------------------- exchange smoke

def test_two_process_exchange_byte_identical(tmp_path):
    """ROADMAP item 1 done-evidence: TWO pooled worker processes run
    the map stage, exchanging through framed shuffle blocks in the
    shared root; the reduce side sees byte-identical results vs the
    in-process run, and every map output is pool-committed."""
    files, data = _write_parquet_inputs(tmp_path)
    sess, plan_json = _two_stage_plan(files)
    expected = _run(sess, plan_json, tmp_path / "shuffle_local")

    m = MetricNode()
    with HostPool(2) as pool:
        got = _run(sess, plan_json, tmp_path / "shuffle_pool",
                   pool=pool, metrics=m)
        # the map stage genuinely ran ON the pool: all 3 map outputs
        # are owned by pooled workers, none fell back to local
        assert pool.owned_map_outputs() == 3
        assert pool.blacklisted() == []
        assert not pool.degraded()
    assert got == expected
    assert m.metrics.get("worker_lost") in (None, 0)
    assert ledger.leak_audit() == []


def test_memory_scan_plans_fall_back_to_local(tmp_path):
    """A memory-scan plan serializes driver-process resources a pooled
    worker can never read: placement must fall back to in-process
    execution, byte-identical, with zero driver-side resource leaks."""
    d = {"l_quantity": [1, 30], "l_extendedprice": [10, 20],
         "l_discount": [7, 8]}
    scan = MemoryScanExec([[batch_from_pydict(d, SCHEMA)]], SCHEMA)
    sess = BlazeSparkSession()
    sess.register_table("lineitem", scan)
    s = F.scan("lineitem", [F.attr("l_quantity", 1),
                            F.attr("l_extendedprice", 2),
                            F.attr("l_discount", 3)])
    partial = F.hash_agg([], [F.agg_expr(F.sum_(F.attr("l_extendedprice", 2)),
                                         "Partial", 20)], s)
    ex = F.shuffle(F.single_partition(), partial)
    final = F.hash_agg(
        [], [F.agg_expr(F.sum_(F.attr("l_extendedprice", 2)), "Final", 20)],
        ex, result=[F.alias(F.attr("s", 20), "total", 21)],
    )
    plan_json = F.flatten(final)
    expected = _run(sess, plan_json, tmp_path / "a")
    with HostPool(1) as pool:
        got = _run(sess, plan_json, tmp_path / "b", pool=pool)
        assert pool.owned_map_outputs() == 0  # everything ran local
    assert got == expected
    assert ledger.leak_audit() == []


# ------------------------------------------------- lost-worker recovery

def test_worker_kill_partial_rerun_and_blacklist(tmp_path):
    """SIGKILL a pooled worker as it starts its SECOND job: its FIRST
    job's committed map output is invalidated and re-run via the
    partial-rerun path (map_tasks_rerun < n_tasks), the slot
    blacklists at maxFailures=1, total collapse degrades to local, and
    the result stays byte-identical."""
    files, data = _write_parquet_inputs(tmp_path)
    sess, plan_json = _two_stage_plan(files)
    expected = _run(sess, plan_json, tmp_path / "shuffle_base")

    conf.HOST_BLACKLIST_MAX_FAILURES.set(1)
    kills_before = dispatch.counters().get("workers_blacklisted", 0)
    m = MetricNode()
    # per-process schedule: a map job probes worker.task once at job
    # start (the writer plan yields no batches), so hit 1 (first job)
    # passes and hit 2 (second job's start) SIGKILLs — each worker
    # dies exactly when it already owns one committed map output
    with HostPool(2, env={"BLAZE_FAULTS_SPEC": "worker.task@2@kill"}) as pool:
        got = _run(sess, plan_json, tmp_path / "shuffle_kill",
                   pool=pool, metrics=m)
        assert pool.blacklisted() == ["w0", "w1"]
        assert pool.degraded()
    assert got == expected
    sched = m.metrics
    assert sched.get("worker_lost") == 2
    # partial, not full: each death lost exactly ONE committed map
    # output, and each regeneration re-ran exactly that one task —
    # strictly fewer than the stage's 3 tasks
    reruns = sched.get("map_stage_reruns")
    assert reruns == 2
    assert sched.get("map_tasks_rerun") == reruns
    assert dispatch.counters().get("workers_blacklisted", 0) \
        - kills_before == 2
    assert ledger.leak_audit() == []


# ------------------------------------------------- cancel reaches the pool

def test_cancel_kills_inflight_pooled_worker():
    """cancel_query must reach a job IN FLIGHT on a pooled worker: the
    wait loop's cancel checkpoint kills the bound worker's process
    group (it cannot see the driver's scope event), accounts the kill
    (``worker_kills``), raises the typed cancel error — and charges the
    slot NO blacklist failure."""
    from blaze_tpu.runtime.context import QueryCancelledError, cancel_scope

    kills_before = dispatch.counters().get("worker_kills", 0)
    # the worker stalls 5s at job start, so it can neither reply nor
    # die before the driver's 50ms cancel checkpoint fires
    with HostPool(1, env={"BLAZE_FAULTS_SPEC":
                          "worker.task@1@slow5000"}) as pool:
        with cancel_scope("q_pool_cancel") as scope:
            scope.cancel()
            with pytest.raises(QueryCancelledError):
                pool.run_task({"partition": 0, "attempt": 0}, "w0")
        assert pool.blacklisted() == []
        assert pool.lost_counts() == {}
    assert dispatch.counters().get("worker_kills", 0) - kills_before == 1
    assert ledger.leak_audit() == []


# ------------------------------------------------- run_worker_with_retry

class _FakeProc:
    """Stands in for the worker subprocess: writes a typed exit record
    next to the spec (like a cleanly-failing worker) and exits with
    the given status."""

    def __init__(self, record, returncode, spec_path):
        self.returncode = returncode
        self.pid = os.getpid()
        if record is not None:
            import json as _json

            with open(worker_mod.exit_record_path(spec_path), "w") as f:
                _json.dump(record, f)

    def communicate(self, timeout=None):
        return b"", b"synthetic failure"


def _patch_popen(monkeypatch, script):
    """``script`` = list of (exit_record | None, returncode) per spawn;
    returns the call-count list."""
    calls = []

    def fake_popen(cmd, **kwargs):
        spec_path = cmd[-1]
        record, rc = script[min(len(calls), len(script) - 1)]
        calls.append(spec_path)
        return _FakeProc(record, rc, spec_path)

    import subprocess as _sp

    monkeypatch.setattr(_sp, "Popen", fake_popen)
    return calls


def test_fatal_classified_worker_exit_does_not_respawn(tmp_path, monkeypatch):
    """The FATAL-respawn fix: a worker whose typed exit record says
    FATAL (here a QueryCancelledError serialized back from the worker)
    raises the REAL typed error after ONE spawn instead of burning the
    retry budget resurrecting a cancelled query."""
    from blaze_tpu.runtime.context import QueryCancelledError

    calls = _patch_popen(monkeypatch, [
        ({"error_type": "QueryCancelledError", "disposition": "fatal",
          "message": "query q7 cancelled", "query_id": "q7",
          "reason": "cancel"}, 1),
    ])
    with pytest.raises(QueryCancelledError) as ei:
        worker_mod.run_worker_with_retry(
            {"partition": 0}, str(tmp_path), "t0", max_attempts=4)
    assert ei.value.query_id == "q7"
    assert len(calls) == 1


def test_fatal_exit_record_raises_typed_wrapper(tmp_path, monkeypatch):
    calls = _patch_popen(monkeypatch, [
        ({"error_type": "AssertionError", "disposition": "fatal",
          "message": "invariant broke"}, 1),
    ])
    with pytest.raises(WorkerTaskFatalError, match="AssertionError"):
        worker_mod.run_worker_with_retry(
            {"partition": 0}, str(tmp_path), "t1", max_attempts=4)
    assert len(calls) == 1


def test_retry_classified_worker_exit_respawns(tmp_path, monkeypatch):
    """A RETRY-classified exit keeps the old behavior: fresh spawn
    with a fresh attempt id, success on the second."""
    calls = _patch_popen(monkeypatch, [
        ({"error_type": "InjectedFault", "disposition": "retry",
          "message": "seeded crash"}, 1),
        (None, 0),
    ])
    attempt = worker_mod.run_worker_with_retry(
        {"partition": 0}, str(tmp_path), "t2", max_attempts=4)
    assert attempt == 1
    assert len(calls) == 2


def test_exit_record_roundtrip(tmp_path):
    spec_path = str(tmp_path / "spec.json")
    try:
        raise ValueError("bad input")
    except ValueError as e:
        worker_mod._write_exit_record(spec_path, e)
    rec = worker_mod.read_exit_record(spec_path)
    assert rec["error_type"] == "ValueError"
    assert rec["disposition"] == RETRY
    assert "bad input" in rec["message"]
    assert worker_mod.read_exit_record(str(tmp_path / "missing.json")) is None
