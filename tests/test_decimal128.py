"""Two-limb int128 decimal semantics: exact wide multiply/divide/
rescale and sum/avg accumulation beyond int64, differentially tested
against Python bignum/Decimal (≙ the reference's Arrow decimal128 +
check_overflow arithmetic, datafusion-ext-commons/src/cast.rs)."""

import decimal

import numpy as np
import pytest
import jax.numpy as jnp

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict, column_from_numpy
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs import int128 as I
from blaze_tpu.ops import (
    AggExec, AggFunction, AggMode, FilterExec, MemoryScanExec, ProjectExec,
)
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.tpch.queries import two_stage_agg

RNG = np.random.RandomState(1234)


def rand_i64(n, bits=63):
    m = RNG.randint(1, bits + 1, n)
    return np.array([RNG.randint(-(2 ** (b - 1)), 2 ** (b - 1)) for b in m],
                    np.int64)


def as_bignum(hi, lo):
    return np.asarray(hi).astype(object) * 2**64 + np.asarray(lo).astype(object)


# ------------------------------------------------------------ int128 core

def test_mul_i64_exact():
    a, b = rand_i64(2000), rand_i64(2000)
    hi, lo = I.mul_i64(jnp.asarray(a), jnp.asarray(b))
    assert (as_bignum(hi, lo) == a.astype(object) * b.astype(object)).all()


def test_add_sub_neg_roundtrip():
    a, b = rand_i64(2000), rand_i64(2000)
    ah, al = I.from_i64(jnp.asarray(a))
    bh, bl = I.from_i64(jnp.asarray(b))
    sh, sl = I.add(ah, al, bh, bl)
    assert (as_bignum(sh, sl) == a.astype(object) + b.astype(object)).all()
    dh, dl = I.sub(sh, sl, bh, bl)
    assert (as_bignum(dh, dl) == a.astype(object)).all()


@pytest.mark.parametrize("k", [1, 2, 4, 9, 13, 18])
def test_mul_pow10_rescale_roundtrip(k):
    v = rand_i64(800, bits=60)
    hi, lo = I.mul_pow10(*I.from_i64(jnp.asarray(v)), k)
    assert (as_bignum(hi, lo) == v.astype(object) * 10**k).all()
    q, ok = I.rescale_down(hi, lo, k)
    assert np.asarray(ok).all()
    assert (np.asarray(q) == v).all()


def _py_half_up(n, d):
    s = -1 if (n < 0) ^ (d < 0) else 1
    n, d = abs(n), abs(d)
    return s * ((n + d // 2) // d)


def test_div_round_half_up_vs_bignum():
    n = 3000
    ah = RNG.randint(-2**40, 2**40, n).astype(np.int64)
    al = (RNG.randint(0, 2**62, n).astype(np.uint64) << np.uint64(1)) | RNG.randint(0, 2, n).astype(np.uint64)
    den = rand_i64(n, bits=62)
    den[den == 0] = 7
    q, ok = I.div_round_half_up(jnp.asarray(ah), jnp.asarray(al), jnp.asarray(den))
    num = ah.astype(object) * 2**64 + al.astype(object)
    q_np, ok_np = np.asarray(q), np.asarray(ok)
    for i in range(n):
        exp = _py_half_up(int(num[i]), int(den[i]))
        if -(2**63) <= exp < 2**63:
            assert bool(ok_np[i]) and int(q_np[i]) == exp, (
                i, int(q_np[i]), exp, int(num[i]), int(den[i]))


def test_half_up_boundary_cases():
    cases = [(5, 10, 1), (-5, 10, -1), (15, 10, 2), (-15, 10, -2),
             (25, 10, 3), (5, 2, 3), (-5, 2, -3), (1, 3, 0), (2, 3, 1)]
    for n_, d_, e_ in cases:
        hi, lo = I.from_i64(jnp.asarray(np.array([n_], np.int64)))
        q, _ = I.div_round_half_up(hi, lo, jnp.asarray(np.array([d_], np.int64)))
        assert int(np.asarray(q)[0]) == e_, (n_, d_)


# ----------------------------------------------------- engine expressions

def _dec_col(unscaled, p, s):
    return column_from_numpy(DataType.decimal(p, s), np.asarray(unscaled, np.int64))


def _run_binop(op, a_unscaled, pa, sa, b_unscaled, pb, sb):
    schema = Schema([Field("a", DataType.decimal(pa, sa)),
                     Field("b", DataType.decimal(pb, sb))])
    batch = batch_from_pydict({}, Schema([]))  # placeholder
    from blaze_tpu.batch import RecordBatch

    cols = [_dec_col(a_unscaled, pa, sa), _dec_col(b_unscaled, pb, sb)]
    rb = RecordBatch(schema, cols, len(a_unscaled))
    src = MemoryScanExec([[rb]], schema)
    e = {"*": col("a") * col("b"), "/": col("a") / col("b")}[op]
    plan = ProjectExec(src, [e.alias("r")])
    out = list(plan.execute(0, TaskContext(0, 1)))[0]
    return plan.schema.field("r").dtype, batch_to_pydict(out)["r"]


def test_wide_decimal_multiply_vs_bignum():
    """decimal(15,2) * decimal(15,2): raw products overflow int64; the
    engine must match bignum HALF_UP rescale exactly (or null when the
    result exceeds the representable domain)."""
    n = 500
    a = rand_i64(n, bits=49)  # up to ~5.6e14 unscaled
    b = rand_i64(n, bits=49)
    res_t, got = _run_binop("*", a, 15, 2, b, 15, 2)
    assert res_t.is_decimal
    k = 2 + 2 - res_t.scale
    for i in range(n):
        raw = int(a[i]) * int(b[i])
        exp = _py_half_up(raw, 10**k) if k > 0 else raw * 10**(-k)
        if -(2**63) <= exp < 2**63:
            assert got[i] == exp, (i, got[i], exp)
        else:
            assert got[i] is None, (i, got[i], exp)


def test_wide_decimal_divide_vs_bignum():
    """decimal(18,4) / decimal(18,4): the shifted numerator exceeds
    int64; engine quotient must equal bignum HALF_UP exactly."""
    n = 500
    a = rand_i64(n, bits=59)
    b = rand_i64(n, bits=40)
    b[b == 0] = 123
    res_t, got = _run_binop("/", a, 18, 4, b, 18, 4)
    shift = res_t.scale - 4 + 4
    for i in range(n):
        exp = _py_half_up(int(a[i]) * 10**shift, int(b[i]))
        if -(2**63) <= exp < 2**63:
            assert got[i] == exp, (i, got[i], exp)


# ------------------------------------------------------- agg accumulation

def _agg_once(values_unscaled, p, s, fns, n_parts=2, batch_rows=64):
    schema = Schema([Field("v", DataType.decimal(p, s))])
    from blaze_tpu.batch import RecordBatch

    parts = []
    vs = np.asarray(values_unscaled, np.int64)
    per = (len(vs) + n_parts - 1) // n_parts
    for pi in range(n_parts):
        sl = vs[pi * per:(pi + 1) * per]
        batches = []
        for off in range(0, len(sl), batch_rows):
            chunk = sl[off:off + batch_rows]
            batches.append(RecordBatch(schema, [_dec_col(chunk, p, s)], len(chunk)))
        parts.append(batches)
    src = MemoryScanExec(parts, schema)
    aggs = [AggFunction(fn, col("v"), f"r_{fn}") for fn in fns]
    plan = two_stage_agg(src, [], aggs, n_parts)
    out = {}
    for pi in range(plan.num_partitions()):
        for b in plan.execute(pi, TaskContext(pi, plan.num_partitions())):
            out.update(batch_to_pydict(b))
    return out


def test_wide_sum_avg_exact_vs_bignum():
    """sum/avg over decimal(12,2) (sum type decimal(22,2) > 18 digits):
    two-limb accumulation must match bignum exactly, including the
    scale-4 avg rescale that previously went through float64 and
    dropped low-order digits."""
    n = 4000
    # values whose low bits float64 cannot carry once shifted by 10^4
    vs = (RNG.randint(0, 2**37, n).astype(np.int64) * 8192
          + RNG.randint(0, 8192, n).astype(np.int64))  # ≤ ~1.1e15 each
    vs = np.where(RNG.rand(n) < 0.3, -vs, vs)
    out = _agg_once(vs, 12, 2, ["sum", "avg"])
    total = int(vs.astype(object).sum())
    assert out["r_sum"] == [total]
    # avg result scale = 2 + 4 = 6 -> unscaled * 10^4 / n, HALF_UP
    assert out["r_avg"] == [_py_half_up(total * 10**4, n)]


def test_wide_sum_overflow_nulls_not_wraps():
    """A sum whose true value exceeds int64 must produce NULL (the
    documented overflow domain), never a silently wrapped value."""
    vs = np.full(10, 4 * 10**18, np.int64)  # Σ = 4e19 > 2^63-1
    out = _agg_once(vs, 18, 0, ["sum"])
    assert out["r_sum"] == [None]


def test_wide_sum_near_max_exact():
    vs = np.full(9, 10**18, np.int64)  # Σ = 9e18, just under 2^63-1
    out = _agg_once(vs, 18, 0, ["sum"])
    assert out["r_sum"] == [9 * 10**18]


def test_grouped_wide_sum_exact():
    """Grouped (segment) path: per-group exact limbs."""
    n = 3000
    keys = RNG.randint(0, 7, n).astype(np.int64)
    vs = (RNG.randint(0, 2**33, n).astype(np.int64) * 2048
          + RNG.randint(0, 2048, n).astype(np.int64))
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.decimal(12, 2))])
    from blaze_tpu.batch import RecordBatch

    cols = [column_from_numpy(DataType.int64(), keys), _dec_col(vs, 12, 2)]
    src = MemoryScanExec([[RecordBatch(schema, cols, n)]], schema)
    from blaze_tpu.ops import GroupingExpr

    plan = two_stage_agg(src, [GroupingExpr(col("k"), "k")],
                         [AggFunction("sum", col("v"), "s"),
                          AggFunction("avg", col("v"), "a")], 2)
    got = {}
    for pi in range(plan.num_partitions()):
        for b in plan.execute(pi, TaskContext(pi, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k, s, a in zip(d["k"], d["s"], d["a"]):
                got[k] = (s, a)
    for k in set(keys.tolist()):
        m = keys == k
        total = int(vs[m].astype(object).sum())
        cnt = int(m.sum())
        assert got[k] == (total, _py_half_up(total * 10**4, cnt)), k


def test_narrow_decimal_avg_two_stage():
    """avg over decimal(7,2) (sum type decimal(17,2), NOT wide): the
    FINAL stage's input-type recovery must agree with the partial
    stage's state layout — regression for a KeyError on #sum_hi when
    recovery misclassified narrow avgs as wide."""
    n = 500
    vs = RNG.randint(-10**6, 10**6, n).astype(np.int64)
    out = _agg_once(vs, 7, 2, ["sum", "avg"])
    total = int(vs.sum())
    assert out["r_sum"] == [total]
    assert out["r_avg"] == [_py_half_up(total * 10**4, n)]
