"""ORC subset format + OrcScanExec (≙ reference orc_exec.rs tests +
the scan half of its differential matrix)."""

import os

import numpy as np
import pytest

from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.io import orc
from blaze_tpu.ops.orc_scan import OrcScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([
    Field("b", DataType.bool_()),
    Field("i8", DataType.int8()),
    Field("i16", DataType.int16()),
    Field("i32", DataType.int32()),
    Field("i64", DataType.int64()),
    Field("f32", DataType.float32()),
    Field("f64", DataType.float64()),
    Field("d", DataType.date32()),
    Field("dec", DataType.decimal(12, 2)),
    Field("s", DataType.string(16)),
])


def _make_columns(n, rng, with_nulls=True):
    cols = {}
    valid = lambda: (rng.random(n) > 0.2) if with_nulls else np.ones(n, bool)
    cols["b"] = (rng.random(n) > 0.5, valid(), None)
    cols["i8"] = (rng.integers(-120, 120, n).astype(np.int8), valid(), None)
    cols["i16"] = (rng.integers(-30000, 30000, n).astype(np.int16), valid(), None)
    cols["i32"] = (rng.integers(-(2**31), 2**31, n).astype(np.int32), valid(), None)
    cols["i64"] = (rng.integers(-(2**62), 2**62, n), valid(), None)
    cols["f32"] = (rng.random(n).astype(np.float32), valid(), None)
    cols["f64"] = (rng.random(n), valid(), None)
    cols["d"] = (rng.integers(0, 20000, n).astype(np.int32), valid(), None)
    cols["dec"] = (rng.integers(-(10**10), 10**10, n), valid(), None)
    strs = [f"s{int(v):08d}" for v in rng.integers(0, 10**7, n)]
    data = np.zeros((n, 16), np.uint8)
    lengths = np.zeros(n, np.int32)
    for i, s in enumerate(strs):
        bs = s.encode()
        data[i, : len(bs)] = np.frombuffer(bs, np.uint8)
        lengths[i] = len(bs)
    cols["s"] = (data, valid(), lengths)
    return cols


def test_orc_roundtrip_all_types(tmp_path):
    rng = np.random.default_rng(3)
    n = 777
    cols = _make_columns(n, rng)
    path = str(tmp_path / "t.orc")
    orc.write_orc(path, SCHEMA, cols, stripe_rows=300)

    meta = orc.read_metadata(path, string_width=16)
    assert meta.num_rows == n
    assert len(meta.stripes) == 3
    assert [f.name for f in meta.schema.fields] == [f.name for f in SCHEMA.fields]

    off = 0
    for stripe in meta.stripes:
        got = orc.read_stripe(path, meta, stripe)
        for name, (data, validity, lengths) in got.items():
            wd, wv, wl = cols[name]
            sl = slice(off, off + stripe.rows)
            np.testing.assert_array_equal(validity, wv[sl])
            live = wv[sl]
            if name == "s":
                np.testing.assert_array_equal(lengths[live], wl[sl][live])
                np.testing.assert_array_equal(data[live], wd[sl][live])
            else:
                np.testing.assert_array_equal(data[live], wd[sl][live])
        off += stripe.rows


def test_orc_rlev1_run_decode():
    # the writer emits literal groups; the reader must also handle runs
    # (other writers produce them): run of 10 starting at 7 step 1
    encoded = bytes([10 - 3, 1]) + orc._uvarint(orc._zz(7))
    got = orc._rlev1_decode(encoded, 10, signed=True)
    np.testing.assert_array_equal(got, np.arange(7, 17))


def test_orc_scan_exec_with_pruning(tmp_path):
    rng = np.random.default_rng(5)
    n = 1000
    schema = Schema([Field("k", DataType.int64()), Field("s", DataType.string(8))])
    ks = np.arange(n, dtype=np.int64)
    data = np.zeros((n, 8), np.uint8)
    lengths = np.zeros(n, np.int32)
    for i in range(n):
        bs = f"r{i:04d}".encode()
        data[i, : len(bs)] = np.frombuffer(bs, np.uint8)
        lengths[i] = len(bs)
    path = str(tmp_path / "scan.orc")
    orc.write_orc(
        path, schema,
        {"k": (ks, None, None), "s": (data, None, lengths)},
        stripe_rows=250,
    )
    scan = OrcScanExec([[path]], schema, predicate=col("k") >= lit(750), batch_rows=128)
    rows = []
    for b in scan.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        rows.extend(zip(d["k"], d["s"]))
    # pruning: only the last stripe (k in [750, 1000)) survives
    assert scan.metrics.get("pruned_stripes") == 3
    assert [r[0] for r in rows] == list(range(750, 1000))
    assert rows[0][1] == "r0750"


def test_orc_schema_adaption_missing_column(tmp_path):
    schema_file = Schema([Field("a", DataType.int32())])
    path = str(tmp_path / "m.orc")
    orc.write_orc(path, schema_file, {"a": (np.arange(10, dtype=np.int32), None, None)})
    read_schema = Schema([Field("a", DataType.int32()), Field("zz", DataType.int64())])
    scan = OrcScanExec([[path]], read_schema)
    d = batch_to_pydict(list(scan.execute(0, TaskContext(0, 1)))[0])
    assert d["a"] == list(range(10))
    assert d["zz"] == [None] * 10


def test_orc_corrupt_file(tmp_path):
    path = str(tmp_path / "bad.orc")
    with open(path, "wb") as f:
        f.write(b"definitely not orc")
    scan = OrcScanExec([[path]], Schema([Field("a", DataType.int32())]))
    with pytest.raises(Exception):
        list(scan.execute(0, TaskContext(0, 1)))


def test_orc_scan_proto_roundtrip(tmp_path):
    """plan -> protobuf TaskDefinition -> plan, through the same serde
    the JNI gateway uses (≙ from_proto.rs scan decode)."""
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    schema = Schema([Field("k", DataType.int64())])
    path = str(tmp_path / "rt.orc")
    orc.write_orc(path, schema, {"k": (np.arange(20, dtype=np.int64), None, None)})
    scan = OrcScanExec([[path]], schema, predicate=col("k") < lit(5))
    rebuilt = plan_from_proto(plan_to_proto(scan))
    assert type(rebuilt).__name__ == "OrcScanExec"
    d = batch_to_pydict(list(rebuilt.execute(0, TaskContext(0, 1)))[0])
    assert d["k"] == list(range(20))  # pruning keeps the stripe; filter is a separate op
    assert rebuilt._conjuncts == [("k", "<", 5)]


def test_orc_timestamp_roundtrip(tmp_path):
    """TIMESTAMP columns (micros) through our writer/reader: positive,
    negative (pre-2015 ORC epoch), sub-second fractions, and nulls."""
    import numpy as np

    from blaze_tpu.io.orc import read_metadata, read_stripe, write_orc
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("ts", DataType.timestamp())])
    vals = np.array([
        0,                       # unix epoch (pre-2015: negative rel)
        1420070400_000_000,      # exactly the ORC epoch
        1700000000_123_456,      # recent with sub-ms fraction
        1420070399_000_000,      # one second before the ORC epoch
        -123_456_789,            # pre-1970 fractional (trunc-zero secs)
        981_173_106_987_000,     # 2001 with trailing-zero nanos
        -1,                      # last µs before the unix epoch (the
                                 # floor-seconds ambiguity boundary)
        -999_000,                # inside the pre-epoch second
        -1_500_000,              # fractional below -1s
        -1_000_000,              # exactly -1s (zero nanos)
        -7_000_000,              # null slot
    ], np.int64)
    validity = np.array([1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0], bool)
    path = str(tmp_path / "ts.orc")
    write_orc(path, schema, {"ts": (vals, validity, None)})
    meta = read_metadata(path)
    got = read_stripe(path, meta, meta.stripes[0])
    data, val, _ = got["ts"]
    assert (val == validity).all()
    assert (data[validity] == vals[validity]).all()


def test_orc_timestamp_pyarrow_differential(tmp_path):
    """Timestamps written by pyarrow's real ORC writer decode to the
    same microsecond values."""
    import numpy as np

    pa = pytest.importorskip("pyarrow")
    paorc = pytest.importorskip("pyarrow.orc")

    from blaze_tpu.io.orc import read_metadata, read_stripe

    micros = [1700000000_000_000, 1500000000_500_000, None,
              1420070400_000_000, 981_173_106_987_654,
              -1, -999_000, -1_500_000, -1_000_000]
    table = pa.table({"ts": pa.array(
        [None if m is None else m for m in micros], pa.timestamp("us"))})
    path = str(tmp_path / "pa_ts.orc")
    paorc.write_table(table, path, compression="zlib")
    meta = read_metadata(path)
    got = read_stripe(path, meta, meta.stripes[0])
    data, val, _ = got["ts"]
    for i, m in enumerate(micros):
        if m is None:
            assert not val[i]
        else:
            assert val[i] and int(data[i]) == m, (i, int(data[i]), m)
