"""Pallas kernel tests (interpret mode on the CPU mesh).

Differential oracles: the pure-XLA implementations in exprs/hash.py
(themselves validated against Spark golden vectors in test_hash.py)
and numpy.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from blaze_tpu.batch import column_from_numpy
from blaze_tpu.exprs.hash import murmur3_columns, pmod
from blaze_tpu.kernels import fused_group_sums, murmur3_pids, pid_histogram
from blaze_tpu.kernels.pallas_ops import column_word_planes
from blaze_tpu.schema import DataType


def _ref_pids(cols, n_parts):
    return np.asarray(pmod(murmur3_columns(cols), n_parts))


def test_murmur3_pids_i64_matches_xla():
    rng = np.random.default_rng(0)
    n = 3000  # not a multiple of the 1024-row tile
    keys = rng.integers(-(2**62), 2**62, n, dtype=np.int64)
    col = column_from_numpy(DataType.int64(), keys, capacity=n)
    planes, w = column_word_planes(col.to_device())
    got = np.asarray(
        murmur3_pids(planes, [w], [jnp.asarray(col.validity)], 200)
    )
    np.testing.assert_array_equal(got, _ref_pids([col.to_device()], 200))


def test_murmur3_pids_multi_col_with_nulls():
    rng = np.random.default_rng(1)
    n = 1500
    a = rng.integers(-(2**31), 2**31, n, dtype=np.int32)
    b = rng.integers(-(2**62), 2**62, n, dtype=np.int64)
    valid_a = rng.random(n) > 0.2
    ca = column_from_numpy(DataType.int32(), a, valid_a, capacity=n).to_device()
    cb = column_from_numpy(DataType.int64(), b, capacity=n).to_device()
    pa, wa = column_word_planes(ca)
    pb, wb = column_word_planes(cb)
    got = np.asarray(
        murmur3_pids(
            pa + pb, [wa, wb], [jnp.asarray(ca.validity), jnp.asarray(cb.validity)], 17
        )
    )
    np.testing.assert_array_equal(got, _ref_pids([ca, cb], 17))


@pytest.mark.parametrize(
    "dtype,gen",
    [
        (DataType.int32(), lambda rng, n: rng.integers(-(2**31), 2**31, n).astype(np.int32)),
        (DataType.float64(), lambda rng, n: np.concatenate([[0.0, -0.0, 1.5], rng.random(n - 3)])),
        (DataType.float32(), lambda rng, n: np.concatenate([[0.0, -0.0], rng.random(n - 2)]).astype(np.float32)),
        (DataType.decimal(12, 2), lambda rng, n: rng.integers(-(2**40), 2**40, n)),
        (DataType.date32(), lambda rng, n: rng.integers(0, 20000, n).astype(np.int32)),
        (DataType.bool_(), lambda rng, n: rng.integers(0, 2, n).astype(np.bool_)),
    ],
    ids=["int32", "float64", "float32", "decimal", "date32", "bool"],
)
def test_murmur3_pids_every_key_dtype(dtype, gen):
    """Every column_word_planes branch must agree with the XLA hash —
    partition ids are a Spark-compat correctness gate."""
    rng = np.random.default_rng(7)
    n = 1100
    vals = gen(rng, n)
    valid = rng.random(n) > 0.15
    col = column_from_numpy(dtype, vals, valid, capacity=n).to_device()
    planes, w = column_word_planes(col)
    got = np.asarray(murmur3_pids(planes, [w], [jnp.asarray(col.validity)], 31))
    np.testing.assert_array_equal(got, _ref_pids([col], 31))


def test_pid_histogram_matches_bincount():
    rng = np.random.default_rng(2)
    n, p = 5000, 37
    pids = rng.integers(0, p, n).astype(np.int32)
    got = np.asarray(pid_histogram(jnp.asarray(pids), p))
    np.testing.assert_array_equal(got, np.bincount(pids, minlength=p))


def test_fused_group_sums_with_filtered_rows():
    rng = np.random.default_rng(3)
    n, g, k = 4000, 6, 3
    gids = rng.integers(-1, g, n).astype(np.int32)  # -1 = filtered out
    vals = [rng.random(n).astype(np.float32) for _ in range(k)]
    got = np.asarray(fused_group_sums(jnp.asarray(gids), [jnp.asarray(v) for v in vals], g))
    want = np.zeros((k, g), np.float32)
    for j in range(g):
        m = gids == j
        for i in range(k):
            want[i, j] = vals[i][m].sum(dtype=np.float64)
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_fused_group_sums_counts():
    # count(*) per group = sum of a ones column
    gids = np.array([0, 1, 1, 2, -1, 2, 2], np.int32)
    ones = jnp.ones(7, jnp.float32)
    got = np.asarray(fused_group_sums(jnp.asarray(gids), [ones], 3))
    np.testing.assert_array_equal(got[0], [1, 2, 3])


def test_shuffle_writer_uses_pallas_pid_path():
    """End-to-end shuffle through the pallas partition-id fast path
    (forced interpret mode off-TPU) must equal the XLA path."""
    from blaze_tpu.kernels import pallas_ops
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.parallel import HashPartitioning, NativeShuffleExchangeExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int32())])
    batches = [
        [
            batch_from_pydict(
                {
                    "k": [int(v) if v % 9 else None for v in range(200 * i, 200 * i + 120)],
                    "v": list(range(120)),
                },
                schema,
            )
        ]
        for i in range(2)
    ]

    def run():
        src = MemoryScanExec(batches, schema)
        ex = NativeShuffleExchangeExec(src, HashPartitioning([col("k")], 3))
        out = {}
        for p in range(3):
            rows = []
            for b in ex.execute(p, TaskContext(p, 3)):
                d = batch_to_pydict(b)
                rows.extend(zip(d["k"], d["v"]))
            out[p] = sorted(rows, key=lambda r: (r[0] is None, r[0], r[1]))
        return out

    want = run()
    # count kernel invocations so a silent fallback to the XLA path
    # can't masquerade as coverage
    calls = {"n": 0}
    real = pallas_ops.murmur3_pids

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    pallas_ops.force_interpret(True)
    pallas_ops.murmur3_pids = counting
    try:
        got = run()
    finally:
        pallas_ops.murmur3_pids = real
        pallas_ops.force_interpret(False)
    assert got == want
    assert calls["n"] > 0, "pallas pid path was never taken"
