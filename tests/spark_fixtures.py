"""Builders emitting Spark catalyst ``TreeNode.toJSON``-format plan
dumps for the interception-layer tests.

The encoding mirrors catalyst's ``TreeNode.jsonValue``: ONE flat
preorder array per tree, ``class``/``num-children`` per node,
expression-valued fields as nested flat arrays, ``ExprId``s as
product-class objects (see ``blaze_tpu/spark/plan_json.py``).  Class
names are the real Spark ones so the converters exercise the exact
match arms the reference's ``BlazeConverters.scala`` has.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

X = "org.apache.spark.sql.catalyst.expressions."
A = "org.apache.spark.sql.catalyst.expressions.aggregate."
P = "org.apache.spark.sql.execution."
PHYS = "org.apache.spark.sql.catalyst.plans.physical."


def T(cls: str, children: Sequence[dict] = (), **fields) -> dict:
    """One tree node (nested form; flatten() converts to catalyst's
    preorder array)."""
    return {"_cls": cls, "_children": list(children), **fields}


def flatten(t: dict) -> List[dict]:
    out: List[dict] = []

    def go(n: dict):
        fields = {k: v for k, v in n.items() if k not in ("_cls", "_children")}
        out.append(
            {"class": n["_cls"], "num-children": len(n["_children"]), **fields}
        )
        for c in n["_children"]:
            go(c)

    go(t)
    return out


def eid(i: int) -> dict:
    return {
        "product-class": X + "ExprId",
        "id": i,
        "jvmId": "00000000-0000-0000-0000-000000000000",
    }


# ------------------------------------------------------------- expressions

def attr(name: str, i: int, dtype: Any = "long", nullable: bool = True) -> dict:
    return T(
        X + "AttributeReference",
        name=name,
        dataType=dtype,
        nullable=nullable,
        metadata={},
        exprId=eid(i),
        qualifier=[],
    )


def lit(value: Any, dtype: Any) -> dict:
    return T(X + "Literal", value=value, dataType=dtype)


def alias(child: dict, name: str, i: int) -> dict:
    return T(X + "Alias", [child], name=name, exprId=eid(i), qualifier=[])


def binop(cls: str, left: dict, right: dict) -> dict:
    return T(X + cls, [left, right])


def un(cls: str, child: dict, **fields) -> dict:
    return T(X + cls, [child], **fields)


def cast(child: dict, to: Any) -> dict:
    return T(X + "Cast", [child], dataType=to, timeZoneId=None)


def sort_order(child: dict, asc: bool = True, nulls_first: Optional[bool] = None) -> dict:
    if nulls_first is None:
        nulls_first = asc
    return T(
        X + "SortOrder",
        [child],
        direction="Ascending" if asc else "Descending",
        nullOrdering="NullsFirst" if nulls_first else "NullsLast",
    )


def agg_expr(fn: dict, mode: str, result_id: int, distinct: bool = False) -> dict:
    return T(
        A + "AggregateExpression",
        [fn],
        mode=mode,
        isDistinct=distinct,
        resultId=eid(result_id),
    )


def sum_(child: dict) -> dict:
    return T(A + "Sum", [child])


def avg(child: dict) -> dict:
    return T(A + "Average", [child])


def count(child: Optional[dict] = None) -> dict:
    return T(A + "Count", [child or lit(1, "integer")])


def min_(child: dict) -> dict:
    return T(A + "Min", [child])


def max_(child: dict) -> dict:
    return T(A + "Max", [child])


# ------------------------------------------------------------- windows

def frame_bound(kind) -> dict:
    """Frame bound: ``"up"``/``"uf"``/``"cr"`` case objects (with the
    trailing ``$`` catalyst's ``getClass.getName`` emits) or an int
    literal offset."""
    if isinstance(kind, int):
        return lit(kind, "integer")
    cls = {"up": "UnboundedPreceding$", "uf": "UnboundedFollowing$",
           "cr": "CurrentRow$"}[kind]
    return T(X + cls)


def window_frame(lower, upper, row: bool = True) -> dict:
    return T(
        X + "SpecifiedWindowFrame",
        [frame_bound(lower), frame_bound(upper)],
        frameType={"product-class": X + ("RowFrame$" if row else "RangeFrame$")},
    )


def window_spec(part: Sequence[dict], order: Sequence[dict], frame=None) -> dict:
    ch = list(part) + list(order) + ([frame] if frame is not None else [])
    return T(X + "WindowSpecDefinition", ch)


def window_expr(fn: dict, spec: dict, name: str, i: int) -> dict:
    return alias(T(X + "WindowExpression", [fn, spec]), name, i)


def rank_fn(order: Sequence[dict] = ()) -> dict:
    return T(X + "Rank", list(order))


def row_number_fn() -> dict:
    return T(X + "RowNumber")


def lag_fn(child: dict, offset: int = 1) -> dict:
    return T(X + "Lag", [child, lit(offset, "integer"), lit(None, "null")],
             ignoreNulls=False)


def lead_fn(child: dict, offset: int = 1) -> dict:
    return T(X + "Lead", [child, lit(offset, "integer"), lit(None, "null")],
             ignoreNulls=False)


def window_agg(fn: dict) -> dict:
    """Window aggregate: catalyst wraps the function in a Complete-mode
    AggregateExpression inside the WindowExpression."""
    return T(
        A + "AggregateExpression",
        [fn],
        mode={"product-class": A + "Complete$"},
        isDistinct=False,
        resultId=eid(0),
    )


# ------------------------------------------------------------------ plans

def scan(table: str, attrs: Sequence[dict]) -> dict:
    return T(
        P + "FileSourceScanExec",
        relation=None,  # catalyst degrades HadoopFsRelation to null
        output=[flatten(a) for a in attrs],
        requiredSchema={"type": "struct", "fields": []},
        partitionFilters=[],
        optionalBucketSet=None,
        optionalNumCoalescedBuckets=None,
        dataFilters=[],
        tableIdentifier={
            "product-class": "org.apache.spark.sql.catalyst.TableIdentifier",
            "table": table,
        },
        disableBucketedScan=False,
    )


def filter_(condition: dict, child: dict) -> dict:
    return T(P + "FilterExec", [child], condition=flatten(condition))


def project(plist: Sequence[dict], child: dict) -> dict:
    return T(P + "ProjectExec", [child], projectList=[flatten(p) for p in plist])


def hash_agg(
    groupings: Sequence[dict],
    aggs: Sequence[dict],
    child: dict,
    result: Optional[Sequence[dict]] = None,
    initial_input_buffer_offset: int = 0,
) -> dict:
    return T(
        P + "aggregate.HashAggregateExec",
        [child],
        requiredChildDistributionExpressions=None,
        groupingExpressions=[flatten(g) for g in groupings],
        aggregateExpressions=[flatten(a) for a in aggs],
        aggregateAttributes=[],
        initialInputBufferOffset=initial_input_buffer_offset,
        resultExpressions=[flatten(r) for r in (result or [])],
    )


def single_partition() -> dict:
    return {"product-class": PHYS + "SinglePartition$"}


def hash_partitioning(keys: Sequence[dict], n: int) -> list:
    return flatten(T(PHYS + "HashPartitioning", list(keys), numPartitions=n))


def shuffle(partitioning: Any, child: dict) -> dict:
    return T(
        P + "exchange.ShuffleExchangeExec",
        [child],
        outputPartitioning=partitioning,
        shuffleOrigin={"product-class": P + "exchange.ENSURE_REQUIREMENTS$"},
    )


def broadcast(child: dict) -> dict:
    return T(P + "exchange.BroadcastExchangeExec", [child], mode=None)


def bhj(
    left_keys: Sequence[dict],
    right_keys: Sequence[dict],
    join_type: str,
    build_side: str,
    left: dict,
    right: dict,
    condition: Optional[dict] = None,
) -> dict:
    return T(
        P + "joins.BroadcastHashJoinExec",
        [left, right],
        leftKeys=[flatten(k) for k in left_keys],
        rightKeys=[flatten(k) for k in right_keys],
        joinType=join_type,
        buildSide="BuildLeft" if build_side == "left" else "BuildRight",
        condition=flatten(condition) if condition else None,
        isNullAwareAntiJoin=False,
    )


def shj(
    left_keys: Sequence[dict],
    right_keys: Sequence[dict],
    join_type: str,
    build_side: str,
    left: dict,
    right: dict,
    condition: Optional[dict] = None,
) -> dict:
    return T(
        P + "joins.ShuffledHashJoinExec",
        [left, right],
        leftKeys=[flatten(k) for k in left_keys],
        rightKeys=[flatten(k) for k in right_keys],
        joinType=join_type,
        buildSide="BuildLeft" if build_side == "left" else "BuildRight",
        condition=flatten(condition) if condition else None,
    )


def smj(
    left_keys: Sequence[dict],
    right_keys: Sequence[dict],
    join_type: str,
    left: dict,
    right: dict,
    condition: Optional[dict] = None,
) -> dict:
    return T(
        P + "joins.SortMergeJoinExec",
        [left, right],
        leftKeys=[flatten(k) for k in left_keys],
        rightKeys=[flatten(k) for k in right_keys],
        joinType=join_type,
        condition=flatten(condition) if condition else None,
        isSkewJoin=False,
    )


def sort(orders: Sequence[dict], child: dict, global_: bool = True) -> dict:
    return T(
        P + "SortExec",
        [child],
        sortOrder=[flatten(o) for o in orders],
        **{"global": global_},
    )


def global_limit(n: int, child: dict) -> dict:
    return T(P + "GlobalLimitExec", [child], limit=n)


def take_ordered(
    n: int, orders: Sequence[dict], plist: Sequence[dict], child: dict
) -> dict:
    return T(
        P + "TakeOrderedAndProjectExec",
        [child],
        limit=n,
        sortOrder=[flatten(o) for o in orders],
        projectList=[flatten(p) for p in plist],
    )


def union(children: Sequence[dict]) -> dict:
    return T(P + "UnionExec", list(children))


def wscg(child: dict) -> dict:
    """WholeStageCodegenExec wrapper (pass-through in conversion)."""
    return T(P + "WholeStageCodegenExec", [child], codegenStageId=1)


def window(wexprs: Sequence[dict], part: Sequence[dict], order: Sequence[dict],
           child: dict) -> dict:
    return T(
        P + "window.WindowExec",
        [child],
        windowExpression=[flatten(w) for w in wexprs],
        partitionSpec=[flatten(p) for p in part],
        orderSpec=[flatten(o) for o in order],
    )


def expand(projections: Sequence[Sequence[dict]], output: Sequence[dict],
           child: dict) -> dict:
    return T(
        P + "ExpandExec",
        [child],
        projections=[[flatten(e) for e in proj] for proj in projections],
        output=[flatten(a) for a in output],
    )


def existence_join_type(exists_attr: dict) -> dict:
    """``ExistenceJoin(exists)`` as catalyst serializes it: a product
    object carrying the appended bool attribute."""
    return {
        "product-class": "org.apache.spark.sql.catalyst.plans.ExistenceJoin",
        "exists": flatten(exists_attr),
    }


def range_partitioning(orders: Sequence[dict], n: int) -> list:
    return flatten(T(PHYS + "RangePartitioning", list(orders), numPartitions=n))
