"""C++ native runtime: cross-checks against the (Spark-golden-tested)
device kernels and the python IO paths.

≙ reference commons unit tests (spark_hash, batch serde roundtrips,
loser tree, FFI helpers)."""

import numpy as np
import pytest

from blaze_tpu import native
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict, column_from_numpy, column_from_strings
from blaze_tpu.exprs.hash import murmur3_columns, xxhash64_columns
from blaze_tpu.io.batch_serde import deserialize_batch, serialize_batch
from blaze_tpu.io.ipc_compression import compress_frame, decompress_frame
from blaze_tpu.schema import DataType, Field, Schema

pytestmark = pytest.mark.skipif(not native.available(), reason="native lib not built")


def test_version():
    assert "blaze-tpu-native" in native.version()


def test_murmur3_matches_device():
    ints = column_from_numpy(DataType.int64(), np.array([1, 0, -1, 2**62], np.int64))
    strs = column_from_strings(["hello", "bar", "", "a-longer-string-over-32-bytes!!!!"])
    n = 4
    host = native.murmur3_host([c.to_host() for c in (ints, strs)], n)
    dev = np.asarray(murmur3_columns([ints, strs]))[:n]
    assert host.tolist() == dev.tolist()


def test_xxhash64_matches_device():
    ints = column_from_numpy(DataType.int32(), np.array([7, -9, 0], np.int32),
                             validity=np.array([True, False, True]))
    strs = column_from_strings(["x", None, "yz"])
    host = native.xxhash64_host([c.to_host() for c in (ints, strs)], 3)
    dev = np.asarray(xxhash64_columns([ints, strs]))[:3]
    assert host.tolist() == dev.tolist()


def test_serde_native_matches_python():
    schema = Schema([
        Field("a", DataType.int64()),
        Field("s", DataType.string(16)),
        Field("d", DataType.decimal(12, 2)),
    ])
    b = batch_from_pydict(
        {"a": [1, None, 3], "s": ["x", "yy", None], "d": [1.25, -2.5, 0.0]}, schema
    )
    py_bytes = serialize_batch(b)
    nat_bytes = native.serialize_batch_native(b)
    assert nat_bytes == py_bytes
    rt = deserialize_batch(nat_bytes, schema)
    assert batch_to_pydict(rt) == batch_to_pydict(b)


def test_frame_native_python_interop():
    payload = b"spark-compatible framing" * 500
    nat = native.compress_frame_native(payload)
    assert decompress_frame(nat) == payload
    py = compress_frame(payload)
    out = native.decompress_frame_native(py, len(payload) + 16)
    assert out == payload


def test_loser_tree_merge():
    rng = np.random.RandomState(7)
    runs = [np.sort(rng.randint(0, 1000, rng.randint(1, 50)).astype(np.uint64)) for _ in range(5)]
    run_idx, off = native.loser_tree_merge(runs)
    merged = np.array([runs[r][o] for r, o in zip(run_idx, off)])
    expected = np.sort(np.concatenate(runs))
    assert merged.tolist() == expected.tolist()
    # stability: equal keys come from lower run index first
    for i in range(1, len(merged)):
        if merged[i] == merged[i - 1]:
            assert not (run_idx[i] < run_idx[i - 1])


def test_arrow_ffi_roundtrip():
    col = column_from_numpy(
        DataType.int64(), np.array([5, 6, 7], np.int64),
        validity=np.array([True, False, True]),
    ).to_host()
    data, valid = native.arrow_roundtrip(col, 3)
    assert data.tolist()[0] == 5 and data.tolist()[2] == 7
    assert valid.tolist() == [True, False, True]
