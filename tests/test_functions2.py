"""Scalar functions round 2: decimal interop (unscaled_value,
make_decimal, check_overflow), nullif, hash exprs, string constructors
(space, repeat, concat_ws).

≙ reference datafusion-ext-functions unit tests for the same names.
"""

import numpy as np

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import Lit, ScalarFunc
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema


def run_project(data, schema, exprs):
    b = batch_from_pydict(data, schema)
    p = ProjectExec(MemoryScanExec([[b]], schema), exprs)
    return batch_to_pydict(list(p.execute(0, TaskContext(0, 1)))[0])


def test_unscaled_value_and_make_decimal_roundtrip():
    schema = Schema([Field("d", DataType.decimal(10, 2))])
    d = run_project(
        {"d": [1.25, -3.5, None]},
        schema,
        [
            ScalarFunc("unscaled_value", [col("d")]).alias("u"),
            ScalarFunc(
                "make_decimal",
                [ScalarFunc("unscaled_value", [col("d")]), Lit(10), Lit(2)],
            ).alias("rt"),
        ],
    )
    assert d["u"] == [125, -350, None]
    assert d["rt"] == [125, -350, None]  # decimals come back unscaled


def test_check_overflow_nulls_on_overflow():
    schema = Schema([Field("d", DataType.decimal(12, 2))])
    # target decimal(4, 2): |v| must be < 10^4 unscaled (i.e. < 100.00)
    d = run_project(
        {"d": [99.99, 100.00, -99.99, -100.01, None]},
        schema,
        [ScalarFunc("check_overflow", [col("d"), Lit(4), Lit(2)]).alias("c")],
    )
    assert d["c"] == [9999, None, -9999, None, None]


def test_check_overflow_wide_precision_keeps_large_values():
    # decimal(22,2): any int64 unscaled value fits 22 digits; values in
    # [10^18, 2^63) must NOT be nulled (Spark CheckOverflow keeps them)
    schema = Schema([Field("d", DataType.decimal(20, 2))])
    big = 2_500_000_000_000_000_000  # 2.5e18 unscaled, > 10**18
    d = run_project(
        {"d": [big / 100.0]},  # 2.5e16 == 2^15 * 5^17: exact in float64
        schema,
        [ScalarFunc("check_overflow", [col("d"), Lit(22), Lit(2)]).alias("c")],
    )
    assert d["c"] == [big]


def test_nullif():
    schema = Schema([Field("a", DataType.int64()), Field("b", DataType.int64())])
    d = run_project(
        {"a": [1, 2, None, 4], "b": [1, 3, 1, None]},
        schema,
        [ScalarFunc("nullif", [col("a"), col("b")]).alias("n")],
    )
    assert d["n"] == [None, 2, None, 4]


def test_nullif_strings():
    schema = Schema([Field("a", DataType.string(8)), Field("b", DataType.string(8))])
    d = run_project(
        {"a": ["x", "y", None], "b": ["x", "z", "x"]},
        schema,
        [ScalarFunc("nullif", [col("a"), col("b")]).alias("n")],
    )
    assert d["n"] == [None, "y", None]


def test_hash_exprs_match_hash_module():
    from blaze_tpu.batch import column_from_numpy
    from blaze_tpu.exprs.hash import murmur3_columns, xxhash64_columns

    schema = Schema([Field("k", DataType.int64())])
    vals = [12345, -7, None, 2**40]
    d = run_project(
        {"k": vals},
        schema,
        [
            ScalarFunc("murmur3_hash", [col("k")]).alias("m"),
            ScalarFunc("xxhash64", [col("k")]).alias("x"),
        ],
    )
    kcol = column_from_numpy(
        DataType.int64(),
        np.array([v if v is not None else 0 for v in vals], np.int64),
        np.array([v is not None for v in vals]),
        capacity=4,
    ).to_device()
    assert d["m"] == [int(v) for v in np.asarray(murmur3_columns([kcol]))[:4]]
    assert d["x"] == [int(v) for v in np.asarray(xxhash64_columns([kcol]))[:4]]


def test_space_and_repeat():
    schema = Schema([Field("n", DataType.int32()), Field("s", DataType.string(8))])
    d = run_project(
        {"n": [0, 3, None], "s": ["ab", "xyz", "q"]},
        schema,
        [
            ScalarFunc("space", [col("n")]).alias("sp"),
            ScalarFunc("repeat", [col("s"), Lit(3)]).alias("r3"),
            ScalarFunc("repeat", [col("s"), col("n")]).alias("rn"),
        ],
    )
    assert d["sp"] == ["", "   ", None]
    assert d["r3"] == ["ababab", "xyzxyzxyz", "qqq"]
    assert d["rn"] == ["", "xyzxyzxyz", None]


def test_concat_ws_skips_nulls():
    schema = Schema([
        Field("a", DataType.string(8)),
        Field("b", DataType.string(8)),
        Field("c", DataType.string(8)),
    ])
    d = run_project(
        {"a": ["x", None, None], "b": ["y", "m", None], "c": [None, "n", None]},
        schema,
        [ScalarFunc("concat_ws", [Lit(","), col("a"), col("b"), col("c")]).alias("j")],
    )
    # Spark: null args skipped entirely; all-null -> empty string
    assert d["j"] == ["x,y", "m,n", ""]
