"""min/max/first over STRING columns in the sort-segment agg
(round-1 roadmap item): lexicographic per-segment min/max via
order-word tie-break passes; verified against python semantics across
partial -> final merges.
"""

import numpy as np

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([Field("g", DataType.int32()), Field("s", DataType.string(16))])


def _run(batches, fns):
    plan = AggExec(MemoryScanExec([batches], SCHEMA), AggMode.PARTIAL,
                   [GroupingExpr(col("g"), "g")], fns)
    plan = AggExec(plan, AggMode.FINAL, [GroupingExpr(col("g"), "g")], fns)
    out = list(plan.execute(0, TaskContext(0, 1)))
    return batch_to_pydict(out[0])


def test_string_min_max_first():
    data = {"g": [1, 1, 1, 2, 2, 3],
            "s": ["banana", "apple", "ab", None, "zz", None]}
    b = batch_from_pydict(data, SCHEMA)
    fns = [AggFunction("min", col("s"), "mn"), AggFunction("max", col("s"), "mx"),
           AggFunction("first_ignores_null", col("s"), "fi")]
    d = _run([b], fns)
    got = {g: (mn, mx, fi) for g, mn, mx, fi in zip(d["g"], d["mn"], d["mx"], d["fi"])}
    assert got[1] == ("ab", "banana", "banana")
    assert got[2] == ("zz", "zz", "zz")
    assert got[3] == (None, None, None)


def test_string_minmax_randomized_multi_batch():
    rng = np.random.RandomState(3)
    words = ["", "a", "ab", "abc", "b", "ba", "zz", "zzz", "m", "mm"]
    gs, ss = [], []
    for _ in range(300):
        gs.append(int(rng.randint(0, 10)))
        ss.append(None if rng.rand() < 0.2 else words[rng.randint(len(words))])
    batches = [
        batch_from_pydict({"g": gs[i : i + 64], "s": ss[i : i + 64]}, SCHEMA)
        for i in range(0, 300, 64)
    ]
    fns = [AggFunction("min", col("s"), "mn"), AggFunction("max", col("s"), "mx")]
    d = _run(batches, fns)
    exp_min, exp_max = {}, {}
    for g, s in zip(gs, ss):
        if s is None:
            continue
        exp_min[g] = min(exp_min.get(g, s), s)
        exp_max[g] = max(exp_max.get(g, s), s)
    for g, mn, mx in zip(d["g"], d["mn"], d["mx"]):
        assert mn == exp_min.get(g), (g, mn, exp_min.get(g))
        assert mx == exp_max.get(g), (g, mx, exp_max.get(g))


def test_window_running_min_max():
    """Running (unbounded-preceding..current-peer) min/max frames via
    segmented associative scan, vs a python oracle with peers+nulls."""
    from blaze_tpu.ops.sort import SortField
    from blaze_tpu.ops.window import WindowExec, WindowFunction

    rng = np.random.RandomState(9)
    n = 200
    ps = sorted(int(rng.randint(0, 6)) for _ in range(n))
    os_, vs = [], []
    for _ in range(n):
        os_.append(int(rng.randint(0, 8)))
        vs.append(None if rng.rand() < 0.25 else int(rng.randint(-50, 50)))
    rows = sorted(zip(ps, os_, vs), key=lambda r: (r[0], r[1]))
    ps, os_, vs = (list(x) for x in zip(*rows))
    schema = Schema([Field("p", DataType.int32()), Field("o", DataType.int32()),
                     Field("v", DataType.int64())])
    b = batch_from_pydict({"p": ps, "o": os_, "v": vs}, schema)
    w = WindowExec(
        MemoryScanExec([[b]], schema),
        [WindowFunction("min", "rmin", col("v")), WindowFunction("max", "rmax", col("v"))],
        [col("p")], [SortField(col("o"), True, True)],
    )
    d = batch_to_pydict(list(w.execute(0, TaskContext(0, 1)))[0])
    # oracle: range frame includes peers (rows with equal (p, o))
    for i in range(n):
        frame = [vs[j] for j in range(n)
                 if ps[j] == ps[i] and os_[j] <= os_[i] and vs[j] is not None]
        exp_min = min(frame) if frame else None
        exp_max = max(frame) if frame else None
        assert d["rmin"][i] == exp_min, (i, d["rmin"][i], exp_min)
        assert d["rmax"][i] == exp_max, (i, d["rmax"][i], exp_max)
