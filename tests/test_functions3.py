"""Scalar function surface round 3: math, trim family, date arithmetic
(device kernels) and the host long tail (regex, hashes, pad/locate,
translate, split, from_unixtime) — Spark-semantics golden cases.

≙ reference datafusion-ext-functions (lib.rs:34-59) + the ScalarFunction
enum (blaze.proto:197-264).
"""

import math

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import Lit, ScalarFunc
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema


def run_project(data, schema, exprs):
    b = batch_from_pydict(data, schema)
    p = ProjectExec(MemoryScanExec([[b]], schema), exprs)
    return batch_to_pydict(list(p.execute(0, TaskContext(0, 1)))[0])


def F(name, *args):
    return ScalarFunc(name, [a if hasattr(a, "alias") else Lit(a) for a in args])


# ----------------------------------------------------------------- math

def test_math_unary():
    schema = Schema([Field("x", DataType.float64())])
    d = run_project(
        {"x": [0.25, 1.0, None]},
        schema,
        [
            F("sqrt", col("x")).alias("sqrt"),
            F("exp", col("x")).alias("exp"),
            F("ln", col("x")).alias("ln"),
            F("log10", col("x")).alias("log10"),
            F("sin", col("x")).alias("sin"),
            F("signum", col("x") - lit(0.5)).alias("sg"),
        ],
    )
    assert d["sqrt"][0] == 0.5 and d["sqrt"][2] is None
    assert abs(d["exp"][1] - math.e) < 1e-12
    assert d["ln"][1] == 0.0
    assert d["log10"][1] == 0.0
    assert abs(d["sin"][1] - math.sin(1.0)) < 1e-12
    assert d["sg"] == [-1.0, 1.0, None]


def test_ceil_floor_power():
    schema = Schema([Field("x", DataType.float64()), Field("y", DataType.float64())])
    d = run_project(
        {"x": [1.2, -1.2, None], "y": [2.0, 3.0, 4.0]},
        schema,
        [
            F("ceil", col("x")).alias("c"),
            F("floor", col("x")).alias("f"),
            F("pow", col("y"), 2).alias("p"),
        ],
    )
    assert d["c"] == [2, -1, None]
    assert d["f"] == [1, -2, None]
    assert d["p"] == [4.0, 9.0, 16.0]


def test_null_if_zero():
    schema = Schema([Field("x", DataType.int64())])
    d = run_project({"x": [0, 5, None]}, schema, [F("null_if_zero", col("x")).alias("z")])
    assert d["z"] == [None, 5, None]


# ----------------------------------------------------------------- trim

def test_trim_family():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["  ab c  ", "x", "   ", "", None]},
        schema,
        [
            F("trim", col("s")).alias("t"),
            F("ltrim", col("s")).alias("l"),
            F("rtrim", col("s")).alias("r"),
            F("btrim", col("s")).alias("b"),
        ],
    )
    assert d["t"] == ["ab c", "x", "", "", None]
    assert d["l"] == ["ab c  ", "x", "", "", None]
    assert d["r"] == ["  ab c", "x", "", "", None]
    assert d["b"] == d["t"]


def test_trim_with_chars():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["xxhixx", "xyxhix", None]},
        schema,
        [
            F("trim", col("s"), "x").alias("t"),
            F("btrim", col("s"), "xy").alias("b"),
            F("ltrim", col("s"), "x").alias("l"),
        ],
    )
    assert d["t"] == ["hi", "yxhi", None]
    assert d["b"] == ["hi", "hi", None]
    assert d["l"] == ["hixx", "yxhix", None]


def test_translate_duplicate_from_chars():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["abc"]},
        schema,
        [F("translate", col("s"), "aa", "xy").alias("t")],
    )
    assert d["t"] == ["xbc"]  # first mapping wins


def test_date_format_timestamp_and_date():
    import datetime

    schema = Schema([Field("d", DataType.date32()), Field("t", DataType.timestamp())])
    ts = int(datetime.datetime(2001, 2, 3, 4, 5, 6, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    d = run_project(
        {"d": [datetime.date(2020, 5, 17)], "t": [ts]},
        schema,
        [F("date_format", col("d"), "yyyy/MM/dd").alias("fd"),
         F("date_format", col("t"), "yyyy-MM-dd HH:mm:ss").alias("ft")],
    )
    assert d["fd"] == ["2020/05/17"]
    assert d["ft"] == ["2001-02-03 04:05:06"]


def test_lengths_and_predicates():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["abc", "", None, "héllo"]},
        schema,
        [
            F("bit_length", col("s")).alias("bl"),
            F("octet_length", col("s")).alias("ol"),
            F("char_length", col("s")).alias("cl"),
            F("starts_with", col("s"), "ab").alias("sw"),
            F("ends_with", col("s"), "c").alias("ew"),
        ],
    )
    assert d["bl"] == [24, 0, None, 48]  # héllo = 6 utf8 bytes
    assert d["ol"] == [3, 0, None, 6]
    assert d["cl"] == [3, 0, None, 5]
    assert d["sw"] == [True, False, None, False]
    assert d["ew"] == [True, False, None, False]


# ----------------------------------------------------------------- dates

def test_date_arithmetic():
    schema = Schema([Field("d", DataType.date32())])
    import datetime

    base = datetime.date(2024, 2, 29)  # leap day
    d = run_project(
        {"d": [base, datetime.date(1999, 12, 31), None]},
        schema,
        [
            F("date_add", col("d"), 1).alias("add1"),
            F("date_sub", col("d"), 60).alias("sub60"),
            F("quarter", col("d")).alias("q"),
            F("dayofweek", col("d")).alias("dow"),
            F("dayofyear", col("d")).alias("doy"),
            F("weekofyear", col("d")).alias("woy"),
            F("last_day", col("d")).alias("ld"),
            F("add_months", col("d"), 12).alias("am"),
        ],
    )
    epoch = datetime.date(1970, 1, 1)
    as_date = lambda v: None if v is None else epoch + datetime.timedelta(days=v)
    assert as_date(d["add1"][0]) == datetime.date(2024, 3, 1)
    assert as_date(d["sub60"][0]) == base - datetime.timedelta(days=60)
    assert d["q"] == [1, 4, None]
    # 2024-02-29 is a Thursday -> Spark dayofweek (1=Sunday) = 5
    assert d["dow"][0] == 5
    assert d["doy"] == [60, 365, None]
    assert d["woy"][0] == 9 and d["woy"][1] == 52
    assert as_date(d["ld"][0]) == datetime.date(2024, 2, 29)
    assert as_date(d["ld"][1]) == datetime.date(1999, 12, 31)
    # add_months clamps: 2024-02-29 + 12 months = 2025-02-28
    assert as_date(d["am"][0]) == datetime.date(2025, 2, 28)


def test_datediff_and_ts_parts():
    import datetime

    schema = Schema([Field("a", DataType.date32()), Field("b", DataType.date32()),
                     Field("t", DataType.timestamp())])
    ts = int(datetime.datetime(2001, 2, 3, 4, 5, 6, tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    d = run_project(
        {"a": [datetime.date(2020, 1, 10)], "b": [datetime.date(2020, 1, 3)], "t": [ts]},
        schema,
        [
            F("datediff", col("a"), col("b")).alias("dd"),
            F("hour", col("t")).alias("h"),
            F("minute", col("t")).alias("m"),
            F("second", col("t")).alias("s"),
            F("unix_timestamp", col("t")).alias("u"),
        ],
    )
    assert d["dd"] == [7]
    assert (d["h"], d["m"], d["s"]) == ([4], [5], [6])
    assert d["u"] == [ts // 1_000_000]


# ------------------------------------------------------------ host tail

def test_hashes():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["abc", None]},
        schema,
        [
            F("md5", col("s")).alias("md5"),
            F("sha1", col("s")).alias("sha1"),
            F("sha2", col("s"), 256).alias("sha2"),
            F("crc32", col("s")).alias("crc"),
        ],
    )
    assert d["md5"] == ["900150983cd24fb0d6963f7d28e17f72", None]
    assert d["sha1"][0].startswith("a9993e364706816aba3e")
    assert d["sha2"][0].startswith("ba7816bf8f01cfea")
    assert d["crc"] == [891568578, None]


def test_regex_family():
    schema = Schema([Field("s", DataType.string(32))])
    d = run_project(
        {"s": ["foo123bar", "nodigits", None]},
        schema,
        [
            F("rlike", col("s"), r"\d+").alias("rl"),
            F("regexp_replace", col("s"), r"\d+", "#").alias("rr"),
            F("regexp_extract", col("s"), r"(\d+)", 1).alias("re"),
        ],
    )
    assert d["rl"] == [True, False, None]
    assert d["rr"] == ["foo#bar", "nodigits", None]
    assert d["re"] == ["123", "", None]


def test_string_tail():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["hello world", "ab", None]},
        schema,
        [
            F("initcap", col("s")).alias("ic"),
            F("reverse", col("s")).alias("rv"),
            F("translate", col("s"), "lo", "01").alias("tr"),
            F("replace", col("s"), "l", "L").alias("rp"),
            F("lpad", col("s"), 4, "*").alias("lp"),
            F("rpad", col("s"), 4, "*").alias("rp2"),
            F("left", col("s"), 3).alias("lf"),
            F("right", col("s"), 3).alias("rt"),
            F("instr", col("s"), "o").alias("in"),
            F("locate", "o", col("s"), 6).alias("lc"),
            F("ascii", col("s")).alias("as"),
            F("to_hex", lit(255)).alias("hx"),
            F("chr", lit(65)).alias("ch"),
        ],
    )
    assert d["ic"] == ["Hello World", "Ab", None]
    assert d["rv"] == ["dlrow olleh", "ba", None]
    assert d["tr"] == ["he001 w1r0d", "ab", None]
    assert d["rp"] == ["heLLo worLd", "ab", None]
    assert d["lp"] == ["hell", "**ab", None]
    assert d["rp2"] == ["hell", "ab**", None]
    assert d["lf"] == ["hel", "ab", None]
    assert d["rt"] == ["rld", "ab", None]
    assert d["in"] == [5, 0, None]
    assert d["lc"] == [8, 0, None]
    assert d["as"] == [104, 97, None]
    assert d["hx"] == ["FF", "FF", None] or d["hx"][:2] == ["FF", "FF"]
    assert d["ch"][:2] == ["A", "A"]


def test_split_family():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["a,b,c", "x", None]},
        schema,
        [
            F("split", col("s"), ",").alias("sp"),
            F("split_part", col("s"), ",", 2).alias("p2"),
            F("split_part", col("s"), ",", 9).alias("p9"),
        ],
    )
    assert d["sp"] == [["a", "b", "c"], ["x"], None]
    assert d["p2"] == ["b", "", None]
    assert d["p9"] == ["", "", None]


def test_datetime_formatting():
    schema = Schema([Field("t", DataType.int64())])
    d = run_project(
        {"t": [981173106, None]},  # 2001-02-03 04:05:06 UTC
        schema,
        [F("from_unixtime", col("t")).alias("f"),
         F("from_unixtime", col("t"), "yyyy/MM/dd").alias("f2")],
    )
    assert d["f"] == ["2001-02-03 04:05:06", None]
    assert d["f2"] == ["2001/02/03", None]


def test_to_date_and_date_format():
    schema = Schema([Field("s", DataType.string(16))])
    d = run_project(
        {"s": ["2020-05-17", "garbage", None]},
        schema,
        [F("to_date", col("s")).alias("d")],
    )
    import datetime

    want = (datetime.date(2020, 5, 17) - datetime.date(1970, 1, 1)).days
    assert d["d"] == [want, None, None]


def test_array_union():
    arr_t = DataType.array(DataType.int64(), 4)
    schema = Schema([Field("a", arr_t), Field("b", arr_t)])
    d = run_project(
        {"a": [[1, 2], [5], None], "b": [[2, 3], [], [1]]},
        schema,
        [F("array_union", col("a"), col("b")).alias("u")],
    )
    assert sorted(d["u"][0]) == [1, 2, 3]
    assert d["u"][1] == [5]
    assert d["u"][2] is None


def test_host_fn_inside_filter_and_nested():
    """Host functions compose: nested host calls + device subtrees, and
    they hoist correctly out of jitted kernels."""
    schema = Schema([Field("s", DataType.string(16)), Field("x", DataType.int64())])
    from blaze_tpu.ops import FilterExec

    b = batch_from_pydict({"s": ["a1", "bb", "c3"], "x": [1, 2, 3]}, schema)
    f = FilterExec(MemoryScanExec([[b]], schema), ScalarFunc("rlike", [col("s"), Lit(r"\d")]))
    d = batch_to_pydict(list(f.execute(0, TaskContext(0, 1)))[0])
    assert d["s"] == ["a1", "c3"]
    # nested: md5(reverse(s))
    d = run_project(
        {"s": ["ab", None], "x": [1, 2]},
        schema,
        [F("md5", F("reverse", col("s"))).alias("h")],
    )
    import hashlib

    assert d["h"] == [hashlib.md5(b"ba").hexdigest(), None]
