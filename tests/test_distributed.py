"""Distributed differential tests: TPC-H q1/q3/q6 end-to-end over the
8-virtual-device CPU mesh through the REAL exchange paths —

1. the ICI fast path (``lax.all_to_all`` over a ``jax.sharding.Mesh``
   via IciShuffleExchangeExec), and
2. the LocalShuffleManager file path under a capped memory budget
   (shuffle spills forced),

both validated against the numpy oracles.  This is the repo's analogue
of the reference's pseudo-distributed testenv (dev/testenv/) and the
basis of ``__graft_entry__.dryrun_multichip``.
"""

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.parallel.ici import use_ici_exchanges
from blaze_tpu.parallel.mesh import make_mesh
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch import oracle as O
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 8  # == mesh size


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], N_PARTS, batch_rows=2048),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _rows(d, fields):
    return sorted(zip(*[d[f] for f in fields]), key=repr)


@pytest.mark.parametrize("q", ["q1", "q6", "q3"])
def test_ici_mesh_matches_file_shuffle_and_oracle(data, q):
    mesh = make_mesh(8)
    file_path = run(build_query(q, _scans(data), N_PARTS))
    ici_plan = use_ici_exchanges(build_query(q, _scans(data), N_PARTS), mesh)
    ici_path = run(ici_plan)
    fields = list(file_path.keys())
    assert _rows(ici_path, fields) == _rows(file_path, fields)


def test_q1_ici_against_oracle(data):
    mesh = make_mesh(8)
    got = run(use_ici_exchanges(build_query("q1", _scans(data), N_PARTS), mesh))
    exp = O.oracle_q1(data)
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert set(keys) == set(exp)
    for i, k in enumerate(keys):
        e = exp[k]
        for m in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge", "count_order"):
            assert got[m][i] == e[m], (k, m)


def test_q6_ici_against_oracle(data):
    mesh = make_mesh(8)
    got = run(use_ici_exchanges(build_query("q6", _scans(data), N_PARTS), mesh))
    assert got["revenue"] == [O.oracle_q6(data)]


def test_q6_file_shuffle_spill_path(data):
    """The LocalShuffleManager path under a tiny budget: spills fire
    and the result still matches the oracle."""
    try:
        MemManager._global = None
        MemManager.init(50_000)
        plan = build_query("q6", _scans(data), N_PARTS)
        got = run(plan)
        assert got["revenue"] == [O.oracle_q6(data)]
    finally:
        MemManager._global = None
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))
