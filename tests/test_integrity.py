"""End-to-end data integrity & storage-failure resilience.

The corruption matrix: one seeded ``@corrupt`` injection per site
(shuffle map output, spill frame, RSS push, broadcast blob, worker
result), each asserting DETECTION (typed error + ``block_corruption``
event + ``corruption_detected`` counter), recovery through the
existing ladder to byte-identical results, and the paired
``fault_injected``/``block_corruption`` events.  Plus the
disk-pressure ladder (``@enospc`` injection, reclaim, in-memory
fallback, victim re-selection, typed ``DiskExhaustedError``), the
quarantine policy, the LZ4 frame-checksum satellite, torn-JSONL
tolerance, and the startup orphan sweep.
"""

import errno
import os
import struct
import time

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.io import ipc_compression as ic
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.parallel.shuffle import (
    HashPartitioning, IpcReaderExec, LocalShuffleManager,
    ShuffleRepartitioner, ShuffleWriterExec, SinglePartitioning,
)
from blaze_tpu.runtime import diskmgr, dispatch, faults, integrity, trace
from blaze_tpu.runtime.context import RESOURCES, TaskContext
from blaze_tpu.runtime.diskmgr import DiskExhaustedError
from blaze_tpu.runtime.integrity import BlockCorruptionError
from blaze_tpu.runtime.metrics import MetricNode, MetricsSet
from blaze_tpu.runtime.retry import RETRY, FetchFailedError, classify
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.schema import DataType, Field, Schema

import spark_fixtures as F
from test_spark_convert import make_session, q6_like_plan


@pytest.fixture(autouse=True)
def _clean_state():
    """Deterministic, sleep-free runs; always clear injected state."""
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    faults.reset()
    integrity.reset()
    yield
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.1)
    conf.IO_CHECKSUM.set("crc32")
    faults.reset()
    integrity.reset()


def _inject(spec: str) -> None:
    conf.FAULTS_SPEC.set(spec)
    faults.reset()


def _scheduler_rows(sess, plan_json, metrics=None, manager=None):
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan, manager)
    out = {f.name: [] for f in stages[-1].plan.schema.fields}
    for b in run_stages(stages, manager, metrics=metrics):
        d = batch_to_pydict(b)
        for k in out:
            out[k].extend(d[k])
    return out, manager


# ------------------------------------------------- frame checksum unit

def test_frame_checksum_roundtrip_and_detection_all_algos():
    payload = b"the quick brown fox " * 500
    for name in ("crc32", "crc32c", "xxh32"):
        conf.IO_CHECKSUM.set(name)
        algo = integrity.frame_algo()
        assert algo is not None
        frame = ic.compress_frame(payload, checksum_algo=algo)
        assert ic.decompress_frame(frame) == payload
        bad = integrity.flip_byte(frame, 5 + len(frame) // 2)
        with pytest.raises(BlockCorruptionError):
            ic.decompress_frame(bad)
    # off: no trailer stamped, plain frames verify-free
    conf.IO_CHECKSUM.set("off")
    assert integrity.frame_algo() is None
    # unknown algorithm names fail loudly, never silently disable
    conf.IO_CHECKSUM.set("md5")
    with pytest.raises(ValueError, match="io.checksum"):
        integrity.frame_algo()


def test_crc32c_known_check_value():
    # the CRC32C check value from RFC 3720 / every hardware impl
    assert integrity.crc32c(b"123456789") == 0xE3069283


def test_corrupt_trailer_algo_byte_cannot_disarm_verification():
    """A flagged frame whose trailer algo byte was itself corrupted
    (to 0 = 'off', or to an unknown id) must raise the TYPED error —
    writers never stamp algo-0 trailers, so treating it as
    'unverified' would let one bit flip defeat the whole layer."""
    payload = b"payload" * 64
    frame = ic.compress_frame(payload, checksum_algo=integrity.ALGO_CRC32)
    # trailer = last 5 bytes: [algo][u32 sum]; zero the algo byte
    off = len(frame) - 5
    for bad_algo in (0x00, 0x55):
        bad = frame[:off] + bytes([bad_algo]) + frame[off + 1:]
        with pytest.raises(BlockCorruptionError):
            ic.decompress_frame(bad)


def test_unstamped_frames_still_read():
    """Back-compat: a pre-integrity (unflagged) frame reads exactly as
    before even with verification armed."""
    payload = b"legacy bytes" * 10
    frame = ic.compress_frame(payload)  # no checksum_algo
    assert ic.decompress_frame(frame) == payload
    assert list(ic.iter_blob_frames(frame)) == [payload]


def test_block_trailer_detects_whole_frame_truncation():
    algo = integrity.frame_algo()
    frames, xor = [], 0
    for p in (b"aaa" * 40, b"bb" * 99):
        fr = ic.compress_frame(p, checksum_algo=algo)
        xor ^= struct.unpack("<BI", fr[-5:])[1]
        frames.append(fr)
    blob = b"".join(frames) + ic.block_trailer(2, xor, algo)
    assert list(ic.iter_blob_frames(blob)) == [b"aaa" * 40, b"bb" * 99]
    # drop a WHOLE frame: per-frame checksums can't see it, the
    # trailer's count/XOR must
    with pytest.raises(BlockCorruptionError, match="frame count"):
        list(ic.iter_blob_frames(frames[0] + ic.block_trailer(2, xor, algo)))


# -------------------------------------------------- LZ4 satellite

def test_lz4_frame_checksums_roundtrip_and_flip():
    payload = (b"Repetitive lz4 content for block compression. " * 300
               + bytes(range(256)))
    frame = ic.lz4_frame_compress(payload, checksums=True)
    assert ic.lz4_frame_decompress(frame) == payload
    # flipped bit inside a block -> typed block-checksum failure
    with pytest.raises(BlockCorruptionError):
        ic.lz4_frame_decompress(integrity.flip_byte(frame, len(frame) // 2))
    # flipped header descriptor -> HC byte failure
    with pytest.raises((BlockCorruptionError, ValueError)):
        ic.lz4_frame_decompress(integrity.flip_byte(frame, 4))
    # checksum-free frames still decode (and cannot detect)
    plain = ic.lz4_frame_compress(payload)
    assert ic.lz4_frame_decompress(plain) == payload


def test_lz4_content_checksum_catches_stored_block_swap():
    """Differential: corrupt a STORED (uncompressed) block in a way
    the framing can't see — only the content checksum can."""
    payload = bytes(np.random.RandomState(3).randint(0, 256, 4096,
                                                     dtype=np.uint8))
    frame = bytearray(ic.lz4_frame_compress(payload, checksums=True))
    # stored block starts after magic+FLG+BD+HC+blocksize = 4+1+1+1+4
    frame[12] ^= 0x01
    with pytest.raises(BlockCorruptionError):
        ic.lz4_frame_decompress(bytes(frame))


# ----------------------------------------------- spill frame integrity

def test_spill_frame_corruption_detected(tmp_path):
    from blaze_tpu.runtime.memmgr import FileSpill, HostMemSpill

    for sp in (FileSpill("zlib", dir=str(tmp_path)), HostMemSpill("zlib")):
        sp.write_frame(b"good" * 100)
        sp.corrupt_next_frame()
        sp.write_frame(b"evil" * 100)
        sp.complete()
        assert sp.read_frame() == b"good" * 100
        with pytest.raises(BlockCorruptionError):
            sp.read_frame()
        sp.release()


def test_spill_corruption_classified_retry():
    assert classify(BlockCorruptionError("spill.read")) == RETRY
    assert classify(DiskExhaustedError("spill.write")) == RETRY


# ------------------------------------------------- disk-pressure ladder

def test_file_spill_enospc_migrates_to_host_ram(tmp_path):
    from blaze_tpu.runtime.memmgr import FileSpill

    sp = FileSpill("zlib", dir=str(tmp_path))
    sp.write_frame(b"on-disk" * 50)
    path = sp.path
    real_write = sp._f.write
    fails = {"n": 1}

    def flaky_write(b):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError(errno.ENOSPC, "disk full")
        return real_write(b)

    before = dispatch.counters().get("disk_pressure_recoveries", 0)
    sp._f.write = flaky_write
    sp.write_frame(b"in-ram" * 50)  # ladder: reclaim -> migrate to RAM
    assert sp._mem is not None
    assert not os.path.exists(path)  # file tier released on migration
    sp.write_frame(b"more" * 10)
    sp.complete()
    assert sp.read_frame() == b"on-disk" * 50
    assert sp.read_frame() == b"in-ram" * 50
    assert sp.read_frame() == b"more" * 10
    assert sp.read_frame() is None
    sp.release()
    assert dispatch.counters().get("disk_pressure_recoveries", 0) > before


def test_try_new_spill_disk_ladder(monkeypatch, tmp_path):
    import blaze_tpu.runtime.memmgr as memmgr_mod

    class FakeMgr:
        total = 100

        def total_used(self):
            return 60  # past total//2: file tier selected

    monkeypatch.setattr(memmgr_mod.MemManager, "get",
                        classmethod(lambda cls: FakeMgr()))

    def no_disk(*a, **k):
        raise OSError(errno.ENOSPC, "disk full")

    monkeypatch.setattr(memmgr_mod.tempfile, "mkstemp", no_disk)
    # headroom left -> in-memory eager fallback
    sp = memmgr_mod.try_new_spill("zlib")
    assert isinstance(sp, memmgr_mod.HostMemSpill)

    FakeMgr.total_used = lambda self: 100  # quota exhausted
    with pytest.raises(DiskExhaustedError):
        memmgr_mod.try_new_spill("zlib")


def test_drain_victims_reselects_on_disk_pressure():
    from blaze_tpu.runtime.memmgr import MemConsumer, MemManager

    mgr = MemManager(1000, watermark=0.5)

    class Victim(MemConsumer):
        def __init__(self, name, fail):
            super().__init__()
            self.name = name
            self.fail = fail
            self.spilled = False

        def spill(self):
            if self.fail:
                raise faults.InjectedDiskFull("spill.write", 1)
            self.spilled = True
            freed = self._mem_used
            self.set_mem_used_no_trigger(0)
            return freed

    bad = Victim("bad", fail=True)
    good = Victim("good", fail=False)
    mgr.register_consumer(bad)
    mgr.register_consumer(good)
    bad._mem_used = 600
    good._mem_used = 400
    before = dispatch.counters().get("disk_pressure_recoveries", 0)
    mgr._maybe_spill()  # bad victim's disk failure must not propagate
    assert good.spilled
    assert dispatch.counters().get("disk_pressure_recoveries", 0) > before


def test_shuffle_write_enospc_recovers_in_place():
    """The ``@enospc`` injection at the commit probe: reclaim + retry
    commits identically, counting a disk-pressure recovery."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_rows(sess, plan_json)
    _inject("shuffle.write@1@enospc")
    m = MetricNode()
    got, _ = _scheduler_rows(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("disk_pressure_recoveries") >= 1
    # the in-place retry means no task retry was needed
    assert m.metrics.get("fetch_failures") == 0


# ------------------------------------------ corruption matrix: shuffle

def test_shuffle_block_corruption_detected_and_recovered():
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_rows(sess, plan_json)
    _inject("shuffle.write@1@corrupt")
    prev_trace = bool(conf.TRACE_ENABLE.get())
    conf.TRACE_ENABLE.set(True)
    trace.reset()
    try:
        from blaze_tpu.runtime import monitor

        with monitor.query_span("integrity_shuffle") as log_path:
            m = MetricNode()
            got, _ = _scheduler_rows(sess, plan_json, metrics=m)
    finally:
        conf.TRACE_ENABLE.set(prev_trace)
        trace.reset()
    assert got == baseline  # byte-identical after recovery
    assert m.metrics.get("corruption_detected") >= 1
    assert m.metrics.get("fetch_failures") >= 1
    assert m.metrics.get("map_stage_reruns") >= 1
    events = trace.read_event_log(log_path)
    injected = [e for e in events if e.get("type") == "fault_injected"
                and e.get("kind") == "corrupt"]
    detected = [e for e in events if e.get("type") == "block_corruption"]
    assert injected and detected
    from blaze_tpu.runtime import trace_report

    rec = trace_report.reconcile_faults(events)
    assert rec["reconciled"], rec["unpaired"]


def test_shuffle_corruption_twice_quarantines_and_regenerates():
    """A re-fetched block failing twice at the same path is renamed
    ``.corrupt`` (kept for forensics), its index dropped, and FULL
    regeneration recovers to identical results."""
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_rows(sess, plan_json)
    # corrupt map task 0's commit AND its first regeneration (probe
    # hits: t0=1, t1=2, t2=3, rerun-t0=4) -> path fails twice
    _inject("shuffle.write@1@corrupt,shuffle.write@4@corrupt")
    m = MetricNode()
    got, manager = _scheduler_rows(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("blocks_quarantined") >= 1
    quarantined = [f for f in os.listdir(manager.root)
                   if f.endswith(".corrupt")]
    assert quarantined, "forensic .corrupt file missing"
    # quarantined files survive invalidate (forensics) and never feed
    # the reduce barrier again
    sid = int(quarantined[0].split("_")[1])
    manager.invalidate(sid)
    assert [f for f in os.listdir(manager.root)
            if f.endswith(".corrupt")] == quarantined


# ---------------------------------------------- corruption matrix: spill

def test_spill_corruption_recovered_by_task_retry(monkeypatch):
    """Every staged batch is force-spilled, so the ``spill.write``
    corruption site deterministically has frames to flip; the corrupt
    frame surfaces at the commit drain as a typed error and the TASK
    RETRY rebuilds the repartitioner's state to identical results."""
    import blaze_tpu.parallel.shuffle as sh

    orig_insert = sh._insert_host

    def insert_and_spill(rep, schema, item):
        orig_insert(rep, schema, item)
        rep.spill()

    monkeypatch.setattr(sh, "_insert_host", insert_and_spill)
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_rows(sess, plan_json)
    _inject("spill.write@1@corrupt")
    m = MetricNode()
    got, _ = _scheduler_rows(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("corruption_detected") >= 1
    assert m.metrics.get("task_retries") >= 1


# ------------------------------------------------ corruption matrix: rss

def test_rss_push_corruption_detected_at_reduce():
    from blaze_tpu.exprs.ir import col
    from blaze_tpu.parallel.rss import LocalRssWriter, RssShuffleWriterExec

    schema = Schema([Field("k", DataType.int64()),
                     Field("v", DataType.int64())])
    n = 200
    src = MemoryScanExec(
        [[batch_from_pydict({"k": list(range(n)), "v": list(range(n))},
                            schema)]], schema)

    def push(tag):
        writer = LocalRssWriter()
        RESOURCES.put(f"rss_int_{tag}.0", writer)
        ex = RssShuffleWriterExec(src, HashPartitioning([col("k")], 2),
                                  f"rss_int_{tag}")
        list(ex.execute(0, TaskContext(0, 1)))
        return writer

    ref = push("ref")
    _inject("rss.push@1@corrupt")
    bad = push("bad")
    _inject("")
    # the corrupted push differs from the clean one ONLY in the flip
    assert sorted(ref.partitions) == sorted(bad.partitions)
    # reduce side: the verified read detects the flip as a typed fetch
    # failure naming the RSS resource
    corrupt_blocks = [b"".join(bad.partitions[p])
                      for p in sorted(bad.partitions)]
    RESOURCES.put("rss_read_int.0", corrupt_blocks)
    reader = IpcReaderExec(schema, "rss_read_int", 1)
    with pytest.raises(FetchFailedError):
        list(reader.execute(0, TaskContext(0, 1)))
    # clean pushes decode fine through the same path
    clean_blocks = [b"".join(ref.partitions[p])
                    for p in sorted(ref.partitions)]
    RESOURCES.put("rss_read_int.1", clean_blocks)
    reader2 = IpcReaderExec(schema, "rss_read_int", 2)
    rows = sum(b.num_rows for b in reader2.execute(1, TaskContext(1, 2)))
    assert rows == n


# ------------------------------------- corruption matrix: broadcast

def test_broadcast_corruption_regenerates_producing_stage():
    sess, data = make_session()
    dim_schema = Schema([
        Field("d_key", DataType.int64()),
        Field("d_name", DataType.string(16)),
    ])
    sess.register_table(
        "dim",
        {"d_key": list(range(10)), "d_name": [f"name{i}" for i in range(10)]},
        dim_schema,
    )
    fact = F.scan("lineitem", [F.attr("l_quantity", 1), F.attr("l_discount", 3)])
    dim = F.broadcast(F.scan("dim", [F.attr("d_key", 5), F.attr("d_name", 6)]))
    join = F.bhj([F.attr("l_discount", 3)], [F.attr("d_key", 5)],
                 "Inner", "right", fact, dim)
    pr = F.project([F.attr("l_quantity", 1), F.attr("d_name", 6)], join)
    plan_json = F.flatten(pr)
    baseline, _ = _scheduler_rows(sess, plan_json)
    assert len(baseline["l_quantity"]) == len(data["l_quantity"])
    _inject("broadcast.write@1@corrupt")
    m = MetricNode()
    got, _ = _scheduler_rows(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("corruption_detected") >= 1
    assert m.metrics.get("fetch_failures") >= 1
    # recovery REGENERATED the producing broadcast stage (re-reading
    # the driver's cached corrupt blob would never converge)
    assert m.metrics.get("map_stage_reruns") >= 1


def test_fetch_failed_broadcast_id_property():
    assert FetchFailedError("broadcast_7", 0).broadcast_id == 7
    assert FetchFailedError("broadcast_7", 0).shuffle_id is None
    assert FetchFailedError("shuffle_3", 0).broadcast_id is None


# --------------------------------- corruption matrix: worker result

@pytest.mark.slow
def test_worker_result_corruption_detected_and_retried(tmp_path):
    """Testenv tier: a worker whose COMMITTED result frames carry a
    flipped byte is caught by the driver's verification and re-run
    with a fresh attempt; the final frames verify and match."""
    import base64

    from blaze_tpu.ops import ParquetScanExec, ParquetSinkExec
    from blaze_tpu.runtime.scheduler import build_task
    from blaze_tpu.runtime.worker import (
        read_result_frames, run_worker_with_retry,
    )

    schema = Schema([Field("x", DataType.int64())])
    src = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(100))}, schema)]], schema)
    pq = str(tmp_path / "in.parquet")
    sink = ParquetSinkExec(src, pq)
    for _ in sink.execute(0, TaskContext(0, 1)):
        pass
    pq = sink.written_files[0] if sink.written_files else pq
    plan = ParquetScanExec([[pq]], schema)
    stages, manager = split_stages(
        plan, LocalShuffleManager(str(tmp_path / "sh")))
    _, td = build_task(stages[-1], manager, 0)
    out = str(tmp_path / "r.frames")
    spec = {
        "task_def": base64.b64encode(td).decode(),
        "partition": 0,
        "shuffle_root": manager.root,
        "readers": [],
        "output": out,
    }
    env = {
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        # flip a committed result byte on the FIRST attempt only
        "BLAZE_FAULTS_SPEC": "worker.result@1@corrupt@a0",
        "BLAZE_TASK_RETRYBACKOFF": "0",
    }
    winning = run_worker_with_retry(spec, str(tmp_path), "t0",
                                    max_attempts=3, env=env)
    assert winning == 1  # attempt 0's corrupt output was rejected
    vals = []
    for b in read_result_frames(out, schema):
        vals.extend(int(v) for v in
                    np.asarray(b.columns[0].data)[: b.num_rows])
    assert vals == list(range(100))


# ------------------------------------------------ torn-JSONL tolerance

def test_read_events_tolerates_torn_final_line(tmp_path, caplog):
    import json as _json
    import logging

    p = str(tmp_path / "log.jsonl")
    with open(p, "w") as f:
        f.write(_json.dumps({"ts": 1.0, "type": "query_start",
                             "query_id": "q"}) + "\n")
        f.write('{"ts": 2.0, "type": "query_en')  # crash mid-append
    with caplog.at_level(logging.WARNING):
        events = trace.read_events(p)
    assert [e["type"] for e in events] == ["query_start"]
    assert any("torn" in r.message for r in caplog.records)


def test_read_history_tolerates_torn_lines(tmp_path, caplog, monkeypatch):
    import json as _json
    import logging

    from blaze_tpu.runtime import monitor

    hist = tmp_path / "hist"
    hist.mkdir()
    good = {"key": "q1", "status": "done"}
    with open(hist / "history-1.jsonl", "w") as f:
        f.write(_json.dumps(good) + "\n")
        f.write('{"key": "q2", "sta')  # torn final line
    # an ORPHAN segment with a torn MIDDLE line: everything after it
    # must still be read (the old reader stopped at the first bad line)
    with open(hist / "history-0.jsonl.seg1", "w") as f:
        f.write(_json.dumps({"key": "q0"}) + "\n")
        f.write('{"torn' + "\n")
        f.write(_json.dumps({"key": "q3"}) + "\n")
    conf.MONITOR_HISTORY_DIR.set(str(hist))
    monitor.reset()
    try:
        with caplog.at_level(logging.WARNING):
            out = monitor.read_history()
    finally:
        conf.MONITOR_HISTORY_DIR.set("")
        monitor.reset()
    keys = {e.get("key") for e in out}
    assert {"q1", "q0", "q3"} <= keys
    assert any("torn" in r.message for r in caplog.records)


# ------------------------------------------------- orphan sweep

def test_orphan_sweep_on_startup(tmp_path):
    root = tmp_path / "shuffle"
    root.mkdir()
    stale = root / "shuffle_0_0.data.inprogress.a0"
    stale.write_bytes(b"dead run debris")
    fresh = root / "shuffle_0_1.data.inprogress.a0"
    fresh.write_bytes(b"live attempt")
    committed = root / "shuffle_0_2.data"
    committed.write_bytes(b"committed")
    quarantined = root / "shuffle_0_3.data.corrupt"
    quarantined.write_bytes(b"forensics")
    old = time.time() - 7200
    os.utime(stale, (old, old))
    os.utime(quarantined, (old, old))
    mgr = LocalShuffleManager(str(root))  # sweep runs on re-open
    names = set(os.listdir(mgr.root))
    assert stale.name not in names          # dead debris reclaimed
    assert fresh.name in names              # age gate protects live temps
    assert committed.name in names          # committed outputs untouched
    assert quarantined.name in names        # forensics kept


def test_sweep_stale_spills_age_gated(tmp_path, monkeypatch):
    monkeypatch.setattr(diskmgr.tempfile, "gettempdir",
                        lambda: str(tmp_path))
    stale = tmp_path / "blaze_spill_dead"
    stale.write_bytes(b"x" * 128)
    old = time.time() - 7200
    os.utime(stale, (old, old))
    fresh = tmp_path / "blaze_spill_live"
    fresh.write_bytes(b"y")
    removed = diskmgr.sweep_stale_spills(3600)
    assert removed == 1
    assert not stale.exists() and fresh.exists()


# ------------------------------------------------ quarantine unit

def test_quarantine_renames_and_drops_index(tmp_path):
    data = tmp_path / "shuffle_5_0.data"
    index = tmp_path / "shuffle_5_0.index"
    data.write_bytes(b"bad bytes")
    index.write_bytes(b"\x00" * 16)
    assert integrity.note_corruption(str(data)) == 1
    assert integrity.note_corruption(str(data)) == 2
    q = integrity.quarantine(str(data))
    assert q == str(data) + ".corrupt"
    assert os.path.exists(q) and not data.exists() and not index.exists()
    # counters reset for the path: a regenerated file starts clean
    assert integrity.note_corruption(str(data)) == 1
