"""Real-world ORC decode: files written by pyarrow's ORC writer (ORC
C++ — the same library Spark uses): RLEv2 integers, DIRECT_V2 strings,
compressed streams (zlib/snappy/lz4/zstd), PRESENT streams, row-index
streams to skip.

≙ reference orc_exec.rs:53-285 (orc-rust handles these natively;
round-1 VERDICT item #7 flagged our RLEv1/uncompressed-only subset).
"""

import datetime
import decimal

import numpy as np
import pyarrow as pa
import pytest
from pyarrow import orc as paorc

from blaze_tpu.batch import batch_to_pydict, concat_batches
from blaze_tpu.exprs import col, lit
from blaze_tpu.ops.orc_scan import OrcScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

N = 400


def _table():
    rng = np.random.RandomState(23)
    ints = rng.randint(-1000, 1000, N)
    return pa.table(
        {
            "i32": pa.array(
                [None if i % 7 == 0 else int(ints[i]) for i in range(N)], pa.int32()
            ),
            "i64": pa.array([int(x) * 1_000_000_007 for x in ints], pa.int64()),
            "f64": pa.array(
                [None if i % 11 == 0 else float(ints[i]) / 3 for i in range(N)],
                pa.float64(),
            ),
            "s": pa.array(
                [None if i % 5 == 0 else f"v_{ints[i] % 41}" for i in range(N)],
                pa.string(),
            ),
            "b": pa.array([bool(ints[i] % 2) for i in range(N)], pa.bool_()),
            "d": pa.array(
                [datetime.date(2021, 6, 1) + datetime.timedelta(days=int(x) % 200) for x in ints],
                pa.date32(),
            ),
        }
    )


SCHEMA = Schema(
    [
        Field("i32", DataType.int32()),
        Field("i64", DataType.int64()),
        Field("f64", DataType.float64()),
        Field("s", DataType.string(16)),
        Field("b", DataType.bool_()),
        Field("d", DataType.date32()),
    ]
)


def _expected(table):
    d = table.to_pydict()
    exp = dict(d)
    exp["d"] = [None if v is None else (v - datetime.date(1970, 1, 1)).days for v in d["d"]]
    return exp


def _read_ours(path, schema=SCHEMA, predicate=None):
    scan = OrcScanExec([[str(path)]], schema, predicate)
    out = list(scan.execute(0, TaskContext(0, 1)))
    return (
        batch_to_pydict(concat_batches(out)) if out else {f.name: [] for f in schema.fields}
    ), scan


@pytest.mark.parametrize("codec", ["uncompressed", "zlib", "snappy", "lz4", "zstd"])
def test_pyarrow_orc_roundtrip(tmp_path, codec):
    table = _table()
    path = tmp_path / f"t_{codec}.orc"
    paorc.write_table(table, path, compression=codec)
    got, _ = _read_ours(path)
    exp = _expected(table)
    for k, want in exp.items():
        g = got[k]
        if k == "f64":
            for a, b in zip(g, want):
                assert (a is None) == (b is None) and (a is None or abs(a - b) < 1e-9), k
        else:
            assert g == want, f"column {k}"


def test_multiple_stripes_and_pruning(tmp_path):
    # sorted + incompressible noise in a second column defeats the
    # writer's memory-estimate batching so multiple stripes are flushed
    n = 400_000
    rng = np.random.RandomState(1)
    noise = rng.randint(-(2**60), 2**60, n)
    path = tmp_path / "stripes.orc"
    w = paorc.ORCWriter(path, compression="zlib", stripe_size=1024 * 1024)
    w.write(pa.table({"x": pa.array(list(range(n)), pa.int64()),
                      "pad": pa.array(noise, pa.int64())}))
    w.close()
    from blaze_tpu.io import orc as orc_io

    assert len(orc_io.read_metadata(str(path)).stripes) >= 2
    schema = Schema([Field("x", DataType.int64())])
    got, scan = _read_ours(path, schema)
    assert got["x"] == list(range(n))
    # pruned read: only stripes whose max >= threshold survive
    threshold = n - 1000
    got2, scan2 = _read_ours(path, schema, col("x") >= lit(threshold))
    assert set(range(threshold, n)).issubset(set(got2["x"]))
    assert len(got2["x"]) < n
    assert scan2.metrics.get("pruned_stripes") >= 1


def test_rlev2_patterns(tmp_path):
    """Exercise RLEv2 sub-encodings: short-repeat (constants), delta
    (monotonic), direct (random), patched base (outliers)."""
    n = 5000
    rng = np.random.RandomState(5)
    outliers = rng.randint(0, 1000, n).astype(np.int64)
    outliers[::501] = 2**45  # forces patched base
    table = pa.table(
        {
            "const": pa.array([7] * n, pa.int64()),
            "mono": pa.array(list(range(n)), pa.int64()),
            "rand": pa.array(rng.randint(-(2**30), 2**30, n), pa.int64()),
            "patched": pa.array(outliers, pa.int64()),
            "neg_mono": pa.array(list(range(n, 0, -1)), pa.int64()),
        }
    )
    path = tmp_path / "rlev2.orc"
    paorc.write_table(table, path, compression="zlib")
    schema = Schema([Field(nm, DataType.int64()) for nm in table.column_names])
    got, _ = _read_ours(path, schema)
    for nm in table.column_names:
        assert got[nm] == table[nm].to_pylist(), nm


def test_pyarrow_orc_list_column(tmp_path):
    """LIST<int64> columns written by pyarrow's ORC writer: LENGTH
    stream + child PRESENT/DATA decode, incl. null rows, empty lists
    and null elements."""
    import random

    rng = random.Random(7)
    rows = []
    for i in range(500):
        r = rng.random()
        if r < 0.1:
            rows.append(None)
        elif r < 0.25:
            rows.append([])
        else:
            rows.append([
                None if rng.random() < 0.15 else rng.randrange(-10**9, 10**9)
                for _ in range(rng.randrange(1, 7))
            ])
    ids = list(range(500))
    table = pa.table({
        "id": pa.array(ids, pa.int64()),
        "vals": pa.array(rows, pa.list_(pa.int64())),
    })
    path = str(tmp_path / "lists.orc")
    paorc.write_table(table, path)

    schema = Schema([
        Field("id", DataType.int64()),
        Field("vals", DataType.array(DataType.int64(), 8)),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=128)
    got_ids, got_vals = [], []
    for b in scan.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        got_ids.extend(d["id"])
        got_vals.extend(d["vals"])
    assert got_ids == ids
    assert got_vals == rows
