"""Real-world ORC decode: files written by pyarrow's ORC writer (ORC
C++ — the same library Spark uses): RLEv2 integers, DIRECT_V2 strings,
compressed streams (zlib/snappy/lz4/zstd), PRESENT streams, row-index
streams to skip.

≙ reference orc_exec.rs:53-285 (orc-rust handles these natively;
round-1 VERDICT item #7 flagged our RLEv1/uncompressed-only subset).
"""

import datetime
import decimal

import numpy as np
import pyarrow as pa
import pytest
from pyarrow import orc as paorc

from blaze_tpu.batch import batch_to_pydict, concat_batches
from blaze_tpu.exprs import col, lit
from blaze_tpu.ops.orc_scan import OrcScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

N = 400


def _table():
    rng = np.random.RandomState(23)
    ints = rng.randint(-1000, 1000, N)
    return pa.table(
        {
            "i32": pa.array(
                [None if i % 7 == 0 else int(ints[i]) for i in range(N)], pa.int32()
            ),
            "i64": pa.array([int(x) * 1_000_000_007 for x in ints], pa.int64()),
            "f64": pa.array(
                [None if i % 11 == 0 else float(ints[i]) / 3 for i in range(N)],
                pa.float64(),
            ),
            "s": pa.array(
                [None if i % 5 == 0 else f"v_{ints[i] % 41}" for i in range(N)],
                pa.string(),
            ),
            "b": pa.array([bool(ints[i] % 2) for i in range(N)], pa.bool_()),
            "d": pa.array(
                [datetime.date(2021, 6, 1) + datetime.timedelta(days=int(x) % 200) for x in ints],
                pa.date32(),
            ),
        }
    )


SCHEMA = Schema(
    [
        Field("i32", DataType.int32()),
        Field("i64", DataType.int64()),
        Field("f64", DataType.float64()),
        Field("s", DataType.string(16)),
        Field("b", DataType.bool_()),
        Field("d", DataType.date32()),
    ]
)


def _expected(table):
    d = table.to_pydict()
    exp = dict(d)
    exp["d"] = [None if v is None else (v - datetime.date(1970, 1, 1)).days for v in d["d"]]
    return exp


def _read_ours(path, schema=SCHEMA, predicate=None):
    scan = OrcScanExec([[str(path)]], schema, predicate)
    out = list(scan.execute(0, TaskContext(0, 1)))
    return (
        batch_to_pydict(concat_batches(out)) if out else {f.name: [] for f in schema.fields}
    ), scan


@pytest.mark.parametrize("codec", ["uncompressed", "zlib", "snappy", "lz4", "zstd"])
def test_pyarrow_orc_roundtrip(tmp_path, codec):
    table = _table()
    path = tmp_path / f"t_{codec}.orc"
    paorc.write_table(table, path, compression=codec)
    got, _ = _read_ours(path)
    exp = _expected(table)
    for k, want in exp.items():
        g = got[k]
        if k == "f64":
            for a, b in zip(g, want):
                assert (a is None) == (b is None) and (a is None or abs(a - b) < 1e-9), k
        else:
            assert g == want, f"column {k}"


def test_multiple_stripes_and_pruning(tmp_path):
    # sorted + incompressible noise in a second column defeats the
    # writer's memory-estimate batching so multiple stripes are flushed
    n = 400_000
    rng = np.random.RandomState(1)
    noise = rng.randint(-(2**60), 2**60, n)
    path = tmp_path / "stripes.orc"
    w = paorc.ORCWriter(path, compression="zlib", stripe_size=1024 * 1024)
    w.write(pa.table({"x": pa.array(list(range(n)), pa.int64()),
                      "pad": pa.array(noise, pa.int64())}))
    w.close()
    from blaze_tpu.io import orc as orc_io

    assert len(orc_io.read_metadata(str(path)).stripes) >= 2
    schema = Schema([Field("x", DataType.int64())])
    got, scan = _read_ours(path, schema)
    assert got["x"] == list(range(n))
    # pruned read: only stripes whose max >= threshold survive
    threshold = n - 1000
    got2, scan2 = _read_ours(path, schema, col("x") >= lit(threshold))
    assert set(range(threshold, n)).issubset(set(got2["x"]))
    assert len(got2["x"]) < n
    assert scan2.metrics.get("pruned_stripes") >= 1


def test_rlev2_patterns(tmp_path):
    """Exercise RLEv2 sub-encodings: short-repeat (constants), delta
    (monotonic), direct (random), patched base (outliers)."""
    n = 5000
    rng = np.random.RandomState(5)
    outliers = rng.randint(0, 1000, n).astype(np.int64)
    outliers[::501] = 2**45  # forces patched base
    table = pa.table(
        {
            "const": pa.array([7] * n, pa.int64()),
            "mono": pa.array(list(range(n)), pa.int64()),
            "rand": pa.array(rng.randint(-(2**30), 2**30, n), pa.int64()),
            "patched": pa.array(outliers, pa.int64()),
            "neg_mono": pa.array(list(range(n, 0, -1)), pa.int64()),
        }
    )
    path = tmp_path / "rlev2.orc"
    paorc.write_table(table, path, compression="zlib")
    schema = Schema([Field(nm, DataType.int64()) for nm in table.column_names])
    got, _ = _read_ours(path, schema)
    for nm in table.column_names:
        assert got[nm] == table[nm].to_pylist(), nm


def test_pyarrow_orc_list_column(tmp_path):
    """LIST<int64> columns written by pyarrow's ORC writer: LENGTH
    stream + child PRESENT/DATA decode, incl. null rows, empty lists
    and null elements."""
    import random

    rng = random.Random(7)
    rows = []
    for i in range(500):
        r = rng.random()
        if r < 0.1:
            rows.append(None)
        elif r < 0.25:
            rows.append([])
        else:
            rows.append([
                None if rng.random() < 0.15 else rng.randrange(-10**9, 10**9)
                for _ in range(rng.randrange(1, 7))
            ])
    ids = list(range(500))
    table = pa.table({
        "id": pa.array(ids, pa.int64()),
        "vals": pa.array(rows, pa.list_(pa.int64())),
    })
    path = str(tmp_path / "lists.orc")
    paorc.write_table(table, path)

    schema = Schema([
        Field("id", DataType.int64()),
        Field("vals", DataType.array(DataType.int64(), 8)),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=128)
    got_ids, got_vals = [], []
    for b in scan.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        got_ids.extend(d["id"])
        got_vals.extend(d["vals"])
    assert got_ids == ids
    assert got_vals == rows


def test_pyarrow_orc_map_column(tmp_path):
    """MAP<string,int64> columns written by pyarrow: LENGTH at the map
    column, recursive key/value decode, incl. null and empty maps."""
    import random

    rng = random.Random(11)
    rows = []
    for i in range(300):
        r = rng.random()
        if r < 0.1:
            rows.append(None)
        elif r < 0.2:
            rows.append({})
        else:
            rows.append({f"k{j}": rng.randrange(-1000, 1000)
                         for j in range(rng.randrange(1, 5))})
    table = pa.table({
        "id": pa.array(list(range(300)), pa.int64()),
        "m": pa.array(
            [None if r is None else list(r.items()) for r in rows],
            pa.map_(pa.string(), pa.int64())),
    })
    path = str(tmp_path / "maps.orc")
    paorc.write_table(table, path)
    schema = Schema([
        Field("id", DataType.int64()),
        Field("m", DataType.map(DataType.string(8), DataType.int64(), 8)),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=128)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["id"] == list(range(300))
    assert d["m"] == rows


def test_pyarrow_orc_struct_column(tmp_path):
    """STRUCT<a:int64, s:string, d:decimal(7,2)> columns: per-child
    PRESENT alignment with the parent validity."""
    import random

    rng = random.Random(13)
    rows = []
    for i in range(300):
        if rng.random() < 0.12:
            rows.append(None)
        else:
            rows.append({
                "a": None if rng.random() < 0.2 else rng.randrange(0, 999),
                "s": None if rng.random() < 0.2 else f"s{rng.randrange(30)}",
                "d": None if rng.random() < 0.2 else decimal.Decimal(
                    rng.randrange(-99999, 99999)) / 100,
            })
    st_type = pa.struct([("a", pa.int64()), ("s", pa.string()),
                         ("d", pa.decimal128(7, 2))])
    table = pa.table({
        "id": pa.array(list(range(300)), pa.int64()),
        "st": pa.array(rows, st_type),
    })
    path = str(tmp_path / "structs.orc")
    paorc.write_table(table, path)
    schema = Schema([
        Field("id", DataType.int64()),
        Field("st", DataType.struct([
            Field("a", DataType.int64()),
            Field("s", DataType.string(8)),
            Field("d", DataType.decimal(7, 2)),
        ])),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=100)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["id"] == list(range(300))
    for g, e in zip(d["st"], rows):
        if e is None:
            assert g is None
            continue
        assert g["a"] == e["a"] and g["s"] == e["s"]
        if e["d"] is None:
            assert g["d"] is None
        else:  # decimals come back unscaled
            assert g["d"] == int(e["d"] * 100)


def test_pyarrow_orc_nested_lists(tmp_path):
    """LIST<LIST<int64>> and LIST<string> columns through the recursive
    compound decode path."""
    import random

    rng = random.Random(17)
    ll_rows, ls_rows = [], []
    for i in range(200):
        ll_rows.append(None if rng.random() < 0.1 else [
            None if rng.random() < 0.1 else
            [rng.randrange(100) for _ in range(rng.randrange(0, 4))]
            for _ in range(rng.randrange(0, 4))
        ])
        ls_rows.append(None if rng.random() < 0.1 else [
            None if rng.random() < 0.15 else f"w{rng.randrange(20)}"
            for _ in range(rng.randrange(0, 5))
        ])
    table = pa.table({
        "ll": pa.array(ll_rows, pa.list_(pa.list_(pa.int64()))),
        "ls": pa.array(ls_rows, pa.list_(pa.string())),
    })
    path = str(tmp_path / "nested.orc")
    paorc.write_table(table, path)
    schema = Schema([
        Field("ll", DataType.array(DataType.array(DataType.int64(), 8), 8)),
        Field("ls", DataType.array(DataType.string(8), 8)),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=64)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["ll"] == ll_rows
    assert d["ls"] == ls_rows


def test_writer_list_column_roundtrip(tmp_path):
    """Our writer's LIST<int32> columns read back by BOTH our reader
    and pyarrow (wire-compatibility both directions)."""
    from blaze_tpu.io.orc import write_orc

    rng = np.random.RandomState(3)
    n, m = 500, 6
    validity = rng.rand(n) > 0.1
    lengths = np.where(validity, rng.randint(0, m + 1, n), 0).astype(np.int32)
    edata = rng.randint(-1000, 1000, (n, m)).astype(np.int32)
    evalid = rng.rand(n, m) > 0.15
    schema = Schema([
        Field("id", DataType.int64()),
        Field("vals", DataType.array(DataType.int32(), m)),
    ])
    path = str(tmp_path / "wlists.orc")
    write_orc(path, schema, {
        "id": (np.arange(n, dtype=np.int64), None, None),
        "vals": (None, validity, lengths, (edata, evalid)),
    }, stripe_rows=200)

    expected = [
        None if not validity[i] else [
            int(edata[i, j]) if evalid[i, j] else None
            for j in range(int(lengths[i]))
        ]
        for i in range(n)
    ]
    scan = OrcScanExec([[path]], schema, batch_rows=128)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["id"] == list(range(n))
    assert d["vals"] == expected

    t = paorc.read_table(path)
    pv = t.column("vals").to_pylist()
    assert pv == expected


def test_writer_compound_columns_roundtrip(tmp_path):
    """MAP/STRUCT/nested-LIST/list-of-string columns written as python
    value lists (the reader's compound-path shape) read back by BOTH
    our reader and pyarrow, nulls at every nesting level."""
    import decimal

    from blaze_tpu.io.orc import write_orc

    rng = np.random.RandomState(11)
    n = 400
    dec = DataType.decimal(10, 2)
    m_vals = [
        None if rng.rand() < 0.1 else {
            f"k{j}": (None if rng.rand() < 0.2 else int(rng.randint(-50, 50)))
            for j in range(rng.randint(0, 4))
        }
        for _ in range(n)
    ]
    st_vals = [
        None if rng.rand() < 0.1 else {
            "a": None if rng.rand() < 0.2 else int(rng.randint(0, 9)),
            "b": None if rng.rand() < 0.2 else f"s{rng.randint(30)}",
            "d": None if rng.rand() < 0.2 else decimal.Decimal(
                int(rng.randint(-9999, 9999))).scaleb(-2),
        }
        for _ in range(n)
    ]
    nl_vals = [
        None if rng.rand() < 0.1 else [
            None if rng.rand() < 0.15 else [
                None if rng.rand() < 0.2 else int(rng.randint(-99, 99))
                for _ in range(rng.randint(0, 4))
            ]
            for _ in range(rng.randint(0, 4))
        ]
        for _ in range(n)
    ]
    ls_vals = [
        None if rng.rand() < 0.1 else [
            None if rng.rand() < 0.15 else f"w{rng.randint(20)}"
            for _ in range(rng.randint(0, 5))
        ]
        for _ in range(n)
    ]
    schema = Schema([
        Field("m", DataType.map(DataType.string(8), DataType.int64(), 8)),
        Field("st", DataType.struct([
            Field("a", DataType.int64()), Field("b", DataType.string(8)),
            Field("d", dec)])),
        Field("nl", DataType.array(DataType.array(DataType.int64(), 8), 8)),
        Field("ls", DataType.array(DataType.string(8), 8)),
        Field("id", DataType.int64()),
    ])
    path = str(tmp_path / "wcompound.orc")
    write_orc(path, schema, {
        "m": m_vals, "st": st_vals, "nl": nl_vals, "ls": ls_vals,
        "id": (np.arange(n, dtype=np.int64), None, None),
    }, stripe_rows=150)

    # our scan layer (batch-level differential via pydict)
    scan = OrcScanExec([[path]], schema, batch_rows=128)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["m"] == m_vals
    assert d["nl"] == nl_vals
    assert d["ls"] == ls_vals
    assert d["id"] == list(range(n))
    # the engine's Column convention stores DECIMAL as unscaled ints
    st_unscaled = [
        None if v is None else dict(v, d=(
            None if v["d"] is None else int(v["d"].scaleb(2))))
        for v in st_vals
    ]
    assert d["st"] == st_unscaled

    # pyarrow reads the same file (wire compatibility)
    t = paorc.read_table(path)
    assert [None if v is None else dict(v) for v in
            t.column("m").to_pylist()] == m_vals
    assert t.column("nl").to_pylist() == nl_vals
    assert t.column("ls").to_pylist() == ls_vals
    pa_st = t.column("st").to_pylist()
    assert pa_st == st_vals


def test_writer_array_first_column_and_nested_has_null_stats(tmp_path):
    """(review findings) A schema whose FIRST column is ARRAY-of-
    primitive must not crash row counting, and compound stripe stats
    must report hasNull truthfully for external SARG readers."""
    from blaze_tpu.io.orc import (
        PbReader, _type_size, read_metadata, write_orc,
    )

    n, m = 10, 3
    lengths = np.full(n, 2, np.int32)
    edata = np.arange(n * m, dtype=np.int32).reshape(n, m)
    evalid = np.ones((n, m), bool)
    schema = Schema([
        Field("vals", DataType.array(DataType.int32(), m)),
        Field("nl", DataType.array(DataType.array(DataType.int64(), 4), 4)),
    ])
    nl_vals = [[[1, None]], None] * 5
    path = str(tmp_path / "arrfirst.orc")
    write_orc(path, schema, {
        "vals": (None, None, lengths, (edata, evalid)),
        "nl": nl_vals,
    })
    meta = read_metadata(path)
    assert meta.num_rows == n
    t = paorc.read_table(path)
    assert t.column("nl").to_pylist() == nl_vals

    # stripe stats (raw Metadata block): hasNull=true must be recorded
    # at the nl slots that contain Nones (external SARG readers prune
    # `IS NULL` stripes on this flag)
    nl_tid = 1 + _type_size(schema.fields[0].dtype)
    raw = open(path, "rb").read()
    ps_len = raw[-1]
    ps = raw[-1 - ps_len : -1]
    footer_len = md_len = 0
    for f_no, _, v in PbReader(ps).fields():
        if f_no == 1:
            footer_len = v
        elif f_no == 5:
            md_len = v
    md = raw[-1 - ps_len - footer_len - md_len : -1 - ps_len - footer_len]
    stripes_stats = []
    for f_no, _, v in PbReader(md).fields():
        if f_no == 1:
            msgs = [vv for f2, _, vv in PbReader(v).fields() if f2 == 1]
            stripes_stats.append(msgs)
    assert stripes_stats, "Metadata stripe stats missing"
    cols = stripes_stats[0]
    # root(0), vals(1), vals-child(2), nl(3=nl_tid), nl-mid, nl-leaf
    def has_null(msg):
        return any(f_no == 10 and val == 1 for f_no, _, val in PbReader(msg).fields())

    assert has_null(cols[nl_tid]), "nl top-level nulls not recorded"
    assert has_null(cols[nl_tid + 2]), "nl leaf nulls not recorded"
    assert not has_null(cols[1]), "vals has no nulls"


@pytest.mark.parametrize("codec", ["zlib", "zstd", "snappy", "lz4"])
def test_writer_compression_roundtrip(tmp_path, codec):
    """compression="zlib" (Spark's ORC default) / "zstd" / "snappy" /
    "lz4" (pure-python LZ77 encoders for the latter two): every region
    gets the chunked framing; our reader and pyarrow both read it and
    the file is materially smaller."""
    import os

    from blaze_tpu.io.orc import write_orc

    n = 4000
    k = np.arange(n, dtype=np.int64)
    m_vals = [None if i % 11 == 0 else {f"a{i % 3}": i} for i in range(n)]
    schema = Schema([
        Field("k", DataType.int64()),
        Field("m", DataType.map(DataType.string(8), DataType.int64(), 4)),
    ])
    cols = {"k": (k, None, None), "m": m_vals}
    pz = str(tmp_path / "z.orc")
    pn = str(tmp_path / "n.orc")
    write_orc(pz, schema, cols, stripe_rows=1500, compression=codec)
    write_orc(pn, schema, cols, stripe_rows=1500)
    # entropy coders (zlib/zstd) better byte-oriented LZ (snappy/lz4)
    shrink = 2 if codec in ("zlib", "zstd") else 3 / 2
    assert os.path.getsize(pz) < os.path.getsize(pn) / shrink

    scan = OrcScanExec([[pz]], schema, batch_rows=1024)
    got = concat_batches([b for b in scan.execute(0, TaskContext(0, 1))])
    d = batch_to_pydict(got)
    assert d["k"] == k.tolist()
    assert d["m"] == m_vals

    t = paorc.read_table(pz)
    assert t.column("k").to_pylist() == k.tolist()
    assert [None if v is None else dict(v) for v in
            t.column("m").to_pylist()] == m_vals


def test_writer_compound_unsupported_element_is_gated(tmp_path):
    """A still-unsupported element type (OPAQUE) inside a compound
    value raises, never writes junk."""
    from blaze_tpu.io.orc import write_orc

    schema = Schema([Field("x", DataType.array(
        DataType.struct([Field("o", DataType.opaque())]), 4))])
    with pytest.raises(NotImplementedError):
        write_orc(str(tmp_path / "bad.orc"), schema,
                  {"x": [[{"o": object()}]]})


def test_writer_compound_timestamp_roundtrip(tmp_path):
    """TIMESTAMP inside LIST and STRUCT values (int64 unix-µs lane):
    our writer -> our reader AND pyarrow, nulls at every level,
    pre-2015-epoch + sub-second-fraction values included."""
    import datetime as dt

    from blaze_tpu.io.orc import write_orc

    micros = [0, 1420070400_000_000, 1700000000_123_456,
              1420070399_000_000, 981_173_106_987_000,
              -1, -999_000, -1_500_000]
    lt_vals = [
        [micros[0], None, micros[2]],
        None,
        [],
        [micros[1], micros[3]],
        [micros[4], micros[5]],
        [micros[6], micros[7]],
    ]
    st_vals = [
        {"t": micros[2], "k": 7},
        None,
        {"t": None, "k": 8},
        {"t": micros[4], "k": None},
        {"t": micros[1], "k": 9},
        {"t": micros[5], "k": 10},
    ]
    schema = Schema([
        Field("lt", DataType.array(DataType.timestamp(), 4)),
        Field("st", DataType.struct([
            Field("t", DataType.timestamp()), Field("k", DataType.int64())])),
    ])
    # flat list-of-timestamp keeps the vectorized 4-tuple writer shape
    n, m = len(lt_vals), 4
    lt_valid = np.array([v is not None for v in lt_vals], bool)
    lt_len = np.array([0 if v is None else len(v) for v in lt_vals], np.int32)
    edata = np.zeros((n, m), np.int64)
    evalid = np.zeros((n, m), bool)
    for i, v in enumerate(lt_vals):
        for j, e in enumerate(v or []):
            evalid[i, j] = e is not None
            edata[i, j] = 0 if e is None else e
    path = str(tmp_path / "wts.orc")
    write_orc(path, schema, {
        "lt": (None, lt_valid, lt_len, (edata, evalid)), "st": st_vals})

    scan = OrcScanExec([[path]], schema, batch_rows=4)
    d = batch_to_pydict(concat_batches(
        [b for b in scan.execute(0, TaskContext(0, 1))]))
    assert d["lt"] == lt_vals
    assert d["st"] == st_vals

    # pyarrow reads the same file (ORC C++ wire compatibility)
    def as_dt(m):
        return None if m is None else (
            dt.datetime(1970, 1, 1) + dt.timedelta(microseconds=m))

    t = paorc.read_table(path)
    got_lt = t.column("lt").to_pylist()
    exp_lt = [None if v is None else [as_dt(m) for m in v] for v in lt_vals]
    assert [None if v is None else [
        None if e is None else e.replace(tzinfo=None) for e in v]
        for v in got_lt] == exp_lt
    got_st = t.column("st").to_pylist()
    for g, want in zip(got_st, st_vals):
        assert (g is None) == (want is None)
        if want is not None:
            gt = g["t"] if g["t"] is None else g["t"].replace(tzinfo=None)
            assert gt == as_dt(want["t"]) and g["k"] == want["k"]


def test_pyarrow_compound_timestamp_differential(tmp_path):
    """Nested timestamps written by pyarrow's real ORC writer decode to
    the same microsecond values through our compound path."""
    lt_vals = [[1700000000_000_000, None], None, [],
               [1420070400_000_000, 981_173_106_987_654],
               [1500000000_500_000],
               [-1, -999_000, -1_500_000, -1_000_000]]
    table = pa.table({"lt": pa.array(
        lt_vals, pa.list_(pa.timestamp("us")))})
    path = str(tmp_path / "pa_nts.orc")
    paorc.write_table(table, path, compression="zlib")
    schema = Schema([Field("lt", DataType.array(DataType.timestamp(), 4))])
    scan = OrcScanExec([[path]], schema, batch_rows=4)
    d = batch_to_pydict(concat_batches(
        [b for b in scan.execute(0, TaskContext(0, 1))]))
    assert d["lt"] == lt_vals


def test_pyarrow_repeated_pre_epoch_timestamp_differential(tmp_path):
    """(ADVICE r5) >=3 consecutive identical pre-epoch fractional
    timestamps hit RLEv2 SHORT_REPEAT in the secondary (packed-nanos)
    stream, whose raw uint64 image of a negative int64 used to raise
    OverflowError on slice-assign in read_stripe.  Mixed distinct
    values alongside exercise the DELTA-base wrap too."""
    cases = [
        [-1_500_000] * 6,                                   # SHORT_REPEAT
        [-1_500_000, -2_500_000, -3_500_000, -1, -999_000,  # DELTA/DIRECT
         -1_500_000, -1_500_000, -1_500_000],
    ]
    for i, us_vals in enumerate(cases):
        table = pa.table({"ts": pa.array(us_vals, pa.timestamp("us"))})
        path = str(tmp_path / f"pa_preepoch_{i}.orc")
        paorc.write_table(table, path, compression="zlib")
        schema = Schema([Field("ts", DataType.timestamp())])
        scan = OrcScanExec([[path]], schema, batch_rows=4)
        d = batch_to_pydict(concat_batches(
            [b for b in scan.execute(0, TaskContext(0, 1))]))
        assert d["ts"] == us_vals


def test_pyarrow_direct_pre_epoch_timestamp_differential(tmp_path):
    """(ADVICE r5, last open item) A long run of DISTINCT pre-epoch
    fractional timestamps keeps the secondary (packed-nanos) stream in
    RLEv2 DIRECT, whose uint64->int64 wrap is now explicit through the
    shared _wrap_u64 helper instead of numpy's implicit slice-assign
    reinterpretation — this pins the vectorized wrap against pyarrow's
    real writer."""
    us_vals = [-1_500_000 - 7 * i - (i % 3) for i in range(64)]
    table = pa.table({"ts": pa.array(us_vals, pa.timestamp("us"))})
    path = str(tmp_path / "pa_preepoch_direct.orc")
    paorc.write_table(table, path, compression="zlib")
    schema = Schema([Field("ts", DataType.timestamp())])
    scan = OrcScanExec([[path]], schema, batch_rows=16)
    d = batch_to_pydict(concat_batches(
        [b for b in scan.execute(0, TaskContext(0, 1))]))
    assert d["ts"] == us_vals


def test_writer_compound_decimal_finer_than_scale_is_gated(tmp_path):
    """(review finding) Decimal('1.005') into DECIMAL(10,2) must raise,
    not silently truncate to 1.00 — the writer mirrors the reader's
    _rescale_decimals gate."""
    import decimal

    from blaze_tpu.io.orc import write_orc

    schema = Schema([Field("x", DataType.struct(
        [Field("d", DataType.decimal(10, 2))]))])
    with pytest.raises(NotImplementedError, match="declared scale"):
        write_orc(str(tmp_path / "bad2.orc"), schema,
                  {"x": [{"d": decimal.Decimal("1.005")}]})


def test_writer_flat_list_has_null_stats(tmp_path):
    """(review finding) ARRAY-of-primitive stripe stats report hasNull
    truthfully for both the list slot (null rows) and the child slot
    (null elements), and element counts are element-level."""
    from blaze_tpu.io.orc import PbReader, write_orc

    n, m = 6, 3
    validity = np.array([True, False, True, True, True, True])
    lengths = np.where(validity, 2, 0).astype(np.int32)
    edata = np.arange(n * m, dtype=np.int32).reshape(n, m)
    evalid = np.ones((n, m), bool)
    evalid[2, 1] = False  # one null element inside a live row
    schema = Schema([Field("vals", DataType.array(DataType.int32(), m))])
    path = str(tmp_path / "flstats.orc")
    write_orc(path, schema, {"vals": (None, validity, lengths, (edata, evalid))})

    raw = open(path, "rb").read()
    ps_len = raw[-1]
    ps = raw[-1 - ps_len : -1]
    footer_len = md_len = 0
    for f_no, _, v in PbReader(ps).fields():
        if f_no == 1:
            footer_len = v
        elif f_no == 5:
            md_len = v
    md = raw[-1 - ps_len - footer_len - md_len : -1 - ps_len - footer_len]
    cols = None
    for f_no, _, v in PbReader(md).fields():
        if f_no == 1:
            cols = [vv for f2, _, vv in PbReader(v).fields() if f2 == 1]
    assert cols is not None

    def stats(msg):
        nv = hn = 0
        for f_no, _, val in PbReader(msg).fields():
            if f_no == 1:
                nv = val
            elif f_no == 10:
                hn = val
        return nv, hn

    # slot 1 = list column: 5 live rows, one null row
    assert stats(cols[1]) == (5, 1)
    # slot 2 = element column: 5 rows x 2 elems - 1 null elem = 9, hasNull
    assert stats(cols[2]) == (9, 1)


def test_list_exceeding_max_elems_is_gated(tmp_path):
    """A file whose lists exceed the declared ARRAY cap must raise, not
    silently truncate (round-4 advisor, io/orc.py gate policy)."""
    table = pa.table({
        "vals": pa.array([[1, 2, 3, 4, 5, 6]], pa.list_(pa.int64())),
    })
    path = str(tmp_path / "long.orc")
    paorc.write_table(table, path)
    schema = Schema([Field("vals", DataType.array(DataType.int64(), 4))])
    scan = OrcScanExec([[path]], schema, batch_rows=16)
    with pytest.raises(NotImplementedError, match="max_elems"):
        list(scan.execute(0, TaskContext(0, 1)))


def test_decimal_rescale_helper():
    """Per-value SECONDARY scales rescale to the declared scale; a
    finer-than-declared scale is gated (round-4 advisor)."""
    from blaze_tpu.io.orc import _rescale_decimals

    vals = np.array([123, 45, 6], np.int64)
    assert _rescale_decimals(vals, np.array([2, 2, 2]), 2).tolist() == [123, 45, 6]
    assert _rescale_decimals(vals, np.array([2, 1, 0]), 2).tolist() == [123, 450, 600]
    with pytest.raises(NotImplementedError, match="scale"):
        _rescale_decimals(vals, np.array([3, 2, 2]), 2)


def test_filter_preserves_nested_children(tmp_path):
    """FilterExec row compaction must carry nested children through
    (compact_columns once rebuilt Columns without them)."""
    from blaze_tpu.exprs import col, lit
    from blaze_tpu.exprs.ir import GetMapValue, GetStructField
    from blaze_tpu.ops import FilterExec, ProjectExec

    rows = [{"a": i, "s": f"x{i % 3}"} for i in range(50)]
    maps = [{f"k{i % 4}": i} for i in range(50)]
    table = pa.table({
        "id": pa.array(list(range(50)), pa.int64()),
        "st": pa.array(rows, pa.struct([("a", pa.int64()), ("s", pa.string())])),
        "m": pa.array([list(r.items()) for r in maps],
                      pa.map_(pa.string(), pa.int64())),
    })
    path = str(tmp_path / "c.orc")
    paorc.write_table(table, path)
    schema = Schema([
        Field("id", DataType.int64()),
        Field("st", DataType.struct([Field("a", DataType.int64()),
                                     Field("s", DataType.string(8))])),
        Field("m", DataType.map(DataType.string(8), DataType.int64(), 8)),
    ])
    scan = OrcScanExec([[path]], schema, batch_rows=32)
    plan = ProjectExec(
        FilterExec(scan, col("id") >= lit(10, DataType.int64())),
        [col("id"), GetStructField(col("st"), "a").alias("sa"),
         GetMapValue(col("m"), "k2").alias("mv")],
    )
    out = {"id": [], "sa": [], "mv": []}
    for b in plan.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        for k in out:
            out[k].extend(d[k])
    assert out["id"] == list(range(10, 50))
    assert out["sa"] == list(range(10, 50))
    assert out["mv"] == [i if i % 4 == 2 else None for i in range(10, 50)]
