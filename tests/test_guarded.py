"""Guarded-by race detector (ISSUE 8): static lock-coverage pass
(analysis/guarded.py) + Eraser-style runtime lockset checker
(runtime/lockset.py).

1. **Seeded static negatives**: each guard.* rule catches a
   deliberately broken temp module, pinned by rule id + location.
2. **Both halves on ONE seed**: the same off-lock mutation is caught
   statically (guard.unlocked with the rule id) AND dynamically (a
   deterministic LocksetViolation from the armed checker driven by a
   second thread) — the acceptance criterion.
3. **Lockset semantics**: single-owner init exemption, lock-covered
   accesses stay quiet, violation suppression after first report,
   disarmed structural no-op.
4. **Deterministic two-thread interleavings** over the PR 7 seams the
   checker guards: speculation loser-rollback vs winner-commit
   (AttemptProgress.discard racing StageProgress.add_batch) and
   _AsyncInserter abort vs put — barrier-driven so the schedule is
   reproducible, each asserting the armed checker stays QUIET and the
   accounting is exact.
5. **--lint --json**: golden-pinned document keys.
"""

import importlib.util
import json
import threading

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.analysis import guarded, lint
from blaze_tpu.analysis import locks as alocks
from blaze_tpu.batch import batch_from_pydict
from blaze_tpu.runtime import lockset, monitor
from blaze_tpu.schema import DataType, Field, Schema


def _write_pkg(tmp_path, name, source):
    pkg = tmp_path / name
    pkg.mkdir()
    (pkg / "mod.py").write_text(source)
    return str(pkg)


@pytest.fixture
def armed_lockset():
    lockset.arm(True)
    try:
        yield
    finally:
        lockset.arm(False)


# ------------------------------------------- 1. seeded static negatives

SEED_UNLOCKED_CLASS = """\
from blaze_tpu.analysis.locks import make_lock
from blaze_tpu.runtime import lockset


class Counter:
    GUARDED_BY = {"count": "metrics.set"}

    def __init__(self):
        self._lock = make_lock("metrics.set")
        self.count = 0

    def safe_bump(self):
        with self._lock:
            lockset.check(self, "count")
            self.count += 1

    def helper_bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self.count += 1          # critical helper: called under the lock

    def racy_bump(self):
        lockset.check(self, "count")
        self.count += 1          # OFF-LOCK: guard.unlocked
"""


def test_seeded_unlocked_class_attribute(tmp_path):
    root = _write_pkg(tmp_path, "pkg_guard", SEED_UNLOCKED_CLASS)
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.unlocked"]
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.symbol == "Counter.racy_bump"
    assert "metrics.set" in f.message
    # location pins the mutation line, not the method header
    assert "self.count += 1" in SEED_UNLOCKED_CLASS.splitlines()[f.line - 1]


def test_seeded_unlocked_module_global(tmp_path):
    root = _write_pkg(tmp_path, "pkg_guard_mod", """\
from typing import Dict
from blaze_tpu.analysis.locks import make_lock

# type-ANNOTATED declaration spelling: must be honored, not silently
# skipped (review finding)
GUARDED_BY: Dict[str, str] = {"_TABLE": "kernel_cache.registry"}
GUARDED_REFS = ("_TABLE",)
_lock = make_lock("kernel_cache.registry")
_TABLE = {}

def safe_put(k, v):
    with _lock:
        _TABLE[k] = v

def racy_put(k, v):
    _TABLE[k] = v               # OFF-LOCK: guard.unlocked
""")
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.unlocked"]
    assert len(findings) == 1, findings
    assert findings[0].symbol == "racy_put"
    assert "kernel_cache.registry" in findings[0].message


def test_seeded_escape_of_guarded_ref(tmp_path):
    root = _write_pkg(tmp_path, "pkg_escape", """\
from blaze_tpu.analysis.locks import make_lock


class Registry:
    GUARDED_BY = {"entries": "monitor.registry",
                  "n": "monitor.registry"}
    GUARDED_REFS = ("entries",)

    def __init__(self):
        self._lock = make_lock("monitor.registry")
        self.entries = {}
        self.n = 0

    def snapshot_ok(self):
        with self._lock:
            return dict(self.entries)   # copy: fine

    def count_ok(self):
        with self._lock:
            return self.n               # immutable int, not in REFS

    def leak(self):
        with self._lock:
            return self.entries         # guard.escape

    def leak_tuple(self):
        with self._lock:
            return (self.n, self.entries)  # guard.escape via packing
""")
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.escape"]
    assert {f.symbol for f in findings} == {"Registry.leak",
                                            "Registry.leak_tuple"}, findings
    assert all("entries" in f.message for f in findings)


def test_seeded_lifecycle_asymmetry(tmp_path):
    root = _write_pkg(tmp_path, "pkg_life", """\
def leaky(mem, consumer, batches):
    mem.register_consumer(consumer)      # guard.lifecycle: no finally
    for b in batches:
        consumer.add(b)
    mem.unregister_consumer(consumer)    # happy path only

def sound(mem, consumer, batches):
    mem.register_consumer(consumer)
    try:
        for b in batches:
            consumer.add(b)
    finally:
        mem.unregister_consumer(consumer)
""")
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.lifecycle"]
    assert len(findings) == 1, findings
    assert findings[0].symbol == "leaky"
    assert "unregister_consumer" in findings[0].message


def test_seeded_bad_declaration(tmp_path):
    root = _write_pkg(tmp_path, "pkg_decl", """\
class A:
    GUARDED_BY = {"x": "not.a.real.lock"}

class B:
    GUARDED_BY = {"x": "conf.store"}
    GUARDED_REFS = ("y",)
""")
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.decl"]
    assert {f.symbol for f in findings} == {"A", "B"}, findings
    assert any("not.a.real.lock" in f.message for f in findings)
    assert any("GUARDED_REFS" in f.message for f in findings)


def test_real_package_guarded_clean():
    """The annotated codebase passes its own gate (modulo the pinned
    MemConsumer waiver — exactly what lint_package applies)."""
    raw = guarded.lint_guarded()
    waivers = lint.load_waivers()
    left = [f for f in raw if not lint._waived(f, waivers)]
    assert left == [], left
    # the waiver is LIVE (pins test_waiver_file_entries_still_needed)
    assert any(f.rule == "guard.unlocked"
               and f.path.endswith("runtime/memmgr.py") for f in raw)


# --------------------------- 2. both halves catch the same seeded race

def test_seeded_race_caught_by_both_halves(tmp_path, armed_lockset):
    """THE acceptance criterion: one seeded module whose guarded
    attribute is mutated off-lock — the static pass names the rule id
    and line, and DRIVING it from a second thread raises a
    deterministic LocksetViolation from the armed runtime checker."""
    root = _write_pkg(tmp_path, "pkg_both", SEED_UNLOCKED_CLASS)

    # static half: rule id + location
    findings = [f for f in guarded.lint_guarded(root)
                if f.rule == "guard.unlocked"]
    assert len(findings) == 1 and findings[0].symbol == "Counter.racy_bump"

    # dynamic half: import the SAME module and race it deterministically
    spec = importlib.util.spec_from_file_location(
        "pkg_both_mod", str(tmp_path / "pkg_both" / "mod.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    c = mod.Counter()
    first_done = threading.Event()
    errs = []

    def t1():
        try:
            c.safe_bump()        # thread 1 establishes the lockset {L}
        except BaseException as e:  # noqa: BLE001
            errs.append(e)
        first_done.set()

    t = threading.Thread(target=t1)
    t.start()
    t.join(5)
    assert first_done.is_set() and not errs
    # second thread (this one), OFF-lock: the intersection empties HERE
    with pytest.raises(lockset.LocksetViolation, match="Counter.count"):
        c.racy_bump()


# -------------------------------------------- 3. lockset semantics

def test_lockset_quiet_when_covered(armed_lockset):
    class Obj:
        pass

    lk = alocks.make_lock("metrics.set")
    o = Obj()
    errs = []

    def worker():
        try:
            for _ in range(50):
                with lk:
                    lockset.check(o, "x")
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert not errs
    assert lockset.counters()["checked_accesses"] >= 200


def test_lockset_single_owner_init_exempt(armed_lockset):
    """Unlocked single-thread construction never trips the checker —
    the Eraser exclusive phase."""
    class Obj:
        pass

    o = Obj()
    for _ in range(10):
        lockset.check(o, "x")    # same thread, no locks: exempt
    assert lockset.counters()["checked_accesses"] >= 10


def test_lockset_reports_once_per_variable(armed_lockset):
    class Obj:
        pass

    lk = alocks.make_lock("metrics.set")
    o = Obj()
    done = threading.Event()

    def t1():
        with lk:
            lockset.check(o, "x")
        done.set()

    threading.Thread(target=t1).start()
    assert done.wait(5)
    with pytest.raises(lockset.LocksetViolation):
        lockset.check(o, "x")
    # suppressed after the first report: chaos runs surface ONE failure
    lockset.check(o, "x")


def test_lockset_disarmed_is_structural_noop():
    lockset.arm(False)
    lockset.reset()

    class Obj:
        pass

    o = Obj()
    for _ in range(100):
        lockset.check(o, "x")
    assert lockset.counters() == {"checked_accesses": 0, "tracked": 0}


def test_conf_key_registered_and_refresh_path():
    assert "spark.blaze.verify.lockset" in conf.registered_conf_keys()
    prev = conf.VERIFY_LOCKSET.get()
    try:
        conf.VERIFY_LOCKSET.set(True)
        lockset.refresh()
        assert lockset.armed()
    finally:
        conf.VERIFY_LOCKSET.set(prev)
        lockset.refresh()
        assert lockset.armed() == bool(prev)


# ------------------- 4. deterministic two-thread interleaving tests

def _mk_batch(n=8):
    schema = Schema([Field("x", DataType.int64())])
    return batch_from_pydict({"x": list(range(n))}, schema)


@pytest.fixture
def armed_monitor():
    prev = conf.MONITOR_ENABLE.get()
    conf.MONITOR_ENABLE.set(True)
    monitor.reset()
    lockset.arm(True)
    alocks.arm(True)  # order assertion too: the seams must hold both
    try:
        yield
    finally:
        alocks.arm(False)
        lockset.arm(False)
        conf.MONITOR_ENABLE.set(prev)
        monitor.reset()


def test_interleaved_loser_rollback_vs_winner_commit(armed_monitor):
    """The speculation seam the checker guards: a losing attempt's
    AttemptProgress.discard racing the winner's add_batch/task_done on
    the SHARED StageProgress, schedule pinned by barriers.  The armed
    lockset + lock-order checkers stay quiet and the loser's delta is
    rolled back exactly."""
    b = _mk_batch(8)
    errs = []
    with monitor.query("t_interleave_spec"):
        sp = monitor.StageProgress(0, "map", 2)
        start = threading.Barrier(2, timeout=10)
        loser_fed = threading.Barrier(2, timeout=10)

        def winner():
            try:
                start.wait()
                sp.add_batch(b)
                loser_fed.wait()     # loser has added its batches now
                sp.add_batch(b)
                sp.task_done()
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        def loser():
            try:
                delta = monitor.AttemptProgress(sp)
                start.wait()
                delta.add_batch(b)
                delta.add_batch(b)
                loser_fed.wait()
                delta.discard()      # rollback races the winner's commit
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=winner), threading.Thread(target=loser)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert not errs, errs
        # exact accounting: only the winner's two batches survive
        assert (sp.rows, sp.batches, sp.tasks_done) == (16, 2, 1)
    assert lockset.counters()["checked_accesses"] > 0


def test_interleaved_async_inserter_abort_vs_put(armed_monitor,
                                                 monkeypatch):
    """The stager seam: abort() races queued put()s, schedule pinned
    exactly — the stager is GATED inside staging item A while the
    producer queues B and C, then abort lands, then the gate opens.
    Armed checkers stay quiet; exactly A reaches the repartitioner
    (B/C discarded by the abort) and every thread joins."""
    from blaze_tpu.parallel import shuffle as shuffle_mod
    from blaze_tpu.parallel.shuffle import ShuffleRepartitioner, _AsyncInserter
    from blaze_tpu.runtime.metrics import MetricsSet

    gate = threading.Event()
    first_staging = threading.Event()
    real_insert = shuffle_mod._insert_host
    staged = []

    def gated_insert(rep, schema, item):
        if not staged:
            first_staging.set()
            assert gate.wait(10)
        staged.append(item)
        real_insert(rep, schema, item)

    monkeypatch.setattr(shuffle_mod, "_insert_host", gated_insert)

    schema = Schema([Field("x", DataType.int64())])
    rep = ShuffleRepartitioner(schema, 1, MetricsSet())
    ins = _AsyncInserter(rep, schema, depth=2, metrics=MetricsSet())
    b = _mk_batch(4).to_host()
    item = (list(b.columns), np.array([4]), 4)
    errs = []
    queued = threading.Event()

    def producer():
        try:
            ins.put(item)            # A: stager picks it up, blocks
            assert first_staging.wait(10)
            ins.put(item)            # B, C: sit in the bounded queue
            ins.put(item)
            queued.set()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)
            queued.set()

    t = threading.Thread(target=producer)
    t.start()
    assert queued.wait(10)

    def aborter():
        ins.abort()                  # races the gated stager; must
        # discard B/C and join once the gate opens

    at = threading.Thread(target=aborter)
    at.start()
    gate.set()
    at.join(10)
    t.join(10)
    assert not t.is_alive() and not at.is_alive()
    assert not errs, errs
    assert not ins._thread.is_alive()
    # exactly A was staged; the queued B/C were discarded by the abort
    assert len(staged) == 1
    with rep._lock:
        assert sum(len(bl) for bl in rep._buffers) == 1
    assert lockset.counters()["checked_accesses"] > 0


# ------------------------------------------------ 5. --lint --json

def test_lint_json_doc_golden_keys(tmp_path):
    """The machine-readable findings document: golden-pinned key sets
    (rule/path/line/symbol/message/waived + summary), waived findings
    marked but present — what CI diffs between lint runs."""
    root = _write_pkg(tmp_path, "pkg_json", SEED_UNLOCKED_CLASS)
    found = guarded.lint_guarded(root)
    assert found
    # one unwaived + one waived entry, so both renderings are pinned
    pairs = [(f, False) for f in found] + [(found[0], True)]
    doc = lint.lint_json_doc(pairs, plans_verified=7)
    assert tuple(doc) == lint.LINT_JSON_TOP_KEYS
    for entry in doc["findings"]:
        assert tuple(entry) == lint.LINT_JSON_FINDING_KEYS
    assert tuple(doc["summary"]) == lint.LINT_JSON_SUMMARY_KEYS
    assert doc["summary"]["total"] == len(pairs)
    assert doc["summary"]["plans_verified"] == 7
    assert doc["summary"]["waived"] + doc["summary"]["unwaived"] \
        == doc["summary"]["total"]
    assert any(e["waived"] for e in doc["findings"])
    json.dumps(doc)  # the document is pure JSON
    # (the real package's document being clean modulo waivers is
    # test_lint_clean_on_head's job — lint_package is the same source)
