"""Static analysis & verification subsystem (blaze_tpu/analysis/).

1. **Lint gate**: the AST rules + conf-registry drift gates run over
   the REAL package and must be clean (``python -m blaze_tpu --lint``
   mirrors this and adds the full 250-plan corpus sweep).
2. **Seeded violations**: each lint rule class catches a deliberately
   broken temp module — trace purity, stray jax.jit, emit-under-lock,
   static lock order, conf drift.
3. **Plan verifier negatives**: hand-corrupted plans (dropped
   exchange, missing buffer bottom, schema-mismatched edge, lost
   writer schema, impure trace key, unsorted SMJ child) each produce
   the right rule id with the offending node path in the message.
4. **Plan verifier acceptance**: real TPC-H/TPC-DS plans verify clean
   fused AND unfused, and FusedStageExec trace keys are deterministic
   across two builds of the same plan.
5. **Lock framework**: hierarchy enforcement at construction, runtime
   inversion assertions, end-to-end scheduler run armed.
6. **Waiver pinning**: the waiver set can only shrink.
7. **_remove_by_identity**: the shared identity-removal helper and its
   duplicate-content regression (the PR 3 bug class).
"""

import json
import os

import pytest

from blaze_tpu import conf
from blaze_tpu.analysis import lint, locks, plan_verify
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.fusion import FusedStageExec, optimize_plan
from blaze_tpu.runtime.metrics import _remove_by_identity
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _empty_scans(schemas):
    return {n: MemoryScanExec([[], []], schemas[n]) for n in schemas}


def _write_pkg(tmp_path, name, source):
    pkg = tmp_path / name
    pkg.mkdir()
    p = pkg / "mod.py"
    p.write_text(source)
    return str(pkg)


# ------------------------------------------------- 1. the lint gate

def test_lint_clean_on_head():
    """THE gate: every AST rule + conf drift over the real package,
    waivers applied, must be clean — exactly what --lint asserts
    (minus the plan-corpus sweep, sampled in this module)."""
    findings = lint.lint_package()
    assert not findings, "\n".join(repr(f) for f in findings)


def test_lint_cli_smoke_subset():
    """The CLI plumbing end to end: the AST half of --lint, through
    the same entry the console uses (the full 250-plan sweep lives in
    the --lint CLI itself; the corpus sample below keeps tier-1
    fast)."""
    assert lint.lint_package(apply_waivers=True) == []
    # waivers actually FILTER something (the pinned exceptions exist)
    raw = lint.lint_package(apply_waivers=False)
    assert any(f.rule in ("purity.host-sync", "jit.uncached",
                          "lock.emit-under-lock") for f in raw)


# ------------------------------------------ 2. seeded rule violations

def test_seeded_trace_purity_violations(tmp_path):
    root = _write_pkg(tmp_path, "pkg_purity", """\
import time
import numpy as np

def _bad_body(cols, num_rows):
    n = int(cols[0].sum())          # device coercion
    host = np.asarray(cols[1])      # host pull
    t = time.perf_counter()         # wall clock baked into the trace
    return cols, n

def fine_host_helper(x):
    return int(x) + len(np.asarray(x))  # not a traced scope
""")
    rules = {f.rule for f in lint.lint_purity(root)}
    assert "purity.host-sync" in rules
    assert "purity.wall-clock" in rules
    # the non-traced helper contributed nothing
    assert all("fine_host_helper" not in f.symbol
               for f in lint.lint_purity(root))


def test_seeded_stray_jit(tmp_path):
    root = _write_pkg(tmp_path, "pkg_jit", """\
import jax

stray = jax.jit(lambda x: x + 1)   # module-level: bypasses the cache

def _build_good_kernel():
    @jax.jit
    def kernel(x):
        return x * 2
    return kernel

def registered():
    from blaze_tpu.runtime.kernel_cache import cached_kernel
    return cached_kernel(("k",), _build_good_kernel)
""")
    findings = lint.lint_uncached_jit(root)
    assert any(f.rule == "jit.uncached" and f.symbol == "<module>"
               for f in findings)
    # the registered builder's jit is NOT flagged
    assert all("_build_good_kernel" not in f.symbol for f in findings)


def test_seeded_emit_under_lock(tmp_path):
    root = _write_pkg(tmp_path, "pkg_emit", """\
import threading
from blaze_tpu.runtime import trace

_lock = threading.Lock()
_sink_lock = threading.Lock()

def bad():
    with _lock:
        trace.emit("spill", consumer="x", bytes=1)

def ok_sink():
    with _sink_lock:
        trace.record_kernel("k", 0, 0, 0)

def ok_outside():
    trace.emit("spill", consumer="x", bytes=1)
""")
    findings = lint.lint_emit_under_lock(root)
    assert any(f.rule == "lock.emit-under-lock" and f.symbol == "bad"
               for f in findings)
    assert all(f.symbol not in ("ok_sink", "ok_outside") for f in findings)


def test_seeded_static_lock_order(tmp_path):
    root = _write_pkg(tmp_path, "pkg_locks", """\
from blaze_tpu.analysis.locks import make_lock

_inner = make_lock("conf.store")
_outer = make_lock("monitor.registry")

def inverted():
    with _inner:
        with _outer:      # conf.store is INNERMOST: this inverts
            pass

def fine():
    with _outer:
        with _inner:
            pass
""")
    findings = locks.lint_lock_order(root)
    assert any(f.rule == "lock.static-order" for f in findings)
    assert all(f.line != 0 for f in findings)
    # only the inverted nesting is flagged
    assert len([f for f in findings if f.rule == "lock.static-order"]) == 1


def test_seeded_conf_drift(tmp_path):
    root = _write_pkg(tmp_path, "pkg_conf", """\
KNOB = "spark.blaze.notAKnob.definitelyUnregistered"
FAMILY_OK = "spark.blaze.enable.myop"
REAL_OK = "spark.blaze.batchSize"
""")
    findings = lint.lint_conf_registry(root)
    bad = [f for f in findings if f.rule == "conf.unregistered"]
    assert len(bad) == 1
    assert "notAKnob" in bad[0].symbol


def test_conf_registry_two_way_and_shape():
    """Registry ⊆ conf.py declarations and vice versa (the live gate
    --lint runs); dynamic prefix present; the new verify knobs are in."""
    reg = conf.load_conf_names()
    keys = set(reg["keys"])
    declared = set(conf.declared_entries())
    assert keys == declared, (keys ^ declared)
    assert "spark.blaze.enable." in reg["dynamic_prefixes"]
    assert {"spark.blaze.verify.plan", "spark.blaze.verify.locks"} <= keys


def test_conf_readme_table_complete():
    with open(os.path.join(REPO, "README.md")) as f:
        text = f.read()
    missing = [k for k in conf.registered_conf_keys()
               if k.startswith("spark.blaze.") and k not in text]
    assert not missing, f"README conf table missing: {missing}"


# --------------------------------- 3. plan-verifier negative tests

def _scan(n_parts=2, fields=("a", "b")):
    schema = Schema([Field(n, DataType.int64()) for n in fields])
    return MemoryScanExec([[] for _ in range(n_parts)], schema)


def test_verifier_catches_dropped_exchange():
    """FINAL grouped agg over a multi-partition child with NO hash
    exchange — the hand-corrupted 'dropped exchange' plan — is caught
    with the rule id and the offending node path."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr

    partial = AggExec(_scan(2), AggMode.PARTIAL,
                      [GroupingExpr(Col("a"), "a")],
                      [AggFunction("sum", Col("b"), "s")])
    final = AggExec(partial, AggMode.FINAL,
                    [GroupingExpr(Col("a"), "a")],
                    [AggFunction("sum", Col("b"), "s")])
    findings = plan_verify.verify_plan(final)
    assert any(f.rule == "dist.final-agg" for f in findings), findings
    f = next(f for f in findings if f.rule == "dist.final-agg")
    assert f.path.startswith("root")
    assert "root" in repr(f) and "dist.final-agg" in repr(f)


def test_verifier_catches_ungrouped_final_over_partitions():
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode

    partial = AggExec(_scan(2), AggMode.PARTIAL, [],
                      [AggFunction("sum", Col("b"), "s")])
    final = AggExec(partial, AggMode.FINAL, [],
                    [AggFunction("sum", Col("b"), "s")])
    findings = plan_verify.verify_plan(final)
    assert any(f.rule == "dist.final-scalar" for f in findings), findings


def test_verifier_accepts_exchange_and_single_partition():
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr
    from blaze_tpu.parallel.exchange import NativeShuffleExchangeExec
    from blaze_tpu.parallel.shuffle import HashPartitioning

    partial = AggExec(_scan(2), AggMode.PARTIAL,
                      [GroupingExpr(Col("a"), "a")],
                      [AggFunction("sum", Col("b"), "s")])
    ex = NativeShuffleExchangeExec(partial, HashPartitioning([Col("a")], 2))
    final = AggExec(ex, AggMode.FINAL,
                    [GroupingExpr(Col("a"), "a")],
                    [AggFunction("sum", Col("b"), "s")])
    assert plan_verify.verify_plan(final) == []
    # single-partition child: any distribution is exact
    partial1 = AggExec(_scan(1), AggMode.PARTIAL,
                       [GroupingExpr(Col("a"), "a")],
                       [AggFunction("sum", Col("b"), "s")])
    final1 = AggExec(partial1, AggMode.FINAL,
                     [GroupingExpr(Col("a"), "a")],
                     [AggFunction("sum", Col("b"), "s")])
    assert plan_verify.verify_plan(final1) == []


def test_verifier_catches_schema_mismatched_edge():
    """A filter re-parented over a child missing its predicate column
    (the 'schema-mismatched edge' corruption) — caught with rule id +
    node path, since it would otherwise fail deep in kernel lowering
    or silently bind a wrong column."""
    from blaze_tpu.exprs.ir import BinOp, Col, Lit
    from blaze_tpu.ops.filter import FilterExec

    good = _scan(1, fields=("a", "b"))
    flt = FilterExec(good, BinOp(">", Col("a"), Lit(0, DataType.int64())))
    assert plan_verify.verify_plan(flt) == []
    flt.children[0] = _scan(1, fields=("x", "y"))  # corrupt the edge
    findings = plan_verify.verify_plan(flt)
    assert any(f.rule == "schema.edge" and "'a'" in f.message
               for f in findings), findings


def test_verifier_catches_missing_buffer_bottom():
    """A fused chain containing a whole-partition (window) op whose
    child is NOT a BufferPartitionExec — the 'missing buffer bottom'
    corruption — is caught; the correct construction passes."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.fusion import BufferPartitionExec
    from blaze_tpu.ops.sort import SortField
    from blaze_tpu.ops.window import WindowExec, WindowFunction

    scan = _scan(1)
    win = WindowExec(scan, [WindowFunction("rank", "r")],
                     [Col("a")], [SortField(Col("b"))])
    fused_bad = FusedStageExec(scan, [win])
    findings = plan_verify.verify_plan(fused_bad)
    assert any(f.rule == "fusion.buffer-bottom" for f in findings), findings
    fused_ok = FusedStageExec(BufferPartitionExec(scan), [win])
    assert not [f for f in plan_verify.verify_plan(fused_ok)
                if f.rule == "fusion.buffer-bottom"]


def test_verifier_catches_lost_writer_schema(tmp_path):
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec

    w = ShuffleWriterExec(_scan(1), HashPartitioning([Col("a")], 4),
                          str(tmp_path / "s.data"), str(tmp_path / "s.index"))
    w.absorb_traceable_chain()  # bare writer: fuses hash+sort
    assert w._fused_write is not None
    assert plan_verify.verify_plan(w) == []
    w._out_schema = None  # the corruption: schema lost after absorption
    findings = plan_verify.verify_plan(w)
    assert any(f.rule == "fusion.writer-schema" for f in findings), findings


def test_verifier_catches_impure_trace_key():
    class _BadTraceOp(MemoryScanExec):
        def trace_fn(self):
            return lambda cols, n: (cols, n)

        def trace_key(self):
            return ("bad", object())  # identity-bearing: ' at 0x...'

    schema = Schema([Field("a", DataType.int64())])
    node = _BadTraceOp([[]], schema)
    findings = plan_verify.verify_plan(node)
    assert any(f.rule == "fusion.trace-key" for f in findings), findings

    class _NoKeyOp(MemoryScanExec):
        def trace_fn(self):
            return lambda cols, n: (cols, n)

    findings = plan_verify.verify_plan(_NoKeyOp([[]], schema))
    assert any(f.rule == "fusion.trace-key" and "None" in f.message
               for f in findings), findings


def test_verifier_catches_unsorted_smj_child():
    """SMJ fed by a hash exchange with the sort DROPPED (the rewrite
    bug class — an exchange provably destroys row order) is caught on
    both sides; re-inserting the sorts passes.  A leaf-source child is
    accepted: its order is the caller's contract."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.joins import JoinType, SortMergeJoinExec
    from blaze_tpu.ops.sort import SortExec, SortField
    from blaze_tpu.parallel.exchange import NativeShuffleExchangeExec
    from blaze_tpu.parallel.shuffle import HashPartitioning

    def exchanged(fields):
        return NativeShuffleExchangeExec(
            _scan(2, fields=fields), HashPartitioning([Col("k")], 2))

    smj = SortMergeJoinExec(exchanged(("k", "v1")), exchanged(("k", "v2")),
                            [Col("k")], [Col("k")], JoinType.INNER)
    findings = plan_verify.verify_plan(smj)
    assert sum(1 for f in findings if f.rule == "order.smj") == 2, findings
    assert any("destroys" in f.message for f in findings)
    sorted_smj = SortMergeJoinExec(
        SortExec(exchanged(("k", "v1")), [SortField(Col("k"))]),
        SortExec(exchanged(("k", "v2")), [SortField(Col("k"))]),
        [Col("k")], [Col("k")], JoinType.INNER)
    assert not [f for f in plan_verify.verify_plan(sorted_smj)
                if f.rule == "order.smj"]
    # leaf-source children: order is the caller's contract, accepted
    leaf_smj = SortMergeJoinExec(_scan(1, fields=("k", "v1")),
                                 _scan(1, fields=("k", "v2")),
                                 [Col("k")], [Col("k")], JoinType.INNER)
    assert not [f for f in plan_verify.verify_plan(leaf_smj)
                if f.rule == "order.smj"]


def test_verifier_catches_wrong_sort_keys_under_smj():
    """A sort IS there but on the wrong key — the prefix check."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.joins import JoinType, SortMergeJoinExec
    from blaze_tpu.ops.sort import SortExec, SortField

    left = SortExec(_scan(1, fields=("k", "v1")), [SortField(Col("v1"))])
    right = SortExec(_scan(1, fields=("k", "v2")), [SortField(Col("k"))])
    smj = SortMergeJoinExec(left, right, [Col("k")], [Col("k")],
                            JoinType.INNER)
    findings = [f for f in plan_verify.verify_plan(smj)
                if f.rule == "order.smj"]
    assert len(findings) == 1 and "child 0" in findings[0].message


def test_verifier_catches_desc_and_reordered_sort_under_smj():
    """Direction and key order are part of what a streaming merge
    relies on: a DESC sort on the join key, or keys sorted (b, a) when
    the join needs (a, b), both break the merge exactly like a dropped
    sort (review finding)."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.joins import JoinType, SortMergeJoinExec
    from blaze_tpu.ops.sort import SortExec, SortField

    def smj_with_left(left_sort_fields):
        left = SortExec(_scan(1, fields=("a", "b")), left_sort_fields)
        right = SortExec(_scan(1, fields=("a", "c")),
                         [SortField(Col("a"))])
        return SortMergeJoinExec(left, right, [Col("a")], [Col("a")],
                                 JoinType.INNER)

    desc = smj_with_left([SortField(Col("a"), ascending=False)])
    findings = [f for f in plan_verify.verify_plan(desc)
                if f.rule == "order.smj"]
    assert len(findings) == 1 and "child 0" in findings[0].message

    # two-key join sorted in the WRONG key order
    left = SortExec(_scan(1, fields=("a", "b")),
                    [SortField(Col("b")), SortField(Col("a"))])
    right = SortExec(_scan(1, fields=("a", "b")),
                     [SortField(Col("a")), SortField(Col("b"))])
    from blaze_tpu.ops.joins import SortMergeJoinExec as SMJ
    smj = SMJ(left, right, [Col("a"), Col("b")], [Col("a"), Col("b")],
              JoinType.INNER)
    findings = [f for f in plan_verify.verify_plan(smj)
                if f.rule == "order.smj"]
    assert len(findings) == 1 and "child 0" in findings[0].message


def test_ambiguous_lock_binding_dropped_not_misranked(tmp_path):
    """Two classes in one module both naming their lock ``self._lock``
    at DIFFERENT ranks: the static pass drops the ambiguous tail
    instead of checking it at an arbitrary rank (review finding) —
    the runtime assertion still covers those nestings."""
    root = _write_pkg(tmp_path, "pkg_ambig", """\
from blaze_tpu.analysis.locks import make_lock

class A:
    def __init__(self):
        self._lock = make_lock("metrics.set")

class B:
    def __init__(self):
        self._lock = make_lock("metrics.node")

    def nested(self, other):
        with self._lock:
            with other._lock:   # tail is ambiguous: must NOT be flagged
                pass

_outer = make_lock("monitor.registry")
_inner = make_lock("conf.store")

def still_checked():
    with _inner:
        with _outer:            # unambiguous names: still flagged
            pass
""")
    findings = [f for f in locks.lint_lock_order(root)
                if f.rule == "lock.static-order"]
    assert len(findings) == 1
    assert findings[0].symbol == "monitor.registry"


def test_verify_or_raise_is_the_execution_hook():
    """optimize_plan with spark.blaze.verify.plan armed (as the whole
    test suite runs, via conftest) raises PlanVerificationError on a
    corrupted plan — the execution hookpoint, not just a library."""
    from blaze_tpu.exprs.ir import Col
    from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr

    partial = AggExec(_scan(2), AggMode.PARTIAL,
                      [GroupingExpr(Col("a"), "a")],
                      [AggFunction("sum", Col("b"), "s")])
    final = AggExec(partial, AggMode.FINAL,
                    [GroupingExpr(Col("a"), "a")],
                    [AggFunction("sum", Col("b"), "s")])
    assert bool(conf.VERIFY_PLAN.get()), "conftest must force this on"
    with pytest.raises(plan_verify.PlanVerificationError) as ei:
        optimize_plan(final)
    assert "dist.final-agg" in str(ei.value)


# ------------------------------------ 4. acceptance over real plans

@pytest.mark.parametrize("fused", [True, False])
def test_real_tpch_plans_verify_clean(fused):
    scans = _empty_scans(TPCH_SCHEMAS)
    prev = bool(conf.FUSION_ENABLE.get())
    conf.FUSION_ENABLE.set(fused)
    try:
        for name in ("q1", "q3", "q6"):
            plan = optimize_plan(build_query(name, scans, 2))
            assert plan_verify.verify_plan(plan) == [], name
    finally:
        conf.FUSION_ENABLE.set(prev)


@pytest.mark.parametrize("fused", [True, False])
def test_real_tpcds_plans_verify_clean(fused):
    from blaze_tpu.tpcds import TPCDS_SCHEMAS
    from blaze_tpu.tpcds import build_query as build_ds

    scans = _empty_scans(TPCDS_SCHEMAS)
    prev = bool(conf.FUSION_ENABLE.get())
    conf.FUSION_ENABLE.set(fused)
    try:
        for name in ("q6", "q36", "q47"):  # agg, window, stacked window
            plan = optimize_plan(build_ds(name, scans, 2))
            assert plan_verify.verify_plan(plan) == [], name
    finally:
        conf.FUSION_ENABLE.set(prev)


def test_fused_stage_trace_key_deterministic_across_builds():
    """Two independent builds of the same plan produce IDENTICAL
    FusedStageExec trace keys (the invariant that makes the fused
    program cache process-wide and the persistent compile cache
    reusable across tasks)."""
    scans = _empty_scans(TPCH_SCHEMAS)

    def fused_keys():
        plan = optimize_plan(build_query("q1", scans, 2))
        out = []

        def walk(n):
            if isinstance(n, FusedStageExec):
                out.append(n.trace_key())
            for c in n.children:
                walk(c)

        walk(plan)
        return out

    k1, k2 = fused_keys(), fused_keys()
    assert k1 == k2
    for k in k1:
        assert " at 0x" not in repr(k)
        hash(k)


# -------------------------------------------- 5. the lock framework

def test_make_lock_refuses_undeclared_names():
    with pytest.raises(ValueError, match="not declared in the hierarchy"):
        locks.make_lock("totally.new.lock")


def test_runtime_lock_order_assertion():
    outer = locks.make_lock("monitor.registry")
    inner = locks.make_lock("conf.store")
    locks.arm(True)
    try:
        with outer:
            with inner:  # inward: fine
                assert locks.held_names() == ["monitor.registry",
                                              "conf.store"]
        with inner:
            with pytest.raises(locks.LockOrderError, match="monitor.registry"):
                outer.acquire()
        # same-rank re-entry is an inversion too (self-deadlock /
        # sibling-instance cycles like consumer->consumer spill)
        other = locks.make_lock("conf.store")
        with inner:
            with pytest.raises(locks.LockOrderError):
                other.acquire()
    finally:
        locks.arm(False)
    assert locks.held_names() == []
    # disarmed: inversion passes silently (one bool read per acquire)
    with inner:
        with outer:
            pass


def test_release_while_disarmed_still_pops_held_stack():
    """Disarming mid-critical-section on ANOTHER thread (the chaos
    finally / suite teardown path) must not strand that thread's
    held-stack entry: release() pops unconditionally, so re-arming
    later cannot raise a spurious LockOrderError against a lock the
    thread no longer holds."""
    import threading

    lk = locks.make_lock("trace.log")
    acquired = threading.Event()
    disarmed = threading.Event()
    rearmed = threading.Event()
    errors = []

    def worker():
        try:
            lk.acquire()          # armed: pushed onto this thread's TLS
            acquired.set()
            assert disarmed.wait(5)
            lk.release()          # DISARMED now: must still pop
            assert rearmed.wait(5)
            with lk:              # armed again: stale entry would raise
                pass
        except BaseException as e:  # noqa: BLE001 — surface to the test
            errors.append(e)

    locks.arm(True)
    t = threading.Thread(target=worker)
    try:
        t.start()
        assert acquired.wait(5)
        locks.arm(False)
        disarmed.set()
        t.join(0.2)  # let the release land disarmed
        locks.arm(True)
        rearmed.set()
        t.join(5)
    finally:
        locks.arm(False)
        disarmed.set()
        rearmed.set()
        t.join(5)
    assert not errors, errors


def test_conf_literal_with_sentence_period_resolves():
    """An exact registered key captured with a trailing sentence
    period ('...set spark.blaze.batchSize.') must not produce a
    phantom conf.unregistered finding."""
    reg = conf.load_conf_names()
    keys = set(reg["keys"])
    prefixes = list(reg["dynamic_prefixes"])
    assert lint._literal_resolves("spark.blaze.batchSize.", keys, prefixes)
    assert not lint._literal_resolves("spark.blaze.nope.", keys, prefixes)


def test_lock_order_armed_end_to_end_scheduler_run():
    """A real multi-stage scheduler query (spills, async staging,
    metrics, trace arming off) under the runtime assertion: the
    declared hierarchy holds on every path the run crosses."""
    from blaze_tpu.runtime.scheduler import run_stages, split_stages
    from blaze_tpu.tpch.datagen import generate_all, table_to_batches

    data = generate_all(0.002)
    scans = {
        n: MemoryScanExec(
            table_to_batches(data[n], TPCH_SCHEMAS[n], 2, batch_rows=65536),
            TPCH_SCHEMAS[n])
        for n in TPCH_SCHEMAS
    }
    conf.VERIFY_LOCKS.set(True)
    locks.refresh()
    try:
        stages, mgr = split_stages(build_query("q6", scans, 2))
        rows = sum(b.num_rows for b in run_stages(stages, mgr))
        assert rows > 0
    finally:
        conf.VERIFY_LOCKS.set(False)
        locks.refresh()


def test_hierarchy_covers_every_make_lock_site():
    """Every make_lock("...") literal in the package names a declared
    hierarchy entry (construction would raise anyway — this pins the
    declared set against drift), and the named subsystems are all
    ranked."""
    import re

    names = set()
    pkg = os.path.join(REPO, "blaze_tpu")
    for root, _, files in os.walk(pkg):
        if os.path.basename(root) == "analysis":
            continue  # the checker's own docstrings use placeholders
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(root, fn)) as f:
                    names |= set(re.findall(r'make_lock\("([^"]+)"\)',
                                            f.read()))
    assert names <= set(locks.HIERARCHY), names - set(locks.HIERARCHY)
    # the subsystems the checker exists for are all present
    assert {"monitor.server", "monitor.registry", "shuffle.repartitioner",
            "kernel_cache.registry", "trace.sink", "dispatch.counters",
            "memmgr.manager", "conf.store"} <= names


# ------------------------------------------------ 6. waiver pinning

#: the REVIEWED waiver set — additions fail here by design (fix the
#: violation instead); removals are always allowed.  PR 8 removed the
#: two spill-path emit-under-lock waivers (ShuffleRepartitioner.spill,
#: _Window.spill): the spill.write fault probe moved to the consumer
#: spill() entry points OUTSIDE their state locks, so no emission rides
#: inside those critical sections anymore.
PINNED_WAIVERS = {
    ("purity.host-sync", "ops/window.py", "_window_body.*"),
    ("jit.uncached", "parallel/ici.py", "ici_shuffle*"),
    ("jit.uncached", "parallel/ici.py", "ici_range_shuffle*"),
    ("lock.emit-under-lock", "parallel/ici.py",
     "IciShuffleExchangeExec._materialize"),
    # emit reached ≤3 helper hops deep while holding a materialize-once
    # lock: each span is load-bearing (exactly-once drive) and every
    # reachable emit rides a trace lock ranked strictly inward of the
    # held lock — no cycle
    ("lock.emit-under-lock", "parallel/exchange.py",
     "NativeShuffleExchangeExec.materialize"),
    ("lock.emit-under-lock", "ops/joins/broadcast.py",
     "BroadcastJoinBuildHashMapExec._build_payload"),
    # the unmanaged (manager-None) branches touch a consumer no other
    # thread can reach; the managed branches all lock
    ("guard.unlocked", "runtime/memmgr.py", "MemConsumer.*"),
    # PR 15 (exception-flow passes, analysis/errflow.py): transports
    # that statically look like swallows but deliver the error onward
    # (the speculation attempt record, the async stager's deferred
    # surfacing), per-row value-parse handlers where nothing inside
    # the try can raise a control-flow/integrity error, and the worker
    # subprocess commit (no cancellation concept; attempt-qualified,
    # driver-verified)
    ("except.swallow", "runtime/speculation.py",
     "StageTaskRunner._spawn.body"),
    ("except.swallow", "parallel/shuffle.py", "_AsyncInserter._drain"),
    ("except.swallow", "ops/generate.py", "json_tuple_generator.gen"),
    ("except.swallow", "exprs/functions.py", "_to_date"),
    ("except.swallow", "exprs/json_path.py", "get_json_object"),
    ("except.swallow", "exprs/json_path.py", "parse_json"),
    ("commit.guard", "runtime/worker.py", "main"),
}


def test_waiver_file_can_only_shrink():
    waivers = lint.load_waivers()
    current = {(w["rule"], w["file"], w["symbol"]) for w in waivers}
    new = current - PINNED_WAIVERS
    assert not new, (
        f"new lint waivers {new} — fix the violation instead of waiving "
        f"it (or get the pinned set in tests/test_analysis.py reviewed)")
    for w in waivers:
        assert w.get("reason", "").strip(), f"waiver without reason: {w}"


def test_waiver_file_entries_still_needed():
    """A waiver whose violation no longer exists is stale — the set
    shrinks instead of accumulating dead exceptions."""
    raw = lint.lint_package(apply_waivers=False)
    for w in lint.load_waivers():
        hit = [f for f in raw if f.rule == w["rule"]
               and f.path.endswith(w["file"])]
        assert hit, f"stale waiver (violation gone — delete it): {w}"


# -------------------------------- 7. _remove_by_identity regression

def test_remove_by_identity_with_equal_duplicates():
    """The PR 3 bug class, pinned at the helper: two EQUAL-content
    entries; removal must evict the exact object, not a lookalike."""
    a = {"programs": 0}
    b = {"programs": 0}
    assert a == b and a is not b
    items = [a, b]
    assert _remove_by_identity(items, b)
    assert len(items) == 1 and items[0] is a
    assert not _remove_by_identity(items, b)  # already gone
    assert items[0] is a


def test_capture_scopes_survive_equal_content_siblings():
    """dispatch.capture + trace.kernel_capture both route through the
    shared helper: an inner scope with content EQUAL to the outer must
    not evict the outer on exit (duplicates exist exactly when nothing
    was recorded yet)."""
    from blaze_tpu.runtime import dispatch, trace

    with dispatch.capture() as outer:
        with dispatch.capture() as inner:
            pass  # inner == outer == {}
        dispatch.record("xla_dispatches")  # must still land on outer
    assert outer.get("xla_dispatches") == 1 and inner == {}

    with trace.kernel_capture() as osink:
        with trace.kernel_capture() as isink:
            pass
        trace.record_kernel("k", 1, 2, 3)
    assert "k" in osink and isink == {}
