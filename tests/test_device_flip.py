"""Majority-device flip gates (tier-1, CPU backend).

The PR-19 acceptance surface: with the batch autotuner armed, warm
q01/q06 spend more wall time ON the device than in the host dispatch
loop; the tier-5 fused shuffle write absorbs the blocking boundary
above it (agg finalize, range partitioning); donated double-buffered
staging changes WHEN buffers die, never WHAT bytes commit; and the
dispatch-driven batch autotuner converges inside its configured bounds
and backs off under memory pressure.

Every path here is a differential against the plain (donation off,
autotune off, fusion off) execution — byte-identical committed shuffle
files, or value-identical query output where coalescing legitimately
reassociates float reductions.
"""

import os
import tempfile

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.ops.sort import SortField
from blaze_tpu.parallel.shuffle import (
    HashPartitioning, RangePartitioning, ShuffleWriterExec,
)
from blaze_tpu.runtime import dispatch, faults, trace
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

SCALE = 0.01
BATCH_ROWS = 4096


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def flip_data():
    # the majority-device gate needs enough per-bucket device work to
    # rise above CPU-backend timer noise; datagen at 0.05 is <1s
    return generate_all(0.05)


def _scans(data, batch_rows=BATCH_ROWS, n_parts=1):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


# --------------------------------- 1. warm majority-device budget


@pytest.mark.parametrize("q", ["q1", "q6"])
def test_warm_query_majority_device_with_autotune(flip_data, q):
    """With the autotuner armed (exactly how --perfcheck measures),
    the warm steady state spends more time on the device than in the
    dispatch loop.  Totals are SUMMED over several warm passes — a
    single pass at test scale is at the mercy of one slow dispatch."""
    def run_once():
        plan = optimize_plan(build_query(q, _scans(flip_data), 1))
        rows = 0
        for p in range(plan.num_partitions()):
            for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
                rows += b.num_rows
        assert rows > 0

    dispatch.autotune_force(True)
    try:
        # pin the controller at its dispatch-bound fixed point, exactly
        # how --perfcheck measures: timing-driven convergence on a
        # loaded CPU host can break early (a window the share coin-flip
        # called device-majority) and then grow DURING the measured
        # passes — a fresh bucket shape there recompiles and breaks the
        # zero-warm-compile assertion.  At the cap further observations
        # cannot move the target, so the cold pass below compiles the
        # final shapes and the measurement is stable.
        dispatch.autotune_saturate(q)
        run_once()  # cold: compiles allowed
        device_ns = dispatch_ns = 0
        with dispatch.capture() as warm:
            for _ in range(3):
                with trace.profile_kernels() as prof:
                    run_once()
                k = trace.sum_kernels(prof)
                device_ns += k["device_time_ns"]
                dispatch_ns += k["dispatch_overhead_ns"]
    finally:
        dispatch.autotune_force(None)
    assert warm.get("xla_compiles", 0) == 0, (
        f"warm {q} recompiled after convergence: {warm}")
    assert device_ns > dispatch_ns, (
        f"warm {q} is dispatch-bound: device {device_ns / 1e6:.2f}ms vs "
        f"dispatch {dispatch_ns / 1e6:.2f}ms over 3 passes")


def test_autotuned_q1_matches_plain_results(data):
    """Coalescing reassociates float reductions, so the differential is
    value-level (allclose), plus bit-determinism: two autotuned runs
    from a reset controller produce identical bytes."""
    def rows_of():
        d = _run(optimize_plan(build_query("q1", _scans(data), 1)))
        return {k: np.asarray(v) for k, v in d.items()}

    plain = rows_of()
    dispatch.autotune_force(True)
    try:
        # saturate both runs: a timing-converged target can differ run
        # to run, and a different coalesce width reassociates float
        # reductions differently — the byte-determinism half would
        # then compare two legitimately different groupings
        dispatch.autotune_saturate("q1")
        with trace.profile_kernels():
            tuned_a = rows_of()
        dispatch.autotune_reset()
        dispatch.autotune_saturate("q1")
        with trace.profile_kernels():
            tuned_b = rows_of()
    finally:
        dispatch.autotune_force(None)
    assert set(plain) == set(tuned_a)
    for k in plain:
        if plain[k].dtype.kind == "f":
            np.testing.assert_allclose(tuned_a[k], plain[k], rtol=1e-9)
            np.testing.assert_array_equal(tuned_a[k], tuned_b[k])
        else:
            np.testing.assert_array_equal(tuned_a[k], plain[k])
            np.testing.assert_array_equal(tuned_a[k], tuned_b[k])


# --------------------------------- 2. autotune controller units


def _autotune_bounds_conf(lo, hi, step, window):
    conf.BATCH_AUTOTUNE_MIN_ROWS.set(lo)
    conf.BATCH_AUTOTUNE_MAX_ROWS.set(hi)
    conf.BATCH_AUTOTUNE_STEP.set(step)
    conf.BATCH_AUTOTUNE_WINDOW.set(window)


def _restore_autotune_conf():
    for e in (conf.BATCH_AUTOTUNE_MIN_ROWS, conf.BATCH_AUTOTUNE_MAX_ROWS,
              conf.BATCH_AUTOTUNE_STEP, conf.BATCH_AUTOTUNE_WINDOW,
              conf.BATCH_AUTOTUNE_TARGET_SHARE):
        e.set(e.default)


def test_autotune_disabled_is_structural_noop():
    dispatch.autotune_force(None)
    prior = conf.BATCH_AUTOTUNE.get()
    conf.BATCH_AUTOTUNE.set(False)
    try:
        assert dispatch.autotune_target_rows() == 0
        with dispatch.capture() as cap:
            dispatch.autotune_memory_pushback("x")
        assert not cap.get("autotune_adjustments")
    finally:
        conf.BATCH_AUTOTUNE.set(prior)


def test_autotune_grows_by_step_within_bounds():
    """Dispatch-bound observations grow the target lo -> lo*step -> cap
    (maxRows), one decision per window, each counted and traced."""
    dispatch.autotune_force(True)
    _autotune_bounds_conf(100, 1000, 4, 2)
    try:
        assert dispatch.autotune_target_rows() == 100
        with dispatch.capture() as cap:
            # window=2: two observations per decision, 10% device share
            for _ in range(2):
                dispatch.autotune_observe("k", device_ns=1, dispatch_ns=9)
            assert dispatch.autotune_target_rows() == 400
            for _ in range(2):
                dispatch.autotune_observe("k", device_ns=1, dispatch_ns=9)
            assert dispatch.autotune_target_rows() == 1000  # capped
            for _ in range(2):
                dispatch.autotune_observe("k", device_ns=1, dispatch_ns=9)
            assert dispatch.autotune_target_rows() == 1000  # stays capped
        assert cap.get("autotune_adjustments") == 2
    finally:
        _restore_autotune_conf()
        dispatch.autotune_force(None)


def test_autotune_stops_growing_past_target_share():
    dispatch.autotune_force(True)
    _autotune_bounds_conf(100, 100000, 4, 1)
    try:
        dispatch.autotune_observe("k", device_ns=9, dispatch_ns=1)
        assert dispatch.autotune_target_rows() == 100, \
            "majority-device window must not grow the bucket"
    finally:
        _restore_autotune_conf()
        dispatch.autotune_force(None)


def test_autotune_memory_pushback_halves_and_caps_regrowth():
    dispatch.autotune_force(True)
    _autotune_bounds_conf(100, 100000, 4, 1)
    try:
        dispatch.autotune_observe("k", device_ns=0, dispatch_ns=10)
        dispatch.autotune_observe("k", device_ns=0, dispatch_ns=10)
        grown = dispatch.autotune_target_rows()
        assert grown == 1600
        with dispatch.capture() as cap:
            dispatch.autotune_memory_pushback("k")
        assert cap.get("autotune_adjustments", 0) >= 1
        halved = dispatch.autotune_target_rows()
        assert halved < grown
        # regrowth is CAPPED below the size that exhausted the device
        for _ in range(20):
            dispatch.autotune_observe("k", device_ns=0, dispatch_ns=10)
        assert dispatch.autotune_target_rows() < grown
    finally:
        _restore_autotune_conf()
        dispatch.autotune_force(None)


# ------------------- 3. blocking-boundary fusion into the fused write


def _agg_plan(data):
    groupings = [GroupingExpr(col("l_returnflag"), "l_returnflag")]
    aggs = [AggFunction("sum", col("l_quantity"), "sum_qty"),
            AggFunction("count_star", None, "cnt")]
    scan = _scans(data, batch_rows=2048)["lineitem"]
    partial = AggExec(scan, AggMode.PARTIAL, groupings, aggs)
    return AggExec(partial, AggMode.FINAL, groupings, aggs)


def _write_once(plan_fn, partitioning_fn, boundaries=None):
    d = tempfile.mkdtemp(prefix="blaze_flip_")
    data_path = os.path.join(d, "m.data")
    index_path = os.path.join(d, "m.index")
    writer = optimize_plan(ShuffleWriterExec(
        plan_fn(), partitioning_fn(), data_path, index_path))
    if boundaries is not None:
        writer.partitioning.boundaries = boundaries
    list(writer.execute(0, TaskContext(0, 1)))
    with open(data_path, "rb") as f:
        blob = f.read()
    with open(index_path, "rb") as f:
        idx = f.read()
    return blob, idx, writer


def test_agg_finalize_absorbed_into_fused_write_byte_identical(data):
    """A FINAL agg feeding a hash shuffle write runs its finalize
    kernel INSIDE the tier-5 fused program (no device round-trip at
    the blocking boundary) and commits identical bytes to the unfused
    finalize-then-write path."""
    blob_f, idx_f, w = _write_once(
        lambda: _agg_plan(data),
        lambda: HashPartitioning([col("l_returnflag")], 3))
    assert w._fused_write is not None, "agg chain not absorbed"
    assert any(isinstance(k, tuple) and k and k[0] == "agg_finalize"
               for k in w._fused_fn_keys), w._fused_fn_keys
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u, wu = _write_once(
            lambda: _agg_plan(data),
            lambda: HashPartitioning([col("l_returnflag")], 3))
        assert wu._fused_write is None
    finally:
        conf.FUSION_ENABLE.set(True)
    assert blob_f == blob_u and idx_f == idx_u


def _range_boundaries(data, fields, n_out):
    import jax.numpy as jnp

    from blaze_tpu.parallel.exchange import _build_range_kernels

    sch = TPCH_SCHEMAS["lineitem"]
    kw, bat, _ = _build_range_kernels(sch, fields, n_out)
    scan = _scans(data, batch_rows=2048)["lineitem"]
    batches = list(scan.execute(0, TaskContext(0, 1)))
    words = [kw(tuple(b.columns), b.num_rows) for b in batches]
    cat = tuple(jnp.concatenate([w[i] for w in words])
                for i in range(len(words[0])))
    total = sum(b.num_rows for b in batches)
    positions = jnp.asarray([total * (i + 1) // n_out
                             for i in range(n_out - 1)])
    return tuple(np.asarray(b) for b in bat(cat, positions))


def test_range_partitioned_fused_write_byte_identical(data):
    """Range partitioning fuses with the boundary arrays as TRACED
    args (not baked constants): the fused program and the eager
    key-words/pids path commit identical files."""
    fields = [SortField(col("l_orderkey"))]
    bounds = _range_boundaries(data, fields, 3)
    blob_f, idx_f, w = _write_once(
        lambda: optimize_plan(_scans(data, batch_rows=2048)["lineitem"]),
        lambda: RangePartitioning(fields, 3), boundaries=bounds)
    assert w._fused_write is not None, "range write not absorbed"
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u, wu = _write_once(
            lambda: _scans(data, batch_rows=2048)["lineitem"],
            lambda: RangePartitioning(fields, 3), boundaries=bounds)
        assert wu._fused_write is None
    finally:
        conf.FUSION_ENABLE.set(True)
    assert blob_f == blob_u and idx_f == idx_u


# --------------------- 4. donated double-buffered staging seams


def _hash_write(data):
    return _write_once(lambda: _agg_plan(data),
                       lambda: HashPartitioning([col("l_returnflag")], 3))


def test_donated_write_fires_and_stays_byte_identical(data):
    plain_blob, plain_idx, _ = _hash_write(data)
    conf.DONATE_BUFFERS.set(True)
    try:
        with dispatch.capture() as cap:
            blob_d, idx_d, _ = _hash_write(data)
    finally:
        conf.DONATE_BUFFERS.set(False)
    assert cap.get("donated_buffers", 0) > 0, (
        f"no batch took the donated twin: {cap}")
    assert blob_d == plain_blob and idx_d == plain_idx


def test_donated_write_sync_staging_byte_identical(data):
    """Donation with the synchronous writer (no inserter, no device
    ring) — the donated kernel itself is staging-agnostic."""
    plain_blob, plain_idx, _ = _hash_write(data)
    conf.DONATE_BUFFERS.set(True)
    conf.SHUFFLE_ASYNC_WRITE.set(False)
    try:
        blob_d, idx_d, _ = _hash_write(data)
    finally:
        conf.SHUFFLE_ASYNC_WRITE.set(True)
        conf.DONATE_BUFFERS.set(False)
    assert blob_d == plain_blob and idx_d == plain_idx


def test_donated_write_unfused_path_byte_identical(data):
    """Fusion off: no fused write exists, donation has nothing to bind
    to, and the conf being on must not perturb the eager path."""
    plain_blob, plain_idx, _ = _hash_write(data)
    conf.DONATE_BUFFERS.set(True)
    conf.FUSION_ENABLE.set(False)
    try:
        blob_d, idx_d, w = _hash_write(data)
        assert w._fused_write is None
    finally:
        conf.FUSION_ENABLE.set(True)
        conf.DONATE_BUFFERS.set(False)
    assert blob_d == plain_blob and idx_d == plain_idx


def test_donated_write_oom_downshift_byte_identical(data):
    """An injected device OOM under donation decomposes to the eager
    per-kernel path with the batch's inputs INTACT (injected faults
    raise before the donating call) — committed bytes unchanged."""
    plain_blob, plain_idx, _ = _hash_write(data)
    conf.DONATE_BUFFERS.set(True)
    conf.FAULTS_SPEC.set("kernel.dispatch@3@oom")
    faults.reset()
    try:
        with dispatch.capture() as cap:
            blob_d, idx_d, _ = _hash_write(data)
    finally:
        conf.FAULTS_SPEC.set("")
        faults.reset()
        conf.DONATE_BUFFERS.set(False)
    assert (cap.get("oom_recoveries", 0) + cap.get("batch_downshifts", 0)
            + cap.get("eager_fallbacks", 0)) > 0, (
        f"the injected OOM never reached the ladder: {cap}")
    assert blob_d == plain_blob and idx_d == plain_idx


def test_device_oom_error_not_reabsorbed_as_resource_exhausted():
    """The OOM ladder's TERMINAL verdict must not re-enter the ladder:
    a donating program's inputs may already be dead, so DeviceOomError
    classifies non-absorbable even though its message embeds the
    cause's RESOURCE_EXHAUSTED text."""
    from blaze_tpu.runtime import oom

    err = oom.DeviceOomError(
        "fused_write: RESOURCE_EXHAUSTED: out of memory")
    assert not oom.is_resource_exhausted(err)
    assert oom.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory"))


def test_abort_mid_stream_drops_ring_without_commit(data):
    """A task killed mid-stream (injected non-OOM fault — the same
    seam a ctx cancel rides) drops the device ring and aborts the
    async writer: nothing commits, and a fresh run afterwards still
    produces the canonical bytes (no poisoned process state)."""
    plain_blob, plain_idx, _ = _hash_write(data)
    conf.DONATE_BUFFERS.set(True)
    conf.FAULTS_SPEC.set("kernel.dispatch@4@a0")
    faults.reset()
    try:
        d = tempfile.mkdtemp(prefix="blaze_cancel_")
        data_path = os.path.join(d, "m.data")
        index_path = os.path.join(d, "m.index")
        writer = optimize_plan(ShuffleWriterExec(
            _agg_plan(data), HashPartitioning([col("l_returnflag")], 3),
            data_path, index_path))
        with pytest.raises(faults.InjectedFault):
            list(writer.execute(0, TaskContext(0, 1)))
        assert not os.path.exists(data_path), \
            "aborted task committed a partial .data file"
        assert not os.path.exists(index_path)
        conf.FAULTS_SPEC.set("")
        faults.reset()
        # the seam leaks nothing into process state: a clean run after
        # the abort still commits the canonical bytes
        blob2, idx2, _ = _hash_write(data)
    finally:
        conf.FAULTS_SPEC.set("")
        faults.reset()
        conf.DONATE_BUFFERS.set(False)
    assert blob2 == plain_blob and idx2 == plain_idx


def test_device_ring_fifo_and_overlap_metric():
    from blaze_tpu.batch import DeviceRing

    ring = DeviceRing()
    with dispatch.capture() as cap:
        out = []
        for i in range(5):
            out.extend(ring.put(i))
        out.extend(ring.flush())
    assert out == [0, 1, 2, 3, 4], "ring must preserve FIFO order"
    assert len(ring) == 0
    assert cap.get("double_buffer_overlap_ns", 0) > 0
    ring.put(9)
    ring.drop()
    assert len(ring) == 0 and ring.flush() == []


# ----------------------------- 5. pallas hash-join probe kernel


def test_sorted_lookup_matches_searchsorted():
    from blaze_tpu.kernels import pallas_ops

    rng = np.random.default_rng(11)
    for t_n, p_n in ((17, 100), (1024, 3000), (4096, 257)):
        table = np.sort(rng.integers(0, 2**63, t_n, dtype=np.uint64))
        # duplicates + exact hits + misses + extremes
        probes = np.concatenate([
            rng.choice(table, p_n // 2),
            rng.integers(0, 2**63, p_n - p_n // 2, dtype=np.uint64),
            np.asarray([0, 2**64 - 2], dtype=np.uint64),
        ])
        import jax.numpy as jnp

        lo, hi = pallas_ops.sorted_lookup(jnp.asarray(table),
                                          jnp.asarray(probes))
        np.testing.assert_array_equal(
            np.asarray(lo), np.searchsorted(table, probes, side="left"))
        np.testing.assert_array_equal(
            np.asarray(hi), np.searchsorted(table, probes, side="right"))


@pytest.mark.parametrize("q", ["q12", "q14"])
def test_pallas_join_probe_differential(data, q):
    """spark.blaze.tpu.pallas.joinProbe (forced interpret off-TPU):
    join results identical to the XLA searchsorted probe path."""
    from blaze_tpu.kernels import pallas_ops

    def rows_of():
        d = _run(optimize_plan(build_query(q, _scans(data), 1)))
        return sorted(zip(*d.values()), key=repr)

    plain = rows_of()
    pallas_ops.force_interpret(True)
    conf.PALLAS_JOIN_PROBE.set(True)
    try:
        got = rows_of()
    finally:
        conf.PALLAS_JOIN_PROBE.set(False)
        pallas_ops.force_interpret(False)
    assert got == plain
