"""The bench driver-line contract: the ONE stdout JSON line must fit
the driver's 2000-char stdout tail (round-4 postmortem: embedded
probe/watchdog logs pushed the metric head off the capture and
BENCH_r04 parsed null)."""

import contextlib
import io
import json
import os
import sys

# repo root (cwd-independent): bench.py is not a package member
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import calendar
import time as _time

import bench


def _ts(stamp):
    return calendar.timegm(_time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))


#: pinned "wall clock" for merge tests — the hardcoded 2026-08-01/02
#: provenance stamps must stay inside the stale-cache window forever
_NOW = _ts("2026-08-02T12:00:00Z")


def _emit_line(result, probe_log, wd_log):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit(dict(result), probe_log, wd_log)
    return buf.getvalue().strip()


BASE = {"metric": "tpch_q06_rows_per_sec_per_chip", "value": 1.0,
        "unit": "rows/s", "vs_baseline": 0.1}


def test_line_always_fits_driver_tail():
    huge_probes = [{"t": f"2026-07-31T{i % 24:02d}:00:00Z", "ok": False}
                   for i in range(500)]
    huge_wd = [{"t": "t", "event": "probe", "ok": False}] * 2000
    line = _emit_line(dict(BASE, note="x" * 3000), huge_probes, huge_wd)
    assert len(line) < 1500
    d = json.loads(line)
    assert d["metric"] == BASE["metric"] and d["value"] == 1.0


def test_summary_counts_only_probe_events():
    s = bench._log_summary([
        {"t": "a", "event": "probe", "ok": True},
        {"t": "b", "event": "measuring"},
        {"t": "c", "event": "measure", "rc": 0},
        {"t": "d", "event": "probe", "ok": False},
    ])
    assert s == {"probes": 2, "ok": 1, "first": "a", "last": "d",
                 "last_ok": "a"}


def test_summary_empty():
    assert bench._log_summary([]) == {"probes": 0, "ok": 0}


def test_merge_cached_carries_whole_q01_half():
    """A fresh q06-only partial merged with a cached full result must
    carry the ENTIRE q01 half — throughput, dispatch counters, AND the
    dispatch-floor profile (programs/device_time_s/dispatch_overhead_s,
    VERDICT r5 next #7) — with the ORIGINAL q01 timestamp."""
    prev = {"backend": "tpu", "value": 1.0, "measured_at": "2026-08-01T00:00:00Z",
            "q01_rows_per_sec": 5.0, "q01_vs_baseline": 0.5,
            "q01_dispatch_count": 1.2, "q01_compile_ms": 30,
            "q01_warm_compiles": 0, "q01_programs": 9,
            "q01_device_time_s": 0.8, "q01_dispatch_overhead_s": 0.1,
            "q01_device_share": 0.89, "q01_timed": 9,
            # the roofline half (runtime/perf.py): provenance travels
            # WITH the carried q01 — a bound class judged on one
            # device must not describe another run's numbers
            "q01_hbm_bytes_est": 123456, "q01_hbm_util": 0.02,
            "q01_mfu_est": 0.001, "q01_bound": "dispatch-bound",
            "q01_device_kind": "TPU v4", "q01_trace_sample_rate": 1,
            "q01_trace_id": "a" * 32, "q01_query_id": "bench_1_1",
            # drift headline (runtime/stats.py) travels with the half
            "q01_qerror_max": 4.2, "q01_skew_ratio": 1.5,
            "q01_cache_miss_s": 0.9, "q01_cache_hit_s": 0.0004,
            "cache": {"q01": {"hit_speedup": 2250.0, "fp": "ab12cd34ef56"}},
            "q01_measured_at": "2026-08-01T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 2.0,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, now=_NOW)
    for k in bench._Q01_CARRY_KEYS:
        assert merged[k] == prev[k], k
    assert merged["q01_measured_at"] == "2026-08-01T00:00:00Z"
    # the q01 cache-provenance subblock travels with the carried half
    assert merged["cache"]["q01"] == prev["cache"]["q01"]
    # fresh q06 is stronger: its half (incl. profile keys) stays fresh
    assert merged["value"] == 2.0
    assert merged["measured_at"] == "2026-08-02T00:00:00Z"


def test_merge_cached_best_of_q06_keeps_profile_with_its_half():
    """When the cached q06 wins, its dispatch-floor profile keys must
    travel WITH it — pairing fresh counters with cached throughput
    would let a compile-polluted number masquerade as clean."""
    prev = {"backend": "tpu", "value": 10.0, "vs_baseline": 1.0,
            "dispatch_count": 1.0, "compile_ms": 100, "warm_compiles": 0,
            "programs": 3, "device_time_s": 0.5,
            "dispatch_overhead_s": 0.05, "timed": 3,
            "hbm_bytes_est": 999, "hbm_util": 0.5, "mfu_est": 0.1,
            "bound": "memory-bound",
            "device_kind": "TPU v4", "trace_sample_rate": 1,
            "measured_at": "2026-08-01T00:00:00Z",
            "q01_rows_per_sec": 5.0}
    fresh = {"backend": "tpu", "value": 4.0, "vs_baseline": 0.4,
             "dispatch_count": 9.0, "compile_ms": 5, "warm_compiles": 2,
             "programs": 40, "device_time_s": 0.1,
             "dispatch_overhead_s": 0.9, "timed": 10,
             "hbm_bytes_est": 111, "hbm_util": 0.01, "mfu_est": 0.001,
             "bound": "dispatch-bound",
             "device_kind": "cpu:0", "trace_sample_rate": 4,
             "measured_at": "2026-08-02T00:00:00Z",
             "q01_rows_per_sec": 6.0}
    merged = bench._merge_cached(fresh, prev, now=_NOW)
    assert merged["value"] == 10.0
    assert merged["programs"] == 3
    assert merged["device_time_s"] == 0.5
    assert merged["dispatch_overhead_s"] == 0.05
    assert merged["warm_compiles"] == 0
    assert merged["measured_at"] == "2026-08-01T00:00:00Z"
    # provenance travels WITH the winning half: its device_time_s is
    # only judgeable against the hardware/sampling that produced it
    assert merged["timed"] == 3
    assert merged["device_kind"] == "TPU v4"
    assert merged["trace_sample_rate"] == 1
    # the roofline judgment is PART of the winning half: pairing the
    # cached throughput with the fresh run's bound class would claim
    # a memory-bound number was dispatch-bound
    assert merged["hbm_bytes_est"] == 999
    assert merged["hbm_util"] == 0.5
    assert merged["mfu_est"] == 0.1
    assert merged["bound"] == "memory-bound"
    # q01 was freshly measured: it stays fresh
    assert merged["q01_rows_per_sec"] == 6.0


def test_merge_cached_old_format_winner_drops_fresh_profile_keys():
    """A cached q06 winner written by an OLDER bench (no profile keys)
    must not leave the fresh run's programs/device_time_s behind —
    that would pair one run's throughput with another run's split."""
    prev = {"backend": "tpu", "value": 10.0, "vs_baseline": 1.0,
            "dispatch_count": 1.0, "compile_ms": 100, "warm_compiles": 0,
            "measured_at": "2026-08-01T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 4.0, "vs_baseline": 0.4,
             "programs": 40, "device_time_s": 0.1,
             "dispatch_overhead_s": 0.9, "timed": 40,
             "hbm_bytes_est": 111, "hbm_util": 0.01, "mfu_est": 0.001,
             "bound": "dispatch-bound",
             "device_kind": "cpu:0", "trace_sample_rate": 1,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, now=_NOW)
    assert merged["value"] == 10.0
    assert "programs" not in merged
    assert "device_time_s" not in merged
    assert "dispatch_overhead_s" not in merged
    # fresh provenance must not describe the cached winner's numbers
    assert "timed" not in merged
    assert "device_kind" not in merged
    assert "trace_sample_rate" not in merged
    # ...nor may the fresh roofline judgment (an old-format winner has
    # no bound class: better absent than somebody else's)
    assert "hbm_bytes_est" not in merged
    assert "hbm_util" not in merged
    assert "mfu_est" not in merged
    assert "bound" not in merged


def test_merge_cached_cache_block_travels_per_half():
    """The ``cache`` provenance block is split per half: a cached q06
    winner brings ITS hit/miss split (or drops the fresh one when the
    old line predates the block), while a freshly measured q01 keeps
    its own subblock untouched."""
    prev = {"backend": "tpu", "value": 10.0,
            "q06_cache_miss_s": 0.5, "q06_cache_hit_s": 0.0002,
            "cache": {"q06": {"hit_speedup": 2500.0, "fp": "aa" * 6}},
            "q01_rows_per_sec": 5.0,
            "measured_at": "2026-08-01T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 4.0,
             "q06_cache_miss_s": 0.1, "q06_cache_hit_s": 0.01,
             "q01_cache_miss_s": 0.3, "q01_cache_hit_s": 0.0003,
             "cache": {"q06": {"hit_speedup": 10.0, "fp": "bb" * 6},
                       "q01": {"hit_speedup": 1000.0, "fp": "cc" * 6}},
             "q01_rows_per_sec": 6.0,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, now=_NOW)
    assert merged["q06_cache_miss_s"] == 0.5
    assert merged["q06_cache_hit_s"] == 0.0002
    assert merged["cache"]["q06"] == prev["cache"]["q06"]
    # q01 was freshly measured: its cache story stays fresh
    assert merged["cache"]["q01"] == fresh["cache"]["q01"]
    assert merged["q01_cache_hit_s"] == 0.0003
    # an old-format winner (no cache block) drops the fresh q06 story
    old_prev = {"backend": "tpu", "value": 10.0, "q01_rows_per_sec": 5.0,
                "measured_at": "2026-08-01T00:00:00Z"}
    merged = bench._merge_cached(dict(fresh), old_prev, now=_NOW)
    assert "q06_cache_miss_s" not in merged
    assert "q06" not in merged["cache"]
    assert merged["cache"]["q01"] == fresh["cache"]["q01"]


def test_merge_cached_non_tpu_prev_never_wins_best_of():
    # best-of selection requires BOTH halves on the tpu backend; the
    # q01 carry only fills a missing half (the cache file is only ever
    # written by tpu children, so prev is tpu in practice)
    prev = {"backend": "cpu", "value": 99.0, "q01_rows_per_sec": 1.0}
    fresh = {"backend": "tpu", "value": 2.0}
    merged = bench._merge_cached(fresh, prev, max_age_days=0)
    assert merged["value"] == 2.0
    assert merged["q01_rows_per_sec"] == 1.0


def test_emitted_line_with_profile_keys_fits_tail():
    result = dict(BASE, programs=12, device_time_s=1.2345,
                  dispatch_overhead_s=0.0123, dispatch_count=1.2,
                  compile_ms=15000, warm_compiles=0,
                  q01_programs=9, q01_device_time_s=4.5678,
                  q01_dispatch_overhead_s=0.0456, q01_rows_per_sec=5.0,
                  q01_vs_baseline=0.5, q01_dispatch_count=1.1,
                  q01_compile_ms=20000, q01_warm_compiles=0,
                  q01_measured_at="2026-08-03T00:00:00Z",
                  tunnel_bytes_per_sec=1e6, cached=True,
                  cache_age_s=100.0)
    line = _emit_line(result, [{"t": "a", "ok": True}] * 50, [])
    assert len(line) < 1500
    d = json.loads(line)
    assert d["programs"] == 12 and d["q01_device_time_s"] == 4.5678


def test_tpu_env_scrubs_only_cpu_forcing_values(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8 "
                       "--xla_dump_to=/tmp/d")
    env = bench._tpu_env()
    # the REAL axon env must pass through (popping it blinds probes)
    assert env["JAX_PLATFORMS"] == "axon"
    assert env["PALLAS_AXON_POOL_IPS"] == "127.0.0.1"
    assert env["XLA_FLAGS"] == "--xla_dump_to=/tmp/d"
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    env = bench._tpu_env()
    assert "JAX_PLATFORMS" not in env and "PALLAS_AXON_POOL_IPS" not in env


# --------------------------- stale-cache guard (PR 19 satellite)


def test_merge_cached_drops_stale_q01_half():
    """A carried half older than spark.blaze.bench.maxCacheAgeDays is
    refused — the kernels it measured predate too many engine changes
    to caption a fresh line — and the refusal is recorded."""
    prev = {"backend": "tpu", "value": 1.0,
            "measured_at": "2026-08-01T00:00:00Z",
            "q01_rows_per_sec": 5.0,
            "q01_measured_at": "2026-07-20T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 2.0,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, max_age_days=3, now=_NOW)
    assert merged.get("q01_rows_per_sec") is None
    assert "q01_measured_at" not in merged
    assert merged["cache_stale_dropped"] == ["q01"]


def test_merge_cached_stale_q06_winner_loses_best_of():
    """A stronger-but-stale cached q06 must NOT win best-of: the fresh
    (weaker) number stands and gets re-measured on its own merits."""
    prev = {"backend": "tpu", "value": 10.0,
            "measured_at": "2026-07-20T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 4.0,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, max_age_days=3, now=_NOW)
    assert merged["value"] == 4.0
    assert merged["measured_at"] == "2026-08-02T00:00:00Z"
    assert merged["cache_stale_dropped"] == ["q06"]


def test_merge_cached_age_guard_zero_disables():
    prev = {"backend": "tpu", "value": 10.0,
            "measured_at": "1999-01-01T00:00:00Z",
            "q01_rows_per_sec": 5.0,
            "q01_measured_at": "1999-01-01T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 4.0}
    merged = bench._merge_cached(fresh, prev, max_age_days=0, now=_NOW)
    assert merged["value"] == 10.0
    assert merged["q01_rows_per_sec"] == 5.0
    assert "cache_stale_dropped" not in merged


def test_merge_cached_unparseable_stamp_counts_as_stale():
    # a half that cannot PROVE its age is not carried
    assert bench._stale(None, 3, _NOW)
    assert bench._stale("not-a-date", 3, _NOW)
    assert not bench._stale("2026-08-02T00:00:00Z", 3, _NOW)
    prev = {"backend": "tpu", "value": 1.0, "q01_rows_per_sec": 5.0}
    fresh = {"backend": "tpu", "value": 2.0}
    merged = bench._merge_cached(fresh, prev, max_age_days=3, now=_NOW)
    assert merged.get("q01_rows_per_sec") is None
    assert merged["cache_stale_dropped"] == ["q01"]


def test_merge_cached_device_share_travels_with_half():
    """qNN_device_share (the majority-device headline) is part of each
    half's profile and must carry/drop WITH that half."""
    assert "q01_device_share" in bench._Q01_CARRY_KEYS
    assert "q06_device_share" in bench._Q06_BEST_OF_KEYS
    prev = {"backend": "tpu", "value": 10.0,
            "measured_at": "2026-08-01T00:00:00Z",
            "q06_device_share": 0.82,
            "q01_rows_per_sec": 5.0,
            "q01_device_share": 0.64,
            "q01_measured_at": "2026-08-01T00:00:00Z"}
    fresh = {"backend": "tpu", "value": 4.0, "q06_device_share": 0.2,
             "measured_at": "2026-08-02T00:00:00Z"}
    merged = bench._merge_cached(fresh, prev, now=_NOW)
    assert merged["q06_device_share"] == 0.82  # cached winner's share
    assert merged["q01_device_share"] == 0.64  # carried with the half
