"""The bench driver-line contract: the ONE stdout JSON line must fit
the driver's 2000-char stdout tail (round-4 postmortem: embedded
probe/watchdog logs pushed the metric head off the capture and
BENCH_r04 parsed null)."""

import contextlib
import io
import json
import os
import sys

# repo root (cwd-independent): bench.py is not a package member
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _emit_line(result, probe_log, wd_log):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit(dict(result), probe_log, wd_log)
    return buf.getvalue().strip()


BASE = {"metric": "tpch_q06_rows_per_sec_per_chip", "value": 1.0,
        "unit": "rows/s", "vs_baseline": 0.1}


def test_line_always_fits_driver_tail():
    huge_probes = [{"t": f"2026-07-31T{i % 24:02d}:00:00Z", "ok": False}
                   for i in range(500)]
    huge_wd = [{"t": "t", "event": "probe", "ok": False}] * 2000
    line = _emit_line(dict(BASE, note="x" * 3000), huge_probes, huge_wd)
    assert len(line) < 1500
    d = json.loads(line)
    assert d["metric"] == BASE["metric"] and d["value"] == 1.0


def test_summary_counts_only_probe_events():
    s = bench._log_summary([
        {"t": "a", "event": "probe", "ok": True},
        {"t": "b", "event": "measuring"},
        {"t": "c", "event": "measure", "rc": 0},
        {"t": "d", "event": "probe", "ok": False},
    ])
    assert s == {"probes": 2, "ok": 1, "first": "a", "last": "d",
                 "last_ok": "a"}


def test_summary_empty():
    assert bench._log_summary([]) == {"probes": 0, "ok": 0}


def test_tpu_env_scrubs_only_cpu_forcing_values(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8 "
                       "--xla_dump_to=/tmp/d")
    env = bench._tpu_env()
    # the REAL axon env must pass through (popping it blinds probes)
    assert env["JAX_PLATFORMS"] == "axon"
    assert env["PALLAS_AXON_POOL_IPS"] == "127.0.0.1"
    assert env["XLA_FLAGS"] == "--xla_dump_to=/tmp/d"
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    env = bench._tpu_env()
    assert "JAX_PLATFORMS" not in env and "PALLAS_AXON_POOL_IPS" not in env
