"""Fault-tolerant stage execution under deterministic fault injection.

≙ the recovery tiers the reference inherits from Spark (task retry,
FetchFailedException -> map-stage regeneration, RSS commit/abort) —
here proven in-tree with the seeded injection registry
(runtime/faults.py): every scenario injects a failure at a named site,
asserts the query recovers to a result identical to the fault-free
run, and checks the retry/fetch counters in the scheduler metrics.
"""

import os
import struct
import time

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.parallel.shuffle import (
    HashPartitioning, IpcReaderExec, LocalShuffleManager,
    ShuffleRepartitioner,
)
from blaze_tpu.runtime import faults
from blaze_tpu.runtime.context import RESOURCES, TaskContext
from blaze_tpu.runtime.metrics import MetricNode, MetricsSet
from blaze_tpu.runtime.retry import (
    FETCH_FAILED, RETRY, FetchFailedError, RetryPolicy, TaskRetriesExhausted,
    classify,
)
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import BlazeSparkSession

import spark_fixtures as F


@pytest.fixture(autouse=True, scope="module")
def _lock_order_assertions():
    """The fault suite drives retries/reruns across spill, shuffle,
    and staging threads — the module runs with the runtime lock-order
    assertion armed (analysis/locks.py), so an inverted acquisition
    raises LockOrderError here instead of deadlocking rarely, AND with
    the error-escape recorder + resource ledger armed
    (spark.blaze.verify.errors): a FATAL-class error absorbed at an
    audited broad-except site, or a resource still live at query end,
    fails the module instead of vanishing into a recovery path."""
    from blaze_tpu.analysis import locks as lock_verify
    from blaze_tpu.runtime import errors, ledger

    conf.VERIFY_LOCKS.set(True)
    lock_verify.refresh()
    conf.VERIFY_ERRORS.set(True)
    errors.refresh()
    ledger.refresh()
    yield
    escaped = errors.escapes()
    leaked = ledger.leaks()
    conf.VERIFY_LOCKS.set(False)
    lock_verify.refresh()
    conf.VERIFY_ERRORS.set(False)
    errors.refresh()
    ledger.refresh()
    assert escaped == [], (
        "FATAL-class error absorbed at an audited site during the "
        "fault suite: " + "; ".join(escaped))
    assert leaked == [], (
        "resource-ledger leaks during the fault suite: "
        + "; ".join(leaked))


@pytest.fixture(autouse=True)
def _clean_faults():
    """Deterministic, sleep-free fault runs; always clear the spec."""
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    faults.reset()
    yield
    conf.FAULTS_SPEC.set("")
    conf.TASK_RETRY_BACKOFF.set(0.1)
    conf.TASK_TIMEOUT.set(0.0)
    faults.reset()


def _inject(spec: str) -> None:
    conf.FAULTS_SPEC.set(spec)
    faults.reset()


# ------------------------------------------------------ registry unit tests

def test_spec_parse_format_roundtrip():
    rules = faults.parse_spec(
        "shuffle.fetch@2,task.compute@1@a0,shuffle.write@1@a0@slow250")
    assert rules == [("shuffle.fetch", 2, None, None, False),
                     ("task.compute", 1, 0, None, False),
                     ("shuffle.write", 1, 0, 250, False)]
    assert faults.parse_spec(faults.format_spec(rules)) == rules
    # modifier order is free: slow before attempt parses the same
    assert faults.parse_spec("shuffle.write@1@slow250@a0") == \
        [("shuffle.write", 1, 0, 250, False)]
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.parse_spec("bogus.site@1")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.parse_spec("task.compute")
    with pytest.raises(ValueError, match="bad modifier"):
        faults.parse_spec("task.compute@1@x3")
    with pytest.raises(ValueError, match="duplicate slow"):
        faults.parse_spec("task.compute@1@slow5@slow6")


def test_random_spec_deterministic():
    assert faults.random_spec(42) == faults.random_spec(42)
    assert faults.random_spec(42) != faults.random_spec(43)
    for site, _, attempt, slow_ms, oom in faults.parse_spec(
            faults.random_spec(42)):
        assert site in faults.SITES
        assert attempt == 0  # recoverable by construction
        assert slow_ms is None and not oom
    # straggler entries: seeded latency, ungated (the one-shot hit
    # counter guarantees the delay is paid exactly once either way)
    spec = faults.random_spec(42, n_stragglers=2)
    assert spec == faults.random_spec(42, n_stragglers=2)
    slows = [r for r in faults.parse_spec(spec) if r[3] is not None]
    assert slows and all(a is None for _, _, a, _, _ in slows)
    assert all(250 <= ms <= 600 for _, _, _, ms, _ in slows)


def test_straggler_rule_sleeps_instead_of_raising():
    import time as _time

    inj = faults.FaultInjector(faults.parse_spec("task.compute@1@a0@slow80"))
    t0 = _time.monotonic()
    inj.hit("task.compute", attempt=0)  # matching hit: sleeps, no raise
    assert _time.monotonic() - t0 >= 0.07
    t0 = _time.monotonic()
    inj.hit("task.compute", attempt=0)  # hit 2: rule already passed
    assert _time.monotonic() - t0 < 0.05
    # attempt-gated: a backup attempt (different id) never pays it
    inj2 = faults.FaultInjector(faults.parse_spec("task.compute@1@a0@slow80"))
    t0 = _time.monotonic()
    inj2.hit("task.compute", attempt=100)
    assert _time.monotonic() - t0 < 0.05


def test_injector_nth_hit_and_attempt_gate():
    inj = faults.FaultInjector(faults.parse_spec("task.compute@3@a0"))
    inj.hit("task.compute", attempt=0)
    inj.hit("task.compute", attempt=0)
    with pytest.raises(faults.InjectedFault, match="hit 3"):
        inj.hit("task.compute", attempt=0)
    # 4th hit (e.g. the retried attempt) passes: single-fire
    inj.hit("task.compute", attempt=1)
    # attempt gate: rule for a0 never fires for attempt 1
    inj2 = faults.FaultInjector(faults.parse_spec("task.compute@1@a0"))
    inj2.hit("task.compute", attempt=1)  # hit 1, wrong attempt -> no raise
    inj2.hit("task.compute", attempt=0)  # hit 2 -> rule already passed


def test_classify_and_backoff_determinism():
    assert classify(FetchFailedError("shuffle_3", 0)) == FETCH_FAILED
    assert classify(RuntimeError("x")) == RETRY
    assert classify(AssertionError()) == "fatal"
    assert classify(NotImplementedError()) == "fatal"
    assert FetchFailedError("shuffle_7", 1).shuffle_id == 7
    assert FetchFailedError("broadcast_7", 1).shuffle_id is None
    p = RetryPolicy(max_attempts=4, backoff_base=0.1)
    assert p.backoff(1, 2, 0) == p.backoff(1, 2, 0)  # deterministic
    assert p.backoff(1, 2, 1) != p.backoff(1, 2, 0)  # attempt-keyed
    assert RetryPolicy(backoff_base=0.0).backoff(0, 0, 0) == 0.0


def test_ipc_reader_missing_block_raises_fetch_failed():
    schema = Schema([Field("x", DataType.int64())])
    reader = IpcReaderExec(schema, "shuffle_9", 1)
    RESOURCES.put("shuffle_9.0", [("/nonexistent/block.data", 0, 128)])
    with pytest.raises(FetchFailedError) as ei:
        list(reader.execute(0, TaskContext(0, 1)))
    assert ei.value.shuffle_id == 9


def test_ipc_reader_corrupt_payload_raises_fetch_failed():
    """A committed block whose bytes survive the frame read but fail
    batch DECODE is still bad producer bytes: it must classify as
    FETCH_FAILED (regenerate the map stage), not RETRY (re-read the
    same corrupt file until the budget burns out)."""
    import struct as _struct

    schema = Schema([Field("x", DataType.int64())])
    reader = IpcReaderExec(schema, "shuffle_11", 1)
    garbage = b"\x99" * 32  # valid frame envelope, undecodable payload
    frame = _struct.pack("<IB", len(garbage), 0) + garbage  # codec 0 = none
    RESOURCES.put("shuffle_11.0", [frame])
    with pytest.raises(FetchFailedError) as ei:
        list(reader.execute(0, TaskContext(0, 1)))
    assert ei.value.shuffle_id == 11


# ------------------------------------------------- scheduler recovery paths

from test_spark_convert import make_session, q6_like_plan  # noqa: E402


def _scheduler_run(sess, plan_json, metrics=None):
    from blaze_tpu.batch import batch_to_pydict

    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    out = {f.name: [] for f in stages[-1].plan.schema.fields}
    for b in run_stages(stages, manager, metrics=metrics):
        d = batch_to_pydict(b)
        for k in out:
            out[k].extend(d[k])
    return out, manager


def test_recovers_from_task_compute_fault():
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _inject("task.compute@2@a0")  # crash the 2nd task's first attempt
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("task_retries") == 1
    assert m.metrics.get("task_attempts") >= 2


def test_recovers_from_shuffle_fetch_fault_by_map_stage_rerun():
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _inject("shuffle.fetch@1@a0")  # first reduce-side block read fails
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("fetch_failures") == 1
    assert m.metrics.get("map_stage_reruns") == 1
    # the regenerated map stage re-ran its tasks on top of the originals
    assert m.metrics.get("task_attempts") > 4


def test_recovers_from_shuffle_write_fault_without_partial_commit():
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    _inject("shuffle.write@1")  # first map task's commit fails
    m = MetricNode()
    got, manager = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("task_retries") == 1
    # abort left no torn temp files behind in the shuffle root
    leftovers = [f for f in os.listdir(manager.root) if "inprogress" in f]
    assert leftovers == []


def test_exhausted_retries_surface_site_stage_task():
    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    # every attempt of the first task fails (attempt-gated so the hit
    # counter tracks the retry loop exactly)
    _inject("task.compute@1@a0,task.compute@2@a1,task.compute@3@a2")
    plan = sess.plan(plan_json)
    stages, manager = split_stages(plan)
    with pytest.raises(TaskRetriesExhausted) as ei:
        list(run_stages(stages, manager, max_task_attempts=3))
    msg = str(ei.value)
    assert "stage 0" in msg and "task 0" in msg and "3 attempt" in msg
    assert "task.compute" in msg  # terminal error names the failing site
    assert isinstance(ei.value.__cause__, faults.InjectedFault)


def test_range_boundary_pass_recovers_from_fetch_failure():
    """The driver-side range-boundary sampling pass reads upstream
    shuffle blocks too; a fetch failure there must trigger the same
    map-stage regeneration as a task-side failure, not abort the
    query."""
    sess, _ = make_session()
    s = F.scan("lineitem", [F.attr("l_extendedprice", 2)])
    ex1 = F.shuffle(F.hash_partitioning([F.attr("l_extendedprice", 2)], 3), s)
    pr = F.project([F.attr("l_extendedprice", 2)], ex1)
    ex2 = F.shuffle(
        F.range_partitioning([F.sort_order(F.attr("l_extendedprice", 2))], 3),
        pr,
    )
    srt = F.sort([F.sort_order(F.attr("l_extendedprice", 2))], ex2)
    plan_json = F.flatten(srt)
    baseline, _ = _scheduler_run(sess, plan_json)
    assert baseline["l_extendedprice"] == sorted(baseline["l_extendedprice"])

    _inject("shuffle.fetch@1@a0")  # first fetch = the boundary pass read
    m = MetricNode()
    got, _ = _scheduler_run(sess, plan_json, metrics=m)
    assert got == baseline
    assert m.metrics.get("fetch_failures") >= 1
    assert m.metrics.get("map_stage_reruns") >= 1


def test_unresolvable_fetch_failure_falls_back_to_plain_retry():
    """A FetchFailedError whose producer can't be resolved (e.g. a
    broadcast read) must consume the plain retry budget instead of
    being instantly terminal — the blobs re-register every attempt, so
    a re-run can succeed."""
    from blaze_tpu.serde import from_proto

    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    real_run_task = from_proto.run_task
    fails = {"n": 1}

    def flaky(td, **kw):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise FetchFailedError("broadcast_0", 0)
        return real_run_task(td, **kw)

    from_proto.run_task = flaky
    try:
        m = MetricNode()
        got, _ = _scheduler_run(sess, plan_json, metrics=m)
    finally:
        from_proto.run_task = real_run_task
    assert got == baseline
    assert fails["n"] == 0
    assert m.metrics.get("fetch_failures") == 1
    assert m.metrics.get("task_retries") == 1
    assert m.metrics.get("map_stage_reruns") == 0


def test_task_timeout_is_retried():
    import time as _time

    from blaze_tpu.serde import from_proto

    sess, _ = make_session()
    plan_json = F.flatten(q6_like_plan())
    baseline, _ = _scheduler_run(sess, plan_json)

    conf.TASK_TIMEOUT.set(0.2)
    real_run_task = from_proto.run_task
    # the timeout is checked between OUTPUT batches, so drag the result
    # task (call #4 after the 3 map tasks) — map tasks yield nothing
    state = {"calls": 0, "dragged": 0}

    def slow_run_task(td, **kw):
        gen = real_run_task(td, **kw)
        state["calls"] += 1
        if state["calls"] == 4:
            state["dragged"] += 1

            def dragging():
                for b in gen:
                    _time.sleep(0.3)  # trip the cooperative deadline
                    yield b

            return dragging()
        return gen

    from_proto.run_task = slow_run_task
    try:
        m = MetricNode()
        got, _ = _scheduler_run(sess, plan_json, metrics=m)
    finally:
        from_proto.run_task = real_run_task
    assert got == baseline
    assert state["dragged"] == 1
    assert m.metrics.get("task_timeouts") == 1


# --------------------------------------------------------- rss commit/abort

def _lineitem_rss_node(writer_rid: str):
    from blaze_tpu.exprs import col
    from blaze_tpu.parallel.rss import RssShuffleWriterExec

    rng = np.random.RandomState(11)
    schema = Schema([
        Field("l_orderkey", DataType.int64()),
        Field("l_extendedprice", DataType.int64()),
    ])
    data = {
        "l_orderkey": [int(v) for v in rng.randint(1, 200, 300)],
        "l_extendedprice": [int(v) for v in rng.randint(100, 9999, 300)],
    }
    scan = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    part = HashPartitioning([col("l_orderkey")], 3)
    return RssShuffleWriterExec(scan, part, writer_rid)


def test_rss_push_fault_aborts_then_retry_commits_identically():
    from blaze_tpu.parallel.rss import LocalRssWriter

    node = _lineitem_rss_node("rss_flt")

    # fault-free reference pushes
    ref = LocalRssWriter()
    RESOURCES.put("rss_flt.0", ref)
    for _ in node.execute(0, TaskContext(0, 1)):
        pass
    assert ref.closed and ref.partitions

    # attempt 0 dies mid-push: the writer must ABORT — no partial
    # pushes may ever count toward the reduce barrier
    _inject("rss.push@2@a0")
    w0 = LocalRssWriter()
    RESOURCES.put("rss_flt.0", w0)
    with pytest.raises(faults.InjectedFault):
        for _ in node.execute(0, TaskContext(0, 1, task_attempt_id=0)):
            pass
    assert w0.closed and w0.partitions == {}  # aborted, nothing committed

    # retry (fresh attempt id, fresh writer) commits bit-identically
    w1 = LocalRssWriter()
    RESOURCES.put("rss_flt.0", w1)
    for _ in node.execute(0, TaskContext(0, 1, task_attempt_id=1)):
        pass
    assert w1.closed
    assert w1.partitions == ref.partitions


def test_rss_concurrent_attempt_race_single_committed_writer():
    """Speculation race through the RSS attempt-id seam: two concurrent
    attempts of the SAME map task push through RssPartitionWriterBase
    (each reading its writer through an attempt-scoped resource view,
    exactly as the speculative runner stages them), the straggling
    loser is cancelled and ``abort()``s, and the reduce side sees
    exactly ONE committed attempt, byte-identical to an undisturbed
    run."""
    import threading as _threading

    from blaze_tpu.exprs import col
    from blaze_tpu.parallel.rss import LocalRssWriter, RssShuffleWriterExec
    from blaze_tpu.parallel.shuffle import HashPartitioning
    from blaze_tpu.runtime.context import ScopedResources

    rng = np.random.RandomState(23)
    schema = Schema([
        Field("l_orderkey", DataType.int64()),
        Field("l_extendedprice", DataType.int64()),
    ])
    # several batches so the loser hits a cancellation checkpoint
    # between pushes (cancellation is cooperative, per batch)
    batches = [
        batch_from_pydict({
            "l_orderkey": [int(v) for v in rng.randint(1, 200, 100)],
            "l_extendedprice": [int(v) for v in rng.randint(100, 9999, 100)],
        }, schema)
        for _ in range(4)
    ]
    scan = MemoryScanExec([list(batches)], schema)
    node = RssShuffleWriterExec(
        scan, HashPartitioning([col("l_orderkey")], 3), "rss_race")

    def drive(ctx):
        for _ in node.execute(0, ctx):
            pass

    # undisturbed reference commit
    ref = LocalRssWriter()
    RESOURCES.put("rss_race.0", ref)
    drive(TaskContext(0, 1))
    assert ref.closed and ref.partitions

    # attempt 0 straggles on its first push; attempt 100 (speculative
    # id range) runs clean — each pops its OWN scoped registration
    _inject("rss.push@1@a0@slow400")
    w0, w1 = LocalRssWriter(), LocalRssWriter()
    RESOURCES.put("rss_race.0#a0", w0)
    RESOURCES.put("rss_race.0#a100", w1)
    cancel0 = _threading.Event()
    ctx0 = TaskContext(0, 1, task_attempt_id=0, cancel_event=cancel0,
                       resources=ScopedResources(
                           RESOURCES, {"rss_race.0": "rss_race.0#a0"}))
    ctx1 = TaskContext(0, 1, task_attempt_id=100,
                       resources=ScopedResources(
                           RESOURCES, {"rss_race.0": "rss_race.0#a100"}))
    t0 = _threading.Thread(target=drive, args=(ctx0,), daemon=True)
    t0.start()
    time.sleep(0.05)          # let the loser enter its straggling push
    drive(ctx1)               # the backup races past it and commits
    assert w1.closed and w1.partitions == ref.partitions
    cancel0.set()             # first commit won: cancel the loser
    t0.join(timeout=10)
    assert not t0.is_alive()
    # loser aborted: closed WITHOUT committing — nothing of its partial
    # push set may ever reach the reduce barrier
    assert w0.closed and w0.partitions == {}


# ------------------------------------------------------ spill / write abort

def test_spill_write_fault_aborts_without_losing_rows(tmp_path):
    schema = Schema([Field("x", DataType.int64())])
    rep = ShuffleRepartitioner(schema, 1, MetricsSet())
    n = 1000
    b = batch_from_pydict({"x": list(range(n))}, schema).to_host()
    rep.insert_sorted(b, np.array([n]))
    assert rep._buffered_bytes > 0

    _inject("spill.write@1")
    with pytest.raises(faults.InjectedFault):
        rep.spill()
    # spill-abort: buffers intact, no phantom spill recorded
    assert rep._buffered_bytes > 0
    assert rep._spills == []

    _inject("")  # clear; write_output must still see every row
    data, index = str(tmp_path / "s.data"), str(tmp_path / "s.index")
    lengths = rep.write_output(data, index)
    assert sum(lengths) > 0
    from blaze_tpu.io.batch_serde import deserialize_batch
    from blaze_tpu.io.ipc_compression import IpcFrameReader

    with open(index, "rb") as f:
        raw = f.read()
    offsets = struct.unpack(f"<{len(raw)//8}Q", raw)
    with open(data, "rb") as f:
        payloads = list(IpcFrameReader(f, offsets[-1]))
    rows = sum(deserialize_batch(p, schema).num_rows for p in payloads)
    assert rows == n


def test_shuffle_write_fault_commits_nothing(tmp_path):
    """A failed map attempt leaves neither .data nor .index, so the
    reduce barrier (index existence) can never see partial output."""
    from blaze_tpu.parallel.shuffle import ShuffleWriterExec, SinglePartitioning

    schema = Schema([Field("x", DataType.int64())])
    scan = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(64))}, schema)]], schema
    )
    manager = LocalShuffleManager(str(tmp_path))
    data, index = manager.map_output_paths(0, 0)
    node = ShuffleWriterExec(scan, SinglePartitioning(), data, index)

    _inject("shuffle.write@1")
    with pytest.raises(faults.InjectedFault):
        for _ in node.execute(0, TaskContext(0, 1)):
            pass
    assert not os.path.exists(data) and not os.path.exists(index)
    assert manager.reduce_blocks(0, 1, 0) == []  # barrier sees no commit

    _inject("")
    node2 = ShuffleWriterExec(scan, SinglePartitioning(), data, index)
    for _ in node2.execute(0, TaskContext(0, 1)):
        pass
    assert os.path.exists(data) and os.path.exists(index)
    assert manager.invalidate(0) >= 2  # fetch-recovery cleanup hook


# ------------------------------------------------- TPC-H end-to-end matrix

@pytest.mark.slow
def test_tpch_q1_bit_identical_under_fault_matrix():
    """Acceptance: a multi-stage TPC-H query under injected
    shuffle-fetch failure (upstream map-stage re-run), map-task crash,
    and shuffle-write failure recovers to results bit-identical to the
    fault-free run, with the recovery visible in metrics."""
    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
    from blaze_tpu.tpch.datagen import generate_all, table_to_batches

    data = generate_all(0.001)
    scans = {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], 2, batch_rows=4096),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }

    def run(metrics=None):
        plan = build_query("q1", scans, 2)
        stages, manager = split_stages(plan)
        out = {f.name: [] for f in stages[-1].plan.schema.fields}
        for b in run_stages(stages, manager, metrics=metrics):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
        return out

    baseline = run()
    scenarios = {
        "shuffle.fetch@1@a0": ("fetch_failures", "map_stage_reruns"),
        "task.compute@2@a0": ("task_retries",),
        "shuffle.write@1": ("task_retries",),
    }
    for spec, counters in scenarios.items():
        _inject(spec)
        m = MetricNode()
        assert run(metrics=m) == baseline, f"mismatch under {spec}"
        for c in counters:
            assert m.metrics.get(c) >= 1, f"{c} not counted under {spec}"


# ---------------------------------------------------- worker-process retry

@pytest.mark.slow
def test_worker_process_crash_is_retried(tmp_path):
    """Testenv tier: a worker process that dies on its first attempt
    (nonzero exit, no committed output file) is re-launched by the
    driver with a fresh attempt id and succeeds."""
    import base64

    from blaze_tpu.io.batch_serde import deserialize_batch
    from blaze_tpu.ops import ParquetSinkExec
    from blaze_tpu.runtime.scheduler import build_task
    from blaze_tpu.runtime.worker import run_worker_with_retry

    schema = Schema([Field("x", DataType.int64())])
    src = MemoryScanExec(
        [[batch_from_pydict({"x": list(range(100))}, schema)]], schema
    )
    pq = str(tmp_path / "in.parquet")
    sink = ParquetSinkExec(src, pq)
    for _ in sink.execute(0, TaskContext(0, 1)):
        pass
    pq = sink.written_files[0] if sink.written_files else pq

    from blaze_tpu.ops import ParquetScanExec

    plan = ParquetScanExec([[pq]], schema)
    stages, manager = split_stages(plan, LocalShuffleManager(str(tmp_path / "sh")))
    _, td = build_task(stages[-1], manager, 0)
    out = str(tmp_path / "r.frames")
    spec = {
        "task_def": base64.b64encode(td).decode(),
        "partition": 0,
        "shuffle_root": manager.root,
        "readers": [],
        "output": out,
    }
    env = {
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BLAZE_FAULTS_SPEC": "task.compute@1@a0",  # kill the 1st attempt
        "BLAZE_TASK_RETRYBACKOFF": "0",
    }
    winning = run_worker_with_retry(spec, str(tmp_path), "t0",
                                    max_attempts=3, env=env)
    assert winning == 1  # first attempt crashed, second committed
    from blaze_tpu.runtime.worker import read_result_frames

    vals = []
    for b in read_result_frames(out, schema):
        vals.extend(int(v) for v in np.asarray(b.columns[0].data)[: b.num_rows])
    assert vals == list(range(100))
