"""Serializable broadcast JoinHashMap.

≙ reference joins/join_hash_map.rs:290-454 (raw-bytes map serde),
broadcast_join_build_hash_map_exec.rs:41, and the per-executor cache
keyed by broadcast id (broadcast_join_exec.rs:456-560): the MAP is what
crosses the broadcast, probe executors rebuild it with buffer copies
only, and re-instantiated plans hit the executor-wide cache.
"""

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.joins import (
    BroadcastJoinBuildHashMapExec,
    BroadcastJoinExec,
    JoinMap,
    JoinType,
    clear_join_map_cache,
)
from blaze_tpu.ops.joins.core import build_join_map, make_build_kernel
from blaze_tpu.parallel.broadcast import BroadcastExchangeExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

BUILD_SCHEMA = Schema([Field("k", DataType.int64()), Field("b", DataType.string(8))])
PROBE_SCHEMA = Schema([Field("k", DataType.int64()), Field("p", DataType.int32())])

BUILD_DATA = {"k": [1, 2, 2, None, 5], "b": ["x", "y", "yy", "n", "z"]}
PROBE_DATA = {"k": [2, 1, 7, None, 5, 2], "p": [10, 20, 30, 40, 50, 60]}


def _build_exec():
    return MemoryScanExec([[batch_from_pydict(BUILD_DATA, BUILD_SCHEMA)]], BUILD_SCHEMA)


def _probe_exec():
    return MemoryScanExec([[batch_from_pydict(PROBE_DATA, PROBE_SCHEMA)]], PROBE_SCHEMA)


def _run(join: BroadcastJoinExec):
    rows = []
    for p in range(join.num_partitions()):
        for b in join.execute(p, TaskContext(p, join.num_partitions())):
            d = batch_to_pydict(b)
            rows += list(zip(*[d[f.name] for f in join.schema.fields]))
    return sorted(rows, key=repr)


def _map_build_side():
    """BroadcastExchange(BuildHashMap(build)) — the serialized map rides
    the normal broadcast IPC path as a one-row binary batch."""
    return BroadcastExchangeExec(BroadcastJoinBuildHashMapExec(_build_exec(), [col("k")]))


@pytest.mark.parametrize(
    "jt", [JoinType.INNER, JoinType.LEFT, JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
           JoinType.EXISTENCE]
)
def test_map_mode_matches_legacy(jt):
    clear_join_map_cache()
    legacy = BroadcastJoinExec(
        _build_exec(), _probe_exec(), [col("k")], [col("k")], jt, build_is_left=False
    )
    mapped = BroadcastJoinExec(
        _map_build_side(), _probe_exec(), [col("k")], [col("k")], jt,
        build_is_left=False, cached_build_id="bc_test_1",
    )
    assert _run(mapped) == _run(legacy)


def test_serialize_deserialize_roundtrip():
    kern = make_build_kernel(BUILD_SCHEMA, [col("k")])
    jmap = build_join_map(batch_from_pydict(BUILD_DATA, BUILD_SCHEMA), kern)
    rt = JoinMap.deserialize(jmap.serialize(), BUILD_SCHEMA)
    assert rt.num_rows == jmap.num_rows
    np.testing.assert_array_equal(np.asarray(rt.sorted_keys), np.asarray(jmap.sorted_keys))
    np.testing.assert_array_equal(np.asarray(rt.sorted_rows), np.asarray(jmap.sorted_rows))
    assert batch_to_pydict(rt.batch) == batch_to_pydict(jmap.batch)


def test_per_executor_cache_hit():
    clear_join_map_cache()
    build = _map_build_side()

    def mk():
        return BroadcastJoinExec(
            build, _probe_exec(), [col("k")], [col("k")], JoinType.INNER,
            build_is_left=False, cached_build_id="bc_cache_test",
        )

    first = mk()
    out1 = _run(first)
    # a RE-INSTANTIATED plan (new exec object, e.g. task retry /
    # re-planning) must hit the executor-wide cache, not rebuild
    second = mk()
    out2 = _run(second)
    assert out1 == out2
    assert second.metrics.get("hashmap_cache_hit") >= 1
    assert first.metrics.get("hashmap_cache_hit") == 0


def test_map_mode_proto_roundtrip():
    clear_join_map_cache()
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    mapped = BroadcastJoinExec(
        BroadcastJoinBuildHashMapExec(_build_exec(), [col("k")]),
        _probe_exec(), [col("k")], [col("k")], JoinType.INNER,
        build_is_left=False, cached_build_id="bc_proto_test",
    )
    rt = plan_from_proto(plan_to_proto(mapped))
    assert _run(rt) == _run(mapped)
