"""Parquet subset: write/read roundtrips, scan exec with row-group
pruning, sink with dynamic partitioning.

≙ the reference's parquet path (parquet_exec.rs scan + page filtering,
parquet_sink_exec.rs incl. hive dynamic partitions)."""

import datetime
import glob
import os

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.io import parquet as pq
from blaze_tpu.ops import MemoryScanExec, ParquetScanExec, ParquetSinkExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([
    Field("i", DataType.int64()),
    Field("s", DataType.string(16)),
    Field("d", DataType.decimal(12, 2)),
    Field("day", DataType.date32()),
    Field("f", DataType.float64()),
    Field("b", DataType.bool_()),
])


def _cols(n, base=0):
    rng = np.random.RandomState(42 + base)
    data = np.arange(base, base + n, dtype=np.int64)
    validity = (data % 7 != 3)
    svals = np.zeros((n, 16), np.uint8)
    slens = np.zeros(n, np.int32)
    for i in range(n):
        b = f"row-{base + i}".encode()
        svals[i, : len(b)] = np.frombuffer(b, np.uint8)
        slens[i] = len(b)
    return {
        "i": (data, validity, None),
        "s": (svals, np.ones(n, bool), slens),
        "d": (data * 100 + 25, None, None),
        "day": ((data % 3000).astype(np.int32), None, None),
        "f": (rng.uniform(-1, 1, n), None, None),
        "b": ((data % 2 == 0), None, None),
    }


def test_roundtrip(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_parquet(path, SCHEMA, _cols(100), row_group_rows=40)
    meta = pq.read_metadata(path)
    assert meta.num_rows == 100
    assert len(meta.row_groups) == 3
    total = 0
    for rg in meta.row_groups:
        ch = rg.chunks["i"]
        data, validity, _ = pq.read_column_chunk(path, ch, DataType.int64())
        expected = np.arange(total, total + rg.rows)
        vmask = expected % 7 != 3
        assert (validity == vmask).all()
        assert (data[validity] == expected[vmask]).all()
        sdata, svalid, slen = pq.read_column_chunk(path, rg.chunks["s"], DataType.string(16))
        assert bytes(sdata[0][: slen[0]]) == f"row-{total}".encode()
        total += rg.rows
    assert total == 100


def test_scan_exec_and_pruning(tmp_path):
    p1 = str(tmp_path / "a.parquet")
    p2 = str(tmp_path / "b.parquet")
    pq.write_parquet(p1, SCHEMA, _cols(50, base=0), row_group_rows=25)
    pq.write_parquet(p2, SCHEMA, _cols(50, base=1000), row_group_rows=25)
    pred = col("i") >= lit(1000)
    scan = ParquetScanExec([[p1], [p2]], SCHEMA, predicate=pred)
    rows = 0
    for p in range(scan.num_partitions()):
        for b in scan.execute(p, TaskContext(p, 2)):
            rows += b.num_rows
    # both row groups of file a pruned by stats
    assert scan.metrics.get("pruned_row_groups") == 2
    assert rows == 50  # only file b's rows survive (a fully pruned)


def test_scan_missing_column_nulls(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_parquet(path, SCHEMA, _cols(10))
    wider = Schema(list(SCHEMA.fields) + [Field("extra", DataType.int32())])
    scan = ParquetScanExec([[path]], wider)
    batches = list(scan.execute(0, TaskContext(0, 1)))
    d = batch_to_pydict(batches[0])
    assert d["extra"] == [None] * 10


def test_sink_roundtrip(tmp_path):
    out = str(tmp_path / "out")
    schema = Schema([Field("k", DataType.int64()), Field("s", DataType.string(8))])
    src = MemoryScanExec(
        [[batch_from_pydict({"k": [1, 2, None], "s": ["a", None, "c"]}, schema)]], schema
    )
    sink = ParquetSinkExec(src, out)
    list(sink.execute(0, TaskContext(0, 1)))
    files = glob.glob(out + "/*.parquet")
    assert len(files) == 1
    scan = ParquetScanExec([files], schema)
    d = batch_to_pydict(list(scan.execute(0, TaskContext(0, 1)))[0])
    assert d == {"k": [1, 2, None], "s": ["a", None, "c"]}


def test_sink_dynamic_partitions(tmp_path):
    out = str(tmp_path / "out")
    schema = Schema([Field("k", DataType.int64()), Field("g", DataType.string(8))])
    src = MemoryScanExec(
        [[batch_from_pydict({"k": [1, 2, 3, 4], "g": ["x", "y", "x", "y"]}, schema)]], schema
    )
    sink = ParquetSinkExec(src, out, partition_columns=["g"])
    list(sink.execute(0, TaskContext(0, 1)))
    assert sorted(os.listdir(out)) == ["g=x", "g=y"]
    sub = Schema([Field("k", DataType.int64())])
    fx = glob.glob(out + "/g=x/*.parquet")
    scan = ParquetScanExec([fx], sub)
    d = batch_to_pydict(list(scan.execute(0, TaskContext(0, 1)))[0])
    assert sorted(d["k"]) == [1, 3]


@pytest.mark.parametrize("codec", [
    pq.CODEC_SNAPPY, pq.CODEC_ZSTD, pq.CODEC_LZ4_RAW, pq.CODEC_UNCOMPRESSED])
def test_writer_codecs_roundtrip(tmp_path, codec):
    """Snappy (Spark's parquet default) / zstd / lz4_raw pages: our
    reader and pyarrow both read them back exactly."""
    paq = pytest.importorskip("pyarrow.parquet")

    path = str(tmp_path / f"c{codec}.parquet")
    n = 500
    pq.write_parquet(path, SCHEMA, _cols(n), row_group_rows=200, codec=codec)

    scan = ParquetScanExec([[path]], SCHEMA)
    out = [b for b in scan.execute(0, TaskContext(0, 1))]
    d = batch_to_pydict(out[0]) if len(out) == 1 else batch_to_pydict(
        __import__("blaze_tpu.batch", fromlist=["concat_batches"]).concat_batches(out))
    data = np.arange(n, dtype=np.int64)
    vmask = data % 7 != 3
    assert d["i"] == [None if not vmask[i] else int(data[i]) for i in range(n)]
    assert d["s"] == [f"row-{i}" for i in range(n)]

    t = paq.read_table(path)
    got_i = t.column("i").to_pylist()
    assert got_i == [None if not vmask[i] else int(data[i]) for i in range(n)]
    assert t.column("s").to_pylist() == [f"row-{i}" for i in range(n)]
    assert t.column("b").to_pylist() == [bool(i % 2 == 0) for i in range(n)]
