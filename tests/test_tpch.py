"""TPC-H differential validation: engine plans vs independent numpy
oracles on generated data.

≙ the reference's end-to-end correctness gate (SURVEY.md §4: per-query
differential TPC-DS validation against vanilla Spark)."""

import numpy as np
import pytest

from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches
from blaze_tpu.tpch import oracle as O

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], N_PARTS, batch_rows=4096),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def test_q1(data, scans):
    got = run(build_query("q1", scans, N_PARTS))
    exp = O.oracle_q1(data)
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert keys == sorted(keys), "q1 must be ordered by returnflag, linestatus"
    assert set(keys) == set(exp)
    for i, k in enumerate(keys):
        e = exp[k]
        for m in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
                  "count_order", "avg_qty", "avg_price", "avg_disc"):
            # EXACT, including the decimal(16,6) averages: int128
            # accumulation + HALF_UP matches the bignum oracle digit
            # for digit
            assert got[m][i] == e[m], (k, m)


def test_q3(data, scans):
    got = run(build_query("q3", scans, N_PARTS))
    exp = O.oracle_q3(data)
    rows = list(zip(got["l_orderkey"], got["revenue"], got["o_orderdate"], got["o_shippriority"]))
    assert len(rows) == len(exp)
    # compare as sets of (key, revenue): order ties on equal revenue+date
    # may break differently between engine and oracle
    assert set((r[0], r[1]) for r in rows) == set((r[0], r[1]) for r in exp)
    assert [r[1] for r in rows] == sorted([r[1] for r in rows], reverse=True)


def test_q4(data, scans):
    got = run(build_query("q4", scans, N_PARTS))
    exp = O.oracle_q4(data)
    assert dict(zip(got["o_orderpriority"], got["order_count"])) == exp
    assert got["o_orderpriority"] == sorted(got["o_orderpriority"])


def test_q5(data, scans):
    got = run(build_query("q5", scans, N_PARTS))
    exp = O.oracle_q5(data)
    assert dict(zip(got["n_name"], got["revenue"])) == exp
    assert got["revenue"] == sorted(got["revenue"], reverse=True)


def test_q6(data, scans):
    got = run(build_query("q6", scans, N_PARTS))
    assert len(got["revenue"]) == 1
    assert got["revenue"][0] == O.oracle_q6(data)


def test_q10(data, scans):
    got = run(build_query("q10", scans, N_PARTS))
    exp = O.oracle_q10(data)
    rows = list(zip(got["c_custkey"], got["c_name"], got["c_acctbal"], got["n_name"], got["revenue"]))
    assert len(rows) == len(exp)
    assert set((r[0], r[4]) for r in rows) == set((r[0], r[4]) for r in exp)
    assert [r[4] for r in rows] == sorted([r[4] for r in rows], reverse=True)
    # grouped string columns survive the exchange intact
    for r in rows:
        match = [e for e in exp if e[0] == r[0]][0]
        assert r[1] == match[1] and r[2] == match[2] and r[3] == match[3]


def test_q12(data, scans):
    got = run(build_query("q12", scans, N_PARTS))
    exp = O.oracle_q12(data)
    assert got["l_shipmode"] == sorted(exp.keys())
    for i, m in enumerate(got["l_shipmode"]):
        assert got["high_line_count"][i] == exp[m][0]
        assert got["low_line_count"][i] == exp[m][1]


def test_q14(data, scans):
    got = run(build_query("q14", scans, N_PARTS))
    exp_pct, sp, sr = O.oracle_q14(data)
    assert len(got["promo_revenue"]) == 1
    assert abs(got["promo_revenue"][0] - exp_pct) <= 1


def test_q19(data, scans):
    got = run(build_query("q19", scans, N_PARTS))
    exp = O.oracle_q19(data)
    assert len(got["revenue"]) == 1
    got_v = got["revenue"][0]
    if exp == 0:
        assert got_v is None or got_v == 0
    else:
        assert got_v == exp


def test_q2(data, scans):
    got = run(build_query("q2", scans, N_PARTS))
    exp = O.oracle_q2(data)
    rows = list(zip(got["s_acctbal"], got["s_name"], got["n_name"], got["p_partkey"], got["p_mfgr"]))
    assert len(rows) == len(exp)
    assert set((r[0], r[3]) for r in rows) == set((e[0], e[3]) for e in exp)
    assert [r[0] for r in rows] == sorted([r[0] for r in rows], reverse=True)


def test_q7(data, scans):
    got = run(build_query("q7", scans, N_PARTS))
    exp = O.oracle_q7(data)
    rows = {
        (sn, cn, y): r
        for sn, cn, y, r in zip(got["supp_nation"], got["cust_nation"], got["l_year"], got["revenue"])
    }
    assert rows == exp


def test_q9(data, scans):
    got = run(build_query("q9", scans, N_PARTS))
    exp = O.oracle_q9(data)
    rows = {
        (n, y): v for n, y, v in zip(got["nation"], got["o_year"], got["sum_profit"])
    }
    assert rows == exp
    keys = list(zip(got["nation"], got["o_year"]))
    assert keys == sorted(keys, key=lambda t: (t[0], -t[1]))


def test_q11(data, scans):
    got = run(build_query("q11", scans, N_PARTS))
    exp = O.oracle_q11(data)
    rows = dict(zip(got["ps_partkey"], got["value"]))
    assert rows == exp
    assert got["value"] == sorted(got["value"], reverse=True)


def test_q13(data, scans):
    got = run(build_query("q13", scans, N_PARTS))
    exp = O.oracle_q13(data)
    rows = dict(zip(got["c_count"], got["custdist"]))
    assert rows == exp


def test_q8(data, scans):
    got = run(build_query("q8", scans, N_PARTS))
    exp = O.oracle_q8(data)
    assert got["o_year"] == sorted(exp.keys())
    for y, share in zip(got["o_year"], got["mkt_share"]):
        assert abs(share - exp[y]) < 1e-9


def test_q15(data, scans):
    got = run(build_query("q15", scans, N_PARTS))
    exp = O.oracle_q15(data)
    rows = list(zip(got["s_suppkey"], got["s_name"], got["total_revenue"]))
    assert rows == exp


def test_q16(data, scans):
    got = run(build_query("q16", scans, N_PARTS))
    exp = O.oracle_q16(data)
    rows = {
        (b, t, s): c
        for b, t, s, c in zip(got["p_brand"], got["p_type"], got["p_size"], got["supplier_cnt"])
    }
    assert rows == exp
    assert got["supplier_cnt"] == sorted(got["supplier_cnt"], reverse=True)


def test_q17(data, scans):
    got = run(build_query("q17", scans, N_PARTS))
    exp = O.oracle_q17(data)
    v = got["avg_yearly"][0]
    if exp == 0:
        assert v is None or v == 0
    else:
        assert abs(v - exp) / max(abs(exp), 1e-9) < 1e-9


def test_q18(data, scans):
    got = run(build_query("q18", scans, N_PARTS))
    exp = O.oracle_q18(data)
    rows = list(zip(got["c_name"], got["c_custkey"], got["o_orderkey"], got["o_orderdate"], got["o_totalprice"], got["qsum"]))
    assert len(rows) == len(exp)
    assert set(r[2] for r in rows) == set(e[2] for e in exp)
    assert [r[4] for r in rows] == sorted([r[4] for r in rows], reverse=True)


def test_q20(data, scans):
    got = run(build_query("q20", scans, N_PARTS))
    exp = O.oracle_q20(data)
    rows = list(zip(got["s_name"], got["s_address"]))
    assert rows == exp


def test_q21(data, scans):
    got = run(build_query("q21", scans, N_PARTS))
    exp = O.oracle_q21(data)
    rows = dict(zip(got["s_name"], got["numwait"]))
    assert rows == exp


def test_q22(data, scans):
    got = run(build_query("q22", scans, N_PARTS))
    exp = O.oracle_q22(data)
    assert got["cntrycode"] == sorted(exp.keys())
    for i, c in enumerate(got["cntrycode"]):
        assert got["numcust"][i] == exp[c][0]
        assert got["totacctbal"][i] == exp[c][1]
