"""Operator unit tests with in-memory sources.

≙ reference operator tests (datafusion-ext-plans: joins/test.rs matrix,
sort_exec.rs test_sort_i32, window_exec.rs:259, expand/limit/agg acc
tests) — same strategy: MemoryExec-style fixtures + sorted result
comparison (SURVEY.md §4)."""

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import func
from blaze_tpu.ops import (
    AggExec,
    AggFunction,
    AggMode,
    BroadcastJoinExec,
    CoalesceBatchesExec,
    ExpandExec,
    FilterExec,
    GenerateExec,
    GroupingExpr,
    HashJoinExec,
    LimitExec,
    MemoryScanExec,
    ProjectExec,
    RenameColumnsExec,
    SortExec,
    SortField,
    SortMergeJoinExec,
    UnionExec,
    WindowExec,
    WindowFunction,
)
from blaze_tpu.ops.joins import JoinType
from blaze_tpu.ops.generate import json_tuple_generator
from blaze_tpu.schema import DataType, Field, Schema


def mem(data, schema, n_parts=1):
    """Split dict-of-lists into n_parts single-batch partitions."""
    n = len(next(iter(data.values())))
    parts = []
    for p in range(n_parts):
        lo = p * n // n_parts
        hi = (p + 1) * n // n_parts
        chunk = {k: v[lo:hi] for k, v in data.items()}
        parts.append([batch_from_pydict(chunk, schema)] if hi > lo else [])
    return MemoryScanExec(parts, schema)


def collect_dict(node):
    batches = node.collect()
    if not batches:
        return {f.name: [] for f in node.schema.fields}
    out = {f.name: [] for f in node.schema.fields}
    for b in batches:
        d = batch_to_pydict(b)
        for k in out:
            out[k].extend(d[k])
    return out


def sorted_rows(d):
    keys = list(d.keys())
    rows = list(zip(*[d[k] for k in keys]))
    return sorted(rows, key=lambda r: tuple((v is None, v) for v in r))


INT_SCHEMA = Schema([Field("a", DataType.int32()), Field("b", DataType.int64())])


def test_project_filter_pipeline():
    src = mem({"a": [1, 2, 3, 4, 5], "b": [10, 20, 30, 40, 50]}, INT_SCHEMA)
    f = FilterExec(src, col("a") % lit(2) == lit(1))
    p = ProjectExec(f, [col("a"), (col("b") + col("a")).alias("c")])
    got = collect_dict(p)
    assert got == {"a": [1, 3, 5], "c": [11, 33, 55]}


def test_filter_all_and_none():
    src = mem({"a": [1, 2], "b": [1, 2]}, INT_SCHEMA)
    assert collect_dict(FilterExec(src, col("a") > lit(100))) == {"a": [], "b": []}
    src2 = mem({"a": [1, 2], "b": [1, 2]}, INT_SCHEMA)
    assert collect_dict(FilterExec(src2, col("a") > lit(0)))["a"] == [1, 2]


def test_agg_scalar_no_groups():
    src = mem({"a": [1, 2, None, 4], "b": [10, 20, 30, 40]}, INT_SCHEMA)
    agg = AggExec(
        src,
        AggMode.PARTIAL,
        [],
        [
            AggFunction("sum", col("a"), "s"),
            AggFunction("count", col("a"), "c"),
            AggFunction("count_star", None, "cs"),
            AggFunction("min", col("b"), "mn"),
            AggFunction("max", col("b"), "mx"),
            AggFunction("avg", col("b"), "av"),
        ],
    )
    final = AggExec(agg, AggMode.FINAL, [], agg.aggs)
    got = collect_dict(final)
    assert got["s"] == [7] and got["c"] == [3] and got["cs"] == [4]
    assert got["mn"] == [10] and got["mx"] == [40] and got["av"] == [25.0]


def test_agg_grouped():
    schema = Schema([Field("g", DataType.string(8)), Field("v", DataType.int64())])
    src = mem(
        {"g": ["x", "y", "x", None, "y", None], "v": [1, 2, 3, 4, None, 6]},
        schema,
        n_parts=2,
    )
    part = AggExec(
        src, AggMode.PARTIAL,
        [GroupingExpr(col("g"), "g")],
        [AggFunction("sum", col("v"), "s"), AggFunction("count_star", None, "n")],
    )
    final = AggExec(
        part, AggMode.FINAL,
        [GroupingExpr(col("g"), "g")],
        part.aggs,
    )
    # run each source partition through partial, then merge via a
    # single-partition final (simulates the exchange)
    batches = part.collect()
    merged_src = MemoryScanExec([batches], part.schema)
    final = AggExec(
        merged_src, AggMode.FINAL,
        [GroupingExpr(col("g"), "g")],
        part.aggs,
    )
    got = collect_dict(final)
    rows = sorted_rows(got)
    assert rows == sorted_rows({"g": ["x", "y", None], "s": [4, 2, 10], "n": [2, 2, 2]})


def test_agg_empty_input_global():
    src = mem({"a": [], "b": []}, INT_SCHEMA)
    agg = AggExec(src, AggMode.PARTIAL, [], [AggFunction("count_star", None, "n"), AggFunction("sum", col("a"), "s")])
    final = AggExec(MemoryScanExec([agg.collect()], agg.schema), AggMode.FINAL, [], agg.aggs)
    got = collect_dict(final)
    assert got["n"] == [0] and got["s"] == [None]


def test_sort_multi_key_nulls():
    schema = Schema([Field("a", DataType.int32()), Field("b", DataType.float64())])
    src = mem({"a": [3, 1, None, 1, 2], "b": [1.0, 5.0, 2.0, -1.0, None]}, schema)
    s = SortExec(src, [SortField(col("a"), True, True), SortField(col("b"), False, False)])
    got = collect_dict(s)
    assert got["a"] == [None, 1, 1, 2, 3]
    assert got["b"] == [2.0, 5.0, -1.0, None, 1.0]


def test_sort_desc_strings():
    schema = Schema([Field("s", DataType.string(8))])
    src = mem({"s": ["pear", "apple", "fig", None]}, schema)
    got = collect_dict(SortExec(src, [SortField(col("s"), False, False)]))
    assert got["s"] == ["pear", "fig", "apple", None]


def test_sort_fetch_topk():
    schema = Schema([Field("a", DataType.int64())])
    src = mem({"a": list(range(100, 0, -1))}, schema, n_parts=3)
    got = collect_dict(SortExec(src, [SortField(col("a"))], fetch=5))
    # collect() concatenates per-partition top-5s; single-partition check:
    one = SortExec(mem({"a": list(range(100, 0, -1))}, schema), [SortField(col("a"))], fetch=5)
    assert collect_dict(one)["a"] == [1, 2, 3, 4, 5]


def test_limit_union_rename_coalesce():
    src1 = mem({"a": [1, 2, 3], "b": [1, 2, 3]}, INT_SCHEMA)
    src2 = mem({"a": [4, 5], "b": [4, 5]}, INT_SCHEMA)
    u = UnionExec([src1, src2])
    got = collect_dict(LimitExec(u, 4))
    assert len(got["a"]) == 4
    r = RenameColumnsExec(mem({"a": [1], "b": [2]}, INT_SCHEMA), ["x", "y"])
    assert collect_dict(r) == {"x": [1], "y": [2]}
    c = CoalesceBatchesExec(UnionExec([mem({"a": [1], "b": [1]}, INT_SCHEMA), mem({"a": [2], "b": [2]}, INT_SCHEMA)]))
    batches = c.collect()
    assert sum(b.num_rows for b in batches) == 2


def test_expand():
    src = mem({"a": [1, 2], "b": [10, 20]}, INT_SCHEMA)
    e = ExpandExec(
        src,
        [[col("a"), lit(0).cast(DataType.int64())], [col("a"), col("b")]],
        ["a", "tag"],
    )
    got = collect_dict(e)
    assert sorted_rows(got) == sorted_rows({"a": [1, 2, 1, 2], "tag": [0, 0, 10, 20]})


LEFT = {"k": [1, 2, 2, 3, None], "lv": [10, 20, 21, 30, 99]}
RIGHT = {"k2": [2, 2, 3, 4, None], "rv": [200, 201, 300, 400, 999]}
L_SCHEMA = Schema([Field("k", DataType.int64()), Field("lv", DataType.int64())])
R_SCHEMA = Schema([Field("k2", DataType.int64()), Field("rv", DataType.int64())])


def _join(jt, cls=HashJoinExec, build_left=False):
    left = mem(LEFT, L_SCHEMA)
    right = mem(RIGHT, R_SCHEMA)
    if cls is SortMergeJoinExec:
        left = SortExec(left, [SortField(col("k"))])
        right = SortExec(right, [SortField(col("k2"))])
        return SortMergeJoinExec(left, right, [col("k")], [col("k2")], jt)
    if build_left:
        return cls(left, right, [col("k")], [col("k2")], jt, build_is_left=True)
    return cls(right, left, [col("k2")], [col("k")], jt, build_is_left=False)


INNER_EXPECTED = sorted_rows(
    {"k": [2, 2, 2, 2, 3], "lv": [20, 20, 21, 21, 30], "k2": [2, 2, 2, 2, 3], "rv": [200, 201, 200, 201, 300]}
)


def _rows(node):
    d = collect_dict(node)
    names = list(d.keys())
    return sorted_rows(d), names


@pytest.mark.parametrize("cls,build_left", [
    (HashJoinExec, False), (HashJoinExec, True),
    (BroadcastJoinExec, False), (BroadcastJoinExec, True),
    (SortMergeJoinExec, False),
])
def test_join_inner(cls, build_left):
    rows, _ = _rows(_join(JoinType.INNER, cls, build_left))
    assert len(rows) == 5
    ks = sorted(r[0] for r in rows)
    assert ks == [2, 2, 2, 2, 3]


@pytest.mark.parametrize("cls", [HashJoinExec, SortMergeJoinExec])
def test_join_left_outer(cls):
    rows, _ = _rows(_join(JoinType.LEFT, cls))
    # 5 matched + unmatched lv 10 (k=1) and 99 (k=None)
    assert len(rows) == 7
    unmatched = [r for r in rows if r[3] is None]
    assert sorted(r[1] for r in unmatched) == [10, 99]


def test_join_right_outer():
    rows, _ = _rows(_join(JoinType.RIGHT, HashJoinExec))
    assert len(rows) == 7
    unmatched = [r for r in rows if r[0] is None and r[1] is None]
    assert sorted(r[3] for r in unmatched) == [400, 999]


def test_join_full_outer():
    rows, _ = _rows(_join(JoinType.FULL, HashJoinExec))
    assert len(rows) == 9


def test_join_semi_anti():
    rows, _ = _rows(_join(JoinType.LEFT_SEMI, HashJoinExec))
    assert sorted(r[1] for r in rows) == [20, 21, 30]
    rows, _ = _rows(_join(JoinType.LEFT_ANTI, HashJoinExec))
    assert sorted(r[1] for r in rows) == [10, 99]


def test_join_existence():
    rows, names = _rows(_join(JoinType.EXISTENCE, HashJoinExec))
    assert len(rows) == 5
    by_lv = {r[1]: r[2] for r in rows}
    assert by_lv[10] is False and by_lv[20] is True and by_lv[30] is True and by_lv[99] is False


def test_window_rank_rownumber():
    schema = Schema([Field("g", DataType.int32()), Field("v", DataType.int64())])
    src = mem({"g": [1, 1, 1, 2, 2], "v": [5, 5, 7, 1, 2]}, schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [
            WindowFunction("row_number", "rn"),
            WindowFunction("rank", "rk"),
            WindowFunction("dense_rank", "dr"),
            WindowFunction("sum", "rs", col("v")),
        ],
        [col("g")],
        [SortField(col("v"))],
    )
    got = collect_dict(w)
    assert got["rn"] == [1, 2, 3, 1, 2]
    assert got["rk"] == [1, 1, 3, 1, 2]
    assert got["dr"] == [1, 1, 2, 1, 2]
    # default RANGE frame: peers (5,5) share the running sum 10
    assert got["rs"] == [10, 10, 17, 1, 3]


def test_generate_json_tuple():
    schema = Schema([Field("j", DataType.string(64))])
    src = mem({"j": ['{"a":"1","b":"x"}', '{"a":"2"}', "oops", None]}, schema)
    g = GenerateExec(
        src,
        json_tuple_generator(["a", "b"]),
        [col("j")],
        [Field("a", DataType.string(16)), Field("b", DataType.string(16))],
    )
    got = collect_dict(g)
    assert got["a"] == ["1", "2", None, None]
    assert got["b"] == ["x", None, None, None]


def test_agg_spill_under_memory_pressure():
    """Regression: a spilled accumulator must not also stay merged in
    the live state (double counting).  ≙ agg_table.rs spill+merge."""
    from blaze_tpu import conf as _conf
    from blaze_tpu.parallel import HashPartitioning, NativeShuffleExchangeExec
    from blaze_tpu.runtime.memmgr import MemManager

    rng = np.random.RandomState(0)
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    batches = [[
        batch_from_pydict(
            {"k": [int(x) for x in rng.randint(0, 50, 400)],
             "v": [int(x) for x in rng.randint(0, 100, 400)]},
            schema,
        )
        for _ in range(3)
    ] for _ in range(2)]

    def q():
        src = MemoryScanExec(batches, schema)
        part = AggExec(src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
                       [AggFunction("sum", col("v"), "s")])
        ex = NativeShuffleExchangeExec(part, HashPartitioning([col("k")], 2))
        return AggExec(ex, AggMode.FINAL, [GroupingExpr(col("k"), "k")], part.aggs)

    from blaze_tpu.runtime.context import TaskContext

    def run_q(plan):
        out = {}
        for p in range(2):
            for b in plan.execute(p, TaskContext(p, 2)):
                d = batch_to_pydict(b)
                out.update(zip(d["k"], d["s"]))
        return out

    want = run_q(q())
    MemManager.init(20_000)
    try:
        starved = q()
        got = run_q(starved)
    finally:
        MemManager.init(int(_conf.HOST_SPILL_BUDGET.get()))
    assert got == want


def test_grouped_agg_segscan_vs_scatter_paths():
    """The scan/gather-based sorted-segment reduce (TPU fast path) and
    the legacy jax.ops.segment_* path produce identical states."""
    import numpy as np

    from blaze_tpu import conf
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.runtime.kernel_cache import clear_kernel_cache
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([
        Field("k", DataType.int64()),
        Field("s", DataType.string(8)),
        Field("v", DataType.int64()),
        Field("f", DataType.float64()),
    ])
    rng = np.random.RandomState(3)
    n = 500
    data = {
        "k": [int(x) if x % 5 else None for x in rng.randint(0, 17, n)],
        "s": [f"s{x}" if x % 4 else None for x in rng.randint(0, 9, n)],
        "v": [int(x) for x in rng.randint(-50, 50, n)],
        "f": [float(x) for x in rng.uniform(-5, 5, n)],
    }

    def run(flag):
        old = conf.SEG_SCAN_REDUCE.get()
        conf.SEG_SCAN_REDUCE.set(flag)
        clear_kernel_cache()
        try:
            src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
            agg = AggExec(
                src, AggMode.PARTIAL,
                [GroupingExpr(col("k"), "k")],
                [
                    AggFunction("sum", col("v"), "sv"),
                    AggFunction("count", col("f"), "cf"),
                    AggFunction("min", col("v"), "mv"),
                    AggFunction("max", col("f"), "xf"),
                    AggFunction("first_ignores_null", col("v"), "fv"),
                    AggFunction("min", col("s"), "ms"),
                ],
            )
            rows = {}
            for b in agg.execute(0, TaskContext(0, 1)):
                d = batch_to_pydict(b)
                for i, k in enumerate(d["k"]):
                    rows[k] = tuple(d[c][i] for c in d if c != "k")
            return rows
        finally:
            conf.SEG_SCAN_REDUCE.set(old)
            clear_kernel_cache()
    assert run(True) == run(False)


def test_partial_hash_sort_two_stage_differential():
    """PARTIAL hash-keyed sort (possible duplicate partial groups) must
    be invisible after the FINAL merge — differential vs the exact-sort
    path across the full two-stage pipeline."""
    import numpy as np

    from blaze_tpu import conf
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr, MemoryScanExec
    from blaze_tpu.parallel import HashPartitioning, NativeShuffleExchangeExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.runtime.kernel_cache import clear_kernel_cache
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    rng = np.random.RandomState(11)
    parts = []
    for p in range(3):
        n = 200
        parts.append([batch_from_pydict({
            "k": [int(x) if x % 6 else None for x in rng.randint(0, 40, n)],
            "v": [int(x) for x in rng.randint(-20, 20, n)],
        }, schema)])

    def run(flag):
        old = conf.AGG_HASH_SORT_PARTIAL.get()
        conf.AGG_HASH_SORT_PARTIAL.set(flag)
        clear_kernel_cache()
        try:
            src = MemoryScanExec(parts, schema)
            partial = AggExec(src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
                              [AggFunction("sum", col("v"), "sv"),
                               AggFunction("count_star", None, "n")])
            ex = NativeShuffleExchangeExec(partial, HashPartitioning([col("k")], 2))
            final = AggExec(ex, AggMode.FINAL, [GroupingExpr(col("k"), "k")], partial.aggs)
            rows = {}
            for p in range(2):
                for b in final.execute(p, TaskContext(p, 2)):
                    d = batch_to_pydict(b)
                    for k, sv, n in zip(d["k"], d["sv"], d["n"]):
                        assert k not in rows, f"duplicate group {k} survived final"
                        rows[k] = (sv, n)
            return rows
        finally:
            conf.AGG_HASH_SORT_PARTIAL.set(old)
            clear_kernel_cache()

    assert run(True) == run(False)


def test_segscan_float_sum_no_cancellation():
    """Float group sums must accumulate within each segment: a small
    group after a huge prefix must not cancel (regression for the
    global-cumsum-difference pitfall)."""
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.float64())])
    # group 0 sums to ~1e15, group 1 sums to 0.001
    data = {
        "k": [0] * 10 + [1] * 4,
        "v": [1e14] * 10 + [0.00025] * 4,
    }
    src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    agg = AggExec(
        src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
        [AggFunction("sum", col("v"), "sv")],
    )
    out = {}
    for b in agg.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        for k, s in zip(d["k"], d["sv#sum"]):
            out[k] = s
    assert out[0] == 1e15
    assert abs(out[1] - 0.001) < 1e-12, out[1]


def test_window_rows_frame_sliding():
    """ROWS BETWEEN p PRECEDING AND q FOLLOWING sliding sums/avg/count
    vs a python oracle, partition clamps included."""
    import numpy as np

    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.ops import SortExec, WindowExec, WindowFunction

    schema = Schema([Field("g", DataType.int32()), Field("v", DataType.int64())])
    rng = np.random.RandomState(2)
    rows = [(int(g), int(v) if v % 7 else None)
            for g, v in zip(rng.randint(0, 3, 40), rng.randint(0, 50, 40))]
    src = mem({"g": [r[0] for r in rows], "v": [r[1] for r in rows]}, schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [
            WindowFunction("sum", "s21", col("v"), rows_frame=(2, 1)),
            WindowFunction("count", "c0u", col("v"), rows_frame=(0, None)),
            WindowFunction("avg", "a10", col("v"), rows_frame=(1, 0)),
        ],
        [col("g")],
        [SortField(col("v"))],
    )
    got = collect_dict(w)
    # oracle over the same (g, v)-sorted order
    key = lambda r: (r[0], r[1] is None, r[1] if r[1] is not None else 0)
    srt = sorted(rows, key=lambda r: (r[0], r[1] is not None, r[1] or 0))
    # engine sorts nulls first within group (nulls_first default)
    by_g = {}
    for g, v in srt:
        by_g.setdefault(g, []).append(v)
    exp_s, exp_c, exp_a = [], [], []
    for g in sorted(by_g):
        vs = by_g[g]
        for i in range(len(vs)):
            win = [x for x in vs[max(0, i - 2): i + 2] if x is not None]
            exp_s.append(sum(win) if win else None)
            cwin = [x for x in vs[i:] if x is not None]
            exp_c.append(len(cwin))
            awin = [x for x in vs[max(0, i - 1): i + 1] if x is not None]
            exp_a.append(sum(awin) / len(awin) if awin else None)
    assert got["s21"] == exp_s
    assert got["c0u"] == exp_c
    assert got["a10"] == exp_a


def test_window_rows_frame_serde_roundtrip():
    from blaze_tpu.ops import SortExec, WindowExec, WindowFunction
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    schema = Schema([Field("g", DataType.int32()), Field("v", DataType.int64())])
    src = mem({"g": [1, 1, 2], "v": [1, 2, 3]}, schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [WindowFunction("sum", "s", col("v"), rows_frame=(3, None)),
         WindowFunction("lag", "lg", col("v"), offset=2)],
        [col("g")], [SortField(col("v"))],
    )
    w2 = plan_from_proto(plan_to_proto(w))
    assert w2.functions[0].rows_frame == (3, None)
    assert w2.functions[1].offset == 2
    assert collect_dict(w2) == collect_dict(w)


def test_window_rows_frame_sliding_minmax():
    """Sparse-table sliding min/max over ROWS frames vs python oracle
    (partition clamps, nulls, float and int)."""
    import numpy as np

    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.ops import SortExec, WindowExec, WindowFunction

    schema = Schema([
        Field("g", DataType.int32()),
        Field("v", DataType.int64()),
        Field("f", DataType.float64()),
    ])
    rng = np.random.RandomState(9)
    n = 60
    rows = [
        (int(g), int(v) if v % 6 else None, float(x) if v % 4 else None)
        for g, v, x in zip(
            rng.randint(0, 3, n), rng.randint(0, 90, n), rng.uniform(-5, 5, n)
        )
    ]
    src = mem(
        {"g": [r[0] for r in rows], "v": [r[1] for r in rows], "f": [r[2] for r in rows]},
        schema,
    )
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [
            WindowFunction("min", "mn", col("v"), rows_frame=(3, 2)),
            WindowFunction("max", "mx", col("f"), rows_frame=(0, 4)),
        ],
        [col("g")],
        [SortField(col("v"))],
    )
    got = collect_dict(w)
    by_g = {}
    srt = sorted(rows, key=lambda r: (r[0], r[1] is not None, r[1] or 0))
    for g, v, x in srt:
        by_g.setdefault(g, []).append((v, x))
    exp_mn, exp_mx = [], []
    for g in sorted(by_g):
        vs = by_g[g]
        for i in range(len(vs)):
            w1 = [t[0] for t in vs[max(0, i - 3): i + 3] if t[0] is not None]
            exp_mn.append(min(w1) if w1 else None)
            w2 = [t[1] for t in vs[i: i + 5] if t[1] is not None]
            exp_mx.append(max(w2) if w2 else None)
    assert got["mn"] == exp_mn
    assert got["mx"] == exp_mx


def test_window_lead_lag_first_last():
    from blaze_tpu.batch import batch_to_pydict
    from blaze_tpu.ops import SortExec, WindowExec, WindowFunction

    schema = Schema([Field("g", DataType.int32()), Field("v", DataType.int64())])
    src = mem({"g": [1, 1, 1, 2, 2], "v": [5, 6, 7, 1, 2]}, schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [
            WindowFunction("lead", "ld", col("v"), offset=1),
            WindowFunction("lag", "lg", col("v"), offset=2),
            WindowFunction("first_value", "fv", col("v")),
            WindowFunction("last_value", "lv", col("v")),
        ],
        [col("g")],
        [SortField(col("v"))],
    )
    got = collect_dict(w)
    assert got["ld"] == [6, 7, None, 2, None]
    assert got["lg"] == [None, None, 5, None, None]
    assert got["fv"] == [5, 5, 5, 1, 1]
    # default frame last_value = current peer-group end (no ties here)
    assert got["lv"] == [5, 6, 7, 1, 2]


def test_window_last_value_whole_partition():
    from blaze_tpu.ops import SortExec, WindowExec, WindowFunction

    schema = Schema([Field("g", DataType.int32()), Field("v", DataType.int64())])
    src = mem({"g": [1, 1, 2], "v": [5, 7, 1]}, schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("v"))])
    w = WindowExec(
        pre,
        [WindowFunction("last_value", "lv", col("v"), whole_partition=True),
         WindowFunction("lead", "l0", col("v"), offset=0)],
        [col("g")], [SortField(col("v"))],
    )
    got = collect_dict(w)
    assert got["lv"] == [7, 7, 1]
    assert got["l0"] == [5, 7, 1]  # offset 0 = current row


def test_stddev_var_samp_two_stage():
    """stddev_samp/var_samp across the partial->merge split, incl. the
    n<=1 NULL contract and decimal input rescaling."""
    import statistics

    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.tpch.queries import two_stage_agg

    schema = Schema([Field("g", DataType.int64()),
                     Field("v", DataType.float64()),
                     Field("d", DataType.decimal(7, 2))])
    data = {"g": [0, 0, 0, 1, 1, 2, 2, 3],
            "v": [1.0, 2.0, 4.0, 5.0, 5.0, 7.0, None, 9.0],
            "d": [1.50, 2.50, 4.50, 5.00, 5.00, 7.25, None, 9.00]}
    src = MemoryScanExec(
        [[batch_from_pydict({k: v[:4] for k, v in data.items()}, schema)],
         [batch_from_pydict({k: v[4:] for k, v in data.items()}, schema)]],
        schema)
    plan = two_stage_agg(
        src, [GroupingExpr(col("g"), "g")],
        [AggFunction("stddev_samp", col("v"), "sd"),
         AggFunction("var_samp", col("v"), "var"),
         AggFunction("stddev_samp", col("d"), "dsd")],
        2)
    got = {}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for g, sd, var, dsd in zip(d["g"], d["sd"], d["var"], d["dsd"]):
                got[g] = (sd, var, dsd)
    exp = {0: ([1.0, 2.0, 4.0], [1.5, 2.5, 4.5]),
           1: ([5.0, 5.0], [5.0, 5.0]),
           2: ([7.0], [7.25]), 3: ([9.0], [9.0])}
    for g, (vs, ds) in exp.items():
        if len(vs) <= 1:
            assert got[g] == (None, None, None), (g, got[g])
        else:
            assert abs(got[g][0] - statistics.stdev(vs)) < 1e-12, g
            assert abs(got[g][1] - statistics.variance(vs)) < 1e-12, g
            assert abs(got[g][2] - statistics.stdev(ds)) < 1e-12, g


def test_var_samp_no_catastrophic_cancellation():
    """Large-magnitude inputs split one-per-state across the merge:
    the deviation-scale parallel merge must hold the exact answer
    (the raw sum-of-squares form returns 0 or ~4 here)."""
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.tpch.queries import two_stage_agg

    schema = Schema([Field("g", DataType.int64()), Field("v", DataType.float64())])
    src = MemoryScanExec(
        [[batch_from_pydict({"g": [0], "v": [1e8]}, schema)],
         [batch_from_pydict({"g": [0], "v": [1e8 + 1]}, schema)]], schema)
    plan = two_stage_agg(src, [GroupingExpr(col("g"), "g")],
                         [AggFunction("var_samp", col("v"), "var")], 2)
    got = None
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            if d["var"]:
                got = d["var"][0]
    assert got is not None and abs(got - 0.5) < 1e-9, got
