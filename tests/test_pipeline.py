"""Pipelined runtime: bounded-queue producer thread.

≙ reference rt.rs:100-133 (tokio stream drive into sync_channel(1)) —
ordering, error propagation, cancellation, bounded buffering, and
actual producer/consumer overlap.
"""

import threading
import time

import pytest

from blaze_tpu import conf
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.pipeline import maybe_pipelined, pipelined


def test_ordering_preserved():
    ctx = TaskContext(0, 1)
    out = list(pipelined(iter(range(100)), ctx, depth=3))
    assert out == list(range(100))


def test_error_propagates_at_consumer():
    ctx = TaskContext(0, 1)

    def gen():
        yield 1
        yield 2
        raise ValueError("boom in producer")

    it = pipelined(gen(), ctx, depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="boom in producer"):
        next(it)


def test_bounded_queue_limits_producer():
    """The producer cannot run ahead more than depth items."""
    ctx = TaskContext(0, 1)
    produced = []

    def gen():
        for i in range(50):
            produced.append(i)
            yield i

    it = pipelined(gen(), ctx, depth=2)
    first = next(it)
    time.sleep(0.3)  # give the producer every chance to run ahead
    # at most: 1 consumed + 2 queued + 1 blocked-in-hand (+1 slack)
    assert first == 0
    assert len(produced) <= 5, produced


def test_consumer_close_stops_producer():
    ctx = TaskContext(0, 1)
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = pipelined(gen(), ctx, depth=1)
    assert next(it) == 0
    it.close()
    time.sleep(0.3)
    snapshot = len(produced)
    time.sleep(0.3)
    # production has STALLED after close (stop flag observed)
    assert len(produced) == snapshot
    assert snapshot < 10_000


def test_never_iterated_stream_starts_no_producer():
    """A pipelined stream that is never consumed must not leak a
    producer thread (lazy start)."""
    ctx = TaskContext(0, 1)
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    _ = pipelined(gen(), ctx, depth=1)
    time.sleep(0.2)
    assert produced == []  # producer never started


def test_task_cancellation_stops_both_sides():
    ctx = TaskContext(0, 1)

    def gen():
        for i in range(10_000):
            yield i
            time.sleep(0.001)

    it = pipelined(gen(), ctx, depth=1)
    assert next(it) == 0
    ctx.cancel()
    out = list(it)  # drains quickly and ends instead of blocking
    assert len(out) < 10_000


def test_overlap_actually_happens():
    """Producer staging and consumer 'compute' run concurrently: total
    wall time is well under the serial sum."""
    ctx = TaskContext(0, 1)
    n, d = 10, 0.02

    def gen():
        for i in range(n):
            time.sleep(d)  # host staging
            yield i

    t0 = time.perf_counter()
    for _ in pipelined(gen(), ctx, depth=2):
        time.sleep(d)  # device compute
    elapsed = time.perf_counter() - t0
    serial = 2 * n * d
    assert elapsed < serial * 0.8, f"no overlap: {elapsed:.3f}s vs serial {serial:.3f}s"


def test_conf_toggle():
    ctx = TaskContext(0, 1)
    old = conf.PIPELINE_DEPTH.get()
    try:
        conf.PIPELINE_DEPTH.set(0)
        it = maybe_pipelined(iter([1, 2, 3]), ctx)
        assert list(it) == [1, 2, 3]
        conf.PIPELINE_DEPTH.set(2)
        it = maybe_pipelined(iter([1, 2, 3]), ctx)
        assert list(it) == [1, 2, 3]
    finally:
        conf.PIPELINE_DEPTH.set(old)


def test_scan_through_pipeline(tmp_path):
    """ParquetScanExec output is identical with and without pipelining."""
    import pyarrow as pa
    import pyarrow.parquet as papq

    from blaze_tpu.batch import batch_to_pydict, concat_batches
    from blaze_tpu.ops import ParquetScanExec
    from blaze_tpu.schema import DataType, Field, Schema

    path = tmp_path / "p.parquet"
    papq.write_table(
        pa.table({"x": pa.array(list(range(5000)), pa.int64())}), path,
        row_group_size=512, compression="snappy",
    )
    schema = Schema([Field("x", DataType.int64())])

    def run():
        scan = ParquetScanExec([[str(path)]], schema)
        out = list(scan.execute(0, TaskContext(0, 1)))
        return batch_to_pydict(concat_batches(out))["x"]

    old = conf.PIPELINE_DEPTH.get()
    try:
        conf.PIPELINE_DEPTH.set(2)
        piped = run()
        conf.PIPELINE_DEPTH.set(0)
        sync = run()
    finally:
        conf.PIPELINE_DEPTH.set(old)
    assert piped == sync == list(range(5000))
