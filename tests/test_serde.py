"""Plan serde roundtrips — ≙ reference blaze-serde scalar/plan decode
tests + the TaskDefinition entry path."""

import numpy as np

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import Case, Like, func
from blaze_tpu.ops import (
    AggExec, AggFunction, AggMode, FilterExec, GroupingExpr, LimitExec,
    MemoryScanExec, ProjectExec, SortExec, SortField,
)
from blaze_tpu.ops.joins import HashJoinExec, JoinType
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.serde import plan_from_proto, plan_to_proto, run_task, task_definition
from blaze_tpu.serde import plan_pb2


SCHEMA = Schema([
    Field("k", DataType.int64()),
    Field("s", DataType.string(16)),
    Field("d", DataType.decimal(12, 2)),
])


def _mem(data, schema):
    return MemoryScanExec([[batch_from_pydict(data, schema)]], schema)


def _collect(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def test_expr_plan_roundtrip_filter_project():
    src = _mem({"k": [1, 2, 3, None], "s": ["aa", "bb", "ab", None], "d": [1.5, 2.0, -3.25, 0.5]}, SCHEMA)
    plan = ProjectExec(
        FilterExec(src, (col("k") > lit(1)) & Like(col("s"), "a%") | col("k").is_null()),
        [col("k"), (col("d") * lit("2", DataType.decimal(3, 0))).alias("dd"),
         Case([(col("k") == lit(3), lit("three"))], lit("other")).alias("c")],
    )
    data = plan_to_proto(plan).SerializeToString()
    decoded = plan_from_proto(_parse(data))
    got = _collect(decoded)
    assert got["k"] == [3, None]
    assert got["dd"] == [-650, 100]
    assert got["c"] == ["three", "other"]


def _parse(data):
    n = plan_pb2.PhysicalPlanNode()
    n.ParseFromString(data)
    return n


def test_agg_sort_limit_roundtrip():
    src = _mem({"k": [1, 1, 2, 2, 2], "s": ["a"] * 5, "d": [1.0, 2.0, 3.0, 4.0, 5.0]}, SCHEMA)
    agg = AggExec(
        src, AggMode.PARTIAL,
        [GroupingExpr(col("k"), "k")],
        [AggFunction("sum", col("d"), "sd"), AggFunction("count_star", None, "n")],
    )
    final = AggExec(
        MemoryScanExec([agg.collect()], agg.schema), AggMode.FINAL,
        [GroupingExpr(col("k"), "k")], agg.aggs,
    )
    plan = LimitExec(SortExec(final, [SortField(col("sd"), ascending=False)]), 1)
    decoded = plan_from_proto(_parse(plan_to_proto(plan).SerializeToString()))
    got = _collect(decoded)
    assert got["k"] == [2] and got["sd"] == [1200] and got["n"] == [3]


def test_join_roundtrip():
    l = _mem({"k": [1, 2, 3], "s": ["a", "b", "c"], "d": [1.0, 2.0, 3.0]}, SCHEMA)
    r_schema = Schema([Field("k2", DataType.int64()), Field("v", DataType.int64())])
    r = MemoryScanExec([[batch_from_pydict({"k2": [2, 3, 4], "v": [20, 30, 40]}, r_schema)]], r_schema)
    plan = HashJoinExec(r, l, [col("k2")], [col("k")], JoinType.INNER, build_is_left=False)
    decoded = plan_from_proto(_parse(plan_to_proto(plan).SerializeToString()))
    got = _collect(decoded)
    assert sorted(got["k"]) == [2, 3]
    assert sorted(got["v"]) == [20, 30]


def test_task_definition_entry():
    src = _mem({"k": [5, 6], "s": ["x", "y"], "d": [1.0, 2.0]}, SCHEMA)
    plan = ProjectExec(src, [(col("k") + lit(1)).alias("k1")])
    td = task_definition(plan, task_id="t-0", stage_id=1, partition=0)
    batches = list(run_task(td))
    assert batch_to_pydict(batches[0])["k1"] == [6, 7]


def _identity_generator(row):
    return [row]


def test_pickled_generator_gate():
    """spark.blaze.udf.allowPickled=false rejects pickled payloads at
    decode (the gateway's trust-boundary hardening)."""
    import pytest

    from blaze_tpu import conf
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.ops.generate import GenerateExec
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    schema = Schema([Field("j", DataType.string(32))])
    b = batch_from_pydict({"j": ['{"a":1}']}, schema)
    g = GenerateExec(
        MemoryScanExec([[b]], schema), _identity_generator,
        [__import__("blaze_tpu.exprs", fromlist=["col"]).col("j")],
        [Field("a", DataType.string(16))],
    )
    proto = plan_to_proto(g)
    old = conf.ALLOW_PICKLED_UDFS.get()
    try:
        conf.ALLOW_PICKLED_UDFS.set(False)
        with pytest.raises(PermissionError, match="allowPickled"):
            plan_from_proto(proto)
        conf.ALLOW_PICKLED_UDFS.set(True)
        assert plan_from_proto(proto) is not None
    finally:
        conf.ALLOW_PICKLED_UDFS.set(old)
