"""Stage fusion + column pruning: optimized plans produce IDENTICAL
results to the naive plans on TPC-H q1/q6/q19, and run_task applies
both to every decoded task plan.
"""

import numpy as np
import pytest

from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec
from blaze_tpu.ops.fusion import fuse_stages
from blaze_tpu.ops.pruning import prune_columns
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], N_PARTS, batch_rows=2048),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _rows(d):
    return sorted(zip(*d.values()), key=repr)


@pytest.mark.parametrize("q", ["q1", "q6", "q19", "q3"])
def test_fused_pruned_matches_naive(data, q):
    naive = run(build_query(q, _scans(data), N_PARTS))
    opt = run(prune_columns(fuse_stages(build_query(q, _scans(data), N_PARTS))))
    assert _rows(opt) == _rows(naive)


def test_fusion_collapses_q6_map_stage(data):
    """q6's filter+project+partial-agg become ONE AggExec with a fused
    pre_filter directly over the scan."""
    plan = fuse_stages(build_query("q6", _scans(data), N_PARTS))

    partials = []

    def walk(n):
        if isinstance(n, AggExec) and n.pre_filter is not None:
            partials.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    assert partials, "no fused partial agg found"
    fused = partials[0]
    assert type(fused.children[0]).__name__ == "MemoryScanExec"


def test_run_task_applies_optimizations(data):
    """run_task fuses+prunes every decoded task plan (TaskDefinitions
    never contain an exchange — the map side of q6 is exactly
    filter->project->partial-agg, the fusable chain)."""
    from blaze_tpu.exprs import col, lit
    from blaze_tpu.ops import AggExec as _Agg, AggFunction, AggMode, FilterExec, ProjectExec
    from blaze_tpu.schema import DataType
    from blaze_tpu.serde.from_proto import run_task
    from blaze_tpu.serde.to_proto import task_definition
    import datetime

    def map_side():
        scan = _scans(data)["lineitem"]
        dec12 = lambda v: lit(v, DataType.decimal(12, 2))
        f = FilterExec(
            scan,
            (col("l_shipdate") >= lit(datetime.date(1994, 1, 1)))
            & (col("l_discount") >= dec12("0.05")),
        )
        proj = ProjectExec(f, [(col("l_extendedprice") * col("l_discount")).alias("rev")])
        return _Agg(proj, AggMode.PARTIAL, [], [AggFunction("sum", col("rev"), "revenue")])

    naive = run(map_side())
    td = task_definition(map_side(), "t", 0, 0)
    # rev is decimal(25,4): the sum state is the wide two-limb layout
    got = {"revenue#sum_hi": [], "revenue#sum_lo25": [], "revenue#nonnull": []}
    for b in run_task(td):
        d = batch_to_pydict(b)
        for k in got:
            got[k].extend(d[k])
    # run_task drives partition 0 only; naive ran both partitions
    assert got["revenue#sum_hi"] == naive["revenue#sum_hi"][:1]
    assert got["revenue#sum_lo25"] == naive["revenue#sum_lo25"][:1]
