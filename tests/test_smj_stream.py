"""Streaming sort-merge join: cursor window + bounded memory + spill.

≙ reference sort_merge_join_exec.rs:58-309 + joins/stream_cursor.rs:38.
Differential oracle: the shuffled-hash join over the same inputs.
"""

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.joins import HashJoinExec, JoinType, SortMergeJoinExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.schema import DataType, Field, Schema

L_SCHEMA = Schema([Field("k", DataType.int64()), Field("l", DataType.int32())])
R_SCHEMA = Schema([Field("k", DataType.int64()), Field("r", DataType.string(8))])


def _sorted_batches(schema, rows, batch_rows):
    """Split key-sorted rows into batches."""
    out = []
    for i in range(0, len(rows[schema.names[0]]), batch_rows):
        out.append(
            batch_from_pydict({k: v[i : i + batch_rows] for k, v in rows.items()}, schema)
        )
    return out


def _mk_inputs(n_left=40, n_right=60, batch_rows=8, skew_key=None):
    rng = np.random.RandomState(7)
    lkeys = sorted(rng.randint(0, 20, n_left).tolist())
    rkeys = sorted(rng.randint(0, 20, n_right).tolist())
    if skew_key is not None:
        rkeys = sorted(rkeys + [skew_key] * 30)
    left = {"k": [k if k != 13 else None for k in lkeys], "l": list(range(len(lkeys)))}
    right = {"k": [k if k != 17 else None for k in rkeys],
             "r": [f"r{i}" for i in range(len(rkeys))]}
    lb = _sorted_batches(L_SCHEMA, left, batch_rows)
    rb = _sorted_batches(R_SCHEMA, right, batch_rows)
    return lb, rb


def _run(join):
    rows = []
    for p in range(join.num_partitions()):
        for b in join.execute(p, TaskContext(p, join.num_partitions())):
            d = batch_to_pydict(b)
            rows += list(zip(*[d[f.name] for f in join.schema.fields]))
    return sorted(rows, key=repr)


@pytest.mark.parametrize(
    "jt",
    [JoinType.INNER, JoinType.LEFT, JoinType.RIGHT, JoinType.FULL,
     JoinType.LEFT_SEMI, JoinType.LEFT_ANTI, JoinType.RIGHT_SEMI,
     JoinType.RIGHT_ANTI, JoinType.EXISTENCE],
)
def test_smj_matches_hash_join(jt):
    lb, rb = _mk_inputs()
    smj = SortMergeJoinExec(
        MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
        [col("k")], [col("k")], jt,
    )
    # oracle: hash join (build = right, probe = left, left output order)
    hj = HashJoinExec(
        MemoryScanExec([rb], R_SCHEMA), MemoryScanExec([lb], L_SCHEMA),
        [col("k")], [col("k")], jt, build_is_left=False,
    )
    assert _run(smj) == _run(hj)


def test_smj_window_stays_bounded(monkeypatch):
    """The window holds only key-overlapping batches: with disjoint key
    ranges per batch it never exceeds a few entries of the 8-batch side."""
    from blaze_tpu.ops.joins import smj as smj_mod

    left = {"k": list(range(0, 64)), "l": list(range(64))}
    right = {"k": list(range(0, 64)), "r": [f"r{i}" for i in range(64)]}
    lb = _sorted_batches(L_SCHEMA, left, 8)
    rb = _sorted_batches(R_SCHEMA, right, 8)
    smj = SortMergeJoinExec(
        MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
        [col("k")], [col("k")], JoinType.INNER,
    )
    peak = {"n": 0}
    orig_add = smj_mod._Window.add

    def spy_add(self, entry):
        orig_add(self, entry)
        peak["n"] = max(peak["n"], len(self.entries))

    monkeypatch.setattr(smj_mod._Window, "add", spy_add)
    out = list(smj.execute(0, TaskContext(0, 1)))
    total = sum(b.num_rows for b in out)
    assert total == 64
    assert 0 < peak["n"] <= 3, peak  # never the whole 8-batch side


def test_smj_spills_under_capped_budget():
    """A build side far larger than the memory budget passes via spill,
    not OOM (VERDICT round-1 item #5)."""
    n = 4096
    left = {"k": sorted(np.random.RandomState(3).randint(0, 500, 600).tolist()),
            "l": list(range(600))}
    right = {"k": sorted(np.random.RandomState(4).randint(0, 500, n).tolist()),
             "r": [f"r{i}" for i in range(n)]}
    lb = _sorted_batches(L_SCHEMA, left, 64)
    rb = _sorted_batches(R_SCHEMA, right, 256)
    try:
        MemManager._global = None
        MemManager.init(20_000)  # ~20 KB budget; right side is much bigger
        smj = SortMergeJoinExec(
            MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
            [col("k")], [col("k")], JoinType.INNER,
        )
        hj = HashJoinExec(
            MemoryScanExec([rb], R_SCHEMA), MemoryScanExec([lb], L_SCHEMA),
            [col("k")], [col("k")], JoinType.INNER, build_is_left=False,
        )
        got = _run(smj)
        MemManager._global = None
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))
        want = _run(hj)
        assert got == want
        assert smj.metrics.get("spill_count") >= 1
    finally:
        MemManager._global = None
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))


def test_smj_right_join_spills_under_capped_budget():
    """Build-preserved join under memory pressure: the final flush and
    eviction emission must survive entries being spilled."""
    n = 2048
    left = {"k": sorted(np.random.RandomState(5).randint(0, 300, 300).tolist()),
            "l": list(range(300))}
    right = {"k": sorted(np.random.RandomState(6).randint(0, 300, n).tolist()),
             "r": [f"r{i}" for i in range(n)]}
    lb = _sorted_batches(L_SCHEMA, left, 64)
    rb = _sorted_batches(R_SCHEMA, right, 256)
    try:
        MemManager._global = None
        MemManager.init(20_000)
        smj = SortMergeJoinExec(
            MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
            [col("k")], [col("k")], JoinType.RIGHT,
        )
        got = _run(smj)
        MemManager._global = None
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))
        hj = HashJoinExec(
            MemoryScanExec([rb], R_SCHEMA), MemoryScanExec([lb], L_SCHEMA),
            [col("k")], [col("k")], JoinType.RIGHT, build_is_left=False,
        )
        assert got == _run(hj)
    finally:
        MemManager._global = None
        MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))


def test_smj_nulls_first_proto_roundtrip():
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    lb = _sorted_batches(L_SCHEMA, {"k": [1, 2], "l": [0, 1]}, 2)
    rb = _sorted_batches(R_SCHEMA, {"k": [1, 2], "r": ["a", "b"]}, 2)
    smj = SortMergeJoinExec(
        MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
        [col("k")], [col("k")], JoinType.INNER, nulls_first=False,
    )
    rt = plan_from_proto(plan_to_proto(smj))
    assert rt.nulls_first is False
    assert _run(rt) == _run(smj)


def test_smj_nulls_last_ordering():
    left = {"k": [1, 2, None, None], "l": [0, 1, 2, 3]}
    right = {"k": [1, 1, 2, None], "r": ["a", "b", "c", "d"]}
    lb = _sorted_batches(L_SCHEMA, left, 2)
    rb = _sorted_batches(R_SCHEMA, right, 2)
    smj = SortMergeJoinExec(
        MemoryScanExec([lb], L_SCHEMA), MemoryScanExec([rb], R_SCHEMA),
        [col("k")], [col("k")], JoinType.FULL, nulls_first=False,
    )
    hj = HashJoinExec(
        MemoryScanExec([rb], R_SCHEMA), MemoryScanExec([lb], L_SCHEMA),
        [col("k")], [col("k")], JoinType.FULL, build_is_left=False,
    )
    assert _run(smj) == _run(hj)
