"""Catalyst-dump parser fuzzing over the LIVE Spark 3.5.1 q6 dump.

The Spark seam's contract (ROADMAP item 5): a ``toJSON`` dump mutated
the ways real-world serialization drift mutates it — field-order
shuffles, optionals degraded to null, unknown/extra fields — must
either parse+convert to a plan EQUIVALENT to the unmutated one, or be
rejected with a TYPED parse error (``CatalystParseError`` /
``Unsupported*``) — never an arbitrary crash (KeyError/AttributeError
escaping the seam) and never a silently different plan.
"""

import copy
import json
import os
import random

import pytest

from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.spark.converters import UnsupportedSparkExec
from blaze_tpu.spark.expr_converter import UnsupportedSparkExpr
from blaze_tpu.spark.plan_json import CatalystParseError, parse_plan_json

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "spark351_q6_plan.json")

#: the full typed-rejection surface of the dump-ingestion seam; every
#: other exception type escaping session.plan() is a crash (= failure)
TYPED_ERRORS = (CatalystParseError, UnsupportedSparkExec,
                UnsupportedSparkExpr, NotImplementedError)


def _load_flat():
    with open(FIXTURE) as f:
        return json.load(f)


def _session():
    """Schema-only catalog (no datagen): the fuzz contract is about
    plan construction, not execution."""
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.tpch import TPCH_SCHEMAS

    sess = BlazeSparkSession(default_parallelism=2)
    sess.register_table(
        "lineitem", MemoryScanExec([[], []], TPCH_SCHEMAS["lineitem"]))
    return sess


def _plan_fingerprint(sess, flat):
    """Structural identity of the converted plan (tree shape + schema;
    the 'equivalent plan' comparator)."""
    plan = sess.plan(parse_plan_json(copy.deepcopy(flat)))
    return plan.tree_string(), tuple(
        (f.name, str(f.dtype)) for f in plan.schema.fields)


def _assert_equivalent_or_typed_error(sess, baseline, mutated, what):
    try:
        got = _plan_fingerprint(sess, mutated)
    except TYPED_ERRORS:
        return  # typed rejection: acceptable outcome
    except Exception as e:  # noqa: BLE001 — the contract under test
        pytest.fail(f"{what}: untyped crash {type(e).__name__}: {e}")
    assert got == baseline, f"{what}: silently different plan"


def test_fixture_parses_and_converts():
    sess = _session()
    tree, schema = _plan_fingerprint(sess, _load_flat())
    assert "AggExec" in tree
    assert [n for n, _ in schema] == ["revenue"]


def test_field_order_shuffles_parse_equivalently():
    """Catalyst's jsonValue emits constructor-parameter order; nothing
    in the contract promises it.  Re-ordering every node object's keys
    (several seeds) must not change the plan."""
    sess = _session()
    flat = _load_flat()
    baseline = _plan_fingerprint(sess, flat)
    for seed in range(5):
        rng = random.Random(seed)
        shuffled = []
        for obj in copy.deepcopy(flat):
            keys = list(obj)
            rng.shuffle(keys)
            shuffled.append({k: obj[k] for k in keys})
        _assert_equivalent_or_typed_error(
            sess, baseline, shuffled, f"key-shuffle seed {seed}")
        # a shuffle is benign BY CONSTRUCTION: it must actually parse
        assert _plan_fingerprint(sess, shuffled) == baseline


def test_unknown_fields_are_ignored():
    """A newer Spark minor adding constructor params must not break
    ingestion of otherwise-identical dumps."""
    sess = _session()
    flat = _load_flat()
    baseline = _plan_fingerprint(sess, flat)
    mutated = copy.deepcopy(flat)
    for i, obj in enumerate(mutated):
        obj[f"__future_param_{i}"] = {"product-class": "x.y.New$", "n": i}
        obj["__another"] = None
    assert _plan_fingerprint(sess, mutated) == baseline


def test_nulled_fields_equivalent_or_typed_error():
    """Field-by-field null degradation (catalyst emits null for every
    type its serializer cannot encode): each single-field null must
    yield an equivalent plan or a typed rejection — never a crash,
    never a silently different plan."""
    sess = _session()
    flat = _load_flat()
    baseline = _plan_fingerprint(sess, flat)
    checked = 0
    for i, obj in enumerate(flat):
        for key in obj:
            if key in ("class", "num-children") or obj[key] is None:
                continue
            mutated = copy.deepcopy(flat)
            mutated[i][key] = None
            _assert_equivalent_or_typed_error(
                sess, baseline, mutated,
                f"null {obj['class'].rsplit('.', 1)[-1]}[{i}].{key}")
            checked += 1
    assert checked > 30  # the dump really was swept field-by-field


def test_truncated_and_structural_damage_is_typed():
    """Structural damage — truncated node array, surplus nodes, child
    counts pointing past the end — must raise the typed parse error."""
    sess = _session()
    flat = _load_flat()
    with pytest.raises(CatalystParseError):
        parse_plan_json(copy.deepcopy(flat)[:-1])      # truncated
    with pytest.raises(CatalystParseError):
        parse_plan_json(copy.deepcopy(flat) + [dict(flat[-1])])  # surplus
    broken = copy.deepcopy(flat)
    broken[0]["num-children"] = 7
    with pytest.raises(CatalystParseError):
        parse_plan_json(broken)
    with pytest.raises(CatalystParseError):
        parse_plan_json([])
    # nested expression arrays get the same treatment through convert
    gutted = copy.deepcopy(flat)
    for obj in gutted:
        if obj["class"].endswith("FilterExec"):
            obj["condition"] = obj["condition"][:2]    # torn expr tree
    _assert_equivalent_or_typed_error(
        sess, _plan_fingerprint(sess, flat), gutted, "torn condition")


def test_class_name_damage_is_typed_or_fallback():
    """Unknown plan/expression classes: either the strategy's typed
    Unsupported signal (no host fallback registered here) or a parse
    rejection — not a crash."""
    sess = _session()
    flat = _load_flat()
    baseline = _plan_fingerprint(sess, flat)
    for i in range(len(flat)):
        mutated = copy.deepcopy(flat)
        mutated[i]["class"] = "org.apache.spark.sql.execution.NotARealExec"
        _assert_equivalent_or_typed_error(
            sess, baseline, mutated, f"class rename node {i}")
