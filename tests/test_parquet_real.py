"""Real-world Parquet decode: files written by pyarrow (the stand-in
for Spark/arrow writers) with dictionary encoding, snappy/zstd/gzip/lz4
codecs, data page v1+v2, required + optional columns, FLBA decimals and
multiple pages per chunk — read through ParquetScanExec with pruning.

≙ reference parquet_exec.rs:65-418 (arrow-rs readers handle all of
this natively; round-1 VERDICT item #7 flagged our subset).
"""

import datetime
import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from blaze_tpu.batch import batch_to_pydict, concat_batches
from blaze_tpu.exprs import col, lit
from blaze_tpu.ops import MemoryScanExec, ParquetScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

N = 500


def _table():
    rng = np.random.RandomState(11)
    ints = rng.randint(-1000, 1000, N)
    return pa.table(
        {
            "i32": pa.array(
                [None if i % 7 == 0 else int(ints[i]) for i in range(N)], pa.int32()
            ),
            "i64": pa.array([int(x) * 10_000_000_000 for x in ints], pa.int64()),
            "f64": pa.array(
                [None if i % 11 == 0 else float(ints[i]) / 3 for i in range(N)],
                pa.float64(),
            ),
            "s": pa.array(
                [None if i % 5 == 0 else f"val_{ints[i] % 37}" for i in range(N)],
                pa.string(),
            ),
            "b": pa.array([bool(ints[i] % 2) for i in range(N)], pa.bool_()),
            "d": pa.array(
                [datetime.date(2020, 1, 1) + datetime.timedelta(days=int(x) % 365) for x in ints],
                pa.date32(),
            ),
            "dec": pa.array(
                [decimal.Decimal(int(x)) / 100 for x in ints], pa.decimal128(12, 2)
            ),
        }
    )


SCHEMA = Schema(
    [
        Field("i32", DataType.int32()),
        Field("i64", DataType.int64()),
        Field("f64", DataType.float64()),
        Field("s", DataType.string(16)),
        Field("b", DataType.bool_()),
        Field("d", DataType.date32()),
        Field("dec", DataType.decimal(12, 2)),
    ]
)


def _read_ours(path, predicate=None):
    scan = ParquetScanExec([[str(path)]], SCHEMA, predicate)
    out = []
    for b in scan.execute(0, TaskContext(0, 1)):
        out.append(b)
    return batch_to_pydict(concat_batches(out)) if out else {f.name: [] for f in SCHEMA.fields}, scan


def _expected(table):
    d = table.to_pydict()
    exp = dict(d)
    exp["d"] = [None if v is None else (v - datetime.date(1970, 1, 1)).days for v in d["d"]]
    exp["dec"] = [None if v is None else int(v.scaleb(2)) for v in d["dec"]]
    return exp


def _assert_equal(got, exp):
    for k, want in exp.items():
        g = got[k]
        if k == "f64":
            for a, b in zip(g, want):
                assert (a is None) == (b is None) and (a is None or abs(a - b) < 1e-9), k
        else:
            assert g == want, f"column {k}"


@pytest.mark.parametrize(
    "codec,dictionary,page_version",
    [
        ("snappy", True, "1.0"),
        ("snappy", False, "1.0"),
        ("zstd", True, "1.0"),
        ("gzip", True, "1.0"),
        ("none", True, "1.0"),
        ("snappy", True, "2.0"),
        ("zstd", False, "2.0"),
        ("lz4", True, "1.0"),
    ],
)
def test_pyarrow_roundtrip(tmp_path, codec, dictionary, page_version):
    table = _table()
    path = tmp_path / f"t_{codec}_{dictionary}_{page_version}.parquet"
    papq.write_table(
        table, path,
        compression=codec if codec != "none" else "NONE",
        use_dictionary=dictionary,
        data_page_version=page_version,
        row_group_size=200,            # multiple row groups
        data_page_size=1024,           # many small pages per chunk
        write_statistics=True,
    )
    got, _ = _read_ours(path)
    _assert_equal(got, _expected(table))


def test_required_columns(tmp_path):
    """REQUIRED (non-nullable) columns carry no def levels."""
    table = pa.table(
        {"r": pa.array(list(range(50)), pa.int64())},
        schema=pa.schema([pa.field("r", pa.int64(), nullable=False)]),
    )
    path = tmp_path / "req.parquet"
    papq.write_table(table, path, compression="snappy")
    scan = ParquetScanExec([[str(path)]], Schema([Field("r", DataType.int64())]))
    out = list(scan.execute(0, TaskContext(0, 1)))
    d = batch_to_pydict(concat_batches(out))
    assert d["r"] == list(range(50))


def test_row_group_pruning_on_real_file(tmp_path):
    table = pa.table({"x": pa.array(list(range(1000)), pa.int64())})
    path = tmp_path / "pruned.parquet"
    papq.write_table(table, path, row_group_size=100, compression="snappy")
    pred = col("x") >= lit(950)
    got, scan = _read_ours_with_schema(path, Schema([Field("x", DataType.int64())]), pred)
    # pruning is row-group granular; residual filtering is FilterExec's
    # job — the group containing 950 survives whole
    assert got["x"] == list(range(900, 1000))
    assert scan.metrics.get("pruned_row_groups") == 9


def _read_ours_with_schema(path, schema, predicate=None):
    scan = ParquetScanExec([[str(path)]], schema, predicate)
    out = []
    for b in scan.execute(0, TaskContext(0, 1)):
        out.append(b)
    return batch_to_pydict(concat_batches(out)) if out else {f.name: [] for f in schema.fields}, scan


def test_missing_column_schema_adaption(tmp_path):
    table = pa.table({"a": pa.array([1, 2, 3], pa.int64())})
    path = tmp_path / "missing.parquet"
    papq.write_table(table, path)
    schema = Schema([Field("a", DataType.int64()), Field("zzz", DataType.string(8))])
    got, _ = _read_ours_with_schema(path, schema)
    assert got["a"] == [1, 2, 3]
    assert got["zzz"] == [None, None, None]


def test_decimal_pruning_flba_stats(tmp_path):
    vals = [decimal.Decimal(i) / 100 for i in range(-500, 500)]
    table = pa.table({"dec": pa.array(vals, pa.decimal128(12, 2))})
    path = tmp_path / "dec.parquet"
    papq.write_table(table, path, row_group_size=250)
    schema = Schema([Field("dec", DataType.decimal(12, 2))])
    dec_lit = lit("4.0", DataType.decimal(12, 2))
    got, scan = _read_ours_with_schema(path, schema, col("dec") >= dec_lit)
    # last row group (unscaled 250..499) survives whole; first three pruned
    assert got["dec"] == list(range(250, 500))
    assert scan.metrics.get("pruned_row_groups") == 3
