"""Generator for the vendored Spark 3.5.1 ``toJSON`` physical-plan
dumps (spark351_*.json in this directory).

These dumps reproduce the REAL catalyst serialization shape — preorder
node arrays with child-INDEX fields, ``product-class`` case objects
for modes/origins/eval modes/join types/build sides, jvmId'ed ExprIds,
table-qualified attributes, Cast nodes with timeZoneId, date literals
as days-since-epoch strings, WholeStageCodegen/InputAdapter/
ColumnarToRow wrappers, and FileSourceScan nodes carrying
requiredSchema/dataFilters/pushedFilters — so the parser and
converters are exercised against Spark's actual output encoding, not
the simplified emulation in tests/spark_fixtures.py (the shape was
validated against a live Spark 3.5.1 dump for TPC-H q6,
spark351_q6_plan.json).

Run ``python tests/fixtures/gen_spark351_dumps.py`` to regenerate.
"""

import datetime
import json
import os

X = "org.apache.spark.sql.catalyst.expressions."
A = "org.apache.spark.sql.catalyst.expressions.aggregate."
P = "org.apache.spark.sql.execution."
PHYS = "org.apache.spark.sql.catalyst.plans.physical."

JVM = "a3f18c6d-2b47-4e09-9d45-7c31f8b6e2aa"
LEGACY = {"product-class": X + "EvalMode$LEGACY$"}


def T(cls, children=(), **fields):
    return {"_cls": cls, "_children": list(children), **fields}


def flatten(t):
    out = []

    def go(n):
        fields = {k: v for k, v in n.items() if k not in ("_cls", "_children")}
        out.append({"class": n["_cls"], "num-children": len(n["_children"]), **fields})
        for c in n["_children"]:
            go(c)

    go(t)
    return out


def eid(i):
    return {"product-class": X + "ExprId", "id": i, "jvmId": JVM}


def attr(name, i, dtype, table=None):
    return T(
        X + "AttributeReference", name=name, dataType=dtype, nullable=True,
        metadata={}, exprId=eid(i),
        qualifier=(["spark_catalog", "default", table] if table else []),
    )


def lit(value, dtype):
    return T(X + "Literal", value=None if value is None else str(value), dataType=dtype)


def date_lit(y, m, d):
    return lit((datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days, "date")


def alias(child, name, i):
    return T(X + "Alias", [child], name=name, exprId=eid(i), qualifier=[],
             explicitMetadata=None, nonInheritableMetadataKeys=[])


def binop(cls, left, right, eval_mode=False):
    extra = {"evalMode": LEGACY} if eval_mode else {}
    return T(X + cls, [left, right], left=0, right=1, **extra)


def is_not_null(child):
    return T(X + "IsNotNull", [child], child=0)


def cast(child, to):
    return T(X + "Cast", [child], child=0, dataType=to,
             timeZoneId="Etc/UTC", evalMode=LEGACY)


def and_all(preds):
    out = preds[0]
    for p in preds[1:]:
        out = T(X + "And", [out, p], left=0, right=1)
    return out


def sort_order(child, asc=True):
    return T(
        X + "SortOrder", [child], child=0,
        direction={"product-class": X + ("Ascending$" if asc else "Descending$")},
        nullOrdering={"product-class": X + ("NullsFirst$" if asc else "NullsLast$")},
        sameOrderExpressions=[],
    )


def agg_expr(fn, mode, result_id, distinct=False):
    return T(
        A + "AggregateExpression", [fn], aggregateFunction=0,
        mode={"product-class": A + mode + "$"},
        isDistinct=distinct, filter=None, resultId=eid(result_id),
    )


def sum_(child):
    return T(A + "Sum", [child], child=0, evalMode=LEGACY)


def avg_(child):
    return T(A + "Average", [child], child=0, evalMode=LEGACY)


def count_(child=None):
    return T(A + "Count", [child or lit(1, "integer")])


def wsc(child, stage_id):
    return T(P + "WholeStageCodegenExec", [child], child=0, codegenStageId=stage_id)


def input_adapter(child):
    return T(P + "InputAdapter", [child], child=0)


def col_to_row(child):
    return T(P + "ColumnarToRowExec", [child], child=0)


_SPARK_T = {"date": "date", "integer": "integer", "long": "long", "string": "string"}


def scan(table, attrs, data_filters=()):
    fields = []
    for a in attrs:
        dt = a["dataType"]
        fields.append({
            "name": a["name"], "type": dt, "nullable": True, "metadata": {},
        })
    return T(
        P + "FileSourceScanExec",
        relation=None,
        output=[flatten(a) for a in attrs],
        requiredSchema={"type": "struct", "fields": fields},
        partitionFilters=[],
        optionalBucketSet=None,
        optionalNumCoalescedBuckets=None,
        dataFilters=[flatten(f) for f in data_filters],
        tableIdentifier={
            "product-class": "org.apache.spark.sql.catalyst.TableIdentifier",
            "table": table, "database": "default",
        },
        disableBucketedScan=False,
    )


def filter_(condition, child):
    return T(P + "FilterExec", [child], condition=flatten(condition), child=0)


def project(plist, child):
    return T(P + "ProjectExec", [child],
             projectList=[flatten(p) for p in plist], child=0)


def hash_agg(groupings, aggs, child, result=None, offset=0, partial=False,
             agg_attrs=None):
    return T(
        P + "aggregate.HashAggregateExec", [child],
        requiredChildDistributionExpressions=None if partial else [],
        isStreaming=False, numShufflePartitions=None,
        groupingExpressions=[flatten(g) for g in groupings],
        aggregateExpressions=[flatten(a) for a in aggs],
        aggregateAttributes=[flatten(a) for a in (agg_attrs or [])],
        initialInputBufferOffset=offset,
        resultExpressions=[flatten(r) for r in (result or [])],
        child=0,
    )


def single_partition():
    return {"product-class": PHYS + "SinglePartition$"}


def hash_partitioning(keys, n):
    return flatten(T(PHYS + "HashPartitioning", list(keys), numPartitions=n))


def range_partitioning(orders, n):
    return flatten(T(PHYS + "RangePartitioning", list(orders), numPartitions=n))


def shuffle(partitioning, child):
    return T(
        P + "exchange.ShuffleExchangeExec", [child],
        outputPartitioning=partitioning, child=0,
        shuffleOrigin={"product-class": P + "exchange.ENSURE_REQUIREMENTS$"},
        advisoryPartitionSize=None,
    )


def broadcast(child, keys):
    return T(
        P + "exchange.BroadcastExchangeExec", [child],
        mode={
            "product-class": P + "joins.HashedRelationBroadcastMode",
            "key": [flatten(k) for k in keys], "isNullAware": False,
        },
        child=0,
    )


def bhj(left_keys, right_keys, join_type, build_left, left, right):
    return T(
        P + "joins.BroadcastHashJoinExec", [left, right],
        leftKeys=[flatten(k) for k in left_keys],
        rightKeys=[flatten(k) for k in right_keys],
        joinType={"product-class": "org.apache.spark.sql.catalyst.plans." + join_type + "$"},
        buildSide={"product-class": P + "joins." + ("BuildLeft$" if build_left else "BuildRight$")},
        condition=None, left=0, right=1, isNullAwareAntiJoin=False,
    )


def smj(left_keys, right_keys, join_type, left, right):
    return T(
        P + "joins.SortMergeJoinExec", [left, right],
        leftKeys=[flatten(k) for k in left_keys],
        rightKeys=[flatten(k) for k in right_keys],
        joinType={"product-class": "org.apache.spark.sql.catalyst.plans." + join_type + "$"},
        condition=None, left=0, right=1, isSkewJoin=False,
    )


def sort(orders, child, global_=True):
    return T(P + "SortExec", [child],
             sortOrder=[flatten(o) for o in orders], child=0,
             testSpillFrequency=0, **{"global": global_})


def take_ordered(n, orders, plist, child):
    return T(
        P + "TakeOrderedAndProjectExec", [child], limit=n,
        sortOrder=[flatten(o) for o in orders],
        projectList=[flatten(p) for p in plist], child=0, offset=0,
    )


def expand(projections, output, child):
    return T(
        P + "ExpandExec", [child],
        projections=[[flatten(e) for e in proj] for proj in projections],
        output=[flatten(a) for a in output], child=0,
    )


# ------------------------------------------------------------------ q1

def gen_q1():
    """TPC-H q1: pruned scan -> filter -> project -> two-stage agg with
    the avg/sum/count set -> range exchange -> global sort."""
    li = "lineitem"
    d122 = "decimal(12,2)"
    cols = {
        "l_quantity": (5, d122), "l_extendedprice": (6, d122),
        "l_discount": (7, d122), "l_tax": (8, d122),
        "l_returnflag": (9, "string"), "l_linestatus": (10, "string"),
        "l_shipdate": (11, "date"),
    }
    a = {n: attr(n, i, t, li) for n, (i, t) in cols.items()}
    ship_pred = binop("LessThanOrEqual", a["l_shipdate"], date_lit(1998, 9, 2))
    sc = scan(li, [a[n] for n in cols], data_filters=[
        is_not_null(a["l_shipdate"]), ship_pred])
    f = filter_(and_all([is_not_null(a["l_shipdate"]), ship_pred]),
                col_to_row(input_adapter(sc)))
    one = cast(lit(1, "integer"), d122)
    disc_price = binop("Multiply", a["l_extendedprice"],
                       binop("Subtract", one, a["l_discount"], True), True)
    charge = binop("Multiply", disc_price,
                   binop("Add", cast(lit(1, "integer"), d122), a["l_tax"], True), True)
    p = project([a["l_returnflag"], a["l_linestatus"], a["l_quantity"],
                 a["l_extendedprice"],
                 alias(disc_price, "disc_price", 90),
                 alias(charge, "charge", 91),
                 a["l_discount"]], f)
    dp = attr("disc_price", 90, "decimal(25,4)")
    ch = attr("charge", 91, "decimal(38,6)")
    groups = [a["l_returnflag"], a["l_linestatus"]]
    fns = [
        ("sum_qty", sum_(a["l_quantity"]), 201),
        ("sum_base_price", sum_(a["l_extendedprice"]), 202),
        ("sum_disc_price", sum_(dp), 203),
        ("sum_charge", sum_(ch), 204),
        ("avg_qty", avg_(a["l_quantity"]), 205),
        ("avg_price", avg_(a["l_extendedprice"]), 206),
        ("avg_disc", avg_(a["l_discount"]), 207),
        ("count_order", count_(), 208),
    ]
    partial = hash_agg(groups, [agg_expr(fn, "Partial", rid) for _, fn, rid in fns],
                       p, partial=True)
    ex = shuffle(hash_partitioning(groups, 2), input_adapter(wsc(partial, 1)))
    results = groups + [
        alias(attr(name, rid, "decimal(38,6)"), name, 300 + k)
        for k, (name, _, rid) in enumerate(fns)
    ]
    final = hash_agg(groups, [agg_expr(fn, "Final", rid) for _, fn, rid in fns],
                     input_adapter(ex), result=results)
    orders = [sort_order(g) for g in groups]
    ex2 = shuffle(range_partitioning(orders, 2), input_adapter(wsc(final, 2)))
    return wsc(sort(orders, input_adapter(ex2)), 3)


# ------------------------------------------------------------------ q3

def _q3_parts(join_builder):
    cu = "customer"
    od = "orders"
    li = "lineitem"
    d122 = "decimal(12,2)"
    c_custkey = attr("c_custkey", 41, "long", cu)
    c_mkt = attr("c_mktsegment", 42, "string", cu)
    o_orderkey = attr("o_orderkey", 21, "long", od)
    o_custkey = attr("o_custkey", 22, "long", od)
    o_orderdate = attr("o_orderdate", 23, "date", od)
    o_ship = attr("o_shippriority", 24, "integer", od)
    l_orderkey = attr("l_orderkey", 1, "long", li)
    l_price = attr("l_extendedprice", 6, d122, li)
    l_disc = attr("l_discount", 7, d122, li)
    l_ship = attr("l_shipdate", 11, "date", li)

    mkt = binop("EqualTo", c_mkt, lit("BUILDING", "string"))
    cscan = scan(cu, [c_custkey, c_mkt], data_filters=[is_not_null(c_mkt), mkt])
    cside = project([c_custkey],
                    filter_(and_all([is_not_null(c_mkt), mkt]),
                            col_to_row(input_adapter(cscan))))
    od_pred = binop("LessThan", o_orderdate, date_lit(1995, 3, 15))
    oscan = scan(od, [o_orderkey, o_custkey, o_orderdate, o_ship],
                 data_filters=[is_not_null(o_orderdate), od_pred])
    oside = filter_(and_all([is_not_null(o_orderdate), od_pred]),
                    col_to_row(input_adapter(oscan)))
    j1 = join_builder([c_custkey], [o_custkey], cside, oside, stage=1)
    j1p = project([o_orderkey, o_orderdate, o_ship], j1)
    l_pred = binop("GreaterThan", l_ship, date_lit(1995, 3, 15))
    lscan = scan(li, [l_orderkey, l_price, l_disc, l_ship],
                 data_filters=[is_not_null(l_ship), l_pred])
    lside = filter_(and_all([is_not_null(l_ship), l_pred]),
                    col_to_row(input_adapter(lscan)))
    j2 = join_builder([o_orderkey], [l_orderkey], j1p, lside, stage=2)
    one = cast(lit(1, "integer"), d122)
    rev = binop("Multiply", l_price, binop("Subtract", one, l_disc, True), True)
    p = project([l_orderkey, o_orderdate, o_ship, alias(rev, "rev", 95)], j2)
    revattr = attr("rev", 95, "decimal(25,4)")
    groups = [l_orderkey, o_orderdate, o_ship]
    partial = hash_agg(groups, [agg_expr(sum_(revattr), "Partial", 210)], p,
                       partial=True)
    ex = shuffle(hash_partitioning(groups, 2), input_adapter(partial))
    srev = attr("sum(rev)", 210, "decimal(35,4)")
    final = hash_agg(
        groups, [agg_expr(sum_(revattr), "Final", 210)], input_adapter(ex),
        result=groups + [alias(srev, "revenue", 211)])
    revenue = attr("revenue", 211, "decimal(35,4)")
    return take_ordered(
        10, [sort_order(revenue, asc=False), sort_order(o_orderdate)],
        [l_orderkey, revenue, o_orderdate, o_ship], final)


def gen_q3_bhj():
    """TPC-H q3 as Spark plans it under the default broadcast
    threshold: two BuildLeft broadcast hash joins."""
    def jb(lk, rk, left, right, stage):
        return bhj(lk, rk, "Inner", True, broadcast(left, lk), right)

    return _q3_parts(jb)


def gen_q3_smj():
    """TPC-H q3 with autoBroadcastJoinThreshold=-1: both joins as
    exchange -> sort -> SortMergeJoin."""
    def jb(lk, rk, left, right, stage):
        ls = sort([sort_order(k) for k in lk],
                  input_adapter(shuffle(hash_partitioning(lk, 2), left)),
                  global_=False)
        rs = sort([sort_order(k) for k in rk],
                  input_adapter(shuffle(hash_partitioning(rk, 2), right)),
                  global_=False)
        return smj(lk, rk, "Inner", ls, rs)

    return _q3_parts(jb)


# --------------------------------------------------------- TPC-DS q27

def gen_ds_q27():
    """TPC-DS q27: demographic slice x date x store x item rollup —
    ExpandExec carrying Spark's rollup projections (grouped-away
    columns nulled, spark_grouping_id literal) + two-stage avg."""
    ss = "store_sales"
    dd = "date_dim"
    it = "item"
    st = "store"
    cd = "customer_demographics"
    d72 = "decimal(7,2)"
    ss_sold = attr("ss_sold_date_sk", 501, "long", ss)
    ss_item = attr("ss_item_sk", 502, "long", ss)
    ss_cdemo = attr("ss_cdemo_sk", 503, "long", ss)
    ss_store = attr("ss_store_sk", 504, "long", ss)
    ss_q = attr("ss_quantity", 505, "integer", ss)
    ss_lp = attr("ss_list_price", 506, d72, ss)
    ss_cp = attr("ss_coupon_amt", 507, d72, ss)
    ss_sp = attr("ss_sales_price", 508, d72, ss)
    cd_sk = attr("cd_demo_sk", 511, "long", cd)
    cd_g = attr("cd_gender", 512, "string", cd)
    cd_m = attr("cd_marital_status", 513, "string", cd)
    cd_e = attr("cd_education_status", 514, "string", cd)
    d_sk = attr("d_date_sk", 521, "long", dd)
    d_year = attr("d_year", 522, "integer", dd)
    s_sk = attr("s_store_sk", 531, "long", st)
    s_state = attr("s_state", 532, "string", st)
    i_sk = attr("i_item_sk", 541, "long", it)
    i_id = attr("i_item_id", 542, "string", it)

    cd_pred = and_all([
        binop("EqualTo", cd_g, lit("M", "string")),
        binop("EqualTo", cd_m, lit("S", "string")),
        binop("EqualTo", cd_e, lit("College", "string")),
    ])
    cside = project([cd_sk], filter_(cd_pred, col_to_row(input_adapter(
        scan(cd, [cd_sk, cd_g, cd_m, cd_e])))))
    d_pred = binop("EqualTo", d_year, lit(2002, "integer"))
    dside = project([d_sk], filter_(d_pred, col_to_row(input_adapter(
        scan(dd, [d_sk, d_year])))))
    sscan = col_to_row(input_adapter(scan(
        ss, [ss_sold, ss_item, ss_cdemo, ss_store, ss_q, ss_lp, ss_cp, ss_sp])))
    j = bhj([cd_sk], [ss_cdemo], "Inner", True, broadcast(cside, [cd_sk]), sscan)
    j = bhj([d_sk], [ss_sold], "Inner", True, broadcast(dside, [d_sk]), j)
    stside = project([s_sk, s_state], col_to_row(input_adapter(scan(st, [s_sk, s_state]))))
    j = bhj([s_sk], [ss_store], "Inner", True, broadcast(stside, [s_sk]), j)
    itside = project([i_sk, i_id], col_to_row(input_adapter(scan(it, [i_sk, i_id]))))
    j = bhj([i_sk], [ss_item], "Inner", True, broadcast(itside, [i_sk]), j)
    pre = project([ss_q, ss_lp, ss_cp, ss_sp, i_id, s_state], j)

    gid = attr("spark_grouping_id", 560, "long")
    out_i = attr("i_item_id", 561, "string")
    out_s = attr("s_state", 562, "string")
    projections = [
        [ss_q, ss_lp, ss_cp, ss_sp, i_id, s_state, lit(0, "long")],
        [ss_q, ss_lp, ss_cp, ss_sp, i_id, lit(None, "string"), lit(1, "long")],
        [ss_q, ss_lp, ss_cp, ss_sp, lit(None, "string"), lit(None, "string"),
         lit(3, "long")],
    ]
    ex_node = expand(projections,
                     [ss_q, ss_lp, ss_cp, ss_sp, out_i, out_s, gid], pre)
    groups = [out_i, out_s, gid]
    fns = [
        ("agg1", avg_(ss_q), 571),
        ("agg2", avg_(ss_lp), 572),
        ("agg3", avg_(ss_cp), 573),
        ("agg4", avg_(ss_sp), 574),
    ]
    partial = hash_agg(groups, [agg_expr(fn, "Partial", rid) for _, fn, rid in fns],
                       ex_node, partial=True)
    exch = shuffle(hash_partitioning(groups, 2), input_adapter(partial))
    results = [alias(out_i, "i_item_id", 581), alias(out_s, "s_state", 582),
               alias(gid, "g_id", 583)] + [
        alias(attr(name, rid, "double"), name, 590 + k)
        for k, (name, _, rid) in enumerate(fns)
    ]
    final = hash_agg(groups, [agg_expr(fn, "Final", rid) for _, fn, rid in fns],
                     input_adapter(exch), result=results)
    out_attrs = [attr("i_item_id", 581, "string"), attr("s_state", 582, "string"),
                 attr("g_id", 583, "long")] + [
        attr(name, 590 + k, "double") for k, (name, _, _) in enumerate(fns)]
    return take_ordered(
        100, [sort_order(out_attrs[0]), sort_order(out_attrs[1])],
        out_attrs, final)


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    for name, gen in (
        ("spark351_q1_plan.json", gen_q1),
        ("spark351_q3_bhj_plan.json", gen_q3_bhj),
        ("spark351_q3_smj_plan.json", gen_q3_smj),
        ("spark351_ds_q27_rollup_plan.json", gen_ds_q27),
    ):
        path = os.path.join(here, name)
        with open(path, "w") as f:
            json.dump(flatten(gen()), f)
        print(name, os.path.getsize(path), "bytes")


if __name__ == "__main__":
    main()
