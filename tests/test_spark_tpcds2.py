"""TPC-DS differentials through full Spark conversion — round-5 widening.

Extends test_spark_tpcds.py's 13-query slice toward the reference
gate's breadth (``tpcds-reusable.yml:83-143``): every test here
authors a catalyst ``toJSON`` physical-plan dump, crosses strategy +
expression conversion, executes via BOTH the in-process collect path
and the stage scheduler (TaskDefinition protobuf bytes + shuffle
files), and validates against the independent numpy oracles.

Dual-shape: each join-bearing plan is parametrized over the broadcast
shape AND the forced sort-merge shape (``SortMergeJoinExec`` over
sorted shuffles) — the reference CI runs every query twice, with
broadcast joins and with ``autoBroadcastJoinThreshold=-1``
(``tpcds-reusable.yml:123-143``).
"""

import json

import pytest

from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.tpcds import TPCDS_SCHEMAS
from blaze_tpu.tpcds import oracle as O
from blaze_tpu.tpcds.datagen import generate_all
from blaze_tpu.tpch.datagen import table_to_batches

import spark_fixtures as F
from test_spark_tpcds import (
    N_PARTS,
    a,
    and_,
    ar,
    in_,
    i32,
    ne,
    or_,
    s,
    two_stage,
)
from test_tpcds import (
    _check_demo_avgs,
    _check_ship_lag,
    _check_ticket_report,
)

pytestmark = pytest.mark.slow

SCALE = 0.002

_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _clear_caches_every_few_tests():
    """Same jaxlib compiled-program ceiling mitigation as
    test_tpcds.py — this module's dual-shape matrix compiles a lot of
    distinct programs."""
    yield
    _SINCE_CLEAR["n"] += 1
    if _SINCE_CLEAR["n"] % 8 == 0:
        import jax

        from blaze_tpu.ops.joins.broadcast import clear_join_map_cache
        from blaze_tpu.runtime.kernel_cache import clear_kernel_cache

        clear_kernel_cache()
        clear_join_map_cache()
        jax.clear_caches()


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def sess(data):
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCDS_SCHEMAS:
        sess.register_table(
            name,
            MemoryScanExec(
                table_to_batches(data[name], TPCDS_SCHEMAS[name], N_PARTS,
                                 batch_rows=4096),
                TPCDS_SCHEMAS[name],
            ),
        )
    return sess


@pytest.fixture(params=["bhj", "smj"])
def strategy(request):
    return request.param


def _ss(keys, child):
    """Sorted shuffle: the child of each forced-SMJ side."""
    return F.sort([F.sort_order(k) for k in keys],
                  F.shuffle(F.hash_partitioning(keys, N_PARTS), child),
                  global_=False)


def join(strategy, build, probe, bkeys, pkeys, jt="Inner",
         build_side="left", condition=None):
    """Strategy-parameterized equi-join: BroadcastHashJoin with the
    dimension side broadcast, or the forced-SMJ shape
    (SortMergeJoin over sorted hash shuffles) that the reference CI's
    autoBroadcastJoinThreshold=-1 run plans."""
    if strategy == "bhj":
        if build_side == "left":
            return F.bhj(bkeys, pkeys, jt, "left", F.broadcast(build),
                         probe, condition=condition)
        return F.bhj(pkeys, bkeys, jt, "right", probe, F.broadcast(build),
                     condition=condition)
    if build_side == "left":
        return F.smj(bkeys, pkeys, jt, _ss(bkeys, build), _ss(pkeys, probe),
                     condition=condition)
    return F.smj(pkeys, bkeys, jt, _ss(pkeys, probe), _ss(bkeys, build),
                 condition=condition)


def _execute_both(sess, plan):
    js = json.dumps(F.flatten(plan))
    got = sess.execute(js)
    got_sched = sess.execute_distributed(js)
    rows = sorted(
        zip(*got.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got else []
    rows_sched = sorted(
        zip(*got_sched.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got_sched else []
    assert rows == rows_sched, "in-process vs scheduler mismatch"
    return got


# ------------------------------------------------ q7/q26 demographic averages

def _demo_avg_plan(st, fact, cdemo_c, date_c, promo_c, item_c, qty_c,
                   list_c, coupon_c, sales_c):
    cd = F.project(
        [a("cd_demo_sk")],
        F.filter_(
            and_(F.binop("EqualTo", a("cd_gender"), s("M")),
                 F.binop("EqualTo", a("cd_marital_status"), s("S")),
                 F.binop("EqualTo", a("cd_education_status"), s("College"))),
            F.scan("customer_demographics",
                   [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
                    a("cd_education_status")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    pr = F.project(
        [a("p_promo_sk")],
        F.filter_(
            or_(F.binop("EqualTo", a("p_channel_email"), s("N")),
                F.binop("EqualTo", a("p_channel_event"), s("N"))),
            F.scan("promotion", [a("p_promo_sk"), a("p_channel_email"),
                                 a("p_channel_event")]),
        ),
    )
    sl = F.scan(fact, [a(cdemo_c), a(date_c), a(promo_c), a(item_c),
                       a(qty_c), a(list_c), a(coupon_c), a(sales_c)])
    j = join(st, cd, sl, [a("cd_demo_sk")], [a(cdemo_c)])
    j = join(st, dt, j, [a("d_date_sk")], [a(date_c)])
    j = join(st, pr, j, [a("p_promo_sk")], [a(promo_c)])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
    agg = two_stage(
        [a("i_item_id")],
        [(F.avg(a(qty_c)), 501), (F.avg(a(list_c)), 502),
         (F.avg(a(coupon_c)), 503), (F.avg(a(sales_c)), 504)],
        j,
    )
    return F.take_ordered(
        100, [F.sort_order(a("i_item_id"))],
        [a("i_item_id"),
         F.alias(ar("agg1", 501, "double"), "agg1", 511),
         F.alias(ar("agg2", 502, "decimal(11,6)"), "agg2", 512),
         F.alias(ar("agg3", 503, "decimal(11,6)"), "agg3", 513),
         F.alias(ar("agg4", 504, "decimal(11,6)"), "agg4", 514)],
        agg,
    )


def test_spark_q7(sess, data, strategy):
    got = _execute_both(sess, _demo_avg_plan(
        strategy, "store_sales", "ss_cdemo_sk", "ss_sold_date_sk",
        "ss_promo_sk", "ss_item_sk", "ss_quantity", "ss_list_price",
        "ss_coupon_amt", "ss_sales_price"))
    _check_demo_avgs(got, O.oracle_q7(data))


def test_spark_q26(sess, data, strategy):
    got = _execute_both(sess, _demo_avg_plan(
        strategy, "catalog_sales", "cs_bill_cdemo_sk", "cs_sold_date_sk",
        "cs_promo_sk", "cs_item_sk", "cs_quantity", "cs_list_price",
        "cs_coupon_amt", "cs_sales_price"))
    _check_demo_avgs(got, O.oracle_q26(data))


# ------------------------------------------- q19 star + non-equi zip residual

def test_spark_q19(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_moy"), i32(11)),
                       F.binop("EqualTo", a("d_year"), i32(1998))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_moy"), a("d_year")])),
    )
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_brand"), a("i_manufact_id"),
         a("i_manufact")],
        F.filter_(F.binop("EqualTo", a("i_manager_id"), i32(8)),
                  F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_brand"),
                                  a("i_manufact_id"), a("i_manufact"),
                                  a("i_manager_id")])),
    )
    cust = F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk")])
    addr = F.scan("customer_address", [a("ca_address_sk"), a("ca_zip")])
    st_ = F.scan("store", [a("s_store_sk"), a("s_zip")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"),
                                a("ss_customer_sk"), a("ss_store_sk"),
                                a("ss_ext_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    j = join(strategy, cust, j, [a("c_customer_sk")], [a("ss_customer_sk")])
    j = join(strategy, addr, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    sub = lambda c: F.T(F.X + "Substring", [c, i32(1), i32(5)])
    j = F.filter_(ne(sub(a("ca_zip")), sub(a("s_zip"))), j)
    agg = two_stage(
        [a("i_brand_id"), a("i_brand"), a("i_manufact_id"), a("i_manufact")],
        [(F.sum_(a("ss_ext_sales_price")), 501)],
        j,
    )
    price = ar("ext_price", 501, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(price, asc=False), F.sort_order(a("i_brand")),
         F.sort_order(a("i_brand_id")), F.sort_order(a("i_manufact_id")),
         F.sort_order(a("i_manufact"))],
        [F.alias(a("i_brand_id"), "brand_id", 510),
         F.alias(a("i_brand"), "brand", 511),
         F.alias(a("i_manufact_id"), "manufact_id", 512),
         F.alias(a("i_manufact"), "manufact", 513),
         F.alias(price, "ext_price", 514)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q19(data)
    assert exp, "q19 oracle empty"
    rows = {
        (bid, b, mid, m): v
        for bid, b, mid, m, v in zip(got["brand_id"], got["brand"],
                                     got["manufact_id"], got["manufact"],
                                     got["ext_price"])
    }
    if len(exp) <= 100:
        assert rows == exp
    else:
        assert set(rows.items()) <= set(exp.items())
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


# ----------------------------------------------- q34/q73 ticket-count reports

def _ticket_plan(st, dom_pred, buy_potentials, cnt_lo, cnt_hi, ratio, orders):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(dom_pred,
                 in_(a("d_year"), 1999, 2000, 2001, dtype="integer")),
            F.scan("date_dim", [a("d_date_sk"), a("d_dom"), a("d_year")]),
        ),
    )
    bp = in_(a("hd_buy_potential"), *buy_potentials)
    ratio_e = F.binop(
        "GreaterThan",
        F.binop("Divide", F.cast(a("hd_dep_count"), "double"),
                F.cast(a("hd_vehicle_count"), "double")),
        F.lit(ratio, "double"),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(
            and_(bp, F.binop("GreaterThan", a("hd_vehicle_count"), i32(0)),
                 ratio_e),
            F.scan("household_demographics",
                   [a("hd_demo_sk"), a("hd_buy_potential"), a("hd_dep_count"),
                    a("hd_vehicle_count")]),
        ),
    )
    st_ = F.project(
        [a("s_store_sk")],
        F.filter_(
            in_(a("s_county"), "Williamson County", "Franklin Parish",
                "Bronx County", "Orange County"),
            F.scan("store", [a("s_store_sk"), a("s_county")]),
        ),
    )
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_hdemo_sk"),
                                a("ss_store_sk"), a("ss_ticket_number"),
                                a("ss_customer_sk")])
    j = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(st, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(st, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    agg = two_stage(
        [a("ss_ticket_number"), a("ss_customer_sk")],
        [(F.count(), 501)],
        j,
    )
    cnt = ar("cnt", 501, "long")
    having = F.filter_(
        and_(F.binop("GreaterThanOrEqual", cnt, F.lit(cnt_lo, "long")),
             F.binop("LessThanOrEqual", cnt, F.lit(cnt_hi, "long"))),
        agg,
    )
    cust = F.scan("customer", [a("c_customer_sk"), a("c_salutation"),
                               a("c_first_name"), a("c_last_name"),
                               a("c_preferred_cust_flag")])
    j2 = join(st, cust, having, [a("c_customer_sk")], [a("ss_customer_sk")])
    proj = [a("c_salutation"), a("c_first_name"), a("c_last_name"),
            a("c_preferred_cust_flag"), a("ss_ticket_number"),
            a("ss_customer_sk"), F.alias(cnt, "cnt", 510)]
    single = F.shuffle(F.single_partition(), F.project(proj, j2))
    return F.sort(orders, single)


def test_spark_q34(ticket_sess, ticket_data, strategy):
    dom = or_(
        and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(1)),
             F.binop("LessThanOrEqual", a("d_dom"), i32(3))),
        and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(25)),
             F.binop("LessThanOrEqual", a("d_dom"), i32(28))),
    )
    plan = _ticket_plan(
        strategy, dom, (">10000", "Unknown"), 15, 20, 1.2,
        [F.sort_order(a("c_last_name")), F.sort_order(a("c_first_name")),
         F.sort_order(a("c_salutation")),
         F.sort_order(a("c_preferred_cust_flag"), asc=False),
         F.sort_order(a("ss_ticket_number"))],
    )
    got = _execute_both(ticket_sess, plan)
    _check_ticket_report(got, O.oracle_q34(ticket_data))


def test_spark_q73(ticket_sess, ticket_data, strategy):
    dom = and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(1)),
               F.binop("LessThanOrEqual", a("d_dom"), i32(2)))
    plan = _ticket_plan(
        strategy, dom, (">10000", "Unknown"), 1, 5, 1.0,
        [F.sort_order(ar("cnt", 510, "long"), asc=False),
         F.sort_order(a("c_last_name"))],
    )
    got = _execute_both(ticket_sess, plan)
    _check_ticket_report(got, O.oracle_q73(ticket_data))


@pytest.fixture(scope="module")
def ticket_data():
    return generate_all(0.01)


@pytest.fixture(scope="module")
def ticket_sess(ticket_data):
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCDS_SCHEMAS:
        sess.register_table(
            name,
            MemoryScanExec(
                table_to_batches(ticket_data[name], TPCDS_SCHEMAS[name],
                                 N_PARTS, batch_rows=4096),
                TPCDS_SCHEMAS[name],
            ),
        )
    return sess


# --------------------------------------------------------- q43 dow pivot

_DOW = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")


def test_spark_q43(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk"), a("d_dow")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_dow"), a("d_year")])),
    )
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_store_sk"),
                                a("ss_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    pivots = [
        F.alias(
            F.T(F.X + "CaseWhen",
                [F.binop("EqualTo", a("d_dow"), i32(k)), a("ss_sales_price")]),
            f"{nm}_v", 520 + k)
        for k, nm in enumerate(_DOW)
    ]
    proj = F.project([a("s_store_name")] + pivots, j)
    agg = two_stage(
        [a("s_store_name")],
        [(F.sum_(ar(f"{nm}_v", 520 + k, "decimal(7,2)")), 501 + k)
         for k, nm in enumerate(_DOW)],
        proj,
    )
    plan = F.take_ordered(
        100, [F.sort_order(a("s_store_name"))],
        [a("s_store_name")]
        + [F.alias(ar(f"{nm}_sales", 501 + k, "decimal(17,2)"),
                   f"{nm}_sales", 540 + k)
           for k, nm in enumerate(_DOW)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q43(data)
    assert exp, "q43 oracle empty"
    assert got["s_store_name"] == sorted(got["s_store_name"])
    for i, nm in enumerate(got["s_store_name"]):
        for k, d in enumerate(_DOW):
            assert (got[f"{d}_sales"][i] or 0) == exp[nm][k], (nm, d)


# ------------------------------------------------------------ q96 count star

def test_spark_q96(sess, data, strategy):
    td = F.project(
        [a("t_time_sk")],
        F.filter_(and_(F.binop("EqualTo", a("t_hour"), i32(20)),
                       F.binop("GreaterThanOrEqual", a("t_minute"), i32(30))),
                  F.scan("time_dim", [a("t_time_sk"), a("t_hour"),
                                      a("t_minute")])),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(F.binop("EqualTo", a("hd_dep_count"), i32(7)),
                  F.scan("household_demographics",
                         [a("hd_demo_sk"), a("hd_dep_count")])),
    )
    st_ = F.project(
        [a("s_store_sk")],
        F.filter_(F.binop("EqualTo", a("s_store_name"), s("ese")),
                  F.scan("store", [a("s_store_sk"), a("s_store_name")])),
    )
    sl = F.scan("store_sales", [a("ss_sold_time_sk"), a("ss_hdemo_sk"),
                                a("ss_store_sk")])
    j = join(strategy, td, sl, [a("t_time_sk")], [a("ss_sold_time_sk")])
    j = join(strategy, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    plan = two_stage([], [(F.count(), 501)], j,
                     result=[F.alias(ar("cnt", 501, "long"), "cnt", 510)])
    got = _execute_both(sess, plan)
    assert got["cnt"] == [O.oracle_q96(data)]


# ---------------------------------------------------- q62/q99 ship-lag pivot

_LAG = ("d30", "d60", "d90", "d120", "dmore")


def _ship_lag_plan(st, fact, sold_c, ship_c, wh_c, sm_c, dim_tab, dim_sk,
                   dim_name, dim_fk):
    dt = F.project(
        [a("d_date_sk"), a("d_date")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_date"),
                                      a("d_year")])),
    )
    d2sk = ar("d_date_sk", 601, "long")
    d2date = ar("d_date", 602, "date")
    d2 = F.project(
        [F.alias(d2sk, "d2_sk", 603), F.alias(d2date, "ship_date", 604)],
        F.scan("date_dim", [d2sk, d2date]),
    )
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_warehouse_name")])
    sm = F.scan("ship_mode", [a("sm_ship_mode_sk"), a("sm_type")])
    dim = F.scan(dim_tab, [a(dim_sk), a(dim_name)])
    sl = F.scan(fact, [a(sold_c), a(ship_c), a(wh_c), a(sm_c), a(dim_fk)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(sold_c)])
    j = join(st, d2, j, [ar("d2_sk", 603, "long")], [a(ship_c)])
    j = join(st, wh, j, [a("w_warehouse_sk")], [a(wh_c)])
    j = join(st, sm, j, [a("sm_ship_mode_sk")], [a(sm_c)])
    j = join(st, dim, j, [a(dim_sk)], [a(dim_fk)])
    lag = F.binop("Subtract",
                  F.cast(ar("ship_date", 604, "date"), "long"),
                  F.cast(a("d_date"), "long"))
    base = F.project(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name),
         F.alias(lag, "lag", 610)],
        j,
    )
    lag_a = ar("lag", 610, "long")
    one, zero = F.lit(1, "long"), F.lit(0, "long")

    def le(n):
        return F.binop("LessThanOrEqual", lag_a, F.lit(n, "long"))

    def gt(n):
        return F.binop("GreaterThan", lag_a, F.lit(n, "long"))

    buckets = [
        F.T(F.X + "CaseWhen", [le(30), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(30), le(60)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(60), le(90)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(90), le(120)), one, zero]),
        F.T(F.X + "CaseWhen", [gt(120), one, zero]),
    ]
    proj = F.project(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)]
        + [F.alias(b, nm, 620 + k) for k, (nm, b) in
           enumerate(zip(_LAG, buckets))],
        base,
    )
    agg = two_stage(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)],
        [(F.sum_(ar(nm, 620 + k, "long")), 501 + k)
         for k, nm in enumerate(_LAG)],
        proj,
    )
    return F.take_ordered(
        100,
        [F.sort_order(a("w_warehouse_name")), F.sort_order(a("sm_type")),
         F.sort_order(a(dim_name))],
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)]
        + [F.alias(ar(nm, 501 + k, "long"), nm, 640 + k)
           for k, nm in enumerate(_LAG)],
        agg,
    )


def test_spark_q62(sess, data, strategy):
    got = _execute_both(sess, _ship_lag_plan(
        strategy, "web_sales", "ws_sold_date_sk", "ws_ship_date_sk",
        "ws_warehouse_sk", "ws_ship_mode_sk", "web_site", "web_site_sk",
        "web_name", "ws_web_site_sk"))
    _check_ship_lag(got, O.oracle_q62(data), "web_name")


def test_spark_q99(sess, data, strategy):
    got = _execute_both(sess, _ship_lag_plan(
        strategy, "catalog_sales", "cs_sold_date_sk", "cs_ship_date_sk",
        "cs_warehouse_sk", "cs_ship_mode_sk", "call_center",
        "cc_call_center_sk", "cc_name", "cs_call_center_sk"))
    _check_ship_lag(got, O.oracle_q99(data), "cc_name")
