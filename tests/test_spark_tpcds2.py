"""TPC-DS differentials through full Spark conversion — round-5 widening.

Extends test_spark_tpcds.py's 13-query slice toward the reference
gate's breadth (``tpcds-reusable.yml:83-143``): every test here
authors a catalyst ``toJSON`` physical-plan dump, crosses strategy +
expression conversion, executes via BOTH the in-process collect path
and the stage scheduler (TaskDefinition protobuf bytes + shuffle
files), and validates against the independent numpy oracles.

Dual-shape: each join-bearing plan is parametrized over the broadcast
shape AND the forced sort-merge shape (``SortMergeJoinExec`` over
sorted shuffles) — the reference CI runs every query twice, with
broadcast joins and with ``autoBroadcastJoinThreshold=-1``
(``tpcds-reusable.yml:123-143``).
"""

import json

import pytest

from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.tpcds import TPCDS_SCHEMAS
from blaze_tpu.tpcds import oracle as O
from blaze_tpu.tpcds.datagen import generate_all
from blaze_tpu.tpch.datagen import table_to_batches

import spark_fixtures as F
from test_spark_tpcds import (
    N_PARTS,
    a,
    and_,
    ar,
    in_,
    i32,
    ne,
    or_,
    s,
    distinct,
    two_stage,
)
from test_tpcds import (
    _check_brand_report,
    _check_class_share,
    _check_demo_avgs,
    _check_inv_price,
    _check_ship_lag,
    _check_ticket_report,
)

pytestmark = pytest.mark.slow

SCALE = 0.002

_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _clear_caches_every_few_tests():
    """Same jaxlib compiled-program ceiling mitigation as
    test_tpcds.py — this module's dual-shape matrix compiles a lot of
    distinct programs."""
    yield
    _SINCE_CLEAR["n"] += 1
    if _SINCE_CLEAR["n"] % 8 == 0:
        import jax

        from blaze_tpu.ops.joins.broadcast import clear_join_map_cache
        from blaze_tpu.runtime.kernel_cache import clear_kernel_cache

        clear_kernel_cache()
        clear_join_map_cache()
        jax.clear_caches()


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def sess(data):
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCDS_SCHEMAS:
        sess.register_table(
            name,
            MemoryScanExec(
                table_to_batches(data[name], TPCDS_SCHEMAS[name], N_PARTS,
                                 batch_rows=4096),
                TPCDS_SCHEMAS[name],
            ),
        )
    return sess


@pytest.fixture(params=["bhj", "smj"])
def strategy(request):
    return request.param


def _ss(keys, child):
    """Sorted shuffle: the child of each forced-SMJ side."""
    return F.sort([F.sort_order(k) for k in keys],
                  F.shuffle(F.hash_partitioning(keys, N_PARTS), child),
                  global_=False)


def join(strategy, build, probe, bkeys, pkeys, jt="Inner",
         build_side="left", condition=None):
    """Strategy-parameterized equi-join: BroadcastHashJoin with the
    dimension side broadcast, or the forced-SMJ shape
    (SortMergeJoin over sorted hash shuffles) that the reference CI's
    autoBroadcastJoinThreshold=-1 run plans."""
    if strategy == "bhj":
        if build_side == "left":
            return F.bhj(bkeys, pkeys, jt, "left", F.broadcast(build),
                         probe, condition=condition)
        return F.bhj(pkeys, bkeys, jt, "right", probe, F.broadcast(build),
                     condition=condition)
    if build_side == "left":
        return F.smj(bkeys, pkeys, jt, _ss(bkeys, build), _ss(pkeys, probe),
                     condition=condition)
    return F.smj(pkeys, bkeys, jt, _ss(pkeys, probe), _ss(bkeys, build),
                 condition=condition)


def _execute_both(sess, plan):
    js = json.dumps(F.flatten(plan))
    got = sess.execute(js)
    got_sched = sess.execute_distributed(js)
    rows = sorted(
        zip(*got.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got else []
    rows_sched = sorted(
        zip(*got_sched.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got_sched else []
    assert rows == rows_sched, "in-process vs scheduler mismatch"
    return got


# ------------------------------------------------ q7/q26 demographic averages

def _demo_avg_plan(st, fact, cdemo_c, date_c, promo_c, item_c, qty_c,
                   list_c, coupon_c, sales_c):
    cd = F.project(
        [a("cd_demo_sk")],
        F.filter_(
            and_(F.binop("EqualTo", a("cd_gender"), s("M")),
                 F.binop("EqualTo", a("cd_marital_status"), s("S")),
                 F.binop("EqualTo", a("cd_education_status"), s("College"))),
            F.scan("customer_demographics",
                   [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
                    a("cd_education_status")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    pr = F.project(
        [a("p_promo_sk")],
        F.filter_(
            or_(F.binop("EqualTo", a("p_channel_email"), s("N")),
                F.binop("EqualTo", a("p_channel_event"), s("N"))),
            F.scan("promotion", [a("p_promo_sk"), a("p_channel_email"),
                                 a("p_channel_event")]),
        ),
    )
    sl = F.scan(fact, [a(cdemo_c), a(date_c), a(promo_c), a(item_c),
                       a(qty_c), a(list_c), a(coupon_c), a(sales_c)])
    j = join(st, cd, sl, [a("cd_demo_sk")], [a(cdemo_c)])
    j = join(st, dt, j, [a("d_date_sk")], [a(date_c)])
    j = join(st, pr, j, [a("p_promo_sk")], [a(promo_c)])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
    agg = two_stage(
        [a("i_item_id")],
        [(F.avg(a(qty_c)), 501), (F.avg(a(list_c)), 502),
         (F.avg(a(coupon_c)), 503), (F.avg(a(sales_c)), 504)],
        j,
    )
    return F.take_ordered(
        100, [F.sort_order(a("i_item_id"))],
        [a("i_item_id"),
         F.alias(ar("agg1", 501, "double"), "agg1", 511),
         F.alias(ar("agg2", 502, "decimal(11,6)"), "agg2", 512),
         F.alias(ar("agg3", 503, "decimal(11,6)"), "agg3", 513),
         F.alias(ar("agg4", 504, "decimal(11,6)"), "agg4", 514)],
        agg,
    )


def test_spark_q7(sess, data, strategy):
    got = _execute_both(sess, _demo_avg_plan(
        strategy, "store_sales", "ss_cdemo_sk", "ss_sold_date_sk",
        "ss_promo_sk", "ss_item_sk", "ss_quantity", "ss_list_price",
        "ss_coupon_amt", "ss_sales_price"))
    _check_demo_avgs(got, O.oracle_q7(data))


def test_spark_q26(sess, data, strategy):
    got = _execute_both(sess, _demo_avg_plan(
        strategy, "catalog_sales", "cs_bill_cdemo_sk", "cs_sold_date_sk",
        "cs_promo_sk", "cs_item_sk", "cs_quantity", "cs_list_price",
        "cs_coupon_amt", "cs_sales_price"))
    _check_demo_avgs(got, O.oracle_q26(data))


# ------------------------------------------- q19 star + non-equi zip residual

def test_spark_q19(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_moy"), i32(11)),
                       F.binop("EqualTo", a("d_year"), i32(1998))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_moy"), a("d_year")])),
    )
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_brand"), a("i_manufact_id"),
         a("i_manufact")],
        F.filter_(F.binop("EqualTo", a("i_manager_id"), i32(8)),
                  F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_brand"),
                                  a("i_manufact_id"), a("i_manufact"),
                                  a("i_manager_id")])),
    )
    cust = F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk")])
    addr = F.scan("customer_address", [a("ca_address_sk"), a("ca_zip")])
    st_ = F.scan("store", [a("s_store_sk"), a("s_zip")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"),
                                a("ss_customer_sk"), a("ss_store_sk"),
                                a("ss_ext_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    j = join(strategy, cust, j, [a("c_customer_sk")], [a("ss_customer_sk")])
    j = join(strategy, addr, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    sub = lambda c: F.T(F.X + "Substring", [c, i32(1), i32(5)])
    j = F.filter_(ne(sub(a("ca_zip")), sub(a("s_zip"))), j)
    agg = two_stage(
        [a("i_brand_id"), a("i_brand"), a("i_manufact_id"), a("i_manufact")],
        [(F.sum_(a("ss_ext_sales_price")), 501)],
        j,
    )
    price = ar("ext_price", 501, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(price, asc=False), F.sort_order(a("i_brand")),
         F.sort_order(a("i_brand_id")), F.sort_order(a("i_manufact_id")),
         F.sort_order(a("i_manufact"))],
        [F.alias(a("i_brand_id"), "brand_id", 510),
         F.alias(a("i_brand"), "brand", 511),
         F.alias(a("i_manufact_id"), "manufact_id", 512),
         F.alias(a("i_manufact"), "manufact", 513),
         F.alias(price, "ext_price", 514)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q19(data)
    assert exp, "q19 oracle empty"
    rows = {
        (bid, b, mid, m): v
        for bid, b, mid, m, v in zip(got["brand_id"], got["brand"],
                                     got["manufact_id"], got["manufact"],
                                     got["ext_price"])
    }
    if len(exp) <= 100:
        assert rows == exp
    else:
        assert set(rows.items()) <= set(exp.items())
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


# ----------------------------------------------- q34/q73 ticket-count reports

def _ticket_plan(st, dom_pred, buy_potentials, cnt_lo, cnt_hi, ratio, orders):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(dom_pred,
                 in_(a("d_year"), 1999, 2000, 2001, dtype="integer")),
            F.scan("date_dim", [a("d_date_sk"), a("d_dom"), a("d_year")]),
        ),
    )
    bp = in_(a("hd_buy_potential"), *buy_potentials)
    ratio_e = F.binop(
        "GreaterThan",
        F.binop("Divide", F.cast(a("hd_dep_count"), "double"),
                F.cast(a("hd_vehicle_count"), "double")),
        F.lit(ratio, "double"),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(
            and_(bp, F.binop("GreaterThan", a("hd_vehicle_count"), i32(0)),
                 ratio_e),
            F.scan("household_demographics",
                   [a("hd_demo_sk"), a("hd_buy_potential"), a("hd_dep_count"),
                    a("hd_vehicle_count")]),
        ),
    )
    st_ = F.project(
        [a("s_store_sk")],
        F.filter_(
            in_(a("s_county"), "Williamson County", "Franklin Parish",
                "Bronx County", "Orange County"),
            F.scan("store", [a("s_store_sk"), a("s_county")]),
        ),
    )
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_hdemo_sk"),
                                a("ss_store_sk"), a("ss_ticket_number"),
                                a("ss_customer_sk")])
    j = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(st, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(st, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    agg = two_stage(
        [a("ss_ticket_number"), a("ss_customer_sk")],
        [(F.count(), 501)],
        j,
    )
    cnt = ar("cnt", 501, "long")
    having = F.filter_(
        and_(F.binop("GreaterThanOrEqual", cnt, F.lit(cnt_lo, "long")),
             F.binop("LessThanOrEqual", cnt, F.lit(cnt_hi, "long"))),
        agg,
    )
    cust = F.scan("customer", [a("c_customer_sk"), a("c_salutation"),
                               a("c_first_name"), a("c_last_name"),
                               a("c_preferred_cust_flag")])
    j2 = join(st, cust, having, [a("c_customer_sk")], [a("ss_customer_sk")])
    proj = [a("c_salutation"), a("c_first_name"), a("c_last_name"),
            a("c_preferred_cust_flag"), a("ss_ticket_number"),
            a("ss_customer_sk"), F.alias(cnt, "cnt", 510)]
    single = F.shuffle(F.single_partition(), F.project(proj, j2))
    return F.sort(orders, single)


def test_spark_q34(ticket_sess, ticket_data, strategy):
    dom = or_(
        and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(1)),
             F.binop("LessThanOrEqual", a("d_dom"), i32(3))),
        and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(25)),
             F.binop("LessThanOrEqual", a("d_dom"), i32(28))),
    )
    plan = _ticket_plan(
        strategy, dom, (">10000", "Unknown"), 15, 20, 1.2,
        [F.sort_order(a("c_last_name")), F.sort_order(a("c_first_name")),
         F.sort_order(a("c_salutation")),
         F.sort_order(a("c_preferred_cust_flag"), asc=False),
         F.sort_order(a("ss_ticket_number"))],
    )
    got = _execute_both(ticket_sess, plan)
    _check_ticket_report(got, O.oracle_q34(ticket_data))


def test_spark_q73(ticket_sess, ticket_data, strategy):
    dom = and_(F.binop("GreaterThanOrEqual", a("d_dom"), i32(1)),
               F.binop("LessThanOrEqual", a("d_dom"), i32(2)))
    plan = _ticket_plan(
        strategy, dom, (">10000", "Unknown"), 1, 5, 1.0,
        [F.sort_order(ar("cnt", 510, "long"), asc=False),
         F.sort_order(a("c_last_name"))],
    )
    got = _execute_both(ticket_sess, plan)
    _check_ticket_report(got, O.oracle_q73(ticket_data))


@pytest.fixture(scope="module")
def ticket_data():
    return generate_all(0.01)


@pytest.fixture(scope="module")
def ticket_sess(ticket_data):
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCDS_SCHEMAS:
        sess.register_table(
            name,
            MemoryScanExec(
                table_to_batches(ticket_data[name], TPCDS_SCHEMAS[name],
                                 N_PARTS, batch_rows=4096),
                TPCDS_SCHEMAS[name],
            ),
        )
    return sess


# --------------------------------------------------------- q43 dow pivot

_DOW = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")


def test_spark_q43(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk"), a("d_dow")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_dow"), a("d_year")])),
    )
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_store_sk"),
                                a("ss_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    pivots = [
        F.alias(
            F.T(F.X + "CaseWhen",
                [F.binop("EqualTo", a("d_dow"), i32(k)), a("ss_sales_price")]),
            f"{nm}_v", 520 + k)
        for k, nm in enumerate(_DOW)
    ]
    proj = F.project([a("s_store_name")] + pivots, j)
    agg = two_stage(
        [a("s_store_name")],
        [(F.sum_(ar(f"{nm}_v", 520 + k, "decimal(7,2)")), 501 + k)
         for k, nm in enumerate(_DOW)],
        proj,
    )
    plan = F.take_ordered(
        100, [F.sort_order(a("s_store_name"))],
        [a("s_store_name")]
        + [F.alias(ar(f"{nm}_sales", 501 + k, "decimal(17,2)"),
                   f"{nm}_sales", 540 + k)
           for k, nm in enumerate(_DOW)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q43(data)
    assert exp, "q43 oracle empty"
    assert got["s_store_name"] == sorted(got["s_store_name"])
    for i, nm in enumerate(got["s_store_name"]):
        for k, d in enumerate(_DOW):
            assert (got[f"{d}_sales"][i] or 0) == exp[nm][k], (nm, d)


# ------------------------------------------------------------ q96 count star

def test_spark_q96(sess, data, strategy):
    td = F.project(
        [a("t_time_sk")],
        F.filter_(and_(F.binop("EqualTo", a("t_hour"), i32(20)),
                       F.binop("GreaterThanOrEqual", a("t_minute"), i32(30))),
                  F.scan("time_dim", [a("t_time_sk"), a("t_hour"),
                                      a("t_minute")])),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(F.binop("EqualTo", a("hd_dep_count"), i32(7)),
                  F.scan("household_demographics",
                         [a("hd_demo_sk"), a("hd_dep_count")])),
    )
    st_ = F.project(
        [a("s_store_sk")],
        F.filter_(F.binop("EqualTo", a("s_store_name"), s("ese")),
                  F.scan("store", [a("s_store_sk"), a("s_store_name")])),
    )
    sl = F.scan("store_sales", [a("ss_sold_time_sk"), a("ss_hdemo_sk"),
                                a("ss_store_sk")])
    j = join(strategy, td, sl, [a("t_time_sk")], [a("ss_sold_time_sk")])
    j = join(strategy, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    plan = two_stage([], [(F.count(), 501)], j,
                     result=[F.alias(ar("cnt", 501, "long"), "cnt", 510)])
    got = _execute_both(sess, plan)
    assert got["cnt"] == [O.oracle_q96(data)]


# ---------------------------------------------------- q62/q99 ship-lag pivot

_LAG = ("d30", "d60", "d90", "d120", "dmore")


def _ship_lag_plan(st, fact, sold_c, ship_c, wh_c, sm_c, dim_tab, dim_sk,
                   dim_name, dim_fk):
    dt = F.project(
        [a("d_date_sk"), a("d_date")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_date"),
                                      a("d_year")])),
    )
    d2sk = ar("d_date_sk", 601, "long")
    d2date = ar("d_date", 602, "date")
    d2 = F.project(
        [F.alias(d2sk, "d2_sk", 603), F.alias(d2date, "ship_date", 604)],
        F.scan("date_dim", [d2sk, d2date]),
    )
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_warehouse_name")])
    sm = F.scan("ship_mode", [a("sm_ship_mode_sk"), a("sm_type")])
    dim = F.scan(dim_tab, [a(dim_sk), a(dim_name)])
    sl = F.scan(fact, [a(sold_c), a(ship_c), a(wh_c), a(sm_c), a(dim_fk)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(sold_c)])
    j = join(st, d2, j, [ar("d2_sk", 603, "long")], [a(ship_c)])
    j = join(st, wh, j, [a("w_warehouse_sk")], [a(wh_c)])
    j = join(st, sm, j, [a("sm_ship_mode_sk")], [a(sm_c)])
    j = join(st, dim, j, [a(dim_sk)], [a(dim_fk)])
    lag = F.binop("Subtract",
                  F.cast(ar("ship_date", 604, "date"), "long"),
                  F.cast(a("d_date"), "long"))
    base = F.project(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name),
         F.alias(lag, "lag", 610)],
        j,
    )
    lag_a = ar("lag", 610, "long")
    one, zero = F.lit(1, "long"), F.lit(0, "long")

    def le(n):
        return F.binop("LessThanOrEqual", lag_a, F.lit(n, "long"))

    def gt(n):
        return F.binop("GreaterThan", lag_a, F.lit(n, "long"))

    buckets = [
        F.T(F.X + "CaseWhen", [le(30), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(30), le(60)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(60), le(90)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(90), le(120)), one, zero]),
        F.T(F.X + "CaseWhen", [gt(120), one, zero]),
    ]
    proj = F.project(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)]
        + [F.alias(b, nm, 620 + k) for k, (nm, b) in
           enumerate(zip(_LAG, buckets))],
        base,
    )
    agg = two_stage(
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)],
        [(F.sum_(ar(nm, 620 + k, "long")), 501 + k)
         for k, nm in enumerate(_LAG)],
        proj,
    )
    return F.take_ordered(
        100,
        [F.sort_order(a("w_warehouse_name")), F.sort_order(a("sm_type")),
         F.sort_order(a(dim_name))],
        [a("w_warehouse_name"), a("sm_type"), a(dim_name)]
        + [F.alias(ar(nm, 501 + k, "long"), nm, 640 + k)
           for k, nm in enumerate(_LAG)],
        agg,
    )


def test_spark_q62(sess, data, strategy):
    got = _execute_both(sess, _ship_lag_plan(
        strategy, "web_sales", "ws_sold_date_sk", "ws_ship_date_sk",
        "ws_warehouse_sk", "ws_ship_mode_sk", "web_site", "web_site_sk",
        "web_name", "ws_web_site_sk"))
    _check_ship_lag(got, O.oracle_q62(data), "web_name")


def test_spark_q99(sess, data, strategy):
    got = _execute_both(sess, _ship_lag_plan(
        strategy, "catalog_sales", "cs_sold_date_sk", "cs_ship_date_sk",
        "cs_warehouse_sk", "cs_ship_mode_sk", "call_center",
        "cc_call_center_sk", "cc_name", "cs_call_center_sk"))
    _check_ship_lag(got, O.oracle_q99(data), "cc_name")


# ------------------------------------- big-side joins (SHJ under bhj variant)

def big_join(strategy, left, right, lk, rk, jt="Inner", build_side="right",
             condition=None):
    """Fact-fact join: ShuffledHashJoin in the broadcast variant (the
    reference plans large-large equi-joins off the broadcast path too),
    SortMergeJoin in the forced-SMJ variant."""
    if strategy == "bhj":
        return F.shj(
            lk, rk, jt, build_side,
            F.shuffle(F.hash_partitioning(lk, N_PARTS), left),
            F.shuffle(F.hash_partitioning(rk, N_PARTS), right),
            condition=condition)
    return F.smj(lk, rk, jt, _ss(lk, left), _ss(rk, right),
                 condition=condition)


# ----------------------------------------------- q25/q29 provenance chain

def _srcandc_join_plan(st):
    d1 = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    d2sk, d2y = ar("d_date_sk", 601, "long"), ar("d_year", 602, "integer")
    d2 = F.project(
        [F.alias(d2sk, "d2_sk", 603)],
        F.filter_(and_(F.binop("GreaterThanOrEqual", d2y, i32(2000)),
                       F.binop("LessThanOrEqual", d2y, i32(2002))),
                  F.scan("date_dim", [d2sk, d2y])),
    )
    d3sk, d3y = ar("d_date_sk", 605, "long"), ar("d_year", 606, "integer")
    d3 = F.project(
        [F.alias(d3sk, "d3_sk", 607)],
        F.filter_(and_(F.binop("GreaterThanOrEqual", d3y, i32(2000)),
                       F.binop("LessThanOrEqual", d3y, i32(2002))),
                  F.scan("date_dim", [d3sk, d3y])),
    )
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_ticket_number"),
                 a("ss_customer_sk"), a("ss_store_sk"), a("ss_net_profit"),
                 a("ss_quantity")])
    j = join(st, d1, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    sr = F.scan("store_returns",
                [a("sr_item_sk"), a("sr_ticket_number"), a("sr_customer_sk"),
                 a("sr_returned_date_sk"), a("sr_net_loss"),
                 a("sr_return_quantity")])
    j = big_join(st, j, sr, [a("ss_item_sk"), a("ss_ticket_number")],
                 [a("sr_item_sk"), a("sr_ticket_number")])
    j = join(st, d2, j, [ar("d2_sk", 603, "long")], [a("sr_returned_date_sk")])
    cs = F.scan("catalog_sales",
                [a("cs_sold_date_sk"), a("cs_bill_customer_sk"),
                 a("cs_item_sk"), a("cs_net_profit"), a("cs_quantity")])
    j = big_join(st, j, cs, [a("sr_customer_sk"), a("sr_item_sk")],
                 [a("cs_bill_customer_sk"), a("cs_item_sk")],
                 build_side="left")
    j = join(st, d3, j, [ar("d3_sk", 607, "long")], [a("cs_sold_date_sk")])
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    j = join(st, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_item_desc")])
    j = join(st, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    return j


def _srcandc_plan(st, sums, sum_names, sum_dtype, cast_long):
    j = _srcandc_join_plan(st)
    sum_in = [F.cast(a(c), "long") if cast_long else a(c) for c in sums]
    agg = two_stage(
        [a("i_item_id"), a("i_item_desc"), a("s_store_name")],
        [(F.sum_(e), 501 + k) for k, e in enumerate(sum_in)],
        j,
    )
    return F.take_ordered(
        100,
        [F.sort_order(a("i_item_id")), F.sort_order(a("i_item_desc")),
         F.sort_order(a("s_store_name"))],
        [a("i_item_id"), a("i_item_desc"), a("s_store_name")]
        + [F.alias(ar(nm, 501 + k, sum_dtype), nm, 510 + k)
           for k, nm in enumerate(sum_names)],
        agg,
    )


def test_spark_q25(sess, data, strategy):
    got = _execute_both(sess, _srcandc_plan(
        strategy, ("ss_net_profit", "sr_net_loss", "cs_net_profit"),
        ("store_sales_profit", "store_returns_loss", "catalog_sales_profit"),
        "decimal(17,2)", cast_long=False))
    from test_tpcds import _check_srcandc
    _check_srcandc(got, O.oracle_q25(data),
                   ["store_sales_profit", "store_returns_loss",
                    "catalog_sales_profit"])


def test_spark_q29(sess, data, strategy):
    got = _execute_both(sess, _srcandc_plan(
        strategy, ("ss_quantity", "sr_return_quantity", "cs_quantity"),
        ("store_sales_quantity", "store_returns_quantity",
         "catalog_sales_quantity"),
        "long", cast_long=True))
    from test_tpcds import _check_srcandc
    _check_srcandc(got, O.oracle_q29(data),
                   ["store_sales_quantity", "store_returns_quantity",
                    "catalog_sales_quantity"])


# ----------------------------------------------- q46/q68 city ticket reports

def _city_ticket_plan(st, hd_pred, amt_c, extra_c, extra_out):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(in_(a("d_dow"), 6, 0, dtype="integer"),
                  F.scan("date_dim", [a("d_date_sk"), a("d_dow")])),
    )
    st_ = F.project(
        [a("s_store_sk")],
        F.filter_(in_(a("s_city"), "Midway", "Fairview"),
                  F.scan("store", [a("s_store_sk"), a("s_city")])),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(hd_pred,
                  F.scan("household_demographics",
                         [a("hd_demo_sk"), a("hd_dep_count"),
                          a("hd_vehicle_count")])),
    )
    ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_city")])
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_hdemo_sk"),
                 a("ss_addr_sk"), a("ss_ticket_number"), a("ss_customer_sk"),
                 a(amt_c), a(extra_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(st, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    j = join(st, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(st, ca, j, [a("ca_address_sk")], [a("ss_addr_sk")])
    bought = ar("bought_city", 615, "string")
    proj = F.project(
        [a("ss_ticket_number"), a("ss_customer_sk"),
         F.alias(a("ca_city"), "bought_city", 615), a(amt_c), a(extra_c)],
        j,
    )
    agg = two_stage(
        [a("ss_ticket_number"), a("ss_customer_sk"), bought],
        [(F.sum_(a(amt_c)), 501), (F.sum_(a(extra_c)), 502)],
        proj,
    )
    cu = F.scan("customer", [a("c_customer_sk"), a("c_last_name"),
                             a("c_first_name"), a("c_current_addr_sk")])
    j2 = join(st, cu, agg, [a("c_customer_sk")], [a("ss_customer_sk")])
    ca2sk, ca2city = ar("ca_address_sk", 611, "long"), ar("ca_city", 612, "string")
    ca2 = F.project(
        [F.alias(ca2sk, "cur_addr_sk", 613),
         F.alias(ca2city, "current_city", 614)],
        F.scan("customer_address", [ca2sk, ca2city]),
    )
    cur_city = ar("current_city", 614, "string")
    j2 = join(st, ca2, j2, [ar("cur_addr_sk", 613, "long")],
              [a("c_current_addr_sk")])
    f = F.filter_(ne(cur_city, bought), j2)
    amt = ar("amt", 501, "decimal(17,2)")
    extra = ar("extra", 502, "decimal(17,2)")
    return F.take_ordered(
        100,
        [F.sort_order(a("c_last_name")), F.sort_order(a("c_first_name")),
         F.sort_order(cur_city), F.sort_order(bought),
         F.sort_order(a("ss_ticket_number"))],
        [a("c_last_name"), a("c_first_name"), cur_city, bought,
         a("ss_ticket_number"), F.alias(amt, "amt", 520),
         F.alias(extra, extra_out, 521)],
        f,
    )


def test_spark_q46(sess, data, strategy):
    from test_tpcds import _check_city_tickets
    hd_pred = or_(F.binop("EqualTo", a("hd_dep_count"), i32(4)),
                  F.binop("EqualTo", a("hd_vehicle_count"), i32(3)))
    got = _execute_both(sess, _city_ticket_plan(
        strategy, hd_pred, "ss_coupon_amt", "ss_net_profit",
        "sum_ss_net_profit"))
    _check_city_tickets(got, O.oracle_q46(data), ["amt", "sum_ss_net_profit"])


def test_spark_q68(sess, data, strategy):
    from test_tpcds import _check_city_tickets
    hd_pred = or_(F.binop("EqualTo", a("hd_dep_count"), i32(5)),
                  F.binop("EqualTo", a("hd_vehicle_count"), i32(3)))
    got = _execute_both(sess, _city_ticket_plan(
        strategy, hd_pred, "ss_ext_sales_price", "ss_ext_list_price",
        "sum_ss_ext_list_price"))
    _check_city_tickets(got, O.oracle_q68(data),
                        ["amt", "sum_ss_ext_list_price"])


# --------------------------------------------------- q79 Monday big-household

def test_spark_q79(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_dow"), i32(1)),
                       F.binop("GreaterThanOrEqual", a("d_year"), i32(1998)),
                       F.binop("LessThanOrEqual", a("d_year"), i32(2000))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_dow"), a("d_year")])),
    )
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(or_(F.binop("EqualTo", a("hd_dep_count"), i32(6)),
                      F.binop("GreaterThan", a("hd_vehicle_count"), i32(2))),
                  F.scan("household_demographics",
                         [a("hd_demo_sk"), a("hd_dep_count"),
                          a("hd_vehicle_count")])),
    )
    st_ = F.scan("store", [a("s_store_sk"), a("s_city")])
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_hdemo_sk"), a("ss_store_sk"),
                 a("ss_ticket_number"), a("ss_customer_sk"),
                 a("ss_coupon_amt"), a("ss_net_profit")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    agg = two_stage(
        [a("ss_ticket_number"), a("ss_customer_sk"), a("s_city")],
        [(F.sum_(a("ss_coupon_amt")), 501), (F.sum_(a("ss_net_profit")), 502)],
        j,
    )
    cu = F.scan("customer", [a("c_customer_sk"), a("c_last_name"),
                             a("c_first_name")])
    j2 = join(strategy, cu, agg, [a("c_customer_sk")], [a("ss_customer_sk")])
    amt = ar("amt", 501, "decimal(17,2)")
    profit = ar("profit", 502, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(a("c_last_name")), F.sort_order(a("c_first_name")),
         F.sort_order(a("s_city")), F.sort_order(profit),
         F.sort_order(a("ss_ticket_number"))],
        [a("c_last_name"), a("c_first_name"), a("s_city"),
         a("ss_ticket_number"), F.alias(amt, "amt", 520),
         F.alias(profit, "profit", 521)],
        j2,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q79(data)
    assert exp, "q79 oracle empty"
    n = len(got["ss_ticket_number"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["c_last_name"][i], got["c_first_name"][i],
               got["s_city"][i], got["ss_ticket_number"][i])
        assert key in exp, key
        assert (got["amt"][i], got["profit"][i]) == exp[key], key


# ------------------------------------------------------ q91 call-center loss

def test_spark_q91(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    cr = F.scan("catalog_returns",
                [a("cr_returned_date_sk"), a("cr_returning_customer_sk"),
                 a("cr_call_center_sk"), a("cr_net_loss")])
    j = join(strategy, dt, cr, [a("d_date_sk")], [a("cr_returned_date_sk")])
    cc = F.scan("call_center", [a("cc_call_center_sk"), a("cc_name")])
    j = join(strategy, cc, j, [a("cc_call_center_sk")],
             [a("cr_call_center_sk")])
    cu = F.scan("customer", [a("c_customer_sk"), a("c_current_cdemo_sk")])
    j = join(strategy, cu, j, [a("c_customer_sk")],
             [a("cr_returning_customer_sk")])
    cd = F.project(
        [a("cd_demo_sk"), a("cd_marital_status"), a("cd_education_status")],
        F.filter_(
            or_(and_(F.binop("EqualTo", a("cd_marital_status"), s("M")),
                     F.binop("EqualTo", a("cd_education_status"), s("Unknown"))),
                and_(F.binop("EqualTo", a("cd_marital_status"), s("W")),
                     F.binop("EqualTo", a("cd_education_status"),
                             s("Advanced Degree")))),
            F.scan("customer_demographics",
                   [a("cd_demo_sk"), a("cd_marital_status"),
                    a("cd_education_status")]),
        ),
    )
    j = join(strategy, cd, j, [a("cd_demo_sk")], [a("c_current_cdemo_sk")])
    agg = two_stage(
        [a("cc_name"), a("cd_marital_status"), a("cd_education_status")],
        [(F.sum_(a("cr_net_loss")), 501)],
        j,
    )
    loss = ar("returns_loss", 501, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(loss, asc=False), F.sort_order(a("cc_name"))],
        [a("cc_name"), a("cd_marital_status"), a("cd_education_status"),
         F.alias(loss, "returns_loss", 510)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q91(data)
    assert exp, "q91 oracle empty"
    n = len(got["cc_name"])
    assert n == min(len(exp), 100)
    rows = {
        (got["cc_name"][i], got["cd_marital_status"][i],
         got["cd_education_status"][i]): got["returns_loss"][i]
        for i in range(n)
    }
    if len(exp) <= 100:
        assert rows == exp
    else:
        assert all(exp.get(k) == v for k, v in rows.items())
    assert got["returns_loss"] == sorted(got["returns_loss"], reverse=True)


# ---------------------------------------------- q93 LEFT join + CASE netting

def test_spark_q93(sess, data, strategy):
    sl = F.scan("store_sales",
                [a("ss_item_sk"), a("ss_ticket_number"), a("ss_customer_sk"),
                 a("ss_quantity"), a("ss_sales_price")])
    sr = F.scan("store_returns",
                [a("sr_item_sk"), a("sr_ticket_number"), a("sr_reason_sk"),
                 a("sr_return_quantity")])
    j = big_join(strategy, sl, sr,
                 [a("ss_item_sk"), a("ss_ticket_number")],
                 [a("sr_item_sk"), a("sr_ticket_number")], jt="LeftOuter")
    reason = F.project(
        [a("r_reason_sk")],
        F.filter_(F.binop("EqualTo", a("r_reason_desc"), s("Stopped working")),
                  F.scan("reason", [a("r_reason_sk"), a("r_reason_desc")])),
    )
    j = join(strategy, reason, j, [a("r_reason_sk")], [a("sr_reason_sk")])
    act = F.T(
        F.X + "CaseWhen",
        [F.un("IsNotNull", a("sr_return_quantity")),
         F.binop("Multiply",
                 F.cast(F.binop("Subtract", a("ss_quantity"),
                                a("sr_return_quantity")), "long"),
                 a("ss_sales_price")),
         F.binop("Multiply", F.cast(a("ss_quantity"), "long"),
                 a("ss_sales_price"))],
    )
    proj = F.project(
        [a("ss_customer_sk"), F.alias(act, "act_sales", 520)],
        j,
    )
    agg = two_stage(
        [a("ss_customer_sk")],
        [(F.sum_(ar("act_sales", 520, "decimal(17,2)")), 501)],
        proj,
    )
    sumsales = ar("sumsales", 501, "decimal(27,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(sumsales), F.sort_order(a("ss_customer_sk"))],
        [a("ss_customer_sk"), F.alias(sumsales, "sumsales", 510)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q93(data)
    assert exp, "q93 oracle empty"
    rows = dict(zip(got["ss_customer_sk"], got["sumsales"]))
    assert len(rows) == len(got["ss_customer_sk"])
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["sumsales"] == sorted(got["sumsales"])


# ------------------------------------------------- q97 FULL-outer overlap

def test_spark_q97(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )

    def pairs(fact, date_c, cust_c, item_c, pc, pi, cid, iid):
        sl = F.scan(fact, [a(date_c), a(cust_c), a(item_c)])
        j = join(strategy, dt, sl, [a("d_date_sk")], [a(date_c)])
        proj = F.project(
            [F.alias(a(cust_c), pc, cid), F.alias(a(item_c), pi, iid)], j)
        return two_stage([ar(pc, cid, "long"), ar(pi, iid, "long")], [], proj)

    ss = pairs("store_sales", "ss_sold_date_sk", "ss_customer_sk",
               "ss_item_sk", "sc", "si", 620, 621)
    cs = pairs("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
               "cs_item_sk", "cc", "ci", 622, 623)
    sc, si = ar("sc", 620, "long"), ar("si", 621, "long")
    cc, ci = ar("cc", 622, "long"), ar("ci", 623, "long")
    j = big_join(strategy, ss, cs, [sc, si], [cc, ci], jt="FullOuter")
    one, zero = F.lit(1, "long"), F.lit(0, "long")
    flags = F.project(
        [F.alias(F.T(F.X + "CaseWhen",
                     [and_(F.un("IsNotNull", sc), F.un("IsNull", cc)), one,
                      zero]), "store_only", 630),
         F.alias(F.T(F.X + "CaseWhen",
                     [and_(F.un("IsNull", sc), F.un("IsNotNull", cc)), one,
                      zero]), "catalog_only", 631),
         F.alias(F.T(F.X + "CaseWhen",
                     [and_(F.un("IsNotNull", sc), F.un("IsNotNull", cc)), one,
                      zero]), "store_and_catalog", 632)],
        j,
    )
    plan = two_stage(
        [],
        [(F.sum_(ar("store_only", 630, "long")), 501),
         (F.sum_(ar("catalog_only", 631, "long")), 502),
         (F.sum_(ar("store_and_catalog", 632, "long")), 503)],
        flags,
        result=[F.alias(ar("store_only", 501, "long"), "store_only", 510),
                F.alias(ar("catalog_only", 502, "long"), "catalog_only", 511),
                F.alias(ar("store_and_catalog", 503, "long"),
                        "store_and_catalog", 512)],
    )
    got = _execute_both(sess, plan)
    so, co, both = O.oracle_q97(data)
    assert (got["store_only"], got["catalog_only"],
            got["store_and_catalog"]) == ([so], [co], [both])


# ------------------------------------------------- q65 aggregation over agg

def test_spark_q65(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_item_sk"),
                 a("ss_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    per_item = two_stage(
        [a("ss_store_sk"), a("ss_item_sk")],
        [(F.sum_(a("ss_sales_price")), 501)],
        j,
    )
    revenue = ar("revenue", 501, "decimal(17,2)")
    sb = F.project(
        [F.alias(a("ss_store_sk"), "sb_store_sk", 520), revenue], per_item)
    per_store = two_stage(
        [ar("sb_store_sk", 520, "long")],
        [(F.avg(revenue), 502)],
        sb,
    )
    ave = ar("ave", 502, "decimal(21,6)")
    jj = join(strategy, per_store, per_item,
              [ar("sb_store_sk", 520, "long")], [a("ss_store_sk")])
    low = F.filter_(
        F.binop("LessThanOrEqual", F.cast(revenue, "double"),
                F.binop("Multiply", F.cast(ave, "double"),
                        F.lit(0.1, "double"))),
        jj,
    )
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_desc"),
                         a("i_current_price"), a("i_brand")])
    out = join(strategy, st_, low, [a("s_store_sk")], [a("ss_store_sk")])
    out = join(strategy, it, out, [a("i_item_sk")], [a("ss_item_sk")])
    plan = F.take_ordered(
        100,
        [F.sort_order(a("s_store_name")), F.sort_order(a("i_item_desc"))],
        [a("s_store_name"), a("i_item_desc"),
         F.alias(revenue, "revenue", 530), a("i_current_price"), a("i_brand")],
        out,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q65(data)
    rows = list(zip(got["s_store_name"], got["i_item_desc"], got["revenue"],
                    got["i_current_price"], got["i_brand"]))
    assert rows, "q65 returned no rows"
    import collections
    if len(exp) <= 100:
        assert collections.Counter(rows) == collections.Counter(exp.values())
    else:
        assert not (collections.Counter(rows) - collections.Counter(exp.values()))
    keys = [(r[0], r[1]) for r in rows]
    assert keys == sorted(keys)


# ------------------------------------------------------ q50 return-lag pivot

def test_spark_q50(sess, data, strategy):
    sl = F.scan("store_sales",
                [a("ss_item_sk"), a("ss_ticket_number"), a("ss_customer_sk"),
                 a("ss_store_sk"), a("ss_sold_date_sk")])
    sr = F.scan("store_returns",
                [a("sr_item_sk"), a("sr_ticket_number"), a("sr_customer_sk"),
                 a("sr_returned_date_sk")])
    j = big_join(strategy, sl, sr,
                 [a("ss_item_sk"), a("ss_ticket_number"), a("ss_customer_sk")],
                 [a("sr_item_sk"), a("sr_ticket_number"), a("sr_customer_sk")])
    d1 = F.scan("date_dim", [a("d_date_sk"), a("d_date")])
    d2sk = ar("d_date_sk", 601, "long")
    d2date = ar("d_date", 602, "date")
    d2y, d2m = ar("d_year", 603, "integer"), ar("d_moy", 604, "integer")
    d2 = F.project(
        [F.alias(d2sk, "d2_sk", 605), F.alias(d2date, "ret_date", 606)],
        F.filter_(and_(F.binop("EqualTo", d2y, i32(2001)),
                       F.binop("EqualTo", d2m, i32(8))),
                  F.scan("date_dim", [d2sk, d2date, d2y, d2m])),
    )
    j = join(strategy, d1, j, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, d2, j, [ar("d2_sk", 605, "long")],
             [a("sr_returned_date_sk")])
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name"), a("s_county"),
                           a("s_state"), a("s_zip")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    lag = F.binop("Subtract", F.cast(ar("ret_date", 606, "date"), "long"),
                  F.cast(a("d_date"), "long"))
    base = F.project(
        [a("s_store_name"), a("s_county"), a("s_state"), a("s_zip"),
         F.alias(lag, "lag", 610)],
        j,
    )
    lag_a = ar("lag", 610, "long")
    one, zero = F.lit(1, "long"), F.lit(0, "long")

    def le(n):
        return F.binop("LessThanOrEqual", lag_a, F.lit(n, "long"))

    def gt(n):
        return F.binop("GreaterThan", lag_a, F.lit(n, "long"))

    buckets = [
        F.T(F.X + "CaseWhen", [le(30), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(30), le(60)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(60), le(90)), one, zero]),
        F.T(F.X + "CaseWhen", [and_(gt(90), le(120)), one, zero]),
        F.T(F.X + "CaseWhen", [gt(120), one, zero]),
    ]
    proj = F.project(
        [a("s_store_name"), a("s_county"), a("s_state"), a("s_zip")]
        + [F.alias(b, nm, 620 + k)
           for k, (nm, b) in enumerate(zip(_LAG, buckets))],
        base,
    )
    agg = two_stage(
        [a("s_store_name"), a("s_county"), a("s_state"), a("s_zip")],
        [(F.sum_(ar(nm, 620 + k, "long")), 501 + k)
         for k, nm in enumerate(_LAG)],
        proj,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a("s_store_name")), F.sort_order(a("s_county")),
         F.sort_order(a("s_state")), F.sort_order(a("s_zip"))],
        [a("s_store_name"), a("s_county"), a("s_state"), a("s_zip")]
        + [F.alias(ar(nm, 501 + k, "long"), nm, 640 + k)
           for k, nm in enumerate(_LAG)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q50(data)
    assert exp, "q50 oracle empty"
    n = len(got["s_store_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["s_store_name"][i], got["s_county"][i], got["s_state"][i],
               got["s_zip"][i])
        assert key in exp, key
        assert tuple(got[b][i] for b in _LAG) == exp[key], key


# ------------------------------------------------- q23a/b best-customer CTEs

def _scalar_subquery(subplan, eid):
    return F.T(F.X + "ScalarSubquery", plan=F.flatten(subplan), exprId=F.eid(eid))


def _q23_frequent_items_plan(st):
    """Items sold >4 times in one (year*12+moy) cell, 1998-2002
    (mirrors queries._q23_frequent_items: no year slice)."""
    dt = F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_item_sk")])
    j = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_desc")])
    j = join(st, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    itemdesc = F.T(F.X + "Substring", [a("i_item_desc"), i32(1), i32(30)])
    cell = F.binop("Add", F.binop("Multiply", a("d_year"), i32(12)), a("d_moy"))
    proj = F.project(
        [a("i_item_sk"), F.alias(itemdesc, "itemdesc", 701),
         F.alias(cell, "cell", 702)],
        j,
    )
    cells = two_stage(
        [a("i_item_sk"), ar("itemdesc", 701, "string"),
         ar("cell", 702, "integer")],
        [(F.count(), 703)],
        proj,
    )
    hot = F.filter_(
        F.binop("GreaterThan", ar("cnt", 703, "long"), F.lit(4, "long")),
        cells,
    )
    return two_stage([a("i_item_sk")], [], F.project([a("i_item_sk")], hot))


def _q23_best_customers_plan(st):
    spend = F.binop("Multiply", F.cast(a("ss_quantity"), "long"),
                    a("ss_sales_price"))
    sl = F.project(
        [a("ss_customer_sk"), F.alias(spend, "spend", 710)],
        F.scan("store_sales", [a("ss_customer_sk"), a("ss_quantity"),
                               a("ss_sales_price")]),
    )
    per_cust = two_stage(
        [a("ss_customer_sk")],
        [(F.sum_(ar("spend", 710, "decimal(17,2)")), 711)],
        sl,
    )
    csales = ar("csales", 711, "decimal(27,2)")
    cmax = two_stage([], [(F.max_(csales), 712)], per_cust,
                     result=[F.alias(ar("mx", 712, "decimal(27,2)"),
                                     "tpcds_cmax", 713)])
    best = F.filter_(
        F.binop("GreaterThan", F.cast(csales, "double"),
                F.binop("Multiply", F.lit(0.5, "double"),
                        F.cast(_scalar_subquery(cmax, 714), "double"))),
        per_cust,
    )
    return F.project([a("ss_customer_sk")], best)


def _q23_month_sales_plan(st, fact, date_c, item_c, cust_c, qty_c, price_c,
                          hot, best, names):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(2000)),
                       F.binop("EqualTo", a("d_moy"), i32(5))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")])),
    )
    fc = F.scan(fact, [a(date_c), a(item_c), a(cust_c), a(qty_c), a(price_c)])
    j = join(st, dt, fc, [a("d_date_sk")], [a(date_c)])
    j = join(st, hot, j, [a("i_item_sk")], [a(item_c)], jt="LeftSemi",
             build_side="right")
    j = join(st, best, j, [a("ss_customer_sk")], [a(cust_c)], jt="LeftSemi",
             build_side="right")
    sales = F.binop("Multiply", F.cast(a(qty_c), "long"), a(price_c))
    if names:
        cu = F.scan("customer", [a("c_customer_sk"), a("c_last_name"),
                                 a("c_first_name")])
        j = join(st, cu, j, [a("c_customer_sk")], [a(cust_c)])
        return F.project(
            [a("c_last_name"), a("c_first_name"),
             F.alias(sales, "sales", 720)], j)
    return F.project([F.alias(sales, "sales", 720)], j)


def _q23_rows_plan(st, names):
    hot = _q23_frequent_items_plan(st)
    best = _q23_best_customers_plan(st)
    return F.union([
        _q23_month_sales_plan(st, "catalog_sales", "cs_sold_date_sk",
                              "cs_item_sk", "cs_bill_customer_sk",
                              "cs_quantity", "cs_list_price", hot, best, names),
        _q23_month_sales_plan(st, "web_sales", "ws_sold_date_sk",
                              "ws_item_sk", "ws_bill_customer_sk",
                              "ws_quantity", "ws_list_price", hot, best, names),
    ])


def test_spark_q23a(sess, data, strategy):
    rows = _q23_rows_plan(strategy, names=False)
    plan = two_stage(
        [], [(F.sum_(ar("sales", 720, "decimal(17,2)")), 501)], rows,
        result=[F.alias(ar("sum_sales", 501, "decimal(27,2)"),
                        "sum_sales", 510)],
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q23a(data)
    assert exp is not None, "q23a oracle empty"
    assert got["sum_sales"] == [exp]


def test_spark_q23b(sess, data, strategy):
    rows = _q23_rows_plan(strategy, names=True)
    agg = two_stage(
        [a("c_last_name"), a("c_first_name")],
        [(F.sum_(ar("sales", 720, "decimal(17,2)")), 501)],
        rows,
    )
    sales = ar("sales", 501, "decimal(27,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(sales, asc=False), F.sort_order(a("c_last_name")),
         F.sort_order(a("c_first_name"))],
        [a("c_last_name"), a("c_first_name"), F.alias(sales, "sales", 510)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q23b(data)
    assert exp, "q23b oracle empty"
    rows_g = {
        (l, f): v for l, f, v in
        zip(got["c_last_name"], got["c_first_name"], got["sales"])
    }
    if len(exp) <= 100:
        assert rows_g == exp
    else:
        assert all(exp.get(k) == v for k, v in rows_g.items())
    assert got["sales"] == sorted(got["sales"], reverse=True)


# ------------------------------------------------- q24a/b returned netpaid

def _q24_ssales_plan(st):
    sl = F.scan("store_sales",
                [a("ss_item_sk"), a("ss_ticket_number"), a("ss_store_sk"),
                 a("ss_customer_sk"), a("ss_net_paid")])
    sr = F.scan("store_returns", [a("sr_item_sk"), a("sr_ticket_number")])
    j = big_join(st, sl, sr, [a("ss_item_sk"), a("ss_ticket_number")],
                 [a("sr_item_sk"), a("sr_ticket_number")])
    st_ = F.project(
        [a("s_store_sk"), a("s_store_name"), a("s_county")],
        F.filter_(F.binop("EqualTo", a("s_market_id"), i32(8)),
                  F.scan("store", [a("s_store_sk"), a("s_store_name"),
                                   a("s_county"), a("s_market_id")])),
    )
    j = join(st, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    cu = F.scan("customer", [a("c_customer_sk"), a("c_last_name"),
                             a("c_first_name"), a("c_current_addr_sk")])
    j = join(st, cu, j, [a("c_customer_sk")], [a("ss_customer_sk")])
    ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_county")])
    j = join(st, ca, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    j = F.filter_(F.binop("EqualTo", a("ca_county"), a("s_county")), j)
    it = F.scan("item", [a("i_item_sk"), a("i_color")])
    j = join(st, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    return two_stage(
        [a("c_last_name"), a("c_first_name"), a("s_store_name"), a("i_color")],
        [(F.sum_(a("ss_net_paid")), 730)],
        j,
    )


def _q24_plan(st, color):
    netpaid = ar("netpaid", 730, "decimal(17,2)")
    avg_all = two_stage(
        [], [(F.avg(netpaid), 731)], _q24_ssales_plan(st),
        result=[F.alias(ar("avg_netpaid", 731, "decimal(21,6)"),
                        "avg_netpaid", 732)],
    )
    cells = F.filter_(F.binop("EqualTo", a("i_color"), s(color)),
                      _q24_ssales_plan(st))
    agg = two_stage(
        [a("c_last_name"), a("c_first_name"), a("s_store_name")],
        [(F.sum_(netpaid), 733)],
        cells,
    )
    paid = ar("paid", 733, "decimal(27,2)")
    f = F.filter_(
        F.binop("GreaterThan", F.cast(paid, "double"),
                F.binop("Multiply", F.lit(0.05, "double"),
                        F.cast(_scalar_subquery(avg_all, 734), "double"))),
        agg,
    )
    single = F.shuffle(F.single_partition(),
                       F.project([a("c_last_name"), a("c_first_name"),
                                  a("s_store_name"),
                                  F.alias(paid, "paid", 735)], f))
    return F.sort(
        [F.sort_order(a("c_last_name")), F.sort_order(a("c_first_name")),
         F.sort_order(a("s_store_name"))],
        single,
    )


def _check_q24_rows(got, exp):
    assert exp, "q24 oracle empty"
    rows = {
        (l, f, st_): v for l, f, st_, v in
        zip(got["c_last_name"], got["c_first_name"], got["s_store_name"],
            got["paid"])
    }
    assert rows == exp
    keys = list(zip(got["c_last_name"], got["c_first_name"],
                    got["s_store_name"]))
    assert keys == sorted(keys)


def test_spark_q24a(ticket_sess, ticket_data, strategy):
    got = _execute_both(ticket_sess, _q24_plan(strategy, "peach"))
    _check_q24_rows(got, O.oracle_q24a(ticket_data))


def test_spark_q24b(ticket_sess, ticket_data, strategy):
    got = _execute_both(ticket_sess, _q24_plan(strategy, "saddle"))
    _check_q24_rows(got, O.oracle_q24b(ticket_data))


# ------------------------------------------------------- q72 inventory giant

def test_spark_q72(sess, data, strategy):
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(F.binop("EqualTo", a("hd_buy_potential"), s(">10000")),
                  F.scan("household_demographics",
                         [a("hd_demo_sk"), a("hd_buy_potential")])),
    )
    cd = F.project(
        [a("cd_demo_sk")],
        F.filter_(F.binop("EqualTo", a("cd_marital_status"), s("D")),
                  F.scan("customer_demographics",
                         [a("cd_demo_sk"), a("cd_marital_status")])),
    )
    d1 = F.scan("date_dim", [a("d_date_sk"), a("d_date"), a("d_week_seq")])
    d3sk, d3date = ar("d_date_sk", 601, "long"), ar("d_date", 602, "date")
    d3 = F.project(
        [F.alias(d3sk, "d3_date_sk", 603), F.alias(d3date, "d3_date", 604)],
        F.scan("date_dim", [d3sk, d3date]),
    )
    d2sk, d2wk = ar("d_date_sk", 605, "long"), ar("d_week_seq", 606, "integer")
    d2 = F.project(
        [F.alias(d2sk, "d2_date_sk", 607), F.alias(d2wk, "d2_week_seq", 608)],
        F.scan("date_dim", [d2sk, d2wk]),
    )
    cs = F.scan("catalog_sales",
                [a("cs_sold_date_sk"), a("cs_ship_date_sk"), a("cs_item_sk"),
                 a("cs_bill_cdemo_sk"), a("cs_bill_hdemo_sk"),
                 a("cs_quantity")])
    j = join(strategy, hd, cs, [a("hd_demo_sk")], [a("cs_bill_hdemo_sk")])
    j = join(strategy, cd, j, [a("cd_demo_sk")], [a("cs_bill_cdemo_sk")])
    j = join(strategy, d1, j, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, d3, j, [ar("d3_date_sk", 603, "long")],
             [a("cs_ship_date_sk")])
    j = F.filter_(
        F.binop("GreaterThan", F.cast(ar("d3_date", 604, "date"), "long"),
                F.binop("Add", F.cast(a("d_date"), "long"),
                        F.lit(5, "long"))),
        j,
    )
    inv = F.scan("inventory",
                 [a("inv_date_sk"), a("inv_item_sk"), a("inv_warehouse_sk"),
                  a("inv_quantity_on_hand")])
    j = big_join(strategy, j, inv, [a("cs_item_sk")], [a("inv_item_sk")],
                 build_side="left")
    j = join(strategy, d2, j, [ar("d2_date_sk", 607, "long")],
             [a("inv_date_sk")])
    j = F.filter_(
        and_(F.binop("EqualTo", ar("d2_week_seq", 608, "integer"),
                     a("d_week_seq")),
             F.binop("LessThan", a("inv_quantity_on_hand"),
                     a("cs_quantity"))),
        j,
    )
    it = F.scan("item", [a("i_item_sk"), a("i_item_desc")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("cs_item_sk")])
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_warehouse_name")])
    j = join(strategy, wh, j, [a("w_warehouse_sk")], [a("inv_warehouse_sk")])
    agg = two_stage(
        [a("i_item_desc"), a("w_warehouse_name"), a("d_week_seq")],
        [(F.count(), 501)],
        j,
    )
    no_promo = ar("no_promo", 501, "long")
    plan = F.take_ordered(
        100,
        [F.sort_order(no_promo, asc=False), F.sort_order(a("i_item_desc")),
         F.sort_order(a("w_warehouse_name")), F.sort_order(a("d_week_seq"))],
        [a("i_item_desc"), a("w_warehouse_name"), a("d_week_seq"),
         F.alias(no_promo, "no_promo", 510)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q72(data)
    assert exp, "q72 oracle empty"
    rows = {
        (d, w, wk): c for d, w, wk, c in
        zip(got["i_item_desc"], got["w_warehouse_name"], got["d_week_seq"],
            got["no_promo"])
    }
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["no_promo"] == sorted(got["no_promo"], reverse=True)


# ----------------------------------------------------- q67 rollup-rank giant

def test_spark_q67(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk"), a("d_year"), a("d_qoy"), a("d_moy")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_qoy"),
                                      a("d_moy")])),
    )
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    it = F.scan("item", [a("i_item_sk"), a("i_category"), a("i_class"),
                         a("i_brand"), a("i_item_id")])
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_item_sk"),
                 a("ss_quantity"), a("ss_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    val = F.binop("Multiply", F.cast(a("ss_quantity"), "long"),
                  a("ss_sales_price"))
    base = F.project(
        [a("i_category"), a("i_class"), a("i_brand"), a("i_item_id"),
         a("d_year"), a("d_qoy"), a("d_moy"), a("s_store_name"),
         F.alias(val, "val", 700)],
        j,
    )
    dims = [("i_category", "string"), ("i_class", "string"),
            ("i_brand", "string"), ("i_item_id", "string"),
            ("d_year", "integer"), ("d_qoy", "integer"),
            ("d_moy", "integer"), ("s_store_name", "string")]
    val_a = ar("val", 700, "decimal(17,2)")
    exp_attrs = [ar(nm, 701 + k, dt_) for k, (nm, dt_) in enumerate(dims)]
    exp_gid = ar("g_id", 709, "integer")
    projections = []
    for level in range(8, -1, -1):
        row = [val_a]
        for k, (nm, dt_) in enumerate(dims):
            row.append(a(nm) if k < level else F.lit(None, dt_))
        row.append(F.lit(8 - level, "integer"))
        projections.append(row)
    expand = F.expand(projections, [val_a] + exp_attrs + [exp_gid], base)
    agg = two_stage(
        exp_attrs + [exp_gid],
        [(F.sum_(val_a), 501)],
        expand,
    )
    sumsales = ar("sumsales", 501, "decimal(27,2)")
    cat = exp_attrs[0]
    ex = F.shuffle(F.hash_partitioning([cat], N_PARTS), agg)
    srt = F.sort([F.sort_order(cat), F.sort_order(sumsales, asc=False)],
                 ex, global_=False)
    w = F.window(
        [F.window_expr(F.rank_fn([F.sort_order(sumsales, asc=False)]),
                       F.window_spec([cat],
                                     [F.sort_order(sumsales, asc=False)]),
                       "rk", 520)],
        [cat], [F.sort_order(sumsales, asc=False)], srt,
    )
    rk = ar("rk", 520, "integer")
    f = F.filter_(F.binop("LessThanOrEqual", rk, i32(100)), w)
    plan = F.take_ordered(
        100,
        [F.sort_order(cat), F.sort_order(rk),
         F.sort_order(sumsales, asc=False)],
        [F.alias(e, nm, 530 + k)
         for k, (e, (nm, _)) in enumerate(zip(exp_attrs, dims))]
        + [F.alias(exp_gid, "g_id", 540), F.alias(sumsales, "sumsales", 541),
           F.alias(rk, "rk", 542)],
        f,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q67(data)
    assert exp, "q67 oracle empty"
    n = len(got["i_category"])
    assert n == min(len(exp), 100)
    dim_names = [d[0] for d in dims]
    for i in range(n):
        key = tuple(got[d][i] for d in dim_names) + (got["g_id"][i],)
        assert key in exp, key
        v, rk_e = exp[key]
        assert (got["sumsales"][i], got["rk"][i]) == (v, rk_e), key
    order = [((0, "") if got["i_category"][i] is None
              else (1, got["i_category"][i]), got["rk"][i]) for i in range(n)]
    assert order == sorted(order)


# ------------------------------------------------- q75 cross-channel YoY

def _q75_channel_plan(st, fact, date_c, item_c, qty_c, amt_c, rtab, r_item_c,
                      r_key2_c, key2_c, r_qty_c, r_amt_c):
    dt = F.scan("date_dim", [a("d_date_sk"), a("d_year")])
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_class_id"), a("i_category_id"),
         a("i_manufact_id")],
        F.filter_(F.binop("EqualTo", a("i_category"), s("Books")),
                  F.scan("item", [a("i_item_sk"), a("i_brand_id"),
                                  a("i_class_id"), a("i_category_id"),
                                  a("i_manufact_id"), a("i_category")])),
    )
    sl = F.scan(fact, [a(date_c), a(item_c), a(key2_c), a(qty_c), a(amt_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
    ret = F.scan(rtab, [a(r_item_c), a(r_key2_c), a(r_qty_c), a(r_amt_c)])
    j = big_join(st, j, ret, [a(item_c), a(key2_c)],
                 [a(r_item_c), a(r_key2_c)], jt="LeftOuter")
    qty_net = F.binop(
        "Subtract", F.cast(a(qty_c), "long"),
        F.T(F.X + "CaseWhen",
            [F.un("IsNotNull", a(r_qty_c)), F.cast(a(r_qty_c), "long"),
             F.lit(0, "long")]),
    )
    dz = F.lit(0, "decimal(8,2)")
    amt_net = F.binop(
        "Subtract", F.binop("Add", a(amt_c), dz),
        F.T(F.X + "CaseWhen",
            [F.un("IsNotNull", a(r_amt_c)), F.binop("Add", a(r_amt_c), dz),
             dz]),
    )
    return F.project(
        [a("d_year"), a("i_brand_id"), a("i_class_id"), a("i_category_id"),
         a("i_manufact_id"), F.alias(qty_net, "qty", 750),
         F.alias(amt_net, "amt", 751)],
        j,
    )


def test_spark_q75(ticket_sess, ticket_data, strategy):
    rows = F.union([
        _q75_channel_plan(strategy, "store_sales", "ss_sold_date_sk",
                          "ss_item_sk", "ss_quantity", "ss_ext_sales_price",
                          "store_returns", "sr_item_sk", "sr_ticket_number",
                          "ss_ticket_number", "sr_return_quantity",
                          "sr_return_amt"),
        _q75_channel_plan(strategy, "catalog_sales", "cs_sold_date_sk",
                          "cs_item_sk", "cs_quantity", "cs_ext_sales_price",
                          "catalog_returns", "cr_item_sk", "cr_order_number",
                          "cs_order_number", "cr_return_quantity",
                          "cr_return_amount"),
        _q75_channel_plan(strategy, "web_sales", "ws_sold_date_sk",
                          "ws_item_sk", "ws_quantity", "ws_ext_sales_price",
                          "web_returns", "wr_item_sk", "wr_order_number",
                          "ws_order_number", "wr_return_quantity",
                          "wr_return_amt"),
    ])
    ids = ["i_brand_id", "i_class_id", "i_category_id", "i_manufact_id"]
    agg = two_stage(
        [a("d_year")] + [a(c) for c in ids],
        [(F.sum_(ar("qty", 750, "long")), 501),
         (F.sum_(ar("amt", 751, "decimal(18,2)")), 502)],
        rows,
    )
    cnt = ar("sales_cnt", 501, "long")
    amt = ar("sales_amt", 502, "decimal(28,2)")
    curr = F.project(
        [a(c) for c in ids]
        + [F.alias(cnt, "curr_cnt", 760), F.alias(amt, "curr_amt", 761)],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2002)), agg),
    )
    prev = F.project(
        [F.alias(a(c), f"p_{c}", 770 + k) for k, c in enumerate(ids)]
        + [F.alias(cnt, "prev_cnt", 762), F.alias(amt, "prev_amt", 763)],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)), agg),
    )
    j = big_join(strategy, curr, prev, [a(c) for c in ids],
                 [ar(f"p_{c}", 770 + k, "integer")
                  for k, c in enumerate(ids)])
    curr_cnt = ar("curr_cnt", 760, "long")
    prev_cnt = ar("prev_cnt", 762, "long")
    curr_amt = ar("curr_amt", 761, "decimal(28,2)")
    prev_amt = ar("prev_amt", 763, "decimal(28,2)")
    f = F.filter_(
        and_(F.binop("GreaterThan", F.cast(prev_cnt, "double"),
                     F.lit(0.0, "double")),
             F.binop("LessThan",
                     F.binop("Divide", F.cast(curr_cnt, "double"),
                             F.cast(prev_cnt, "double")),
                     F.lit(0.9, "double"))),
        j,
    )
    cnt_diff = F.binop("Subtract", curr_cnt, prev_cnt)
    amt_diff = F.binop("Subtract", curr_amt, prev_amt)
    proj = F.project(
        [F.alias(F.lit(2001, "integer"), "prev_year", 780),
         F.alias(F.lit(2002, "integer"), "year", 781)]
        + [a(c) for c in ids]
        + [F.alias(cnt_diff, "sales_cnt_diff", 782),
           F.alias(amt_diff, "sales_amt_diff", 783)],
        f,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(ar("sales_cnt_diff", 782, "long")),
         F.sort_order(ar("sales_amt_diff", 783, "decimal(28,2)"))],
        [ar("prev_year", 780, "integer"), ar("year", 781, "integer")]
        + [a(c) for c in ids]
        + [ar("sales_cnt_diff", 782, "long"),
           ar("sales_amt_diff", 783, "decimal(28,2)")],
        proj,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q75(ticket_data)
    assert exp, "q75 oracle empty"
    rows_g = {
        (b, c, cat, m): (cd, ad) for b, c, cat, m, cd, ad in
        zip(got["i_brand_id"], got["i_class_id"], got["i_category_id"],
            got["i_manufact_id"], got["sales_cnt_diff"],
            got["sales_amt_diff"])
    }
    if len(exp) <= 100:
        assert rows_g == exp
    else:
        assert all(exp.get(k) == v for k, v in rows_g.items())
    assert got["sales_cnt_diff"] == sorted(got["sales_cnt_diff"])
    assert all(y == 2002 for y in got["year"])


# ------------------------------------------------- q78 channel loyalty

def _q78_channel_plan(st, fact, date_c, item_c, cust_c, qty_c, wc_c, sp_c,
                      rtab, r_item_c, r_key2_c, key2_c, pre, base_id):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    sl = F.scan(fact, [a(date_c), a(item_c), a(cust_c), a(key2_c), a(qty_c),
                       a(wc_c), a(sp_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    ret = F.scan(rtab, [a(r_item_c), a(r_key2_c)])
    j = big_join(st, j, ret, [a(item_c), a(key2_c)],
                 [a(r_item_c), a(r_key2_c)], jt="LeftAnti")
    proj = F.project(
        [F.alias(a(item_c), f"{pre}_item_sk", base_id),
         F.alias(a(cust_c), f"{pre}_customer_sk", base_id + 1),
         F.alias(F.cast(a(qty_c), "long"), "q", base_id + 2),
         a(wc_c), a(sp_c)],
        j,
    )
    return two_stage(
        [ar(f"{pre}_item_sk", base_id, "long"),
         ar(f"{pre}_customer_sk", base_id + 1, "long")],
        [(F.sum_(ar("q", base_id + 2, "long")), base_id + 3),
         (F.sum_(a(wc_c)), base_id + 4), (F.sum_(a(sp_c)), base_id + 5)],
        proj,
    )


def test_spark_q78(sess, data, strategy):
    ss = _q78_channel_plan(strategy, "store_sales", "ss_sold_date_sk",
                           "ss_item_sk", "ss_customer_sk", "ss_quantity",
                           "ss_wholesale_cost", "ss_sales_price",
                           "store_returns", "sr_item_sk", "sr_ticket_number",
                           "ss_ticket_number", "ss", 800)
    ws = _q78_channel_plan(strategy, "web_sales", "ws_sold_date_sk",
                           "ws_item_sk", "ws_bill_customer_sk", "ws_quantity",
                           "ws_wholesale_cost", "ws_sales_price",
                           "web_returns", "wr_item_sk", "wr_order_number",
                           "ws_order_number", "ws", 810)
    cs = _q78_channel_plan(strategy, "catalog_sales", "cs_sold_date_sk",
                           "cs_item_sk", "cs_bill_customer_sk", "cs_quantity",
                           "cs_wholesale_cost", "cs_sales_price",
                           "catalog_returns", "cr_item_sk", "cr_order_number",
                           "cs_order_number", "cs", 820)
    ss_i, ss_c = ar("ss_item_sk", 800, "long"), ar("ss_customer_sk", 801, "long")
    ws_i, ws_c = ar("ws_item_sk", 810, "long"), ar("ws_customer_sk", 811, "long")
    cs_i, cs_c = ar("cs_item_sk", 820, "long"), ar("cs_customer_sk", 821, "long")
    ss_qty = ar("ss_qty", 803, "long")
    ws_qty = ar("ws_qty", 813, "long")
    cs_qty = ar("cs_qty", 823, "long")
    j = big_join(strategy, ss, ws, [ss_i, ss_c], [ws_i, ws_c], jt="LeftOuter")
    j = big_join(strategy, j, cs, [ss_i, ss_c], [cs_i, cs_c], jt="LeftOuter")

    def czero(c):
        return F.T(F.X + "CaseWhen",
                   [F.un("IsNotNull", c), c, F.lit(0, "long")])

    f = F.filter_(
        or_(F.binop("GreaterThan", czero(ws_qty), F.lit(0, "long")),
            F.binop("GreaterThan", czero(cs_qty), F.lit(0, "long"))),
        j,
    )
    other = F.cast(F.binop("Add", czero(ws_qty), czero(cs_qty)), "double")
    den = F.T(F.X + "CaseWhen",
              [F.binop("GreaterThan", other, F.lit(0.0, "double")), other,
               F.lit(1.0, "double")])
    ratio = F.binop("Divide", F.cast(ss_qty, "double"), den)
    other_q = F.binop("Add", czero(ws_qty), czero(cs_qty))
    proj = F.project(
        [ss_i, ss_c, ss_qty, ar("ss_wc", 804, "decimal(17,2)"),
         ar("ss_sp", 805, "decimal(17,2)"),
         F.alias(ratio, "ratio", 830),
         F.alias(other_q, "other_chan_qty", 831)],
        f,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(ss_qty, asc=False), F.sort_order(ss_i),
         F.sort_order(ss_c)],
        [ss_i, ss_c, ss_qty, ar("ss_wc", 804, "decimal(17,2)"),
         ar("ss_sp", 805, "decimal(17,2)"), ar("ratio", 830, "double"),
         ar("other_chan_qty", 831, "long")],
        proj,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q78(data)
    assert exp, "q78 oracle empty"
    n = len(got["ss_item_sk"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["ss_item_sk"][i], got["ss_customer_sk"][i])
        assert key in exp, key
        q, w_, sp_, ratio_e, other_e = exp[key]
        assert (got["ss_qty"][i], got["ss_wc"][i], got["ss_sp"][i]) == (q, w_, sp_), key
        assert abs(got["ratio"][i] - ratio_e) < 1e-12, key
        assert got["other_chan_qty"][i] == other_e, key
    assert got["ss_qty"] == sorted(got["ss_qty"], reverse=True)


# ------------------------------------------------- q14a/b INTERSECT giants

def _q14_cross_items_plan(st):
    def triples(fact, date_c, item_c):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(and_(F.binop("GreaterThanOrEqual", a("d_year"), i32(1998)),
                           F.binop("LessThanOrEqual", a("d_year"), i32(2000))),
                      F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
        )
        it = F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_class_id"),
                             a("i_category_id")])
        sl = F.scan(fact, [a(date_c), a(item_c)])
        j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
        j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
        return two_stage(
            [a("i_brand_id"), a("i_class_id"), a("i_category_id")], [], j)

    ss = triples("store_sales", "ss_sold_date_sk", "ss_item_sk")
    cs = triples("catalog_sales", "cs_sold_date_sk", "cs_item_sk")
    ws = triples("web_sales", "ws_sold_date_sk", "ws_item_sk")
    keys = [a("i_brand_id"), a("i_class_id"), a("i_category_id")]
    inter = join(st, cs, ss, keys, keys, jt="LeftSemi", build_side="right")
    inter = join(st, ws, inter, keys, keys, jt="LeftSemi", build_side="right")
    items = F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_class_id"),
                            a("i_category_id")])
    hot = join(st, inter, items, keys, keys, jt="LeftSemi", build_side="right")
    return F.project([a("i_item_sk")], hot)


def _q14_avg_sales_plan(st):
    branches = []
    for k, (fact, date_c, q_c, p_c) in enumerate((
        ("store_sales", "ss_sold_date_sk", "ss_quantity", "ss_list_price"),
        ("catalog_sales", "cs_sold_date_sk", "cs_quantity", "cs_list_price"),
        ("web_sales", "ws_sold_date_sk", "ws_quantity", "ws_list_price"),
    )):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(and_(F.binop("GreaterThanOrEqual", a("d_year"), i32(1998)),
                           F.binop("LessThanOrEqual", a("d_year"), i32(2000))),
                      F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
        )
        sl = F.scan(fact, [a(date_c), a(q_c), a(p_c)])
        j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
        v = F.binop("Multiply", F.cast(a(q_c), "long"), a(p_c))
        branches.append(F.project([F.alias(v, "v", 900)], j))
    return two_stage(
        [], [(F.avg(ar("v", 900, "decimal(17,2)")), 901)],
        F.union(branches),
        result=[F.alias(ar("average_sales", 901, "decimal(21,6)"),
                        "average_sales", 902)],
    )


def _q14_cells_plan(st, fact, date_c, item_c, q_c, p_c, cross, avg_sub, year):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(year)),
                       F.binop("EqualTo", a("d_moy"), i32(11))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                      a("d_moy")])),
    )
    it = F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_class_id"),
                         a("i_category_id")])
    sl = F.scan(fact, [a(date_c), a(item_c), a(q_c), a(p_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    j = join(st, cross, j, [a("i_item_sk")], [a(item_c)], jt="LeftSemi",
             build_side="right")
    j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
    v = F.binop("Multiply", F.cast(a(q_c), "long"), a(p_c))
    proj = F.project(
        [a("i_brand_id"), a("i_class_id"), a("i_category_id"),
         F.alias(v, "v", 910)],
        j,
    )
    agg = two_stage(
        [a("i_brand_id"), a("i_class_id"), a("i_category_id")],
        [(F.sum_(ar("v", 910, "decimal(17,2)")), 911), (F.count(), 912)],
        proj,
    )
    return F.filter_(
        F.binop("GreaterThan",
                F.cast(ar("sales", 911, "decimal(27,2)"), "double"),
                F.cast(avg_sub, "double")),
        agg,
    )


def test_spark_q14a(sess, data, strategy):
    cross = _q14_cross_items_plan(strategy)
    avg_plan = _q14_avg_sales_plan(strategy)
    sales = ar("sales", 911, "decimal(27,2)")
    number = ar("number_sales", 912, "long")
    branches = []
    for k, (name, fact, date_c, item_c, q_c, p_c) in enumerate((
        ("store", "store_sales", "ss_sold_date_sk", "ss_item_sk",
         "ss_quantity", "ss_list_price"),
        ("catalog", "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
         "cs_quantity", "cs_list_price"),
        ("web", "web_sales", "ws_sold_date_sk", "ws_item_sk",
         "ws_quantity", "ws_list_price"),
    )):
        cells = _q14_cells_plan(strategy, fact, date_c, item_c, q_c, p_c,
                                cross, _scalar_subquery(avg_plan, 920 + k),
                                2002)
        branches.append(F.project(
            [F.alias(F.lit(name, "string"), "channel", 930),
             a("i_brand_id"), a("i_class_id"), a("i_category_id"),
             sales, number],
            cells,
        ))
    u = F.union(branches)
    chan = ar("channel", 930, "string")
    dims = [(chan, "string"), (a("i_brand_id"), "integer"),
            (a("i_class_id"), "integer"), (a("i_category_id"), "integer")]
    exp_attrs = [ar(["channel", "i_brand_id", "i_class_id",
                     "i_category_id"][k], 940 + k, dt_)
                 for k, (_, dt_) in enumerate(dims)]
    exp_gid = ar("g_id", 944, "integer")
    projections = []
    for level in range(4, -1, -1):
        row = [sales, number]
        for k, (e, dt_) in enumerate(dims):
            row.append(e if k < level else F.lit(None, dt_))
        row.append(F.lit(4 - level, "integer"))
        projections.append(row)
    expand = F.expand(projections, [sales, number] + exp_attrs + [exp_gid], u)
    agg = two_stage(
        exp_attrs + [exp_gid],
        [(F.sum_(sales), 950), (F.sum_(number), 951)],
        expand,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(e) for e in exp_attrs] + [F.sort_order(exp_gid)],
        [F.alias(exp_attrs[0], "channel", 960),
         F.alias(exp_attrs[1], "i_brand_id", 961),
         F.alias(exp_attrs[2], "i_class_id", 962),
         F.alias(exp_attrs[3], "i_category_id", 963),
         F.alias(exp_gid, "g_id", 964),
         F.alias(ar("sum_sales", 950, "decimal(37,2)"), "sum_sales", 965),
         F.alias(ar("sum_number_sales", 951, "long"),
                 "sum_number_sales", 966)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q14a(data)
    assert exp, "q14a oracle empty"
    n = len(got["channel"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["channel"][i], got["i_brand_id"][i], got["i_class_id"][i],
               got["i_category_id"][i])
        assert key in exp, key
        assert (got["sum_sales"][i], got["sum_number_sales"][i]) == exp[key], key
    from test_tpcds import _nf
    order = [tuple(_nf(got[c][i]) for c in
                   ("channel", "i_brand_id", "i_class_id", "i_category_id"))
             for i in range(n)]
    assert order == sorted(order)


def test_spark_q14b(sess, data, strategy):
    cross = _q14_cross_items_plan(strategy)
    avg_plan = _q14_avg_sales_plan(strategy)
    ty = _q14_cells_plan(strategy, "store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_quantity", "ss_list_price",
                         cross, _scalar_subquery(avg_plan, 920), 2002)
    ly = _q14_cells_plan(strategy, "store_sales", "ss_sold_date_sk",
                         "ss_item_sk", "ss_quantity", "ss_list_price",
                         cross, _scalar_subquery(avg_plan, 921), 2001)
    sales = ar("sales", 911, "decimal(27,2)")
    number = ar("number_sales", 912, "long")
    ly = F.project(
        [F.alias(a("i_brand_id"), "l_brand_id", 970),
         F.alias(a("i_class_id"), "l_class_id", 971),
         F.alias(a("i_category_id"), "l_category_id", 972),
         F.alias(sales, "last_sales", 973),
         F.alias(number, "last_number_sales", 974)],
        ly,
    )
    j = big_join(strategy, ty, ly,
                 [a("i_brand_id"), a("i_class_id"), a("i_category_id")],
                 [ar("l_brand_id", 970, "integer"),
                  ar("l_class_id", 971, "integer"),
                  ar("l_category_id", 972, "integer")])
    last_sales = ar("last_sales", 973, "decimal(27,2)")
    f = F.filter_(
        F.binop("GreaterThan", F.cast(sales, "double"),
                F.cast(last_sales, "double")),
        j,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a("i_brand_id")), F.sort_order(a("i_class_id")),
         F.sort_order(a("i_category_id"))],
        [a("i_brand_id"), a("i_class_id"), a("i_category_id"),
         F.alias(sales, "sales", 980), F.alias(number, "number_sales", 981),
         F.alias(last_sales, "last_sales", 982),
         F.alias(ar("last_number_sales", 974, "long"),
                 "last_number_sales", 983)],
        f,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q14b(data)
    assert exp, "q14b oracle empty"
    rows_g = {
        (b, c, cat): (s_, ns, ls, lns) for b, c, cat, s_, ns, ls, lns in
        zip(got["i_brand_id"], got["i_class_id"], got["i_category_id"],
            got["sales"], got["number_sales"], got["last_sales"],
            got["last_number_sales"])
    }
    if len(exp) <= 100:
        assert rows_g == exp
    else:
        assert all(exp.get(k) == v for k, v in rows_g.items())


# ------------------------------------------------- q64 cross-year self-join

def _q64_cross_sales_plan(st, year):
    sl = F.scan("store_sales",
                [a("ss_item_sk"), a("ss_ticket_number"), a("ss_store_sk"),
                 a("ss_sold_date_sk"), a("ss_wholesale_cost"),
                 a("ss_list_price"), a("ss_coupon_amt")])
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(year)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    sl = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    sr = F.scan("store_returns", [a("sr_item_sk"), a("sr_ticket_number")])
    j = big_join(st, sl, sr, [a("ss_item_sk"), a("ss_ticket_number")],
                 [a("sr_item_sk"), a("sr_ticket_number")])
    it = F.project(
        [a("i_item_sk"), a("i_item_id")],
        F.filter_(
            in_(a("i_color"), "purple", "burlywood", "indian", "spring",
                "floral", "medium", "peach", "saddle", "navy", "slate"),
            F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_color")]),
        ),
    )
    j = join(st, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    st2 = F.scan("store", [a("s_store_sk"), a("s_store_name"), a("s_zip")])
    j = join(st, st2, j, [a("s_store_sk")], [a("ss_store_sk")])
    return two_stage(
        [a("i_item_id"), a("s_store_name"), a("s_zip")],
        [(F.count(), 851), (F.sum_(a("ss_wholesale_cost")), 852),
         (F.sum_(a("ss_list_price")), 853), (F.sum_(a("ss_coupon_amt")), 854)],
        j,
    )


def test_spark_q64(sess, data, strategy):
    cnt = ar("cnt", 851, "long")
    s1 = ar("s1", 852, "decimal(17,2)")
    s2 = ar("s2", 853, "decimal(17,2)")
    s3 = ar("s3", 854, "decimal(17,2)")
    cs1 = _q64_cross_sales_plan(strategy, 2001)
    cs2 = F.project(
        [F.alias(a("i_item_id"), "r_item_id", 860),
         F.alias(a("s_store_name"), "r_store_name", 861),
         F.alias(a("s_zip"), "r_zip", 862),
         F.alias(cnt, "cnt2", 863), F.alias(s1, "s1_2", 864),
         F.alias(s2, "s2_2", 865), F.alias(s3, "s3_2", 866)],
        _q64_cross_sales_plan(strategy, 2002),
    )
    j = big_join(strategy, cs1, cs2,
                 [a("i_item_id"), a("s_store_name"), a("s_zip")],
                 [ar("r_item_id", 860, "string"),
                  ar("r_store_name", 861, "string"),
                  ar("r_zip", 862, "string")])
    cnt2 = ar("cnt2", 863, "long")
    f = F.filter_(F.binop("LessThanOrEqual", cnt2, cnt), j)
    plan = F.take_ordered(
        100,
        [F.sort_order(s1, asc=False), F.sort_order(a("i_item_id")),
         F.sort_order(a("s_store_name")), F.sort_order(a("s_zip"))],
        [a("i_item_id"), a("s_store_name"), a("s_zip"),
         F.alias(cnt, "cnt", 870), F.alias(s1, "s1", 871),
         F.alias(s2, "s2", 872), F.alias(s3, "s3", 873),
         F.alias(cnt2, "cnt2", 874),
         F.alias(ar("s1_2", 864, "decimal(17,2)"), "s1_2", 875),
         F.alias(ar("s2_2", 865, "decimal(17,2)"), "s2_2", 876),
         F.alias(ar("s3_2", 866, "decimal(17,2)"), "s3_2", 877)],
        f,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q64(data)
    assert exp, "q64 oracle empty"
    rows_g = {
        (i, st_, z): (c1, x, y, zz, c2, d, e, f_) for
        i, st_, z, c1, x, y, zz, c2, d, e, f_ in
        zip(got["i_item_id"], got["s_store_name"], got["s_zip"], got["cnt"],
            got["s1"], got["s2"], got["s3"], got["cnt2"], got["s1_2"],
            got["s2_2"], got["s3_2"])
    }
    if len(exp) <= 100:
        assert rows_g == exp
    else:
        assert all(exp.get(k) == v for k, v in rows_g.items())
    assert got["s1"] == sorted(got["s1"], reverse=True)


# ---------------------- q51: FULL OUTER of two cumulative-window streams

def _q51_chan(strategy, fact, date_c, item_c, price_c, px, b):
    """One channel's per-item daily running-sum stream: the FIRST
    running-frame (order-by default RANGE up->CURRENT ROW) window
    through the conversion layer."""
    dt = F.project(
        [a("d_date_sk"), a("d_date")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_date"), a("d_year")])),
    )
    sl = F.scan(fact, [a(date_c), a(item_c), a(price_c)])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a(date_c)])
    agg = two_stage([a(item_c), a("d_date")], [(F.sum_(a(price_c)), b)], j)
    sales = ar("sales", b, "decimal(17,2)")
    ex = F.shuffle(F.hash_partitioning([a(item_c)], N_PARTS), agg)
    srt = F.sort([F.sort_order(a(item_c)), F.sort_order(a("d_date"))], ex,
                 global_=False)
    w = F.window(
        [F.window_expr(F.window_agg(F.sum_(sales)),
                       F.window_spec([a(item_c)], [F.sort_order(a("d_date"))]),
                       "cume", b + 1)],
        [a(item_c)], [F.sort_order(a("d_date"))], srt,
    )
    return F.project(
        [F.alias(a(item_c), f"{px}_item_sk", b + 2),
         F.alias(a("d_date"), f"{px}_date", b + 3),
         F.alias(ar("cume", b + 1, "decimal(27,2)"), f"{px}_cume", b + 4)],
        w,
    )


def test_spark_q51(sess, data, strategy):
    web = _q51_chan(strategy, "web_sales", "ws_sold_date_sk", "ws_item_sk",
                    "ws_sales_price", "w", 9001)
    store = _q51_chan(strategy, "store_sales", "ss_sold_date_sk", "ss_item_sk",
                      "ss_sales_price", "s", 9011)
    wi, wd = ar("w_item_sk", 9003), ar("w_date", 9004, "date")
    wc = ar("w_cume", 9005, "decimal(27,2)")
    si, sd = ar("s_item_sk", 9013), ar("s_date", 9014, "date")
    sc = ar("s_cume", 9015, "decimal(27,2)")
    j = big_join(strategy, web, store, [wi, wd], [si, sd], jt="FullOuter")
    item = F.alias(F.T(F.X + "Coalesce", [wi, si]), "item_sk", 9021)
    dd = F.alias(F.T(F.X + "Coalesce", [wd, sd]), "d_date", 9022)
    proj = F.project([item, dd, wc, sc], j)
    item_a, dd_a = ar("item_sk", 9021), ar("d_date", 9022, "date")
    ex = F.shuffle(F.hash_partitioning([item_a], N_PARTS), proj)
    srt = F.sort([F.sort_order(item_a), F.sort_order(dd_a)], ex, global_=False)
    # running maxes carry each channel's cumulative value across the
    # FULL OUTER join's null gaps
    w2 = F.window(
        [F.window_expr(F.window_agg(F.max_(wc)),
                       F.window_spec([item_a], [F.sort_order(dd_a)]),
                       "web_cumulative", 9023),
         F.window_expr(F.window_agg(F.max_(sc)),
                       F.window_spec([item_a], [F.sort_order(dd_a)]),
                       "store_cumulative", 9024)],
        [item_a], [F.sort_order(dd_a)], srt,
    )
    wcu = ar("web_cumulative", 9023, "decimal(27,2)")
    scu = ar("store_cumulative", 9024, "decimal(27,2)")
    filt = F.filter_(F.binop("GreaterThan", wcu, scu), w2)
    plan = F.take_ordered(
        100, [F.sort_order(item_a), F.sort_order(dd_a)],
        [item_a, dd_a, wcu, scu], filt,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q51(data)
    assert exp, "q51 oracle empty"
    n = len(got["item_sk"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["item_sk"][i], got["d_date"][i])
        assert key in exp, key
        assert (got["web_cumulative"][i], got["store_cumulative"][i]) == exp[key], key
    keys = list(zip(got["item_sk"], got["d_date"]))
    assert keys == sorted(keys)
    if len(exp) > 100:
        assert keys == sorted(exp)[:100]


# ------------------------- q44: rank-paired best/worst items by profit

def test_spark_q44(sess, data, strategy):
    """Two rank() windows (asc/desc) over per-item average profit above
    90% of a scalar-subquery baseline, joined ON THE RANK — the rank
    self-pairing + second item scan with fresh exprIds exercise window
    output flowing into join keys through conversion."""
    store = F.lit(4, "long")
    scan_cols = [a("ss_item_sk"), a("ss_net_profit"), a("ss_store_sk"),
                 a("ss_addr_sk")]
    base = F.project(
        [a("ss_item_sk"), a("ss_net_profit")],
        F.filter_(F.binop("EqualTo", a("ss_store_sk"), store),
                  F.scan("store_sales", scan_cols)),
    )
    per_item = two_stage([a("ss_item_sk")],
                         [(F.avg(a("ss_net_profit")), 9101)], base)
    rank_col = ar("rank_col", 9101, "decimal(11,6)")
    null_addr = F.project(
        [a("ss_net_profit")],
        F.filter_(and_(F.binop("EqualTo", a("ss_store_sk"), store),
                       F.binop("EqualTo", a("ss_addr_sk"), F.lit(-1, "long"))),
                  F.scan("store_sales", scan_cols)),
    )
    thr_plan = two_stage([], [(F.avg(a("ss_net_profit")), 9102)], null_addr)
    keep = F.filter_(
        F.binop(
            "GreaterThan", F.cast(rank_col, "double"),
            F.binop("Multiply", F.lit(0.9, "double"),
                    F.cast(_scalar_subquery(thr_plan, 9102), "double")),
        ),
        per_item,
    )
    single = F.shuffle(F.single_partition(), keep)

    def ranked(asc, item_alias, rnk_alias, b):
        o = [F.sort_order(rank_col, asc=asc)]
        srt = F.sort(o, single, global_=False)
        w = F.window(
            [F.window_expr(F.rank_fn([rank_col]), F.window_spec([], o),
                           "rnk", b)],
            [], o, srt,
        )
        f = F.filter_(F.binop("LessThanOrEqual", ar("rnk", b),
                              F.lit(10, "integer")), w)
        return F.project(
            [F.alias(a("ss_item_sk"), item_alias, b + 1),
             F.alias(ar("rnk", b), rnk_alias, b + 2)], f)

    asc = ranked(True, "best_sk", "rnk", 9103)
    desc = ranked(False, "worst_sk", "rnk_d", 9106)
    rnk_a, rnkd_a = ar("rnk", 9105, "integer"), ar("rnk_d", 9108, "integer")
    best_a, worst_a = ar("best_sk", 9104), ar("worst_sk", 9107)
    j = big_join(strategy, asc, desc, [rnk_a], [rnkd_a])
    i1 = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    j = join(strategy, i1, j, [a("i_item_sk")], [best_a])
    i2sk, i2id = ar("i_item_sk", 9121), ar("i_item_id", 9122, "string")
    i2 = F.scan("item", [i2sk, i2id])
    j = join(strategy, i2, j, [i2sk], [worst_a])
    plan = F.take_ordered(
        100, [F.sort_order(rnk_a)],
        [rnk_a, F.alias(a("i_item_id"), "best_name", 9131),
         F.alias(i2id, "worst_name", 9132)], j)
    got = _execute_both(sess, plan)
    exp = O.oracle_q44(data)
    assert exp, "q44 oracle empty"
    rows = set(zip(got["rnk"], got["best_name"], got["worst_name"]))
    assert len(got["rnk"]) == min(len(exp), 100)
    assert rows == exp if len(exp) <= 100 else rows <= exp
    assert got["rnk"] == sorted(got["rnk"])


# ----------------- q9: five CASE buckets over 15 scalar subqueries

def test_spark_q9(sess, data, strategy):
    """Fifteen ScalarSubqueries (count/avg/avg per quantity band)
    inside five CaseWhen branches, projected over the 1-row reason
    slice — the heaviest driver-side subquery resolution shape in the
    matrix (≙ SparkScalarSubqueryWrapperExpr evaluation)."""
    from blaze_tpu.tpcds.queries import Q9_THRESHOLDS

    if strategy == "smj":
        pytest.skip("no joins in q9: the strategy axis is vacuous")

    def band_plan(lo, hi, agg_fn, rid):
        band = F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("ss_quantity"), i32(lo)),
                 F.binop("LessThanOrEqual", a("ss_quantity"), i32(hi))),
            F.scan("store_sales", [a("ss_quantity"), a("ss_ext_discount_amt"),
                                   a("ss_net_profit")]),
        )
        return two_stage([], [(agg_fn, rid)], band)

    exprs = []
    for b, thresh in enumerate(Q9_THRESHOLDS):
        lo, hi = 20 * b + 1, 20 * (b + 1)
        rid = 9200 + b * 10
        cnt = _scalar_subquery(band_plan(lo, hi, F.count(), rid), rid)
        avg_disc = _scalar_subquery(
            band_plan(lo, hi, F.avg(a("ss_ext_discount_amt")), rid + 1), rid + 1)
        avg_profit = _scalar_subquery(
            band_plan(lo, hi, F.avg(a("ss_net_profit")), rid + 2), rid + 2)
        case = F.T(
            F.X + "CaseWhen",
            [F.binop("GreaterThan", cnt, F.lit(thresh, "long")),
             avg_disc, avg_profit],
        )
        exprs.append(F.alias(case, f"bucket{b + 1}", 9300 + b))
    src = F.filter_(F.binop("EqualTo", a("r_reason_sk"), F.lit(1, "long")),
                    F.scan("reason", [a("r_reason_sk"), a("r_reason_desc")]))
    plan = F.project(exprs, src)
    got = _execute_both(sess, plan)
    exp = O.oracle_q9(data, Q9_THRESHOLDS)
    assert len(got["bucket1"]) == 1
    for b in range(len(Q9_THRESHOLDS)):
        g = got[f"bucket{b + 1}"][0]
        assert abs(g - exp[b]) <= 1, (b, g, exp[b])


# --------------------------------------- q3 brand report (ticket slice)

def test_spark_q3(ticket_sess, ticket_data, strategy):
    """Star join + brand rollup (manufact 128 only appears at the 0.01
    datagen slice, same as test_tpcds.test_q3)."""
    dt = F.project(
        [a("d_date_sk"), a("d_year")],
        F.filter_(F.binop("EqualTo", a("d_moy"), i32(11)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")])),
    )
    sales = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"),
                                   a("ss_ext_sales_price")])
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_brand")],
        F.filter_(F.binop("EqualTo", a("i_manufact_id"), i32(128)),
                  F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_brand"),
                                  a("i_manufact_id")])),
    )
    j = join(strategy, dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("ss_item_sk")])
    agg = two_stage([a("d_year"), a("i_brand_id"), a("i_brand")],
                    [(F.sum_(a("ss_ext_sales_price")), 501)], j)
    sum_agg = ar("sum_agg", 501, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(a("d_year")), F.sort_order(sum_agg, asc=False),
         F.sort_order(a("i_brand_id"))],
        [F.alias(a("d_year"), "d_year", 510),
         F.alias(a("i_brand_id"), "brand_id", 511),
         F.alias(a("i_brand"), "brand", 512),
         F.alias(sum_agg, "sum_agg", 513)],
        agg,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q3(ticket_data)
    assert exp, "q3 oracle matched no rows"
    _check_brand_report(got, exp, "sum_agg")
    assert got["d_year"] == sorted(got["d_year"])


# --------------------------- q12/q20 class-share reports (q98's twins)

def _class_share_plan(st, fact, date_c, item_c, price_c):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("1999-02-22", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("1999-03-24", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    it = F.project(
        [a("i_item_sk"), a("i_item_id"), a("i_item_desc"), a("i_category"),
         a("i_class"), a("i_current_price")],
        F.filter_(
            in_(a("i_category"), "Sports", "Books", "Home"),
            F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_item_desc"),
                            a("i_class"), a("i_category"), a("i_current_price")]),
        ),
    )
    sales = F.scan(fact, [a(date_c), a(item_c), a(price_c)])
    j = join(st, dt, sales, [a("d_date_sk")], [a(date_c)])
    j = join(st, it, j, [a("i_item_sk")], [a(item_c)])
    agg = two_stage(
        [a("i_item_id"), a("i_item_desc"), a("i_category"), a("i_class"),
         a("i_current_price")],
        [(F.sum_(a(price_c)), 501)],
        j,
    )
    itemrev = ar("itemrevenue", 501, "decimal(17,2)")
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort([F.sort_order(a("i_class"))], single)
    w = F.window(
        [F.window_expr(
            F.window_agg(F.sum_(itemrev)),
            F.window_spec([a("i_class")], [], F.window_frame("up", "uf", row=True)),
            "class_revenue", 502)],
        [a("i_class")],
        [],
        pre,
    )
    class_rev = ar("class_revenue", 502, "decimal(27,2)")
    ratio = F.binop(
        "Divide",
        F.binop("Multiply", F.cast(itemrev, "double"), F.lit(100.0, "double")),
        F.cast(class_rev, "double"),
    )
    proj = F.project(
        [a("i_item_id"), a("i_item_desc"), a("i_category"), a("i_class"),
         a("i_current_price"), itemrev,
         F.alias(ratio, "revenueratio", 510)],
        w,
    )
    ratio_o = ar("revenueratio", 510, "double")
    sorted_ = F.sort(
        [F.sort_order(a("i_category")), F.sort_order(a("i_class")),
         F.sort_order(a("i_item_id")), F.sort_order(a("i_item_desc")),
         F.sort_order(ratio_o)],
        F.shuffle(F.single_partition(), proj),
    )
    return F.project(
        [F.alias(a("i_item_id"), "i_item_id", 520),
         F.alias(a("i_item_desc"), "i_item_desc", 521),
         F.alias(a("i_category"), "i_category", 522),
         F.alias(a("i_class"), "i_class", 523),
         F.alias(a("i_current_price"), "i_current_price", 524),
         F.alias(itemrev, "itemrevenue", 525),
         F.alias(ratio_o, "revenueratio", 526)],
        sorted_,
    )


def test_spark_q20(sess, data, strategy):
    plan = _class_share_plan(strategy, "catalog_sales", "cs_sold_date_sk",
                             "cs_item_sk", "cs_ext_sales_price")
    got = _execute_both(sess, plan)
    _check_class_share(got, O.oracle_q20(data))


def test_spark_q12(sess, data, strategy):
    plan = _class_share_plan(strategy, "web_sales", "ws_sold_date_sk",
                             "ws_item_sk", "ws_ext_sales_price")
    got = _execute_both(sess, plan)
    _check_class_share(got, O.oracle_q12(data))


# ------------------------------ q37/q82 inventory price-band items

def _inv_price_plan(st, fact, item_c):
    """Items in a price band with healthy inventory that also sold in
    the channel: bcast date window, strategy-shaped item<->inventory
    join, LEFT SEMI against the fact, grouping-only (DISTINCT) agg."""
    dec = "decimal(7,2)"
    it = F.project(
        [a("i_item_sk"), a("i_item_id"), a("i_item_desc"), a("i_current_price")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("i_current_price"),
                         F.lit("30", dec)),
                 F.binop("LessThanOrEqual", a("i_current_price"),
                         F.lit("60", dec))),
            F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_item_desc"),
                            a("i_current_price")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-02-01", "date")),
                 F.binop("LessThan", a("d_date"), F.lit("2000-04-01", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    inv = F.project(
        [a("inv_date_sk"), a("inv_item_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("inv_quantity_on_hand"), i32(100)),
                 F.binop("LessThanOrEqual", a("inv_quantity_on_hand"), i32(500))),
            F.scan("inventory", [a("inv_date_sk"), a("inv_item_sk"),
                                 a("inv_quantity_on_hand")]),
        ),
    )
    j = join(st, dt, inv, [a("d_date_sk")], [a("inv_date_sk")])
    j = join(st, it, j, [a("i_item_sk")], [a("inv_item_sk")])
    sold = F.scan(fact, [a(item_c)])
    j = join(st, sold, j, [a(item_c)], [a("i_item_sk")], jt="LeftSemi",
             build_side="right")
    agg = distinct([a("i_item_id"), a("i_item_desc"), a("i_current_price")], j)
    return F.take_ordered(
        100, [F.sort_order(a("i_item_id"))],
        [F.alias(a("i_item_id"), "i_item_id", 530),
         F.alias(a("i_item_desc"), "i_item_desc", 531),
         F.alias(a("i_current_price"), "i_current_price", 532)],
        agg,
    )


def test_spark_q37(sess, data, strategy):
    got = _execute_both(sess, _inv_price_plan(strategy, "catalog_sales",
                                              "cs_item_sk"))
    _check_inv_price(got, O.oracle_q37(data))


def test_spark_q82(sess, data, strategy):
    got = _execute_both(sess, _inv_price_plan(strategy, "store_sales",
                                              "ss_item_sk"))
    _check_inv_price(got, O.oracle_q82(data))


# ------------------- q32/q92 excess discount (decorrelated per-item avg)

def _excess_discount_plan(st, fact, date_c, item_c, amt_c):
    from blaze_tpu.tpcds.queries import Q32_MFG_MAX

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-01-27", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2000-04-26", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    sl = F.scan(fact, [a(date_c), a(item_c), a(amt_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    src = F.project([F.alias(a(item_c), "avg_item_sk", 520), a(amt_c)], j)
    per_item = two_stage([ar("avg_item_sk", 520, "long")],
                         [(F.avg(a(amt_c)), 501)], src)
    avg_amt = ar("avg_amt", 501, "decimal(11,6)")
    jj = join(st, per_item, j, [ar("avg_item_sk", 520, "long")], [a(item_c)])
    keep = F.binop(
        "GreaterThan", F.cast(a(amt_c), "double"),
        F.binop("Multiply", F.cast(avg_amt, "double"), F.lit(1.3, "double")))
    f = F.filter_(keep, jj)
    it_p = F.project(
        [a("i_item_sk")],
        F.filter_(F.binop("LessThanOrEqual", a("i_manufact_id"),
                          i32(Q32_MFG_MAX)),
                  F.scan("item", [a("i_item_sk"), a("i_manufact_id")])),
    )
    f = join(st, it_p, f, [a("i_item_sk")], [a(item_c)], jt="LeftSemi",
             build_side="right")
    agg = two_stage([], [(F.sum_(a(amt_c)), 502)], f)
    return F.project(
        [F.alias(ar("excess", 502, "decimal(17,2)"), "excess_discount", 530)],
        agg,
    )


def test_spark_q32(sess, data, strategy):
    got = _execute_both(sess, _excess_discount_plan(
        strategy, "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
        "cs_ext_discount_amt"))
    exp = O.oracle_q32(data)
    assert exp is not None, "q32 slice matched no rows"
    assert got["excess_discount"] == [exp]


def test_spark_q92(sess, data, strategy):
    got = _execute_both(sess, _excess_discount_plan(
        strategy, "web_sales", "ws_sold_date_sk", "ws_item_sk",
        "ws_ext_discount_amt"))
    exp = O.oracle_q92(data)
    assert exp is not None, "q92 slice matched no rows"
    assert got["excess_discount"] == [exp]


# -------------------------- q15 OR-of-unlike-predicates zip report

def test_spark_q15(sess, data, strategy):
    from blaze_tpu.tpcds.queries import Q15_ZIPS

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_qoy"), i32(2)),
                       F.binop("EqualTo", a("d_year"), i32(2001))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_qoy"), a("d_year")])),
    )
    cust = F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk")])
    ca = F.scan("customer_address",
                [a("ca_address_sk"), a("ca_zip"), a("ca_state")])
    sl = F.scan("catalog_sales",
                [a("cs_sold_date_sk"), a("cs_bill_customer_sk"),
                 a("cs_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, cust, j, [a("c_customer_sk")], [a("cs_bill_customer_sk")])
    j = join(strategy, ca, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    zip5 = F.T(F.X + "Substring", [a("ca_zip"), i32(1), i32(5)])
    keep = or_(
        in_(zip5, *Q15_ZIPS),
        in_(a("ca_state"), "TN", "GA", "OH"),
        F.binop("GreaterThan", a("cs_sales_price"),
                F.lit("250", "decimal(7,2)")),
    )
    f = F.filter_(keep, j)
    agg = two_stage([a("ca_zip")], [(F.sum_(a("cs_sales_price")), 501)], f)
    plan = F.take_ordered(
        100, [F.sort_order(a("ca_zip"))],
        [F.alias(a("ca_zip"), "ca_zip", 510),
         F.alias(ar("sum_price", 501, "decimal(17,2)"), "sum_price", 511)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q15(data)
    assert exp, "q15 oracle matched no rows"
    rows = dict(zip(got["ca_zip"], got["sum_price"]))
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["ca_zip"] == sorted(got["ca_zip"])


# ---------------- q88/q90/q61 scalar-subquery cross-join one-row reports

def test_spark_q88(sess, data, strategy):
    """Eight half-hour store traffic counts: the spec's cross join of
    eight scalar COUNT subqueries, each a 3-join star under the
    strategy shape, resolved driver-side."""
    hd = F.project(
        [a("hd_demo_sk")],
        F.filter_(
            or_(and_(F.binop("EqualTo", a("hd_dep_count"), i32(4)),
                     F.binop("LessThanOrEqual", a("hd_vehicle_count"), i32(6))),
                and_(F.binop("EqualTo", a("hd_dep_count"), i32(2)),
                     F.binop("LessThanOrEqual", a("hd_vehicle_count"), i32(4))),
                and_(F.binop("EqualTo", a("hd_dep_count"), i32(0)),
                     F.binop("LessThanOrEqual", a("hd_vehicle_count"), i32(2)))),
            F.scan("household_demographics",
                   [a("hd_demo_sk"), a("hd_dep_count"), a("hd_vehicle_count")]),
        ),
    )
    st_p = F.project(
        [a("s_store_sk")],
        F.filter_(F.binop("EqualTo", a("s_store_name"), s("ese")),
                  F.scan("store", [a("s_store_sk"), a("s_store_name")])),
    )
    exprs = []
    for k in range(8):
        h, half = divmod(k + 17, 2)
        tpred = (F.binop("GreaterThanOrEqual", a("t_minute"), i32(30)) if half
                 else F.binop("LessThan", a("t_minute"), i32(30)))
        td = F.project(
            [a("t_time_sk")],
            F.filter_(and_(F.binop("EqualTo", a("t_hour"), i32(h)), tpred),
                      F.scan("time_dim", [a("t_time_sk"), a("t_hour"),
                                          a("t_minute")])),
        )
        sl = F.scan("store_sales", [a("ss_sold_time_sk"), a("ss_hdemo_sk"),
                                    a("ss_store_sk")])
        j = join(strategy, td, sl, [a("t_time_sk")], [a("ss_sold_time_sk")])
        j = join(strategy, hd, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
        j = join(strategy, st_p, j, [a("s_store_sk")], [a("ss_store_sk")])
        cnt_plan = two_stage([], [(F.count(), 601 + k)], j)
        exprs.append(F.alias(
            _scalar_subquery(cnt_plan, 601 + k),
            f"h{h}_{30 if half else 0}", 620 + k))
    src = F.filter_(F.binop("EqualTo", a("r_reason_sk"), F.lit(1, "long")),
                    F.scan("reason", [a("r_reason_sk")]))
    got = _execute_both(sess, F.project(exprs, src))
    exp = O.oracle_q88(data)
    row = [got[k][0] for k in got]
    assert row == exp, (row, exp)
    assert sum(exp) > 0, "q88 slice matched no rows"


def test_spark_q90(sess, data, strategy):
    """AM/PM web-sales count ratio: two scalar subqueries + CaseWhen
    zero guard."""
    wp = F.project(
        [a("wp_web_page_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("wp_char_count"), i32(2000)),
                 F.binop("LessThanOrEqual", a("wp_char_count"), i32(6000))),
            F.scan("web_page", [a("wp_web_page_sk"), a("wp_char_count")]),
        ),
    )

    def half_count(lo, hi, rid):
        td = F.project(
            [a("t_time_sk")],
            F.filter_(
                and_(F.binop("GreaterThanOrEqual", a("t_hour"), i32(lo)),
                     F.binop("LessThanOrEqual", a("t_hour"), i32(hi))),
                F.scan("time_dim", [a("t_time_sk"), a("t_hour")]),
            ),
        )
        ws = F.scan("web_sales", [a("ws_sold_time_sk"), a("ws_web_page_sk")])
        j = join(strategy, td, ws, [a("t_time_sk")], [a("ws_sold_time_sk")])
        j = join(strategy, wp, j, [a("wp_web_page_sk")], [a("ws_web_page_sk")])
        return _scalar_subquery(two_stage([], [(F.count(), rid)], j), rid)

    am = half_count(8, 9, 651)
    pm = half_count(19, 20, 652)
    amf = F.cast(am, "double")
    pmf = F.cast(pm, "double")
    den = F.T(F.X + "CaseWhen",
              [F.binop("GreaterThan", pmf, F.lit(0.0, "double")), pmf,
               F.lit(1.0, "double")])
    one_row = two_stage([], [(F.count(), 653)],
                        F.scan("web_page", [a("wp_web_page_sk")]))
    plan = F.project(
        [F.alias(amf, "am_count", 660),
         F.alias(pmf, "pm_count", 661),
         F.alias(F.binop("Divide", amf, den), "am_pm_ratio", 662)],
        one_row,
    )
    got = _execute_both(sess, plan)
    am_e, pm_e, ratio_e = O.oracle_q90(data)
    assert got["am_count"] == [float(am_e)]
    assert got["pm_count"] == [float(pm_e)]
    assert abs(got["am_pm_ratio"][0] - ratio_e) < 1e-12


def test_spark_q61(ticket_sess, ticket_data, strategy):
    """Promotional vs total revenue: two 4/5-join scalar-subquery
    aggregates (LEFT SEMI address filter inside) and their ratio."""
    def revenue(with_promo, rid):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(1998)),
                           F.binop("EqualTo", a("d_moy"), i32(11))),
                      F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                          a("d_moy")])),
        )
        st_p = F.scan("store", [a("s_store_sk")])
        it = F.project(
            [a("i_item_sk")],
            F.filter_(F.binop("EqualTo", a("i_category"), s("Jewelry")),
                      F.scan("item", [a("i_item_sk"), a("i_category")])),
        )
        ca = F.project(
            [a("ca_address_sk")],
            F.filter_(F.binop("EqualTo", a("ca_gmt_offset"),
                              F.lit("-5", "decimal(5,2)")),
                      F.scan("customer_address",
                             [a("ca_address_sk"), a("ca_gmt_offset")])),
        )
        cust = F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk")])
        cust = join(strategy, ca, cust, [a("ca_address_sk")],
                    [a("c_current_addr_sk")], jt="LeftSemi",
                    build_side="right")
        sl = F.scan("store_sales",
                    [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_item_sk"),
                     a("ss_customer_sk"), a("ss_promo_sk"),
                     a("ss_ext_sales_price")])
        j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
        j = join(strategy, st_p, j, [a("s_store_sk")], [a("ss_store_sk")])
        j = join(strategy, it, j, [a("i_item_sk")], [a("ss_item_sk")])
        j = join(strategy, cust, j, [a("c_customer_sk")], [a("ss_customer_sk")])
        if with_promo:
            pr = F.project(
                [a("p_promo_sk")],
                F.filter_(or_(F.binop("EqualTo", a("p_channel_email"), s("Y")),
                              F.binop("EqualTo", a("p_channel_event"), s("Y"))),
                          F.scan("promotion", [a("p_promo_sk"),
                                               a("p_channel_email"),
                                               a("p_channel_event")])),
            )
            j = join(strategy, pr, j, [a("p_promo_sk")], [a("ss_promo_sk")])
        return _scalar_subquery(
            two_stage([], [(F.sum_(a("ss_ext_sales_price")), rid)], j), rid)

    promo = revenue(True, 671)
    total = revenue(False, 672)
    ratio = F.binop(
        "Divide",
        F.binop("Multiply", F.cast(promo, "double"), F.lit(100.0, "double")),
        F.cast(total, "double"))
    src = F.filter_(F.binop("EqualTo", a("r_reason_sk"), F.lit(1, "long")),
                    F.scan("reason", [a("r_reason_sk")]))
    plan = F.project(
        [F.alias(promo, "promotions", 680),
         F.alias(total, "total", 681),
         F.alias(ratio, "promo_pct", 682)],
        src,
    )
    got = _execute_both(ticket_sess, plan)
    promo_e, total_e = O.oracle_q61(ticket_data)
    assert total_e > 0, "q61 slice matched no rows"
    assert got["promotions"] == [promo_e]
    assert got["total"] == [total_e]
    exp_pct = (promo_e / 100.0) * 100.0 / (total_e / 100.0)
    assert abs(got["promo_pct"][0] - exp_pct) < 1e-9


# ----------------------- q41 manufact EXISTS rewritten as semi join

def test_spark_q41(sess, data, strategy):
    combo = or_(
        and_(in_(a("i_color"), "powder", "navy"),
             in_(a("i_units"), "Each", "Dozen")),
        and_(in_(a("i_color"), "peach", "saddle"),
             in_(a("i_units"), "Case", "Pallet")),
    )
    qual = F.project(
        [F.alias(a("i_manufact"), "qual_manufact", 690)],
        F.filter_(combo, F.scan("item", [a("i_manufact"), a("i_color"),
                                         a("i_units")])),
    )
    qm = ar("qual_manufact", 690, "string")
    manufacts = distinct([qm], qual)
    i1 = F.project(
        [a("i_manufact"), a("i_item_id")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("i_manufact_id"), i32(50)),
                 F.binop("LessThanOrEqual", a("i_manufact_id"), i32(120))),
            F.scan("item", [a("i_manufact"), a("i_item_id"),
                            a("i_manufact_id")]),
        ),
    )
    j = join(strategy, manufacts, i1, [qm], [a("i_manufact")], jt="LeftSemi",
             build_side="right")
    dis = distinct([a("i_item_id")], F.project([a("i_item_id")], j))
    plan = F.take_ordered(
        100, [F.sort_order(a("i_item_id"))],
        [F.alias(a("i_item_id"), "i_item_id", 695)], dis)
    got = _execute_both(sess, plan)
    exp = O.oracle_q41(data)
    assert exp, "q41 oracle empty"
    assert got["i_item_id"] == exp[:100]


# -------------------- q45 zip-list OR hot-item-subquery web revenue

def test_spark_q45(sess, data, strategy):
    """The item IN-subquery is evaluated driver-side into literals
    (the engine's q45 does the same via _collect_column)."""
    import numpy as np

    hot_sks = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)
    ids, lens = data["item"]["i_item_id"]
    sks = data["item"]["i_item_sk"][0]
    hot_ids = sorted({
        bytes(ids[i][:lens[i]]).decode()
        for i in range(sks.shape[0]) if int(sks[i]) in hot_sks})
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(2000)),
                       F.binop("EqualTo", a("d_qoy"), i32(2))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_qoy")])),
    )
    ws = F.scan("web_sales", [a("ws_sold_date_sk"), a("ws_item_sk"),
                              a("ws_bill_customer_sk"), a("ws_sales_price")])
    j = join(strategy, dt, ws, [a("d_date_sk")], [a("ws_sold_date_sk")])
    cu = F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk")])
    j = join(strategy, cu, j, [a("c_customer_sk")], [a("ws_bill_customer_sk")])
    ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_city"),
                                     a("ca_zip")])
    j = join(strategy, ca, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("ws_item_sk")])
    zips = ("35000", "35137", "60031", "60062", "60093")
    zip5 = F.T(F.X + "Substring", [a("ca_zip"), i32(1), i32(5)])
    pred = in_(zip5, *zips)
    if hot_ids:
        pred = or_(pred, in_(a("i_item_id"), *hot_ids))
    f = F.filter_(pred, j)
    agg = two_stage([a("ca_zip"), a("ca_city")],
                    [(F.sum_(a("ws_sales_price")), 501)], f)
    plan = F.take_ordered(
        100, [F.sort_order(a("ca_zip")), F.sort_order(a("ca_city"))],
        [F.alias(a("ca_zip"), "ca_zip", 510),
         F.alias(a("ca_city"), "ca_city", 511),
         F.alias(ar("sum_sales", 501, "decimal(17,2)"), "sum_sales", 512)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q45(data)
    assert exp, "q45 oracle empty"
    n = len(got["ca_zip"])
    assert n == min(len(exp), 100)
    rows = {(got["ca_zip"][i], got["ca_city"][i]): got["sum_sales"][i]
            for i in range(n)}
    assert rows == exp if len(exp) <= 100 else all(
        exp.get(k) == v for k, v in rows.items())


# -------------- q76 missing-dimension-key channel union (sentinel FKs)

def test_spark_q76(sess, data, strategy):
    dt = F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_qoy")])
    it = F.scan("item", [a("i_item_sk"), a("i_category")])

    def channel(fact, date_c, item_c, null_c, price_c, name):
        f = F.filter_(F.binop("EqualTo", a(null_c), F.lit(-1, "long")),
                      F.scan(fact, [a(date_c), a(item_c), a(null_c),
                                    a(price_c)]))
        j = join(strategy, dt, f, [a("d_date_sk")], [a(date_c)])
        j = join(strategy, it, j, [a("i_item_sk")], [a(item_c)])
        return F.project(
            [F.alias(F.lit(name, "string"), "channel", 740),
             F.alias(F.lit(null_c, "string"), "col_name", 741),
             a("d_year"), a("d_qoy"), a("i_category"),
             F.alias(a(price_c), "ext_sales_price", 742)],
            j,
        )

    u = F.union([
        channel("store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_customer_sk", "ss_ext_sales_price", "store"),
        channel("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_promo_sk", "ws_ext_sales_price", "web"),
        channel("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                "cs_bill_customer_sk", "cs_ext_sales_price", "catalog"),
    ])
    groups = [ar("channel", 740, "string"), ar("col_name", 741, "string"),
              a("d_year"), a("d_qoy"), a("i_category")]
    agg = two_stage(
        groups,
        [(F.count(), 501), (F.sum_(ar("ext_sales_price", 742,
                                      "decimal(7,2)")), 502)],
        u,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(g) for g in groups],
        [F.alias(ar("channel", 740, "string"), "channel", 750),
         F.alias(ar("col_name", 741, "string"), "col_name", 751),
         F.alias(a("d_year"), "d_year", 752),
         F.alias(a("d_qoy"), "d_qoy", 753),
         F.alias(a("i_category"), "i_category", 754),
         F.alias(ar("sales_cnt", 501, "long"), "sales_cnt", 755),
         F.alias(ar("sales_amt", 502, "decimal(17,2)"), "sales_amt", 756)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q76(data)
    assert exp, "q76 oracle empty"
    n = len(got["channel"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["channel"][i], got["col_name"][i], got["d_year"][i],
               got["d_qoy"][i], got["i_category"][i])
        assert key in exp, key
        assert (got["sales_cnt"][i], got["sales_amt"][i]) == exp[key], key


# --------------- q33/q56/q60 three-channel union by filtered item set

def _channel_by_item_plan(st, fact, date_c, item_c, addr_c, price_c, *,
                          group_col, gdtype, item_filter, year, moy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(year)),
                       F.binop("EqualTo", a("d_moy"), i32(moy))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")])),
    )
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(F.binop("EqualTo", a("ca_gmt_offset"),
                          F.lit("-5", "decimal(5,2)")),
                  F.scan("customer_address",
                         [a("ca_address_sk"), a("ca_gmt_offset")])),
    )
    ids = distinct(
        [ar("id_set", 760, gdtype)],
        F.project([F.alias(a(group_col), "id_set", 760)],
                  F.filter_(item_filter,
                            F.scan("item", [a(group_col), a("i_category"),
                                            a("i_color")]))),
    )
    it = F.scan("item", [a("i_item_sk"), a(group_col)])
    it_f = join(st, ids, it, [ar("id_set", 760, gdtype)], [a(group_col)],
                jt="LeftSemi", build_side="right")
    sl = F.scan(fact, [a(date_c), a(item_c), a(addr_c), a(price_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    j = join(st, ca, j, [a("ca_address_sk")], [a(addr_c)])
    j = join(st, it_f, j, [a("i_item_sk")], [a(item_c)])
    return F.project(
        [a(group_col), F.alias(a(price_c), "sales_price", 761)], j)


def _three_channel_union_plan(st, *, group_col, gdtype, item_filter, year,
                              moy):
    arms = [
        _channel_by_item_plan(st, s_, d_, i_, ad, p_, group_col=group_col,
                              gdtype=gdtype, item_filter=item_filter,
                              year=year, moy=moy)
        for s_, d_, i_, ad, p_ in [
            ("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_bill_addr_sk", "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk",
             "ws_ext_sales_price"),
        ]
    ]
    u = F.union(arms)
    agg = two_stage(
        [a(group_col)],
        [(F.sum_(ar("sales_price", 761, "decimal(7,2)")), 501)], u)
    total = ar("total_sales", 501, "decimal(17,2)")
    return F.take_ordered(
        100, [F.sort_order(total), F.sort_order(a(group_col))],
        [F.alias(a(group_col), group_col, 770),
         F.alias(total, "total_sales", 771)],
        agg,
    )


def test_spark_q33(sess, data, strategy):
    plan = _three_channel_union_plan(
        strategy, group_col="i_manufact_id", gdtype="integer",
        item_filter=F.binop("EqualTo", a("i_category"), s("Electronics")),
        year=1998, moy=5)
    got = _execute_both(sess, plan)
    exp = O.oracle_q33(data)
    rows = dict(zip(got["i_manufact_id"], got["total_sales"]))
    assert rows, "q33 returned no rows"
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)


def test_spark_q56(sess, data, strategy):
    plan = _three_channel_union_plan(
        strategy, group_col="i_item_id", gdtype="string",
        item_filter=in_(a("i_color"), "slate", "blanched", "burnished"),
        year=2000, moy=2)
    got = _execute_both(sess, plan)
    exp = O.oracle_q56(data)
    rows = dict(zip(got["i_item_id"], got["total_sales"]))
    assert rows, "q56 returned no rows"
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)


def test_spark_q60(sess, data, strategy):
    plan = _three_channel_union_plan(
        strategy, group_col="i_item_id", gdtype="string",
        item_filter=F.binop("EqualTo", a("i_category"), s("Music")),
        year=1999, moy=9)
    got = _execute_both(sess, plan)
    exp = O.oracle_q60(data)
    rows = dict(zip(got["i_item_id"], got["total_sales"]))
    assert rows, "q60 returned no rows"
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)


# ------------------- q13/q48 OR-of-bands star join (ticket slice)

def _q13_source_plan(st):
    from blaze_tpu.tpcds.queries import Q13_BANDS, Q13_STATE_BANDS

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    st_p = F.scan("store", [a("s_store_sk")])
    cd_p = F.scan("customer_demographics",
                  [a("cd_demo_sk"), a("cd_marital_status"),
                   a("cd_education_status")])
    hd_p = F.scan("household_demographics",
                  [a("hd_demo_sk"), a("hd_dep_count")])
    ca_p = F.scan("customer_address", [a("ca_address_sk"), a("ca_state")])
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_cdemo_sk"),
                 a("ss_hdemo_sk"), a("ss_addr_sk"), a("ss_quantity"),
                 a("ss_sales_price"), a("ss_ext_sales_price"),
                 a("ss_ext_discount_amt"), a("ss_net_profit")])
    j = join(st, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(st, st_p, j, [a("s_store_sk")], [a("ss_store_sk")])
    j = join(st, cd_p, j, [a("cd_demo_sk")], [a("ss_cdemo_sk")])
    j = join(st, hd_p, j, [a("hd_demo_sk")], [a("ss_hdemo_sk")])
    j = join(st, ca_p, j, [a("ca_address_sk")], [a("ss_addr_sk")])
    dec = "decimal(7,2)"
    demo = or_(*[
        and_(F.binop("EqualTo", a("cd_marital_status"), s(ms)),
             F.binop("EqualTo", a("cd_education_status"), s(ed)),
             F.binop("GreaterThanOrEqual", a("ss_sales_price"),
                     F.lit(str(lo), dec)),
             F.binop("LessThanOrEqual", a("ss_sales_price"),
                     F.lit(str(hi), dec)),
             F.binop("EqualTo", a("hd_dep_count"), i32(dep)))
        for ms, ed, lo, hi, dep in Q13_BANDS])
    geo = or_(*[
        and_(in_(a("ca_state"), *states),
             F.binop("GreaterThanOrEqual", a("ss_net_profit"),
                     F.lit(str(lo), dec)),
             F.binop("LessThanOrEqual", a("ss_net_profit"),
                     F.lit(str(hi), dec)))
        for states, lo, hi in Q13_STATE_BANDS])
    return F.filter_(and_(demo, geo), j)


def test_spark_q13(ticket_sess, ticket_data, strategy):
    agg = two_stage(
        [],
        [(F.avg(a("ss_quantity")), 501),
         (F.avg(a("ss_ext_sales_price")), 502),
         (F.avg(a("ss_ext_discount_amt")), 503),
         (F.count(), 504)],
        _q13_source_plan(strategy),
    )
    plan = F.project(
        [F.alias(ar("avg_qty", 501, "double"), "avg_qty", 510),
         F.alias(ar("avg_ext_sales", 502, "decimal(11,6)"),
                 "avg_ext_sales", 511),
         F.alias(ar("avg_ext_disc", 503, "decimal(11,6)"),
                 "avg_ext_disc", 512),
         F.alias(ar("cnt", 504, "long"), "cnt", 513)],
        agg,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q13(ticket_data)
    assert exp is not None, "q13 bands matched no rows"
    assert got["cnt"] == [exp["cnt"]]
    assert abs(got["avg_qty"][0] - exp["avg_qty"]) < 1e-9
    assert got["avg_ext_sales"] == [exp["avg_ext_sales"]]
    assert got["avg_ext_disc"] == [exp["avg_ext_disc"]]


def test_spark_q48(ticket_sess, ticket_data, strategy):
    agg = two_stage([], [(F.sum_(a("ss_quantity")), 501)],
                    _q13_source_plan(strategy))
    plan = F.project(
        [F.alias(ar("qty_sum", 501, "long"), "qty_sum", 510)], agg)
    got = _execute_both(ticket_sess, plan)
    assert got["qty_sum"] == [O.oracle_q48(ticket_data)]


# ------------- q53/q63 manufacturer window-average ratio reports

def _manufact_window_plan(st, group_col, avg_name, order_cols):
    it = F.project(
        [a("i_item_sk"), a("i_manufact_id")],
        F.filter_(
            or_(and_(in_(a("i_category"), "Books", "Children", "Electronics"),
                     in_(a("i_class"), "personal", "self-help", "reference")),
                and_(in_(a("i_category"), "Women", "Music", "Men"),
                     in_(a("i_class"), "accessories", "classical",
                         "fragrances"))),
            F.scan("item", [a("i_item_sk"), a("i_manufact_id"), a("i_class"),
                            a("i_category")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk"), a(group_col)],
        F.filter_(F.T(F.X + "In", [a("d_year"), i32(1999), i32(2000)]),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                      a(group_col)])),
    )
    st_p = F.scan("store", [a("s_store_sk")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"),
                                a("ss_store_sk"), a("ss_sales_price")])
    j = join(st, it, sl, [a("i_item_sk")], [a("ss_item_sk")])
    j = join(st, dt, j, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(st, st_p, j, [a("s_store_sk")], [a("ss_store_sk")])
    agg = two_stage([a("i_manufact_id"), a(group_col)],
                    [(F.sum_(a("ss_sales_price")), 501)], j)
    sum_sales = ar("sum_sales", 501, "decimal(17,2)")
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort([F.sort_order(a("i_manufact_id"))], single)
    w = F.window(
        [F.window_expr(
            F.window_agg(F.avg(sum_sales)),
            F.window_spec([a("i_manufact_id")], [],
                          F.window_frame("up", "uf", row=True)),
            avg_name, 502)],
        [a("i_manufact_id")],
        [],
        pre,
    )
    avg_a = ar(avg_name, 502, "decimal(21,6)")
    sum_f = F.cast(sum_sales, "double")
    avg_f = F.cast(avg_a, "double")
    ratio = F.T(
        F.X + "CaseWhen",
        [F.binop("GreaterThan", avg_f, F.lit(0.0, "double")),
         F.binop("Divide", F.un("Abs", F.binop("Subtract", sum_f, avg_f)),
                 avg_f)],
    )
    filt = F.filter_(F.binop("GreaterThan", ratio, F.lit(0.1, "double")), w)
    attr_of = {"i_manufact_id": a("i_manufact_id"), group_col: a(group_col),
               "sum_sales": sum_sales, avg_name: avg_a}
    return F.take_ordered(
        100,
        [F.sort_order(attr_of[c]) for c in order_cols],
        [F.alias(a("i_manufact_id"), "i_manufact_id", 510),
         F.alias(a(group_col), group_col, 511),
         F.alias(sum_sales, "sum_sales", 512),
         F.alias(avg_a, avg_name, 513)],
        filt,
    )


def test_spark_q53(sess, data, strategy):
    from test_tpcds import _check_manufact_window

    order = ["avg_quarterly_sales", "sum_sales", "i_manufact_id"]
    plan = _manufact_window_plan(strategy, "d_qoy", "avg_quarterly_sales",
                                 order)
    got = _execute_both(sess, plan)
    _check_manufact_window(got, O.oracle_q53(data), "d_qoy",
                           "avg_quarterly_sales", order)


def test_spark_q63(sess, data, strategy):
    from test_tpcds import _check_manufact_window

    order = ["i_manufact_id", "avg_monthly_sales", "sum_sales"]
    plan = _manufact_window_plan(strategy, "d_moy", "avg_monthly_sales",
                                 order)
    got = _execute_both(sess, plan)
    _check_manufact_window(got, O.oracle_q63(data), "d_moy",
                           "avg_monthly_sales", order)


# --------------- q21/q40 inventory/sales before-after pivot reports

def test_spark_q21(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk"), a("d_date")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-02-10", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2000-04-10", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    dec = "decimal(7,2)"
    it = F.project(
        [a("i_item_sk"), a("i_item_id")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("i_current_price"),
                         F.lit("20", dec)),
                 F.binop("LessThanOrEqual", a("i_current_price"),
                         F.lit("50", dec))),
            F.scan("item", [a("i_item_sk"), a("i_item_id"),
                            a("i_current_price")]),
        ),
    )
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_warehouse_name")])
    inv = F.scan("inventory", [a("inv_date_sk"), a("inv_item_sk"),
                               a("inv_warehouse_sk"),
                               a("inv_quantity_on_hand")])
    j = join(strategy, dt, inv, [a("d_date_sk")], [a("inv_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("inv_item_sk")])
    j = join(strategy, wh, j, [a("w_warehouse_sk")], [a("inv_warehouse_sk")])
    pivot = F.lit("2000-03-11", "date")
    qoh = F.cast(a("inv_quantity_on_hand"), "long")
    zero = F.lit(0, "long")
    before = F.T(F.X + "CaseWhen",
                 [F.binop("LessThan", a("d_date"), pivot), qoh, zero])
    after = F.T(F.X + "CaseWhen",
                [F.binop("GreaterThanOrEqual", a("d_date"), pivot), qoh, zero])
    proj = F.project(
        [a("w_warehouse_name"), a("i_item_id"),
         F.alias(before, "b", 520), F.alias(after, "a", 521)], j)
    agg = two_stage(
        [a("w_warehouse_name"), a("i_item_id")],
        [(F.sum_(ar("b", 520, "long")), 501),
         (F.sum_(ar("a", 521, "long")), 502)],
        proj,
    )
    bf = F.cast(ar("inv_before", 501, "long"), "double")
    af = F.cast(ar("inv_after", 502, "long"), "double")
    ratio = F.binop("Divide", af, bf)
    f = F.filter_(
        and_(F.binop("GreaterThan", bf, F.lit(0.0, "double")),
             F.binop("GreaterThanOrEqual", ratio,
                     F.lit(2.0 / 3.0, "double")),
             F.binop("LessThanOrEqual", ratio, F.lit(1.5, "double"))),
        agg,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a("w_warehouse_name")), F.sort_order(a("i_item_id"))],
        [F.alias(a("w_warehouse_name"), "w_warehouse_name", 530),
         F.alias(a("i_item_id"), "i_item_id", 531),
         F.alias(ar("inv_before", 501, "long"), "inv_before", 532),
         F.alias(ar("inv_after", 502, "long"), "inv_after", 533)],
        f,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q21(data)
    assert exp, "q21 oracle empty"
    n = len(got["w_warehouse_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_warehouse_name"][i], got["i_item_id"][i])
        assert key in exp, key
        assert (got["inv_before"][i], got["inv_after"][i]) == exp[key], key
    keys = [(got["w_warehouse_name"][i], got["i_item_id"][i])
            for i in range(n)]
    assert keys == sorted(keys)


def test_spark_q40(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk"), a("d_date")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-02-10", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2000-04-10", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    dec = "decimal(7,2)"
    it = F.project(
        [a("i_item_sk"), a("i_item_id")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("i_current_price"),
                         F.lit("20", dec)),
                 F.binop("LessThanOrEqual", a("i_current_price"),
                         F.lit("50", dec))),
            F.scan("item", [a("i_item_sk"), a("i_item_id"),
                            a("i_current_price")]),
        ),
    )
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_state")])
    cs = F.scan("catalog_sales",
                [a("cs_sold_date_sk"), a("cs_item_sk"), a("cs_order_number"),
                 a("cs_warehouse_sk"), a("cs_sales_price")])
    j = join(strategy, dt, cs, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("cs_item_sk")])
    j = join(strategy, wh, j, [a("w_warehouse_sk")], [a("cs_warehouse_sk")])
    cr = F.scan("catalog_returns", [a("cr_item_sk"), a("cr_order_number"),
                                    a("cr_refunded_cash")])
    j = join(strategy, cr, j, [a("cr_item_sk"), a("cr_order_number")],
             [a("cs_item_sk"), a("cs_order_number")], jt="LeftOuter",
             build_side="right")
    dz = F.lit("0", dec)
    net_sales = F.binop("Add", a("cs_sales_price"), dz)  # decimal(8,2)
    refund = F.T(
        F.X + "CaseWhen",
        [F.un("IsNotNull", a("cr_refunded_cash")),
         F.binop("Add", a("cr_refunded_cash"), dz),
         F.binop("Add", dz, dz)],
    )
    net = F.binop("Subtract", net_sales, refund)
    pivot = F.lit("2000-03-11", "date")
    before = F.T(F.X + "CaseWhen",
                 [F.binop("LessThan", a("d_date"), pivot), net])
    after = F.T(F.X + "CaseWhen",
                [F.binop("GreaterThanOrEqual", a("d_date"), pivot), net])
    proj = F.project(
        [a("w_state"), a("i_item_id"),
         F.alias(before, "b", 520), F.alias(after, "a", 521)], j)
    agg = two_stage(
        [a("w_state"), a("i_item_id")],
        [(F.sum_(ar("b", 520, "decimal(9,2)")), 501),
         (F.sum_(ar("a", 521, "decimal(9,2)")), 502)],
        proj,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a("w_state")), F.sort_order(a("i_item_id"))],
        [F.alias(a("w_state"), "w_state", 530),
         F.alias(a("i_item_id"), "i_item_id", 531),
         F.alias(ar("sales_before", 501, "decimal(19,2)"), "sales_before", 532),
         F.alias(ar("sales_after", 502, "decimal(19,2)"), "sales_after", 533)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q40(data)
    assert exp, "q40 oracle empty"
    n = len(got["w_state"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_state"][i], got["i_item_id"][i])
        assert key in exp, key
        assert (got["sales_before"][i], got["sales_after"][i]) == exp[key], key


# ---------------- q28 six price-band buckets (scalar-subquery trios)

def test_spark_q28(sess, data, strategy):
    """avg/count/count-distinct per band, each a driver-resolved
    scalar subquery; the DISTINCT count is a grouping-only agg under a
    count (the shape Spark plans instead of a distinct aggregate)."""
    if strategy == "smj":
        pytest.skip("no joins in q28: the strategy axis is vacuous")
    bands = [
        ("B1", 0, 5, 0, 10, 0, 50),
        ("B2", 6, 10, 10, 20, 50, 100),
        ("B3", 11, 15, 20, 30, 100, 150),
        ("B4", 16, 20, 30, 40, 150, 200),
        ("B5", 21, 25, 40, 50, 200, 250),
        ("B6", 26, 30, 50, 60, 250, 300),
    ]
    dec = "decimal(7,2)"
    exprs = []
    rid = 801
    for bi, (name, q_lo, q_hi, c_lo, c_hi, w_lo, w_hi) in enumerate(bands):
        pred = and_(
            F.binop("GreaterThanOrEqual", a("ss_quantity"), i32(q_lo)),
            F.binop("LessThanOrEqual", a("ss_quantity"), i32(q_hi)),
            or_(
                and_(F.binop("GreaterThanOrEqual", a("ss_list_price"),
                             F.lit(str(c_lo), dec)),
                     F.binop("LessThanOrEqual", a("ss_list_price"),
                             F.lit(str(c_lo + 10), dec))),
                and_(F.binop("GreaterThanOrEqual", a("ss_coupon_amt"),
                             F.lit(str(w_lo), dec)),
                     F.binop("LessThanOrEqual", a("ss_coupon_amt"),
                             F.lit(str(w_lo + 1000), dec))),
                and_(F.binop("GreaterThanOrEqual", a("ss_wholesale_cost"),
                             F.lit(str(c_hi), dec)),
                     F.binop("LessThanOrEqual", a("ss_wholesale_cost"),
                             F.lit(str(c_hi + 20), dec))),
            ),
        )
        lp = F.project(
            [a("ss_list_price")],
            F.filter_(pred, F.scan(
                "store_sales",
                [a("ss_quantity"), a("ss_list_price"), a("ss_coupon_amt"),
                 a("ss_wholesale_cost")])),
        )
        avg_sq = _scalar_subquery(
            two_stage([], [(F.avg(a("ss_list_price")), rid)], lp), rid)
        cnt_sq = _scalar_subquery(
            two_stage([], [(F.count(), rid + 1)], lp), rid + 1)
        dis = distinct([a("ss_list_price")], lp)
        cntd_sq = _scalar_subquery(
            two_stage([], [(F.count(), rid + 2)], dis), rid + 2)
        exprs += [
            F.alias(avg_sq, f"{name}_lp", 850 + bi * 3),
            F.alias(cnt_sq, f"{name}_cnt", 851 + bi * 3),
            F.alias(cntd_sq, f"{name}_cntd", 852 + bi * 3),
        ]
        rid += 3
    src = F.filter_(F.binop("EqualTo", a("r_reason_sk"), F.lit(1, "long")),
                    F.scan("reason", [a("r_reason_sk")]))
    got = _execute_both(sess, F.project(exprs, src))
    exp = O.oracle_q28(data)
    for name, (avg_u, cnt, cntd) in exp.items():
        assert got[f"{name}_lp"] == [avg_u], name
        assert got[f"{name}_cnt"] == [cnt], name
        assert got[f"{name}_cntd"] == [cntd], name


# ------------- q1/q30/q81 returns-above-location-average family

def _returns_above_avg_plan(st, *, rtab, r_cust, r_amt, r_date, r_loc,
                            loc_tab=None, loc_sk=None, loc_filter_col=None,
                            loc_filter_val=None, names=False):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    rt = F.scan(rtab, [a(r_date), a(r_cust), a(r_loc), a(r_amt)])
    j = join(st, dt, rt, [a("d_date_sk")], [a(r_date)])
    if loc_tab is not None:
        loc = F.project(
            [a(loc_sk)],
            F.filter_(F.binop("EqualTo", a(loc_filter_col),
                              s(loc_filter_val)),
                      F.scan(loc_tab, [a(loc_sk), a(loc_filter_col)])),
        )
        j = join(st, loc, j, [a(loc_sk)], [a(r_loc)])
    per_cust = two_stage(
        [a(r_cust), a(r_loc)], [(F.sum_(a(r_amt)), 501)],
        F.project([a(r_cust), a(r_loc), a(r_amt)], j))
    total = ar("ctr_total_return", 501, "decimal(17,2)")
    loc_avg_src = F.project(
        [F.alias(a(r_loc), "avg_loc_sk", 520), total], per_cust)
    loc_avg = two_stage(
        [ar("avg_loc_sk", 520, "long")], [(F.avg(total), 502)], loc_avg_src)
    avg_r = ar("avg_return", 502, "decimal(21,6)")
    j2 = join(st, loc_avg, per_cust, [ar("avg_loc_sk", 520, "long")],
              [a(r_loc)])
    f = F.filter_(
        F.binop("GreaterThan", F.cast(total, "double"),
                F.binop("Multiply", F.lit(1.2, "double"),
                        F.cast(avg_r, "double"))),
        j2,
    )
    cu_cols = [a("c_customer_sk"), a("c_customer_id")] + (
        [a("c_first_name"), a("c_last_name")] if names else [])
    cu = F.scan("customer", cu_cols)
    j3 = join(st, cu, f, [a("c_customer_sk")], [a(r_cust)])
    if names:
        return F.take_ordered(
            100,
            [F.sort_order(a("c_customer_id")), F.sort_order(total)],
            [F.alias(a("c_customer_id"), "c_customer_id", 530),
             F.alias(a("c_first_name"), "c_first_name", 531),
             F.alias(a("c_last_name"), "c_last_name", 532),
             F.alias(total, "ctr_total_return", 533)],
            j3,
        )
    return F.take_ordered(
        100, [F.sort_order(a("c_customer_id"))],
        [F.alias(a("c_customer_id"), "c_customer_id", 530)], j3)


def test_spark_q1(sess, data, strategy):
    plan = _returns_above_avg_plan(
        strategy, rtab="store_returns", r_cust="sr_customer_sk",
        r_amt="sr_return_amt", r_date="sr_returned_date_sk",
        r_loc="sr_store_sk", loc_tab="store", loc_sk="s_store_sk",
        loc_filter_col="s_state", loc_filter_val="TN")
    got = _execute_both(sess, plan)
    exp = O.oracle_q1(data)
    assert exp, "q1 oracle empty"
    assert len(got["c_customer_id"]) == min(len(exp), 100)
    assert set(got["c_customer_id"]) == exp if len(exp) <= 100 else set(
        got["c_customer_id"]) <= exp
    assert got["c_customer_id"] == sorted(got["c_customer_id"])


def test_spark_q30(sess, data, strategy):
    from test_tpcds import _check_returns_family

    plan = _returns_above_avg_plan(
        strategy, rtab="web_returns", r_cust="wr_returning_customer_sk",
        r_amt="wr_return_amt", r_date="wr_returned_date_sk",
        r_loc="wr_web_page_sk", names=True)
    got = _execute_both(sess, plan)
    _check_returns_family(got, O.oracle_q30(data))


def test_spark_q81(sess, data, strategy):
    from test_tpcds import _check_returns_family

    plan = _returns_above_avg_plan(
        strategy, rtab="catalog_returns", r_cust="cr_returning_customer_sk",
        r_amt="cr_return_amount", r_date="cr_returned_date_sk",
        r_loc="cr_call_center_sk", names=True)
    got = _execute_both(sess, plan)
    _check_returns_family(got, O.oracle_q81(data))


# ------------------ q17 quantity-spread statistics over the chain

def test_spark_q17(sess, data, strategy):
    j = _srcandc_join_plan(strategy)
    qs = [("ss_quantity", "store"), ("sr_return_quantity", "returns"),
          ("cs_quantity", "catalog")]
    aggs = []
    rid = 501
    for src, nm in qs:
        e = F.cast(a(src), "long")
        aggs += [(F.count(e), rid), (F.avg(e), rid + 1),
                 (F.T(F.A + "StddevSamp", [e]), rid + 2)]
        rid += 3
    agg = two_stage(
        [a("i_item_id"), a("i_item_desc"), a("s_store_name")], aggs, j)
    outs = [a("i_item_id"), a("i_item_desc"), a("s_store_name")]
    oid = 530
    names = []
    rid = 501
    for _, nm in qs:
        cnt = ar(f"{nm}_qty_count", rid, "long")
        avg = ar(f"{nm}_qty_avg", rid + 1, "double")
        sd = ar(f"{nm}_qty_stdev", rid + 2, "double")
        cov = F.T(F.X + "CaseWhen",
                  [F.binop("GreaterThan", avg, F.lit(0.0, "double")),
                   F.binop("Divide", sd, avg)])
        outs += [F.alias(cnt, f"{nm}_qty_count", oid),
                 F.alias(avg, f"{nm}_qty_avg", oid + 1),
                 F.alias(sd, f"{nm}_qty_stdev", oid + 2),
                 F.alias(cov, f"{nm}_qty_cov", oid + 3)]
        names += [f"{nm}_qty_count", f"{nm}_qty_avg", f"{nm}_qty_stdev",
                  f"{nm}_qty_cov"]
        rid += 3
        oid += 4
    plan = F.take_ordered(
        100,
        [F.sort_order(a("i_item_id")), F.sort_order(a("i_item_desc")),
         F.sort_order(a("s_store_name"))],
        outs,
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q17(data)
    assert exp, "q17 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["i_item_desc"][i],
               got["s_store_name"][i])
        assert key in exp, key
        for k, nm in enumerate(("store", "returns", "catalog")):
            cnt, mean, sd, cov = exp[key][k]
            assert got[f"{nm}_qty_count"][i] == cnt, (key, nm)
            assert abs(got[f"{nm}_qty_avg"][i] - mean) < 1e-9, (key, nm)
            for gv, ev in ((got[f"{nm}_qty_stdev"][i], sd),
                           (got[f"{nm}_qty_cov"][i], cov)):
                if ev is None:
                    assert gv is None, (key, nm)
                else:
                    assert gv is not None and abs(gv - ev) < 1e-9, (key, nm)


# ---------------- q22 product-hierarchy inventory ROLLUP (5 levels)

def test_spark_q22(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    it = F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_brand"),
                         a("i_class"), a("i_category")])
    inv = F.scan("inventory", [a("inv_date_sk"), a("inv_item_sk"),
                               a("inv_quantity_on_hand")])
    j = join(strategy, dt, inv, [a("d_date_sk")], [a("inv_date_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("inv_item_sk")])
    dims = ["i_item_id", "i_brand", "i_class", "i_category"]
    null_s = F.lit(None, "string")
    exp_dims = [ar(d, 520 + k, "string") for k, d in enumerate(dims)]
    exp_gid = ar("g_id", 524, "integer")
    vals = [a("inv_quantity_on_hand")]
    rows = []
    for level in range(4, -1, -1):
        row = list(vals)
        for k, d in enumerate(dims):
            row.append(a(d) if k < level else null_s)
        row.append(F.lit(4 - level, "integer"))
        rows.append(row)
    expand = F.expand(rows, vals + exp_dims + [exp_gid], j)
    agg = two_stage(
        exp_dims + [exp_gid],
        [(F.avg(a("inv_quantity_on_hand")), 501)],
        expand,
    )
    qoh = ar("qoh", 501, "double")
    plan = F.take_ordered(
        100,
        [F.sort_order(qoh)] + [F.sort_order(d) for d in exp_dims],
        [F.alias(d, dims[k], 540 + k) for k, d in enumerate(exp_dims)]
        + [F.alias(exp_gid, "g_id", 544), F.alias(qoh, "qoh", 545)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q22(data)
    assert exp, "q22 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["i_brand"][i], got["i_class"][i],
               got["i_category"][i], got["g_id"][i])
        assert key in exp, key
        assert abs(got["qoh"][i] - exp[key]) < 1e-9, key
    assert got["qoh"] == sorted(got["qoh"])


# ------------- q94/q95/q16 multi-warehouse ship reports

def _multi_wh_orders_plan(st, fact, order_c, wh_c, base_id):
    pairs = distinct([a(order_c), a(wh_c)],
                     F.project([a(order_c), a(wh_c)], F.scan(fact, [a(order_c), a(wh_c)])))
    per_order = two_stage(
        [a(order_c)], [(F.count(), base_id)],
        F.project([a(order_c)], pairs))
    hot = F.filter_(
        F.binop("GreaterThan", ar("wh_cnt", base_id, "long"),
                F.lit(1, "long")),
        per_order,
    )
    return F.project([F.alias(a(order_c), "hot_order", base_id + 1)], hot)


def _ship_report_plan(st, rows, order_c, ship_c, profit_c):
    per_order = two_stage(
        [a(order_c)],
        [(F.sum_(a(ship_c)), 551), (F.sum_(a(profit_c)), 552)],
        rows,
    )
    agg = two_stage(
        [],
        [(F.count(), 553),
         (F.sum_(ar("s1", 551, "decimal(17,2)")), 554),
         (F.sum_(ar("p1", 552, "decimal(17,2)")), 555)],
        per_order,
    )
    return F.project(
        [F.alias(ar("order_count", 553, "long"), "order_count", 560),
         F.alias(ar("total_shipping_cost", 554, "decimal(27,2)"),
                 "total_shipping_cost", 561),
         F.alias(ar("total_net_profit", 555, "decimal(27,2)"),
                 "total_net_profit", 562)],
        agg,
    )


def _q94_shape_plan(st, returns_jt):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("1999-02-01", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("1999-12-31", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(F.binop("EqualTo", a("ca_state"), s("TN")),
                  F.scan("customer_address", [a("ca_address_sk"),
                                              a("ca_state")])),
    )
    site = F.project(
        [a("web_site_sk")],
        F.filter_(F.binop("EqualTo", a("web_company_name"), s("pri")),
                  F.scan("web_site", [a("web_site_sk"),
                                      a("web_company_name")])),
    )
    ws1 = F.scan("web_sales",
                 [a("ws_ship_date_sk"), a("ws_ship_addr_sk"),
                  a("ws_web_site_sk"), a("ws_order_number"),
                  a("ws_ext_ship_cost"), a("ws_net_profit")])
    j = join(st, dt, ws1, [a("d_date_sk")], [a("ws_ship_date_sk")])
    j = join(st, ca, j, [a("ca_address_sk")], [a("ws_ship_addr_sk")])
    j = join(st, site, j, [a("web_site_sk")], [a("ws_web_site_sk")])
    hot = _multi_wh_orders_plan(st, "web_sales", "ws_order_number",
                                "ws_warehouse_sk", 540)
    j = join(st, hot, j, [ar("hot_order", 541, "long")],
             [a("ws_order_number")], jt="LeftSemi", build_side="right")
    wr = F.scan("web_returns", [a("wr_order_number")])
    j = join(st, wr, j, [a("wr_order_number")], [a("ws_order_number")],
             jt=returns_jt, build_side="right")
    return _ship_report_plan(st, j, "ws_order_number", "ws_ext_ship_cost",
                             "ws_net_profit")


def test_spark_q94(sess, data, strategy):
    from test_tpcds import _check_ship_report

    got = _execute_both(sess, _q94_shape_plan(strategy, "LeftAnti"))
    _check_ship_report(got, O.oracle_q94(data))


def test_spark_q95(sess, data, strategy):
    from test_tpcds import _check_ship_report

    got = _execute_both(sess, _q94_shape_plan(strategy, "LeftSemi"))
    _check_ship_report(got, O.oracle_q95(data))


def test_spark_q16(sess, data, strategy):
    from test_tpcds import _check_ship_report

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2002-02-01", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2002-12-31", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(F.binop("EqualTo", a("ca_state"), s("GA")),
                  F.scan("customer_address", [a("ca_address_sk"),
                                              a("ca_state")])),
    )
    cc = F.project(
        [a("cc_call_center_sk")],
        F.filter_(F.binop("EqualTo", a("cc_county"), s("Williamson County")),
                  F.scan("call_center", [a("cc_call_center_sk"),
                                         a("cc_county")])),
    )
    cs1 = F.scan("catalog_sales",
                 [a("cs_ship_date_sk"), a("cs_ship_addr_sk"),
                  a("cs_call_center_sk"), a("cs_order_number"),
                  a("cs_ext_ship_cost"), a("cs_net_profit")])
    j = join(strategy, dt, cs1, [a("d_date_sk")], [a("cs_ship_date_sk")])
    j = join(strategy, ca, j, [a("ca_address_sk")], [a("cs_ship_addr_sk")])
    j = join(strategy, cc, j, [a("cc_call_center_sk")],
             [a("cs_call_center_sk")])
    hot = _multi_wh_orders_plan(strategy, "catalog_sales", "cs_order_number",
                                "cs_warehouse_sk", 545)
    j = join(strategy, hot, j, [ar("hot_order", 546, "long")],
             [a("cs_order_number")], jt="LeftSemi", build_side="right")
    cr = F.scan("catalog_returns", [a("cr_order_number")])
    j = join(strategy, cr, j, [a("cr_order_number")], [a("cs_order_number")],
             jt="LeftAnti", build_side="right")
    got = _execute_both(
        sess, _ship_report_plan(strategy, j, "cs_order_number",
                                "cs_ext_ship_cost", "cs_net_profit"))
    _check_ship_report(got, O.oracle_q16(data))


# -------------------- q2/q59 weekly dow-pivot year-over-year ratios

_DOW7 = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")


def _dow_pivot_plan(group_attrs, price_attr, rows, base_rid):
    """CASE-pivot 7 dow sums grouped by group_attrs (q43's shape)."""
    pivots = [
        F.alias(F.T(F.X + "CaseWhen",
                    [F.binop("EqualTo", a("d_dow"), i32(k)), price_attr]),
                f"{nm}_v", base_rid + k)
        for k, nm in enumerate(_DOW7)
    ]
    proj = F.project(list(group_attrs) + pivots, rows)
    return two_stage(
        list(group_attrs),
        [(F.sum_(ar(f"{nm}_v", base_rid + k, "decimal(7,2)")),
          base_rid + 10 + k) for k, nm in enumerate(_DOW7)],
        proj,
    )


def _week_set_plan(year, out_name, out_id):
    y = F.filter_(F.binop("EqualTo", a("d_year"), i32(year)),
                  F.scan("date_dim", [a("d_week_seq"), a("d_year")]))
    return distinct(
        [ar(out_name, out_id, "integer")],
        F.project([F.alias(a("d_week_seq"), out_name, out_id)], y))


def _dow_ratios(base_rid, rid2_base, out_base):
    outs = []
    for k, nm in enumerate(_DOW7):
        num = F.cast(ar(f"{nm}1", base_rid + k, "decimal(17,2)"), "double")
        den = F.cast(ar(f"{nm}2", rid2_base + k, "decimal(17,2)"), "double")
        den = F.T(F.X + "CaseWhen",
                  [F.binop("GreaterThan", den, F.lit(0.0, "double")), den,
                   F.lit(1.0, "double")])
        outs.append(F.alias(F.binop("Divide", num, den), f"{nm}_ratio",
                            out_base + k))
    return outs


def test_spark_q2(sess, data, strategy):
    from test_tpcds import _check_weekly_ratios

    dt = F.scan("date_dim", [a("d_date_sk"), a("d_week_seq"), a("d_dow")])
    sold = ar("sold_date_sk", 901, "long")
    price = ar("sales_price", 902, "decimal(7,2)")
    branches = [
        F.project([F.alias(a(date_c), "sold_date_sk", 901),
                   F.alias(a(price_c), "sales_price", 902)],
                  F.scan(fact, [a(date_c), a(price_c)]))
        for fact, date_c, price_c in (
            ("web_sales", "ws_sold_date_sk", "ws_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price"),
        )
    ]
    u = F.union(branches)
    j = join(strategy, dt, u, [a("d_date_sk")], [sold])
    wk = _dow_pivot_plan([a("d_week_seq")], price, j, 910)
    wk1 = join(strategy, _week_set_plan(2001, "wk1", 930), wk,
               [ar("wk1", 930, "integer")], [a("d_week_seq")],
               jt="LeftSemi", build_side="right")
    wk1 = F.project(
        [a("d_week_seq")] + [
            F.alias(ar(f"{nm}_sales", 920 + k, "decimal(17,2)"),
                    f"{nm}1", 940 + k)
            for k, nm in enumerate(_DOW7)],
        wk1,
    )
    wk2 = join(strategy, _week_set_plan(2002, "wk2", 931), wk,
               [ar("wk2", 931, "integer")], [a("d_week_seq")],
               jt="LeftSemi", build_side="right")
    wk2 = F.project(
        [F.alias(F.binop("Subtract", a("d_week_seq"), i32(52)),
                 "wk_m52", 950)] + [
            F.alias(ar(f"{nm}_sales", 920 + k, "decimal(17,2)"),
                    f"{nm}2", 951 + k)
            for k, nm in enumerate(_DOW7)],
        wk2,
    )
    j2 = big_join(strategy, wk1, wk2, [a("d_week_seq")],
                  [ar("wk_m52", 950, "integer")])
    plan = F.take_ordered(
        100, [F.sort_order(a("d_week_seq"))],
        [F.alias(a("d_week_seq"), "d_week_seq", 970)]
        + _dow_ratios(940, 951, 971),
        j2,
    )
    got = _execute_both(sess, plan)
    _check_weekly_ratios(got, O.oracle_q2(data), ["d_week_seq"])


def test_spark_q59(sess, data, strategy):
    from test_tpcds import _check_weekly_ratios

    dt = F.scan("date_dim", [a("d_date_sk"), a("d_week_seq"), a("d_dow")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_store_sk"),
                                a("ss_sales_price")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    wk = _dow_pivot_plan([a("ss_store_sk"), a("d_week_seq")],
                         a("ss_sales_price"), j, 910)
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    wk = join(strategy, st_, wk, [a("s_store_sk")], [a("ss_store_sk")])
    wk1 = join(strategy, _week_set_plan(2001, "wk1", 930), wk,
               [ar("wk1", 930, "integer")], [a("d_week_seq")],
               jt="LeftSemi", build_side="right")
    wk1 = F.project(
        [a("s_store_name"), a("ss_store_sk"), a("d_week_seq")] + [
            F.alias(ar(f"{nm}_sales", 920 + k, "decimal(17,2)"),
                    f"{nm}1", 940 + k)
            for k, nm in enumerate(_DOW7)],
        wk1,
    )
    wk2 = join(strategy, _week_set_plan(2002, "wk2", 931), wk,
               [ar("wk2", 931, "integer")], [a("d_week_seq")],
               jt="LeftSemi", build_side="right")
    wk2 = F.project(
        [F.alias(a("ss_store_sk"), "store2", 949),
         F.alias(F.binop("Subtract", a("d_week_seq"), i32(52)),
                 "wk_m52", 950)] + [
            F.alias(ar(f"{nm}_sales", 920 + k, "decimal(17,2)"),
                    f"{nm}2", 951 + k)
            for k, nm in enumerate(_DOW7)],
        wk2,
    )
    j2 = big_join(strategy, wk1, wk2,
                  [a("ss_store_sk"), a("d_week_seq")],
                  [ar("store2", 949, "long"), ar("wk_m52", 950, "integer")])
    plan = F.take_ordered(
        100,
        [F.sort_order(a("s_store_name")), F.sort_order(a("d_week_seq"))],
        [F.alias(a("s_store_name"), "s_store_name", 969),
         F.alias(a("d_week_seq"), "d_week_seq", 970)]
        + _dow_ratios(940, 951, 971),
        j2,
    )
    got = _execute_both(sess, plan)
    _check_weekly_ratios(got, O.oracle_q59(data),
                         ["s_store_name", "d_week_seq"])


# --------------- q74/q11 year-over-year customer growth family

def _yoy_customer_plan(st, *, store_measure, store_cols, web_measure,
                       web_cols, y1, y2, out_cols, sum_dtype):
    def slice_(fact, date_c, cust_c, cols, measure, year, base, names=False):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(F.binop("EqualTo", a("d_year"), i32(year)),
                      F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
        )
        fc = F.scan(fact, [a(date_c), a(cust_c)] + [a(c) for c in cols])
        cust_cols = [a("c_customer_sk")] + (
            [a("c_customer_id"), a("c_first_name"), a("c_last_name"),
             a("c_preferred_cust_flag")] if names else [])
        cu = F.scan("customer", cust_cols)
        j = join(st, dt, fc, [a("d_date_sk")], [a(date_c)])
        j = join(st, cu, j, [a("c_customer_sk")], [a(cust_c)])
        groups = [a("c_customer_sk")] + (
            [a(c) for c in ("c_customer_id", "c_first_name", "c_last_name",
                            "c_preferred_cust_flag")] if names else [])
        yt = two_stage(groups, [(F.sum_(measure), base)], j)
        keep = [F.alias(a("c_customer_sk"), f"sk{base}", base + 1),
                F.alias(ar("year_total", base, sum_dtype), f"yt{base}",
                        base + 2)]
        if names:
            keep += [a(c) for c in
                     ("c_customer_id", "c_first_name", "c_last_name",
                      "c_preferred_cust_flag")]
        return F.project(keep, yt)

    s1 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk",
                store_cols, store_measure("ss"), y1, 1000)
    s2 = slice_("store_sales", "ss_sold_date_sk", "ss_customer_sk",
                store_cols, store_measure("ss"), y2, 1010, names=True)
    w1 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                web_cols, web_measure("ws"), y1, 1020)
    w2 = slice_("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
                web_cols, web_measure("ws"), y2, 1030)
    sk = lambda b: ar(f"sk{b}", b + 1, "long")
    yt = lambda b: ar(f"yt{b}", b + 2, sum_dtype)
    j = join(st, s1, s2, [sk(1000)], [sk(1010)])
    j = join(st, w1, j, [sk(1020)], [sk(1010)])
    j = join(st, w2, j, [sk(1030)], [sk(1010)])
    fl = lambda e: F.cast(e, "double")
    f = F.filter_(
        and_(F.binop("GreaterThan", fl(yt(1000)), F.lit(0.0, "double")),
             F.binop("GreaterThan", fl(yt(1020)), F.lit(0.0, "double")),
             F.binop("GreaterThan",
                     F.binop("Divide", fl(yt(1030)), fl(yt(1020))),
                     F.binop("Divide", fl(yt(1010)), fl(yt(1000))))),
        j,
    )
    return F.take_ordered(
        100, [F.sort_order(a(out_cols[0]))],
        [F.alias(a(c), c, 1050 + i) for i, c in enumerate(out_cols)],
        f,
    )


def test_spark_q74(sess, data, strategy):
    from test_tpcds import _check_yoy_customer

    plan = _yoy_customer_plan(
        strategy,
        store_measure=lambda p: a("ss_net_paid"),
        store_cols=["ss_net_paid"],
        web_measure=lambda p: a("ws_net_paid"),
        web_cols=["ws_net_paid"],
        y1=1999, y2=2000,
        out_cols=["c_customer_id", "c_first_name", "c_last_name"],
        sum_dtype="decimal(17,2)")
    got = _execute_both(sess, plan)
    _check_yoy_customer(got, O.oracle_q74(data),
                        ["c_customer_id", "c_first_name", "c_last_name"])


def test_spark_q11(sess, data, strategy):
    from test_tpcds import _check_yoy_customer

    plan = _yoy_customer_plan(
        strategy,
        store_measure=lambda p: F.binop(
            "Subtract", a("ss_ext_list_price"), a("ss_ext_discount_amt")),
        store_cols=["ss_ext_list_price", "ss_ext_discount_amt"],
        web_measure=lambda p: F.binop(
            "Subtract", a("ws_ext_list_price"), a("ws_ext_discount_amt")),
        web_cols=["ws_ext_list_price", "ws_ext_discount_amt"],
        y1=2000, y2=2001,
        out_cols=["c_customer_id", "c_preferred_cust_flag", "c_first_name",
                  "c_last_name"],
        sum_dtype="decimal(18,2)")
    got = _execute_both(sess, plan)
    _check_yoy_customer(got, O.oracle_q11(data),
                        ["c_customer_id", "c_preferred_cust_flag",
                         "c_first_name", "c_last_name"])


# ---------------- q18 catalog demographic averages geography rollup

def test_spark_q18(sess, data, strategy):
    cd = F.project(
        [a("cd_demo_sk"), a("cd_dep_count")],
        F.filter_(and_(F.binop("EqualTo", a("cd_gender"), s("F")),
                       F.binop("EqualTo", a("cd_education_status"),
                               s("College"))),
                  F.scan("customer_demographics",
                         [a("cd_demo_sk"), a("cd_gender"),
                          a("cd_education_status"), a("cd_dep_count")])),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    cu = F.project(
        [a("c_customer_sk"), a("c_current_addr_sk"), a("c_birth_year")],
        F.filter_(and_(F.binop("GreaterThanOrEqual", a("c_birth_year"),
                               i32(1966)),
                       F.binop("LessThanOrEqual", a("c_birth_year"),
                               i32(1980))),
                  F.scan("customer", [a("c_customer_sk"),
                                      a("c_current_addr_sk"),
                                      a("c_birth_year")])),
    )
    ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_county"),
                                     a("ca_state")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    cs = F.scan("catalog_sales",
                [a("cs_sold_date_sk"), a("cs_item_sk"),
                 a("cs_bill_customer_sk"), a("cs_bill_cdemo_sk"),
                 a("cs_quantity"), a("cs_list_price"), a("cs_coupon_amt"),
                 a("cs_sales_price"), a("cs_net_profit")])
    j = join(strategy, dt, cs, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, cd, j, [a("cd_demo_sk")], [a("cs_bill_cdemo_sk")])
    j = join(strategy, cu, j, [a("c_customer_sk")], [a("cs_bill_customer_sk")])
    j = join(strategy, ca, j, [a("ca_address_sk")], [a("c_current_addr_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("cs_item_sk")])
    measures = [("cs_quantity", "agg1"), ("cs_list_price", "agg2"),
                ("cs_coupon_amt", "agg3"), ("cs_sales_price", "agg4"),
                ("cs_net_profit", "agg5"), ("c_birth_year", "agg6"),
                ("cd_dep_count", "agg7")]
    base = F.project(
        [F.alias(F.cast(a(src), "double"), nm, 1100 + k)
         for k, (src, nm) in enumerate(measures)]
        + [a("i_item_id"), a("ca_county"), a("ca_state")],
        j,
    )
    meas_attrs = [ar(nm, 1100 + k, "double")
                  for k, (_, nm) in enumerate(measures)]
    dims = ["i_item_id", "ca_county", "ca_state"]
    null_s = F.lit(None, "string")
    exp_dims = [ar(d, 1110 + k, "string") for k, d in enumerate(dims)]
    exp_gid = ar("g_id", 1113, "long")
    rows = []
    for level in range(3, -1, -1):
        row = list(meas_attrs)
        for k, d in enumerate(dims):
            row.append(a(d) if k < level else null_s)
        row.append(F.lit(3 - level, "long"))
        rows.append(row)
    expand = F.expand(rows, meas_attrs + exp_dims + [exp_gid], base)
    agg = two_stage(
        exp_dims + [exp_gid],
        [(F.avg(m), 1120 + k) for k, m in enumerate(meas_attrs)],
        expand,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(exp_dims[1]), F.sort_order(exp_dims[2]),
         F.sort_order(exp_dims[0]), F.sort_order(exp_gid)],
        [F.alias(exp_dims[0], "i_item_id", 1130),
         F.alias(exp_dims[1], "ca_county", 1131),
         F.alias(exp_dims[2], "ca_state", 1132),
         F.alias(exp_gid, "g_id", 1133)]
        + [F.alias(ar(nm, 1120 + k, "double"), nm, 1134 + k)
           for k, (_, nm) in enumerate(measures)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q18(data)
    assert exp, "q18 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["ca_county"][i], got["ca_state"][i],
               got["g_id"][i])
        assert key in exp, key
        for k in range(7):
            assert abs(got[f"agg{k+1}"][i] - exp[key][k]) < 1e-9, (key, k)


# ---------------- q83 three-channel return shares

def test_spark_q83(sess, data, strategy):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])

    def channel(rtab, r_date, r_item, r_qty, nm, base):
        rt = F.scan(rtab, [a(r_date), a(r_item), a(r_qty)])
        j = join(strategy, dt, rt, [a("d_date_sk")], [a(r_date)])
        j = join(strategy, it, j, [a("i_item_sk")], [a(r_item)])
        src = F.project(
            [F.alias(a("i_item_id"), f"{nm}_item_id", base),
             F.alias(F.cast(a(r_qty), "long"), "q", base + 1)], j)
        return two_stage(
            [ar(f"{nm}_item_id", base, "string")],
            [(F.sum_(ar("q", base + 1, "long")), base + 2)],
            src,
        )

    sr = channel("store_returns", "sr_returned_date_sk", "sr_item_sk",
                 "sr_return_quantity", "sr", 1200)
    cr = channel("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
                 "cr_return_quantity", "cr", 1210)
    wr = channel("web_returns", "wr_returned_date_sk", "wr_item_sk",
                 "wr_return_quantity", "wr", 1220)
    sid = ar("sr_item_id", 1200, "string")
    j = big_join(strategy, sr, cr, [sid], [ar("cr_item_id", 1210, "string")])
    j = big_join(strategy, j, wr, [sid], [ar("wr_item_id", 1220, "string")])
    qty = {nm: ar(f"{nm}_qty", base + 2, "long")
           for nm, base in (("sr", 1200), ("cr", 1210), ("wr", 1220))}
    total = F.cast(
        F.binop("Add", F.binop("Add", qty["sr"], qty["cr"]), qty["wr"]),
        "double")
    outs = [F.alias(sid, "item_id", 1230),
            F.alias(qty["sr"], "sr_qty", 1231),
            F.alias(qty["cr"], "cr_qty", 1232),
            F.alias(qty["wr"], "wr_qty", 1233)]
    for k, nm in enumerate(("sr", "cr", "wr")):
        outs.append(F.alias(
            F.binop("Multiply",
                    F.binop("Divide", F.cast(qty[nm], "double"), total),
                    F.lit(100.0, "double")),
            f"{nm}_dev", 1234 + k))
    outs.append(F.alias(F.binop("Divide", total, F.lit(3.0, "double")),
                        "average", 1237))
    plan = F.take_ordered(
        100,
        [F.sort_order(sid), F.sort_order(qty["sr"])],
        outs,
        j,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q83(data)
    assert exp, "q83 oracle empty"
    n = len(got["item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = got["item_id"][i]
        assert key in exp, key
        a_, b_, c_, da, db, dc, avg = exp[key]
        assert (got["sr_qty"][i], got["cr_qty"][i],
                got["wr_qty"][i]) == (a_, b_, c_), key
        assert abs(got["sr_dev"][i] - da) < 1e-9
        assert abs(got["cr_dev"][i] - db) < 1e-9
        assert abs(got["wr_dev"][i] - dc) < 1e-9
        assert abs(got["average"][i] - avg) < 1e-9


# ---------------- q84 income-band returning customers

def test_spark_q84(ticket_sess, ticket_data, strategy):
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(F.binop("EqualTo", a("ca_city"), s("Midway")),
                  F.scan("customer_address", [a("ca_address_sk"),
                                              a("ca_city")])),
    )
    cust = F.scan("customer", [
        a("c_customer_id"), a("c_first_name"), a("c_last_name"),
        a("c_current_addr_sk"), a("c_current_cdemo_sk"),
        a("c_current_hdemo_sk")])
    j = join(strategy, ca, cust, [a("ca_address_sk")],
             [a("c_current_addr_sk")])
    ib = F.project(
        [a("ib_income_band_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("ib_lower_bound"),
                         i32(38128)),
                 F.binop("LessThanOrEqual", a("ib_upper_bound"),
                         i32(38128 + 50000))),
            F.scan("income_band", [a("ib_income_band_sk"),
                                   a("ib_lower_bound"),
                                   a("ib_upper_bound")])),
    )
    hd = F.scan("household_demographics", [a("hd_demo_sk"),
                                           a("hd_income_band_sk")])
    hd = join(strategy, ib, hd, [a("ib_income_band_sk")],
              [a("hd_income_band_sk")])
    hd = F.project([a("hd_demo_sk")], hd)
    j = join(strategy, hd, j, [a("hd_demo_sk")], [a("c_current_hdemo_sk")])
    cd = F.scan("customer_demographics", [a("cd_demo_sk")])
    j = join(strategy, cd, j, [a("cd_demo_sk")], [a("c_current_cdemo_sk")])
    sr = F.scan("store_returns", [a("sr_cdemo_sk")])
    j = big_join(strategy, j, sr, [a("cd_demo_sk")], [a("sr_cdemo_sk")],
                 build_side="left")
    name = F.T(F.X + "Concat",
               [a("c_last_name"), F.lit(", ", "string"), a("c_first_name")])
    plan = F.take_ordered(
        100, [F.sort_order(a("c_customer_id"))],
        [F.alias(a("c_customer_id"), "customer_id", 1250),
         F.alias(name, "customername", 1251)],
        j,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q84(ticket_data)
    assert exp, "q84 oracle empty"
    rows = sorted(zip(got["customer_id"], got["customername"]))
    assert rows == exp
    assert got["customer_id"] == sorted(got["customer_id"])


# ------------- q57 catalog year-over-year window (q47's twin)

def test_spark_q57(sess, data, strategy):
    from test_tpcds import _check_yoy

    year = 1999
    dt = F.project(
        [a("d_date_sk"), a("d_year"), a("d_moy")],
        F.filter_(
            or_(
                F.binop("EqualTo", a("d_year"), i32(year)),
                and_(F.binop("EqualTo", a("d_year"), i32(year - 1)),
                     F.binop("EqualTo", a("d_moy"), i32(12))),
                and_(F.binop("EqualTo", a("d_year"), i32(year + 1)),
                     F.binop("EqualTo", a("d_moy"), i32(1))),
            ),
            F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")]),
        ),
    )
    cc = F.scan("call_center", [a("cc_call_center_sk"), a("cc_name")])
    it = F.scan("item", [a("i_item_sk"), a("i_brand"), a("i_category")])
    sales = F.scan(
        "catalog_sales",
        [a("cs_sold_date_sk"), a("cs_item_sk"), a("cs_call_center_sk"),
         a("cs_sales_price")],
    )
    j = join(strategy, dt, sales, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, cc, j, [a("cc_call_center_sk")],
             [a("cs_call_center_sk")])
    j = join(strategy, it, j, [a("i_item_sk")], [a("cs_item_sk")])
    part = [a("i_category"), a("i_brand"), a("cc_name")]
    agg = two_stage(
        part + [a("d_year"), a("d_moy")],
        [(F.sum_(a("cs_sales_price")), 501)],
        j,
    )
    sum_sales = ar("sum_sales", 501, "decimal(17,2)")
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort(
        [F.sort_order(p) for p in part]
        + [F.sort_order(a("d_year")), F.sort_order(a("d_moy"))],
        single,
    )
    w_avg = F.window(
        [F.window_expr(
            F.window_agg(F.avg(sum_sales)),
            F.window_spec(part + [a("d_year")], [],
                          F.window_frame("up", "uf", row=True)),
            "avg_monthly_sales", 502)],
        part + [a("d_year")],
        [],
        pre,
    )
    orders = [F.sort_order(a("d_year")), F.sort_order(a("d_moy"))]
    w = F.window(
        [F.window_expr(F.lag_fn(sum_sales), F.window_spec(part, orders),
                       "psum", 503),
         F.window_expr(F.lead_fn(sum_sales), F.window_spec(part, orders),
                       "nsum", 504)],
        part,
        orders,
        w_avg,
    )
    avg_m = ar("avg_monthly_sales", 502, "decimal(11,6)")
    sum_f = F.cast(sum_sales, "double")
    avg_f = F.cast(avg_m, "double")
    filt = F.filter_(
        and_(
            F.binop("EqualTo", a("d_year"), i32(year)),
            F.binop("GreaterThan", avg_m, i32(0)),
            F.binop(
                "GreaterThan",
                F.binop("Divide",
                        F.un("Abs", F.binop("Subtract", sum_f, avg_f)),
                        avg_f),
                F.lit(0.1, "double"),
            ),
        ),
        w,
    )
    proj = F.project(
        [a("i_category"), a("i_brand"), a("cc_name"),
         a("d_year"), a("d_moy"), sum_sales, avg_m,
         ar("psum", 503, "decimal(17,2)"), ar("nsum", 504, "decimal(17,2)"),
         F.alias(F.binop("Subtract", sum_f, avg_f), "delta", 510)],
        filt,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(ar("delta", 510, "double")), F.sort_order(a("d_moy"))],
        [F.alias(a("i_category"), "i_category", 520),
         F.alias(a("i_brand"), "i_brand", 521),
         F.alias(a("cc_name"), "cc_name", 522),
         F.alias(a("d_year"), "d_year", 524),
         F.alias(a("d_moy"), "d_moy", 525),
         F.alias(sum_sales, "sum_sales", 526),
         F.alias(avg_m, "avg_monthly_sales", 527),
         F.alias(ar("psum", 503, "decimal(17,2)"), "psum", 528),
         F.alias(ar("nsum", 504, "decimal(17,2)"), "nsum", 529)],
        proj,
    )
    got = _execute_both(sess, plan)
    _check_yoy(got, O.oracle_q57(data), ("cc_name",))


# ------------- q39a/b inventory cov month-over-month self-join

def _q39_monthly_cov_plan(st, moy, base):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(2001)),
                       F.binop("EqualTo", a("d_moy"), i32(moy))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                      a("d_moy")])),
    )
    inv = F.scan("inventory", [a("inv_date_sk"), a("inv_item_sk"),
                               a("inv_warehouse_sk"),
                               a("inv_quantity_on_hand")])
    j = join(st, dt, inv, [a("d_date_sk")], [a("inv_date_sk")])
    wh = F.scan("warehouse", [a("w_warehouse_sk"), a("w_warehouse_name")])
    j = join(st, wh, j, [a("w_warehouse_sk")], [a("inv_warehouse_sk")])
    agg = two_stage(
        [a("w_warehouse_name"), a("inv_item_sk")],
        [(F.avg(a("inv_quantity_on_hand")), base),
         (F.T(F.A + "StddevSamp", [a("inv_quantity_on_hand")]), base + 1)],
        j,
    )
    mean = ar("mean", base, "double")
    stdev = ar("stdev", base + 1, "double")
    cov = F.T(F.X + "CaseWhen",
              [F.binop("GreaterThan", mean, F.lit(0.0, "double")),
               F.binop("Divide", stdev, mean)])
    return F.project(
        [a("w_warehouse_name"), a("inv_item_sk"), mean,
         F.alias(cov, "cov", base + 2)], agg)


def _q39_plan(st, thr1, thr2):
    m1 = F.filter_(
        F.binop("GreaterThan", ar("cov", 1302, "double"),
                F.lit(thr1, "double")),
        _q39_monthly_cov_plan(st, 1, 1300))
    m2 = F.filter_(
        F.binop("GreaterThan", ar("cov", 1312, "double"),
                F.lit(thr2, "double")),
        _q39_monthly_cov_plan(st, 2, 1310))
    m2 = F.project(
        [F.alias(a("w_warehouse_name"), "w2", 1320),
         F.alias(a("inv_item_sk"), "i2", 1321),
         F.alias(ar("mean", 1310, "double"), "mean2", 1322),
         F.alias(ar("cov", 1312, "double"), "cov2", 1323)],
        m2,
    )
    j = big_join(st, m1, m2, [a("w_warehouse_name"), a("inv_item_sk")],
                 [ar("w2", 1320, "string"), ar("i2", 1321, "long")])
    return F.take_ordered(
        100,
        [F.sort_order(a("w_warehouse_name")), F.sort_order(a("inv_item_sk"))],
        [F.alias(a("w_warehouse_name"), "w_warehouse_name", 1330),
         F.alias(a("inv_item_sk"), "inv_item_sk", 1331),
         F.alias(ar("mean", 1300, "double"), "mean", 1332),
         F.alias(ar("cov", 1302, "double"), "cov", 1333),
         F.alias(ar("mean2", 1322, "double"), "mean2", 1334),
         F.alias(ar("cov2", 1323, "double"), "cov2", 1335)],
        j,
    )


def test_spark_q39a(sess, data, strategy):
    from test_tpcds import _check_q39

    got = _execute_both(sess, _q39_plan(strategy, 0.7, 0.7))
    _check_q39(got, O.oracle_q39a(data))


def test_spark_q39b(sess, data, strategy):
    from test_tpcds import _check_q39

    got = _execute_both(sess, _q39_plan(strategy, 0.85, 0.7))
    _check_q39(got, O.oracle_q39b(data))


# ------------- q49 worst return ratios double-ranked per channel

def _q49_channel_plan(st, channel, fact, ret, s_item, s_ord, s_qty, s_paid,
                      s_profit, r_item, r_ord, r_qty, r_amt, date_c, base):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(2001)),
                       F.binop("EqualTo", a("d_moy"), i32(12))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                      a("d_moy")])),
    )
    sl = F.project(
        [a(date_c), a(s_item), a(s_ord), a(s_qty), a(s_paid)],
        F.filter_(
            and_(F.binop("GreaterThan", F.cast(a(s_profit), "double"),
                         F.lit(1.0, "double")),
                 F.binop("GreaterThan", F.cast(a(s_paid), "double"),
                         F.lit(0.0, "double")),
                 F.binop("GreaterThan", a(s_qty), i32(0))),
            F.scan(fact, [a(date_c), a(s_item), a(s_ord), a(s_qty),
                          a(s_paid), a(s_profit)]),
        ),
    )
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    rt = F.project(
        [a(r_item), a(r_ord), a(r_qty), a(r_amt)],
        F.filter_(F.binop("GreaterThan", F.cast(a(r_amt), "double"),
                          F.lit(250.0, "double")),
                  F.scan(ret, [a(r_item), a(r_ord), a(r_qty), a(r_amt)])),
    )
    j = big_join(st, j, rt, [a(s_ord), a(s_item)], [a(r_ord), a(r_item)])
    src = F.project(
        [F.alias(a(s_item), "item", base), a(r_qty), a(s_qty), a(r_amt),
         a(s_paid)], j)
    agg = two_stage(
        [ar("item", base, "long")],
        [(F.sum_(a(r_qty)), base + 1), (F.sum_(a(s_qty)), base + 2),
         (F.sum_(a(r_amt)), base + 3), (F.sum_(a(s_paid)), base + 4)],
        src,
    )
    f64 = "double"
    rr = F.binop("Divide",
                 F.cast(ar("ret_q", base + 1, "long"), f64),
                 F.cast(ar("qty", base + 2, "long"), f64))
    cur = F.binop("Divide",
                  F.cast(ar("ret_amt", base + 3, "decimal(17,2)"), f64),
                  F.cast(ar("paid", base + 4, "decimal(17,2)"), f64))
    ratios = F.project(
        [ar("item", base, "long"), F.alias(rr, "return_ratio", base + 5),
         F.alias(cur, "currency_ratio", base + 6)],
        agg,
    )
    rr_a = ar("return_ratio", base + 5, f64)
    cur_a = ar("currency_ratio", base + 6, f64)
    single = F.shuffle(F.single_partition(), ratios)
    s1 = F.sort([F.sort_order(rr_a)], single)
    w1 = F.window(
        [F.window_expr(F.rank_fn([rr_a]), F.window_spec([], [F.sort_order(rr_a)]),
                       "return_rank", base + 7)],
        [], [F.sort_order(rr_a)], s1)
    s2 = F.sort([F.sort_order(cur_a)], w1)
    w2 = F.window(
        [F.window_expr(F.rank_fn([cur_a]),
                       F.window_spec([], [F.sort_order(cur_a)]),
                       "currency_rank", base + 8)],
        [], [F.sort_order(cur_a)], s2)
    rrank = ar("return_rank", base + 7, "integer")
    crank = ar("currency_rank", base + 8, "integer")
    f = F.filter_(
        or_(F.binop("LessThanOrEqual", rrank, i32(10)),
            F.binop("LessThanOrEqual", crank, i32(10))),
        w2,
    )
    # union arms share output exprIds (1400-1404)
    return F.project(
        [F.alias(F.lit(channel, "string"), "channel", 1400),
         F.alias(ar("item", base, "long"), "item", 1401),
         F.alias(rr_a, "return_ratio", 1402),
         F.alias(rrank, "return_rank", 1403),
         F.alias(crank, "currency_rank", 1404)],
        f,
    )


def test_spark_q49(ticket_sess, ticket_data, strategy):
    web = _q49_channel_plan(
        strategy, "web", "web_sales", "web_returns", "ws_item_sk",
        "ws_order_number", "ws_quantity", "ws_net_paid", "ws_net_profit",
        "wr_item_sk", "wr_order_number", "wr_return_quantity",
        "wr_return_amt", "ws_sold_date_sk", 1410)
    cat = _q49_channel_plan(
        strategy, "catalog", "catalog_sales", "catalog_returns", "cs_item_sk",
        "cs_order_number", "cs_quantity", "cs_net_paid", "cs_net_profit",
        "cr_item_sk", "cr_order_number", "cr_return_quantity",
        "cr_return_amount", "cs_sold_date_sk", 1430)
    store = _q49_channel_plan(
        strategy, "store", "store_sales", "store_returns", "ss_item_sk",
        "ss_ticket_number", "ss_quantity", "ss_net_paid", "ss_net_profit",
        "sr_item_sk", "sr_ticket_number", "sr_return_quantity",
        "sr_return_amt", "ss_sold_date_sk", 1450)
    u = F.union([web, cat, store])
    ch = ar("channel", 1400, "string")
    rrank = ar("return_rank", 1403, "integer")
    crank = ar("currency_rank", 1404, "integer")
    plan = F.take_ordered(
        100,
        [F.sort_order(ch), F.sort_order(rrank), F.sort_order(crank)],
        [F.alias(ch, "channel", 1470),
         F.alias(ar("item", 1401, "long"), "item", 1471),
         F.alias(ar("return_ratio", 1402, "double"), "return_ratio", 1472),
         F.alias(rrank, "return_rank", 1473),
         F.alias(crank, "currency_rank", 1474)],
        u,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q49(ticket_data)
    assert exp, "q49 oracle empty"
    rows = set(zip(got["channel"], got["item"], got["return_ratio"],
                   got["return_rank"], got["currency_rank"]))
    assert rows == exp
    keys = list(zip(got["channel"], got["return_rank"],
                    got["currency_rank"]))
    assert keys == sorted(keys)


# ------------------ q5 channel sales/returns/profit ROLLUP

def _channel_report_tail_plan(st, union_plan):
    """Shared q5-family tail: ROLLUP(channel, id) via Expand + two-stage
    agg, ORDER BY channel, id NULLS FIRST LIMIT 100.  Union arms must
    alias (channel 1500, id 1501, sales 1502, returns 1503,
    profit 1504)."""
    ch = ar("channel", 1500, "string")
    idc = ar("id", 1501, "string")
    sales = ar("sales", 1502, "decimal(8,2)")
    rets = ar("returns", 1503, "decimal(8,2)")
    prof = ar("profit", 1504, "decimal(9,2)")
    null_s = F.lit(None, "string")
    exp_ch = ar("channel", 1510, "string")
    exp_id = ar("id", 1511, "string")
    exp_gid = ar("g_id", 1512, "integer")
    vals = [sales, rets, prof]
    expand = F.expand(
        [
            vals + [ch, idc, F.lit(0, "integer")],
            vals + [ch, null_s, F.lit(1, "integer")],
            vals + [null_s, null_s, F.lit(3, "integer")],
        ],
        vals + [exp_ch, exp_id, exp_gid],
        union_plan,
    )
    agg = two_stage(
        [exp_ch, exp_id, exp_gid],
        [(F.sum_(sales), 1520), (F.sum_(rets), 1521), (F.sum_(prof), 1522)],
        expand,
    )
    return F.take_ordered(
        100,
        [F.sort_order(exp_ch), F.sort_order(exp_id)],
        [F.alias(exp_ch, "channel", 1530), F.alias(exp_id, "id", 1531),
         F.alias(ar("sales", 1520, "decimal(18,2)"), "sales", 1532),
         F.alias(ar("returns", 1521, "decimal(18,2)"), "returns", 1533),
         F.alias(ar("profit", 1522, "decimal(19,2)"), "profit", 1534)],
        agg,
    )


def test_spark_q5(sess, data, strategy):
    from test_tpcds import _check_channel_report

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-08-23", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2000-09-05", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    dz = F.lit("0", "decimal(7,2)")

    def d8(e):
        return F.binop("Add", e, dz)

    def neg(e):
        return F.binop("Subtract", dz, e)

    def arm(id_expr, sales_e, ret_e, prof_e, src):
        return F.project(
            [F.alias(id_expr, "id", 1501), F.alias(sales_e, "sales", 1502),
             F.alias(ret_e, "returns", 1503), F.alias(prof_e, "profit", 1504)],
            src,
        )

    def tag(plan, channel):
        return F.project(
            [F.alias(F.lit(channel, "string"), "channel", 1500),
             ar("id", 1501, "string"), ar("sales", 1502, "decimal(8,2)"),
             ar("returns", 1503, "decimal(8,2)"),
             ar("profit", 1504, "decimal(9,2)")],
            plan,
        )

    # store channel
    st_ = F.scan("store", [a("s_store_sk"), a("s_store_name")])
    sl = F.scan("store_sales", [a("ss_sold_date_sk"), a("ss_store_sk"),
                                a("ss_ext_sales_price"), a("ss_net_profit")])
    j = join(strategy, dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = join(strategy, st_, j, [a("s_store_sk")], [a("ss_store_sk")])
    s_sales = arm(a("s_store_name"), d8(a("ss_ext_sales_price")), d8(dz),
                  d8(a("ss_net_profit")), j)
    sr = F.scan("store_returns", [a("sr_returned_date_sk"), a("sr_store_sk"),
                                  a("sr_return_amt"), a("sr_net_loss")])
    jr = join(strategy, dt, sr, [a("d_date_sk")], [a("sr_returned_date_sk")])
    jr = join(strategy, st_, jr, [a("s_store_sk")], [a("sr_store_sk")])
    s_ret = arm(a("s_store_name"), d8(dz), d8(a("sr_return_amt")),
                neg(a("sr_net_loss")), jr)
    store_rows = tag(F.union([s_sales, s_ret]), "store channel")

    # catalog channel
    cp = F.scan("catalog_page", [a("cp_catalog_page_sk"),
                                 a("cp_catalog_page_id")])
    cl = F.scan("catalog_sales", [a("cs_sold_date_sk"), a("cs_catalog_page_sk"),
                                  a("cs_ext_sales_price"), a("cs_net_profit")])
    j = join(strategy, dt, cl, [a("d_date_sk")], [a("cs_sold_date_sk")])
    j = join(strategy, cp, j, [a("cp_catalog_page_sk")],
             [a("cs_catalog_page_sk")])
    c_sales = arm(a("cp_catalog_page_id"), d8(a("cs_ext_sales_price")),
                  d8(dz), d8(a("cs_net_profit")), j)
    cr = F.scan("catalog_returns",
                [a("cr_returned_date_sk"), a("cr_catalog_page_sk"),
                 a("cr_return_amount"), a("cr_net_loss")])
    jr = join(strategy, dt, cr, [a("d_date_sk")], [a("cr_returned_date_sk")])
    jr = join(strategy, cp, jr, [a("cp_catalog_page_sk")],
              [a("cr_catalog_page_sk")])
    c_ret = arm(a("cp_catalog_page_id"), d8(dz), d8(a("cr_return_amount")),
                neg(a("cr_net_loss")), jr)
    cat_rows = tag(F.union([c_sales, c_ret]), "catalog channel")

    # web channel (returns recover the site via (item, order))
    wsit = F.scan("web_site", [a("web_site_sk"), a("web_name")])
    wl = F.scan("web_sales", [a("ws_sold_date_sk"), a("ws_web_site_sk"),
                              a("ws_ext_sales_price"), a("ws_net_profit")])
    j = join(strategy, dt, wl, [a("d_date_sk")], [a("ws_sold_date_sk")])
    j = join(strategy, wsit, j, [a("web_site_sk")], [a("ws_web_site_sk")])
    w_sales = arm(a("web_name"), d8(a("ws_ext_sales_price")), d8(dz),
                  d8(a("ws_net_profit")), j)
    wr = F.scan("web_returns",
                [a("wr_returned_date_sk"), a("wr_item_sk"),
                 a("wr_order_number"), a("wr_return_amt"), a("wr_net_loss")])
    jr = join(strategy, dt, wr, [a("d_date_sk")], [a("wr_returned_date_sk")])
    ws_keys = F.scan("web_sales", [a("ws_item_sk"), a("ws_order_number"),
                                   a("ws_web_site_sk")])
    jr = big_join(strategy, jr, ws_keys,
                  [a("wr_item_sk"), a("wr_order_number")],
                  [a("ws_item_sk"), a("ws_order_number")])
    jr = join(strategy, wsit, jr, [a("web_site_sk")], [a("ws_web_site_sk")])
    w_ret = arm(a("web_name"), d8(dz), d8(a("wr_return_amt")),
                neg(a("wr_net_loss")), jr)
    web_rows = tag(F.union([w_sales, w_ret]), "web channel")

    plan = _channel_report_tail_plan(
        strategy, F.union([store_rows, cat_rows, web_rows]))
    got = _execute_both(sess, plan)
    _check_channel_report(got, O.oracle_q5(data))


# --------------- q31 county store-vs-web quarterly growth

def test_spark_q31(ticket_sess, ticket_data, strategy):
    def channel(fact, date_c, addr_c, price_c, qoy, base):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(2000)),
                           F.binop("EqualTo", a("d_qoy"), i32(qoy))),
                      F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                          a("d_qoy")])),
        )
        sl = F.scan(fact, [a(date_c), a(addr_c), a(price_c)])
        j = join(strategy, dt, sl, [a("d_date_sk")], [a(date_c)])
        ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_county")])
        j = join(strategy, ca, j, [a("ca_address_sk")], [a(addr_c)])
        src = F.project(
            [F.alias(a("ca_county"), "county", base), a(price_c)], j)
        return two_stage(
            [ar("county", base, "string")],
            [(F.sum_(a(price_c)), base + 1)], src)

    b = {}
    for k, (pre, fact, date_c, addr_c, price_c) in enumerate((
        ("ss1", "store_sales", "ss_sold_date_sk", "ss_addr_sk",
         "ss_ext_sales_price"),
        ("ss2", "store_sales", "ss_sold_date_sk", "ss_addr_sk",
         "ss_ext_sales_price"),
        ("ss3", "store_sales", "ss_sold_date_sk", "ss_addr_sk",
         "ss_ext_sales_price"),
        ("ws1", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
         "ws_ext_sales_price"),
        ("ws2", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
         "ws_ext_sales_price"),
        ("ws3", "web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
         "ws_ext_sales_price"),
    )):
        b[pre] = (channel(fact, date_c, addr_c, price_c, int(pre[-1]),
                          1600 + 10 * k), 1600 + 10 * k)

    j, _ = b["ss1"]
    county = ar("county", 1600, "string")
    for pre in ("ss2", "ss3", "ws1", "ws2", "ws3"):
        arm_plan, base = b[pre]
        j = big_join(strategy, j, arm_plan, [county],
                     [ar("county", base, "string")])
    sales = {pre: ar("sales", base + 1, "decimal(17,2)")
             for pre, (_, base) in b.items()}
    fl = lambda e: F.cast(e, "double")

    def ratio(num, den):
        return F.binop("Divide", fl(num), fl(den))

    def guarded(num, den):
        return F.T(F.X + "CaseWhen",
                   [F.binop("GreaterThan", fl(den), F.lit(0.0, "double")),
                    ratio(num, den)])

    web12 = guarded(sales["ws2"], sales["ws1"])
    store12 = guarded(sales["ss2"], sales["ss1"])
    web23 = guarded(sales["ws3"], sales["ws2"])
    store23 = guarded(sales["ss3"], sales["ss2"])
    f = F.filter_(
        or_(F.binop("GreaterThan", web12, store12),
            F.binop("GreaterThan", web23, store23)),
        j,
    )
    plan = F.take_ordered(
        100, [F.sort_order(county)],
        [F.alias(county, "ca_county", 1700),
         F.alias(F.lit(2000, "integer"), "d_year", 1701),
         F.alias(ratio(sales["ws2"], sales["ws1"]), "web_q1_q2_increase", 1702),
         F.alias(ratio(sales["ss2"], sales["ss1"]), "store_q1_q2_increase", 1703),
         F.alias(ratio(sales["ws3"], sales["ws2"]), "web_q2_q3_increase", 1704),
         F.alias(ratio(sales["ss3"], sales["ss2"]), "store_q2_q3_increase", 1705)],
        f,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q31(ticket_data)
    assert exp, "q31 oracle empty"
    rows = {
        c: (w12, s12, w23, s23)
        for c, w12, s12, w23, s23 in zip(
            got["ca_county"], got["web_q1_q2_increase"],
            got["store_q1_q2_increase"], got["web_q2_q3_increase"],
            got["store_q2_q3_increase"])
    }
    assert set(rows) == set(exp)
    for c, vals in rows.items():
        assert vals == pytest.approx(exp[c], rel=1e-12), c
    assert got["d_year"] == [2000] * len(rows)


# ----------- q58 cross-channel items sold evenly (month window)

def test_spark_q58(ticket_sess, ticket_data, strategy):
    wk = distinct(
        [ar("wk_sel", 1800, "integer")],
        F.project([F.alias(a("d_month_seq"), "wk_sel", 1800)],
                  F.filter_(F.binop("EqualTo", a("d_date"),
                                    F.lit("2000-01-03", "date")),
                            F.scan("date_dim", [a("d_date"),
                                                a("d_month_seq")]))),
    )
    wk_seq = _scalar_subquery(wk, 1800)

    def channel(fact, item_c, date_c, price_c, base):
        dd = F.project(
            [a("d_date_sk")],
            F.filter_(F.binop("EqualTo", a("d_month_seq"), wk_seq),
                      F.scan("date_dim", [a("d_date_sk"), a("d_month_seq")])),
        )
        sl = F.scan(fact, [a(date_c), a(item_c), a(price_c)])
        j = join(strategy, dd, sl, [a("d_date_sk")], [a(date_c)])
        it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
        j = join(strategy, it, j, [a("i_item_sk")], [a(item_c)])
        src = F.project(
            [F.alias(a("i_item_id"), "item_id", base), a(price_c)], j)
        return two_stage(
            [ar("item_id", base, "string")],
            [(F.sum_(a(price_c)), base + 1)], src)

    ss_items = channel("store_sales", "ss_item_sk", "ss_sold_date_sk",
                       "ss_ext_sales_price", 1810)
    cs_items = channel("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                       "cs_ext_sales_price", 1820)
    ws_items = channel("web_sales", "ws_item_sk", "ws_sold_date_sk",
                       "ws_ext_sales_price", 1830)
    iid = ar("item_id", 1810, "string")
    j = big_join(strategy, ss_items, cs_items, [iid],
                 [ar("item_id", 1820, "string")])
    j = big_join(strategy, j, ws_items, [iid],
                 [ar("item_id", 1830, "string")])
    rev = {p: ar("rev", b + 1, "decimal(17,2)")
           for p, b in (("ss", 1810), ("cs", 1820), ("ws", 1830))}
    fl = lambda e: F.cast(e, "double")

    def near(x, y):
        return and_(
            F.binop("GreaterThanOrEqual", fl(x),
                    F.binop("Multiply", F.lit(0.25, "double"), fl(y))),
            F.binop("LessThanOrEqual", fl(x),
                    F.binop("Multiply", F.lit(4.0, "double"), fl(y))))

    f = F.filter_(
        and_(near(rev["ss"], rev["cs"]), near(rev["ss"], rev["ws"]),
             near(rev["cs"], rev["ss"]), near(rev["cs"], rev["ws"]),
             near(rev["ws"], rev["ss"]), near(rev["ws"], rev["cs"])),
        j,
    )
    total = F.binop("Add", F.binop("Add", fl(rev["ss"]), fl(rev["cs"])),
                    fl(rev["ws"]))

    def dev(x):
        return F.binop(
            "Multiply",
            F.binop("Divide", F.binop("Divide", fl(x), total),
                    F.lit(3.0, "double")),
            F.lit(100.0, "double"))

    plan = F.take_ordered(
        100,
        [F.sort_order(iid), F.sort_order(rev["ss"])],
        [F.alias(iid, "item_id", 1840),
         F.alias(rev["ss"], "ss_item_rev", 1841),
         F.alias(dev(rev["ss"]), "ss_dev", 1842),
         F.alias(rev["cs"], "cs_item_rev", 1843),
         F.alias(dev(rev["cs"]), "cs_dev", 1844),
         F.alias(rev["ws"], "ws_item_rev", 1845),
         F.alias(dev(rev["ws"]), "ws_dev", 1846),
         F.alias(F.binop("Divide", total, F.lit(3.0, "double")),
                 "average", 1847)],
        f,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q58(ticket_data)
    assert exp, "q58 oracle empty"
    rows = {
        i_: (sr, sd, cr, cd, wr, wd, avg)
        for i_, sr, sd, cr, cd, wr, wd, avg in zip(
            got["item_id"], got["ss_item_rev"], got["ss_dev"],
            got["cs_item_rev"], got["cs_dev"], got["ws_item_rev"],
            got["ws_dev"], got["average"])
    }
    assert set(rows) == set(exp)
    for i_, (sr, sd, cr, cd, wr, wd, avg) in rows.items():
        e = exp[i_]
        assert (sr, cr, wr) == (e[0], e[2], e[4]), i_
        assert (sd, cd, wd, avg) == pytest.approx(
            (e[1], e[3], e[5], e[6]), rel=1e-12), i_
    assert got["item_id"] == sorted(got["item_id"])


# ------------- q71 brand sales by meal-time minute

def test_spark_q71(ticket_sess, ticket_data, strategy):
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_brand")],
        F.filter_(F.binop("EqualTo", a("i_manager_id"), i32(1)),
                  F.scan("item", [a("i_item_sk"), a("i_brand_id"),
                                  a("i_brand"), a("i_manager_id")])),
    )
    parts = []
    for fact, price_c, date_c, item_c, time_c in (
        ("web_sales", "ws_ext_sales_price", "ws_sold_date_sk",
         "ws_item_sk", "ws_sold_time_sk"),
        ("catalog_sales", "cs_ext_sales_price", "cs_sold_date_sk",
         "cs_item_sk", "cs_sold_time_sk"),
        ("store_sales", "ss_ext_sales_price", "ss_sold_date_sk",
         "ss_item_sk", "ss_sold_time_sk"),
    ):
        dt = F.project(
            [a("d_date_sk")],
            F.filter_(and_(F.binop("EqualTo", a("d_moy"), i32(11)),
                           F.binop("EqualTo", a("d_year"), i32(1999))),
                      F.scan("date_dim", [a("d_date_sk"), a("d_moy"),
                                          a("d_year")])),
        )
        sl = F.project(
            [F.alias(a(price_c), "ext_price_v", 1900),
             F.alias(a(date_c), "sold_date_sk", 1901),
             F.alias(a(item_c), "sold_item_sk", 1902),
             F.alias(a(time_c), "time_sk", 1903)],
            F.scan(fact, [a(price_c), a(date_c), a(item_c), a(time_c)]))
        parts.append(join(strategy, dt, sl, [a("d_date_sk")],
                          [ar("sold_date_sk", 1901, "long")]))
    u = F.union(parts)
    j = join(strategy, it, u, [a("i_item_sk")],
             [ar("sold_item_sk", 1902, "long")])
    tm = F.project(
        [a("t_time_sk"), a("t_hour"), a("t_minute")],
        F.filter_(or_(F.binop("EqualTo", a("t_meal_time"), s("breakfast")),
                      F.binop("EqualTo", a("t_meal_time"), s("dinner"))),
                  F.scan("time_dim", [a("t_time_sk"), a("t_hour"),
                                      a("t_minute"), a("t_meal_time")])),
    )
    j = join(strategy, tm, j, [a("t_time_sk")], [ar("time_sk", 1903, "long")])
    agg = two_stage(
        [a("i_brand_id"), a("i_brand"), a("t_hour"), a("t_minute")],
        [(F.sum_(ar("ext_price_v", 1900, "decimal(7,2)")), 1910)],
        j,
    )
    price = ar("ext_price", 1910, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(price, asc=False), F.sort_order(a("i_brand_id"))],
        [F.alias(a("i_brand_id"), "brand_id", 1920),
         F.alias(a("i_brand"), "brand", 1921),
         F.alias(a("t_hour"), "t_hour", 1922),
         F.alias(a("t_minute"), "t_minute", 1923),
         F.alias(price, "ext_price", 1924)],
        agg,
    )
    got = _execute_both(ticket_sess, plan)
    exp = O.oracle_q71(ticket_data)
    assert exp, "q71 oracle empty"
    rows = dict(zip(zip(got["brand_id"], got["brand"], got["t_hour"],
                        got["t_minute"]), got["ext_price"]))
    assert rows == exp
    keys = list(zip([-p for p in got["ext_price"]], got["brand_id"]))
    assert keys == sorted(keys)


# ------------- q66 warehouse monthly sales/net pivot

_Q66_MONTHS = ("jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
               "sep", "oct", "nov", "dec")
_Q66_KEYS = ("w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
             "w_state", "w_country")


def _q66_channel_plan(st, fact, wh_c, date_c, time_c, mode_c, qty_c,
                      sales_c, net_c):
    dt = F.project(
        [a("d_date_sk"), a("d_moy")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"),
                                      a("d_moy")])),
    )
    tm = F.project(
        [a("t_time_sk")],
        F.filter_(and_(F.binop("GreaterThanOrEqual", a("t_time"),
                               F.lit(30838, "long")),
                       F.binop("LessThanOrEqual", a("t_time"),
                               F.lit(30838 + 28800, "long"))),
                  F.scan("time_dim", [a("t_time_sk"), a("t_time")])),
    )
    sm = F.project(
        [a("sm_ship_mode_sk")],
        F.filter_(in_(a("sm_carrier"), "DHL", "BARIAN"),
                  F.scan("ship_mode", [a("sm_ship_mode_sk"),
                                       a("sm_carrier")])),
    )
    sl = F.scan(fact, [a(wh_c), a(date_c), a(time_c), a(mode_c), a(qty_c),
                       a(sales_c), a(net_c)])
    j = join(st, dt, sl, [a("d_date_sk")], [a(date_c)])
    j = join(st, tm, j, [a("t_time_sk")], [a(time_c)])
    j = join(st, sm, j, [a("sm_ship_mode_sk")], [a(mode_c)])
    wh = F.scan("warehouse", [a("w_warehouse_sk")] + [a(k) for k in _Q66_KEYS])
    j = join(st, wh, j, [a("w_warehouse_sk")], [a(wh_c)])
    qdec = F.cast(a(qty_c), "decimal(10,0)")
    sales = F.binop("Multiply", a(sales_c), qdec)
    net = F.binop("Multiply", a(net_c), qdec)
    pivots = []
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        pivots.append(F.alias(
            F.T(F.X + "CaseWhen",
                [F.binop("EqualTo", a("d_moy"), i32(m)), sales]),
            f"{nm}_sales_v", 2000 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        pivots.append(F.alias(
            F.T(F.X + "CaseWhen",
                [F.binop("EqualTo", a("d_moy"), i32(m)), net]),
            f"{nm}_net_v", 2020 + m))
    proj = F.project([a(k) for k in _Q66_KEYS] + pivots, j)
    agg = two_stage(
        [a(k) for k in _Q66_KEYS],
        [(F.sum_(ar(f"{nm}_sales_v", 2000 + m, "decimal(18,2)")), 2040 + m)
         for m, nm in enumerate(_Q66_MONTHS, start=1)]
        + [(F.sum_(ar(f"{nm}_net_v", 2020 + m, "decimal(18,2)")), 2060 + m)
           for m, nm in enumerate(_Q66_MONTHS, start=1)],
        proj,
    )
    outs = [a(k) for k in _Q66_KEYS] + [
        F.alias(F.lit("DHL,BARIAN", "string"), "ship_carriers", 2080),
        F.alias(F.lit(2001, "integer"), "year", 2081),
    ]
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(ar(f"{nm}_sales", 2040 + m, "decimal(28,2)"),
                            f"{nm}_sales", 2100 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(
            F.binop("Divide",
                    F.cast(ar(f"{nm}_sales", 2040 + m, "decimal(28,2)"),
                           "double"),
                    F.cast(a("w_warehouse_sq_ft"), "double")),
            f"{nm}_sales_per_sq_foot", 2120 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(ar(f"{nm}_net", 2060 + m, "decimal(28,2)"),
                            f"{nm}_net", 2140 + m))
    return F.project(outs, agg)


def test_spark_q66(sess, data, strategy):
    web = _q66_channel_plan(
        strategy, "web_sales", "ws_warehouse_sk", "ws_sold_date_sk",
        "ws_sold_time_sk", "ws_ship_mode_sk", "ws_quantity",
        "ws_ext_sales_price", "ws_net_paid")
    cat = _q66_channel_plan(
        strategy, "catalog_sales", "cs_warehouse_sk", "cs_sold_date_sk",
        "cs_sold_time_sk", "cs_ship_mode_sk", "cs_quantity",
        "cs_sales_price", "cs_net_paid_inc_tax")
    u = F.union([web, cat])
    groups = [a(k) for k in _Q66_KEYS] + [
        ar("ship_carriers", 2080, "string"), ar("year", 2081, "integer")]
    aggs = []
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        aggs.append((F.sum_(ar(f"{nm}_sales", 2100 + m, "decimal(28,2)")),
                     2200 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        aggs.append((F.sum_(
            ar(f"{nm}_sales_per_sq_foot", 2120 + m, "double")), 2220 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        aggs.append((F.sum_(ar(f"{nm}_net", 2140 + m, "decimal(28,2)")),
                     2240 + m))
    agg = two_stage(groups, aggs, u)
    outs = [F.alias(a(k), k, 2300 + i) for i, k in enumerate(_Q66_KEYS)]
    outs += [F.alias(ar("ship_carriers", 2080, "string"), "ship_carriers",
                     2310),
             F.alias(ar("year", 2081, "integer"), "year", 2311)]
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(ar(f"{nm}_sales", 2200 + m, "decimal(38,2)"),
                            f"{nm}_sales", 2320 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(ar(f"{nm}_sales_per_sq_foot", 2220 + m, "double"),
                            f"{nm}_sales_per_sq_foot", 2340 + m))
    for m, nm in enumerate(_Q66_MONTHS, start=1):
        outs.append(F.alias(ar(f"{nm}_net", 2240 + m, "decimal(38,2)"),
                            f"{nm}_net", 2360 + m))
    plan = F.take_ordered(
        100, [F.sort_order(a("w_warehouse_name"))], outs, agg)
    got = _execute_both(sess, plan)
    exp = O.oracle_q66(data)
    assert exp, "q66 oracle empty"
    assert got["w_warehouse_name"] == sorted(exp)
    for i, name in enumerate(got["w_warehouse_name"]):
        sq_ft, city, cty, state, country, sales_e, ratios, nets = exp[name]
        assert (got["w_warehouse_sq_ft"][i], got["w_city"][i],
                got["w_county"][i], got["w_state"][i],
                got["w_country"][i]) == (sq_ft, city, cty, state, country)
        assert got["ship_carriers"][i] == "DHL,BARIAN"
        assert got["year"][i] == 2001
        for m, nm in enumerate(_Q66_MONTHS):
            assert got[f"{nm}_sales"][i] == sales_e[m], (name, nm)
            assert got[f"{nm}_net"][i] == nets[m], (name, nm)
            g = got[f"{nm}_sales_per_sq_foot"][i]
            if ratios[m] is None:
                assert g is None, (name, nm)
            else:
                assert g == pytest.approx(ratios[m], rel=1e-12), (name, nm)


# ------------- q80 per-item channel totals net of returns

def test_spark_q80(sess, data, strategy):
    from test_tpcds import _check_channel_report

    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"),
                         F.lit("2000-08-03", "date")),
                 F.binop("LessThanOrEqual", a("d_date"),
                         F.lit("2000-09-01", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    dz = F.lit("0", "decimal(7,2)")
    it_p = F.project(
        [a("i_item_sk"), a("i_item_id")],
        F.filter_(F.binop("GreaterThan", a("i_current_price"),
                          F.lit("50", "decimal(7,2)")),
                  F.scan("item", [a("i_item_sk"), a("i_item_id"),
                                  a("i_current_price")])),
    )
    pr_p = F.project(
        [a("p_promo_sk")],
        F.filter_(F.binop("EqualTo", a("p_channel_email"), s("N")),
                  F.scan("promotion", [a("p_promo_sk"),
                                       a("p_channel_email")])),
    )

    def d8(e):
        return F.binop("Add", e, dz)

    def co0(e):
        return F.T(F.X + "CaseWhen",
                   [F.un("IsNotNull", e), F.binop("Add", e, dz),
                    F.binop("Add", dz, dz)])

    def channel(fact, ret, fact_cols, ret_cols, skeys, rkeys, date_c,
                item_c, promo_c, price_c, profit_c, ramt_c, rloss_c, name):
        sl = F.scan(fact, [a(c) for c in fact_cols])
        rt = F.scan(ret, [a(c) for c in ret_cols])
        j = join(strategy, dt, sl, [a("d_date_sk")], [a(date_c)])
        j = join(strategy, it_p, j, [a("i_item_sk")], [a(item_c)])
        j = join(strategy, pr_p, j, [a("p_promo_sk")], [a(promo_c)])
        j = join(strategy, rt, j, [a(k) for k in rkeys],
                 [a(k) for k in skeys], jt="LeftOuter", build_side="right")
        return F.project(
            [F.alias(F.lit(name, "string"), "channel", 1500),
             F.alias(a("i_item_id"), "id", 1501),
             F.alias(d8(a(price_c)), "sales", 1502),
             F.alias(co0(a(ramt_c)), "returns", 1503),
             F.alias(F.binop("Subtract", d8(a(profit_c)), co0(a(rloss_c))),
                     "profit", 1504)],
            j,
        )

    store_rows = channel(
        "store_sales", "store_returns",
        ["ss_sold_date_sk", "ss_item_sk", "ss_promo_sk", "ss_ticket_number",
         "ss_ext_sales_price", "ss_net_profit"],
        ["sr_item_sk", "sr_ticket_number", "sr_return_amt", "sr_net_loss"],
        ["ss_item_sk", "ss_ticket_number"],
        ["sr_item_sk", "sr_ticket_number"],
        "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
        "ss_ext_sales_price", "ss_net_profit", "sr_return_amt",
        "sr_net_loss", "store channel")
    cat_rows = channel(
        "catalog_sales", "catalog_returns",
        ["cs_sold_date_sk", "cs_item_sk", "cs_promo_sk", "cs_order_number",
         "cs_ext_sales_price", "cs_net_profit"],
        ["cr_item_sk", "cr_order_number", "cr_return_amount", "cr_net_loss"],
        ["cs_item_sk", "cs_order_number"],
        ["cr_item_sk", "cr_order_number"],
        "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
        "cs_ext_sales_price", "cs_net_profit", "cr_return_amount",
        "cr_net_loss", "catalog channel")
    web_rows = channel(
        "web_sales", "web_returns",
        ["ws_sold_date_sk", "ws_item_sk", "ws_promo_sk", "ws_order_number",
         "ws_ext_sales_price", "ws_net_profit"],
        ["wr_item_sk", "wr_order_number", "wr_return_amt", "wr_net_loss"],
        ["ws_item_sk", "ws_order_number"],
        ["wr_item_sk", "wr_order_number"],
        "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
        "ws_ext_sales_price", "ws_net_profit", "wr_return_amt",
        "wr_net_loss", "web channel")

    # q80's id is a string item_id; profit subtracts the loss coalesce,
    # widening to decimal(9,2) — reuse the q5 rollup tail by aliasing
    # profit down into the same slot types
    plan = _channel_report_tail_plan(
        strategy, F.union([store_rows, cat_rows, web_rows]))
    got = _execute_both(sess, plan)
    _check_channel_report(got, O.oracle_q80(data))
