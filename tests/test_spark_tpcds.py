"""TPC-DS differentials THROUGH the full Spark interception layer.

Round-3 gap: 38 of 42 TPC-DS differentials executed hand-built
ExecNode trees in-process, bypassing ``spark/converters.py`` and the
TaskDefinition serde.  Here a representative slice — star joins
(q42/q52), rollup/Expand (q27/q36), windows (q47/q89/q98), INTERSECT
(q8/q38), correlated EXISTS (q10/q35) — is expressed as catalyst
``toJSON`` physical-plan dumps, crosses strategy + expression
conversion, runs via BOTH the in-process collect path and the stage
scheduler (every task crossing TaskDefinition protobuf bytes), and is
validated against the same independent numpy oracles the ExecNode
suite uses: the shape of the reference's differential gate, which
always runs full conversion (``tpcds-reusable.yml:83-143``).

Plans are authored from the TPC-DS query text with the real catalyst
encodings (Expand null-filled projections, WindowSpecDefinition +
SpecifiedWindowFrame with ``$``-suffixed case objects, ExistenceJoin
product objects carrying the exists attribute) — not emitted from the
engine's own IR, so the loop stays open.
"""

import json

import pytest

from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.tpcds import TPCDS_SCHEMAS
from blaze_tpu.tpcds import oracle as O
from blaze_tpu.tpcds.datagen import generate_all
from blaze_tpu.tpch.datagen import table_to_batches

import spark_fixtures as F
from test_tpcds import (
    _check_brand_report,
    _check_class_share,
    _check_rollup_margin,
    _check_yoy,
)

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2

# stable exprId blocks per table (column order = TPCDS_SCHEMAS order)
_DTYPES = {}
_IDS = {}
for _ti, (_t, _schema) in enumerate(TPCDS_SCHEMAS.items()):
    for _i, _f in enumerate(_schema.fields):
        _IDS[_f.name] = _ti * 40 + _i + 1
        dt = _f.dtype
        if dt.is_decimal:
            _DTYPES[_f.name] = f"decimal({dt.precision},{dt.scale})"
        elif dt.is_string:
            _DTYPES[_f.name] = "string"
        elif dt.kind.name == "DATE32":
            _DTYPES[_f.name] = "date"
        elif dt.kind.name == "INT32":
            _DTYPES[_f.name] = "integer"
        else:
            _DTYPES[_f.name] = "long"


def a(name: str) -> dict:
    """AttributeReference for a base-table column."""
    return F.attr(name, _IDS[name], _DTYPES[name])


def ar(name: str, i: int, dtype: str = "long") -> dict:
    return F.attr(name, i, dtype)


def and_(*es):
    out = es[0]
    for e in es[1:]:
        out = F.binop("And", out, e)
    return out


def or_(*es):
    out = es[0]
    for e in es[1:]:
        out = F.binop("Or", out, e)
    return out


def in_(child, *vals, dtype="string"):
    return F.T(F.X + "In", [child] + [F.lit(v, dtype) for v in vals])


def ne(l, r):
    return F.un("Not", F.binop("EqualTo", l, r))


def i32(v):
    return F.lit(v, "integer")


def s(v):
    return F.lit(v, "string")


def two_stage(groupings, aggs_fns, child, n_parts=N_PARTS, result=None):
    partial = F.hash_agg(
        groupings,
        [F.agg_expr(fn, "Partial", rid) for fn, rid in aggs_fns],
        child,
    )
    part = (
        F.hash_partitioning(groupings, n_parts)
        if groupings
        else F.single_partition()
    )
    ex = F.shuffle(part, partial)
    return F.hash_agg(
        groupings,
        [F.agg_expr(fn, "Final", rid) for fn, rid in aggs_fns],
        ex,
        result=result,
    )


def distinct(groupings, child, n_parts=N_PARTS):
    """Grouping-only two-stage aggregation (Spark's DISTINCT plan)."""
    return two_stage(groupings, [], child, n_parts)


def bhj_build_left(build, probe, bkeys, pkeys, jt="Inner"):
    """BroadcastHashJoin with the (broadcast) build side on the left —
    the common dimension-table shape."""
    return F.bhj(bkeys, pkeys, jt, "left", F.broadcast(build), probe)


def semi_right(probe, build, pkeys, bkeys, jt="LeftSemi"):
    """probe LEFT SEMI JOIN broadcast(build) — output = probe columns."""
    return F.bhj(pkeys, bkeys, jt, "right", probe, F.broadcast(build))


def existence_right(probe, build, pkeys, bkeys, exists_attr):
    """probe ExistenceJoin broadcast(build): appends the exists flag."""
    return F.bhj(
        pkeys, bkeys, F.existence_join_type(exists_attr), "right",
        probe, F.broadcast(build),
    )


# ----------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def sess(data):
    sess = BlazeSparkSession(default_parallelism=N_PARTS)
    for name in TPCDS_SCHEMAS:
        sess.register_table(
            name,
            MemoryScanExec(
                table_to_batches(data[name], TPCDS_SCHEMAS[name], N_PARTS,
                                 batch_rows=4096),
                TPCDS_SCHEMAS[name],
            ),
        )
    return sess


def _execute_both(sess, plan):
    """In-process collect AND the stage scheduler (TaskDefinition
    protobuf boundary + shuffle files) must agree."""
    js = json.dumps(F.flatten(plan))
    got = sess.execute(js)
    got_sched = sess.execute_distributed(js)
    rows = sorted(
        zip(*got.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got else []
    rows_sched = sorted(
        zip(*got_sched.values()), key=lambda r: tuple((v is None, v) for v in r)
    ) if got_sched else []
    assert rows == rows_sched, "in-process vs scheduler mismatch"
    return got


# ------------------------------------------------------- star joins (q42/q52)

def _brand_report_plan(*, year, moy, manager, order_year_first):
    dt = F.project(
        [a("d_date_sk"), a("d_year")],
        F.filter_(
            and_(F.binop("EqualTo", a("d_moy"), i32(moy)),
                 F.binop("EqualTo", a("d_year"), i32(year))),
            F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")]),
        ),
    )
    sales = F.scan(
        "store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_ext_sales_price")]
    )
    j1 = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    it = F.project(
        [a("i_item_sk"), a("i_brand_id"), a("i_brand")],
        F.filter_(
            F.binop("EqualTo", a("i_manager_id"), i32(manager)),
            F.scan("item", [a("i_item_sk"), a("i_brand_id"), a("i_brand"),
                            a("i_manager_id")]),
        ),
    )
    j2 = bhj_build_left(it, j1, [a("i_item_sk")], [a("ss_item_sk")])
    agg = two_stage(
        [a("d_year"), a("i_brand_id"), a("i_brand")],
        [(F.sum_(a("ss_ext_sales_price")), 501)],
        j2,
    )
    price = ar("ext_price", 501, "decimal(17,2)")
    orders = (
        [F.sort_order(a("d_year")), F.sort_order(price, asc=False),
         F.sort_order(a("i_brand_id"))]
        if order_year_first
        else [F.sort_order(price, asc=False), F.sort_order(a("i_brand_id"))]
    )
    return F.take_ordered(
        100, orders,
        [F.alias(a("d_year"), "d_year", 510),
         F.alias(a("i_brand_id"), "brand_id", 511),
         F.alias(a("i_brand"), "brand", 512),
         F.alias(price, "ext_price", 513)],
        agg,
    )


def test_spark_q52(sess, data):
    got = _execute_both(
        sess, _brand_report_plan(year=2000, moy=11, manager=1, order_year_first=True)
    )
    _check_brand_report(got, O.oracle_q52(data), "ext_price")


def test_spark_q55(sess, data):
    got = _execute_both(
        sess, _brand_report_plan(year=1999, moy=11, manager=28, order_year_first=False)
    )
    exp = O.oracle_q55(data)
    rows = {
        (y, bid, bname): v
        for y, bid, bname, v in zip(got["d_year"], got["brand_id"],
                                    got["brand"], got["ext_price"])
    }
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


def test_spark_q42(sess, data):
    dt = F.project(
        [a("d_date_sk"), a("d_year")],
        F.filter_(
            and_(F.binop("EqualTo", a("d_moy"), i32(11)),
                 F.binop("EqualTo", a("d_year"), i32(2000))),
            F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")]),
        ),
    )
    sales = F.scan(
        "store_sales", [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_ext_sales_price")]
    )
    j1 = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    it = F.project(
        [a("i_item_sk"), a("i_category_id"), a("i_category")],
        F.filter_(
            F.binop("EqualTo", a("i_manager_id"), i32(1)),
            F.scan("item", [a("i_item_sk"), a("i_category_id"), a("i_category"),
                            a("i_manager_id")]),
        ),
    )
    j2 = bhj_build_left(it, j1, [a("i_item_sk")], [a("ss_item_sk")])
    agg = two_stage(
        [a("d_year"), a("i_category_id"), a("i_category")],
        [(F.sum_(a("ss_ext_sales_price")), 501)],
        j2,
    )
    sum_agg = ar("sum_agg", 501, "decimal(17,2)")
    plan = F.take_ordered(
        100,
        [F.sort_order(sum_agg, asc=False), F.sort_order(a("d_year")),
         F.sort_order(a("i_category_id")), F.sort_order(a("i_category"))],
        [F.alias(a("d_year"), "d_year", 510),
         F.alias(a("i_category_id"), "category_id", 511),
         F.alias(a("i_category"), "category", 512),
         F.alias(sum_agg, "sum_agg", 513)],
        agg,
    )
    got = _execute_both(sess, plan)
    _check_brand_report(got, O.oracle_q42(data), "sum_agg",
                        id_col="category_id", name_col="category")
    assert got["sum_agg"] == sorted(got["sum_agg"], reverse=True)


# --------------------------------------------------- rollup / Expand (q27/q36)

def test_spark_q27(sess, data):
    cd = F.project(
        [a("cd_demo_sk")],
        F.filter_(
            and_(F.binop("EqualTo", a("cd_gender"), s("M")),
                 F.binop("EqualTo", a("cd_marital_status"), s("S")),
                 F.binop("EqualTo", a("cd_education_status"), s("College"))),
            F.scan("customer_demographics",
                   [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
                    a("cd_education_status")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2002)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    st = F.project(
        [a("s_store_sk"), a("s_state")],
        F.filter_(in_(a("s_state"), "TN", "SD", "AL", "GA", "OH"),
                  F.scan("store", [a("s_store_sk"), a("s_state")])),
    )
    sales = F.scan(
        "store_sales",
        [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_cdemo_sk"), a("ss_store_sk"),
         a("ss_quantity"), a("ss_list_price"), a("ss_sales_price"),
         a("ss_coupon_amt")],
    )
    j = bhj_build_left(cd, sales, [a("cd_demo_sk")], [a("ss_cdemo_sk")])
    j = bhj_build_left(dt, j, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(st, j, [a("s_store_sk")], [a("ss_store_sk")])
    it = F.scan("item", [a("i_item_sk"), a("i_item_id")])
    j = bhj_build_left(it, j, [a("i_item_sk")], [a("ss_item_sk")])

    # ROLLUP(i_item_id, s_state): Expand with null-filled projections
    # and fresh output ids for the rollup dims + grouping id
    vals = [a("ss_quantity"), a("ss_list_price"), a("ss_coupon_amt"),
            a("ss_sales_price")]
    null_s = F.lit(None, "string")
    exp_item = ar("i_item_id", 520, "string")
    exp_state = ar("s_state", 521, "string")
    exp_gid = ar("spark_grouping_id", 522, "integer")
    expand = F.expand(
        [
            vals + [a("i_item_id"), a("s_state"), F.lit(0, "integer")],
            vals + [a("i_item_id"), null_s, F.lit(1, "integer")],
            vals + [null_s, null_s, F.lit(3, "integer")],
        ],
        vals + [exp_item, exp_state, exp_gid],
        j,
    )
    agg = two_stage(
        [exp_item, exp_state, exp_gid],
        [(F.avg(a("ss_quantity")), 501), (F.avg(a("ss_list_price")), 502),
         (F.avg(a("ss_coupon_amt")), 503), (F.avg(a("ss_sales_price")), 504)],
        expand,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(exp_item), F.sort_order(exp_state)],
        [F.alias(exp_item, "i_item_id", 530),
         F.alias(exp_state, "s_state", 531),
         F.alias(exp_gid, "g_id", 532),
         F.alias(ar("agg1", 501, "double"), "agg1", 533),
         F.alias(ar("agg2", 502, "decimal(11,6)"), "agg2", 534),
         F.alias(ar("agg3", 503, "decimal(11,6)"), "agg3", 535),
         F.alias(ar("agg4", 504, "decimal(11,6)"), "agg4", 536)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q27(data)
    assert got["i_item_id"], "q27 returned no rows"
    for iid, state, gid, a1, a2, a3, a4 in zip(
        got["i_item_id"], got["s_state"], got["g_id"],
        got["agg1"], got["agg2"], got["agg3"], got["agg4"],
    ):
        key = (iid, state, gid)
        assert key in exp, key
        ea1, ea2, ea3, ea4 = exp[key]
        assert abs(a1 - ea1) < 1e-9 and (a2, a3, a4) == (ea2, ea3, ea4), key
    assert set(got["g_id"]) <= {0, 1, 3}


def test_spark_q36(sess, data):
    st = F.project(
        [a("s_store_sk")],
        F.filter_(in_(a("s_state"), "TN", "SD", "AL", "GA", "OH"),
                  F.scan("store", [a("s_store_sk"), a("s_state")])),
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2001)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    it = F.scan("item", [a("i_item_sk"), a("i_class"), a("i_category")])
    sales = F.scan(
        "store_sales",
        [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_store_sk"),
         a("ss_ext_sales_price"), a("ss_net_profit")],
    )
    j = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(st, j, [a("s_store_sk")], [a("ss_store_sk")])
    j = bhj_build_left(it, j, [a("i_item_sk")], [a("ss_item_sk")])

    null_s = F.lit(None, "string")
    exp_cat = ar("i_category", 520, "string")
    exp_cls = ar("i_class", 521, "string")
    exp_gid = ar("spark_grouping_id", 522, "integer")
    vals = [a("ss_net_profit"), a("ss_ext_sales_price")]
    expand = F.expand(
        [
            vals + [a("i_category"), a("i_class"), F.lit(0, "integer")],
            vals + [a("i_category"), null_s, F.lit(1, "integer")],
            vals + [null_s, null_s, F.lit(3, "integer")],
        ],
        vals + [exp_cat, exp_cls, exp_gid],
        j,
    )
    agg = two_stage(
        [exp_cat, exp_cls, exp_gid],
        [(F.sum_(a("ss_net_profit")), 501),
         (F.sum_(a("ss_ext_sales_price")), 502)],
        expand,
    )
    # lochierarchy + gross-margin measure
    loch = F.T(
        F.X + "CaseWhen",
        [F.binop("EqualTo", exp_gid, i32(0)), i32(0),
         F.binop("EqualTo", exp_gid, i32(1)), i32(1),
         i32(2)],
    )
    num = ar("num_sum", 501, "decimal(17,2)")
    den = ar("den_sum", 502, "decimal(17,2)")
    measure = F.binop("Divide", F.cast(num, "double"), F.cast(den, "double"))
    proj = F.project(
        [F.alias(exp_cat, "i_category", 540), F.alias(exp_cls, "i_class", 541),
         F.alias(loch, "lochierarchy", 542), F.alias(measure, "measure", 543)],
        agg,
    )
    cat_o = ar("i_category", 540, "string")
    cls_o = ar("i_class", 541, "string")
    loch_o = ar("lochierarchy", 542, "integer")
    meas_o = ar("measure", 543, "double")
    parent = F.T(F.X + "CaseWhen",
                 [F.binop("EqualTo", loch_o, i32(0)), cat_o])
    single = F.shuffle(F.single_partition(), proj)
    pre = F.sort(
        [F.sort_order(loch_o), F.sort_order(parent), F.sort_order(meas_o)],
        single,
    )
    w = F.window(
        [F.window_expr(F.rank_fn([meas_o]),
                       F.window_spec([loch_o, parent], [F.sort_order(meas_o)]),
                       "rank_within_parent", 550)],
        [loch_o, parent],
        [F.sort_order(meas_o)],
        pre,
    )
    rank_o = ar("rank_within_parent", 550, "integer")
    plan = F.take_ordered(
        100,
        [F.sort_order(loch_o, asc=False), F.sort_order(parent),
         F.sort_order(rank_o)],
        [F.alias(cat_o, "i_category", 560), F.alias(cls_o, "i_class", 561),
         F.alias(loch_o, "lochierarchy", 562), F.alias(meas_o, "measure", 563),
         F.alias(rank_o, "rank_within_parent", 564)],
        w,
    )
    got = _execute_both(sess, plan)
    _check_rollup_margin(got, O.oracle_q36(data))


def test_spark_q86(sess, data):
    """q36's ROLLUP shape over web_sales: single net-paid measure
    (no denominator), rank within parent by measure DESC."""
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year")])),
    )
    it = F.scan("item", [a("i_item_sk"), a("i_class"), a("i_category")])
    sales = F.scan("web_sales", [a("ws_sold_date_sk"), a("ws_item_sk"),
                                 a("ws_net_paid")])
    j = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ws_sold_date_sk")])
    j = bhj_build_left(it, j, [a("i_item_sk")], [a("ws_item_sk")])

    null_s = F.lit(None, "string")
    exp_cat = ar("i_category", 520, "string")
    exp_cls = ar("i_class", 521, "string")
    exp_gid = ar("spark_grouping_id", 522, "integer")
    vals = [a("ws_net_paid")]
    expand = F.expand(
        [
            vals + [a("i_category"), a("i_class"), F.lit(0, "integer")],
            vals + [a("i_category"), null_s, F.lit(1, "integer")],
            vals + [null_s, null_s, F.lit(3, "integer")],
        ],
        vals + [exp_cat, exp_cls, exp_gid],
        j,
    )
    agg = two_stage(
        [exp_cat, exp_cls, exp_gid],
        [(F.sum_(a("ws_net_paid")), 501)],
        expand,
    )
    loch = F.T(
        F.X + "CaseWhen",
        [F.binop("EqualTo", exp_gid, i32(0)), i32(0),
         F.binop("EqualTo", exp_gid, i32(1)), i32(1),
         i32(2)],
    )
    measure = F.cast(ar("num_sum", 501, "decimal(17,2)"), "double")
    proj = F.project(
        [F.alias(exp_cat, "i_category", 540), F.alias(exp_cls, "i_class", 541),
         F.alias(loch, "lochierarchy", 542), F.alias(measure, "measure", 543)],
        agg,
    )
    cat_o = ar("i_category", 540, "string")
    cls_o = ar("i_class", 541, "string")
    loch_o = ar("lochierarchy", 542, "integer")
    meas_o = ar("measure", 543, "double")
    parent = F.T(F.X + "CaseWhen",
                 [F.binop("EqualTo", loch_o, i32(0)), cat_o])
    single = F.shuffle(F.single_partition(), proj)
    pre = F.sort(
        [F.sort_order(loch_o), F.sort_order(parent),
         F.sort_order(meas_o, asc=False)],
        single,
    )
    w = F.window(
        [F.window_expr(F.rank_fn([meas_o]),
                       F.window_spec([loch_o, parent],
                                     [F.sort_order(meas_o, asc=False)]),
                       "rank_within_parent", 550)],
        [loch_o, parent],
        [F.sort_order(meas_o, asc=False)],
        pre,
    )
    rank_o = ar("rank_within_parent", 550, "integer")
    plan = F.take_ordered(
        100,
        [F.sort_order(loch_o, asc=False), F.sort_order(parent),
         F.sort_order(rank_o)],
        [F.alias(cat_o, "i_category", 560), F.alias(cls_o, "i_class", 561),
         F.alias(loch_o, "lochierarchy", 562), F.alias(meas_o, "measure", 563),
         F.alias(rank_o, "rank_within_parent", 564)],
        w,
    )
    got = _execute_both(sess, plan)
    _check_rollup_margin(got, O.oracle_q86(data))


# -------------------------------------------------- windows (q47/q89/q98)

def test_spark_q47(sess, data):
    year = 1999
    dt = F.project(
        [a("d_date_sk"), a("d_year"), a("d_moy")],
        F.filter_(
            or_(
                F.binop("EqualTo", a("d_year"), i32(year)),
                and_(F.binop("EqualTo", a("d_year"), i32(year - 1)),
                     F.binop("EqualTo", a("d_moy"), i32(12))),
                and_(F.binop("EqualTo", a("d_year"), i32(year + 1)),
                     F.binop("EqualTo", a("d_moy"), i32(1))),
            ),
            F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")]),
        ),
    )
    st = F.scan("store", [a("s_store_sk"), a("s_store_name"), a("s_company_name")])
    it = F.scan("item", [a("i_item_sk"), a("i_brand"), a("i_category")])
    sales = F.scan(
        "store_sales",
        [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_store_sk"),
         a("ss_sales_price")],
    )
    j = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(st, j, [a("s_store_sk")], [a("ss_store_sk")])
    j = bhj_build_left(it, j, [a("i_item_sk")], [a("ss_item_sk")])
    part = [a("i_category"), a("i_brand"), a("s_store_name"), a("s_company_name")]
    agg = two_stage(
        part + [a("d_year"), a("d_moy")],
        [(F.sum_(a("ss_sales_price")), 501)],
        j,
    )
    sum_sales = ar("sum_sales", 501, "decimal(17,2)")
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort(
        [F.sort_order(p) for p in part]
        + [F.sort_order(a("d_year")), F.sort_order(a("d_moy"))],
        single,
    )
    # avg within (entity, year): whole-partition frame
    w_avg = F.window(
        [F.window_expr(
            F.window_agg(F.avg(sum_sales)),
            F.window_spec(part + [a("d_year")], [],
                          F.window_frame("up", "uf", row=True)),
            "avg_monthly_sales", 502)],
        part + [a("d_year")],
        [],
        pre,
    )
    # lag/lead across the month sequence (year NOT in the partition)
    orders = [F.sort_order(a("d_year")), F.sort_order(a("d_moy"))]
    w = F.window(
        [F.window_expr(F.lag_fn(sum_sales), F.window_spec(part, orders), "psum", 503),
         F.window_expr(F.lead_fn(sum_sales), F.window_spec(part, orders), "nsum", 504)],
        part,
        orders,
        w_avg,
    )
    avg_m = ar("avg_monthly_sales", 502, "decimal(11,6)")
    sum_f = F.cast(sum_sales, "double")
    avg_f = F.cast(avg_m, "double")
    filt = F.filter_(
        and_(
            F.binop("EqualTo", a("d_year"), i32(year)),
            F.binop("GreaterThan", avg_m, i32(0)),
            F.binop(
                "GreaterThan",
                F.binop("Divide", F.un("Abs", F.binop("Subtract", sum_f, avg_f)),
                        avg_f),
                F.lit(0.1, "double"),
            ),
        ),
        w,
    )
    proj = F.project(
        [a("i_category"), a("i_brand"), a("s_store_name"), a("s_company_name"),
         a("d_year"), a("d_moy"), sum_sales, avg_m,
         ar("psum", 503, "decimal(17,2)"), ar("nsum", 504, "decimal(17,2)"),
         F.alias(F.binop("Subtract", sum_f, avg_f), "delta", 510)],
        filt,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(ar("delta", 510, "double")), F.sort_order(a("d_moy"))],
        [F.alias(a("i_category"), "i_category", 520),
         F.alias(a("i_brand"), "i_brand", 521),
         F.alias(a("s_store_name"), "s_store_name", 522),
         F.alias(a("s_company_name"), "s_company_name", 523),
         F.alias(a("d_year"), "d_year", 524),
         F.alias(a("d_moy"), "d_moy", 525),
         F.alias(sum_sales, "sum_sales", 526),
         F.alias(avg_m, "avg_monthly_sales", 527),
         F.alias(ar("psum", 503, "decimal(17,2)"), "psum", 528),
         F.alias(ar("nsum", 504, "decimal(17,2)"), "nsum", 529)],
        proj,
    )
    got = _execute_both(sess, plan)
    _check_yoy(got, O.oracle_q47(data), ("s_store_name", "s_company_name"))


def test_spark_q89(sess, data):
    it = F.project(
        [a("i_item_sk"), a("i_category"), a("i_class"), a("i_brand")],
        F.filter_(
            or_(
                and_(in_(a("i_category"), "Books", "Electronics", "Sports"),
                     in_(a("i_class"), "accessories", "reference", "football")),
                and_(in_(a("i_category"), "Men", "Jewelry", "Women"),
                     in_(a("i_class"), "shirts", "birdal", "dresses")),
            ),
            F.scan("item", [a("i_item_sk"), a("i_class"), a("i_category"),
                            a("i_brand")]),
        ),
    )
    dt = F.project(
        [a("d_date_sk"), a("d_moy")],
        F.filter_(F.binop("EqualTo", a("d_year"), i32(1999)),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")])),
    )
    st = F.scan("store", [a("s_store_sk"), a("s_store_name"), a("s_company_name")])
    sales = F.scan(
        "store_sales",
        [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_store_sk"),
         a("ss_sales_price")],
    )
    j = bhj_build_left(it, sales, [a("i_item_sk")], [a("ss_item_sk")])
    j = bhj_build_left(dt, j, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(st, j, [a("s_store_sk")], [a("ss_store_sk")])
    agg = two_stage(
        [a("i_category"), a("i_class"), a("i_brand"), a("s_store_name"),
         a("s_company_name"), a("d_moy")],
        [(F.sum_(a("ss_sales_price")), 501)],
        j,
    )
    sum_sales = ar("sum_sales", 501, "decimal(17,2)")
    part = [a("i_category"), a("i_brand"), a("s_store_name"), a("s_company_name")]
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort([F.sort_order(p) for p in part], single)
    w = F.window(
        [F.window_expr(
            F.window_agg(F.avg(sum_sales)),
            F.window_spec(part, [], F.window_frame("up", "uf", row=True)),
            "avg_monthly_sales", 502)],
        part,
        [],
        pre,
    )
    avg_m = ar("avg_monthly_sales", 502, "decimal(11,6)")
    sum_f = F.cast(sum_sales, "double")
    avg_f = F.cast(avg_m, "double")
    ratio = F.T(
        F.X + "CaseWhen",
        [ne(avg_f, F.lit(0.0, "double")),
         F.binop("Divide", F.un("Abs", F.binop("Subtract", sum_f, avg_f)), avg_f)],
    )
    filt = F.filter_(F.binop("GreaterThan", ratio, F.lit(0.1, "double")), w)
    proj = F.project(
        [a("i_category"), a("i_class"), a("i_brand"), a("s_store_name"),
         a("s_company_name"), a("d_moy"), sum_sales, avg_m,
         F.alias(F.binop("Subtract", sum_f, avg_f), "delta", 510)],
        filt,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(ar("delta", 510, "double")),
         F.sort_order(a("s_store_name"))],
        [F.alias(a("i_category"), "i_category", 520),
         F.alias(a("i_class"), "i_class", 521),
         F.alias(a("i_brand"), "i_brand", 522),
         F.alias(a("s_store_name"), "s_store_name", 523),
         F.alias(a("s_company_name"), "s_company_name", 524),
         F.alias(a("d_moy"), "d_moy", 525),
         F.alias(sum_sales, "sum_sales", 526),
         F.alias(avg_m, "avg_monthly_sales", 527)],
        proj,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q89(data)
    seen = set()
    for cat, cls, brand, stn, co, moy, sm, avg in zip(
        got["i_category"], got["i_class"], got["i_brand"], got["s_store_name"],
        got["s_company_name"], got["d_moy"], got["sum_sales"],
        got["avg_monthly_sales"],
    ):
        key = (cat, cls, brand, stn, co, moy)
        assert key in exp, key
        assert exp[key] == (sm, avg), key
        seen.add(key)
    if len(exp) <= 100:
        assert seen == set(exp)


def test_spark_q98(sess, data):
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("GreaterThanOrEqual", a("d_date"), F.lit("1999-02-22", "date")),
                 F.binop("LessThanOrEqual", a("d_date"), F.lit("1999-03-24", "date"))),
            F.scan("date_dim", [a("d_date_sk"), a("d_date")]),
        ),
    )
    it = F.project(
        [a("i_item_sk"), a("i_item_id"), a("i_item_desc"), a("i_category"),
         a("i_class"), a("i_current_price")],
        F.filter_(
            in_(a("i_category"), "Sports", "Books", "Home"),
            F.scan("item", [a("i_item_sk"), a("i_item_id"), a("i_item_desc"),
                            a("i_class"), a("i_category"), a("i_current_price")]),
        ),
    )
    sales = F.scan(
        "store_sales",
        [a("ss_sold_date_sk"), a("ss_item_sk"), a("ss_ext_sales_price")],
    )
    j = bhj_build_left(dt, sales, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(it, j, [a("i_item_sk")], [a("ss_item_sk")])
    agg = two_stage(
        [a("i_item_id"), a("i_item_desc"), a("i_category"), a("i_class"),
         a("i_current_price")],
        [(F.sum_(a("ss_ext_sales_price")), 501)],
        j,
    )
    itemrev = ar("itemrevenue", 501, "decimal(17,2)")
    single = F.shuffle(F.single_partition(), agg)
    pre = F.sort([F.sort_order(a("i_class"))], single)
    w = F.window(
        [F.window_expr(
            F.window_agg(F.sum_(itemrev)),
            F.window_spec([a("i_class")], [], F.window_frame("up", "uf", row=True)),
            "class_revenue", 502)],
        [a("i_class")],
        [],
        pre,
    )
    class_rev = ar("class_revenue", 502, "decimal(27,2)")
    ratio = F.binop(
        "Divide",
        F.binop("Multiply", F.cast(itemrev, "double"), F.lit(100.0, "double")),
        F.cast(class_rev, "double"),
    )
    proj = F.project(
        [a("i_item_id"), a("i_item_desc"), a("i_category"), a("i_class"),
         a("i_current_price"), itemrev,
         F.alias(ratio, "revenueratio", 510)],
        w,
    )
    ratio_o = ar("revenueratio", 510, "double")
    sorted_ = F.sort(
        [F.sort_order(a("i_category")), F.sort_order(a("i_class")),
         F.sort_order(a("i_item_id")), F.sort_order(a("i_item_desc")),
         F.sort_order(ratio_o)],
        F.shuffle(F.single_partition(), proj),
    )
    plan = F.project(
        [F.alias(a("i_item_id"), "i_item_id", 520),
         F.alias(a("i_item_desc"), "i_item_desc", 521),
         F.alias(a("i_category"), "i_category", 522),
         F.alias(a("i_class"), "i_class", 523),
         F.alias(a("i_current_price"), "i_current_price", 524),
         F.alias(itemrev, "itemrevenue", 525),
         F.alias(ratio_o, "revenueratio", 526)],
        sorted_,
    )
    got = _execute_both(sess, plan)
    _check_class_share(got, O.oracle_q98(data))


# ------------------------------------------------ INTERSECT family (q8/q38)

def test_spark_q38(sess, data):
    def channel(sales, date_col, cust_col):
        dt = F.project(
            [a("d_date_sk"), a("d_date")],
            F.filter_(F.binop("EqualTo", a("d_year"), i32(2000)),
                      F.scan("date_dim", [a("d_date_sk"), a("d_date"), a("d_year")])),
        )
        cust = F.scan(
            "customer", [a("c_customer_sk"), a("c_first_name"), a("c_last_name")]
        )
        sl = F.scan(sales, [a(date_col), a(cust_col)])
        j = bhj_build_left(dt, sl, [a("d_date_sk")], [a(date_col)])
        j = bhj_build_left(cust, j, [a("c_customer_sk")], [a(cust_col)])
        return distinct([a("c_last_name"), a("c_first_name"), a("d_date")], j)

    ss = channel("store_sales", "ss_sold_date_sk", "ss_customer_sk")
    cs = channel("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk")
    ws = channel("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk")
    keys = [a("c_last_name"), a("c_first_name"), a("d_date")]
    inter = semi_right(ss, cs, keys, keys)
    inter = semi_right(inter, ws, keys, keys)
    plan = two_stage(
        [], [(F.count(), 501)], inter,
        result=[F.alias(ar("count(1)", 501, "long"), "cnt", 510)],
    )
    got = _execute_both(sess, plan)
    assert got["cnt"] == [O.oracle_q38(data)]


def test_spark_q8(sess, data):
    from blaze_tpu.tpcds.queries import Q8_MIN_PREFERRED, Q8_ZIPS

    def zip5(child):
        return F.T(F.X + "Substring", [child, i32(1), i32(5)])

    # A1: literal-list zips, DISTINCT
    ca1 = F.scan("customer_address", [a("ca_address_sk"), a("ca_zip")])
    a1 = distinct(
        [ar("zip5", 601, "string")],
        F.project(
            [F.alias(zip5(a("ca_zip")), "zip5", 601)],
            F.filter_(in_(zip5(a("ca_zip")), *Q8_ZIPS), ca1),
        ),
    )
    # A2: zips with >= N preferred customers (HAVING over a count)
    cust = F.project(
        [a("c_current_addr_sk")],
        F.filter_(F.binop("EqualTo", a("c_preferred_cust_flag"), s("Y")),
                  F.scan("customer", [a("c_customer_sk"), a("c_current_addr_sk"),
                                      a("c_preferred_cust_flag")])),
    )
    ca2 = F.scan("customer_address", [a("ca_address_sk"), a("ca_zip")])
    cj = bhj_build_left(ca2, cust, [a("ca_address_sk")], [a("c_current_addr_sk")])
    a2_agg = two_stage(
        [ar("zip5", 602, "string")],
        [(F.count(), 603)],
        F.project([F.alias(zip5(a("ca_zip")), "zip5", 602)], cj),
    )
    a2 = F.project(
        [ar("zip5", 602, "string")],
        F.filter_(
            F.binop("GreaterThanOrEqual", ar("cnt", 603, "long"),
                    F.lit(Q8_MIN_PREFERRED, "long")),
            a2_agg,
        ),
    )
    inter = semi_right(a1, a2, [ar("zip5", 601, "string")],
                       [ar("zip5", 602, "string")])
    prefixes = distinct(
        [ar("zip2", 604, "string")],
        F.project(
            [F.alias(F.T(F.X + "Substring",
                         [ar("zip5", 601, "string"), i32(1), i32(2)]),
                     "zip2", 604)],
            inter,
        ),
    )
    st = semi_right(
        F.scan("store", [a("s_store_sk"), a("s_store_name"), a("s_zip")]),
        prefixes,
        [F.T(F.X + "Substring", [a("s_zip"), i32(1), i32(2)])],
        [ar("zip2", 604, "string")],
    )
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(and_(F.binop("EqualTo", a("d_year"), i32(1998)),
                       F.binop("EqualTo", a("d_qoy"), i32(2))),
                  F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_qoy")])),
    )
    sl = F.scan("store_sales",
                [a("ss_sold_date_sk"), a("ss_store_sk"), a("ss_net_profit")])
    j = bhj_build_left(dt, sl, [a("d_date_sk")], [a("ss_sold_date_sk")])
    j = bhj_build_left(
        F.project([a("s_store_sk"), a("s_store_name")], st), j,
        [a("s_store_sk")], [a("ss_store_sk")],
    )
    agg = two_stage(
        [a("s_store_name")],
        [(F.sum_(a("ss_net_profit")), 605)],
        j,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a("s_store_name"))],
        [F.alias(a("s_store_name"), "s_store_name", 610),
         F.alias(ar("net_profit", 605, "decimal(17,2)"), "net_profit", 611)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q8(data, Q8_ZIPS, Q8_MIN_PREFERRED)
    assert exp, "q8 oracle matched no stores (datagen too sparse)"
    assert dict(zip(got["s_store_name"], got["net_profit"])) == exp
    assert got["s_store_name"] == sorted(got["s_store_name"])


# --------------------------------------- correlated EXISTS family (q10/q35)

def _active_set_plan(sales, date_col, cust_col, out_id, moy_hi=4):
    """DISTINCT customer sks of a channel in the (2002, moy 1..hi)
    window (q10/q35: hi=4; q69: hi=3)."""
    dt = F.project(
        [a("d_date_sk")],
        F.filter_(
            and_(F.binop("EqualTo", a("d_year"), i32(2002)),
                 F.binop("GreaterThanOrEqual", a("d_moy"), i32(1)),
                 F.binop("LessThanOrEqual", a("d_moy"), i32(moy_hi))),
            F.scan("date_dim", [a("d_date_sk"), a("d_year"), a("d_moy")]),
        ),
    )
    sl = F.scan(sales, [a(date_col), a(cust_col)])
    j = bhj_build_left(dt, sl, [a("d_date_sk")], [a(date_col)])
    return distinct(
        [ar("cust_sk", out_id, "long")],
        F.project([F.alias(a(cust_col), "cust_sk", out_id)], j),
    )


def _exists_or_channels_plan(cust, *, negate=False, moy_hi=4):
    """cust + EXISTS(store) + (web OR catalog) existence flags — the
    LEFT_SEMI + two ExistenceJoin shape Spark plans for correlated
    EXISTS (catalyst appends the exists attrs carried in the join
    type)."""
    ss = _active_set_plan("store_sales", "ss_sold_date_sk", "ss_customer_sk", 601, moy_hi)
    ws = _active_set_plan("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", 602, moy_hi)
    cs = _active_set_plan("catalog_sales", "cs_sold_date_sk", "cs_ship_customer_sk", 603, moy_hi)
    ck = [a("c_customer_sk")]
    j = semi_right(cust, ss, ck, [ar("cust_sk", 601, "long")])
    ex_ws = F.attr("exists", 611, "boolean")
    ex_cs = F.attr("exists", 612, "boolean")
    j = existence_right(j, ws, ck, [ar("cust_sk", 602, "long")], ex_ws)
    j = existence_right(j, cs, ck, [ar("cust_sk", 603, "long")], ex_cs)
    if negate:
        cond = and_(F.un("Not", ex_ws), F.un("Not", ex_cs))
    else:
        cond = or_(ex_ws, ex_cs)
    return F.filter_(cond, j)


def test_spark_q10(sess, data):
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(
            in_(a("ca_county"), "Williamson County", "Franklin Parish",
                "Bronx County"),
            F.scan("customer_address", [a("ca_address_sk"), a("ca_county")]),
        ),
    )
    cust = F.scan(
        "customer",
        [a("c_customer_sk"), a("c_current_addr_sk"), a("c_current_cdemo_sk")],
    )
    cust = semi_right(cust, ca, [a("c_current_addr_sk")], [a("ca_address_sk")])
    act = _exists_or_channels_plan(cust)
    cd = F.scan(
        "customer_demographics",
        [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
         a("cd_education_status"), a("cd_purchase_estimate"),
         a("cd_credit_rating"), a("cd_dep_count"), a("cd_dep_employed_count"),
         a("cd_dep_college_count")],
    )
    j = bhj_build_left(cd, act, [a("cd_demo_sk")], [a("c_current_cdemo_sk")])
    group_cols = ["cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
                  "cd_dep_employed_count", "cd_dep_college_count"]
    agg = two_stage(
        [a(c) for c in group_cols],
        [(F.count(), 620)],
        j,
    )
    plan = F.take_ordered(
        100,
        [F.sort_order(a(c)) for c in group_cols],
        [F.alias(a(c), c, 630 + i) for i, c in enumerate(group_cols)]
        + [F.alias(ar("cnt", 620, "long"), "cnt", 640)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q10(data)
    keys = list(zip(got["cd_gender"], got["cd_marital_status"],
                    got["cd_education_status"], got["cd_purchase_estimate"],
                    got["cd_credit_rating"], got["cd_dep_count"],
                    got["cd_dep_employed_count"], got["cd_dep_college_count"]))
    assert keys and len(set(keys)) == len(keys)
    for k, c in zip(keys, got["cnt"]):
        assert exp.get(k) == c, k
    assert len(keys) == min(len(exp), 100)
    assert keys == sorted(keys)


def test_spark_q35(sess, data):
    ca = F.scan("customer_address", [a("ca_address_sk"), a("ca_state")])
    cust = F.scan(
        "customer",
        [a("c_customer_sk"), a("c_current_addr_sk"), a("c_current_cdemo_sk")],
    )
    cust = bhj_build_left(ca, cust, [a("ca_address_sk")], [a("c_current_addr_sk")])
    act = _exists_or_channels_plan(cust)
    cd = F.scan(
        "customer_demographics",
        [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
         a("cd_dep_count"), a("cd_dep_employed_count"), a("cd_dep_college_count")],
    )
    j = bhj_build_left(cd, act, [a("cd_demo_sk")], [a("c_current_cdemo_sk")])
    group_cols = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
                  "cd_dep_employed_count", "cd_dep_college_count"]
    aggs = [(F.count(), 650)]
    rid = 651
    dep_cols = ("cd_dep_count", "cd_dep_employed_count", "cd_dep_college_count")
    for c in dep_cols:
        aggs += [(F.avg(a(c)), rid), (F.max_(a(c)), rid + 1), (F.sum_(a(c)), rid + 2)]
        rid += 3
    agg = two_stage([a(c) for c in group_cols], aggs, j)
    out_aliases = [F.alias(a(c), c, 700 + i) for i, c in enumerate(group_cols)]
    out_aliases.append(F.alias(ar("cnt1", 650, "long"), "cnt1", 710))
    rid = 651
    for i in range(1, 4):
        out_aliases += [
            F.alias(ar(f"avg{i}", rid, "double"), f"avg{i}", 710 + 3 * i - 2),
            F.alias(ar(f"max{i}", rid + 1, "integer"), f"max{i}", 710 + 3 * i - 1),
            F.alias(ar(f"sum{i}", rid + 2, "long"), f"sum{i}", 710 + 3 * i),
        ]
        rid += 3
    plan = F.take_ordered(
        100,
        [F.sort_order(a(c)) for c in group_cols],
        out_aliases,
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q35(data)
    keys = list(zip(got["ca_state"], got["cd_gender"], got["cd_marital_status"],
                    got["cd_dep_count"], got["cd_dep_employed_count"],
                    got["cd_dep_college_count"]))
    assert keys and len(set(keys)) == len(keys)
    for i, k in enumerate(keys):
        assert k in exp, k
        e = exp[k]
        assert got["cnt1"][i] == e[0], k
        for j_ in range(3):
            assert abs(got[f"avg{j_+1}"][i] - e[1 + 3 * j_]) < 1e-9, k
            assert got[f"max{j_+1}"][i] == e[2 + 3 * j_], k
            assert got[f"sum{j_+1}"][i] == e[3 + 3 * j_], k
    if len(exp) <= 100:
        assert set(keys) == set(exp)


def test_spark_q69(sess, data):
    """q10's existence shape with NEGATED flags (NOT EXISTS web AND
    NOT EXISTS catalog) over state-resident in-store customers."""
    ca = F.project(
        [a("ca_address_sk")],
        F.filter_(in_(a("ca_state"), "TN", "SD", "AL"),
                  F.scan("customer_address", [a("ca_address_sk"),
                                              a("ca_state")])),
    )
    cust = F.scan(
        "customer",
        [a("c_customer_sk"), a("c_current_addr_sk"), a("c_current_cdemo_sk")],
    )
    cust = semi_right(cust, ca, [a("c_current_addr_sk")], [a("ca_address_sk")])
    act = _exists_or_channels_plan(cust, negate=True, moy_hi=3)
    cd = F.scan(
        "customer_demographics",
        [a("cd_demo_sk"), a("cd_gender"), a("cd_marital_status"),
         a("cd_education_status"), a("cd_purchase_estimate"),
         a("cd_credit_rating")],
    )
    j = bhj_build_left(cd, act, [a("cd_demo_sk")], [a("c_current_cdemo_sk")])
    group_cols = ["cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating"]
    agg = two_stage([a(c) for c in group_cols], [(F.count(), 620)], j)
    plan = F.take_ordered(
        100,
        [F.sort_order(a(c)) for c in group_cols],
        [F.alias(a(c), c, 630 + i) for i, c in enumerate(group_cols)]
        + [F.alias(ar("cnt", 620, "long"), "cnt", 640)],
        agg,
    )
    got = _execute_both(sess, plan)
    exp = O.oracle_q69(data)
    keys = list(zip(got["cd_gender"], got["cd_marital_status"],
                    got["cd_education_status"], got["cd_purchase_estimate"],
                    got["cd_credit_rating"]))
    assert keys and len(set(keys)) == len(keys)
    for k, c in zip(keys, got["cnt"]):
        assert exp.get(k) == c, k
    assert len(keys) == min(len(exp), 100)
    assert keys == sorted(keys)


def test_spark351_dump_ds_q27_rollup(sess, data):
    """Real-format TPC-DS q27: ExpandExec carrying Spark's rollup
    projections (nulled grouped-away columns + spark_grouping_id)."""
    import os

    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "spark351_ds_q27_rollup_plan.json")
    with open(path) as f:
        js = f.read()
    assert '"jvmId"' in js and "ExpandExec" in js and "spark_grouping_id" in js
    got = sess.execute(js)
    exp = O.oracle_q27(data)
    assert got["i_item_id"], "no rows"
    for iid, state, gid, a1, a2, a3, a4 in zip(
        got["i_item_id"], got["s_state"], got["g_id"],
        got["agg1"], got["agg2"], got["agg3"], got["agg4"],
    ):
        key = (iid, state, gid)
        assert key in exp, key
        ea1, ea2, ea3, ea4 = exp[key]
        assert abs(a1 - ea1) < 1e-9 and (a2, a3, a4) == (ea2, ea3, ea4), key
