"""Runtime statistics observatory (runtime/stats.py):

1. **Estimator units** — cold cardinality estimates from MemoryScan
   lengths with the documented default selectivities (filter x0.25,
   grouped agg x0.1, scalar agg -> 1 row), stamped as
   ``est_rows``/``est_bytes`` in every node's MetricsSet.
2. **Q-error math** — ``max(est/act, act/est)``, None on an
   unobserved side.
3. **HyperLogLog** — accuracy within the p=12 error envelope, merge =
   union, JSON round-trip, corrupt register list rejected.
4. **Skew histograms** — per-partition exchange histograms accumulate
   across map tasks; flush names the hot partition iff BOTH the ratio
   and min-rows gates pass.
5. **Store** — persist/reuse across two real processes (the warm
   process's estimates converge on the cold process's actuals),
   stale-source and corrupt-entry invalidation, FATAL retry class.
6. **Disarmed** — structural no-op: a poisoned sketch hook proves the
   disarmed agg path never touches sketch state.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import Col
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.ops.project import ProjectExec
from blaze_tpu.runtime import dispatch, retry, stats
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([Field("k", DataType.int64()),
                 Field("v", DataType.float64())])


@pytest.fixture(autouse=True)
def _armed(tmp_path):
    """Arm stats with an isolated store dir; restore defaults after."""
    conf.STATS_ENABLED.set(True)
    conf.STATS_STORE_ENABLED.set(True)
    conf.STATS_STORE_DIR.set(str(tmp_path / "store"))
    stats.reset()
    yield
    conf.STATS_ENABLED.set(True)
    conf.STATS_SKETCHES.set(False)
    conf.STATS_STORE_ENABLED.set(True)
    conf.STATS_STORE_DIR.set("")
    conf.STATS_SKEW_RATIO.set(4.0)
    conf.STATS_SKEW_MIN_ROWS.set(4096)
    stats.reset()


def _batch(n=400, seed=3, n_keys=50):
    rng = np.random.RandomState(seed)
    return batch_from_pydict(
        {"k": rng.randint(0, n_keys, n).tolist(),
         "v": rng.rand(n).round(3).tolist()}, SCHEMA)


def _run(plan):
    out = []
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            out.append(b)
            np.asarray(b.columns[0].data)
    return out


def _est(node):
    return node.metrics.snapshot().get("est_rows")


# --------------------------------------------------- 1. estimator units

def test_estimator_stamps_scan_filter_project():
    scan = MemoryScanExec([[_batch(400)]], SCHEMA)
    f = FilterExec(scan, col("v") > lit(0.5))
    plan = ProjectExec(f, [col("k").alias("k")])
    stats.annotate(plan, None)
    assert _est(scan) == 400                       # exact: scan length
    assert _est(f) == 100                          # 400 * 0.25
    assert _est(plan) == 100                       # pass-through
    assert scan.metrics.snapshot()["est_bytes"] > 0


def test_estimator_agg_selectivities():
    scan = MemoryScanExec([[_batch(400)]], SCHEMA)
    grouped = AggExec(scan, AggMode.PARTIAL,
                      [GroupingExpr(Col("k"), "k")],
                      [AggFunction("sum", Col("v"), "s")])
    stats.annotate(grouped, None)
    assert _est(grouped) == 40                     # 400 * 0.1

    scan2 = MemoryScanExec([[_batch(400)]], SCHEMA)
    scalar = AggExec(scan2, AggMode.PARTIAL, [],
                     [AggFunction("sum", Col("v"), "s")])
    stats.annotate(scalar, None)
    assert _est(scalar) == 1                       # global agg: one row


def test_estimator_disarmed_never_stamps():
    conf.STATS_ENABLED.set(False)
    stats.refresh()
    scan = MemoryScanExec([[_batch(64)]], SCHEMA)
    stats.annotate(scan, None)
    assert _est(scan) is None


# ------------------------------------------------------ 2. Q-error math

def test_q_error_math():
    assert stats.q_error(10, 10) == 1.0
    assert stats.q_error(5, 20) == 4.0
    assert stats.q_error(20, 5) == 4.0             # symmetric
    assert stats.q_error(0, 5) is None             # unobserved side
    assert stats.q_error(5, 0) is None


# -------------------------------------------------------------- 3. HLL

def test_hll_accuracy_and_merge():
    n = 60_000
    h = stats._mix64(np.arange(1, n + 1, dtype=np.uint64))
    hll = stats.HyperLogLog()
    hll.update_hashed(h)
    est = hll.estimate()
    # p=12 standard error ~1.6%; 10% is > 6 sigma
    assert abs(est - n) / n < 0.10

    a, b = stats.HyperLogLog(), stats.HyperLogLog()
    a.update_hashed(h[: n // 2])
    b.update_hashed(h[n // 3:])                    # overlapping halves
    a.merge(b)
    merged = a.estimate()
    assert abs(merged - n) / n < 0.10              # merge == union


def test_hll_json_roundtrip_and_corrupt_registers():
    hll = stats.HyperLogLog()
    hll.update_hashed(stats._mix64(np.arange(1, 5000, dtype=np.uint64)))
    back = stats.HyperLogLog.from_list(hll.to_list())
    assert back.estimate() == hll.estimate()
    with pytest.raises(stats.StatsStoreCorruptError):
        stats.HyperLogLog.from_list([0, 1, 2])     # wrong register count


# --------------------------------------------- 4. skew histograms

def test_skew_finding_names_hot_partition():
    conf.STATS_SKEW_RATIO.set(3.0)
    conf.STATS_SKEW_MIN_ROWS.set(100)
    stats.refresh()
    # two map tasks of the same shuffle fold into ONE histogram
    stats.note_exchange("shuffle_9", "ShuffleWriterExec",
                        [2500, 10, 12, 8], [20000, 80, 96, 64])
    stats.note_exchange("shuffle_9", "ShuffleWriterExec",
                        [2500, 10, 12, 8], [20000, 80, 96, 64])
    summary = stats.flush("skewq")
    assert summary["skew_ratio"] > 3.0
    assert len(summary["findings"]) == 1
    f = summary["findings"][0]
    assert f["exchange"] == "shuffle_9"
    assert f["partition"] == 0                     # the seeded hot slot
    assert f["rows"] == 5000
    assert f["partitions"] == 4
    # the registry surface serves the same finding
    assert stats.recent_findings()[-1]["partition"] == 0


def test_skew_gates_min_rows_and_ratio():
    conf.STATS_SKEW_RATIO.set(3.0)
    conf.STATS_SKEW_MIN_ROWS.set(100)
    stats.refresh()
    # hot partition below the min-rows floor: ratio alone is not enough
    stats.note_exchange("shuffle_1", "op", [50, 2, 2, 2], [400, 16, 16, 16])
    s = stats.flush("small")
    assert s["findings"] == [] and s["skew_ratio"] > 3.0
    # balanced exchange: no finding either
    stats.note_exchange("shuffle_2", "op", [500, 480, 510, 505], [1] * 4)
    s = stats.flush("balanced")
    assert s["findings"] == []


def test_exchange_key_merges_map_outputs():
    assert stats.exchange_key("/tmp/x/shuffle_3_7.data") == "shuffle_3"
    assert stats.exchange_key("/tmp/x/shuffle_3_11.data") == "shuffle_3"


# ------------------------------------------------------------ 5. store

_ROUNDTRIP = """
import json, sys
import numpy as np
from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.runtime import dispatch, stats
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

conf.STATS_ENABLED.set(True)
conf.STATS_STORE_ENABLED.set(True)
conf.STATS_STORE_DIR.set(sys.argv[1])
stats.reset()
schema = Schema([Field("k", DataType.int64()),
                 Field("v", DataType.float64())])
rng = np.random.RandomState(7)
b = batch_from_pydict({"k": rng.randint(0, 50, 512).tolist(),
                       "v": rng.rand(512).round(3).tolist()}, schema)
scan = MemoryScanExec([[b]], schema)
with dispatch.capture() as caps:
    plan = optimize_plan(FilterExec(scan, col("v") > lit(0.5)))
    for p in range(plan.num_partitions()):
        for out in plan.execute(p, TaskContext(p, plan.num_partitions())):
            np.asarray(out.columns[0].data)
summary = stats.flush("roundtrip")
print(json.dumps({"summary": summary,
                  "hits": caps.get("stats_store_hits", 0)}))
"""


def _run_roundtrip(script_path, store_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    out = subprocess.run(
        [sys.executable, script_path, store_dir],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_store_roundtrip_across_two_processes(tmp_path):
    """Cold process persists observed actuals; a SECOND process with
    the identical workload reuses them — its estimates converge on the
    cold run's truth (Q-error collapses to 1.0)."""
    script = tmp_path / "roundtrip.py"
    script.write_text(_ROUNDTRIP)
    store = str(tmp_path / "store2")
    cold = _run_roundtrip(str(script), store)
    assert cold["summary"]["persisted"] >= 1
    assert cold["summary"]["qerror_max"] > 1.5     # x0.25 guess vs ~50%
    assert cold["hits"] == 0
    warm = _run_roundtrip(str(script), store)
    assert warm["hits"] >= 1                       # stats_store_hits
    assert warm["summary"]["qerror_max"] is not None
    assert warm["summary"]["qerror_max"] <= 1.001  # converged on actuals
    assert warm["summary"]["qerror_max"] < cold["summary"]["qerror_max"]


def _fp(digest, sources):
    return types.SimpleNamespace(digest=digest, exact=True, sources=sources)


def test_store_stale_source_invalidation():
    digest = "ab" * 32
    assert stats._store_write(digest, (("mem", "1", 0),), {"1": 100},
                              {"0": {"op": "MemoryScanExec",
                                     "rows": 5, "bytes": 40}})
    stats.reset()  # the write primed the cache; force a real file read
    with dispatch.capture() as caps:
        rec = stats._store_lookup(_fp(digest, (("mem", "1", 0),)),
                                  {"1": 100})
    assert rec is not None and rec["nodes"]["0"]["rows"] == 5
    assert caps.get("stats_store_hits") == 1

    stats.reset()  # drop the per-process store cache, keep the file
    # source epoch bumped (MemoryScan replace): entry must NOT serve
    with dispatch.capture() as caps:
        rec = stats._store_lookup(_fp(digest, (("mem", "1", 1),)),
                                  {"1": 100})
    assert rec is None
    assert caps.get("stats_store_invalidations") == 1
    assert not os.path.exists(stats.store_path(digest))  # dropped


def test_store_mem_rows_mismatch_invalidates():
    digest = "cd" * 32
    assert stats._store_write(digest, (("mem", "1", 0),), {"1": 100},
                              {"0": {"op": "X", "rows": 5, "bytes": 40}})
    stats.reset()
    with dispatch.capture() as caps:
        rec = stats._store_lookup(_fp(digest, (("mem", "1", 0),)),
                                  {"1": 999})      # scan grew in place
    assert rec is None
    assert caps.get("stats_store_invalidations") == 1


def test_store_corrupt_entry_dropped_and_fatal_class():
    digest = "ef" * 32
    os.makedirs(stats.store_dir(), exist_ok=True)
    with open(stats.store_path(digest), "w") as f:
        f.write("{not json")
    with dispatch.capture() as caps:
        rec = stats._store_lookup(_fp(digest, ()), {})
    assert rec is None
    assert caps.get("stats_store_invalidations") == 1
    assert not os.path.exists(stats.store_path(digest))
    # the error class itself is FATAL for the retry ladder: a corrupt
    # artifact must never be retried into
    assert retry.classify(stats.StatsStoreCorruptError("x")) == retry.FATAL


def test_flush_persists_and_warm_overlay_in_process():
    """Same-process store round-trip through the real optimize_plan
    choke point: flush persists, a rebuilt identical plan's estimates
    are the stored actuals."""
    scan = MemoryScanExec([[_batch(512, seed=11)]], SCHEMA)
    plan = optimize_plan(FilterExec(scan, col("v") > lit(0.25)))
    _run(plan)
    s = stats.flush("inproc")
    assert s["persisted"] >= 1
    # SAME served scan instance (same source id + epoch => same
    # fingerprint digest — the repeated-query shape the store keys on)
    plan2 = optimize_plan(FilterExec(scan, col("v") > lit(0.25)))
    _run(plan2)
    s2 = stats.flush("inproc2")
    assert s2["qerror_max"] is not None
    assert s2["qerror_max"] <= 1.001
    stats.discard_pending()


# --------------------------------------------------------- 6. disarmed

def test_disarmed_agg_never_touches_poisoned_sketch(monkeypatch):
    """Structural no-op: stats AND sketches disarmed — a grouped agg
    executes end to end with the sketch hash function poisoned, so any
    touch of the sketch path would explode."""
    conf.STATS_ENABLED.set(False)
    conf.STATS_SKETCHES.set(True)                  # sketches need ARMED too
    stats.refresh()
    monkeypatch.setattr(stats, "group_key_hash",
                        lambda *a, **k: pytest.fail("sketch path entered"))
    plan = optimize_plan(
        AggExec(MemoryScanExec([[_batch(256)]], SCHEMA), AggMode.PARTIAL,
                [GroupingExpr(Col("k"), "k")],
                [AggFunction("sum", Col("v"), "s")]))
    out = _run(plan)
    assert sum(b.num_rows for b in out) > 0
    assert getattr(plan, "_stats_hll", None) is None
    assert stats.flush("disarmed") is None         # flush is a no-op too


def test_armed_sketch_ndv_reaches_store():
    conf.STATS_SKETCHES.set(True)
    stats.refresh()
    assert stats.sketches_enabled()
    plan = optimize_plan(
        AggExec(MemoryScanExec([[_batch(512, n_keys=40)]], SCHEMA),
                AggMode.PARTIAL,
                [GroupingExpr(Col("k"), "k")],
                [AggFunction("sum", Col("v"), "s")]))
    _run(plan)
    s = stats.flush("sketched")
    assert s["persisted"] >= 1
    # the persisted agg node carries the NDV estimate + registers
    entries = [json.load(open(os.path.join(stats.store_dir(), fn)))
               for fn in os.listdir(stats.store_dir())
               if fn.endswith(".json")]
    ndvs = [rec["nodes"][p]["ndv"] for rec in entries
            for p in rec["nodes"] if "ndv" in rec["nodes"][p]]
    assert ndvs, "no NDV sketch persisted"
    assert abs(ndvs[0] - 40) <= 4                  # ~40 distinct keys
