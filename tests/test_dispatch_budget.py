"""Whole-stage fusion regression gates (tier-1, CPU backend).

1. **Dispatch budget**: warm TPC-H q01 must execute in <= 8 XLA
   dispatches per input batch with ZERO recompiles on the second run —
   the q01 collapse (ISSUE 2) that future PRs must not silently
   re-fragment.  A warm hash-shuffle MAP stage over a traceable chain
   through the stage scheduler must execute <= 2 dispatches per batch
   (ISSUE 4's fused shuffle write), also with zero warm recompiles.
2. **Fused-vs-unfused differential**: every tier-1 sample query must
   produce identical results with ``spark.blaze.fusion.enabled=false``
   (the per-operator fallback path stays correct) — including
   generate/expand/window chains and the fused shuffle write, whose
   ``.data``/``.index`` output (spill path included) must be
   byte-identical to the unfused writer's.
3. **Observability plumbing**: the scheduler MetricNode carries the
   ``xla_dispatches`` / ``xla_compiles`` / ``compile_ms`` /
   ``fused_stage_len`` counters per stage.
4. **Deferred agg count**: the fused agg update keeps its accumulator
   occupancy count device-resident — zero scalar syncs gate a dispatch
   on the warm q01 steady state (``fused_agg_stall_syncs``).
"""

import os
import tempfile

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.runtime import dispatch
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

SCALE = 0.01
BATCH_ROWS = 4096
DISPATCH_BUDGET = 8  # per warm input batch (acceptance criterion)


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data, batch_rows=BATCH_ROWS, n_parts=1):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _optimized(q, data, n_parts=1):
    return optimize_plan(build_query(q, _scans(data, n_parts=n_parts), n_parts))


def _run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _rows(d):
    return sorted(zip(*d.values()), key=repr)


def test_q1_warm_dispatch_budget(data):
    """Warm q01: <= 8 dispatches per input batch, zero recompiles.
    Plans are rebuilt between runs exactly like run_task rebuilds them
    per task — the budget holds because kernels are cached
    process-wide, not per exec instance."""
    n_rows = len(data["lineitem"]["l_quantity"][0])
    n_batches = (n_rows + BATCH_ROWS - 1) // BATCH_ROWS
    assert n_batches >= 4, "scale too small to exercise the per-batch loop"

    _run(_optimized("q1", data))  # cold: compiles allowed
    with dispatch.capture() as warm:
        _run(_optimized("q1", data))

    assert warm.get("xla_compiles", 0) == 0, (
        f"warm q01 recompiled: {warm}")
    per_batch = warm.get("xla_dispatches", 0) / n_batches
    assert per_batch <= DISPATCH_BUDGET, (
        f"warm q01 issued {warm.get('xla_dispatches', 0)} dispatches over "
        f"{n_batches} batches ({per_batch:.1f}/batch > {DISPATCH_BUDGET})")


def test_q1_zero_recompiles_across_plan_rebuilds(data):
    """Same-bucket batches never recompile even across fresh plan
    builds (the kernel-cache + shape-bucketing contract the persistent
    compile cache depends on)."""
    _run(_optimized("q1", data))
    with dispatch.capture() as caps:
        for _ in range(2):
            _run(_optimized("q1", data))
    assert caps.get("xla_compiles", 0) == 0


@pytest.mark.parametrize("q", ["q1", "q6", "q19", "q12", "q14"])
def test_fused_vs_unfused_differential_tpch(data, q):
    """spark.blaze.fusion.enabled=false must be result-identical —
    the fallback path every fusion tier rests on."""
    fused = _rows(_run(_optimized(q, data, n_parts=2)))
    conf.FUSION_ENABLE.set(False)
    try:
        unfused = _rows(_run(_optimized(q, data, n_parts=2)))
    finally:
        conf.FUSION_ENABLE.set(True)
    assert fused == unfused


def test_fused_vs_unfused_differential_tpcds():
    from blaze_tpu.tpcds import TPCDS_SCHEMAS, generate_all as ds_gen
    from blaze_tpu.tpcds import build_query as ds_build

    data = ds_gen(0.002)
    def scans():
        return {
            name: MemoryScanExec(
                table_to_batches(data[name], TPCDS_SCHEMAS[name], 1,
                                 batch_rows=BATCH_ROWS),
                TPCDS_SCHEMAS[name],
            )
            for name in TPCDS_SCHEMAS
        }

    def run(q):
        return _rows(_run(optimize_plan(ds_build(q, scans(), 1))))

    for q in ("q3", "q55"):
        fused = run(q)
        conf.FUSION_ENABLE.set(False)
        try:
            unfused = run(q)
        finally:
            conf.FUSION_ENABLE.set(True)
        assert fused == unfused, q


def test_fused_agg_update_off_differential(data):
    """The single-program agg update (spark.blaze.tpu.fusedAggUpdate)
    must agree with the eager pending/doubling path."""
    fused = _rows(_run(_optimized("q1", data)))
    conf.FUSED_AGG_UPDATE.set(False)
    try:
        eager = _rows(_run(_optimized("q1", data)))
    finally:
        conf.FUSED_AGG_UPDATE.set(True)
    assert fused == eager


def test_fused_update_overflow_falls_back_to_eager(data):
    """All-distinct keys overflow the fused update's stacked-state
    bucket on batch 2 (triggering the eager re-merge, which must
    re-bucket to a power-of-two capacity) and push the accumulator
    past one batch bucket (triggering the pending/doubling fallback
    on later batches) — both rare paths stay exact."""
    import numpy as np

    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr
    from blaze_tpu.schema import DataType, Field, Schema

    n = 5 * 2048
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    table = {"k": (np.arange(n, dtype=np.int64), None),
             "v": (np.full(n, 3, dtype=np.int64), None)}
    scan = MemoryScanExec(
        table_to_batches(table, schema, 1, batch_rows=2048), schema)
    agg = AggExec(scan, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
                  [AggFunction("sum", col("v"), "s")])
    seen = {}
    for b in agg.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        for k, s in zip(d["k"], d["s#sum"]):
            seen[k] = seen.get(k, 0) + s
    assert len(seen) == n and all(v == 3 for v in seen.values())


def test_fused_update_rollback_after_eager_interleave_exact():
    """Regression: when the fused path resumes from a state the EAGER
    pending-merge built (a plain RecordBatch), that state must become
    the overflow-rollback base — rebuilding from the pre-merge
    accumulator silently dropped the eager-merged groups (9000 of
    14000 keys surviving in the repro)."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])

    def mk(keys):
        return batch_from_pydict({"k": list(keys), "v": [3] * len(keys)}, schema)

    # seed 2000 distinct (cap 2048); five cap-1024 batches force the
    # stall path then the eager pending merge (5000 rows >= 4096); a
    # cap-8192 batch resumes the fused path and overflows it
    batches = [mk(range(0, 2000))]
    base = 2000
    for _ in range(5):
        batches.append(mk(range(base, base + 1000)))
        base += 1000
    batches.append(mk(range(base, base + 7000)))
    base += 7000

    scan = MemoryScanExec([batches], schema)
    agg = AggExec(scan, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
                  [AggFunction("sum", col("v"), "s")])
    seen = {}
    with dispatch.capture() as cap:
        for b in agg.execute(0, TaskContext(0, 1)):
            d = batch_to_pydict(b)
            for k, s in zip(d["k"], d["s#sum"]):
                seen[k] = seen.get(k, 0) + s
    assert cap.get("fused_agg_rollbacks", 0) >= 1, (
        f"scenario no longer reaches the resumed-overflow rollback: {cap}")
    assert len(seen) == base and all(v == 3 for v in seen.values())


def test_fused_agg_update_no_per_batch_stall(data):
    """The warm q01 fused update never blocks a dispatch on the
    accumulator count: the occupancy scalar stays device-resident, its
    overflow check resolves AFTER the next batch's program is already
    in the device queue (``fused_agg_deferred_syncs``), and no batch
    forces a pre-dispatch fetch or an overflow rollback."""
    _run(_optimized("q1", data))  # warm the kernels
    with dispatch.capture() as warm:
        _run(_optimized("q1", data))
    assert warm.get("fused_agg_deferred_syncs", 0) > 0, warm
    assert warm.get("fused_agg_stall_syncs", 0) == 0, warm
    assert warm.get("fused_agg_rollbacks", 0) == 0, warm


# ------------------------------------ fused shuffle write (tier 5)


def _shuffle_chain_plan(data, n_parts=1):
    """lineitem scan -> filter -> compute projection: the traceable map
    chain a hash shuffle write absorbs."""
    from blaze_tpu.exprs import col
    from blaze_tpu.exprs.ir import Alias, BinOp, Lit
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.schema import DataType

    scan = _scans(data, batch_rows=2048, n_parts=n_parts)["lineitem"]
    f = FilterExec(scan, BinOp(">", col("l_quantity"),
                               Lit(10.0, DataType.float64())))
    return ProjectExec(
        f,
        [col("l_orderkey"),
         Alias(BinOp("+", col("l_linenumber"), Lit(1, DataType.int32())), "ln1"),
         col("l_returnflag")],
        ["l_orderkey", "ln1", "l_returnflag"],
    )


def _write_shuffle(data, n_out=4, budget=None):
    """Run one optimized ShuffleWriterExec map task; returns the
    committed (.data bytes, .index bytes, partition_lengths,
    spill_count)."""
    from blaze_tpu.exprs import col
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec
    from blaze_tpu.runtime.memmgr import MemManager

    d = tempfile.mkdtemp(prefix="blaze_fused_write_")
    data_path, index_path = os.path.join(d, "m.data"), os.path.join(d, "m.index")
    writer = optimize_plan(ShuffleWriterExec(
        _shuffle_chain_plan(data), HashPartitioning([col("l_orderkey")], n_out),
        data_path, index_path,
    ))
    if budget is not None:
        MemManager._global = None
        MemManager.init(budget)
    try:
        list(writer.execute(0, TaskContext(0, 1)))
    finally:
        if budget is not None:
            MemManager._global = None
            MemManager.init(int(conf.HOST_SPILL_BUDGET.get()))
    with open(data_path, "rb") as f:
        blob = f.read()
    with open(index_path, "rb") as f:
        idx = f.read()
    spills = writer.metrics.get("spill_count")
    lengths = writer.partition_lengths
    return blob, idx, lengths, spills


def test_fused_shuffle_write_byte_identical(data):
    """Tier 5 differential: hash pids, per-partition counts, and the
    committed .data/.index pair are byte-identical between the fused
    one-program writer and the unfused chain+hash+sort path."""
    blob_f, idx_f, lengths_f, _ = _write_shuffle(data)
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u, lengths_u, _ = _write_shuffle(data)
    finally:
        conf.FUSION_ENABLE.set(True)
    assert lengths_f == lengths_u
    assert blob_f == blob_u and idx_f == idx_u


def test_fused_shuffle_write_spill_path_byte_identical(data):
    """The spill path (memory pressure mid-map) commits the same bytes
    fused and unfused — the async double-buffered writer preserves
    insertion order and the commit-by-rename contract."""
    blob_f, idx_f, _, spills_f = _write_shuffle(data, budget=60_000)
    assert spills_f > 0, "budget too high to force the spill path"
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u, _, spills_u = _write_shuffle(data, budget=60_000)
    finally:
        conf.FUSION_ENABLE.set(True)
    assert spills_u > 0
    assert blob_f == blob_u and idx_f == idx_u


def test_fused_shuffle_write_sync_writer_byte_identical(data):
    """spark.blaze.shuffle.asyncWrite=false (the synchronous staging
    path) commits identical bytes."""
    blob_a, idx_a, _, _ = _write_shuffle(data)
    conf.SHUFFLE_ASYNC_WRITE.set(False)
    try:
        blob_s, idx_s, _, _ = _write_shuffle(data)
    finally:
        conf.SHUFFLE_ASYNC_WRITE.set(True)
    assert blob_a == blob_s and idx_a == idx_s


def test_shuffle_map_stage_warm_dispatch_budget(data):
    """A warm hash-shuffle map stage over a traceable chain, through
    the stage scheduler (TaskDefinition bytes), executes <= 2 XLA
    dispatches per input batch with zero warm recompiles — the
    ISSUE 4 acceptance criterion (one fused chain+pids+sort+counts
    program per batch, plus slack for per-task constants)."""
    from blaze_tpu.exprs import col
    from blaze_tpu.parallel import HashPartitioning, NativeShuffleExchangeExec
    from blaze_tpu.runtime.metrics import MetricNode
    from blaze_tpu.runtime.scheduler import run_stages, split_stages

    n_parts = 2
    n_rows = len(data["lineitem"]["l_quantity"][0])
    batch_rows = 2048
    # map tasks see ceil(rows_in_part / batch_rows) batches each
    per_part = (n_rows + n_parts - 1) // n_parts
    n_batches = n_parts * ((per_part + batch_rows - 1) // batch_rows)
    assert n_batches >= 4

    def run_once():
        plan = NativeShuffleExchangeExec(
            _shuffle_chain_plan(data, n_parts=n_parts),
            HashPartitioning([col("l_orderkey")], 3),
        )
        stages, manager = split_stages(plan)
        node = MetricNode()
        rows = 0
        for b in run_stages(stages, manager, metrics=node):
            rows += b.num_rows
        assert rows > 0
        return node

    run_once()  # cold: compiles allowed
    node = run_once()
    map_stage = node.child(0).metrics
    assert map_stage.get("xla_compiles") == 0, "warm map stage recompiled"
    per_batch = map_stage.get("xla_dispatches") / n_batches
    assert per_batch <= 2, (
        f"warm map stage issued {map_stage.get('xla_dispatches')} dispatches "
        f"over {n_batches} batches ({per_batch:.2f}/batch > 2)")


# --------------------------- generate / expand / window chains


def _rows_of(plan):
    return _rows(_run(plan))


def test_fused_vs_unfused_generate_chain():
    """explode -> filter -> compute projection collapses into one
    FusedStageExec program; fusion off must match row-for-row."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.exprs.ir import Alias, BinOp, Lit
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.fusion import FusedStageExec
    from blaze_tpu.ops.generate import GenerateExec, NativeGenerator
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.schema import DataType, Field, Schema

    arr_t = DataType.array(DataType.int64(), 4)
    schema = Schema([Field("k", DataType.int64()), Field("xs", arr_t)])
    rows = {"k": list(range(40)),
            "xs": [[i, i + 1, i + 2][: (i % 4)] or None for i in range(40)]}

    def plan():
        scan = MemoryScanExec([[batch_from_pydict(rows, schema)]], schema)
        g = GenerateExec(scan, NativeGenerator("explode", col("xs")), [col("xs")])
        f = FilterExec(g, BinOp(">", col("col"), Lit(5, DataType.int64())))
        return optimize_plan(ProjectExec(
            f, [col("k"), Alias(BinOp("+", col("col"), Lit(1, DataType.int64())), "c1")],
            ["k", "c1"]))

    fused_plan = plan()
    assert isinstance(fused_plan, FusedStageExec), fused_plan.tree_string()
    fused = _rows_of(fused_plan)
    assert fused
    conf.FUSION_ENABLE.set(False)
    try:
        unfused = _rows_of(plan())
    finally:
        conf.FUSION_ENABLE.set(True)
    assert fused == unfused


def test_fused_vs_unfused_expand_chain():
    """expand (grouping-sets style projections) -> filter fuses into
    one program emitting all P projections compacted to a prefix."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.exprs.ir import BinOp, Lit
    from blaze_tpu.ops.expand import ExpandExec
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.fusion import FusedStageExec
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("k", DataType.int64())])
    rows = {"k": list(range(50))}

    def plan():
        scan = MemoryScanExec([[batch_from_pydict(rows, schema)]], schema)
        e = ExpandExec(
            scan,
            [[col("k"), Lit(0, DataType.int64())],
             [BinOp("*", col("k"), Lit(2, DataType.int64())), Lit(1, DataType.int64())]],
            ["v", "tag"],
        )
        return optimize_plan(
            FilterExec(e, BinOp(">", col("v"), Lit(10, DataType.int64()))))

    fused_plan = plan()
    assert isinstance(fused_plan, FusedStageExec), fused_plan.tree_string()
    fused = _rows_of(fused_plan)
    assert fused
    conf.FUSION_ENABLE.set(False)
    try:
        unfused = _rows_of(plan())
    finally:
        conf.FUSION_ENABLE.set(True)
    assert fused == unfused


def test_fused_vs_unfused_window_shuffle_write():
    """A window map-side feeding a hash shuffle write: the writer
    absorbs the window kernel (partition-buffered bottom) + pids +
    sort into one program; files byte-identical to the unfused path."""
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops.sort import SortField
    from blaze_tpu.ops.window import WindowExec, WindowFunction
    from blaze_tpu.parallel.shuffle import HashPartitioning, ShuffleWriterExec
    from blaze_tpu.schema import DataType, Field, Schema

    schema = Schema([Field("g", DataType.int64()), Field("v", DataType.int64())])
    rows = {"g": sorted(i % 5 for i in range(200)),
            "v": [i * 7 % 13 for i in range(200)]}

    def write():
        d = tempfile.mkdtemp(prefix="blaze_window_write_")
        data_path, index_path = os.path.join(d, "m.data"), os.path.join(d, "m.index")
        scan = MemoryScanExec([[batch_from_pydict(rows, schema)]], schema)
        w = WindowExec(scan, [WindowFunction("row_number", "rn")],
                       [col("g")], [SortField(col("v"), True, True)])
        writer = optimize_plan(ShuffleWriterExec(
            w, HashPartitioning([col("g")], 3), data_path, index_path))
        list(writer.execute(0, TaskContext(0, 1)))
        with open(data_path, "rb") as f:
            blob = f.read()
        with open(index_path, "rb") as f:
            idx = f.read()
        return blob, idx, writer

    blob_f, idx_f, writer = write()
    assert writer._fused_write is not None, "window chain not absorbed"
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u, writer_u = write()
        assert writer_u._fused_write is None
    finally:
        conf.FUSION_ENABLE.set(True)
    assert blob_f == blob_u and idx_f == idx_u


def test_fused_vs_unfused_round_robin_write(data):
    """Round-robin partitioning fuses too (pids from a traced offset);
    byte-identical to the unfused arange/sort path."""
    from blaze_tpu.parallel.shuffle import RoundRobinPartitioning, ShuffleWriterExec

    def write():
        d = tempfile.mkdtemp(prefix="blaze_rr_write_")
        data_path, index_path = os.path.join(d, "m.data"), os.path.join(d, "m.index")
        writer = optimize_plan(ShuffleWriterExec(
            _shuffle_chain_plan(data), RoundRobinPartitioning(3),
            data_path, index_path))
        list(writer.execute(0, TaskContext(0, 1)))
        with open(data_path, "rb") as f:
            blob = f.read()
        with open(index_path, "rb") as f:
            idx = f.read()
        return blob, idx

    blob_f, idx_f = write()
    conf.FUSION_ENABLE.set(False)
    try:
        blob_u, idx_u = write()
    finally:
        conf.FUSION_ENABLE.set(True)
    assert blob_f == blob_u and idx_f == idx_u


def test_scheduler_stage_dispatch_counters(data):
    """Per-stage dispatch observability flows through the scheduler
    MetricNode (root totals + per-stage children)."""
    from blaze_tpu.runtime.scheduler import run_stages, split_stages

    plan = build_query("q6", _scans(data, n_parts=2), 2)
    stages, manager = split_stages(plan)
    from blaze_tpu.runtime.metrics import MetricNode

    node = MetricNode()
    rows = 0
    for b in run_stages(stages, manager, metrics=node):
        rows += b.num_rows
    assert rows > 0
    root = node.metrics
    assert root.get("xla_dispatches") > 0
    assert root.get("fused_stage_len") > 0  # run_task fused the map side
    per_stage = [c.metrics.get("xla_dispatches") for c in node.children]
    assert sum(per_stage) == root.get("xla_dispatches")
