"""Whole-stage fusion regression gates (tier-1, CPU backend).

1. **Dispatch budget**: warm TPC-H q01 must execute in <= 8 XLA
   dispatches per input batch with ZERO recompiles on the second run —
   the q01 collapse (ISSUE 2) that future PRs must not silently
   re-fragment.
2. **Fused-vs-unfused differential**: every tier-1 sample query must
   produce identical results with ``spark.blaze.fusion.enabled=false``
   (the per-operator fallback path stays correct).
3. **Observability plumbing**: the scheduler MetricNode carries the
   ``xla_dispatches`` / ``xla_compiles`` / ``compile_ms`` /
   ``fused_stage_len`` counters per stage.
"""

import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.runtime import dispatch
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

SCALE = 0.01
BATCH_ROWS = 4096
DISPATCH_BUDGET = 8  # per warm input batch (acceptance criterion)


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data, batch_rows=BATCH_ROWS, n_parts=1):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _optimized(q, data, n_parts=1):
    return optimize_plan(build_query(q, _scans(data, n_parts=n_parts), n_parts))


def _run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _rows(d):
    return sorted(zip(*d.values()), key=repr)


def test_q1_warm_dispatch_budget(data):
    """Warm q01: <= 8 dispatches per input batch, zero recompiles.
    Plans are rebuilt between runs exactly like run_task rebuilds them
    per task — the budget holds because kernels are cached
    process-wide, not per exec instance."""
    n_rows = len(data["lineitem"]["l_quantity"][0])
    n_batches = (n_rows + BATCH_ROWS - 1) // BATCH_ROWS
    assert n_batches >= 4, "scale too small to exercise the per-batch loop"

    _run(_optimized("q1", data))  # cold: compiles allowed
    with dispatch.capture() as warm:
        _run(_optimized("q1", data))

    assert warm.get("xla_compiles", 0) == 0, (
        f"warm q01 recompiled: {warm}")
    per_batch = warm.get("xla_dispatches", 0) / n_batches
    assert per_batch <= DISPATCH_BUDGET, (
        f"warm q01 issued {warm.get('xla_dispatches', 0)} dispatches over "
        f"{n_batches} batches ({per_batch:.1f}/batch > {DISPATCH_BUDGET})")


def test_q1_zero_recompiles_across_plan_rebuilds(data):
    """Same-bucket batches never recompile even across fresh plan
    builds (the kernel-cache + shape-bucketing contract the persistent
    compile cache depends on)."""
    _run(_optimized("q1", data))
    with dispatch.capture() as caps:
        for _ in range(2):
            _run(_optimized("q1", data))
    assert caps.get("xla_compiles", 0) == 0


@pytest.mark.parametrize("q", ["q1", "q6", "q19", "q12", "q14"])
def test_fused_vs_unfused_differential_tpch(data, q):
    """spark.blaze.fusion.enabled=false must be result-identical —
    the fallback path every fusion tier rests on."""
    fused = _rows(_run(_optimized(q, data, n_parts=2)))
    conf.FUSION_ENABLE.set(False)
    try:
        unfused = _rows(_run(_optimized(q, data, n_parts=2)))
    finally:
        conf.FUSION_ENABLE.set(True)
    assert fused == unfused


def test_fused_vs_unfused_differential_tpcds():
    from blaze_tpu.tpcds import TPCDS_SCHEMAS, generate_all as ds_gen
    from blaze_tpu.tpcds import build_query as ds_build

    data = ds_gen(0.002)
    def scans():
        return {
            name: MemoryScanExec(
                table_to_batches(data[name], TPCDS_SCHEMAS[name], 1,
                                 batch_rows=BATCH_ROWS),
                TPCDS_SCHEMAS[name],
            )
            for name in TPCDS_SCHEMAS
        }

    def run(q):
        return _rows(_run(optimize_plan(ds_build(q, scans(), 1))))

    for q in ("q3", "q55"):
        fused = run(q)
        conf.FUSION_ENABLE.set(False)
        try:
            unfused = run(q)
        finally:
            conf.FUSION_ENABLE.set(True)
        assert fused == unfused, q


def test_fused_agg_update_off_differential(data):
    """The single-program agg update (spark.blaze.tpu.fusedAggUpdate)
    must agree with the eager pending/doubling path."""
    fused = _rows(_run(_optimized("q1", data)))
    conf.FUSED_AGG_UPDATE.set(False)
    try:
        eager = _rows(_run(_optimized("q1", data)))
    finally:
        conf.FUSED_AGG_UPDATE.set(True)
    assert fused == eager


def test_fused_update_overflow_falls_back_to_eager(data):
    """All-distinct keys overflow the fused update's stacked-state
    bucket on batch 2 (triggering the eager re-merge, which must
    re-bucket to a power-of-two capacity) and push the accumulator
    past one batch bucket (triggering the pending/doubling fallback
    on later batches) — both rare paths stay exact."""
    import numpy as np

    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggFunction, AggMode, GroupingExpr
    from blaze_tpu.schema import DataType, Field, Schema

    n = 5 * 2048
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    table = {"k": (np.arange(n, dtype=np.int64), None),
             "v": (np.full(n, 3, dtype=np.int64), None)}
    scan = MemoryScanExec(
        table_to_batches(table, schema, 1, batch_rows=2048), schema)
    agg = AggExec(scan, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
                  [AggFunction("sum", col("v"), "s")])
    seen = {}
    for b in agg.execute(0, TaskContext(0, 1)):
        d = batch_to_pydict(b)
        for k, s in zip(d["k"], d["s#sum"]):
            seen[k] = seen.get(k, 0) + s
    assert len(seen) == n and all(v == 3 for v in seen.values())


def test_scheduler_stage_dispatch_counters(data):
    """Per-stage dispatch observability flows through the scheduler
    MetricNode (root totals + per-stage children)."""
    from blaze_tpu.runtime.scheduler import run_stages, split_stages

    plan = build_query("q6", _scans(data, n_parts=2), 2)
    stages, manager = split_stages(plan)
    from blaze_tpu.runtime.metrics import MetricNode

    node = MetricNode()
    rows = 0
    for b in run_stages(stages, manager, metrics=node):
        rows += b.num_rows
    assert rows > 0
    root = node.metrics
    assert root.get("xla_dispatches") > 0
    assert root.get("fused_stage_len") > 0  # run_task fused the map side
    per_stage = [c.metrics.get("xla_dispatches") for c in node.children]
    assert sum(per_stage) == root.get("xla_dispatches")
