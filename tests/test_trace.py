"""Query-level tracing + structured event log (tier-1, CPU backend).

1. **Reconciliation** (acceptance): a warm TPC-H q01 run with tracing
   enabled produces a JSONL event log whose per-stage
   ``device_time_ns + dispatch_overhead_ns + compile_ns`` never
   exceeds the measured stage wall (no double counting), and
   reconciles with it within 20% on the stage that carries the
   query's compute (tiny stages are fixed host overhead — proto
   serde, file IO — by construction, not kernel cost).
2. **Report**: ``python -m blaze_tpu --report`` renders the
   plan-annotated profile from that log.
3. **Chaos recovery pairing** (acceptance): a seeded fault spec run
   yields an event log where every injected fault pairs with its
   recovery event (task retry or map-stage rerun).
4. **Overhead gating**: with ``spark.blaze.trace.enabled=false`` the
   dispatch hot path takes the pre-existing code path — no span
   allocation, no kernel-timing callback — asserted structurally.
5. **Schema**: every event type round-trips through the golden JSON
   schema (trace_schema.json); schema drift fails tier-1.
6. **MetricsSet/MetricNode thread safety** (regression): concurrent
   add()/child() from worker threads must not lose updates.
"""

import json
import os
import threading

import jsonschema
import pytest

from blaze_tpu import conf
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime import dispatch, trace, trace_report
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

SCALE = 0.05
BATCH_ROWS = 65536


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


def _scans(data, n_parts=1, batch_rows=BATCH_ROWS):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


def _run_traced(data, q, tmp_path, n_parts=1, runs=2, query_id=None,
                batch_rows=BATCH_ROWS):
    """Run ``q`` through the stage scheduler ``runs`` times with
    tracing armed; returns the LAST run's event list (warm when
    runs >= 2: kernels compiled + persistent caches populated)."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        for _ in range(runs):
            with trace.query(query_id or f"trace_{q}") as path:
                stages, manager = split_stages(
                    build_query(q, _scans(data, n_parts, batch_rows), n_parts))
                rows = sum(b.num_rows for b in run_stages(stages, manager))
        assert rows > 0 and path is not None
        return trace.read_events(path), path
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


# --------------------------------------------------- 1. reconciliation

def test_q01_stage_time_reconciles_with_event_log(data, tmp_path):
    events, _ = _run_traced(data, "q1", tmp_path)
    stages = [e for e in events if e["type"] == "stage_complete"]
    assert stages, "no stage_complete events in the log"
    total_wall = sum(e["wall_ns"] for e in stages)
    for e in stages:
        attributed = (e["device_time_ns"] + e["dispatch_overhead_ns"]
                      + e["compile_ns"])
        # the split is measured INSIDE the stage wall: exceeding it by
        # more than clock noise means double counting
        assert attributed <= e["wall_ns"] * 1.2, (
            f"stage {e['stage_id']}: attributed {attributed} > "
            f"1.2x wall {e['wall_ns']}")
    # the stage carrying the query's compute must reconcile two-sided:
    # its wall is kernel-dominated, so the attribution must account
    # for >= 80% of it (the dispatch-floor story is judgeable)
    major = max(stages, key=lambda e: e["wall_ns"])
    assert major["wall_ns"] >= 0.5 * total_wall, (
        "expected one compute-dominant stage in warm q01")
    attributed = (major["device_time_ns"] + major["dispatch_overhead_ns"]
                  + major["compile_ns"])
    assert attributed >= 0.8 * major["wall_ns"], (
        f"dominant stage {major['stage_id']} attributes only "
        f"{attributed / major['wall_ns']:.0%} of its wall "
        f"(device {major['device_time_ns']}, dispatch "
        f"{major['dispatch_overhead_ns']}, compile {major['compile_ns']}, "
        f"wall {major['wall_ns']})")
    assert major["programs"] > 0


def test_trace_covers_lifecycle_and_attribution(data, tmp_path):
    events, _ = _run_traced(data, "q1", tmp_path)
    types = {e["type"] for e in events}
    assert {"query_start", "query_end", "stage_submit", "stage_complete",
            "task_attempt_start", "task_attempt_end", "task_kernels",
            "task_plan", "shuffle_write", "shuffle_fetch"} <= types
    # kernel costs land on operator labels, not one anonymous bucket
    kernels = [e for e in events if e["type"] == "task_kernels"]
    labels = {lbl for e in kernels for lbl in e["kernels"]}
    assert "agg_update" in labels or "agg" in labels
    # the plan-annotated tree carries per-node metrics
    plans = [e for e in events if e["type"] == "task_plan"]
    assert any("AggExec" in json.dumps(e["plan"]) for e in plans)
    assert any(e["plan"]["metrics"] or any(
        c["metrics"] for c in e["plan"]["children"]) for e in plans)


# ----------------------------------------------------------- 2. report

def test_report_cli_renders_profile(data, tmp_path):
    _, path = _run_traced(data, "q1", tmp_path, runs=1)
    import contextlib
    import io

    from blaze_tpu.__main__ import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--report", path])
    assert rc == 0
    out = buf.getvalue()
    assert "stage timeline" in out
    assert "dispatch" in out and "device" in out
    assert "plan (stage" in out and "AggExec" in out
    assert "shuffle write" in out


def test_report_cli_missing_log(tmp_path):
    from blaze_tpu.__main__ import main

    assert main(["--report", str(tmp_path / "nope.jsonl")]) == 2


# -------------------------------------------- 3. chaos recovery pairing

def test_chaos_event_log_pairs_faults_with_recovery(data, tmp_path):
    """Acceptance: a seeded fault spec leaves an event log containing
    every injected fault paired with its recovery event — a plain task
    retry for compute/write faults, a map-stage rerun for the fetch
    fault."""
    from blaze_tpu.runtime import faults

    conf.FAULTS_SPEC.set("task.compute@1@a0,shuffle.fetch@2@a0")
    conf.TASK_RETRY_BACKOFF.set(0.0)
    faults.reset()
    try:
        events, _ = _run_traced(data, "q6", tmp_path, n_parts=2, runs=1,
                                query_id="chaos_q6", batch_rows=16384)
    finally:
        conf.FAULTS_SPEC.set("")
        conf.TASK_RETRY_BACKOFF.set(0.1)
        faults.reset()
    injected = [e for e in events if e["type"] == "fault_injected"]
    assert len(injected) == 2, f"expected both faults to fire: {injected}"
    assert {e["site"] for e in injected} == {"task.compute", "shuffle.fetch"}
    rec = trace_report.reconcile_faults(events)
    assert rec["reconciled"], (
        f"unpaired faults: {rec['unpaired']} "
        f"(recoveries seen: {rec['recoveries']})")
    # the fetch fault's recovery must be the map-stage rerun tier
    assert any(e["type"] == "map_stage_rerun" for e in events)
    assert any(e["type"] == "task_retry" for e in events)
    assert any(e["type"] == "fetch_failure" for e in events)


def test_reconcile_flags_unrecovered_fault():
    events = [
        {"ts": 1.0, "type": "fault_injected", "site": "task.compute",
         "hit": 1, "attempt": 0},
        {"ts": 2.0, "type": "task_retry", "stage_id": 0, "task": 0,
         "attempt": 1, "reason": "InjectedFault"},
        {"ts": 3.0, "type": "fault_injected", "site": "shuffle.write",
         "hit": 1, "attempt": 0},
    ]
    rec = trace_report.reconcile_faults(events)
    assert rec["injected"] == 2 and rec["recoveries"] == 1
    assert not rec["reconciled"]
    assert rec["unpaired"][0]["site"] == "shuffle.write"


# ------------------------------------------------- 4. overhead gating

def test_disabled_trace_keeps_pre_existing_dispatch_path(data, monkeypatch):
    """With spark.blaze.trace.enabled=false the per-batch hot path must
    be byte-for-byte the pre-existing one: no kernel-timing callback
    (record_kernel poisoned — a single traced jit call would raise),
    no block_until_ready, no span or event allocation.  Lifecycle
    sites still CALL trace.emit, but the disarmed emit is a bool-check
    no-op: zero events/spans after a full scheduler run."""
    conf.TRACE_ENABLE.set(False)
    trace.reset()
    assert not trace.enabled()
    assert trace._KERNEL_TIMING is False

    def poisoned(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("kernel timing entered with tracing disabled")

    monkeypatch.setattr(trace, "record_kernel", poisoned)
    stages, manager = split_stages(build_query("q6", _scans(data), 1))
    rows = sum(b.num_rows for b in run_stages(stages, manager))
    assert rows > 0
    assert trace.counters() == {"events": 0, "spans": 0}
    assert trace.current_path() is None  # no log file was even named


def test_emit_is_noop_when_disarmed(tmp_path):
    conf.TRACE_ENABLE.set(False)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    trace.emit("query_start", query_id="x")
    assert trace.counters()["events"] == 0
    assert list(tmp_path.iterdir()) == []
    conf.EVENT_LOG_DIR.set("")
    trace.reset()


def test_nested_kernel_captures_keep_identity():
    """Regression: sink removal must be by identity — equal (e.g.
    empty) dicts from nested captures must not evict each other."""
    with trace.kernel_capture() as outer:
        with trace.kernel_capture() as inner:
            pass
        assert trace._KERNEL_TIMING is True
        trace.record_kernel("k", 10, 2, 0)
    assert trace._KERNEL_TIMING is False
    assert outer["k"]["programs"] == 1 and outer["k"]["device_ns"] == 10
    assert inner == {}


def test_nested_dispatch_captures_keep_identity():
    with dispatch.capture() as outer:
        with dispatch.capture() as inner:
            pass
        dispatch.record("xla_dispatches")
    assert outer.get("xla_dispatches") == 1
    assert inner == {}


# ------------------------------------------------------- 5. schema

def _synthetic_events():
    """One representative instance of every event type the runtime can
    emit, produced through the real emit path (round-trip: emit ->
    JSONL -> parse -> validate)."""
    return [
        ("query_start", {"query_id": "q"}),
        ("query_end", {"query_id": "q", "status": "ok", "wall_ns": 5}),
        ("stage_submit", {"stage_id": 0, "kind": "map", "n_tasks": 2,
                          "shuffle_id": 0}),
        ("stage_complete", {"stage_id": 0, "kind": "map", "n_tasks": 2,
                            "shuffle_id": None, "status": "ok",
                            "wall_ns": 9, "programs": 1,
                            "device_time_ns": 4, "dispatch_overhead_ns": 2,
                            "compile_ns": 0,
                            "kernels": {"agg": {"programs": 1,
                                                "device_ns": 4,
                                                "dispatch_ns": 2,
                                                "compile_ns": 0}},
                            "counters": {"xla_dispatches": 1}}),
        ("task_attempt_start", {"stage_id": 0, "task": 0, "attempt": 0}),
        ("task_attempt_end", {"stage_id": 0, "task": 0, "attempt": 0,
                              "status": "failed", "error": "boom"}),
        ("task_retry", {"stage_id": 0, "task": 0, "attempt": 1,
                        "reason": "InjectedFault"}),
        ("task_timeout", {"stage_id": 0, "task": 0, "attempt": 0}),
        ("fetch_failure", {"stage_id": 1, "task": 0, "shuffle_id": 0}),
        ("map_stage_rerun", {"stage_id": 0, "shuffle_id": 0,
                             "map_ids": [1]}),
        ("speculative_attempt_start", {"stage_id": 0, "task": 1,
                                       "attempt": 100, "reason": "slow"}),
        ("speculative_attempt_won", {"stage_id": 0, "task": 1,
                                     "attempt": 100}),
        ("speculative_attempt_lost", {"stage_id": 0, "task": 2,
                                      "attempt": 101}),
        ("task_kernels", {"task_id": "task_0_0", "stage_id": 0,
                          "partition": 0, "attempt": 0, "wall_ns": 9,
                          "programs": 1, "device_time_ns": 4,
                          "dispatch_overhead_ns": 2, "compile_ns": 0,
                          "kernels": {"filter": {"programs": 1,
                                                 "device_ns": 4,
                                                 "dispatch_ns": 2,
                                                 "compile_ns": 0}}}),
        ("task_plan", {"task_id": "task_0_0", "stage_id": 0,
                       "partition": 0, "attempt": 0,
                       "plan": {"op": "FilterExec",
                                "metrics": {"output_rows": 3},
                                "children": [{"op": "MemoryScanExec",
                                              "metrics": {},
                                              "children": []}]}}),
        ("stage_progress", {"stage_id": 0, "kind": "map", "rows": 100,
                            "bytes": 4096, "batches": 2, "tasks_done": 1,
                            "n_tasks": 2, "elapsed_ns": 7,
                            "counters": {"xla_dispatches": 3},
                            "attempts": {"task_attempts": 1}}),
        ("task_heartbeat", {"task_id": "task_0_0", "stage_id": 0,
                            "partition": 0, "attempt": 0, "rows": 10,
                            "batches": 1, "elapsed_ns": 5,
                            "progress_rows": 10,
                            "metrics": {"output_rows": 10}}),
        ("query_cancel_requested", {"query_id": "q", "reason": "cancel"}),
        ("query_cancelled", {"query_id": "q", "reason": "deadline",
                             "stage_id": 1, "task": 0}),
        ("oom_recovery", {"label": "fused_stage", "action": "downshift",
                          "rows": 4096, "depth": 1}),
        ("autotune", {"action": "grow", "target_rows": 32768,
                      "device_share": 0.31, "label": "q1"}),
        ("fault_injected", {"site": "shuffle.fetch", "hit": 2,
                            "attempt": 0, "detail": "shuffle_0"}),
        ("straggler_injected", {"site": "shuffle.write", "hit": 1,
                                "attempt": 0, "slow_ms": 400,
                                "detail": "/tmp/x.data"}),
        ("worker_lost", {"worker": "w0", "reason": "killed by signal 9",
                         "stage_id": 0, "task": 2, "lost_maps": 1}),
        ("worker_blacklisted", {"worker": "w0", "failures": 2,
                                "reason": "heartbeat silent for 1200ms"}),
        ("pool_degraded", {"reason": "all workers dead or blacklisted",
                           "stage_id": 0, "task": 2}),
        ("block_corruption", {"site": "shuffle.fetch",
                              "resource": "shuffle_0",
                              "path": "/tmp/shuffle_0_1.data",
                              "detail": "crc32 mismatch",
                              "quarantined": True}),
        ("disk_pressure", {"action": "retry", "site": "shuffle.write",
                           "detail": "/tmp/shuffle_0_1.data"}),
        ("mem_watermark", {"used": 1024, "total": 4096}),
        ("spill", {"consumer": "shuffle", "bytes": 512}),
        ("shuffle_write", {"bytes": 100, "blocks": 2, "attempt": 0,
                           "path": "/tmp/x.data"}),
        ("shuffle_fetch", {"resource": "shuffle_0", "partition": 1,
                           "bytes": 100, "blocks": 2}),
        ("rss_push", {"resource": "rss_0", "partition": 0, "bytes": 7,
                      "blocks": 1}),
        ("plan_cache", {"action": "hit", "fingerprint": "ab12" * 8}),
        ("result_cache", {"action": "invalidate",
                          "fingerprint": "cd34" * 8, "bytes": 2048}),
        ("worker_telemetry", {"worker": "w0", "pid": 4242, "jobs_ok": 3,
                              "jobs_failed": 1, "rows": 640, "bytes": 5120,
                              "device_ns": 900, "dispatch_ns": 300,
                              "compile_ns": 0, "mem_peak": 1 << 20,
                              "eventlog": "/tmp/w0.jsonl"}),
        ("slo_alert_firing", {"pool": "etl", "slo": "latency",
                              "burn_fast": 14.4, "burn_slow": 6.0,
                              "window_sec": 3600.0, "objective": 0.99,
                              "threshold": 250.0}),
        ("slo_alert_resolved", {"pool": "etl", "slo": "latency",
                                "burn_fast": 0.0, "burn_slow": 0.5,
                                "fired_for_s": 12.5}),
        ("stats_skew_detected", {"exchange": "shuffle_0",
                                 "op": "ShuffleWriterExec[HashPartitioning]",
                                 "partition": 3, "rows": 9000,
                                 "bytes": 72000, "ratio": 6.5,
                                 "partitions": 8}),
        ("stats_persisted", {"fingerprint": "ab" * 32, "nodes": 4}),
        ("stats_reused", {"fingerprint": "ab" * 32, "nodes": 4}),
    ]


def test_every_event_type_roundtrips_golden_schema(tmp_path):
    schema = trace.load_schema()
    synth = _synthetic_events()
    # registry, golden schema, and synthetic coverage in lockstep:
    # adding/removing an event type without updating all three is drift
    assert set(schema["events"]) == set(trace.EVENT_TYPES)
    assert {t for t, _ in synth} == set(trace.EVENT_TYPES)

    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with trace.query("schema_check") as path:
            for etype, fields in synth:
                if etype in ("query_start", "query_end"):
                    continue  # emitted by the query span itself
                trace.emit(etype, **fields)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    events = trace.read_events(path)
    assert {e["type"] for e in events} == set(trace.EVENT_TYPES)
    for e in events:
        jsonschema.validate(e, schema["events"][e["type"]])


def test_real_run_events_validate_against_schema(data, tmp_path):
    schema = trace.load_schema()
    events, _ = _run_traced(data, "q1", tmp_path, runs=1, n_parts=2,
                            batch_rows=16384)
    assert events
    for e in events:
        assert e["type"] in schema["events"], f"undeclared type {e['type']}"
        jsonschema.validate(e, schema["events"][e["type"]])


def test_unregistered_event_type_raises(tmp_path):
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with pytest.raises(ValueError, match="unregistered"):
            trace.emit("not_a_real_event", x=1)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


# ------------------------------------- 6. metrics thread safety

def test_metrics_set_concurrent_add():
    from blaze_tpu.runtime.metrics import MetricsSet

    ms = MetricsSet()
    n_threads, n_iters = 8, 2000

    def worker():
        for _ in range(n_iters):
            ms.add("output_rows", 1)
            ms.add("bytes", 3)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ms.get("output_rows") == n_threads * n_iters
    assert ms.get("bytes") == 3 * n_threads * n_iters


def test_metric_node_concurrent_child_growth():
    from blaze_tpu.runtime.metrics import MetricNode

    node = MetricNode()
    errs = []

    def worker(i):
        try:
            for j in range(300):
                node.child(j % 17).metrics.add("c", 1)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(node.children) == 17
    total = sum(c.metrics.get("c") for c in node.children)
    assert total == 8 * 300


def test_metrics_merge():
    from blaze_tpu.runtime.metrics import MetricsSet

    a, b = MetricsSet(), MetricsSet()
    a.add("rows", 2)
    b.add("rows", 3)
    b.add("bytes", 7)
    a.merge(b)
    assert a.snapshot() == {"rows": 5, "bytes": 7}


# ------------------------------------- 7. sampling + log rotation

def test_trace_sample_rate_times_every_nth_program(data, tmp_path):
    """spark.blaze.trace.sampleRate=N: with tracing armed, only every
    Nth instrumented program pays the block-until-ready device drain;
    unsampled calls still count programs and launch overhead, and
    sum_kernels scales the device total by programs/timed."""
    from blaze_tpu.ops.fusion import optimize_plan
    from blaze_tpu.runtime.context import TaskContext

    def run_once():
        plan = optimize_plan(build_query("q6", _scans(data, 1, 8192), 1))
        for p in range(plan.num_partitions()):
            for _ in plan.execute(p, TaskContext(p, plan.num_partitions())):
                pass

    run_once()  # warm: compiles out of the way
    conf.TRACE_SAMPLE_RATE.set(4)
    trace.reset()
    try:
        with trace.kernel_capture() as kc:
            run_once()
    finally:
        conf.TRACE_SAMPLE_RATE.set(1)
        trace.reset()
    programs = sum(v["programs"] for v in kc.values())
    timed = sum(v["timed"] for v in kc.values())
    assert programs > 4
    assert 0 < timed < programs, (programs, timed)
    # scaling: the span total estimates full-fidelity device time
    raw = sum(v["device_ns"] for v in kc.values())
    scaled = trace.sum_kernels(kc)["device_time_ns"]
    assert scaled >= raw
    # the per-label scaler round-trips programs/timed
    for v in kc.values():
        if v["timed"]:
            assert trace.scaled_device_ns(v) >= v["device_ns"]


def test_trace_sample_rate_one_times_everything(data, tmp_path):
    """The default sampleRate=1 keeps full-fidelity attribution:
    every program timed (the pre-existing contract)."""
    from blaze_tpu.ops.fusion import optimize_plan
    from blaze_tpu.runtime.context import TaskContext

    plan = optimize_plan(build_query("q6", _scans(data, 1, 8192), 1))
    trace.reset()
    with trace.kernel_capture() as kc:
        for p in range(plan.num_partitions()):
            for _ in plan.execute(p, TaskContext(p, plan.num_partitions())):
                pass
    for label, v in kc.items():
        assert v["timed"] == v["programs"], (label, v)


def test_event_log_rotation_and_rotated_report(tmp_path):
    """spark.blaze.eventLog.maxBytes: the active file rolls over into
    numbered segments; read_event_log reassembles the set in emission
    order and --report renders from it."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    conf.EVENT_LOG_MAX_BYTES.set(1500)
    trace.reset()
    try:
        with trace.query("rotation_check") as path:
            for i in range(200):
                trace.emit("mem_watermark", used=i, total=4096)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        conf.EVENT_LOG_MAX_BYTES.set(0)
        trace.reset()
    segs = sorted(p for p in os.listdir(tmp_path) if ".seg" in p)
    assert segs, "no rollover segments despite the 1.5 KB cap"
    for seg in segs:
        assert os.path.getsize(os.path.join(tmp_path, seg)) >= 1500
    events = trace.read_event_log(path)
    watermarks = [e for e in events if e["type"] == "mem_watermark"]
    assert len(watermarks) == 200
    # emission order survives the segment stitching
    assert [e["used"] for e in watermarks] == list(range(200))
    # the active (last) file stays under the cap + one event of slack
    assert os.path.getsize(path) < 1500 + 200
    # the CLI renders the rotated set
    from blaze_tpu.__main__ import main

    assert main(["--report", path]) == 0


def test_event_log_no_rotation_by_default(tmp_path):
    """maxBytes=0 (default): one unbounded file, no segments."""
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with trace.query("no_rotation") as path:
            for i in range(50):
                trace.emit("mem_watermark", used=i, total=4096)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    assert not [p for p in os.listdir(tmp_path) if ".seg" in p]
    assert trace.read_event_log(path) == trace.read_events(path)


def test_event_log_rotation_never_clobbers_prior_segments(tmp_path):
    """Regression, twice over: reset() clears the in-memory sequence
    AND segment counters while the same query_id + pid regenerates the
    same log name.  The span allocator now probes past files already
    on disk, so a re-run gets a FRESH file — the stronger contract: no
    clobbered segments AND no two runs (two trace ids) appended into
    one log, which tore the OTLP single-trace-per-export invariant on
    every chaos sweep past seed 1.  Both runs' events must survive in
    full, each in its own file set."""
    def run_once():
        conf.TRACE_ENABLE.set(True)
        conf.EVENT_LOG_DIR.set(str(tmp_path))
        conf.EVENT_LOG_MAX_BYTES.set(1000)
        trace.reset()
        try:
            with trace.query("clobber_check") as path:
                for i in range(60):
                    trace.emit("mem_watermark", used=i, total=4096)
        finally:
            conf.TRACE_ENABLE.set(False)
            conf.EVENT_LOG_DIR.set("")
            conf.EVENT_LOG_MAX_BYTES.set(0)
            trace.reset()
        return path

    p1 = run_once()
    p2 = run_once()
    assert p1 != p2, (
        "a re-run after reset() must get a fresh log file, never "
        "append a second trace into the first run's")
    for p in (p1, p2):
        events = trace.read_event_log(p)
        watermarks = [e for e in events if e["type"] == "mem_watermark"]
        assert len(watermarks) == 60, (
            f"rollover clobbered earlier segments: "
            f"{len(watermarks)}/60 events in {p}")
        # exactly ONE trace id per file — the OTLP export invariant
        assert len({e["trace_id"] for e in events if "trace_id" in e}) == 1
