"""TPC-DS differential validation: engine plans vs independent numpy
oracles on generated data (≙ the reference's TPC-DS CI matrix,
SURVEY.md §4)."""

import numpy as np
import pytest

from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpcds import TPCDS_SCHEMAS, build_query, generate_all
from blaze_tpu.tpcds import oracle as O
from blaze_tpu.tpch.datagen import table_to_batches

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCDS_SCHEMAS[name], N_PARTS, batch_rows=4096),
            TPCDS_SCHEMAS[name],
        )
        for name in TPCDS_SCHEMAS
    }


def run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _check_brand_report(got, exp, sum_col, id_col="brand_id", name_col="brand"):
    rows = {
        (y, bid, bname): s
        for y, bid, bname, s in zip(got["d_year"], got[id_col], got[name_col], got[sum_col])
    }
    top = dict(sorted(exp.items(), key=lambda kv: -kv[1])[:100])
    # engine output is limited to 100; every returned row must be exact
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    # the returned set must be the top-100 by the sum
    if len(exp) > 100:
        assert min(rows.values()) >= sorted(exp.values(), reverse=True)[99] or set(rows) == set(top)


def test_q3(data, scans):
    got = run(build_query("q3", scans, N_PARTS))
    exp = O.oracle_q3(data)
    _check_brand_report(got, exp, "sum_agg")
    assert got["d_year"] == sorted(got["d_year"])  # primary order key


def test_q52(data, scans):
    got = run(build_query("q52", scans, N_PARTS))
    exp = O.oracle_q52(data)
    _check_brand_report(got, exp, "ext_price")


def test_q55(data, scans):
    got = run(build_query("q55", scans, N_PARTS))
    exp = O.oracle_q55(data)
    rows = {
        (y, bid, bname): s
        for y, bid, bname, s in zip(got["d_year"], got["brand_id"], got["brand"], got["ext_price"])
    }
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


def test_q42(data, scans):
    got = run(build_query("q42", scans, N_PARTS))
    exp = O.oracle_q42(data)
    _check_brand_report(got, exp, "sum_agg", id_col="category_id", name_col="category")
    assert got["sum_agg"] == sorted(got["sum_agg"], reverse=True)


def test_q7(data, scans):
    got = run(build_query("q7", scans, N_PARTS))
    exp = O.oracle_q7(data)
    assert got["i_item_id"] == sorted(got["i_item_id"])
    assert len(got["i_item_id"]) == min(len(exp), 100)
    for i, iid in enumerate(got["i_item_id"]):
        e = exp[iid]
        assert abs(got["agg1"][i] - e[0]) < 1e-9, (iid, got["agg1"][i], e[0])
        for gi, m in enumerate(("agg2", "agg3", "agg4"), start=1):
            assert abs(got[m][i] - e[gi]) <= 1, (iid, m, got[m][i], e[gi])


def test_q96(data, scans):
    got = run(build_query("q96", scans, N_PARTS))
    assert got["cnt"] == [O.oracle_q96(data)]


def test_q27(data, scans):
    got = run(build_query("q27", scans, N_PARTS))
    exp = O.oracle_q27(data)
    assert got["i_item_id"], "q27 returned no rows"
    for iid, state, gid, a1, a2, a3, a4 in zip(
        got["i_item_id"], got["s_state"], got["g_id"],
        got["agg1"], got["agg2"], got["agg3"], got["agg4"],
    ):
        key = (iid, state, gid)
        assert key in exp, key
        ea1, ea2, ea3, ea4 = exp[key]
        assert abs(a1 - ea1) < 1e-9 and (a2, a3, a4) == (ea2, ea3, ea4), key
    # the total row (grouping id 3) must be present in the top-100
    # only if it sorts there; rollup must produce all three levels
    assert set(got["g_id"]) <= {0, 1, 3}


def test_q89(data, scans):
    got = run(build_query("q89", scans, N_PARTS))
    exp = O.oracle_q89(data)
    seen = set()
    for cat, cls, brand, stn, co, moy, s, avg in zip(
        got["i_category"], got["i_class"], got["i_brand"], got["s_store_name"],
        got["s_company_name"], got["d_moy"], got["sum_sales"], got["avg_monthly_sales"],
    ):
        key = (cat, cls, brand, stn, co, moy)
        assert key in exp, key
        assert exp[key] == (s, avg), key
        seen.add(key)
    if len(exp) <= 100:
        assert seen == set(exp)


def test_q98(data, scans):
    got = run(build_query("q98", scans, N_PARTS))
    exp = O.oracle_q98(data)
    assert len(got["i_item_id"]) == len(exp)
    for iid, desc, cat, cls, price, rev, ratio in zip(
        got["i_item_id"], got["i_item_desc"], got["i_category"], got["i_class"],
        got["i_current_price"], got["itemrevenue"], got["revenueratio"],
    ):
        key = (iid, desc, cat, cls, price)
        assert key in exp, key
        erev, eratio = exp[key]
        assert rev == erev and abs(ratio - eratio) < 1e-9, key
    # spec ordering: category then class
    cats = got["i_category"]
    assert cats == sorted(cats)


def _check_ticket_report(got, exp):
    assert got["ss_ticket_number"], "query returned no rows"
    keys = list(zip(got["ss_ticket_number"], got["ss_customer_sk"]))
    assert len(set(keys)) == len(keys), "duplicate (ticket, customer) rows"
    assert set(keys) == set(exp)
    for tick, csk, sal, fn_, ln_, pf, cnt in zip(
        got["ss_ticket_number"], got["ss_customer_sk"], got["c_salutation"],
        got["c_first_name"], got["c_last_name"], got["c_preferred_cust_flag"],
        got["cnt"],
    ):
        key = (tick, csk)
        assert key in exp, key
        assert exp[key] == (sal, fn_, ln_, pf, cnt), key
    assert len(got["ss_ticket_number"]) == len(exp)


@pytest.fixture(scope="module")
def ticket_data():
    # the q34/q73 HAVING windows are sparse; a larger slice keeps the
    # differential non-trivial at test time
    return generate_all(0.01)


@pytest.fixture(scope="module")
def ticket_scans(ticket_data):
    return {
        name: MemoryScanExec(
            table_to_batches(ticket_data[name], TPCDS_SCHEMAS[name], N_PARTS, batch_rows=8192),
            TPCDS_SCHEMAS[name],
        )
        for name in TPCDS_SCHEMAS
    }


def test_q73(ticket_data, ticket_scans):
    got = run(build_query("q73", ticket_scans, N_PARTS))
    _check_ticket_report(got, O.oracle_q73(ticket_data))
    # q73 spec ordering: cnt desc primary
    assert got["cnt"] == sorted(got["cnt"], reverse=True)


def test_q34(ticket_data, ticket_scans):
    _check_ticket_report(
        run(build_query("q34", ticket_scans, N_PARTS)), O.oracle_q34(ticket_data)
    )


def test_q19(data, scans):
    got = run(build_query("q19", scans, N_PARTS))
    exp = O.oracle_q19(data)
    assert got["brand_id"], "q19 returned no rows"
    keys = list(zip(got["brand_id"], got["brand"], got["manufact_id"], got["manufact"]))
    assert len(set(keys)) == len(keys)
    for key, price in zip(keys, got["ext_price"]):
        assert exp.get(key) == price, key
    if len(exp) <= 100:
        assert set(keys) == set(exp)
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


def _check_manufact_window(got, exp, group_col, avg_name, order_cols):
    assert got["i_manufact_id"], "query returned no rows"
    seen = set()
    for m, g, sv, av in zip(
        got["i_manufact_id"], got[group_col], got["sum_sales"], got[avg_name],
    ):
        key = (m, g)
        assert key in exp, key
        assert exp[key] == (sv, av), key
        seen.add(key)
    assert len(seen) == len(got["i_manufact_id"]), "duplicate rows"
    assert len(seen) == min(len(exp), 100)
    if len(exp) <= 100:
        assert seen == set(exp)
    # spec ordering (ascending lexicographic over order_cols)
    rows = list(zip(*(got[c] for c in order_cols)))
    assert rows == sorted(rows)


def test_q53(data, scans):
    _check_manufact_window(
        run(build_query("q53", scans, N_PARTS)), O.oracle_q53(data), "d_qoy",
        "avg_quarterly_sales",
        ["avg_quarterly_sales", "sum_sales", "i_manufact_id"],
    )


def test_q63(data, scans):
    _check_manufact_window(
        run(build_query("q63", scans, N_PARTS)), O.oracle_q63(data), "d_moy",
        "avg_monthly_sales",
        ["i_manufact_id", "avg_monthly_sales", "sum_sales"],
    )
