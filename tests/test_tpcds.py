"""TPC-DS differential validation: engine plans vs independent numpy
oracles on generated data (≙ the reference's TPC-DS CI matrix,
SURVEY.md §4)."""

import numpy as np
import pytest

from blaze_tpu.batch import batch_to_pydict
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.tpcds import TPCDS_SCHEMAS, build_query, generate_all
from blaze_tpu.tpcds import oracle as O
from blaze_tpu.tpch.datagen import table_to_batches

pytestmark = pytest.mark.slow

SCALE = 0.002
N_PARTS = 2

_SINCE_CLEAR = {"n": 0}


@pytest.fixture(autouse=True)
def _clear_caches_every_few_tests():
    """jaxlib's CPU backend segfaults once enough compiled programs
    accumulate in one process (see conftest's per-module clear).  This
    module alone now exceeds that ceiling (58 differential queries),
    so ALSO clear every 10 tests within it."""
    yield
    _SINCE_CLEAR["n"] += 1
    if _SINCE_CLEAR["n"] % 10 == 0:
        import jax

        from blaze_tpu.ops.joins.broadcast import clear_join_map_cache
        from blaze_tpu.runtime.kernel_cache import clear_kernel_cache

        clear_kernel_cache()
        clear_join_map_cache()
        jax.clear_caches()


@pytest.fixture(scope="module")
def data():
    return generate_all(SCALE)


@pytest.fixture(scope="module")
def scans(data):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCDS_SCHEMAS[name], N_PARTS, batch_rows=4096),
            TPCDS_SCHEMAS[name],
        )
        for name in TPCDS_SCHEMAS
    }


def run(plan):
    out = {f.name: [] for f in plan.schema.fields}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
    return out


def _check_brand_report(got, exp, sum_col, id_col="brand_id", name_col="brand"):
    rows = {
        (y, bid, bname): s
        for y, bid, bname, s in zip(got["d_year"], got[id_col], got[name_col], got[sum_col])
    }
    top = dict(sorted(exp.items(), key=lambda kv: -kv[1])[:100])
    # engine output is limited to 100; every returned row must be exact
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    # the returned set must be the top-100 by the sum
    if len(exp) > 100:
        assert min(rows.values()) >= sorted(exp.values(), reverse=True)[99] or set(rows) == set(top)


def test_q3(ticket_data, ticket_scans):
    # manufact 128 first appears at the 0.01 slice (60-item datagen at
    # 0.002 has no match, making the differential trivially empty)
    got = run(build_query("q3", ticket_scans, N_PARTS))
    exp = O.oracle_q3(ticket_data)
    assert exp, "q3 oracle matched no rows"
    _check_brand_report(got, exp, "sum_agg")
    assert got["d_year"] == sorted(got["d_year"])  # primary order key


def test_q52(data, scans):
    got = run(build_query("q52", scans, N_PARTS))
    exp = O.oracle_q52(data)
    _check_brand_report(got, exp, "ext_price")


def test_q55(data, scans):
    got = run(build_query("q55", scans, N_PARTS))
    exp = O.oracle_q55(data)
    rows = {
        (y, bid, bname): s
        for y, bid, bname, s in zip(got["d_year"], got["brand_id"], got["brand"], got["ext_price"])
    }
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


def test_q42(data, scans):
    got = run(build_query("q42", scans, N_PARTS))
    exp = O.oracle_q42(data)
    _check_brand_report(got, exp, "sum_agg", id_col="category_id", name_col="category")
    assert got["sum_agg"] == sorted(got["sum_agg"], reverse=True)


def _check_demo_avgs(got, exp):
    """q7/q26-family: avg(int) is double (1e-9), decimal avgs are
    unscaled at scale+4 (one-unit slack on the HALF_UP boundary)."""
    assert got["i_item_id"] == sorted(got["i_item_id"])
    assert len(got["i_item_id"]) == min(len(exp), 100)
    for i, iid in enumerate(got["i_item_id"]):
        e = exp[iid]
        assert abs(got["agg1"][i] - e[0]) < 1e-9, (iid, got["agg1"][i], e[0])
        for gi, m in enumerate(("agg2", "agg3", "agg4"), start=1):
            assert abs(got[m][i] - e[gi]) <= 1, (iid, m, got[m][i], e[gi])


def test_q7(data, scans):
    _check_demo_avgs(run(build_query("q7", scans, N_PARTS)), O.oracle_q7(data))


def test_q96(data, scans):
    got = run(build_query("q96", scans, N_PARTS))
    assert got["cnt"] == [O.oracle_q96(data)]


def test_q27(data, scans):
    got = run(build_query("q27", scans, N_PARTS))
    exp = O.oracle_q27(data)
    assert got["i_item_id"], "q27 returned no rows"
    for iid, state, gid, a1, a2, a3, a4 in zip(
        got["i_item_id"], got["s_state"], got["g_id"],
        got["agg1"], got["agg2"], got["agg3"], got["agg4"],
    ):
        key = (iid, state, gid)
        assert key in exp, key
        ea1, ea2, ea3, ea4 = exp[key]
        assert abs(a1 - ea1) < 1e-9 and (a2, a3, a4) == (ea2, ea3, ea4), key
    # the total row (grouping id 3) must be present in the top-100
    # only if it sorts there; rollup must produce all three levels
    assert set(got["g_id"]) <= {0, 1, 3}


def test_q89(data, scans):
    got = run(build_query("q89", scans, N_PARTS))
    exp = O.oracle_q89(data)
    seen = set()
    for cat, cls, brand, stn, co, moy, s, avg in zip(
        got["i_category"], got["i_class"], got["i_brand"], got["s_store_name"],
        got["s_company_name"], got["d_moy"], got["sum_sales"], got["avg_monthly_sales"],
    ):
        key = (cat, cls, brand, stn, co, moy)
        assert key in exp, key
        assert exp[key] == (s, avg), key
        seen.add(key)
    if len(exp) <= 100:
        assert seen == set(exp)


def test_q98(data, scans):
    _check_class_share(run(build_query("q98", scans, N_PARTS)), O.oracle_q98(data))


def _check_ticket_report(got, exp):
    assert got["ss_ticket_number"], "query returned no rows"
    keys = list(zip(got["ss_ticket_number"], got["ss_customer_sk"]))
    assert len(set(keys)) == len(keys), "duplicate (ticket, customer) rows"
    assert set(keys) == set(exp)
    for tick, csk, sal, fn_, ln_, pf, cnt in zip(
        got["ss_ticket_number"], got["ss_customer_sk"], got["c_salutation"],
        got["c_first_name"], got["c_last_name"], got["c_preferred_cust_flag"],
        got["cnt"],
    ):
        key = (tick, csk)
        assert key in exp, key
        assert exp[key] == (sal, fn_, ln_, pf, cnt), key
    assert len(got["ss_ticket_number"]) == len(exp)


@pytest.fixture(scope="module")
def ticket_data():
    # the q34/q73 HAVING windows are sparse; a larger slice keeps the
    # differential non-trivial at test time
    return generate_all(0.01)


@pytest.fixture(scope="module")
def ticket_scans(ticket_data):
    return {
        name: MemoryScanExec(
            table_to_batches(ticket_data[name], TPCDS_SCHEMAS[name], N_PARTS, batch_rows=8192),
            TPCDS_SCHEMAS[name],
        )
        for name in TPCDS_SCHEMAS
    }


def test_q73(ticket_data, ticket_scans):
    got = run(build_query("q73", ticket_scans, N_PARTS))
    _check_ticket_report(got, O.oracle_q73(ticket_data))
    # q73 spec ordering: cnt desc primary
    assert got["cnt"] == sorted(got["cnt"], reverse=True)


def test_q34(ticket_data, ticket_scans):
    _check_ticket_report(
        run(build_query("q34", ticket_scans, N_PARTS)), O.oracle_q34(ticket_data)
    )


def test_q19(data, scans):
    got = run(build_query("q19", scans, N_PARTS))
    exp = O.oracle_q19(data)
    assert got["brand_id"], "q19 returned no rows"
    keys = list(zip(got["brand_id"], got["brand"], got["manufact_id"], got["manufact"]))
    assert len(set(keys)) == len(keys)
    for key, price in zip(keys, got["ext_price"]):
        assert exp.get(key) == price, key
    if len(exp) <= 100:
        assert set(keys) == set(exp)
    assert got["ext_price"] == sorted(got["ext_price"], reverse=True)


def _check_manufact_window(got, exp, group_col, avg_name, order_cols):
    assert got["i_manufact_id"], "query returned no rows"
    seen = set()
    for m, g, sv, av in zip(
        got["i_manufact_id"], got[group_col], got["sum_sales"], got[avg_name],
    ):
        key = (m, g)
        assert key in exp, key
        assert exp[key] == (sv, av), key
        seen.add(key)
    assert len(seen) == len(got["i_manufact_id"]), "duplicate rows"
    assert len(seen) == min(len(exp), 100)
    if len(exp) <= 100:
        assert seen == set(exp)
    # spec ordering (ascending lexicographic over order_cols)
    rows = list(zip(*(got[c] for c in order_cols)))
    assert rows == sorted(rows)


def test_q53(data, scans):
    _check_manufact_window(
        run(build_query("q53", scans, N_PARTS)), O.oracle_q53(data), "d_qoy",
        "avg_quarterly_sales",
        ["avg_quarterly_sales", "sum_sales", "i_manufact_id"],
    )


def test_q63(data, scans):
    _check_manufact_window(
        run(build_query("q63", scans, N_PARTS)), O.oracle_q63(data), "d_moy",
        "avg_monthly_sales",
        ["i_manufact_id", "avg_monthly_sales", "sum_sales"],
    )


def test_q38(data, scans):
    got = run(build_query("q38", scans, N_PARTS))
    assert got["cnt"] == [O.oracle_q38(data)]


def test_q87(data, scans):
    got = run(build_query("q87", scans, N_PARTS))
    assert got["cnt"] == [O.oracle_q87(data)]


def _check_channel_union(got, exp, group_col):
    assert got[group_col], "query returned no rows"
    rows = dict(zip(got[group_col], got["total_sales"]))
    assert len(rows) == len(got[group_col]), "duplicate groups"
    for k, v in rows.items():
        assert exp.get(k) == v, (k, v, exp.get(k))
    assert len(rows) == min(len(exp), 100)
    # spec order: total_sales ascending
    assert got["total_sales"] == sorted(got["total_sales"])


def test_q33(data, scans):
    _check_channel_union(run(build_query("q33", scans, N_PARTS)),
                         O.oracle_q33(data), "i_manufact_id")


def test_q56(data, scans):
    _check_channel_union(run(build_query("q56", scans, N_PARTS)),
                         O.oracle_q56(data), "i_item_id")


def test_q60(data, scans):
    _check_channel_union(run(build_query("q60", scans, N_PARTS)),
                         O.oracle_q60(data), "i_item_id")


def _check_rollup_margin(got, exp):
    assert got["lochierarchy"], "query returned no rows"
    for cat, cls, loch, meas, rank in zip(
        got["i_category"], got["i_class"], got["lochierarchy"],
        got["measure"], got["rank_within_parent"],
    ):
        key = (cat, cls, loch)
        assert key in exp, key
        emeas, erank = exp[key]
        assert abs(meas - emeas) < 1e-9 and rank == erank, (key, meas, rank, exp[key])
    # rollup must produce all three levels when <=100 rows
    if len(exp) <= 100:
        assert set(got["lochierarchy"]) == {0, 1, 2}
        assert len(got["lochierarchy"]) == len(exp)
    # spec order: lochierarchy desc first
    assert got["lochierarchy"] == sorted(got["lochierarchy"], reverse=True)


def test_q36(data, scans):
    _check_rollup_margin(run(build_query("q36", scans, N_PARTS)), O.oracle_q36(data))


def test_q86(data, scans):
    _check_rollup_margin(run(build_query("q86", scans, N_PARTS)), O.oracle_q86(data))


def _check_yoy(got, exp, entity_cols):
    assert got["d_moy"], "query returned no rows"
    for i in range(len(got["d_moy"])):
        key = (got["i_category"][i], got["i_brand"][i]) + tuple(
            got[c][i] for c in entity_cols
        ) + (got["d_year"][i], got["d_moy"][i])
        assert key in exp, key
        s, avg, psum, nsum = exp[key]
        assert got["sum_sales"][i] == s, key
        assert abs(got["avg_monthly_sales"][i] - avg) <= 1, key
        assert got["psum"][i] == psum and got["nsum"][i] == nsum, (
            key, got["psum"][i], got["nsum"][i], psum, nsum)
    if len(exp) <= 100:
        assert len(got["d_moy"]) == len(exp)


def test_q47(data, scans):
    _check_yoy(run(build_query("q47", scans, N_PARTS)), O.oracle_q47(data),
               ("s_store_name", "s_company_name"))


def test_q57(data, scans):
    _check_yoy(run(build_query("q57", scans, N_PARTS)), O.oracle_q57(data),
               ("cc_name",))


def test_q10(data, scans):
    got = run(build_query("q10", scans, N_PARTS))
    exp = O.oracle_q10(data)
    keys = list(zip(got["cd_gender"], got["cd_marital_status"],
                    got["cd_education_status"], got["cd_purchase_estimate"],
                    got["cd_credit_rating"], got["cd_dep_count"],
                    got["cd_dep_employed_count"], got["cd_dep_college_count"]))
    assert keys and len(set(keys)) == len(keys)
    for k, c in zip(keys, got["cnt"]):
        assert exp.get(k) == c, k
    assert len(keys) == min(len(exp), 100)
    assert keys == sorted(keys)


def test_q35(data, scans):
    got = run(build_query("q35", scans, N_PARTS))
    exp = O.oracle_q35(data)
    keys = list(zip(got["ca_state"], got["cd_gender"], got["cd_marital_status"],
                    got["cd_dep_count"], got["cd_dep_employed_count"],
                    got["cd_dep_college_count"]))
    assert keys and len(set(keys)) == len(keys)
    for i, k in enumerate(keys):
        assert k in exp, k
        e = exp[k]
        assert got["cnt1"][i] == e[0], k
        for j in range(3):
            assert abs(got[f"avg{j+1}"][i] - e[1 + 3*j]) < 1e-9, k
            assert got[f"max{j+1}"][i] == e[2 + 3*j], k
            assert got[f"sum{j+1}"][i] == e[3 + 3*j], k
    if len(exp) <= 100:
        assert set(keys) == set(exp)


def test_q9(data, scans):
    from blaze_tpu.tpcds.queries import Q9_THRESHOLDS

    got = run(build_query("q9", scans, N_PARTS))
    exp = O.oracle_q9(data, Q9_THRESHOLDS)
    assert len(got["bucket1"]) == 1
    for b in range(5):
        g = got[f"bucket{b+1}"][0]
        assert abs(g - exp[b]) <= 1, (b, g, exp[b])


def test_q88(data, scans):
    got = run(build_query("q88", scans, N_PARTS))
    exp = O.oracle_q88(data)
    row = [got[k][0] for k in got]
    assert row == exp, (row, exp)
    assert sum(exp) > 0, "q88 slice matched no rows (datagen too sparse)"


def test_q8(data, scans):
    from blaze_tpu.tpcds.queries import Q8_MIN_PREFERRED, Q8_ZIPS

    got = run(build_query("q8", scans, N_PARTS))
    exp = O.oracle_q8(data, Q8_ZIPS, Q8_MIN_PREFERRED)
    assert exp, "q8 oracle matched no stores (datagen too sparse)"
    assert dict(zip(got["s_store_name"], got["net_profit"])) == exp
    assert got["s_store_name"] == sorted(got["s_store_name"])


def test_q13(ticket_data, ticket_scans):
    got = run(build_query("q13", ticket_scans, N_PARTS))
    exp = O.oracle_q13(ticket_data)
    assert exp is not None, "q13 bands matched no rows (datagen too sparse)"
    assert got["cnt"] == [exp["cnt"]]
    assert abs(got["avg_qty"][0] - exp["avg_qty"]) < 1e-9
    assert got["avg_ext_sales"] == [exp["avg_ext_sales"]]
    assert got["avg_ext_disc"] == [exp["avg_ext_disc"]]


def test_q48(ticket_data, ticket_scans):
    got = run(build_query("q48", ticket_scans, N_PARTS))
    assert got["qty_sum"] == [O.oracle_q48(ticket_data)]


def test_q69(data, scans):
    got = run(build_query("q69", scans, N_PARTS))
    exp = O.oracle_q69(data)
    keys = list(zip(got["cd_gender"], got["cd_marital_status"],
                    got["cd_education_status"], got["cd_purchase_estimate"],
                    got["cd_credit_rating"]))
    assert keys and len(set(keys)) == len(keys)
    for k, c in zip(keys, got["cnt"]):
        assert exp.get(k) == c, k
    assert len(keys) == min(len(exp), 100)
    assert keys == sorted(keys)


def test_q65(data, scans):
    got = run(build_query("q65", scans, N_PARTS))
    exp = O.oracle_q65(data)
    rows = list(zip(got["s_store_name"], got["i_item_desc"], got["revenue"],
                    got["i_current_price"], got["i_brand"]))
    assert rows, "q65 returned no rows"
    # one row per (store, item); descriptions may collide — compare the
    # full row multiset and the (name, desc) ordering
    import collections
    if len(exp) <= 100:
        assert collections.Counter(rows) == collections.Counter(exp.values())
    else:
        assert not (collections.Counter(rows) - collections.Counter(exp.values()))
    keys = [(r[0], r[1]) for r in rows]
    assert keys == sorted(keys)


def test_q26(data, scans):
    got = run(build_query("q26", scans, N_PARTS))
    exp = O.oracle_q26(data)
    assert got["i_item_id"] == sorted(got["i_item_id"])
    assert len(got["i_item_id"]) == min(len(exp), 100)
    for i, iid in enumerate(got["i_item_id"]):
        e = exp[iid]
        assert abs(got["agg1"][i] - e[0]) < 1e-9, iid
        for gi, mname in enumerate(("agg2", "agg3", "agg4"), start=1):
            assert got[mname][i] == e[gi], (iid, mname)


def test_q93(data, scans):
    got = run(build_query("q93", scans, N_PARTS))
    exp = O.oracle_q93(data)
    assert exp, "q93 oracle matched no rows"
    rows = dict(zip(got["ss_customer_sk"], got["sumsales"]))
    assert len(rows) == len(got["ss_customer_sk"]), "duplicate customers"
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["sumsales"] == sorted(got["sumsales"])


def test_q70(data, scans):
    got = run(build_query("q70", scans, N_PARTS))
    exp = O.oracle_q70(data)
    assert got["lochierarchy"], "q70 returned no rows"
    for st, co, loch, total, rank in zip(
        got["s_state"], got["s_county"], got["lochierarchy"],
        got["total_sum"], got["rank_within_parent"],
    ):
        key = (st, co, loch)
        assert key in exp, key
        et, er = exp[key]
        assert (total, rank) == (et, er), (key, total, rank, exp[key])
    if len(exp) <= 100:
        assert len(got["lochierarchy"]) == len(exp)
        assert set(got["lochierarchy"]) == {0, 1, 2}
    assert got["lochierarchy"] == sorted(got["lochierarchy"], reverse=True)


def test_q15(data, scans):
    got = run(build_query("q15", scans, N_PARTS))
    exp = O.oracle_q15(data)
    assert exp, "q15 oracle matched no rows"
    rows = dict(zip(got["ca_zip"], got["sum_price"]))
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["ca_zip"] == sorted(got["ca_zip"])


def test_q61(ticket_data, ticket_scans):
    got = run(build_query("q61", ticket_scans, N_PARTS))
    promo, total = O.oracle_q61(ticket_data)
    assert total > 0, "q61 slice matched no rows"
    assert got["promotions"] == [promo]
    assert got["total"] == [total]
    exp_pct = (promo / 100.0) * 100.0 / (total / 100.0)
    assert abs(got["promo_pct"][0] - exp_pct) < 1e-9


def test_q32(data, scans):
    got = run(build_query("q32", scans, N_PARTS))
    exp = O.oracle_q32(data)
    assert exp is not None, "q32 slice matched no rows"
    assert got["excess_discount"] == [exp]


def test_q92(data, scans):
    got = run(build_query("q92", scans, N_PARTS))
    exp = O.oracle_q92(data)
    assert exp is not None, "q92 slice matched no rows"
    assert got["excess_discount"] == [exp]


def test_q43(data, scans):
    got = run(build_query("q43", scans, N_PARTS))
    exp = O.oracle_q43(data)
    assert exp, "q43 oracle matched no rows"
    assert got["s_store_name"] == sorted(got["s_store_name"])
    assert len(got["s_store_name"]) == min(len(exp), 100)
    days = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")
    for i, nm in enumerate(got["s_store_name"]):
        for k, d in enumerate(days):
            v = got[f"{d}_sales"][i]
            assert (v or 0) == exp[nm][k], (nm, d)


def _check_class_share(got, exp):
    assert len(got["i_item_id"]) == len(exp)
    for iid, desc, cat, cls, price, rev, ratio in zip(
        got["i_item_id"], got["i_item_desc"], got["i_category"], got["i_class"],
        got["i_current_price"], got["itemrevenue"], got["revenueratio"],
    ):
        key = (iid, desc, cat, cls, price)
        assert key in exp, key
        erev, eratio = exp[key]
        assert rev == erev and abs(ratio - eratio) < 1e-9, key
    assert got["i_category"] == sorted(got["i_category"])


def test_q20(data, scans):
    _check_class_share(run(build_query("q20", scans, N_PARTS)), O.oracle_q20(data))


def test_q12(data, scans):
    _check_class_share(run(build_query("q12", scans, N_PARTS)), O.oracle_q12(data))


def _check_channel_report(got, exp):
    """rollup(channel, id) reports: every engine row exact, count
    matches (<=100), output ordered by (channel, id) nulls-first."""
    n = len(got["channel"])
    assert n, "query returned no rows"
    rows = {}
    for i in range(n):
        rows[(got["channel"][i], got["id"][i])] = (
            got["sales"][i], got["returns"][i], got["profit"][i])
    assert len(rows) == n  # rollup keys are unique
    for k, v in rows.items():
        assert exp.get(k) == v, (k, v, exp.get(k))
    assert len(rows) == min(len(exp), 100)
    keys = [((0, "") if got["channel"][i] is None else (1, got["channel"][i]),
             (0, 0) if got["id"][i] is None else (1, got["id"][i]))
            for i in range(n)]
    assert keys == sorted(keys)


def test_q5(data, scans):
    _check_channel_report(run(build_query("q5", scans, N_PARTS)), O.oracle_q5(data))


def test_q77(data, scans):
    _check_channel_report(run(build_query("q77", scans, N_PARTS)), O.oracle_q77(data))


def test_q80(data, scans):
    _check_channel_report(run(build_query("q80", scans, N_PARTS)), O.oracle_q80(data))


def _check_ship_report(got, exp):
    cnt, ship, profit = exp
    assert cnt > 0, "oracle matched no orders"
    assert got["order_count"] == [cnt]
    assert got["total_shipping_cost"] == [ship]
    assert got["total_net_profit"] == [profit]


def test_q94(data, scans):
    _check_ship_report(run(build_query("q94", scans, N_PARTS)), O.oracle_q94(data))


def test_q95(data, scans):
    _check_ship_report(run(build_query("q95", scans, N_PARTS)), O.oracle_q95(data))


def test_q16(data, scans):
    _check_ship_report(run(build_query("q16", scans, N_PARTS)), O.oracle_q16(data))


def _check_yoy_customer(got, exp, cols):
    n = len(got[cols[0]])
    assert n, "query returned no rows"
    rows = {tuple(got[c][i] for c in cols) for i in range(n)}
    assert rows == exp if len(exp) <= 100 else rows <= exp
    assert got[cols[0]] == sorted(got[cols[0]])


def test_q74(data, scans):
    _check_yoy_customer(
        run(build_query("q74", scans, N_PARTS)), O.oracle_q74(data),
        ["c_customer_id", "c_first_name", "c_last_name"],
    )


def test_q11(data, scans):
    _check_yoy_customer(
        run(build_query("q11", scans, N_PARTS)), O.oracle_q11(data),
        ["c_customer_id", "c_preferred_cust_flag", "c_first_name", "c_last_name"],
    )


def test_q23a(data, scans):
    got = run(build_query("q23a", scans, N_PARTS))
    exp = O.oracle_q23a(data)
    assert exp is not None, "q23a oracle empty"
    assert got["sum_sales"] == [exp]


def test_q23b(data, scans):
    got = run(build_query("q23b", scans, N_PARTS))
    exp = O.oracle_q23b(data)
    assert exp, "q23b oracle empty"
    rows = {
        (l, f): v for l, f, v in
        zip(got["c_last_name"], got["c_first_name"], got["sales"])
    }
    assert rows == exp if len(exp) <= 100 else all(exp.get(k) == v for k, v in rows.items())
    assert got["sales"] == sorted(got["sales"], reverse=True)


def _check_q24(got, exp):
    assert exp, "q24 oracle empty"
    rows = {
        (l, f, st): v for l, f, st, v in
        zip(got["c_last_name"], got["c_first_name"], got["s_store_name"],
            got["paid"])
    }
    assert rows == exp
    keys = list(zip(got["c_last_name"], got["c_first_name"], got["s_store_name"]))
    assert keys == sorted(keys)


def test_q24a(ticket_data, ticket_scans):
    _check_q24(run(build_query("q24a", ticket_scans, N_PARTS)),
               O.oracle_q24a(ticket_data))


def test_q24b(ticket_data, ticket_scans):
    _check_q24(run(build_query("q24b", ticket_scans, N_PARTS)),
               O.oracle_q24b(ticket_data))


def test_q75(ticket_data, ticket_scans):
    got = run(build_query("q75", ticket_scans, N_PARTS))
    exp = O.oracle_q75(ticket_data)
    assert exp, "q75 oracle empty"
    rows = {
        (b, c, cat, m): (cd, ad) for b, c, cat, m, cd, ad in
        zip(got["i_brand_id"], got["i_class_id"], got["i_category_id"],
            got["i_manufact_id"], got["sales_cnt_diff"], got["sales_amt_diff"])
    }
    assert rows == exp if len(exp) <= 100 else all(exp.get(k) == v for k, v in rows.items())
    assert got["sales_cnt_diff"] == sorted(got["sales_cnt_diff"])
    assert all(y == 2002 for y in got["year"])


def test_q78(data, scans):
    got = run(build_query("q78", scans, N_PARTS))
    exp = O.oracle_q78(data)
    assert exp, "q78 oracle empty"
    n = len(got["ss_item_sk"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["ss_item_sk"][i], got["ss_customer_sk"][i])
        assert key in exp, key
        q, w, sp, ratio, other = exp[key]
        assert (got["ss_qty"][i], got["ss_wc"][i], got["ss_sp"][i]) == (q, w, sp), key
        assert abs(got["ratio"][i] - ratio) < 1e-12, key
        assert got["other_chan_qty"][i] == other, key
    assert got["ss_qty"] == sorted(got["ss_qty"], reverse=True)


def test_q51(data, scans):
    got = run(build_query("q51", scans, N_PARTS))
    exp = O.oracle_q51(data)
    assert exp, "q51 oracle empty"
    n = len(got["item_sk"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["item_sk"][i], got["d_date"][i])
        assert key in exp, key
        assert (got["web_cumulative"][i], got["store_cumulative"][i]) == exp[key], key
    keys = list(zip(got["item_sk"], got["d_date"]))
    assert keys == sorted(keys)
    if len(exp) > 100:
        assert keys == sorted(exp)[:100]


def test_q67(data, scans):
    got = run(build_query("q67", scans, N_PARTS))
    exp = O.oracle_q67(data)
    assert exp, "q67 oracle empty"
    n = len(got["i_category"])
    assert n == min(len(exp), 100)
    dims = ["i_category", "i_class", "i_brand", "i_item_id",
            "d_year", "d_qoy", "d_moy", "s_store_name"]
    for i in range(n):
        key = tuple(got[d][i] for d in dims) + (got["g_id"][i],)
        assert key in exp, key
        v, rk = exp[key]
        assert (got["sumsales"][i], got["rk"][i]) == (v, rk), (key, got["sumsales"][i], got["rk"][i], v, rk)
    order = [((0, "") if got["i_category"][i] is None else (1, got["i_category"][i]), got["rk"][i]) for i in range(n)]
    assert order == sorted(order)


def _nf(v):
    return (0, 0) if v is None else (1, v)


def test_q14a(data, scans):
    got = run(build_query("q14a", scans, N_PARTS))
    exp = O.oracle_q14a(data)
    assert exp, "q14a oracle empty"
    n = len(got["channel"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["channel"][i], got["i_brand_id"][i], got["i_class_id"][i],
               got["i_category_id"][i])
        assert key in exp, key
        assert (got["sum_sales"][i], got["sum_number_sales"][i]) == exp[key], key
    order = [tuple(_nf(got[c][i]) for c in
                   ("channel", "i_brand_id", "i_class_id", "i_category_id"))
             for i in range(n)]
    assert order == sorted(order)
    if len(exp) > 100:
        full = sorted(tuple(_nf(x) for x in k) for k in exp)
        assert order == full[:100]


def test_q14b(data, scans):
    got = run(build_query("q14b", scans, N_PARTS))
    exp = O.oracle_q14b(data)
    assert exp, "q14b oracle empty"
    rows = {
        (b, c, cat): (s, ns, ls, lns) for b, c, cat, s, ns, ls, lns in
        zip(got["i_brand_id"], got["i_class_id"], got["i_category_id"],
            got["sales"], got["number_sales"], got["last_sales"],
            got["last_number_sales"])
    }
    assert rows == exp if len(exp) <= 100 else all(exp.get(k) == v for k, v in rows.items())


def test_q72(data, scans):
    got = run(build_query("q72", scans, N_PARTS))
    exp = O.oracle_q72(data)
    assert exp, "q72 oracle empty"
    rows = {
        (d, w, wk): c for d, w, wk, c in
        zip(got["i_item_desc"], got["w_warehouse_name"], got["d_week_seq"],
            got["no_promo"])
    }
    for k, v in rows.items():
        assert exp.get(k) == v, k
    assert len(rows) == min(len(exp), 100)
    assert got["no_promo"] == sorted(got["no_promo"], reverse=True)


def test_q64(data, scans):
    got = run(build_query("q64", scans, N_PARTS))
    exp = O.oracle_q64(data)
    assert exp, "q64 oracle empty"
    rows = {
        (i, st, z): (c1, a, b, c, c2, d, e, f) for i, st, z, c1, a, b, c, c2, d, e, f in
        zip(got["i_item_id"], got["s_store_name"], got["s_zip"], got["cnt"],
            got["s1"], got["s2"], got["s3"], got["cnt2"], got["s1_2"],
            got["s2_2"], got["s3_2"])
    }
    assert rows == exp if len(exp) <= 100 else all(exp.get(k) == v for k, v in rows.items())
    assert got["s1"] == sorted(got["s1"], reverse=True)


def test_q97(data, scans):
    got = run(build_query("q97", scans, N_PARTS))
    so, co, both = O.oracle_q97(data)
    assert (got["store_only"], got["catalog_only"],
            got["store_and_catalog"]) == ([so], [co], [both])


def _check_city_tickets(got, exp, sum_names):
    assert exp, "oracle empty"
    n = len(got["ss_ticket_number"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["c_last_name"][i], got["c_first_name"][i],
               got["current_city"][i], got["bought_city"][i],
               got["ss_ticket_number"][i])
        assert key in exp, key
        assert tuple(got[c][i] for c in sum_names) == exp[key], key
    keys = [tuple(got[c][i] for c in
                  ("c_last_name", "c_first_name", "current_city",
                   "bought_city", "ss_ticket_number")) for i in range(n)]
    assert keys == sorted(keys)


def test_q46(data, scans):
    _check_city_tickets(run(build_query("q46", scans, N_PARTS)),
                        O.oracle_q46(data), ["amt", "sum_ss_net_profit"])


def test_q68(data, scans):
    _check_city_tickets(run(build_query("q68", scans, N_PARTS)),
                        O.oracle_q68(data), ["amt", "sum_ss_ext_list_price"])


def test_q79(data, scans):
    got = run(build_query("q79", scans, N_PARTS))
    exp = O.oracle_q79(data)
    assert exp, "q79 oracle empty"
    n = len(got["ss_ticket_number"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["c_last_name"][i], got["c_first_name"][i],
               got["s_city"][i], got["ss_ticket_number"][i])
        assert key in exp, key
        assert (got["amt"][i], got["profit"][i]) == exp[key], key


def _check_ship_lag(got, exp, dim_name):
    assert exp, "oracle empty"
    n = len(got["w_warehouse_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_warehouse_name"][i], got["sm_type"][i], got[dim_name][i])
        assert key in exp, key
        assert tuple(got[b][i] for b in
                     ("d30", "d60", "d90", "d120", "dmore")) == exp[key], key
    keys = [(got["w_warehouse_name"][i], got["sm_type"][i], got[dim_name][i])
            for i in range(n)]
    assert keys == sorted(keys)


def test_q62(data, scans):
    _check_ship_lag(run(build_query("q62", scans, N_PARTS)),
                    O.oracle_q62(data), "web_name")


def test_q99(data, scans):
    _check_ship_lag(run(build_query("q99", scans, N_PARTS)),
                    O.oracle_q99(data), "cc_name")


def _check_inv_price(got, exp):
    assert exp, "oracle empty"
    rows = set(zip(got["i_item_id"], got["i_item_desc"], got["i_current_price"]))
    assert len(rows) == min(len(exp), 100)
    assert rows == exp if len(exp) <= 100 else rows <= exp
    assert got["i_item_id"] == sorted(got["i_item_id"])


def test_q37(data, scans):
    _check_inv_price(run(build_query("q37", scans, N_PARTS)), O.oracle_q37(data))


def test_q82(data, scans):
    _check_inv_price(run(build_query("q82", scans, N_PARTS)), O.oracle_q82(data))


def test_q41(data, scans):
    got = run(build_query("q41", scans, N_PARTS))
    exp = O.oracle_q41(data)
    assert exp, "q41 oracle empty"
    assert got["i_item_id"] == exp[:100]


def test_q4(data, scans):
    got = run(build_query("q4", scans, N_PARTS))
    exp = O.oracle_q4(data)
    assert exp, "q4 oracle empty"
    rows = set(zip(got["c_customer_id"], got["c_first_name"], got["c_last_name"]))
    assert len(got["c_customer_id"]) == min(len(exp), 100)
    assert rows == exp if len(exp) <= 100 else rows <= exp
    assert got["c_customer_id"] == sorted(got["c_customer_id"])


def test_q50(data, scans):
    got = run(build_query("q50", scans, N_PARTS))
    exp = O.oracle_q50(data)
    assert exp, "q50 oracle empty"
    n = len(got["s_store_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["s_store_name"][i], got["s_county"][i], got["s_state"][i],
               got["s_zip"][i])
        assert key in exp, key
        assert tuple(got[b][i] for b in
                     ("d30", "d60", "d90", "d120", "dmore")) == exp[key], key


def test_q22(data, scans):
    got = run(build_query("q22", scans, N_PARTS))
    exp = O.oracle_q22(data)
    assert exp, "q22 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["i_brand"][i], got["i_class"][i],
               got["i_category"][i], got["g_id"][i])
        assert key in exp, key
        assert abs(got["qoh"][i] - exp[key]) < 1e-9, key
    assert got["qoh"] == sorted(got["qoh"])


def test_q21(data, scans):
    got = run(build_query("q21", scans, N_PARTS))
    exp = O.oracle_q21(data)
    assert exp, "q21 oracle empty"
    n = len(got["w_warehouse_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_warehouse_name"][i], got["i_item_id"][i])
        assert key in exp, key
        assert (got["inv_before"][i], got["inv_after"][i]) == exp[key], key
    keys = [(got["w_warehouse_name"][i], got["i_item_id"][i]) for i in range(n)]
    assert keys == sorted(keys)


def test_q28(data, scans):
    got = run(build_query("q28", scans, N_PARTS))
    exp = O.oracle_q28(data)
    for name, (avg_u, cnt, cntd) in exp.items():
        assert got[f"{name}_lp"] == [avg_u], name
        assert got[f"{name}_cnt"] == [cnt], name
        assert got[f"{name}_cntd"] == [cntd], name


def test_q90(data, scans):
    got = run(build_query("q90", scans, N_PARTS))
    am, pm, ratio = O.oracle_q90(data)
    assert got["am_count"] == [float(am)]
    assert got["pm_count"] == [float(pm)]
    assert abs(got["am_pm_ratio"][0] - ratio) < 1e-12


def test_q76(data, scans):
    got = run(build_query("q76", scans, N_PARTS))
    exp = O.oracle_q76(data)
    assert exp, "q76 oracle empty"
    n = len(got["channel"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["channel"][i], got["col_name"][i], got["d_year"][i],
               got["d_qoy"][i], got["i_category"][i])
        assert key in exp, key
        assert (got["sales_cnt"][i], got["sales_amt"][i]) == exp[key], key


def test_q1(data, scans):
    got = run(build_query("q1", scans, N_PARTS))
    exp = O.oracle_q1(data)
    assert exp, "q1 oracle empty"
    assert len(got["c_customer_id"]) == min(len(exp), 100)
    assert set(got["c_customer_id"]) == exp if len(exp) <= 100 else set(
        got["c_customer_id"]) <= exp
    assert got["c_customer_id"] == sorted(got["c_customer_id"])


def _check_returns_family(got, exp):
    assert exp, "oracle empty"
    # row COUNT by list (projected rows may tie across locations);
    # content as a set against the oracle's set
    assert len(got["c_customer_id"]) == min(len(exp), 100)
    rows = set(zip(got["c_customer_id"], got["c_first_name"],
                   got["c_last_name"], got["ctr_total_return"]))
    assert rows == exp if len(exp) <= 100 else rows <= exp


def test_q30(data, scans):
    _check_returns_family(run(build_query("q30", scans, N_PARTS)),
                          O.oracle_q30(data))


def test_q81(data, scans):
    _check_returns_family(run(build_query("q81", scans, N_PARTS)),
                          O.oracle_q81(data))


def _check_weekly_ratios(got, exp, key_cols):
    assert exp, "oracle empty"
    n = len(got[key_cols[0]])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = got[key_cols[0]][i] if len(key_cols) == 1 else tuple(
            got[c][i] for c in key_cols)
        assert key in exp, key
        for k, nm in enumerate(("sun", "mon", "tue", "wed", "thu", "fri", "sat")):
            g, e = got[f"{nm}_ratio"][i], exp[key][k]
            if e is None:
                assert g is None, (key, nm)
            else:
                assert g is not None and abs(g - e) < 1e-12, (key, nm)


def test_q2(data, scans):
    _check_weekly_ratios(run(build_query("q2", scans, N_PARTS)),
                         O.oracle_q2(data), ["d_week_seq"])


def test_q59(data, scans):
    _check_weekly_ratios(run(build_query("q59", scans, N_PARTS)),
                         O.oracle_q59(data), ["s_store_name", "d_week_seq"])


def _check_srcandc(got, exp, names):
    assert exp, "oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["i_item_desc"][i], got["s_store_name"][i])
        assert key in exp, key
        assert tuple(got[c][i] for c in names) == exp[key], key


def test_q25(data, scans):
    _check_srcandc(run(build_query("q25", scans, N_PARTS)), O.oracle_q25(data),
                   ["store_sales_profit", "store_returns_loss",
                    "catalog_sales_profit"])


def test_q29(data, scans):
    _check_srcandc(run(build_query("q29", scans, N_PARTS)), O.oracle_q29(data),
                   ["store_sales_quantity", "store_returns_quantity",
                    "catalog_sales_quantity"])


def test_q91(data, scans):
    got = run(build_query("q91", scans, N_PARTS))
    exp = O.oracle_q91(data)
    assert exp, "q91 oracle empty"
    n = len(got["cc_name"])
    assert n == min(len(exp), 100)
    rows = {
        (got["cc_name"][i], got["cd_marital_status"][i],
         got["cd_education_status"][i]): got["returns_loss"][i]
        for i in range(n)
    }
    assert rows == exp if len(exp) <= 100 else all(
        exp.get(k) == v for k, v in rows.items())
    assert got["returns_loss"] == sorted(got["returns_loss"], reverse=True)


def test_q45(data, scans):
    got = run(build_query("q45", scans, N_PARTS))
    exp = O.oracle_q45(data)
    assert exp, "q45 oracle empty"
    n = len(got["ca_zip"])
    assert n == min(len(exp), 100)
    rows = {(got["ca_zip"][i], got["ca_city"][i]): got["sum_sales"][i]
            for i in range(n)}
    assert rows == exp if len(exp) <= 100 else all(
        exp.get(k) == v for k, v in rows.items())


def test_q17(data, scans):
    got = run(build_query("q17", scans, N_PARTS))
    exp = O.oracle_q17(data)
    assert exp, "q17 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["i_item_desc"][i], got["s_store_name"][i])
        assert key in exp, key
        for k, nm in enumerate(("store", "returns", "catalog")):
            cnt, mean, sd, cov = exp[key][k]
            assert got[f"{nm}_qty_count"][i] == cnt, (key, nm)
            assert abs(got[f"{nm}_qty_avg"][i] - mean) < 1e-9, (key, nm)
            for gv, ev in ((got[f"{nm}_qty_stdev"][i], sd),
                           (got[f"{nm}_qty_cov"][i], cov)):
                if ev is None:
                    assert gv is None, (key, nm)
                else:
                    assert gv is not None and abs(gv - ev) < 1e-9, (key, nm)


def _check_q39(got, exp):
    assert exp, "q39 oracle empty"
    n = len(got["w_warehouse_name"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_warehouse_name"][i], got["inv_item_sk"][i])
        assert key in exp, key
        m1, c1, m2, c2 = exp[key]
        assert abs(got["mean"][i] - m1) < 1e-9 and abs(got["cov"][i] - c1) < 1e-9, key
        assert abs(got["mean2"][i] - m2) < 1e-9 and abs(got["cov2"][i] - c2) < 1e-9, key
    keys = [(got["w_warehouse_name"][i], got["inv_item_sk"][i]) for i in range(n)]
    assert keys == sorted(keys)


def test_q39a(data, scans):
    _check_q39(run(build_query("q39a", scans, N_PARTS)), O.oracle_q39a(data))


def test_q39b(data, scans):
    _check_q39(run(build_query("q39b", scans, N_PARTS)), O.oracle_q39b(data))


def test_q18(data, scans):
    got = run(build_query("q18", scans, N_PARTS))
    exp = O.oracle_q18(data)
    assert exp, "q18 oracle empty"
    n = len(got["i_item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["i_item_id"][i], got["ca_county"][i], got["ca_state"][i],
               got["g_id"][i])
        assert key in exp, key
        for k in range(7):
            assert abs(got[f"agg{k+1}"][i] - exp[key][k]) < 1e-9, (key, k)


def test_q40(data, scans):
    got = run(build_query("q40", scans, N_PARTS))
    exp = O.oracle_q40(data)
    assert exp, "q40 oracle empty"
    n = len(got["w_state"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = (got["w_state"][i], got["i_item_id"][i])
        assert key in exp, key
        assert (got["sales_before"][i], got["sales_after"][i]) == exp[key], key


def test_q6(data, scans):
    got = run(build_query("q6", scans, N_PARTS))
    exp = O.oracle_q6(data)
    assert exp, "q6 oracle empty"
    assert dict(zip(got["state"], got["cnt"])) == exp
    assert got["cnt"] == sorted(got["cnt"])


def test_q83(data, scans):
    got = run(build_query("q83", scans, N_PARTS))
    exp = O.oracle_q83(data)
    assert exp, "q83 oracle empty"
    n = len(got["item_id"])
    assert n == min(len(exp), 100)
    for i in range(n):
        key = got["item_id"][i]
        assert key in exp, key
        a, b, c, da, db, dc, avg = exp[key]
        assert (got["sr_qty"][i], got["cr_qty"][i], got["wr_qty"][i]) == (a, b, c), key
        assert abs(got["sr_dev"][i] - da) < 1e-9
        assert abs(got["cr_dev"][i] - db) < 1e-9
        assert abs(got["wr_dev"][i] - dc) < 1e-9
        assert abs(got["average"][i] - avg) < 1e-9


def test_q44(data, scans):
    got = run(build_query("q44", scans, N_PARTS))
    exp = O.oracle_q44(data)
    assert exp, "q44 oracle empty"
    rows = set(zip(got["rnk"], got["best_name"], got["worst_name"]))
    assert len(got["rnk"]) == min(len(exp), 100)
    assert rows == exp if len(exp) <= 100 else rows <= exp
    assert got["rnk"] == sorted(got["rnk"])


def test_q31(ticket_data, ticket_scans):
    got = run(build_query("q31", ticket_scans, N_PARTS))
    exp = O.oracle_q31(ticket_data)
    assert exp, "q31 oracle empty"
    rows = {
        c: (w12, s12, w23, s23)
        for c, w12, s12, w23, s23 in zip(
            got["ca_county"], got["web_q1_q2_increase"],
            got["store_q1_q2_increase"], got["web_q2_q3_increase"],
            got["store_q2_q3_increase"])
    }
    assert set(rows) == set(exp)
    for c, vals in rows.items():  # XLA FMA contraction: ULP-level slack
        assert vals == pytest.approx(exp[c], rel=1e-12), c
    assert got["d_year"] == [2000] * len(rows)
    assert got["ca_county"] == sorted(got["ca_county"])


def test_q49(ticket_data, ticket_scans):
    got = run(build_query("q49", ticket_scans, N_PARTS))
    exp = O.oracle_q49(ticket_data)
    assert exp, "q49 oracle empty"
    assert len(exp) <= 100, "q49 fixture outgrew fetch=100; cap the oracle"
    rows = set(zip(got["channel"], got["item"], got["return_ratio"],
                   got["return_rank"], got["currency_rank"]))
    assert rows == exp
    # ORDER BY channel, return_rank, currency_rank
    keys = list(zip(got["channel"], got["return_rank"], got["currency_rank"]))
    assert keys == sorted(keys)


def test_q54(ticket_data, ticket_scans):
    got = run(build_query("q54", ticket_scans, N_PARTS))
    exp = O.oracle_q54(ticket_data)
    assert exp, "q54 oracle empty"
    assert len(exp) <= 100, "q54 fixture outgrew fetch=100; cap the oracle"
    rows = dict(zip(got["segment"], got["num_customers"]))
    assert rows == exp
    assert got["segment_base"] == [s * 50 for s in got["segment"]]
    assert got["segment"] == sorted(got["segment"])


def test_q58(ticket_data, ticket_scans):
    got = run(build_query("q58", ticket_scans, N_PARTS))
    exp = O.oracle_q58(ticket_data)
    assert exp, "q58 oracle empty"
    assert len(exp) <= 100, "q58 fixture outgrew fetch=100; cap the oracle"
    rows = {
        iid: (sr, sd, cr, cd, wr, wd, avg)
        for iid, sr, sd, cr, cd, wr, wd, avg in zip(
            got["item_id"], got["ss_item_rev"], got["ss_dev"],
            got["cs_item_rev"], got["cs_dev"], got["ws_item_rev"],
            got["ws_dev"], got["average"])
    }
    assert set(rows) == set(exp)
    for iid, (sr, sd, cr, cd, wr, wd, avg) in rows.items():
        e = exp[iid]
        assert (sr, cr, wr) == (e[0], e[2], e[4]), iid  # cents exact
        # XLA FMA contraction: ULP-level slack on derived ratios
        assert (sd, cd, wd, avg) == pytest.approx(
            (e[1], e[3], e[5], e[6]), rel=1e-12), iid
    assert got["item_id"] == sorted(got["item_id"])


def test_q66(data, scans):
    got = run(build_query("q66", scans, N_PARTS))
    exp = O.oracle_q66(data)
    assert exp, "q66 oracle empty"
    assert got["w_warehouse_name"] == sorted(exp)
    for i, name in enumerate(got["w_warehouse_name"]):
        sq_ft, city, cty, state, country, sales, ratios, nets = exp[name]
        assert (got["w_warehouse_sq_ft"][i], got["w_city"][i],
                got["w_county"][i], got["w_state"][i],
                got["w_country"][i]) == (sq_ft, city, cty, state, country)
        assert got["ship_carriers"][i] == "DHL,BARIAN"
        assert got["year"][i] == 2001
        for m, nm in enumerate(
                ("jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
                 "sep", "oct", "nov", "dec")):
            assert got[f"{nm}_sales"][i] == sales[m], (name, nm)
            assert got[f"{nm}_net"][i] == nets[m], (name, nm)
            g = got[f"{nm}_sales_per_sq_foot"][i]
            if ratios[m] is None:
                assert g is None, (name, nm)
            else:
                assert g == pytest.approx(ratios[m], rel=1e-12), (name, nm)


def test_q71(ticket_data, ticket_scans):
    got = run(build_query("q71", ticket_scans, N_PARTS))
    exp = O.oracle_q71(ticket_data)
    assert exp, "q71 oracle empty"
    rows = dict(zip(zip(got["brand_id"], got["brand"], got["t_hour"],
                        got["t_minute"]), got["ext_price"]))
    assert rows == exp
    keys = list(zip([-p for p in got["ext_price"]], got["brand_id"]))
    assert keys == sorted(keys)


def test_q84(ticket_data, ticket_scans):
    got = run(build_query("q84", ticket_scans, N_PARTS))
    exp = O.oracle_q84(ticket_data)
    assert exp, "q84 oracle empty"
    rows = sorted(zip(got["customer_id"], got["customername"]))
    assert rows == exp
    assert got["customer_id"] == sorted(got["customer_id"])


def test_q85(ticket_data, ticket_scans):
    got = run(build_query("q85", ticket_scans, N_PARTS))
    exp = O.oracle_q85(ticket_data)
    assert exp, "q85 oracle empty"
    rows = {
        r: (q, c, f)
        for r, q, c, f in zip(got["reason"], got["avg_q"], got["avg_cash"],
                              got["avg_fee"])
    }
    assert set(rows) == set(exp)
    for r, (q, c, f) in rows.items():
        eq, ec, ef = exp[r]
        assert q == pytest.approx(eq, rel=1e-12), r
        assert (c, f) == (ec, ef), r
    assert got["reason"] == sorted(got["reason"])


def test_null_foreign_keys_end_to_end(data):
    """NULL foreign keys as REAL nulls end-to-end (not -1 sentinels):
    `IS NULL` filters, a NULL grouping key, LEFT-join null extension,
    and INNER-join null-key dropping, through full serde + the stage
    scheduler, vs a numpy oracle honoring NULL semantics.  The base
    draws are the SAME arrays every other differential uses — only the
    validity view differs (tpcds.datagen.with_null_fks)."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, FilterExec, GroupingExpr
    from blaze_tpu.ops.joins.core import JoinType
    from blaze_tpu.runtime.scheduler import run_stages, split_stages
    from blaze_tpu.tpcds.datagen import with_null_fks
    from blaze_tpu.tpch.queries import broadcast_join, two_stage_agg

    ss = with_null_fks(data["store_sales"], ["ss_customer_sk"])
    fk = ss["ss_customer_sk"][0]
    valid = ss["ss_customer_sk"][2]
    assert not valid.all() and valid.any(), "need a mix of null/non-null keys"

    scan = MemoryScanExec(
        table_to_batches(ss, TPCDS_SCHEMAS["store_sales"], N_PARTS, batch_rows=4096),
        TPCDS_SCHEMAS["store_sales"],
    )
    cust = MemoryScanExec(
        table_to_batches(data["customer"], TPCDS_SCHEMAS["customer"], 1, batch_rows=65536),
        TPCDS_SCHEMAS["customer"],
    )

    def run_sched(plan):
        stages, manager = split_stages(plan)
        out = {f.name: [] for f in plan.schema.fields}
        for b in run_stages(stages, manager):
            d = batch_to_pydict(b)
            for k in out:
                out[k].extend(d[k])
        return out

    # 1. IS NULL count: -1 sentinels would make this zero
    got = run_sched(two_stage_agg(
        FilterExec(scan, col("ss_customer_sk").is_null()),
        [], [AggFunction("count_star", None, "n")], 1))
    assert got["n"] == [int((~valid).sum())]

    # 2. GROUP BY the nullable key: exactly one NULL group whose count
    # equals the null-row count, and every non-null group exact
    got = run_sched(two_stage_agg(
        scan, [GroupingExpr(col("ss_customer_sk"), "k")],
        [AggFunction("count_star", None, "n")], N_PARTS))
    got_rows = dict(zip(got["k"], got["n"]))
    exp_rows = {}
    for v, ok in zip(fk, valid):
        key = int(v) if ok else None
        exp_rows[key] = exp_rows.get(key, 0) + 1
    assert got_rows == exp_rows
    assert None in got_rows

    # 3. INNER join drops null keys entirely (Spark null-key semantics)
    j = broadcast_join(cust, scan, [col("c_customer_sk")],
                       [col("ss_customer_sk")], JoinType.INNER,
                       build_is_left=False)
    got = run_sched(two_stage_agg(
        j, [], [AggFunction("count_star", None, "n")], 1))
    csk = set(data["customer"]["c_customer_sk"][0].tolist())
    exp_inner = sum(1 for v, ok in zip(fk, valid) if ok and int(v) in csk)
    assert got["n"] == [exp_inner]

    # 4. LEFT join null-extends the null-key rows instead of dropping
    # (build side first: customer broadcasts, store_sales is the
    # preserved left/probe side)
    j = broadcast_join(cust, scan, [col("c_customer_sk")],
                       [col("ss_customer_sk")], JoinType.LEFT,
                       build_is_left=False)
    got = run_sched(two_stage_agg(
        FilterExec(j, col("c_customer_sk").is_null()),
        [], [AggFunction("count_star", None, "n")], 1))
    exp_unmatched = sum(
        1 for v, ok in zip(fk, valid) if not ok or int(v) not in csk)
    assert got["n"] == [exp_unmatched]
