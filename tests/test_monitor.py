"""Live query monitoring (tier-1, CPU backend).

1. **Live progress** (acceptance): with the monitor armed, a query
   running on a background thread is observable MID-FLIGHT via
   ``/queries`` — stage rows strictly increase across polls — and
   ``/metrics`` parses as Prometheus text exposition format.
2. **Structural no-op** (acceptance): with
   ``spark.blaze.monitor.enabled=false`` (the default) no server or
   thread is created and the heartbeat path never reaches the
   registry or the emitter (poisoned, like the trace-off gate).
3. **Gateway-path spans** (acceptance): ``session.execute`` (the
   non-scheduler path) produces query -> stage spans in the event log
   that ``--report`` and ``--report --json`` render with the same
   shape as scheduler-path runs.
4. **Heartbeats**: stage_progress / task_heartbeat events round-trip
   the golden event schema from a REAL run (the synthetic lockstep
   lives in test_trace.py).
5. **Metric-name registry**: metric_names.json pins every
   counter/gauge name, gated both ways (source literal -> registry,
   registry -> source literal) plus a dynamic subset check.
6. **--report --json**: golden top-level/stage/kernel keys.
7. **Server lifecycle**: endpoints, clean shutdown, no thread leak
   (the chaos CLI runs the same gate via ``--chaos --monitor``).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import jsonschema
import pytest

import spark_fixtures as F
from blaze_tpu import conf
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime import monitor, trace, trace_report
from blaze_tpu.runtime.metrics import registered_metric_names
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.schema import DataType, Field, Schema
from blaze_tpu.spark import BlazeSparkSession
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True, scope="module")
def _lock_order_assertions():
    """The monitor suite exercises every background-thread subsystem
    (HTTP handler threads, heartbeat TLS, scheduler fan-out), so the
    whole module runs with the runtime lock-order assertion armed
    (analysis/locks.py): an inverted acquisition raises LockOrderError
    in the test instead of deadlocking rarely in production."""
    from blaze_tpu.analysis import locks as lock_verify

    conf.VERIFY_LOCKS.set(True)
    lock_verify.refresh()
    yield
    conf.VERIFY_LOCKS.set(False)
    lock_verify.refresh()


@pytest.fixture(scope="module")
def data():
    return generate_all(0.02)


def _scans(data, n_parts=2, batch_rows=16384):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=batch_rows),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


@pytest.fixture
def armed_monitor():
    """Monitor armed on an ephemeral port with a fast heartbeat; the
    server (if started) and all conf restored afterwards."""
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    conf.MONITOR_HEARTBEAT_MS.set(1)
    monitor.reset()
    try:
        yield monitor
    finally:
        monitor.shutdown_server()
        conf.MONITOR_ENABLE.set(False)
        conf.MONITOR_PORT.set(4048)
        conf.MONITOR_HEARTBEAT_MS.set(1000)
        monitor.reset()
        assert monitor.monitor_threads() == []


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        body = r.read()
        return r.status, r.headers.get("Content-Type", ""), body


# ---- Prometheus text exposition parser (format contract, no client lib)

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\""          # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\")*\})?"     # more labels
    r" -?[0-9.eE+\-Na-n]+( [0-9]+)?"                   # value [timestamp]
    # OpenMetrics exemplar on histogram buckets: " # {labels} value [ts]"
    r"( # \{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\"\}"
    r" -?[0-9.eE+-]+( [0-9.]+)?)?$")
_PROM_COMMENT = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def _assert_prometheus(text: str) -> dict:
    """Validate text exposition format line-by-line; returns
    {family: [sample lines]}.  Prometheus REJECTS a scrape containing
    duplicate name+label samples, so uniqueness is part of the format
    contract."""
    families = {}
    seen = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            assert _PROM_COMMENT.match(line), f"bad comment line: {line!r}"
            continue
        assert _PROM_SAMPLE.match(line), f"bad sample line: {line!r}"
        series = line.rsplit(" ", 1)[0]
        assert series not in seen, f"duplicate series: {series!r}"
        seen.add(series)
        families.setdefault(line.split("{")[0].split(" ")[0], []).append(line)
    assert families, "no samples rendered"
    return families


# ------------------------------------------------- 1. live progress

class SlowScanExec(MemoryScanExec):
    """A scan that sleeps between batches — the observable slow query
    for the mid-flight poll test."""

    def __init__(self, partitions, schema, delay_s: float):
        super().__init__(partitions, schema)
        self._delay = delay_s

    def execute(self, partition, ctx):
        def stream():
            if partition < len(self._partitions):
                for b in self._partitions[partition]:
                    time.sleep(self._delay)
                    self.metrics.add("output_rows", b.num_rows)
                    monitor.tick()
                    yield b.to_device()

        return stream()


def _slow_session(n_rows=2000, n_batches=20, delay_s=0.02):
    schema = Schema([Field("v", DataType.int64())])
    per = n_rows // n_batches
    from blaze_tpu.batch import batch_from_pydict

    parts = [[batch_from_pydict({"v": list(range(i * per, (i + 1) * per))},
                                schema) for i in range(n_batches)]]
    sess = BlazeSparkSession()
    sess.register_table("slow", SlowScanExec(parts, schema, delay_s))
    plan = F.flatten(F.scan("slow", [F.attr("v", 1)]))
    return sess, plan, n_rows


def test_live_progress_visible_mid_flight(armed_monitor):
    """Acceptance: a background-thread query's stage progress strictly
    increases across /queries polls while it runs, and /metrics parses
    as Prometheus text format mid-flight."""
    srv = monitor.ensure_server()
    assert srv is not None and srv.port > 0
    sess, plan, n_rows = _slow_session()
    done = threading.Event()
    result = {}

    def run():
        try:
            result["out"] = sess.execute(plan, query_id="slow_poll_test")
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    samples = []
    try:
        deadline = time.monotonic() + 30
        while not done.is_set() and time.monotonic() < deadline:
            _, _, body = _get(srv.url, "/queries")
            snap = json.loads(body)
            for q in snap["queries"]:
                if q["query_id"] == "slow_poll_test" and q["stages"]:
                    samples.append(q["stages"][0]["rows"])
            if len([s for s in samples if s > 0]) >= 3 and len(set(samples)) >= 3:
                break
            time.sleep(0.02)
    finally:
        t.join(timeout=60)
    assert done.is_set(), "slow query never finished"
    assert len(result["out"]["v"]) == n_rows
    # mid-flight observability: at least two strictly increasing
    # nonzero row counts BEFORE completion-time totals
    increasing = [s for s in samples if 0 < s < n_rows]
    assert len(set(increasing)) >= 2, (
        f"no mid-flight progress observed: samples={samples}")
    assert sorted(samples) == samples, f"progress regressed: {samples}"
    # /metrics parses mid-run state too
    _, ctype, body = _get(srv.url, "/metrics")
    assert ctype.startswith("text/plain")
    fams = _assert_prometheus(body.decode())
    assert "blaze_query_stage_rows" in fams
    assert "blaze_monitor_queries" in fams


def test_queries_endpoint_scheduler_run(data, armed_monitor):
    """A scheduler-path run registers per-stage live state: map stages
    carry task heartbeat rows, the result stage carries driver rows,
    and the recovery tallies ride on the query entry."""
    srv = monitor.ensure_server()
    with monitor.query_span("mon_q1", mode="scheduler"):
        stages, mgr = split_stages(build_query("q1", _scans(data), 2))
        rows = sum(b.num_rows for b in run_stages(stages, mgr))
    assert rows > 0
    _, _, body = _get(srv.url, "/queries")
    snap = json.loads(body)
    q = next(q for q in snap["queries"] if q["query_id"] == "mon_q1")
    assert q["status"] == "done" and q["mode"] == "scheduler"
    assert q["attempts"].get("task_attempts", 0) >= 3
    kinds = {s["kind"] for s in q["stages"]}
    assert "map" in kinds and "result" in kinds
    result_stage = next(s for s in q["stages"] if s["kind"] == "result")
    assert result_stage["rows"] == rows
    assert result_stage["tasks_done"] == result_stage["n_tasks"]
    map_stage = next(s for s in q["stages"] if s["kind"] == "map")
    # task heartbeats reported operator rows for driver-invisible maps
    assert map_stage["task_rows"] > 0
    # ...and NOT inflated by the operator-chain depth (progress_rows is
    # the widest single node, never the tree sum): bounded by the
    # source table size
    n_lineitem = next(iter(data["lineitem"].values()))[0].shape[0]
    assert map_stage["task_rows"] <= n_lineitem, (
        map_stage["task_rows"], n_lineitem)
    assert map_stage["counters"].get("xla_dispatches", 0) > 0
    # memory block present
    assert set(snap["memory"]) == {"used", "total"}
    # /metrics reports the SAME row semantics for the map stage (the
    # driver-observed 0 would be indistinguishable from a wedged stage)
    line = next(
        l for l in monitor.render_prometheus().splitlines()
        if l.startswith("blaze_query_stage_rows")
        and 'query="mon_q1"' in l
        and f'stage="{map_stage["stage_id"]}"' in l)
    assert int(float(line.rsplit(" ", 1)[1])) == max(
        map_stage["rows"], map_stage["task_rows"]) > 0


def test_metrics_endpoint_renders_scheduler_tree(data, armed_monitor):
    srv = monitor.ensure_server()
    with monitor.query_span("mon_q6", mode="scheduler"):
        stages, mgr = split_stages(build_query("q6", _scans(data), 2))
        assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    _, _, body = _get(srv.url, "/metrics")
    fams = _assert_prometheus(body.decode())
    # scheduler root counters + per-stage labeled samples
    assert "blaze_scheduler_task_attempts" in fams
    assert any(f.startswith("blaze_stage_") for f in fams)
    stage_samples = [s for f, ss in fams.items() if f.startswith("blaze_stage_")
                     for s in ss]
    assert any('stage="' in s for s in stage_samples)
    # every rendered scheduler/stage/dispatch name is a registered one;
    # histogram families are registered under their FULL name (plus the
    # _bucket/_sum/_count sample suffixes the exposition format adds)
    registered = registered_metric_names()
    hist_fams = {n + suffix for n in registered
                 for suffix in ("", "_bucket", "_sum", "_count")}
    for fam in fams:
        if fam in hist_fams:
            continue
        for prefix in ("blaze_scheduler_", "blaze_stage_"):
            if fam.startswith(prefix):
                assert fam[len(prefix):] in registered, fam


def test_metrics_no_duplicate_series_for_repeated_query(armed_monitor):
    """Regression: the registry keeps every RUN of a query (unique
    keys), but /metrics labels series by query_id — repeated runs must
    export the latest only, or the whole scrape is rejected."""
    srv = monitor.ensure_server()
    for _ in range(2):
        with monitor.query_span("dup_q", mode="in-process"):
            with monitor.stage_span(0, "result", 1):
                pass
    _, _, body = _get(srv.url, "/queries")
    runs = [q for q in json.loads(body)["queries"]
            if q["query_id"] == "dup_q"]
    assert len(runs) == 2, "history must stay visible in /queries"
    _, _, body = _get(srv.url, "/metrics")
    _assert_prometheus(body.decode())  # uniqueness asserted in helper


def test_heartbeat_age_gauge_only_for_running_queries(armed_monitor):
    """Regression: a finished query's last_beat is frozen, so its
    heartbeat age climbs forever — exporting it would fire any
    wedge-detection alert on every NORMAL completion.  The gauge must
    cover running queries only (elapsed stays for both)."""
    srv = monitor.ensure_server()
    with monitor.query_span("hb_done", mode="in-process"):
        with monitor.stage_span(0, "result", 1):
            pass
    with monitor.query_span("hb_live", mode="in-process"):
        _, _, body = _get(srv.url, "/metrics")
        fams = _assert_prometheus(body.decode())
        ages = fams.get("blaze_query_heartbeat_age_seconds", [])
        assert any('query="hb_live"' in s for s in ages)
        assert not any('query="hb_done"' in s for s in ages)
        # elapsed is a plain duration, not a wedge signal: both export
        elapsed = fams["blaze_query_elapsed_seconds"]
        assert any('query="hb_done"' in s for s in elapsed)


def test_gateway_task_span_lands_task_identity(armed_monitor, tmp_path):
    """gateway.task_span brackets an FFI drive in the scheduler's
    task-attempt event shape and lands the task_id + rows in the live
    registry."""
    from blaze_tpu import gateway
    from blaze_tpu.batch import batch_from_pydict

    srv = monitor.ensure_server()
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with gateway.query_span("ffi_q") as path:
            with gateway.task_span("task_ffi_0", partition=0) as progress:
                schema = Schema([Field("v", DataType.int64())])
                progress.add_batch(
                    batch_from_pydict({"v": [1, 2, 3]}, schema))
        events = trace.read_event_log(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    types = [e["type"] for e in events]
    for t in ("query_start", "task_attempt_start", "stage_submit",
              "stage_complete", "task_attempt_end", "query_end"):
        assert t in types, f"missing {t}: {types}"
    schema_doc = trace.load_schema()
    for e in events:
        jsonschema.validate(e, schema_doc["events"][e["type"]])
    _, _, body = _get(srv.url, "/queries")
    q = next(q for q in json.loads(body)["queries"]
             if q["query_id"] == "ffi_q")
    task = q["stages"][0]["tasks"]["0"]
    assert task["task_id"] == "task_ffi_0"
    assert task["rows"] == 3
    # a bare task_span (no enclosing query-level stage) still counts
    # its own completion — 0/1 forever would read as a stuck drive
    assert q["stages"][0]["tasks_done"] == 1


def test_gateway_multi_task_query_opens_one_stage_span(armed_monitor,
                                                      tmp_path):
    """Regression: task_spans nested in a query_span share ONE stage
    span — a 2-task FFI drive must not reset the registry stage or
    emit duplicate stage_submit/stage_complete pairs for stage 0."""
    from blaze_tpu import gateway
    from blaze_tpu.batch import batch_from_pydict

    srv = monitor.ensure_server()
    schema = Schema([Field("v", DataType.int64())])
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with gateway.query_span("ffi_multi", n_tasks=2) as path:
            for part, vals in ((0, [1, 2]), (1, [3, 4, 5])):
                with gateway.task_span(f"t_{part}", partition=part) as p:
                    p.add_batch(batch_from_pydict({"v": vals}, schema))
        events = trace.read_event_log(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    types = [e["type"] for e in events]
    assert types.count("stage_submit") == 1
    assert types.count("stage_complete") == 1
    assert types.count("task_attempt_start") == 2
    # --report sees ONE stage-0 timeline row, like a scheduler log
    assert len(trace_report.render_json(events)["stages"]) == 1
    _, _, body = _get(srv.url, "/queries")
    q = next(q for q in json.loads(body)["queries"]
             if q["query_id"] == "ffi_multi")
    stage = q["stages"][0]
    assert stage["tasks_done"] == 2 and stage["n_tasks"] == 2
    assert stage["rows"] == 5  # both tasks' batches, not just the last
    assert {t["task_id"] for t in stage["tasks"].values()} == {"t_0", "t_1"}
    assert stage["tasks"]["1"]["rows"] == 3  # per-task delta, not total


def test_gateway_task_span_default_partition_stays_unique(armed_monitor):
    """Regression: the registry keys tasks by partition; a caller that
    omits it (JNI drives don't always know an index) must still get
    one entry PER task, not every task collapsed onto partition 0."""
    from blaze_tpu import gateway
    from blaze_tpu.batch import batch_from_pydict

    srv = monitor.ensure_server()
    schema = Schema([Field("v", DataType.int64())])
    with gateway.query_span("ffi_nopart", n_tasks=3):
        for i, vals in enumerate(([1], [2, 3], [4, 5, 6])):
            with gateway.task_span(f"t_{i}") as p:
                p.add_batch(batch_from_pydict({"v": vals}, schema))
    _, _, body = _get(srv.url, "/queries")
    q = next(q for q in json.loads(body)["queries"]
             if q["query_id"] == "ffi_nopart")
    stage = q["stages"][0]
    assert {t["task_id"] for t in stage["tasks"].values()} == {
        "t_0", "t_1", "t_2"}
    assert {t["rows"] for t in stage["tasks"].values()} == {1, 2, 3}


def test_ffi_export_accounting_scoped_to_gateway_span(armed_monitor):
    """Regression: export_batch_ffi feeds the ACTIVE gateway span's
    progress only — exports outside one (udf_bridge shipping UDF
    argument batches) must not mint phantom registry rows."""
    from blaze_tpu import gateway

    assert getattr(gateway._gw_tls, "progress", None) is None
    with monitor.query("no_gw_span", mode="in-process"):
        # a monitored non-gateway query leaves no export target
        assert getattr(gateway._gw_tls, "progress", None) is None
    with gateway.query_span("scoped_gw"):
        shared = gateway._gw_tls.progress
        assert shared is not None and shared.armed
        with gateway.task_span("t0") as p:
            assert p is shared  # task spans share the query stage
    assert getattr(gateway._gw_tls, "progress", None) is None
    snap = monitor.snapshot()
    no_span = next(q for q in snap["queries"]
                   if q["query_id"] == "no_gw_span")
    assert no_span["stages"] == []  # no phantom stage


def test_udf_argument_export_not_counted_as_progress(armed_monitor):
    """Regression: UDF *argument* batches cross export_batch_ffi INSIDE
    the task drive — i.e. inside an active gateway span — and must not
    be counted as query output (a UDF projection over N rows would
    report ~2N).  udf_bridge.evaluate suppresses span accounting for
    its whole FFI round-trip."""
    import inspect

    from blaze_tpu import gateway
    from blaze_tpu.batch import batch_from_pydict
    from blaze_tpu.spark import udf_bridge

    schema = Schema([Field("v", DataType.int64())])
    b = batch_from_pydict({"v": [1, 2, 3]}, schema)
    with gateway.query_span("udf_gw"):
        progress = gateway._gw_tls.progress
        gateway._count_span_progress(b)        # the unsuppressed path
        assert progress.rows == 3
        # the evaluator's RESULT export counts like any other, so
        # evaluate suppresses the whole round-trip
        with gateway.suppressed_span_progress():
            gateway._count_span_progress(b)
        assert progress.rows == 3              # intermediates uncounted
        assert gateway._gw_tls.progress is progress  # span restored
    # the call site contract: evaluate's argument AND evaluator-result
    # exports are intermediates, not output
    src = inspect.getsource(udf_bridge.evaluate)
    assert "suppressed_span_progress" in src
    snap = monitor.snapshot()
    q = next(q for q in snap["queries"] if q["query_id"] == "udf_gw")
    assert q["stages"][0]["rows"] == 3


def test_retry_rolls_back_partial_attempt_progress(armed_monitor):
    """Regression: StageProgress is cumulative across a stage; a failed
    attempt's partially-drained batches must roll back or the retry
    re-counts them (rows doubled exactly in the failure scenarios the
    monitor exists to make trustworthy)."""
    from blaze_tpu.batch import batch_from_pydict

    schema = Schema([Field("v", DataType.int64())])
    batches = [batch_from_pydict({"v": [1, 2, 3]}, schema)
               for _ in range(3)]
    with monitor.query("retry_q", mode="scheduler"):
        progress = monitor.StageProgress(0, "broadcast", 1)
        assert progress.armed
        mark = progress.mark()
        for b in batches:        # attempt 0: drains 3 batches, fails
            progress.add_batch(b)
        progress.rollback(mark)
        for b in batches:        # attempt 1: succeeds
            progress.add_batch(b)
        progress.task_done()
        progress.flush(force=True)
    snap = monitor.snapshot()
    st = next(q for q in snap["queries"]
              if q["query_id"] == "retry_q")["stages"][0]
    assert st["rows"] == 9       # not 18
    assert st["batches"] == 3    # not 6
    assert st["tasks_done"] == 1
    # disarmed: both are one-attribute-read no-ops
    disarmed = monitor.StageProgress(0, "map", 1)
    disarmed.armed = False
    assert disarmed.mark() is None
    disarmed.rollback(None)


def test_failed_attempt_task_beat_is_discarded(armed_monitor):
    """Regression: a failed attempt's registry heartbeat must go with
    its rollback — a retry faster than the heartbeat interval never
    beats again, so the stale entry's rows would inflate task_rows
    (and /queries, --watch, blaze_query_stage_rows) forever."""
    with monitor.query("beat_rb_q", mode="scheduler"):
        monitor.stage_started(0, "map", 2)
        monitor.task_beat(0, 0, 0, rows=10_000, batches=3,
                          progress_rows=10_000, task_id="t0")
        monitor.task_discard(0, 0)        # scheduler rollback path
        monitor.task_beat(0, 1, 0, rows=5, batches=1, progress_rows=5,
                          task_id="t1")   # an unrelated healthy task
    snap = monitor.snapshot()
    st = next(q for q in snap["queries"]
              if q["query_id"] == "beat_rb_q")["stages"][0]
    assert "0" not in st["tasks"]         # the failed beat is gone
    assert st["task_rows"] == 5           # not 10005


def test_abandoned_stream_leaves_no_stale_task_beat(data, armed_monitor):
    """Regression: the instrumented task stream activates its
    heartbeat TLS only while the plan drive runs (inside next()), not
    across yields — abandoning a half-consumed result stream must not
    leave a stale callback that would cross-attribute the dead task's
    beats into the next query on this thread."""
    plan = build_query("q6", _scans(data), 2)
    stages, manager = split_stages(plan)
    with monitor.query("abandoned_q", mode="scheduler"):
        gen = run_stages(stages, manager)
        next(gen)  # partially consume, keep the reference (no GC)
        assert getattr(monitor._tls, "task_beat", None) is None
    gen.close()
    assert getattr(monitor._tls, "task_beat", None) is None


def test_disarmed_stage_span_registers_no_dispatch_capture():
    """Regression: with tracing and the monitor both off, stage_span
    (the session.execute / in-process CLI / gateway wrapper) must not
    register a dispatch capture nobody reads — per-dispatch capture
    updates on previously capture-free paths break the structural
    no-op contract.  The scheduler opts back in: its MetricNode
    publishes dispatch counters even with observability off."""
    from blaze_tpu.runtime import dispatch

    conf.MONITOR_ENABLE.set(False)
    monitor.reset()
    assert not monitor.enabled() and not trace.enabled()
    n0 = len(dispatch._CAPTURES)
    with monitor.stage_span(0, "result", 1) as p:
        assert p.counters is None
        assert len(dispatch._CAPTURES) == n0
    with monitor.stage_span(0, "result", 1, capture_dispatch=True) as p:
        assert isinstance(p.counters, dict)
        assert len(dispatch._CAPTURES) == n0 + 1
    assert len(dispatch._CAPTURES) == n0


def test_server_handler_threads_are_named_and_tracked(armed_monitor):
    """Regression: stdlib block_on_close tracks only NON-daemon
    threads, so with daemon handlers it joins nothing — the server
    tracks its own named handler threads and server_close joins them
    (a survivor shows up in monitor_threads() by name)."""
    import socketserver

    srv = monitor.ensure_server()
    _get(srv.url, "/healthz")
    assert any(t.name == "blaze-monitor-handler"
               for t in srv._httpd._handlers)
    # a scraper disconnect mid-response must not traceback-spam the
    # monitored workload's stderr (default handle_error prints one)
    assert (type(srv._httpd).handle_error
            is not socketserver.BaseServer.handle_error)
    monitor.shutdown_server()
    assert monitor.monitor_threads() == []


def test_healthz_and_404(armed_monitor):
    srv = monitor.ensure_server()
    status, _, body = _get(srv.url, "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    with pytest.raises(urllib.error.HTTPError):
        _get(srv.url, "/nope")


def test_server_bind_conflict_falls_back_to_ephemeral(armed_monitor):
    """Regression: a bind failure on the configured port must not take
    down the monitored run — the server falls back to an ephemeral
    port (observability never kills the workload it observes)."""
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    taken = sock.getsockname()[1]
    try:
        conf.MONITOR_PORT.set(taken)
        monitor.reset()
        srv = monitor.ensure_server()
        assert srv is not None and srv.port != taken
        _get(srv.url, "/healthz")
    finally:
        sock.close()


def test_rerun_progress_does_not_clobber_stage_counters(armed_monitor):
    """Regression: the map-rerun path's StageProgress has no dispatch
    capture; its flushes must not overwrite the counters the original
    stage span recorded with an empty dict."""
    with monitor.query("rr_q", mode="scheduler"):
        monitor.stage_started(0, "map", 2)
        monitor.stage_progress_update(
            0, rows=10, bytes_=0, batches=1, tasks_done=1,
            counters={"xla_dispatches": 7})
        rerun = monitor.StageProgress(0, "map", 2)  # counters=None
        assert rerun.armed
        rerun.task_done()
        rerun.flush(force=True)
    snap = monitor.snapshot()
    st = next(q for q in snap["queries"]
              if q["query_id"] == "rr_q")["stages"][0]
    assert st["counters"] == {"xla_dispatches": 7}


def test_server_shutdown_leaves_no_threads(armed_monitor):
    srv = monitor.ensure_server()
    _get(srv.url, "/healthz")
    assert monitor.monitor_threads()
    monitor.shutdown_server()
    assert monitor.monitor_threads() == []
    # idempotent
    monitor.shutdown_server()


# ---------------------------------------------- 2. structural no-op

def test_monitor_off_is_structural_noop(data, monkeypatch):
    """With spark.blaze.monitor.enabled=false (default) a full
    scheduler run must never reach the registry writers, the heartbeat
    emitter, or the server — poisoned like the trace-off gate."""
    conf.MONITOR_ENABLE.set(False)
    conf.TRACE_ENABLE.set(False)
    monitor.reset()
    trace.reset()
    assert not monitor.enabled()

    def poisoned(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("monitor path entered while disarmed")

    # the registry writers and the heartbeat ticker must be
    # structurally unreachable (lifecycle sites may still CALL the
    # disarmed StageProgress methods — those return on one bool read)
    for fn in ("stage_started", "stage_finished", "stage_progress_update",
               "task_beat"):
        monkeypatch.setattr(monitor, fn, poisoned)
    monkeypatch.setattr(monitor._TaskBeatState, "tick", poisoned)

    stages, mgr = split_stages(build_query("q6", _scans(data), 2))
    rows = sum(b.num_rows for b in run_stages(stages, mgr))
    assert rows > 0
    assert monitor.counters() == {"updates": 0, "queries": 0}
    assert trace.counters() == {"events": 0, "spans": 0}
    assert monitor.server_port() is None
    assert monitor.monitor_threads() == []
    # the in-process gateway path is a no-op too
    sess, plan, n_rows = _slow_session(n_rows=100, n_batches=2, delay_s=0)
    assert len(sess.execute(plan)["v"]) == 100
    assert monitor.counters() == {"updates": 0, "queries": 0}


def test_stage_progress_disarmed_add_batch_is_cheap(data):
    """Disarmed StageProgress never materializes counters/heartbeat
    state — add_batch returns on the armed check alone."""
    conf.MONITOR_ENABLE.set(False)
    conf.TRACE_ENABLE.set(False)
    monitor.reset()
    trace.reset()
    p = monitor.StageProgress(0, "result", 1)
    assert not p.armed
    p.add_batch(object())  # would raise on .num_rows if armed
    p.task_done()
    p.flush(force=True)


# ------------------------------------------- 3. gateway-path spans

def _traced_events(tmp_path, fn, query_suffix=""):
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        fn()
        # the query span restored the previous (None) path; find the
        # file the run wrote
        files = sorted(
            (os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
             if query_suffix in f and f.endswith(".jsonl")),
            key=os.path.getmtime)
        return trace.read_event_log(files[-1])
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()


def test_session_execute_produces_query_stage_spans(data, tmp_path):
    """Acceptance: the non-scheduler session.execute path leaves a
    query -> stage -> kernel span tree in the event log."""
    sess, plan, n_rows = _slow_session(n_rows=200, n_batches=4, delay_s=0)

    def run():
        out = sess.execute(plan, query_id="gw_span_q")
        assert len(out["v"]) == n_rows

    events = _traced_events(tmp_path, run, "gw_span_q")
    types = [e["type"] for e in events]
    assert types[0] == "query_start" and types[-1] == "query_end"
    assert "stage_submit" in types and "stage_complete" in types
    comp = next(e for e in events if e["type"] == "stage_complete")
    assert comp["kind"] == "result" and comp["status"] == "ok"
    assert comp["programs"] >= 0 and "kernels" in comp
    schema = trace.load_schema()
    for e in events:
        jsonschema.validate(e, schema["events"][e["type"]])


def test_gateway_and_scheduler_reports_render_identically(data, tmp_path):
    """Acceptance: --report and --report --json render gateway-path
    logs with the same structure as scheduler-path logs (stage
    timeline present, same JSON stage keys)."""
    import contextlib
    import io

    from blaze_tpu.__main__ import main

    sess, plan, _ = _slow_session(n_rows=200, n_batches=4, delay_s=0)

    def run_gateway():
        sess.execute(plan, query_id="gw_report_q")

    def run_scheduler():
        with monitor.query_span("sched_report_q", mode="scheduler"):
            stages, mgr = split_stages(build_query("q6", _scans(data), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0

    gw_dir = tmp_path / "gw"
    sched_dir = tmp_path / "sched"
    gw_dir.mkdir()
    sched_dir.mkdir()
    gw_events = _traced_events(gw_dir, run_gateway, "gw_report_q")
    sched_events = _traced_events(sched_dir, run_scheduler, "sched_report_q")

    docs = {}
    for label, events in (("gw", gw_events), ("sched", sched_events)):
        text = trace_report.render(events)
        assert "stage timeline" in text
        assert "device" in text and "dispatch" in text
        docs[label] = trace_report.render_json(events)
    assert set(docs["gw"]) == set(docs["sched"])
    for doc in docs.values():
        assert doc["stages"], "no stage rows in JSON profile"
    gw_keys = set(docs["gw"]["stages"][0])
    sched_keys = set(docs["sched"]["stages"][0])
    assert gw_keys == sched_keys
    # the CLI path: text + --json written from the same log
    gw_log = sorted((str(p) for p in gw_dir.iterdir()
                     if str(p).endswith(".jsonl")), key=os.path.getmtime)[-1]
    out_json = str(tmp_path / "profile.json")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--report", gw_log, "--json", out_json])
    assert rc == 0
    assert "stage timeline" in buf.getvalue()
    with open(out_json) as f:
        disk_doc = json.load(f)
    assert set(disk_doc) == set(docs["gw"])


# ------------------------------------------------- 4. heartbeats

def test_heartbeat_events_roundtrip_schema_from_real_run(data, tmp_path):
    """A traced scheduler run with a fast heartbeat produces
    stage_progress AND task_heartbeat events that validate against the
    golden schema, with monotone per-task rows."""
    conf.MONITOR_HEARTBEAT_MS.set(1)
    monitor.reset()
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with trace.query("hb_q1") as path:
            stages, mgr = split_stages(
                build_query("q1", _scans(data, 2, 4096), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
        events = trace.read_event_log(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
        conf.MONITOR_HEARTBEAT_MS.set(1000)
        monitor.reset()
    schema = trace.load_schema()
    beats = [e for e in events if e["type"] == "task_heartbeat"]
    progress = [e for e in events if e["type"] == "stage_progress"]
    assert beats, "no task_heartbeat events despite 1ms cadence"
    assert progress, "no stage_progress events despite 1ms cadence"
    for e in beats + progress:
        jsonschema.validate(e, schema["events"][e["type"]])
    # map-task heartbeats carry operator metrics even with zero
    # driver-yielded rows
    map_beats = [e for e in beats if e["rows"] == 0]
    assert any(e["metrics"].get("output_rows", 0) > 0 for e in map_beats)
    # progress_rows = widest single node <= tree-summed output_rows
    for e in beats:
        assert 0 <= e["progress_rows"] <= e["metrics"].get("output_rows", 0)
    # per-(stage, task, attempt) heartbeat metrics are monotone
    by_task = {}
    for e in beats:
        key = (e["stage_id"], e["partition"], e["attempt"])
        prev = by_task.get(key, -1)
        cur = e["metrics"].get("output_rows", 0)
        assert cur >= prev, f"heartbeat regressed for {key}"
        by_task[key] = cur


def test_heartbeat_cadence_is_bounded(data, tmp_path):
    """At the default 1000ms cadence this fast q6 run emits (almost)
    no heartbeats — the events are interval-gated, not per-batch."""
    conf.MONITOR_HEARTBEAT_MS.set(60000)
    monitor.reset()
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with trace.query("fast_q6") as path:
            stages, mgr = split_stages(build_query("q6", _scans(data), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
        events = trace.read_event_log(path)
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
        conf.MONITOR_HEARTBEAT_MS.set(1000)
        monitor.reset()
    assert not [e for e in events if e["type"] == "task_heartbeat"]
    # stage_progress still appears exactly once per stage: the forced
    # final flush on stage close
    prog = [e for e in events if e["type"] == "stage_progress"]
    stages_seen = {e["stage_id"] for e in prog}
    assert len(prog) == len(stages_seen)


# ------------------------------------- 5. metric-name golden registry

def _source_metric_literals():
    """Every metric-name string literal in blaze_tpu source: first-arg
    literals of MetricsSet.add/set/timer and dispatch.record/record_max
    (+ counter= kwargs), plus the histogram/timer observation sites
    (observe_hist / record_timer) that carry full family names."""
    names = set()
    hist_re = re.compile(
        r'(?:observe_hist|record_timer)\(\s*"([a-z][a-z_0-9]*)"')
    pkg = os.path.join(REPO, "blaze_tpu")
    for root, _, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(root, fname)) as f:
                src = f.read()
            for m in hist_re.finditer(src):
                names.add(m.group(1))
            if fname == "monitor.py":
                # its _PromDoc.add calls carry derived FAMILY names
                # (blaze_query_*...), not tree metric names — EXCEPT
                # the fleet/SLO gauge families, which are registered
                # verbatim (worker_gauges / pool_gauges / slo_gauges),
                # and the runtime-stats drift gauges (stats_gauges)
                for m in re.finditer(
                        r'\.add\(\s*"(blaze_(?:worker|pool|slo|'
                        r'query_qerror|stage_skew)_'
                        r'[a-z_0-9]*)"', src):
                    names.add(m.group(1))
                continue
            for m in re.finditer(
                    r'(?:\.(?:add|set|timer)\(|record\(|record_max\(|counter=)'
                    r'\s*"([a-z][a-z_0-9]*)"', src):
                names.add(m.group(1))
    return names


def test_metric_names_registry_covers_source_literals():
    """Drift gate, way 1: every metric-name literal recorded anywhere
    in the source must be registered — a NEW metric lands in
    metric_names.json or fails tier-1."""
    registered = registered_metric_names()
    unregistered = _source_metric_literals() - registered
    assert not unregistered, (
        f"unregistered metric names (add them to "
        f"runtime/metric_names.json): {sorted(unregistered)}")


def test_metric_names_registry_has_no_stale_entries():
    """Drift gate, way 2: every registered name still appears as a
    literal in the source — a silent rename leaves a stale registry
    entry and fails tier-1 (dashboards keyed on the old name break)."""
    stale = registered_metric_names() - _source_metric_literals()
    assert not stale, (
        f"registered metric names no longer produced anywhere "
        f"(renamed without updating runtime/metric_names.json?): "
        f"{sorted(stale)}")


def test_metric_tree_names_are_registered_at_runtime(data):
    """Dynamic subset check: every name a real scheduler run lands in
    the MetricNode tree (operator metrics + mirrored dispatch
    counters) is registered."""
    from blaze_tpu.runtime import scheduler

    stages, mgr = split_stages(build_query("q1", _scans(data), 2))
    assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    registered = registered_metric_names()
    flat = scheduler.LAST_RUN_METRICS.flatten()
    produced = {k.split(":", 1)[1] for k in flat}
    assert produced, "no metrics produced"
    unknown = produced - registered
    assert not unknown, f"unregistered runtime metric names: {sorted(unknown)}"


def test_metric_names_registry_shape():
    from blaze_tpu.runtime.metrics import load_metric_names

    reg = load_metric_names()
    assert {"operator_metrics", "scheduler_counters",
            "dispatch_counters"} <= set(reg)
    flat = registered_metric_names()
    assert "output_rows" in flat and "xla_dispatches" in flat


# --------------------------------------------- 6. --report --json keys

GOLDEN_TOP_KEYS = {"query", "events", "stages", "totals", "kernels",
                   "plans", "data_movement", "memory", "recovery",
                   "progress"}
GOLDEN_STAGE_KEYS = {"stage_id", "kind", "n_tasks", "status", "start_s",
                     "wall_ns", "programs", "device_time_ns",
                     "dispatch_overhead_ns", "compile_ns", "counters"}
GOLDEN_KERNEL_KEYS = {"programs", "device_ns", "device_ns_scaled",
                      "dispatch_ns", "compile_ns", "timed", "sampled"}


def test_report_json_golden_keys(data, tmp_path):
    """The JSON profile shape is API for dashboards: pinned top-level,
    per-stage, and per-kernel keys (add keys freely, never rename)."""
    def run():
        with monitor.query_span("json_q1", mode="scheduler"):
            stages, mgr = split_stages(build_query("q1", _scans(data), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0

    events = _traced_events(tmp_path, run, "json_q1")
    doc = trace_report.render_json(events)
    assert GOLDEN_TOP_KEYS <= set(doc)
    for s in doc["stages"]:
        assert GOLDEN_STAGE_KEYS <= set(s)
    assert doc["kernels"], "no kernel table"
    for v in doc["kernels"].values():
        assert GOLDEN_KERNEL_KEYS <= set(v)
    assert doc["query"]["ids"] == ["json_q1"]
    assert doc["recovery"]["reconciled"] is True
    assert doc["totals"]["wall_ns"] > 0
    # the document is JSON-serializable as-is
    json.dumps(doc)


def test_report_json_recovery_section(tmp_path):
    events = [
        {"ts": 1.0, "type": "fault_injected", "site": "task.compute",
         "hit": 1, "attempt": 0},
        {"ts": 2.0, "type": "task_retry", "stage_id": 0, "task": 0,
         "attempt": 1, "reason": "InjectedFault"},
    ]
    doc = trace_report.render_json(events)
    assert doc["recovery"]["injected"] == 1
    assert doc["recovery"]["recoveries"] == 1
    assert doc["recovery"]["reconciled"] is True
    assert doc["recovery"]["incidents"][0]["type"] == "fault_injected"


# ------------------------------------------------- 7. CLI + watch

def test_render_watch_table():
    snap = {
        "ts": 0.0,
        "queries": [{
            "query_id": "tpch_q1", "mode": "scheduler", "status": "running",
            "started_at": 0.0, "elapsed_s": 3.2, "heartbeat_age_s": 0.1,
            "attempts": {"task_attempts": 5, "task_retries": 1,
                         "fetch_failures": 0},
            "mem_peak_bytes": 1024,
            "stages": [
                {"stage_id": 0, "kind": "map", "status": "ok", "n_tasks": 2,
                 "tasks_done": 2, "rows": 0, "bytes": 0, "batches": 0,
                 "task_rows": 123456, "tasks": {},
                 "counters": {"xla_dispatches": 34},
                 "elapsed_s": 2.1, "heartbeat_age_s": 0.1},
                {"stage_id": 1, "kind": "result", "status": "running",
                 "n_tasks": 1, "tasks_done": 0, "rows": 42, "bytes": 2048,
                 "batches": 1, "task_rows": 42, "tasks": {},
                 "counters": {}, "elapsed_s": 1.0, "heartbeat_age_s": 5.0},
            ],
        }],
        "memory": {"used": 512, "total": 4096},
    }
    out = monitor.render_watch(snap, "http://127.0.0.1:9")
    assert "tpch_q1" in out and "RUNNING" in out
    assert "123,456" in out          # map progress from task heartbeats
    assert "attempts 5 retries 1" in out
    assert "5.0s" in out             # the wedge detector column
    empty = monitor.render_watch({"queries": [], "memory": {}})
    assert "no queries" in empty


def test_watch_cli_polls_live_server(armed_monitor, capsys):
    from blaze_tpu.__main__ import _watch

    srv = monitor.ensure_server()
    with monitor.query_span("watch_q", mode="in-process"):
        pass
    rc = _watch(str(srv.port), interval=0.01, polls=2)
    assert rc == 0
    out = capsys.readouterr().out
    assert "watch_q" in out or "queries 1" in out


def test_watch_cli_unreachable():
    from blaze_tpu.__main__ import _watch

    rc = _watch("http://127.0.0.1:1", interval=0.01, polls=1)
    assert rc == 1


def test_json_without_report_is_a_usage_error(capsys):
    from blaze_tpu.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["tpch", "q6", "--json", "/tmp/out.json"])
    assert exc.value.code == 2
    assert "--json requires --report" in capsys.readouterr().err


def test_chaos_cli_with_monitor_shuts_down_cleanly(data):
    """Satellite: --chaos --monitor runs the fault smoke with the
    monitor armed and asserts the server shut down without leaking a
    thread (exit 0 = chaos reconciled AND clean shutdown)."""
    from blaze_tpu.__main__ import main

    before = len(monitor.monitor_threads())
    assert before == 0
    rc = main(["tpch", "q6", "--chaos", "--monitor", "--monitor-port", "0",
               "--scale", "0.002", "--parts", "2", "--chaos-faults", "2"])
    assert rc == 0
    assert monitor.monitor_threads() == []
    conf.MONITOR_ENABLE.set(False)
    monitor.reset()
