"""Auxiliary components: bloom filter, RSS shuffle write path, python
UDF wrapper — ≙ reference spark_bloom_filter tests, rss shuffle, and
the SparkUDFWrapper round trip."""

import numpy as np
import pytest

from blaze_tpu.batch import (
    Column,
    batch_from_pydict,
    batch_to_pydict,
    column_from_numpy,
)
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.bloom import SparkBloomFilter
from blaze_tpu.exprs.ir import PythonUdf, func
from blaze_tpu.io.batch_serde import deserialize_batch
from blaze_tpu.io.ipc_compression import decompress_frame
from blaze_tpu.ops import FilterExec, MemoryScanExec, ProjectExec
from blaze_tpu.parallel.rss import LocalRssWriter, RssShuffleWriterExec
from blaze_tpu.parallel.shuffle import HashPartitioning
from blaze_tpu.runtime.context import RESOURCES, TaskContext
from blaze_tpu.schema import DataType, Field, Schema


def test_bloom_filter_basic():
    f = SparkBloomFilter.create(1000)
    inserted = np.arange(0, 2000, 2, dtype=np.int64)
    f.put_longs(inserted)
    # no false negatives
    assert f.might_contain_longs(inserted).all()
    # low false-positive rate on disjoint values
    probe = np.arange(1, 4001, 2, dtype=np.int64)
    fpr = f.might_contain_longs(probe).mean()
    assert fpr < 0.1


def test_bloom_filter_serde_roundtrip():
    f = SparkBloomFilter.create(100)
    f.put_longs(np.array([1, 7, 42], np.int64))
    g = SparkBloomFilter.deserialize(f.serialize())
    assert g.num_hashes == f.num_hashes
    assert (g.words == f.words).all()
    assert g.might_contain_longs(np.array([1, 7, 42], np.int64)).all()


def test_bloom_device_matches_host():
    f = SparkBloomFilter.create(500)
    f.put_longs(np.arange(100, dtype=np.int64) * 3)
    vals = np.arange(0, 300, dtype=np.int64)
    c = column_from_numpy(DataType.int64(), vals)
    dev = np.asarray(f.might_contain_device(c.to_device()))[: len(vals)]
    host = f.might_contain_longs(vals)
    assert (dev == host).all()


def test_might_contain_expr():
    f = SparkBloomFilter.create(100)
    f.put_longs(np.array([5, 10], np.int64))
    schema = Schema([Field("k", DataType.int64())])
    src = MemoryScanExec([[batch_from_pydict({"k": [5, 6, 10, None]}, schema)]], schema)
    e = func("might_contain", lit(f.serialize(), DataType.binary(64)), col("k"))
    plan = FilterExec(src, e)
    got = batch_to_pydict(list(plan.execute(0, TaskContext(0, 1)))[0])
    assert 5 in got["k"] and 10 in got["k"] and 6 not in got["k"] and None not in got["k"]


def test_rss_shuffle_writer():
    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    n = 200
    src = MemoryScanExec(
        [[batch_from_pydict({"k": list(range(n)), "v": list(range(n))}, schema)]], schema
    )
    writer = LocalRssWriter()
    RESOURCES.put("rss_test.0", writer)
    ex = RssShuffleWriterExec(src, HashPartitioning([col("k")], 4), "rss_test")
    list(ex.execute(0, TaskContext(0, 1)))
    assert writer.closed
    # all rows arrive, partitioned by spark hash
    from blaze_tpu.exprs.hash import murmur3_columns, pmod

    total = 0
    for pid, frames in writer.partitions.items():
        for frame in frames:
            b = deserialize_batch(decompress_frame(frame), schema)
            d = batch_to_pydict(b)
            total += b.num_rows
            c = column_from_numpy(DataType.int64(), np.array(d["k"], np.int64))
            pids = np.asarray(pmod(murmur3_columns([c.to_device()]), 4))[: b.num_rows]
            assert (pids == pid).all()
    assert total == n


def test_python_udf_wrapper():
    schema = Schema([Field("a", DataType.int64()), Field("s", DataType.string(16))])
    src = MemoryScanExec(
        [[batch_from_pydict({"a": [1, 2, None], "s": ["x", "yy", "zzz"]}, schema)]], schema
    )
    udf = PythonUdf(
        fn=lambda a, s: (a or 0) * 10 + len(s),
        args=[col("a"), col("s")],
        dtype=DataType.int64(),
    )
    plan = ProjectExec(src, [col("a"), udf.alias("u")])
    got = batch_to_pydict(list(plan.execute(0, TaskContext(0, 1)))[0])
    assert got["u"] == [11, 22, 3]
    # UDF result composes with device exprs downstream
    plan2 = FilterExec(
        MemoryScanExec([[batch_from_pydict({"a": [1, 2, None], "s": ["x", "yy", "zzz"]}, schema)]], schema),
        PythonUdf(fn=lambda a: a is not None and a > 1, args=[col("a")], dtype=DataType.bool_()),
    )
    got2 = batch_to_pydict(list(plan2.execute(0, TaskContext(0, 1)))[0])
    assert got2["a"] == [2]


def test_bloom_filter_agg_two_stage():
    """bloom_filter agg (≙ agg/bloom_filter.rs): partial per partition,
    OR-merge, final payload probed by might_contain on device."""
    import numpy as np

    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col, lit
    from blaze_tpu.exprs.ir import Lit, ScalarFunc
    from blaze_tpu.ops import MemoryScanExec, ProjectExec
    from blaze_tpu.ops.agg import AggMode
    from blaze_tpu.ops.bloom_agg import BloomFilterAggExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    schema = Schema([Field("k", DataType.int64())])
    members = list(range(0, 2000, 2))
    parts = [
        [batch_from_pydict({"k": members[:500]}, schema)],
        [batch_from_pydict({"k": members[500:]}, schema)],
    ]
    scan = MemoryScanExec(parts, schema)
    partial = BloomFilterAggExec(scan, col("k"), "bf", AggMode.PARTIAL,
                                 expected_items=2000)
    # collect partial states from both partitions into one input
    states = []
    for p in range(2):
        states.extend(partial.execute(p, TaskContext(p, 2)))
    merged_in = MemoryScanExec([states], partial.schema)
    final = BloomFilterAggExec(merged_in, None, "bf", AggMode.FINAL,
                               expected_items=2000)
    out = list(final.execute(0, TaskContext(0, 1)))[0]
    from blaze_tpu.batch import column_to_pylist

    payload = column_to_pylist(out.columns[0], 1)[0]
    assert isinstance(payload, bytes)

    # probe: every member true; non-members mostly false (fpp ~3%)
    probe_schema = Schema([Field("x", DataType.int64())])
    xs = members + list(range(1, 4001, 2))  # odds are non-members
    pb = batch_from_pydict({"x": xs}, probe_schema)
    proj = ProjectExec(
        MemoryScanExec([[pb]], probe_schema),
        [ScalarFunc("might_contain", [Lit(payload), col("x")]).alias("hit")],
    )
    d = batch_to_pydict(list(proj.execute(0, TaskContext(0, 1)))[0])
    hits = d["hit"]
    assert all(hits[: len(members)]), "false negative in bloom filter"
    fp = sum(1 for h in hits[len(members):] if h) / 2000
    assert fp < 0.1, f"false-positive rate too high: {fp}"

    # proto roundtrip of the partial node
    rt = plan_from_proto(plan_to_proto(
        BloomFilterAggExec(MemoryScanExec(parts, schema), col("k"), "bf",
                           AggMode.PARTIAL, expected_items=2000)
    ))
    s2 = list(rt.execute(0, TaskContext(0, 2)))
    assert s2 and s2[0].num_rows == 1


def test_rss_service_end_to_end():
    """Real push/fetch RSS protocol over TCP: map tasks push partition
    frames to the service (≙ Celeborn client path), reduce tasks fetch
    blocks and stream them through IpcReaderExec."""
    from blaze_tpu.parallel.rss import RssShuffleWriterExec
    from blaze_tpu.parallel.rss_service import (
        RssServer, SocketRssWriter, rss_fetch_blocks,
    )
    from blaze_tpu.parallel.shuffle import IpcReaderExec

    schema = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])
    n_maps, n_out, n = 2, 3, 120
    parts = []
    expected = []
    for m in range(n_maps):
        d = {
            "k": [m * 1000 + i for i in range(n)],
            "v": [i * 3 for i in range(n)],
        }
        expected.extend(zip(d["k"], d["v"]))
        parts.append([batch_from_pydict(d, schema)])

    with RssServer() as server:
        src = MemoryScanExec(parts, schema)
        for m in range(n_maps):
            writer = SocketRssWriter(server.host, server.port, shuffle_id=7, map_id=m)
            RESOURCES.put(f"rss_e2e.{m}", writer)
            ex = RssShuffleWriterExec(src, HashPartitioning([col("k")], n_out), "rss_e2e")
            list(ex.execute(m, TaskContext(m, n_maps)))
            # barrier semantics: committed only once ALL maps report
            assert server.is_committed(7, expected_maps=m + 1)
            assert not server.is_committed(7, expected_maps=n_maps) or m == n_maps - 1
        assert server.is_committed(7, expected_maps=n_maps)

        got = []
        per_part_keys = []
        for p in range(n_out):
            blocks = rss_fetch_blocks(
                server.host, server.port, 7, p, expected_maps=n_maps
            )
            RESOURCES.put(f"rss_read.{p}", blocks)
            reader = IpcReaderExec(schema, "rss_read", n_out)
            keys = set()
            for b in reader.execute(p, TaskContext(p, n_out)):
                d = batch_to_pydict(b)
                got.extend(zip(d["k"], d["v"]))
                keys.update(d["k"])
            per_part_keys.append(keys)
    assert sorted(got) == sorted(expected)
    for i in range(n_out):
        for j in range(i + 1, n_out):
            assert not (per_part_keys[i] & per_part_keys[j])


def test_rss_speculative_attempts_first_mapper_end_wins():
    """Celeborn semantics: two CONCURRENT attempts of one map push
    under distinct attempt ids; the FIRST mapperEnd wins the map id,
    the loser's commit is a no-op and its data is never served — a
    reducer can never see a mix of attempts (CelebornPartitionWriter
    pushData/mapperEnd contract)."""
    from blaze_tpu.parallel.rss_service import (
        RssServer, SocketRssWriter, rss_fetch_blocks,
    )

    with RssServer() as server:
        a0 = SocketRssWriter(server.host, server.port, shuffle_id=21,
                             map_id=0, attempt_id=0)
        a1 = SocketRssWriter(server.host, server.port, shuffle_id=21,
                             map_id=0, attempt_id=1)
        # both attempts push interleaved (speculation)
        a0.write(0, b"a0-block1")
        a1.write(0, b"a1-block1")
        a0.write(1, b"a0-block2")
        a1.write(1, b"a1-block2")
        assert a0.partition_lengths == {0: 9, 1: 9}

        a1.close()  # attempt 1 ends first -> wins
        a0.close()  # attempt 0 ends second -> no-op loser
        assert a1.won and not a0.won

        assert rss_fetch_blocks(
            server.host, server.port, 21, 0, expected_maps=1
        ) == [b"a1-block1"]
        assert rss_fetch_blocks(
            server.host, server.port, 21, 1, expected_maps=1
        ) == [b"a1-block2"]


def test_rss_cleanup_and_unregister():
    """cleanup discards an attempt's staged pushes (≙ ShuffleClient.
    cleanup); unregister frees a shuffle's published blocks
    (≙ unregisterShuffle)."""
    from blaze_tpu.parallel.rss_service import (
        RssServer, SocketRssWriter, rss_fetch_blocks,
        rss_unregister_shuffle,
    )

    with RssServer() as server:
        w = SocketRssWriter(server.host, server.port, shuffle_id=31, map_id=0)
        w.write(0, b"doomed")
        w.abort()  # cleanup: staged pushes discarded, no commit
        assert not server.is_committed(31, expected_maps=1)

        w2 = SocketRssWriter(server.host, server.port, shuffle_id=31, map_id=0)
        w2.write(0, b"kept")
        w2.close()
        assert w2.won
        assert rss_fetch_blocks(
            server.host, server.port, 31, 0, expected_maps=1) == [b"kept"]

        assert server.is_registered(31)
        rss_unregister_shuffle(server.host, server.port, 31)
        assert not server.is_registered(31)
        # post-unregister fetch with no barrier: nothing served
        assert rss_fetch_blocks(
            server.host, server.port, 31, 0, expected_maps=0) == []


def test_rss_straggler_commit_after_unregister_is_tombstoned():
    """(review finding) A straggler attempt's mapperEnd landing AFTER
    unregisterShuffle must not resurrect the shuffle: its blocks are
    discarded, the commit reports lost, and the shuffle stays dead."""
    from blaze_tpu.parallel.rss_service import (
        RssServer, SocketRssWriter, rss_fetch_blocks,
        rss_unregister_shuffle,
    )

    with RssServer() as server:
        # winner commits; straggler a0 stays connected with staged data
        a0 = SocketRssWriter(server.host, server.port, shuffle_id=41,
                             map_id=0, attempt_id=0)
        a1 = SocketRssWriter(server.host, server.port, shuffle_id=41,
                             map_id=0, attempt_id=1)
        a0.write(0, b"straggler")
        a1.write(0, b"winner")
        a1.close()
        assert a1.won
        rss_unregister_shuffle(server.host, server.port, 41)
        assert not server.is_registered(41)
        a0.close()  # straggler's late mapperEnd
        assert not a0.won
        assert not server.is_registered(41)
        assert rss_fetch_blocks(
            server.host, server.port, 41, 0, expected_maps=0) == []


def test_rss_retry_and_barrier_semantics():
    """Map-attempt retry + fetch barrier: a failed attempt's partial
    pushes are never served (its retry's publication replaces them),
    an early fetch blocks until the commit lands, and a barrier
    timeout surfaces the commit counts to the client."""
    import threading
    import time

    from blaze_tpu import conf
    from blaze_tpu.parallel.rss_service import (
        RssServer, SocketRssWriter, rss_fetch_blocks,
    )

    with RssServer() as server:
        # attempt 1 of map 0 pushes one block, then dies (abort)
        w = SocketRssWriter(server.host, server.port, shuffle_id=11, map_id=0)
        w.write(0, b"stale-partial")
        w.abort()
        assert not server.is_committed(11, expected_maps=1)

        # early fetch blocks on the barrier until the retry commits
        got = {}

        def fetch():
            t0 = time.time()
            got["blocks"] = rss_fetch_blocks(
                server.host, server.port, 11, 0, expected_maps=1
            )
            got["dt"] = time.time() - t0

        th = threading.Thread(target=fetch)
        th.start()
        time.sleep(0.5)
        assert th.is_alive(), "fetch must wait for the map commit"

        # retry (same map id) re-pushes and commits: last attempt wins
        w2 = SocketRssWriter(server.host, server.port, shuffle_id=11, map_id=0)
        w2.write(0, b"good-1")
        w2.write(0, b"good-2")
        w2.close()
        th.join(10)
        assert not th.is_alive()
        assert got["blocks"] == [b"good-1", b"good-2"], got
        assert got["dt"] >= 0.5

        # barrier timeout carries the commit counts to the client
        conf.RSS_FETCH_BARRIER_TIMEOUT.set(0.3)
        try:
            try:
                rss_fetch_blocks(server.host, server.port, 11, 0, expected_maps=5)
                assert False, "expected barrier timeout"
            except ConnectionError as e:
                assert "1/5 map commits" in str(e)
        finally:
            conf.RSS_FETCH_BARRIER_TIMEOUT.set(120.0)


@pytest.mark.slow
def test_cli_runner_end_to_end(capsys):
    """python -m blaze_tpu: the benchmark-runner analogue (reference
    dev/run-tpcds-test + tpcds/benchmark-runner) — runs queries through
    datagen + plan build + both execution paths, reports per-query
    wall/rows, and surfaces unknown names."""
    from blaze_tpu.__main__ import main

    rc = main(["tpch", "q6", "--scale", "0.005"])
    out = capsys.readouterr().out
    assert rc == 0 and "tpch q6: 1 rows" in out

    rc = main(["tpcds", "q42", "--scale", "0.002", "--scheduler"])
    out = capsys.readouterr().out
    assert rc == 0 and "[scheduler]" in out and "tpcds q42:" in out

    rc = main(["tpch", "nope"])
    err = capsys.readouterr().err
    assert rc == 2 and "unknown tpch queries: nope" in err
