"""Latency histograms, exemplars, healthz admission state, statsd
timers, and flame-profile endpoint (tier-1, CPU backend).

1. **Histogram correctness** (acceptance): bucket counts vs recorded
   samples, sum/count, exemplar trace-id round-trip, quantiles.
2. **/metrics rendering**: OpenMetrics-style histogram families whose
   bucket exemplars resolve to the run's trace id.
3. **/queries + --watch**: p50/p95/p99 latency block.
4. **/healthz**: golden-pinned service admission block (queue depth,
   running count, shed totals) — the load-balancer drain signal.
5. **statsd**: ``|ms`` timer lines for query latency and queue wait,
   drained once per render, buckets kept off the gauge lines.
6. **Flame endpoint**: ``/queries/<id>/profile`` collapsed stacks.
7. **Overhead**: disarmed = structural no-op (poisoned observe);
   armed recording bounded by a budget test.
"""

import json
import math
import time
import urllib.request

import pytest

from blaze_tpu import conf
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.runtime import monitor, trace
from blaze_tpu.runtime.scheduler import run_stages, split_stages
from blaze_tpu.tpch import TPCH_SCHEMAS, build_query
from blaze_tpu.tpch.datagen import generate_all, table_to_batches


@pytest.fixture(autouse=True, scope="module")
def _lock_order_assertions():
    """Histogram observation runs from query/stage span exits across
    worker threads — the whole module runs under the armed lock-order
    assertion like test_monitor.py."""
    from blaze_tpu.analysis import locks as lock_verify

    conf.VERIFY_LOCKS.set(True)
    lock_verify.refresh()
    yield
    conf.VERIFY_LOCKS.set(False)
    lock_verify.refresh()


@pytest.fixture(scope="module")
def data():
    return generate_all(0.01)


def _scans(data, n_parts=2):
    return {
        name: MemoryScanExec(
            table_to_batches(data[name], TPCH_SCHEMAS[name], n_parts,
                             batch_rows=16384),
            TPCH_SCHEMAS[name],
        )
        for name in TPCH_SCHEMAS
    }


@pytest.fixture
def armed_monitor():
    conf.MONITOR_ENABLE.set(True)
    conf.MONITOR_PORT.set(0)
    conf.MONITOR_HEARTBEAT_MS.set(1)
    monitor.reset()
    try:
        yield monitor
    finally:
        monitor.shutdown_server()
        conf.MONITOR_ENABLE.set(False)
        conf.MONITOR_PORT.set(4048)
        conf.MONITOR_HEARTBEAT_MS.set(1000)
        monitor.reset()
        assert monitor.monitor_threads() == []


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return r.status, r.read()


# ------------------------------------------- 1. histogram correctness

def test_bucket_counts_match_recorded_samples():
    h = monitor.Histogram("t", bounds=(0.01, 0.1, 1.0))
    samples = [0.005, 0.01, 0.02, 0.09, 0.5, 2.0, 7.0]
    for v in samples:
        h.observe(v)
    snap = h.snapshot()
    # cumulative counts per upper bound, computed independently
    expected = []
    for b in (0.01, 0.1, 1.0, math.inf):
        expected.append((b, sum(1 for v in samples if v <= b)))
    assert snap["buckets"] == expected
    assert snap["count"] == len(samples)
    assert abs(snap["sum"] - sum(samples)) < 1e-9
    assert snap["max"] == 7.0


def test_exemplar_trace_id_roundtrip():
    h = monitor.Histogram("t", bounds=(0.01, 0.1, 1.0))
    h.observe(0.05, trace_id="a" * 32)   # bucket index 1 (le=0.1)
    h.observe(5.0, trace_id="b" * 32)    # +Inf bucket (index 3)
    h.observe(0.06)                      # no trace id: exemplar kept
    snap = h.snapshot()
    assert snap["exemplars"][1][0] == "a" * 32
    assert abs(snap["exemplars"][1][1] - 0.05) < 1e-9
    assert snap["exemplars"][3][0] == "b" * 32
    # the newest exemplar WITH a trace id wins its bucket
    h.observe(0.07, trace_id="c" * 32)
    assert h.snapshot()["exemplars"][1][0] == "c" * 32


def test_quantile_estimates():
    h = monitor.Histogram("t", bounds=(0.01, 0.1, 1.0, 10.0))
    for _ in range(90):
        h.observe(0.05)       # le=0.1
    for _ in range(9):
        h.observe(0.5)        # le=1.0
    h.observe(5.0)            # le=10.0
    assert h.quantile(0.5) == 0.1
    assert h.quantile(0.95) == 1.0
    assert h.quantile(0.999) == 10.0
    assert monitor.Histogram("e").quantile(0.5) == 0.0


# --------------------------------- 2. /metrics rendering + exemplars

def test_metrics_histograms_with_exemplar_resolving_to_trace(
        data, armed_monitor, tmp_path):
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with monitor.query_span("hist_q6", mode="scheduler") as lp:
            stages, mgr = split_stages(build_query("q6", _scans(data), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    run_tid = {e.get("trace_id") for e in trace.read_event_log(lp)}.pop()
    srv = monitor.ensure_server()
    # classic 0.0.4 scrape: histograms render WITHOUT exemplar syntax
    # (a 0.0.4 parser meeting one would reject the whole scrape)
    _, body = _get(srv.url, "/metrics")
    assert " # {" not in body.decode()
    # OpenMetrics negotiation via Accept: exemplars + # EOF terminator
    req = urllib.request.Request(
        srv.url + "/metrics",
        headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert "openmetrics-text" in r.headers.get("Content-Type", "")
        body = r.read()
    prom = body.decode()
    assert prom.endswith("# EOF\n")
    for fam in ("blaze_query_latency_seconds",
                "blaze_stage_wall_seconds",
                "blaze_program_device_seconds",
                "blaze_program_dispatch_seconds"):
        assert f"# TYPE {fam} histogram" in prom, fam
        assert f'{fam}_bucket{{le="+Inf"}}' in prom, fam
        assert f"{fam}_sum" in prom and f"{fam}_count" in prom, fam
    # the exemplar resolves to THE trace id of the run that landed it
    assert f'trace_id="{run_tid}"' in prom
    # bucket conservation: +Inf cumulative count == _count
    for line in prom.splitlines():
        if line.startswith('blaze_query_latency_seconds_bucket{le="+Inf"}'):
            inf_count = int(line.split("}")[1].split("#")[0].strip())
        if line.startswith("blaze_query_latency_seconds_count"):
            assert int(line.split()[1]) == inf_count


# --------------------------------------- 3. /queries + --watch tails

def test_queries_latency_block_and_watch(data, armed_monitor):
    for i in range(3):
        with monitor.query_span(f"lat_q{i}", mode="in-process"):
            with monitor.stage_span(0, "result", 1):
                time.sleep(0.002)
    snap = monitor.snapshot()
    lat = snap["latency"]["blaze_query_latency_seconds"]
    assert lat["count"] == 3
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]
    assert "blaze_stage_wall_seconds" in snap["latency"]
    frame = monitor.render_watch(snap)
    assert "latency: p50" in frame and "(3 queries)" in frame


# ------------------------------------------------ 4. /healthz golden

def test_healthz_service_admission_block_golden_keys(data, armed_monitor):
    from blaze_tpu.runtime import service

    # without a service: no block (liveness only)
    srv = monitor.ensure_server()
    _, body = _get(srv.url, "/healthz")
    assert "service" not in json.loads(body)

    prev = conf.SERVICE_MAX_CONCURRENT.get(), conf.SERVICE_MAX_QUEUED.get()
    conf.SERVICE_MAX_CONCURRENT.set(1)
    conf.SERVICE_MAX_QUEUED.set(0)
    svc = service.QueryService().start()
    try:
        _, body = _get(srv.url, "/healthz")
        doc = json.loads(body)
        # golden shape, BOTH ways: keys are API for load balancers
        assert set(doc["service"]) == set(monitor.HEALTHZ_SERVICE_KEYS)
        assert doc["service"]["accepting"] is True
        assert doc["service"]["shed_total"] == 0
        # saturate: a shed submission shows up in the drain signal
        scans = _scans(data)
        import threading

        release = threading.Event()

        def build():
            release.wait(10)
            return build_query("q6", scans, 2)

        h = svc.submit("block_q", build=build)
        with pytest.raises(service.QueryRejectedError):
            svc.submit("shed_q", build=lambda: build_query(
                "q6", scans, 2))
        _, body = _get(srv.url, "/healthz")
        doc = json.loads(body)
        assert doc["service"]["accepting"] is False
        assert doc["service"]["shed_total"] == 1
        assert doc["service"]["running"] == 1
        release.set()
        h.result(timeout=60)
    finally:
        svc.shutdown()
        conf.SERVICE_MAX_CONCURRENT.set(prev[0])
        conf.SERVICE_MAX_QUEUED.set(prev[1])


# ------------------------------------------------- 5. statsd timers

def test_statsd_ms_timer_lines_drain_once(data, armed_monitor):
    from blaze_tpu.runtime import service

    svc = service.QueryService().start()
    try:
        scans = _scans(data)
        h = svc.submit("stats_q6",
                       build=lambda: build_query("q6", scans, 2))
        assert sum(b.num_rows for b in h.result(timeout=60)) > 0
    finally:
        svc.shutdown()
    lines = monitor.render_statsd_lines()
    ms = [ln for ln in lines if ln.endswith("|ms")]
    names = {ln.split(":")[0] for ln in ms}
    assert "blaze_query_latency_ms" in names
    assert "blaze_admission_wait_ms" in names
    # histogram buckets stay off the gauge transport
    assert not any("_bucket" in ln for ln in lines)
    # timers are EVENTS: drained, so the next render pushes none twice
    again = monitor.render_statsd_lines()
    assert not any(ln.endswith("|ms") for ln in again)


def test_statsd_pusher_carries_timer_lines(armed_monitor):
    """The push loop sends whatever render_statsd_lines yields —
    including the |ms samples — in bounded datagrams."""
    import socket

    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5)
    with monitor.query_span("push_t", mode="in-process"):
        pass
    pusher = monitor._StatsdPusher(f"127.0.0.1:{rx.getsockname()[1]}")
    try:
        pusher._push_once()
        payload = b""
        try:
            while True:
                rx.settimeout(0.5)
                payload += rx.recv(65536) + b"\n"
        except socket.timeout:
            pass
        assert b"blaze_query_latency_ms:" in payload
        assert b"|ms" in payload
    finally:
        pusher._sock.close()
        rx.close()


# ------------------------------------------- 6. flame endpoint

def test_profile_endpoint_serves_collapsed_stacks(
        data, armed_monitor, tmp_path):
    conf.TRACE_ENABLE.set(True)
    conf.EVENT_LOG_DIR.set(str(tmp_path))
    trace.reset()
    try:
        with monitor.query_span("prof_q6", mode="scheduler"):
            stages, mgr = split_stages(build_query("q6", _scans(data), 2))
            assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    finally:
        conf.TRACE_ENABLE.set(False)
        conf.EVENT_LOG_DIR.set("")
        trace.reset()
    srv = monitor.ensure_server()
    status, body = _get(srv.url, "/queries/prof_q6/profile")
    assert status == 200
    lines = body.decode().splitlines()
    assert lines and all(" " in ln for ln in lines)
    stack, _, val = lines[0].rpartition(" ")
    assert stack.startswith("prof_q6;stage_")
    assert int(val) >= 1
    # unknown query -> 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.url, "/queries/no_such/profile")
    assert ei.value.code == 404


def test_profile_endpoint_untraced_explains(data, armed_monitor):
    with monitor.query_span("prof_plain", mode="in-process"):
        with monitor.stage_span(0, "result", 1):
            pass
    srv = monitor.ensure_server()
    status, body = _get(srv.url, "/queries/prof_plain/profile")
    assert status == 200
    assert b"no kernel data" in body


# ----------------------------------------------- 7. overhead contract

def test_disarmed_histogram_recording_is_structural_noop(
        data, monkeypatch):
    """Monitor off (the default): query/stage spans never reach the
    histogram, timer queue, or exemplar paths — poisoned like the
    monitor-off gate."""
    def poisoned(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("histogram path reached while disarmed")

    assert not monitor.enabled()
    monkeypatch.setattr(monitor.Histogram, "observe", poisoned)
    monkeypatch.setattr(monitor, "_histogram", poisoned)
    monkeypatch.setattr(monitor, "drain_timers", poisoned)
    with monitor.query_span("noop_q", mode="scheduler"):
        stages, mgr = split_stages(build_query("q6", _scans(data), 2))
        assert sum(b.num_rows for b in run_stages(stages, mgr)) > 0
    with monitor._hist_lock:
        assert not monitor._TIMERS


def test_armed_recording_overhead_budget(armed_monitor):
    """Tier-1 budget: armed histogram observation is a few dict/list
    ops under a leaf lock — 10k observations with exemplars must stay
    far under a second (generous bound; a regression to per-sample IO
    or rendering would blow it by orders of magnitude)."""
    tid = "f" * 32
    t0 = time.perf_counter()
    for i in range(10_000):
        monitor.observe_hist("blaze_query_latency_seconds",
                             (i % 100) / 1000.0, trace_id=tid)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"10k observations took {dt:.3f}s"
    snap = {h["name"]: h for h in monitor.histograms_snapshot()}
    assert snap["blaze_query_latency_seconds"]["count"] == 10_000
    # rendering the full exposition with histograms stays bounded too
    t0 = time.perf_counter()
    monitor.render_prometheus()
    assert time.perf_counter() - t0 < 1.0
