"""Window function additions: ntile, nth_value, lead/lag IGNORE NULLS,
RANGE offset frames — randomized differential tests vs python oracles
(the window surface beyond the reference's minimal processor set)."""

import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.ops import (
    MemoryScanExec, SortExec, SortField, WindowExec, WindowFunction,
)
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

RNG = np.random.RandomState(77)


def _make(n=200, n_groups=5, null_frac=0.2):
    g = RNG.randint(0, n_groups, n)
    k = RNG.randint(0, 50, n)
    v = RNG.randint(-100, 100, n).astype(object)
    for i in range(n):
        if RNG.rand() < null_frac:
            v[i] = None
    schema = Schema([
        Field("g", DataType.int64()), Field("k", DataType.int64()),
        Field("v", DataType.int64()),
    ])
    data = {"g": g.tolist(), "k": k.tolist(), "v": list(v)}
    return data, schema


def _run(data, schema, functions, order_key=True):
    src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    fields = [SortField(col("g"))] + ([SortField(col("k"))] if order_key else [])
    pre = SortExec(src, fields)
    w = WindowExec(
        pre, functions, [col("g")],
        [SortField(col("k"))] if order_key else [],
    )
    out = list(w.execute(0, TaskContext(0, 1)))[0]
    return batch_to_pydict(out)


def _partitions(d):
    """group -> rows sorted by (k), in engine row order."""
    rows = sorted(zip(d["g"], d["k"], range(len(d["g"]))), key=lambda t: (t[0], t[1]))
    parts = {}
    for g, k, i in rows:
        parts.setdefault(g, []).append(i)
    return parts


def test_ntile_matches_spark_bucketing():
    data, schema = _make()
    for n_buckets in (1, 3, 7):
        got = _run(data, schema, [WindowFunction("ntile", "t", offset=n_buckets)])
        parts = {}
        for i, g in enumerate(got["g"]):
            parts.setdefault(g, []).append(i)
        for g, idxs in parts.items():
            cnt = len(idxs)
            base, rem = divmod(cnt, n_buckets)
            exp = []
            for b in range(n_buckets):
                exp.extend([b + 1] * (base + (1 if b < rem else 0)))
            assert [got["t"][i] for i in idxs] == exp, (g, n_buckets)


def test_nth_value_default_and_whole_frames():
    data, schema = _make(null_frac=0.0)
    for k_, whole in [(1, False), (3, False), (2, True), (100, False)]:
        got = _run(data, schema, [
            WindowFunction("nth_value", "nv", col("v"), offset=k_,
                           whole_partition=whole),
        ])
        parts = {}
        for i, g in enumerate(got["g"]):
            parts.setdefault(g, []).append(i)
        for g, idxs in parts.items():
            vals = [got["v"][i] for i in idxs]
            ks = [got["k"][i] for i in idxs]
            for j, i in enumerate(idxs):
                if whole:
                    exp = vals[k_ - 1] if k_ <= len(idxs) else None
                else:
                    # default running frame: rows 0..peer_end(j)
                    peer_end = max(p for p in range(len(idxs)) if ks[p] == ks[j])
                    exp = vals[k_ - 1] if k_ - 1 <= peer_end else None
                assert got["nv"][i] == exp, (g, j, k_)


@pytest.mark.parametrize("kind,off", [("lag", 1), ("lag", 2), ("lead", 1), ("lead", 3)])
def test_lead_lag_ignore_nulls(kind, off):
    data, schema = _make(null_frac=0.35)
    got = _run(data, schema, [
        WindowFunction(kind, "x", col("v"), offset=off, ignore_nulls=True),
    ])
    parts = {}
    for i, g in enumerate(got["g"]):
        parts.setdefault(g, []).append(i)
    for g, idxs in parts.items():
        vals = [got["v"][i] for i in idxs]
        for j, i in enumerate(idxs):
            if kind == "lag":
                pool = [v for v in vals[:j] if v is not None]
                exp = pool[-off] if len(pool) >= off else None
            else:
                pool = [v for v in vals[j + 1:] if v is not None]
                exp = pool[off - 1] if len(pool) >= off else None
            assert got["x"][i] == exp, (g, j, kind, off)


@pytest.mark.parametrize("lo,hi", [(5, 5), (0, 10), (10, 0), (None, 3), (2, None)])
def test_range_offset_frame_sum_count_min_max(lo, hi):
    data, schema = _make(null_frac=0.2)
    got = _run(data, schema, [
        WindowFunction("sum", "s", col("v"), range_frame=(lo, hi)),
        WindowFunction("count", "c", col("v"), range_frame=(lo, hi)),
        WindowFunction("min", "mn", col("v"), range_frame=(lo, hi)),
        WindowFunction("max", "mx", col("v"), range_frame=(lo, hi)),
    ])
    parts = {}
    for i, g in enumerate(got["g"]):
        parts.setdefault(g, []).append(i)
    for g, idxs in parts.items():
        ks = [got["k"][i] for i in idxs]
        vs = [got["v"][i] for i in idxs]
        for j, i in enumerate(idxs):
            in_frame = [
                vs[p] for p in range(len(idxs))
                if (lo is None or ks[p] >= ks[j] - lo)
                and (hi is None or ks[p] <= ks[j] + hi)
                and vs[p] is not None
            ]
            if in_frame:
                assert got["s"][i] == sum(in_frame), (g, j)
                assert got["c"][i] == len(in_frame), (g, j)
                assert got["mn"][i] == min(in_frame), (g, j)
                assert got["mx"][i] == max(in_frame), (g, j)
            else:
                assert got["s"][i] is None and got["c"][i] == 0, (g, j)
                assert got["mn"][i] is None and got["mx"][i] is None, (g, j)


def test_range_frame_descending_order():
    data, schema = _make(null_frac=0.0)
    src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    fields = [SortField(col("g")), SortField(col("k"), ascending=False)]
    pre = SortExec(src, fields)
    w = WindowExec(
        pre,
        [WindowFunction("sum", "s", col("v"), range_frame=(3, 0))],
        [col("g")],
        [SortField(col("k"), ascending=False)],
    )
    got = batch_to_pydict(list(w.execute(0, TaskContext(0, 1)))[0])
    parts = {}
    for i, g in enumerate(got["g"]):
        parts.setdefault(g, []).append(i)
    for g, idxs in parts.items():
        ks = [got["k"][i] for i in idxs]
        vs = [got["v"][i] for i in idxs]
        for j, i in enumerate(idxs):
            # DESC order: "3 PRECEDING" = values up to 3 ABOVE current
            in_frame = [vs[p] for p in range(len(idxs))
                        if ks[j] <= ks[p] <= ks[j] + 3]
            assert got["s"][i] == sum(in_frame), (g, j)


def test_new_window_functions_proto_roundtrip():
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    data, schema = _make()
    src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("k"))])
    w = WindowExec(
        pre,
        [WindowFunction("ntile", "t", offset=4),
         WindowFunction("nth_value", "nv", col("v"), offset=2),
         WindowFunction("lag", "lg", col("v"), offset=1, ignore_nulls=True),
         WindowFunction("sum", "s", col("v"), range_frame=(5, None))],
        [col("g")],
        [SortField(col("k"))],
    )
    rt = plan_from_proto(plan_to_proto(w))
    a = batch_to_pydict(list(w.execute(0, TaskContext(0, 1)))[0])
    b = batch_to_pydict(list(rt.execute(0, TaskContext(0, 1)))[0])
    assert a == b


def test_range_frame_null_order_keys():
    """Spark null semantics for RANGE offset frames: null-key rows
    frame over their null peer group; non-null rows never see them."""
    n = 120
    g = RNG.randint(0, 3, n)
    k = [int(v) if RNG.rand() > 0.25 else None for v in RNG.randint(0, 20, n)]
    v = RNG.randint(1, 50, n)
    schema = Schema([
        Field("g", DataType.int64()), Field("k", DataType.int64()),
        Field("v", DataType.int64()),
    ])
    data = {"g": g.tolist(), "k": k, "v": v.tolist()}
    src = MemoryScanExec([[batch_from_pydict(data, schema)]], schema)
    pre = SortExec(src, [SortField(col("g")), SortField(col("k"))])
    w = WindowExec(
        pre,
        [WindowFunction("sum", "s", col("v"), range_frame=(2, 2)),
         WindowFunction("count", "c", col("v"), range_frame=(2, 2))],
        [col("g")],
        [SortField(col("k"))],
    )
    got = batch_to_pydict(list(w.execute(0, TaskContext(0, 1)))[0])
    parts = {}
    for i, gg in enumerate(got["g"]):
        parts.setdefault(gg, []).append(i)
    for gg, idxs in parts.items():
        ks = [got["k"][i] for i in idxs]
        vs = [got["v"][i] for i in idxs]
        for j, i in enumerate(idxs):
            if ks[j] is None:
                frame = [vs[p] for p in range(len(idxs)) if ks[p] is None]
            else:
                frame = [vs[p] for p in range(len(idxs))
                         if ks[p] is not None and ks[j] - 2 <= ks[p] <= ks[j] + 2]
            assert got["s"][i] == (sum(frame) if frame else None), (gg, j)
            assert got["c"][i] == len(frame), (gg, j)


def test_window_converter_new_functions():
    """ntile/nth_value/lead-ignore-nulls/RANGE frames through the
    catalyst toJSON converter (the layer test_window2 otherwise
    bypasses)."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    import json

    import spark_fixtures as F
    from blaze_tpu.spark import BlazeSparkSession

    n = 60
    data = {
        "g": [int(v) for v in RNG.randint(0, 3, n)],
        "k": [int(v) for v in RNG.randint(0, 15, n)],
        "v": [int(v) for v in RNG.randint(1, 50, n)],
    }
    schema = Schema([
        Field("g", DataType.int64()), Field("k", DataType.int64()),
        Field("v", DataType.int64()),
    ])
    sess = BlazeSparkSession()
    sess.register_table("t", data, schema, partitions=1)

    ag, ak, av = F.attr("g", 1), F.attr("k", 2), F.attr("v", 3)
    spec = F.T(F.X + "WindowSpecDefinition", [ag, F.sort_order(ak)],
               frameSpecification=None)

    def wexpr(fn_tree, name, eid):
        return F.alias(F.T(F.X + "WindowExpression", [fn_tree, spec]), name, eid)

    def spec_with_frame(ftype, lo_tree, hi_tree):
        frame = F.T(F.X + "SpecifiedWindowFrame", [lo_tree, hi_tree],
                    frameType={"product-class": F.X + ftype + "$"})
        return F.T(F.X + "WindowSpecDefinition", [ag, F.sort_order(ak), frame])

    ntile = F.T(F.X + "NTile", [F.lit(3, "integer")])
    nth = F.T(F.X + "NthValue", [av, F.lit(2, "integer")], ignoreNulls=False)
    lead_in = F.T(F.X + "Lead", [av, F.lit(1, "integer"), F.lit(None, "long")],
                  ignoreNulls=True)
    rsum = F.T(F.A + "AggregateExpression",
               [F.T(F.A + "Sum", [av])], mode="Complete", isDistinct=False,
               resultId=F.eid(90))
    range_spec = spec_with_frame(
        "RangeFrame",
        F.T(F.X + "UnaryMinus", [F.lit(2, "integer")]),
        F.lit(2, "integer"),
    )
    sorted_scan = F.sort(
        [F.sort_order(ag), F.sort_order(ak)], F.scan("t", [ag, ak, av])
    )
    w_node = F.T(
        F.P + "window.WindowExec",
        [sorted_scan],
        windowExpression=[
            F.flatten(wexpr(ntile, "t3", 10)),
            F.flatten(wexpr(nth, "nv", 11)),
            F.flatten(wexpr(lead_in, "ld", 12)),
            F.flatten(F.alias(F.T(F.X + "WindowExpression", [rsum, range_spec]), "rs", 13)),
        ],
        partitionSpec=[F.flatten(ag)],
        orderSpec=[F.flatten(F.sort_order(ak))],
    )
    got = sess.execute(json.dumps(F.flatten(w_node)))
    # root rename has no window mapping: columns come back keyed by
    # exprId (#10..#13), rows in (g, k) sort order
    order = sorted(range(n), key=lambda i: (data["g"][i], data["k"][i]))
    parts = {}
    for i in order:
        parts.setdefault(data["g"][i], []).append(i)
    out_rows = list(zip(got["#10"], got["#11"], got["#12"], got["#13"]))
    m = {}
    for row, i in zip(out_rows, order):
        m[i] = row
    for gg, idxs in parts.items():
        cnt = len(idxs)
        base, rem = divmod(cnt, 3)
        exp_t = []
        for b in range(3):
            exp_t.extend([b + 1] * (base + (1 if b < rem else 0)))
        for j, i in enumerate(idxs):
            t3, nv, ld, rs = m[i]
            assert t3 == exp_t[j], (gg, j)
            ks = [data["k"][p] for p in idxs]
            peer_end = max(p for p in range(cnt) if ks[p] == ks[j])
            assert nv == (data["v"][idxs[1]] if peer_end >= 1 else None), (gg, j)
            pool = [data["v"][p] for p in idxs[j + 1:]]
            assert ld == (pool[0] if pool else None), (gg, j)
            frame = [data["v"][p] for p in idxs
                     if ks[j] - 2 <= data["k"][p] <= ks[j] + 2]
            assert rs == sum(frame), (gg, j)
