"""Serving-scale query cache hierarchy (runtime/querycache.py):

1. **Plan cache / literal slots** — parameter-shifted variants of one
   plan shape share a fingerprint and ONE compiled fused program: the
   warm shifted run is gated at ZERO xla compiles.
2. **Result cache invalidation** — any source mutation (MemoryScan
   append/replace epoch bump, parquet/ORC file rewrite) changes the
   source version inside the fingerprint, so a stale entry is never
   served and a post-mutation run is byte-identical to a fresh one.
3. **Concurrency** — invalidate-during-hit races run under the armed
   lockset + lock-order checkers; every hit returns the complete row
   set for the epoch its fingerprint named.
"""

import threading

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col, lit
from blaze_tpu.ops import MemoryScanExec, ParquetScanExec
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.fusion import optimize_plan
from blaze_tpu.ops.orc_scan import OrcScanExec
from blaze_tpu.ops.project import ProjectExec
from blaze_tpu.runtime import dispatch, lockset, querycache
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

SCHEMA = Schema([Field("k", DataType.int64()),
                 Field("v", DataType.float64())])


@pytest.fixture(autouse=True)
def _fresh_cache():
    querycache.reset_for_tests()
    yield
    querycache.reset_for_tests()


def _batch(seed: int, n: int = 256):
    rng = np.random.RandomState(seed)
    return batch_from_pydict(
        {"k": rng.randint(0, 50, n).tolist(),
         "v": (rng.rand(n) * 100).round(3).tolist()}, SCHEMA)


def _param_plan(scan, thresh: float, factor: float):
    f = FilterExec(scan, col("v") > lit(float(thresh)))
    p = ProjectExec(f, [col("k").alias("k"),
                        (col("v") * lit(float(factor))).alias("v2")])
    return p


def _run(plan):
    out = []
    for part in range(plan.num_partitions()):
        for b in plan.execute(part, TaskContext(part,
                                                plan.num_partitions())):
            out.append(b)
    return out


def _rows(batches):
    rows = []
    for b in batches:
        d = batch_to_pydict(b)
        names = sorted(d)
        rows.extend(zip(*[d[n] for n in names]))
    return sorted(rows, key=repr)


# ------------------------------------------------ 1. plan cache / slots

def test_parameter_shift_zero_recompiles():
    """WHERE v > 5 and WHERE v > 9 (and a shifted projection factor)
    share one fused program: the second variant's warm run must not
    compile anything — the tentpole's program-reuse claim as a
    dispatch-budget gate."""
    scan = MemoryScanExec([[_batch(0)]], SCHEMA)
    base = optimize_plan(_param_plan(scan, 5.0, 2.0))
    _run(base)  # cold: compiles allowed
    with dispatch.capture() as warm:
        shifted = optimize_plan(_param_plan(scan, 9.0, 3.0))
        got = _rows(_run(shifted))
    assert warm.get("xla_compiles", 0) == 0, (
        f"literal shift recompiled: {warm}")
    # and the shifted program computed the SHIFTED answer
    d = batch_to_pydict(_batch(0))
    want = sorted(((k, round(v * 3.0, 10)) for k, v in zip(d["k"], d["v"])
                   if v > 9.0), key=repr)
    assert [(k, round(v, 10)) for k, v in got] == want


def test_shifted_literals_share_fingerprint_distinct_slots():
    scan = MemoryScanExec([[_batch(1)]], SCHEMA)
    fa = querycache.plan_fingerprint(optimize_plan(_param_plan(scan, 5.0, 2.0)))
    fb = querycache.plan_fingerprint(optimize_plan(_param_plan(scan, 9.0, 2.0)))
    assert fa is not None and fb is not None
    assert fa.exact and fb.exact
    assert fa.digest == fb.digest, "literal shift changed the digest"
    assert fa.slots != fb.slots
    assert fa.result_key() != fb.result_key()


def test_structural_literal_args_never_become_slots():
    """Type-determining literal args (decimal precision/scale, slice
    bounds) are read with ``.value`` at trace time — slotification must
    leave them as ``Lit`` while still slotting true data literals."""
    from blaze_tpu.exprs.compile import infer_dtype, slotify_literals
    from blaze_tpu.exprs.ir import Lit, ScalarFunc, Slot

    e = ScalarFunc("check_overflow",
                   [col("v") * lit(1.5), lit(12), lit(2)])
    (new,), vals = slotify_literals([e])
    assert isinstance(new.args[1], Lit) and isinstance(new.args[2], Lit)
    assert isinstance(new.args[0].right, Slot), "data literal must slot"
    assert len(vals) == 1 and float(vals[0]) == 1.5
    # type inference still works on the slotified tree
    t = infer_dtype(new, SCHEMA)
    assert t.is_decimal and t.precision == 12 and t.scale == 2


def test_result_cache_never_serves_other_slot_values():
    """Same digest, different slot values: the result key differs, so
    a WHERE v > 5 entry can never answer WHERE v > 9."""
    scan = MemoryScanExec([[_batch(2)]], SCHEMA)
    plan_a = optimize_plan(_param_plan(scan, 5.0, 2.0))
    fa = querycache.plan_fingerprint(plan_a)
    rc = querycache.result_cache()
    assert rc.store(fa, _run(plan_a))
    assert rc.lookup(fa) is not None
    fb = querycache.plan_fingerprint(optimize_plan(_param_plan(scan, 9.0, 2.0)))
    assert rc.lookup(fb) is None


# ------------------------------------------- 2. source-version changes

def _store_and_check_roundtrip(plan):
    fp = querycache.plan_fingerprint(plan)
    assert fp is not None and fp.exact, "plan must be exactly cacheable"
    rc = querycache.result_cache()
    fresh = _run(plan)
    assert rc.store(fp, fresh)
    got = rc.lookup(fp)
    assert got is not None
    assert _rows(got) == _rows(fresh)
    return fp, rc


def test_memoryscan_append_invalidates():
    scan = MemoryScanExec([[_batch(3)]], SCHEMA)
    plan = optimize_plan(_param_plan(scan, 10.0, 2.0))
    fp, rc = _store_and_check_roundtrip(plan)
    before = dispatch.counters().get("result_cache_invalidations", 0)
    scan.append(0, _batch(4))
    fp2 = querycache.plan_fingerprint(plan)
    assert fp2.digest == fp.digest and fp2.sources != fp.sources
    # the stale entry is dropped at lookup, never served
    assert rc.lookup(fp2) is None
    assert dispatch.counters()["result_cache_invalidations"] == before + 1
    # post-mutation recompute is byte-identical to a fresh run
    fresh = _run(plan)
    assert rc.store(fp2, fresh)
    assert _rows(rc.lookup(fp2)) == _rows(fresh)
    assert len(_rows(fresh)) > len(_rows(_run(
        optimize_plan(_param_plan(MemoryScanExec([[_batch(3)]], SCHEMA),
                                  10.0, 2.0)))))


def test_memoryscan_replace_invalidates():
    scan = MemoryScanExec([[_batch(5)]], SCHEMA)
    plan = optimize_plan(_param_plan(scan, 10.0, 2.0))
    fp, rc = _store_and_check_roundtrip(plan)
    scan.replace([[_batch(6)]])
    fp2 = querycache.plan_fingerprint(plan)
    assert fp2.sources != fp.sources
    assert rc.lookup(fp2) is None
    assert _rows(_run(plan)) == _rows(_run(optimize_plan(_param_plan(
        MemoryScanExec([[_batch(6)]], SCHEMA), 10.0, 2.0))))


def _write_file(path, n, writer):
    t = pa.table({"x": pa.array(list(range(n)), pa.int64())})
    writer(t, str(path))
    return Schema([Field("x", DataType.int64())])


def _file_scan_case(tmp_path, cls, writer, fname):
    """Shared body: rewrite-the-file invalidation for a file scan."""
    path = tmp_path / fname
    schema = _write_file(path, 300, writer)
    plan = cls([[str(path)]], schema)
    fp, rc = _store_and_check_roundtrip(plan)
    # rewrite with different content (size changes with row count, so
    # the (mtime_ns, size) version moves even on coarse-mtime
    # filesystems)
    _write_file(path, 450, writer)
    fp2 = querycache.plan_fingerprint(plan)
    assert fp2 is not None and fp2.sources != fp.sources
    assert rc.lookup(fp2) is None, "stale file-scan result served"
    fresh = _run(plan)
    assert sorted(x for r in _rows(fresh) for x in r) == list(range(450))
    assert rc.store(fp2, fresh)
    assert _rows(rc.lookup(fp2)) == _rows(fresh)


def test_parquet_rewrite_invalidates(tmp_path):
    import pyarrow.parquet as papq

    _file_scan_case(tmp_path, ParquetScanExec,
                    lambda t, p: papq.write_table(t, p), "t.parquet")


def test_orc_rewrite_invalidates(tmp_path):
    from pyarrow import orc as paorc

    _file_scan_case(tmp_path, OrcScanExec,
                    lambda t, p: paorc.write_table(t, p), "t.orc")


def test_deleted_source_file_is_uncacheable(tmp_path):
    import pyarrow.parquet as papq

    path = tmp_path / "gone.parquet"
    schema = _write_file(path, 10, lambda t, p: papq.write_table(t, p))
    plan = ParquetScanExec([[str(path)]], schema)
    assert querycache.plan_fingerprint(plan) is not None
    path.unlink()
    assert querycache.plan_fingerprint(plan) is None


# ------------------------------------------------- 3. budget mechanics

def test_lru_eviction_respects_byte_budget():
    rc = querycache.result_cache()
    scans = [MemoryScanExec([[_batch(10 + i, n=512)]], SCHEMA)
             for i in range(3)]
    plans = [optimize_plan(_param_plan(s, 0.0, 2.0)) for s in scans]
    fps = [querycache.plan_fingerprint(p) for p in plans]
    results = [_run(p) for p in plans]
    one = querycache._batches_nbytes([b.to_host() for b in results[0]])
    prev = conf.CACHE_RESULT_MAX_BYTES.get()
    conf.CACHE_RESULT_MAX_BYTES.set(int(one * 2.5))
    try:
        for fp, res in zip(fps, results):
            assert rc.store(fp, res)
        # budget fits ~2.5 entries: the LRU-coldest (first) was evicted
        assert rc.lookup(fps[0]) is None
        assert rc.lookup(fps[2]) is not None
        assert dispatch.counters().get("result_cache_evictions", 0) >= 1
        assert rc.stats()["total_bytes"] <= int(one * 2.5)
    finally:
        conf.CACHE_RESULT_MAX_BYTES.set(prev)


def test_oversized_entry_refused():
    rc = querycache.result_cache()
    scan = MemoryScanExec([[_batch(20, n=512)]], SCHEMA)
    plan = optimize_plan(_param_plan(scan, 0.0, 2.0))
    fp = querycache.plan_fingerprint(plan)
    prev = conf.CACHE_RESULT_MAX_ENTRY_BYTES.get()
    conf.CACHE_RESULT_MAX_ENTRY_BYTES.set(64)
    try:
        assert not rc.store(fp, _run(plan))
        assert rc.stats()["entries"] == 0
    finally:
        conf.CACHE_RESULT_MAX_ENTRY_BYTES.set(prev)


def test_spill_promote_roundtrip():
    """A spilled entry (memmgr pressure path) is promoted back on hit,
    byte-identical — the one-shot spill cursor is drained exactly once
    under the cache lock."""
    rc = querycache.result_cache()
    scan = MemoryScanExec([[_batch(21)]], SCHEMA)
    plan = optimize_plan(_param_plan(scan, 0.0, 2.0))
    fp = querycache.plan_fingerprint(plan)
    fresh = _run(plan)
    assert rc.store(fp, fresh)
    freed = rc._consumer.spill()
    assert freed > 0
    assert rc.stats()["resident_bytes"] == 0
    assert dispatch.counters().get("result_cache_spills", 0) >= 1
    got = rc.lookup(fp)
    assert got is not None and _rows(got) == _rows(fresh)
    assert rc.stats()["resident_bytes"] > 0  # promoted back
    # a second hit serves from RAM again
    assert _rows(rc.lookup(fp)) == _rows(fresh)


# --------------------------------------------- 4. concurrency, armed

def test_invalidate_during_hit_race_armed():
    """Readers hammer lookup() while a writer appends to the source
    and stores fresh results — under the armed lockset + lock-order
    checkers.  Every hit must return the COMPLETE row set of the epoch
    its fingerprint named: a fingerprint taken before the append may
    legitimately hit the old entry, but a post-append fingerprint must
    never see old rows."""
    from blaze_tpu.analysis import locks

    scan = MemoryScanExec([[_batch(30, n=128)]], SCHEMA)

    def plan():
        return optimize_plan(_param_plan(scan, 0.0, 2.0))

    rc = querycache.result_cache()
    expected = {}  # epoch -> sorted rows

    def publish():
        p = plan()
        fp = querycache.plan_fingerprint(p)
        rows = _run(p)
        expected[scan.epoch] = _rows(rows)
        assert rc.store(fp, rows)

    publish()
    errors = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                fp = querycache.plan_fingerprint(plan())
                got = rc.lookup(fp)
                if got is None:
                    continue
                epoch = fp.sources[0][2]
                want = expected.get(epoch)
                # expected[] is written before store() on the writer
                # thread, so a hit's epoch is always published
                if want is None or _rows(got) != want:
                    errors.append(
                        f"hit for epoch {epoch} served wrong rows")
                    return
        except Exception as e:  # noqa: BLE001 — surfaced to the assert
            errors.append(f"{type(e).__name__}: {e}")

    conf.VERIFY_LOCKS.set(True)
    locks.refresh()
    conf.VERIFY_LOCKSET.set(True)
    lockset.refresh()
    threads = [threading.Thread(target=reader) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for i in range(6):
            scan.append(0, _batch(31 + i, n=64))
            publish()
        stop.set()
        for t in threads:
            t.join(10)
    finally:
        stop.set()
        conf.VERIFY_LOCKS.set(False)
        locks.refresh()
        conf.VERIFY_LOCKSET.set(False)
        lockset.refresh()
        for t in threads:
            t.join(10)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    # the stale-drop path fired at least once across the appends
    assert dispatch.counters().get("result_cache_invalidations", 0) >= 1
