"""Nested types: ARRAY/MAP/STRUCT layouts, nested exprs, native
explode, collect_list/collect_set aggs, serde + proto roundtrips.

≙ reference coverage for generate/explode.rs, agg collect accs,
GetIndexedFieldExpr/GetMapValueExpr/NamedStructExpr
(datafusion-ext-exprs), and the Arrow nested encodings in
blaze.proto:738-941 — re-designed here as fixed max-elements padded
device layouts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.batch import batch_from_pydict, batch_to_pydict, concat_batches
from blaze_tpu.exprs import col, lit
from blaze_tpu.exprs.ir import (
    GetIndexedField,
    GetMapValue,
    GetStructField,
    NamedStruct,
    ScalarFunc,
)
from blaze_tpu.io.batch_serde import deserialize_batch, serialize_batch
from blaze_tpu.ops import MemoryScanExec, ProjectExec
from blaze_tpu.ops.agg import AggExec, AggFunction, AggMode, GroupingExpr
from blaze_tpu.ops.generate import GenerateExec, NativeGenerator
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema

ARR_T = DataType.array(DataType.int64(), 4)
MAP_T = DataType.map(DataType.string(8), DataType.int32(), 4)
ST_T = DataType.struct([Field("x", DataType.int32()), Field("s", DataType.string(8))])
NN_T = DataType.array(DataType.array(DataType.int32(), 3), 2)

SCHEMA = Schema(
    [Field("id", DataType.int32()), Field("a", ARR_T), Field("m", MAP_T),
     Field("st", ST_T), Field("nn", NN_T)]
)
DATA = {
    "id": [1, 2, 3, 4],
    "a": [[10, 20], None, [], [30, None, 50]],
    "m": [{"x": 1}, None, {}, {"y": 2, "z": None}],
    "st": [{"x": 1, "s": "hi"}, None, {"x": None, "s": "yo"}, {"x": 4, "s": None}],
    "nn": [[[1, 2], [3]], None, [], [None, [4, 5, 6]]],
}


def make_batch():
    return batch_from_pydict(DATA, SCHEMA)


def run_plan(plan):
    out = list(plan.execute(0, TaskContext(0, 1)))
    if not out:
        return {f.name: [] for f in plan.schema.fields}
    return batch_to_pydict(out[0]) if len(out) == 1 else batch_to_pydict(concat_batches(out))


# ------------------------------------------------------------ layouts

def test_pydict_roundtrip():
    assert batch_to_pydict(make_batch()) == DATA


def test_concat_take_capacity():
    b = make_batch()
    two = concat_batches([b, b])
    assert batch_to_pydict(two) == {k: v + v for k, v in DATA.items()}
    t = b.take(jnp.array([3, 0]), 2)
    d = batch_to_pydict(t)
    assert d["a"] == [[30, None, 50], [10, 20]]
    assert d["nn"] == [[None, [4, 5, 6]], [[1, 2], [3]]]
    assert batch_to_pydict(b.with_capacity(64)) == DATA


def test_serde_roundtrip():
    b = make_batch()
    rt = deserialize_batch(serialize_batch(b), SCHEMA)
    assert batch_to_pydict(rt) == DATA


def test_dtype_proto_roundtrip():
    from blaze_tpu.serde.from_proto import dtype_from_proto
    from blaze_tpu.serde.to_proto import dtype_to_proto

    for t in (ARR_T, MAP_T, ST_T, NN_T, DataType.map(DataType.int64(), ST_T, 3)):
        assert dtype_from_proto(dtype_to_proto(t)) == t


# ------------------------------------------------------------- exprs

def test_nested_exprs():
    b = make_batch()
    p = ProjectExec(
        MemoryScanExec([[b]], SCHEMA),
        [
            GetIndexedField(col("a"), 0).alias("a0"),
            GetIndexedField(col("a"), 9).alias("a9"),
            GetIndexedField(col("nn"), 1).alias("nn1"),
            GetMapValue(col("m"), "y").alias("my"),
            GetStructField(col("st"), "s").alias("ss"),
            NamedStruct(["u", "v"], [col("id"), lit(5)]).alias("ns"),
            ScalarFunc("make_array", [col("id"), lit(None)]).alias("ma"),
            ScalarFunc("size", [col("a")]).alias("sz"),
            ScalarFunc("map_keys", [col("m")]).alias("mk"),
            ScalarFunc("map_values", [col("m")]).alias("mv"),
            ScalarFunc("array_contains", [col("a"), lit(30)]).alias("ac"),
            ScalarFunc("array_contains", [col("a"), lit(999)]).alias("ac2"),
        ],
    )
    d = run_plan(p)
    assert d["a0"] == [10, None, None, 30]
    assert d["a9"] == [None] * 4
    assert d["nn1"] == [[3], None, None, [4, 5, 6]]
    assert d["my"] == [None, None, None, 2]
    assert d["ss"] == ["hi", None, "yo", None]
    assert d["ns"] == [{"u": i, "v": 5} for i in [1, 2, 3, 4]]
    assert d["ma"] == [[i, None] for i in [1, 2, 3, 4]]
    assert d["sz"] == [2, -1, 0, 3]  # size(NULL) = -1 (legacy.sizeOfNull)
    assert d["mk"] == [["x"], None, [], ["y", "z"]]
    assert d["mv"] == [[1], None, [], [2, None]]
    assert d["ac"] == [False, None, False, True]
    # not found + null element present -> NULL (three-valued logic)
    assert d["ac2"] == [False, None, False, None]


def test_expr_proto_roundtrip():
    from blaze_tpu.serde.from_proto import expr_from_proto
    from blaze_tpu.serde.to_proto import expr_to_proto

    b = make_batch()
    exprs = [
        GetIndexedField(col("a"), 1).alias("o"),
        GetMapValue(col("m"), "x").alias("o"),
        GetStructField(col("st"), "x").alias("o"),
        NamedStruct(["k"], [col("id")]).alias("o"),
    ]
    for e in exprs:
        rt = expr_from_proto(expr_to_proto(e))
        p1 = ProjectExec(MemoryScanExec([[b]], SCHEMA), [e])
        p2 = ProjectExec(MemoryScanExec([[b]], SCHEMA), [rt])
        assert run_plan(p1) == run_plan(p2)


# ----------------------------------------------------------- explode

def test_explode_array():
    b = make_batch()
    g = GenerateExec(MemoryScanExec([[b]], SCHEMA), NativeGenerator("explode", col("a")), [])
    d = run_plan(g)
    assert d["id"] == [1, 1, 4, 4, 4]
    assert d["col"] == [10, 20, 30, None, 50]
    # input columns (nested included) survive the gather
    assert d["m"] == [{"x": 1}] * 2 + [{"y": 2, "z": None}] * 3


def test_explode_outer_and_pos():
    b = make_batch()
    g = GenerateExec(
        MemoryScanExec([[b]], SCHEMA), NativeGenerator("pos_explode", col("a")), [], outer=True
    )
    d = run_plan(g)
    assert d["id"] == [1, 1, 2, 3, 4, 4, 4]
    assert d["pos"] == [0, 1, None, None, 0, 1, 2]
    assert d["col"] == [10, 20, None, None, 30, None, 50]


def test_explode_map():
    b = make_batch()
    g = GenerateExec(MemoryScanExec([[b]], SCHEMA), NativeGenerator("explode", col("m")), [])
    d = run_plan(g)
    assert d["id"] == [1, 4, 4]
    assert d["key"] == ["x", "y", "z"]
    assert d["value"] == [1, 2, None]


def test_explode_proto_roundtrip():
    from blaze_tpu.serde.from_proto import plan_from_proto
    from blaze_tpu.serde.to_proto import plan_to_proto

    b = make_batch()
    g = GenerateExec(MemoryScanExec([[b]], SCHEMA), NativeGenerator("explode", col("a")), [])
    rt = plan_from_proto(plan_to_proto(g))
    assert run_plan(rt) == run_plan(g)


# ------------------------------------------------------ collect aggs

AGG_SCHEMA = Schema(
    [Field("g", DataType.int32()), Field("v", DataType.int64()), Field("s", DataType.string(8))]
)
AGG_DATA = {
    "g": [1, 2, 1, 1, 2, 3, 1],
    "v": [10, 20, 10, None, 40, 50, 30],
    "s": ["a", "b", "a", "c", None, "d", "a"],
}


def _by_group(d):
    order = sorted(range(len(d["g"])), key=lambda i: d["g"][i])
    return {k: [v[i] for i in order] for k, v in d.items()}


def _two_level(fns, batches):
    src = MemoryScanExec([batches], AGG_SCHEMA)
    plan = AggExec(src, AggMode.PARTIAL, [GroupingExpr(col("g"), "g")], fns)
    plan = AggExec(plan, AggMode.FINAL, [GroupingExpr(col("g"), "g")], fns)
    return _by_group(run_plan(plan))


def test_collect_list_and_set():
    b = batch_from_pydict(AGG_DATA, AGG_SCHEMA)
    d = _two_level(
        [
            AggFunction("collect_list", col("v"), "cl"),
            AggFunction("collect_set", col("v"), "cs"),
            AggFunction("collect_list", col("s"), "sl"),
            AggFunction("collect_set", col("s"), "ss"),
        ],
        [b],
    )
    assert d["g"] == [1, 2, 3]
    assert sorted(d["cl"][0]) == [10, 10, 30] and sorted(d["cl"][1]) == [20, 40]
    assert d["cl"][2] == [50]
    assert sorted(d["cs"][0]) == [10, 30] and sorted(d["cs"][1]) == [20, 40]
    assert sorted(d["sl"][0]) == ["a", "a", "a", "c"] and d["sl"][1] == ["b"]
    assert sorted(d["ss"][0]) == ["a", "c"] and d["ss"][1] == ["b"] and d["ss"][2] == ["d"]


def test_collect_multi_batch_merge():
    """States merge across batches (exercises the ARRAY-state merging
    reduce, ≙ PartialMerge of collect accs)."""
    half1 = {k: v[:4] for k, v in AGG_DATA.items()}
    half2 = {k: v[4:] for k, v in AGG_DATA.items()}
    bs = [batch_from_pydict(half1, AGG_SCHEMA), batch_from_pydict(half2, AGG_SCHEMA)]
    d = _two_level(
        [AggFunction("collect_list", col("v"), "cl"), AggFunction("collect_set", col("s"), "ss")],
        bs,
    )
    assert d["g"] == [1, 2, 3]
    assert sorted(d["cl"][0]) == [10, 10, 30]
    assert sorted(d["cl"][1]) == [20, 40]
    assert sorted(d["ss"][0]) == ["a", "c"]


def test_collect_global_no_groups():
    src = MemoryScanExec([[batch_from_pydict(AGG_DATA, AGG_SCHEMA)]], AGG_SCHEMA)
    fns = [AggFunction("collect_set", col("v"), "cs")]
    plan = AggExec(src, AggMode.PARTIAL, [], fns)
    plan = AggExec(plan, AggMode.FINAL, [], fns)
    d = run_plan(plan)
    assert sorted(d["cs"][0]) == [10, 20, 30, 40, 50]


def test_collect_max_elems_drops():
    """Elements past the budget are dropped, not corrupted."""
    arr_t = DataType.array(DataType.int64(), 64)
    n = 100
    data = {"g": [1] * n, "v": list(range(n)), "s": ["x"] * n}
    d = _two_level([AggFunction("collect_list", col("v"), "cl")], [batch_from_pydict(data, AGG_SCHEMA)])
    assert len(d["cl"][0]) == 64
    assert set(d["cl"][0]) <= set(range(n))


# --------------------------------------------------- shuffle of nested

def test_nested_through_shuffle():
    from blaze_tpu.parallel.exchange import NativeShuffleExchangeExec
    from blaze_tpu.parallel.shuffle import HashPartitioning

    b = make_batch()
    ex = NativeShuffleExchangeExec(
        MemoryScanExec([[b]], SCHEMA), HashPartitioning([col("id")], 3)
    )
    rows = []
    for p in range(3):
        for ob in ex.execute(p, TaskContext(p, 3)):
            d = batch_to_pydict(ob)
            rows += list(zip(d["id"], [repr(x) for x in d["a"]], [repr(x) for x in d["nn"]]))
    want = list(zip(DATA["id"], [repr(x) for x in DATA["a"]], [repr(x) for x in DATA["nn"]]))
    assert sorted(rows) == sorted(want)


def test_collect_list_over_array_elements():
    """collect_list of ARRAY-typed values: two-stage aggregation whose
    state is an array-of-arrays column (nested element scatter + serde
    across the exchange)."""
    import numpy as np

    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.tpch.queries import two_stage_agg

    arr_t = DataType.array(DataType.int64(), 4)
    schema = Schema([Field("g", DataType.int64()), Field("v", arr_t)])
    rows = [
        (0, [1, 2]), (1, [3]), (0, [4, 5, 6]), (1, []), (0, None),
        (2, [7]), (1, [8, 9]), (2, [10, None, 12]),
    ]
    data = {"g": [r[0] for r in rows], "v": [r[1] for r in rows]}
    parts = [[batch_from_pydict({k: v[:4] for k, v in data.items()}, schema)],
             [batch_from_pydict({k: v[4:] for k, v in data.items()}, schema)]]
    src = MemoryScanExec(parts, schema)
    plan = two_stage_agg(
        src,
        [GroupingExpr(col("g"), "g")],
        [AggFunction("collect_list", col("v"), "lists")],
        2,
    )
    got = {}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for g, ls in zip(d["g"], d["lists"]):
                got[g] = ls
    exp = {}
    for g, v in rows:
        if v is not None:  # collect skips NULL rows (Spark)
            exp.setdefault(g, []).append(v)
    assert set(got) == set(exp)
    for g in exp:
        # order within a group is partition-order dependent; compare
        # as multisets of tuples (inner nulls preserved)
        canon = lambda ls: sorted(tuple(x) for x in ls)
        assert canon(got[g]) == canon(exp[g]), g


def test_collect_set_over_array_elements():
    """collect_set of ARRAY-typed values: element dedup via the
    (length, validity-flags, value) word encoding — [1,2] == [1,2]
    across batches, [] != [1], inner nulls distinguish."""
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.tpch.queries import two_stage_agg

    arr_t = DataType.array(DataType.int64(), 4)
    schema = Schema([Field("g", DataType.int64()), Field("v", arr_t)])
    rows = [
        (0, [1, 2]), (0, [1, 2]), (0, [2, 1]), (0, []),
        (1, [3]), (1, [3, None]), (1, [3, None]), (1, None),
        (2, [1, 2]), (2, []), (2, [1, 2, 3]),
    ]
    data = {"g": [r[0] for r in rows], "v": [r[1] for r in rows]}
    parts = [[batch_from_pydict({k: v[:6] for k, v in data.items()}, schema)],
             [batch_from_pydict({k: v[6:] for k, v in data.items()}, schema)]]
    src = MemoryScanExec(parts, schema)
    plan = two_stage_agg(
        src,
        [GroupingExpr(col("g"), "g")],
        [AggFunction("collect_set", col("v"), "sets")],
        2,
    )
    got = {}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for g, ls in zip(d["g"], d["sets"]):
                got[g] = ls
    exp = {}
    for g, v in rows:
        if v is not None:
            exp.setdefault(g, set()).add(tuple(v))
    assert set(got) == set(exp)
    for g in exp:
        canon = lambda ls: sorted(
            (tuple(-1 if x is None else x for x in e) for e in ls),
        )
        assert canon(got[g]) == canon(
            [list(e) for e in exp[g]]
        ), (g, got[g], exp[g])


def _run_collect_set(rows, value_t):
    from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggFunction, GroupingExpr, MemoryScanExec
    from blaze_tpu.runtime.context import TaskContext
    from blaze_tpu.schema import DataType, Field, Schema
    from blaze_tpu.tpch.queries import two_stage_agg

    schema = Schema([Field("g", DataType.int64()), Field("v", value_t)])
    data = {"g": [r[0] for r in rows], "v": [r[1] for r in rows]}
    half = len(rows) // 2
    parts = [[batch_from_pydict({k: v[:half] for k, v in data.items()}, schema)],
             [batch_from_pydict({k: v[half:] for k, v in data.items()}, schema)]]
    plan = two_stage_agg(
        MemoryScanExec(parts, schema),
        [GroupingExpr(col("g"), "g")],
        [AggFunction("collect_set", col("v"), "sets")],
        2,
    )
    got = {}
    for p in range(plan.num_partitions()):
        for b in plan.execute(p, TaskContext(p, plan.num_partitions())):
            d = batch_to_pydict(b)
            for g, ls in zip(d["g"], d["sets"]):
                got[g] = ls
    return got


def _canon(v):
    if isinstance(v, list):
        return ("L",) + tuple(_canon(x) for x in v)
    if isinstance(v, dict):
        return ("D",) + tuple(sorted((k, _canon(x)) for k, x in v.items()))
    return v


def test_collect_set_over_lists_of_lists():
    """collect_set of ARRAY<ARRAY<int>>: recursive word encoding —
    [[1],[2]] == [[1],[2]], != [[1,2]], != [[2],[1]]."""
    from blaze_tpu.schema import DataType

    t = DataType.array(DataType.array(DataType.int64(), 3), 3)
    rows = [
        (0, [[1], [2]]), (0, [[1], [2]]), (0, [[1, 2]]), (0, [[2], [1]]),
        (1, [[]]), (1, []), (1, None), (1, [[]]),
        (2, [[1, None]]), (2, [[1, None]]), (2, [[1]]), (2, [[None, 1]]),
    ]
    got = _run_collect_set(rows, t)
    exp = {}
    for g, v in rows:
        if v is not None:
            exp.setdefault(g, set()).add(_canon(v))
    assert set(got) == set(exp)
    for g in exp:
        assert sorted(map(str, {_canon(e) for e in got[g]})) == sorted(
            map(str, exp[g])), (g, got[g])


def test_collect_set_over_lists_of_structs():
    """collect_set of ARRAY<STRUCT<a,s>>: per-field null flags + value
    words distinguish field-level differences."""
    from blaze_tpu.schema import DataType, Field

    st = DataType.struct([Field("a", DataType.int64()),
                          Field("s", DataType.string(8))])
    t = DataType.array(st, 3)
    rows = [
        (0, [{"a": 1, "s": "x"}]), (0, [{"a": 1, "s": "x"}]),
        (0, [{"a": 1, "s": "y"}]), (0, [{"a": None, "s": "x"}]),
        (1, [{"a": 2, "s": None}]), (1, [{"a": 2, "s": None}]),
        (1, [{"a": 2, "s": "z"}, {"a": 3, "s": "w"}]),
        (1, [{"a": 3, "s": "w"}, {"a": 2, "s": "z"}]),
    ]
    got = _run_collect_set(rows, t)
    exp = {}
    for g, v in rows:
        if v is not None:
            exp.setdefault(g, set()).add(_canon(v))
    assert set(got) == set(exp)
    for g in exp:
        assert sorted(map(str, {_canon(e) for e in got[g]})) == sorted(
            map(str, exp[g])), (g, got[g])


def test_collect_set_over_wide_array_level():
    """collect_set of ARRAY<int> wider than 64 elements: the element
    validity flags spill into multiple 64-bit words, and values
    differing ONLY past element 64 (incl. null-position-only
    differences) must stay distinct."""
    from blaze_tpu.schema import DataType

    t = DataType.array(DataType.int64(), 70)
    base = list(range(70))
    v_null66 = base[:66] + [None] + base[67:]
    v_null67 = base[:67] + [None] + base[68:]
    v_diff69 = base[:69] + [999]
    rows = [
        (0, base), (0, base), (0, v_null66), (0, v_null67), (0, v_diff69),
        (1, base[:65]), (1, base[:65]), (1, base[:66]),
    ]
    got = _run_collect_set(rows, t)
    exp = {}
    for g, v in rows:
        if v is not None:
            exp.setdefault(g, set()).add(_canon(v))
    assert set(got) == set(exp)
    for g in exp:
        assert sorted(map(str, {_canon(e) for e in got[g]})) == sorted(
            map(str, exp[g])), (g, got[g])


def test_collect_set_map_elements_rejected_like_spark():
    """MAP elements: Spark's CollectSet itself refuses map-typed data,
    so the gate is reference semantics, not a gap."""
    import pytest

    from blaze_tpu.ops.agg import agg_result_type
    from blaze_tpu.schema import DataType

    t = DataType.map(DataType.string(8), DataType.int64(), 4)
    with pytest.raises(NotImplementedError, match="[Mm]ap"):
        agg_result_type("collect_set", t)


def test_collect_set_over_lists_of_strings():
    """collect_set of ARRAY<string>: byte-packed words inside the list
    encoding."""
    from blaze_tpu.schema import DataType

    t = DataType.array(DataType.string(8), 3)
    rows = [
        (0, ["ab"]), (0, ["ab"]), (0, ["abc"]), (0, ["ab", "cd"]),
        (1, ["x", None]), (1, ["x", None]), (1, [None, "x"]), (1, []),
    ]
    got = _run_collect_set(rows, t)
    exp = {}
    for g, v in rows:
        if v is not None:
            exp.setdefault(g, set()).add(_canon(v))
    assert set(got) == set(exp)
    for g in exp:
        assert sorted(map(str, {_canon(e) for e in got[g]})) == sorted(
            map(str, exp[g])), (g, got[g])
