"""OPAQUE columns + ObjectAggExec: the UserDefinedArray analogue
(≙ datafusion-ext-commons/src/uda.rs + partial ObjectHashAggregate).

Opaque python UDAF states must survive batch serde, shuffle exchanges,
and the TaskDefinition boundary, and two-stage aggregation must match
a host oracle."""

import numpy as np
import pytest

from blaze_tpu import conf
from blaze_tpu.batch import batch_from_pydict, batch_to_pydict
from blaze_tpu.exprs import col
from blaze_tpu.io import deserialize_batch, serialize_batch
from blaze_tpu.ops import AggMode, GroupingExpr, MemoryScanExec, ObjectAggExec, Udaf
from blaze_tpu.parallel import HashPartitioning, NativeShuffleExchangeExec
from blaze_tpu.runtime.context import TaskContext
from blaze_tpu.schema import DataType, Field, Schema


# module-level functions: UDAF callables must be picklable to cross
# the TaskDefinition boundary (the Udaf docstring's contract)
def _set_init():
    return set()


def _set_update(s, v):
    return s if v is None else (s | {v})


def _set_merge(a, b):
    return a | (b or set())


def _set_finish(s):
    return len(s)


def _sketch_udaf():
    """A set-union 'sketch' (stand-in for HLL/TDigest-class states)."""
    return Udaf(
        name="distinct_set",
        init=_set_init,
        update=_set_update,
        merge=_set_merge,
        finish=_set_finish,
        args=[col("v")],
        result_dtype=DataType.int64(),
    )


SCHEMA = Schema([Field("k", DataType.int64()), Field("v", DataType.int64())])


def make_parts(n_parts=3, n=150, seed=5):
    rng = np.random.RandomState(seed)
    parts, raw = [], []
    for _ in range(n_parts):
        d = {
            "k": [int(x) for x in rng.randint(0, 6, n)],
            "v": [int(x) if x % 9 else None for x in rng.randint(0, 25, n)],
        }
        raw.append(d)
        parts.append([batch_from_pydict(d, SCHEMA)])
    return parts, raw


def test_opaque_column_serde_roundtrip():
    schema = Schema([Field("s", DataType.opaque())])
    b = batch_from_pydict({"s": [{1, 2}, None, {"x": [3]}, (4, 5)]}, schema)
    b2 = deserialize_batch(serialize_batch(b), schema)
    assert batch_to_pydict(b2) == {"s": [{1, 2}, None, {"x": [3]}, (4, 5)]}


def test_opaque_deser_gated_by_conf():
    schema = Schema([Field("s", DataType.opaque())])
    data = serialize_batch(batch_from_pydict({"s": [{1}]}, schema))
    prev = conf.ALLOW_PICKLED_UDFS.get()
    conf.ALLOW_PICKLED_UDFS.set(False)
    try:
        with pytest.raises(PermissionError):
            deserialize_batch(data, schema)
    finally:
        conf.ALLOW_PICKLED_UDFS.set(prev)


def test_object_agg_two_stage_matches_oracle():
    """partial(object states) -> hash exchange -> final(finish) ==
    exact distinct counts per group."""
    parts, raw = make_parts()
    src = MemoryScanExec(parts, SCHEMA)
    partial = ObjectAggExec(
        src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")], [_sketch_udaf()]
    )
    ex = NativeShuffleExchangeExec(partial, HashPartitioning([col("k")], 2))
    final = ObjectAggExec(
        ex, AggMode.FINAL, [GroupingExpr(col("k"), "k")], [_sketch_udaf()]
    )
    got = {}
    for p in range(2):
        for b in final.execute(p, TaskContext(p, 2)):
            d = batch_to_pydict(b)
            for k, n in zip(d["k"], d["distinct_set"]):
                assert k not in got
                got[k] = n
    oracle = {}
    for d in raw:
        for k, v in zip(d["k"], d["v"]):
            if v is not None:
                oracle.setdefault(k, set()).add(v)
    assert got == {k: len(s) for k, s in oracle.items()}


def test_object_agg_over_task_definition():
    """The pickled-UDAF plan node crosses the protobuf boundary."""
    from blaze_tpu.serde.from_proto import run_task
    from blaze_tpu.serde.to_proto import task_definition

    parts, raw = make_parts(n_parts=1)
    src = MemoryScanExec(parts, SCHEMA)
    partial = ObjectAggExec(
        src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")], [_sketch_udaf()]
    )
    final = ObjectAggExec(
        partial, AggMode.FINAL, [GroupingExpr(col("k"), "k")], [_sketch_udaf()]
    )
    td = task_definition(final, "t", 0, 0)
    got = {}
    for b in run_task(td):
        d = batch_to_pydict(b)
        got.update(zip(d["k"], d["distinct_set"]))
    oracle = {}
    for k, v in zip(raw[0]["k"], raw[0]["v"]):
        if v is not None:
            oracle.setdefault(k, set()).add(v)
    assert got == {k: len(s) for k, s in oracle.items()}


def test_hll_approx_count_distinct():
    """HLL++ distinct count within ~3% across a two-stage pipeline."""
    import numpy as np

    from blaze_tpu.ops import ObjectAggExec
    from blaze_tpu.ops.udafs import approx_count_distinct

    rng = np.random.RandomState(4)
    n_parts, per = 3, 6000
    true_distinct = 20000
    parts = []
    for p in range(n_parts):
        vals = rng.randint(0, true_distinct, per)
        parts.append([batch_from_pydict(
            {"k": [0] * per, "v": [int(x) for x in vals]}, SCHEMA
        )])
    src = MemoryScanExec(parts, SCHEMA)
    partial = ObjectAggExec(
        src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
        [approx_count_distinct(col("v"), "acd")],
    )
    ex = NativeShuffleExchangeExec(partial, HashPartitioning([col("k")], 2))
    final = ObjectAggExec(
        ex, AggMode.FINAL, [GroupingExpr(col("k"), "k")],
        [approx_count_distinct(col("v"), "acd")],
    )
    got = {}
    for p in range(2):
        for b in final.execute(p, TaskContext(p, 2)):
            d = batch_to_pydict(b)
            got.update(zip(d["k"], d["acd"]))
    exact = len({v for part in parts for b in part
                 for v in batch_to_pydict(b)["v"]})
    assert abs(got[0] - exact) / exact < 0.03, (got[0], exact)


def test_tdigest_approx_percentile():
    """t-digest median/p90 within 2% of exact across partitions +
    TaskDefinition roundtrip (pickle-able partial finish)."""
    import numpy as np

    from blaze_tpu.ops import ObjectAggExec
    from blaze_tpu.ops.udafs import approx_percentile
    from blaze_tpu.serde.from_proto import run_task
    from blaze_tpu.serde.to_proto import task_definition

    rng = np.random.RandomState(8)
    all_vals = []
    parts = []
    for p in range(3):
        vals = rng.gamma(3.0, 100.0, 4000)
        all_vals.extend(vals)
        parts.append([batch_from_pydict(
            {"k": [0] * len(vals), "v": [int(x) for x in vals]}, SCHEMA
        )])
    src = MemoryScanExec(parts, SCHEMA)
    partial = ObjectAggExec(
        src, AggMode.PARTIAL, [GroupingExpr(col("k"), "k")],
        [approx_percentile(col("v"), 0.5, "p50"),
         approx_percentile(col("v"), 0.9, "p90")],
    )
    final = ObjectAggExec(
        partial, AggMode.FINAL, [GroupingExpr(col("k"), "k")],
        [approx_percentile(col("v"), 0.5, "p50"),
         approx_percentile(col("v"), 0.9, "p90")],
    )
    td = task_definition(final, "t", 0, 0)
    got = {}
    for b in run_task(td):
        d = batch_to_pydict(b)
        got["p50"] = d["p50"][0]
        got["p90"] = d["p90"][0]
    exact50 = float(np.percentile([int(x) for x in all_vals], 50))
    exact90 = float(np.percentile([int(x) for x in all_vals], 90))
    assert abs(got["p50"] - exact50) / exact50 < 0.02, (got["p50"], exact50)
    assert abs(got["p90"] - exact90) / exact90 < 0.02, (got["p90"], exact90)


def test_hash64_process_stable():
    """_hash64 must NOT inherit PYTHONHASHSEED randomization (sketches
    merge across processes): golden values pin the encoding."""
    from blaze_tpu.ops.udafs import _hash64

    assert _hash64(42) == 1617879888388836812
    assert _hash64("abc") == 379167468994990588
    assert _hash64(2.5) == 6632595409814502509
    assert _hash64(2.0) == _hash64(2)      # numeric equality
    assert _hash64(float("nan")) == _hash64(float("nan"))
    assert _hash64(True) != _hash64(1)     # bool is its own domain
